"""Benchmark: ResNet-101 Faster R-CNN end-to-end train throughput.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "imgs/sec/chip", "vs_baseline": N/30}

Baseline = the 30 imgs/sec/chip north-star target from BASELINE.json
(the reference never published per-chip throughput; its GPU-era numbers
were O(2-5) imgs/sec/GPU).
"""

import dataclasses
import json
import time

import numpy as np

BASELINE_IMGS_PER_SEC_PER_CHIP = 30.0


def main():
    import jax

    from mx_rcnn_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    from __graft_entry__ import _batch, _flagship_cfg
    from mx_rcnn_tpu.core.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )
    from mx_rcnn_tpu.models import FasterRCNN

    cfg = _flagship_cfg()
    # The perf configuration: bf16 compute (f32 params) rides the MXU, and
    # 8 images/chip/step amortize fixed per-step costs (measured: b1=29.9,
    # b2=40.2, b4=44.6, b8=52.9 img/s).  entry()/dryrun keep f32 batch-1
    # for conservative compile/correctness checks.
    cfg = cfg.replace(
        network=dataclasses.replace(cfg.network, COMPUTE_DTYPE="bfloat16"),
        TRAIN=dataclasses.replace(cfg.TRAIN, BATCH_IMAGES=8),
    )
    model = FasterRCNN(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]
    b = cfg.TRAIN.BATCH_IMAGES
    batch = _batch(cfg, b, h, w)
    params = model.init(
        {"params": jax.random.key(0), "sampling": jax.random.key(1)},
        batch["images"],
        batch["im_info"],
        batch["gt_boxes"],
        batch["gt_valid"],
        train=True,
    )["params"]
    tx = make_optimizer(cfg, lambda s: cfg.TRAIN.LEARNING_RATE)
    state = create_train_state(params, tx)
    step = make_train_step(model, tx, donate=True)

    rng = jax.random.key(0)
    # warmup / compile (value fetch = the only trustworthy sync on the
    # axon relay; block_until_ready returns early there)
    state, aux = step(state, batch, rng)
    float(aux["loss"])

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        state, aux = step(state, batch, rng)
    # the final loss depends on every chained step, so this fetch forces
    # the whole sequence; one ~85ms tunnel roundtrip amortized over iters
    assert np.isfinite(float(aux["loss"]))
    dt = time.perf_counter() - t0

    imgs_per_sec = b * iters / dt
    print(
        json.dumps(
            {
                "metric": "train_imgs_per_sec_per_chip_resnet101_e2e",
                "value": round(imgs_per_sec, 3),
                "unit": "imgs/sec/chip",
                "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
