"""Benchmark: end-to-end train throughput per model family.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "imgs/sec/chip", "vs_baseline": N/30}

Default (driver) config: ResNet-101 C4 Faster R-CNN, the flagship.
``--network resnet_fpn`` / ``--network mask_resnet_fpn`` benchmark the
BASELINE config-4/5 graphs (VERDICT r3 #3) with the same JSON contract.

``--all`` (VERDICT r4 #4): bench every family in one process — one JSON
line per family, plus ``--out FILE`` to write the driver-format artifact
(``BENCH_families_rNN.json``) that replaces README-quoted perf prose.

Baseline = the 30 imgs/sec/chip north-star target from BASELINE.json
(the reference never published per-chip throughput; its GPU-era numbers
were O(2-5) imgs/sec/GPU).
"""

import argparse
import dataclasses
import json
import time

import numpy as np

BASELINE_IMGS_PER_SEC_PER_CHIP = 30.0

_METRIC_NAMES = {
    "resnet": "resnet101_e2e",
    "resnet50": "resnet50_e2e",
    "resnet_fpn": "resnet50_fpn_e2e",
    "mask_resnet_fpn": "mask_resnet101_fpn_e2e",
    "vgg": "vgg16_e2e",
}

# the per-family artifact set: flagship + BASELINE configs 4/5 + VGG
_ALL_FAMILIES = ("resnet", "resnet_fpn", "mask_resnet_fpn", "vgg")


def bench_one(
    network: str, batch_images: int, iters: int, steps_per_call: int = 1
) -> dict:
    """Train-throughput measurement for one family; → the JSON record."""
    import jax

    from __graft_entry__ import _batch
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )
    from mx_rcnn_tpu.models import build_model

    cfg = generate_config(network, "PascalVOC")
    # The perf configuration: bf16 compute (f32 params) rides the MXU,
    # 8 images/chip/step amortize fixed per-step costs (measured: b1=29.9,
    # b2=40.2, b4=44.6, b8=52.9 img/s on the C4 flagship), and FOLD_BN
    # folds the frozen-BN affines into the conv kernels (+2-3%; exact
    # rewrite — default-off only because its fp-reassociation measurably
    # shifted the f32 random-init gate trajectory; the bf16+FOLD_BN bench
    # config has its own committed gate evidence, see PARITY.md round-5
    # notes).  entry()/dryrun keep f32 batch-1 defaults for conservative
    # compile/correctness checks.
    cfg = cfg.replace(
        network=dataclasses.replace(
            cfg.network, COMPUTE_DTYPE="bfloat16", FOLD_BN=True
        ),
        TRAIN=dataclasses.replace(cfg.TRAIN, BATCH_IMAGES=batch_images),
    )
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]
    b = cfg.TRAIN.BATCH_IMAGES
    batch = _batch(cfg, b, h, w)
    if cfg.network.USE_MASK:
        # all-ones box-frame bitmaps: same shapes/flops as real polygon
        # gts through crop_resize_masks (the bitmap content is data)
        batch["gt_masks"] = np.ones(
            (b, batch["gt_boxes"].shape[1], cfg.TRAIN.MASK_GT_SIZE,
             cfg.TRAIN.MASK_GT_SIZE),
            np.uint8,
        )
    params = model.init(
        {"params": jax.random.key(0), "sampling": jax.random.key(1)},
        train=True,
        **batch,
    )["params"]
    tx = make_optimizer(cfg, lambda s: cfg.TRAIN.LEARNING_RATE)
    state = create_train_state(params, tx)
    # steps_per_call > 1: the device-side training loop (lax.scan of K
    # full optimizer steps per dispatch) — a single host dispatch carries
    # ~17 ms of relay/tunnel latency (scripts/probe_opt.py), which K
    # amortizes; exact-equivalence pinned by
    # test_model.py::test_multi_step_matches_sequential_steps
    step = make_train_step(model, tx, donate=True,
                           steps_per_call=steps_per_call)
    if steps_per_call > 1:
        # device-resident stack (jnp): a numpy stack here would re-cross
        # the host->device tunnel (~300 MB) on EVERY dispatch
        import jax.numpy as jnp

        batch = {
            k: jnp.broadcast_to(v[None], (steps_per_call,) + v.shape)
            for k, v in batch.items()
        }

    def last_loss(aux):
        l = np.asarray(aux["loss"])
        return float(l[-1]) if l.ndim else float(l)

    rng = jax.random.key(0)
    # warmup / compile (value fetch = the only trustworthy sync on the
    # axon relay; block_until_ready returns early there)
    state, aux = step(state, batch, rng)
    last_loss(aux)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, aux = step(state, batch, rng)
    # the final loss depends on every chained step, so this fetch forces
    # the whole sequence; one ~85ms tunnel roundtrip amortized over iters
    assert np.isfinite(last_loss(aux))
    dt = time.perf_counter() - t0

    imgs_per_sec = b * iters * steps_per_call / dt
    return {
        "metric": f"train_imgs_per_sec_per_chip_{_METRIC_NAMES[network]}",
        "value": round(imgs_per_sec, 3),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
    }


def bench_serve(
    network: str,
    requests: int,
    concurrency: int,
    max_batch: int,
    linger_ms: float,
    small: bool = True,
) -> tuple:
    """Online-serving measurement: drive the dynamic-batching engine with
    the deterministic synthetic load generator and report latency,
    throughput, occupancy, and the compile count that proves the shape
    ladder held (misses == len(ladder), and not one more).

    → (records, report): the per-metric JSON-line records plus the full
    engine snapshot for the artifact.  Serving has no reference baseline
    (the MXNet repo had no online path), so ``vs_baseline`` is null.
    """
    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import DEFAULT_SIZES, run_load
    from mx_rcnn_tpu.serve.runner import ServeRunner
    from mx_rcnn_tpu.tools.serve import small_config

    if small:
        cfg = small_config(network)
        sizes = ((72, 96), (96, 128), (64, 80))
    else:
        cfg = generate_config(network, "PascalVOC")
        sizes = DEFAULT_SIZES
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]
    params = model.init(
        {"params": jax.random.key(0)},
        np.zeros((1, h, w, 3), np.float32),
        np.array([[h, w, 1.0]], np.float32),
        train=False,
    )["params"]
    runner = ServeRunner(model, params, cfg, max_batch=max_batch)
    with ServingEngine(runner, max_linger=linger_ms / 1000.0) as engine:
        report = run_load(
            engine, num_requests=requests, concurrency=concurrency,
            sizes=sizes, seed=0,
        )
    eng = report["engine"]
    tag = _METRIC_NAMES[network].replace("_e2e", "")
    records = [
        {
            "metric": f"serve_p50_ms_{tag}",
            "value": eng["latency"]["e2e"]["p50_ms"],
            "unit": "ms",
            "vs_baseline": None,
        },
        {
            "metric": f"serve_p99_ms_{tag}",
            "value": eng["latency"]["e2e"]["p99_ms"],
            "unit": "ms",
            "vs_baseline": None,
        },
        {
            "metric": f"serve_imgs_per_sec_{tag}",
            "value": report["imgs_per_sec"],
            "unit": "imgs/sec",
            "vs_baseline": None,
        },
        {
            "metric": f"serve_batch_occupancy_{tag}",
            "value": eng["batches"]["occupancy"],
            "unit": "fraction",
            "vs_baseline": None,
        },
        {
            "metric": f"serve_compile_misses_{tag}",
            "value": eng["compile"]["misses"],
            "unit": "compiles",
            "vs_baseline": None,
        },
    ]
    return records, report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--network", default="resnet",
        choices=sorted(_METRIC_NAMES),
    )
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument(
        "--steps_per_call", type=int, default=1,
        help="K train steps per dispatch (device-side lax.scan loop)",
    )
    ap.add_argument(
        "--all", action="store_true",
        help="bench every family; one JSON line each",
    )
    ap.add_argument(
        "--serve", action="store_true",
        help="bench the online serving engine instead of training",
    )
    # defaults chosen to SATURATE the engine (concurrency > in_flight *
    # max_batch, linger visible next to CPU service times) so the
    # occupancy number is a statement about the batcher, not the load
    ap.add_argument("--serve_requests", type=int, default=64)
    ap.add_argument("--serve_concurrency", type=int, default=16)
    ap.add_argument("--serve_max_batch", type=int, default=4)
    ap.add_argument("--serve_linger_ms", type=float, default=25.0)
    ap.add_argument(
        "--serve_full", action="store_true",
        help="serve at the full config (default: tiny CPU-runnable one)",
    )
    ap.add_argument(
        "--out", default=None,
        help="also write the records as a JSON array artifact",
    )
    args = ap.parse_args()

    from mx_rcnn_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    if args.serve:
        network = "resnet50" if args.network == "resnet" else args.network
        records, report = bench_serve(
            network, args.serve_requests, args.serve_concurrency,
            args.serve_max_batch, args.serve_linger_ms,
            small=not args.serve_full,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    families = _ALL_FAMILIES if args.all else (args.network,)
    records = []
    for network in families:
        rec = bench_one(network, args.batch, args.iters, args.steps_per_call)
        records.append(rec)
        print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
