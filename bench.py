"""Benchmark: end-to-end train throughput per model family.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "imgs/sec/chip", "vs_baseline": N/30}

Default (driver) config: ResNet-101 C4 Faster R-CNN, the flagship.
``--network resnet_fpn`` / ``--network mask_resnet_fpn`` benchmark the
BASELINE config-4/5 graphs (VERDICT r3 #3) with the same JSON contract.

``--all`` (VERDICT r4 #4): bench every family in one process — one JSON
line per family, plus ``--out FILE`` to write the driver-format artifact
(``BENCH_families_rNN.json``) that replaces README-quoted perf prose.

Baseline = the 30 imgs/sec/chip north-star target from BASELINE.json
(the reference never published per-chip throughput; its GPU-era numbers
were O(2-5) imgs/sec/GPU).
"""

import argparse
import dataclasses
import json
import threading
import time

import numpy as np

BASELINE_IMGS_PER_SEC_PER_CHIP = 30.0

_METRIC_NAMES = {
    "resnet": "resnet101_e2e",
    "resnet50": "resnet50_e2e",
    "resnet_fpn": "resnet50_fpn_e2e",
    "mask_resnet_fpn": "mask_resnet101_fpn_e2e",
    "vgg": "vgg16_e2e",
}

# the per-family artifact set: flagship + BASELINE configs 4/5 + VGG
_ALL_FAMILIES = ("resnet", "resnet_fpn", "mask_resnet_fpn", "vgg")


def bench_one(
    network: str, batch_images: int, iters: int, steps_per_call: int = 1
) -> dict:
    """Train-throughput measurement for one family; → the JSON record."""
    import jax

    from __graft_entry__ import _batch
    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )
    from mx_rcnn_tpu.models import build_model

    cfg = generate_config(network, "PascalVOC")
    # The perf configuration: bf16 compute (f32 params) rides the MXU,
    # 8 images/chip/step amortize fixed per-step costs (measured: b1=29.9,
    # b2=40.2, b4=44.6, b8=52.9 img/s on the C4 flagship), and FOLD_BN
    # folds the frozen-BN affines into the conv kernels (+2-3%; exact
    # rewrite — default-off only because its fp-reassociation measurably
    # shifted the f32 random-init gate trajectory; the bf16+FOLD_BN bench
    # config has its own committed gate evidence, see PARITY.md round-5
    # notes).  entry()/dryrun keep f32 batch-1 defaults for conservative
    # compile/correctness checks.
    cfg = cfg.replace(
        network=dataclasses.replace(
            cfg.network, COMPUTE_DTYPE="bfloat16", FOLD_BN=True
        ),
        TRAIN=dataclasses.replace(cfg.TRAIN, BATCH_IMAGES=batch_images),
    )
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]
    b = cfg.TRAIN.BATCH_IMAGES
    batch = _batch(cfg, b, h, w)
    if cfg.network.USE_MASK:
        # all-ones box-frame bitmaps: same shapes/flops as real polygon
        # gts through crop_resize_masks (the bitmap content is data)
        batch["gt_masks"] = np.ones(
            (b, batch["gt_boxes"].shape[1], cfg.TRAIN.MASK_GT_SIZE,
             cfg.TRAIN.MASK_GT_SIZE),
            np.uint8,
        )
    params = model.init(
        {"params": jax.random.key(0), "sampling": jax.random.key(1)},
        train=True,
        **batch,
    )["params"]
    tx = make_optimizer(cfg, lambda s: cfg.TRAIN.LEARNING_RATE)
    state = create_train_state(params, tx)
    # steps_per_call > 1: the device-side training loop (lax.scan of K
    # full optimizer steps per dispatch) — a single host dispatch carries
    # ~17 ms of relay/tunnel latency (scripts/probe_opt.py), which K
    # amortizes; exact-equivalence pinned by
    # test_model.py::test_multi_step_matches_sequential_steps
    step = make_train_step(model, tx, donate=True,
                           steps_per_call=steps_per_call)
    if steps_per_call > 1:
        # device-resident stack (jnp): a numpy stack here would re-cross
        # the host->device tunnel (~300 MB) on EVERY dispatch
        import jax.numpy as jnp

        batch = {
            k: jnp.broadcast_to(v[None], (steps_per_call,) + v.shape)
            for k, v in batch.items()
        }

    def last_loss(aux):
        l = np.asarray(aux["loss"])
        return float(l[-1]) if l.ndim else float(l)

    rng = jax.random.key(0)
    # warmup / compile (value fetch = the only trustworthy sync on the
    # axon relay; block_until_ready returns early there)
    state, aux = step(state, batch, rng)
    last_loss(aux)

    t0 = time.perf_counter()
    for _ in range(iters):
        state, aux = step(state, batch, rng)
    # the final loss depends on every chained step, so this fetch forces
    # the whole sequence; one ~85ms tunnel roundtrip amortized over iters
    assert np.isfinite(last_loss(aux))
    dt = time.perf_counter() - t0

    imgs_per_sec = b * iters * steps_per_call / dt
    return {
        "metric": f"train_imgs_per_sec_per_chip_{_METRIC_NAMES[network]}",
        "value": round(imgs_per_sec, 3),
        "unit": "imgs/sec/chip",
        "vs_baseline": round(imgs_per_sec / BASELINE_IMGS_PER_SEC_PER_CHIP, 3),
    }


def _serve_model(network: str, small: bool, max_batch: int,
                 deterministic: bool = False):
    """Shared serve-bench setup → (model, params, cfg, sizes, factory).
    ``factory`` builds one device-pinned ServeRunner per replica index —
    the ReplicaPool's runner source (and what a rewarm re-invokes)."""
    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve.loadgen import DEFAULT_SIZES
    from mx_rcnn_tpu.serve.router import make_replica_factory
    from mx_rcnn_tpu.serve.runner import ServeRunner
    from mx_rcnn_tpu.tools.serve import small_config

    if small:
        cfg = small_config(network)
        sizes = ((72, 96), (96, 128), (64, 80))
    else:
        cfg = generate_config(network, "PascalVOC")
        sizes = DEFAULT_SIZES
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]
    params = model.init(
        {"params": jax.random.key(0)},
        np.zeros((1, h, w, 3), np.float32),
        np.array([[h, w, 1.0]], np.float32),
        train=False,
    )["params"]
    factory = make_replica_factory(
        lambda params: ServeRunner(
            model, params, cfg, max_batch=max_batch,
            deterministic=deterministic,
        ),
        params,
    )
    return model, params, cfg, sizes, factory


def bench_serve(
    network: str,
    requests: int,
    concurrency: int,
    max_batch: int,
    linger_ms: float,
    small: bool = True,
    replicas: int = 1,
    inflight_depth: int = 2,
) -> tuple:
    """Online-serving measurement: drive the dynamic-batching engine with
    the deterministic synthetic load generator and report latency,
    throughput, occupancy, and the compile count that proves the shape
    ladder held (misses == len(ladder), and not one more).

    → (records, report): the per-metric JSON-line records plus the full
    engine snapshot for the artifact.  Serving has no reference baseline
    (the MXNet repo had no online path), so ``vs_baseline`` is null.

    Routing always goes through the :class:`ReplicaPool` (ISSUE 6) —
    ``replicas=1`` is the no-regression case the committed
    ``BENCH_serve_cpu.json`` pins (same compile-miss invariant through
    the pool's merged cache view).
    """
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import run_load
    from mx_rcnn_tpu.serve.router import ReplicaPool

    _, _, _, sizes, factory = _serve_model(network, small, max_batch)
    pool = ReplicaPool(factory, n_replicas=replicas,
                       inflight_depth=inflight_depth)
    with ServingEngine(pool, max_linger=linger_ms / 1000.0) as engine:
        report = run_load(
            engine, num_requests=requests, concurrency=concurrency,
            sizes=sizes, seed=0,
        )
    pool.close()
    eng = report["engine"]
    tag = _METRIC_NAMES[network].replace("_e2e", "")
    records = [
        {
            "metric": f"serve_p50_ms_{tag}",
            "value": eng["latency"]["e2e"]["p50_ms"],
            "unit": "ms",
            "vs_baseline": None,
        },
        {
            "metric": f"serve_p99_ms_{tag}",
            "value": eng["latency"]["e2e"]["p99_ms"],
            "unit": "ms",
            "vs_baseline": None,
        },
        {
            "metric": f"serve_imgs_per_sec_{tag}",
            "value": report["imgs_per_sec"],
            "unit": "imgs/sec",
            "vs_baseline": None,
        },
        {
            "metric": f"serve_batch_occupancy_{tag}",
            "value": eng["batches"]["occupancy"],
            "unit": "fraction",
            "vs_baseline": None,
        },
        {
            "metric": f"serve_compile_misses_{tag}",
            "value": eng["compile"]["misses"],
            "unit": "compiles",
            "vs_baseline": None,
        },
    ]
    return records, report


def _mask_serve_cfg():
    """Small mask-family serving config sized so the fetch ratio is a
    statement about the PATH, not the padding: 64 post-NMS rois keep the
    raw ``(B, R, S, S, K)`` mask stack the dominant fetch term (~3.2 MB
    per b=4 batch at S=28, K=4) while the device path ships only the 16
    capped survivors' grids (~0.2 MB).  The flagship config's ratio is
    larger still (R=300, K=21 → ~50×); this is the CPU-runnable
    miniature of the same geometry."""
    from mx_rcnn_tpu.tools.serve import small_config

    cfg = small_config("mask_resnet_fpn")
    return cfg.replace(
        TEST=dataclasses.replace(
            cfg.TEST,
            RPN_POST_NMS_TOP_N=64,
            DET_PER_CLASS=16,
            MAX_PER_IMAGE=16,
        ),
    )


def _rles_for_image(runner, out, batch, h, w, model=None):
    """One image's outputs → (cls_dets, {cls: [rle, ...]}) through the
    canonical decode + cap + paste + RLE chain (eval/segm.py)."""
    from mx_rcnn_tpu.eval.segm import rles_for_detections

    cls_dets, mask_probs = runner.detections_for(
        out, batch, 0, orig_hw=(h, w), model=model, with_masks=True
    )
    rles = {
        j: rles_for_detections(mask_probs[j], cls_dets[j], h, w)
        for j in range(1, len(cls_dets))
    }
    return cls_dets, rles


def _rles_equal(a: dict, b: dict) -> bool:
    if set(a) != set(b):
        return False
    for j in a:
        if len(a[j]) != len(b[j]):
            return False
        for ra, rb in zip(a[j], b[j]):
            if ra["size"] != rb["size"] or ra["counts"] != rb["counts"]:
                return False
    return True


def bench_serve_mask(
    requests: int,
    concurrency: int,
    max_batch: int,
    linger_ms: float,
    replicas: int = 1,
    inflight_depth: int = 2,
) -> tuple:
    """Mask-family serving bench (ISSUE 14): device-side mask selection
    vs the raw-head path.

    Two phases on one model + params:

    1. **parity + fetch accounting** — every ladder bucket (and an
       odd-size request per bucket, exercising the padding config) runs
       through BOTH a device-postprocess runner and a raw-head runner
       (``device_postprocess=False``), both ``deterministic=True``; the
       final per-detection RLEs must be byte-identical and the
       ``fetch_bytes`` counters give the measured per-complete reduction.
    2. **pool + engine load** — the mask family registered as a NAMED
       registry entry ("masks") served through the ReplicaPool and the
       real engine intake by the synthetic load generator; p50/p99,
       per-model pool fetch bytes, and the zero-steady-state-recompile
       invariant (misses == ladder rungs) come from this phase.
    """
    import jax

    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import run_load, synthetic_image
    from mx_rcnn_tpu.serve.registry import ModelRegistry
    from mx_rcnn_tpu.serve.router import ReplicaPool, make_replica_factory
    from mx_rcnn_tpu.serve.runner import ServeRunner

    cfg = _mask_serve_cfg()
    sizes = ((72, 96), (96, 128), (64, 80), (128, 128))
    model = build_model(cfg)
    h0, w0 = cfg.SHAPE_BUCKETS[0]
    params = model.init(
        {"params": jax.random.key(0)},
        np.zeros((1, h0, w0, 3), np.float32),
        np.array([[h0, w0, 1.0]], np.float32),
        train=False,
    )["params"]

    # Random init saturates the softmax — every roi scores EXACTLY 1.0
    # for one class, so host-vs-device keep order on those exact float
    # ties is undefined and the parity phase would measure tie-break
    # luck, not the path.  Damp the score/delta heads so every roi
    # carries a distinct non-saturated score and decoded boxes stay off
    # the clip rails; the mask head too, which also keeps the reference
    # sigmoid out of float overflow.  The compiled programs are
    # unchanged — only the weights are.
    def _damp(path, leaf):
        name = "/".join(str(getattr(p, "key", p)) for p in path)
        for frag in ("rpn_cls_score", "rpn_bbox_pred", "cls_score",
                     "bbox_pred", "mask_logits"):
            if frag in name:
                return leaf * 1e-2
        return leaf

    params = jax.tree_util.tree_map_with_path(_damp, params)

    registry = ModelRegistry()
    registry.register("masks", model, cfg, params)
    factory = make_replica_factory(
        lambda registry, device: ServeRunner(
            registry=registry, device=device, max_batch=max_batch,
            deterministic=True,
        ),
        registry=registry,
    )
    pool = ReplicaPool(factory, n_replicas=replicas,
                       inflight_depth=inflight_depth)
    rungs = pool.warmup()

    # raw-head reference runner: same model/params/cfg, postprocess OFF —
    # the pre-ISSUE-14 mask serving path, fetching the full head outputs
    raw = ServeRunner(
        model, params, cfg, max_batch=max_batch, deterministic=True,
        device_postprocess=False,
    )
    raw.warmup()

    dev_runner = pool.replicas[0].runner
    dev_base = (dev_runner.fetch_bytes_total, dev_runner.split_completes)
    raw_base = (raw.fetch_bytes_total, raw.split_completes)
    byte_identical = True
    parity = []
    for i, (ih, iw) in enumerate(sizes):
        im = synthetic_image(i, ih, iw, seed=0)
        dreq = dev_runner.make_request(im, model="masks")
        rreq = raw.make_request(im)
        dout = dev_runner.run(dev_runner.assemble([dreq]), model="masks")
        rout = raw.run(raw.assemble([rreq]))
        d_dets, d_rles = _rles_for_image(
            dev_runner, dout, {"im_info": [dreq.im_info]}, ih, iw,
            model="masks",
        )
        r_dets, r_rles = _rles_for_image(
            raw, rout, {"im_info": [rreq.im_info]}, ih, iw
        )
        # scores must be bitwise equal (pure gather on device); box
        # coords carry the known XLA-vs-numpy decode ulp (~4e-6 px), so
        # they get a tight tolerance, NOT equality — the RLE check
        # downstream is the strict byte-level bar
        scores_eq, box_delta, count_eq = True, 0.0, True
        for a, b in zip(d_dets[1:], r_dets[1:]):
            if (a is None) != (b is None) or \
                    (a is not None and len(a) != len(b)):
                count_eq = False
                continue
            if a is None or len(a) == 0:
                continue
            scores_eq &= a[:, 4].tobytes() == b[:, 4].tobytes()
            box_delta = max(
                box_delta, float(np.abs(a[:, :4] - b[:, :4]).max())
            )
        dets_eq = count_eq and scores_eq and box_delta <= 1e-4
        rles_eq = _rles_equal(d_rles, r_rles)
        byte_identical &= dets_eq and rles_eq
        parity.append({
            "size": [ih, iw], "bucket": list(dreq.bucket),
            "detections": int(sum(
                len(d) for d in d_dets[1:] if d is not None
            )),
            "scores_byte_identical": scores_eq,
            "max_box_delta": box_delta,
            "rles_byte_identical": rles_eq,
        })
    dev_bytes = dev_runner.fetch_bytes_total - dev_base[0]
    dev_completes = dev_runner.split_completes - dev_base[1]
    raw_bytes = raw.fetch_bytes_total - raw_base[0]
    raw_completes = raw.split_completes - raw_base[1]
    dev_per_batch = dev_bytes / max(dev_completes, 1)
    raw_per_batch = raw_bytes / max(raw_completes, 1)
    reduction = raw_per_batch / max(dev_per_batch, 1)

    with ServingEngine(pool, max_linger=linger_ms / 1000.0) as engine:
        load = run_load(
            engine, num_requests=requests, concurrency=concurrency,
            sizes=sizes[:3], seed=0, models=["masks"],
        )
    snap = pool.snapshot()
    pool.close()
    eng = load["engine"]
    steady_misses = snap["compile"]["misses"] - rungs
    claims = {
        "fetch_reduction_ge_5x": bool(reduction >= 5.0),
        "rle_byte_identical": bool(byte_identical),
        "zero_steady_state_recompiles": bool(steady_misses == 0),
    }
    report = {
        "claims": claims,
        "fetch_bytes": {
            "raw_per_batch": round(raw_per_batch, 1),
            "device_per_batch": round(dev_per_batch, 1),
            "reduction": round(reduction, 2),
            "pool_fetch_bytes": snap["overlap"]["fetch_bytes"],
            "pool_fetch_bytes_by_model":
                snap["overlap"]["fetch_bytes_by_model"],
        },
        "parity": parity,
        "config": {
            "rpn_post_nms_top_n": cfg.TEST.RPN_POST_NMS_TOP_N,
            "det_per_class": cfg.TEST.DET_PER_CLASS,
            "max_per_image": cfg.TEST.MAX_PER_IMAGE,
            "mask_size": cfg.TRAIN.MASK_SIZE,
            "num_classes": cfg.dataset.NUM_CLASSES,
            "ladder_rungs": rungs,
        },
        "engine": eng,
        "load": {
            "imgs_per_sec": load["imgs_per_sec"],
            "requests": requests,
        },
    }
    records = [
        {"metric": "serve_mask_p50_ms",
         "value": eng["latency"]["e2e"]["p50_ms"],
         "unit": "ms", "vs_baseline": None},
        {"metric": "serve_mask_p99_ms",
         "value": eng["latency"]["e2e"]["p99_ms"],
         "unit": "ms", "vs_baseline": None},
        {"metric": "serve_mask_imgs_per_sec",
         "value": load["imgs_per_sec"],
         "unit": "imgs/sec", "vs_baseline": None},
        {"metric": "serve_mask_fetch_bytes_per_batch_raw",
         "value": round(raw_per_batch, 1),
         "unit": "bytes", "vs_baseline": None},
        {"metric": "serve_mask_fetch_bytes_per_batch_device",
         "value": round(dev_per_batch, 1),
         "unit": "bytes", "vs_baseline": None},
        {"metric": "serve_mask_fetch_reduction",
         "value": round(reduction, 2),
         "unit": "x", "vs_baseline": None},
        {"metric": "serve_mask_rle_byte_identical",
         "value": 1.0 if byte_identical else 0.0,
         "unit": "bool", "vs_baseline": None},
        {"metric": "serve_mask_steady_state_compile_misses",
         "value": steady_misses,
         "unit": "compiles", "vs_baseline": None},
    ]
    return records, report


def _pctl_ms(lats_ms: list, p: float) -> float:
    """Exact percentile over a small latency sample (sorted interp)."""
    if not lats_ms:
        return None
    return round(float(np.percentile(np.asarray(lats_ms), p)), 3)


def _dets_equal(a, b) -> bool:
    """Byte-level equality of two per-class detections lists."""
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x is None or y is None:
            if x is not y:
                return False
            continue
        x, y = np.asarray(x), np.asarray(y)
        if x.shape != y.shape or x.dtype != y.dtype:
            return False
        if x.tobytes() != y.tobytes():
            return False
    return True


class _OverlapStubRunner:
    """Split-capable runner stub with a CALIBRATED device-stall model
    (the ``bench_eval --stub_device_ms`` idiom, applied to serving).

    The real overlap win is invisible on a 1-core CPU — model FLOPs
    dwarf the fetch — so the stub models the three phases the split
    predict path actually reorders, each as an explicit stall:

    * ``dispatch`` sleeps ``h2d_ms`` (host-blocking staging copy), then
      books ``device_ms`` of modeled device time onto a single-device
      timeline (``_device_free_t``): compute for batch N+1 queues
      behind batch N exactly like one accelerator's stream.
    * ``complete`` blocks until the handle's modeled ready time, then
      sleeps ``fetch_ms`` (the D2H output copy + host postprocess).

    Serial cost per batch is ``h2d + device + fetch``; at depth 2 the
    fetch of batch N overlaps the staging + compute of batch N+1, so
    steady-state cost drops to ``max(device, h2d + fetch)`` — the same
    algebra as the train pipeline's ROOFLINE entry.  Outputs stay the
    FakeRunner digest (a pure function of the slot pixels), so the
    depth-1 vs depth-2 byte-identity check is exact, and
    ``device_busy_s`` gives a stub-exact device-busy fraction to put
    next to the conservative estimate the replicas export.
    """

    LADDER = ((32, 32), (48, 64))

    def __init__(self, index: int = 0, h2d_ms: float = 10.0,
                 device_ms: float = 60.0, fetch_ms: float = 25.0):
        from mx_rcnn_tpu.serve.buckets import BucketLadder, CompileCache

        self.index = index
        self.h2d_s = h2d_ms / 1000.0
        self.device_s = device_ms / 1000.0
        self.fetch_s = fetch_ms / 1000.0
        self.ladder = BucketLadder(self.LADDER)
        self.max_batch = 2
        self.cfg = None
        self.compile_cache = CompileCache()
        self._lock = threading.Lock()
        self._device_free_t = 0.0
        self.device_busy_s = 0.0

    def warmup(self) -> int:
        for bh, bw in self.ladder:
            self.compile_cache.record(((self.max_batch, bh, bw, 3), "f32"))
        return self.compile_cache.misses

    def make_request(self, im, deadline=None):
        from mx_rcnn_tpu.serve.batcher import Request

        h, w = im.shape[:2]
        bh, bw = self.ladder.select(h, w)
        canvas = np.zeros((bh, bw, 3), np.float32)
        canvas[:h, :w] = im
        return Request(
            image=canvas,
            im_info=np.array([h, w, 1.0], np.float32),
            orig_hw=(h, w),
            bucket=(bh, bw),
            deadline=deadline,
        )

    def assemble(self, requests):
        images = [r.image for r in requests]
        while len(images) < self.max_batch:
            images.append(images[0])
        return {
            "images": np.stack(images),
            "im_info": np.stack(
                [r.im_info for r in requests]
                + [requests[0].im_info] * (self.max_batch - len(requests))
            ),
            "orig_hw": np.array(
                [r.orig_hw for r in requests]
                + [requests[0].orig_hw] * (self.max_batch - len(requests))
            ),
        }

    def dispatch(self, batch, model=None):
        time.sleep(self.h2d_s)  # host-blocking H2D staging
        self.compile_cache.record((batch["images"].shape, "f32"))
        im = batch["images"].astype(np.float64)
        out = {
            "digest": np.stack(
                [im.sum(axis=(1, 2, 3)), (im * im).sum(axis=(1, 2, 3))],
                axis=1,
            )
        }
        with self._lock:
            start = max(time.monotonic(), self._device_free_t)
            ready = start + self.device_s
            self._device_free_t = ready
            self.device_busy_s += self.device_s
        return {"out": out, "ready_t": ready}

    def complete(self, handle):
        delay = handle["ready_t"] - time.monotonic()
        if delay > 0:
            time.sleep(delay)  # modeled device compute still running
        time.sleep(self.fetch_s)  # D2H fetch + host postprocess
        return handle["out"]

    def run(self, batch, model=None):
        return self.complete(self.dispatch(batch, model=model))

    def detections_for(self, out, batch, index, orig_hw=None, thresh=None):
        return [out["digest"][index].copy()]


# overlap fault matrix (depth=2, 2 replicas): one transient predict
# failure absorbed by the retry tail, and a hard stall that trips the
# watchdog while TWO dispatches are in flight — both must requeue
_OVERLAP_FAULT_SCENARIOS = {
    "predict_fail": "predict_fail@0.2x1",
    "stall_two_inflight": "predict_stall@0.5:1.5",
}


def bench_serve_overlap(
    requests: int = 48,
    concurrency: int = 8,
    linger_ms: float = 5.0,
    h2d_ms: float = 10.0,
    device_ms: float = 60.0,
    fetch_ms: float = 25.0,
) -> tuple:
    """Overlapped-serving bench (ISSUE 13 acceptance evidence).

    Three legs over the :class:`_OverlapStubRunner` timing model:

    1. depth=1 on a 1-replica pool — the serial reference;
    2. depth=2 on a 1-replica pool — same load, same seed; claims
       throughput >= 1.3x the serial leg with byte-identical
       detections, and reports both the stub-exact device-busy
       fraction (``device_busy_s / wall``) and the conservative
       estimate the replica's :class:`OverlapStats` exports;
    3. the overlap fault matrix at depth=2 on 2 replicas — zero lost
       requests per scenario, ok detections byte-identical to the
       healthy depth-2 leg, and zero steady-state recompiles (a second
       traffic wave after recovery adds no compile-cache misses).
    """
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import run_load
    from mx_rcnn_tpu.serve.replica import HealthPolicy
    from mx_rcnn_tpu.serve.router import ReplicaPool
    from mx_rcnn_tpu.utils import faults

    sizes = ((24, 24), (32, 48), (16, 16))

    def factory(index: int) -> _OverlapStubRunner:
        return _OverlapStubRunner(
            index, h2d_ms=h2d_ms, device_ms=device_ms, fetch_ms=fetch_ms
        )

    def throughput_leg(depth: int):
        pool = ReplicaPool(factory, n_replicas=1, inflight_depth=depth)
        engine = ServingEngine(
            pool, max_linger=linger_ms / 1000.0, in_flight=4
        )
        t0 = time.monotonic()
        with engine:
            report = run_load(
                engine, num_requests=requests, concurrency=concurrency,
                sizes=sizes, seed=0, collect=True,
            )
        wall = time.monotonic() - t0
        busy = sum(r.runner.device_busy_s for r in pool.replicas)
        snap = pool.snapshot()
        pool.close()
        results = report.pop("_results")
        return {
            "inflight_depth": depth,
            "imgs_per_sec": report["imgs_per_sec"],
            "p50_ms": report["engine"]["latency"]["e2e"]["p50_ms"],
            "p99_ms": report["engine"]["latency"]["e2e"]["p99_ms"],
            "compile_misses": report["engine"]["compile"]["misses"],
            "device_busy_fraction": round(busy / wall, 4),
            "overlap": snap["overlap"],
        }, {i: r for i, (kind, r) in results.items() if kind == "ok"}

    depth1, ok1 = throughput_leg(1)
    depth2, ok2 = throughput_leg(2)
    speedup = round(depth2["imgs_per_sec"] / depth1["imgs_per_sec"], 3)
    byte_identical = (
        set(ok1) == set(ok2)
        and all(_dets_equal(ok1[i], ok2[i]) for i in ok1)
    )

    # ---- fault matrix leg: depth=2, 2 replicas, watchdog sized so the
    # injected 1.5 s stall trips it with the window full
    import os

    policy = HealthPolicy(stall_timeout=0.4, fail_threshold=2,
                          breaker_backoff=0.05, breaker_max_backoff=0.5)
    fault = {}
    prior = os.environ.get(faults.ENV_VAR)
    try:
        for name, spec in _OVERLAP_FAULT_SCENARIOS.items():
            os.environ[faults.ENV_VAR] = spec
            faults.reset()
            pool = ReplicaPool(factory, n_replicas=2, inflight_depth=2,
                               policy=policy)
            engine = ServingEngine(
                pool, max_linger=linger_ms / 1000.0, in_flight=4
            )
            with engine:
                report = run_load(
                    engine, num_requests=requests,
                    concurrency=concurrency, sizes=sizes, seed=0,
                    collect=True,
                )
                # wait out any drain -> rewarm -> rejoin before the
                # steady-state wave (stub warmup is instant; bounded)
                t_wait = time.monotonic()
                while time.monotonic() - t_wait < 30.0:
                    reps = pool.snapshot()["replicas"]
                    if all(r["state"] == "healthy" for r in reps):
                        break
                    time.sleep(0.05)
                misses_settled = engine.snapshot()["compile"]["misses"]
                report2 = run_load(
                    engine, num_requests=requests,
                    concurrency=concurrency, sizes=sizes, seed=0,
                )
            pool_snap = pool.snapshot()
            pool.close()
            results = report.pop("_results")
            ok = {i: r for i, (kind, r) in results.items() if kind == "ok"}
            out1, out2 = report["outcomes"], report2["outcomes"]
            lost = (
                requests - (out1["ok"] + out1["deadline"] + out1["error"])
            ) + (
                requests - (out2["ok"] + out2["deadline"] + out2["error"])
            )
            fault[name] = {
                "spec": spec,
                "lost_requests": lost,
                "detections_match_healthy": all(
                    _dets_equal(ok2[i], ok[i]) for i in ok if i in ok2
                ),
                "steady_state_compile_misses": (
                    report2["engine"]["compile"]["misses"] - misses_settled
                ),
                "requeued": sum(
                    rep["requeued_out"] for rep in pool_snap["replicas"]
                ),
                "overlap": pool_snap["overlap"],
            }
    finally:
        if prior is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = prior
        faults.reset()

    zero_lost = all(s["lost_requests"] == 0 for s in fault.values())
    zero_recompiles = all(
        s["steady_state_compile_misses"] == 0 for s in fault.values()
    )
    records = [
        {"metric": "serve_overlap_imgs_per_sec_depth1",
         "value": depth1["imgs_per_sec"], "unit": "imgs/sec",
         "vs_baseline": None},
        {"metric": "serve_overlap_imgs_per_sec_depth2",
         "value": depth2["imgs_per_sec"], "unit": "imgs/sec",
         "vs_baseline": None},
        {"metric": "serve_overlap_speedup",
         "value": speedup, "unit": "x", "vs_baseline": None},
        {"metric": "serve_overlap_device_busy_fraction_depth1",
         "value": depth1["device_busy_fraction"], "unit": "fraction",
         "vs_baseline": None},
        {"metric": "serve_overlap_device_busy_fraction_depth2",
         "value": depth2["device_busy_fraction"], "unit": "fraction",
         "vs_baseline": None},
        {"metric": "serve_overlap_fetch_stall_ms_depth1",
         "value": depth1["overlap"]["fetch_stall_ms"], "unit": "ms",
         "vs_baseline": None},
        {"metric": "serve_overlap_fetch_stall_ms_depth2",
         "value": depth2["overlap"]["fetch_stall_ms"], "unit": "ms",
         "vs_baseline": None},
        {"metric": "serve_overlap_hidden_host_ms_depth2",
         "value": depth2["overlap"]["overlap_hidden_host_ms"], "unit": "ms",
         "vs_baseline": None},
        {"metric": "serve_overlap_inflight_hw_depth2",
         "value": depth2["overlap"]["inflight_hw"], "unit": "dispatches",
         "vs_baseline": None},
        {"metric": "serve_overlap_byte_identical",
         "value": int(byte_identical), "unit": "bool", "vs_baseline": None},
        {"metric": "serve_overlap_fault_lost",
         "value": sum(s["lost_requests"] for s in fault.values()),
         "unit": "requests", "vs_baseline": None},
        {"metric": "serve_overlap_steady_state_compile_misses",
         "value": sum(
             s["steady_state_compile_misses"] for s in fault.values()
         ),
         "unit": "compiles", "vs_baseline": None},
    ]
    report = {
        "stub": {"h2d_ms": h2d_ms, "device_ms": device_ms,
                 "fetch_ms": fetch_ms},
        "requests": requests,
        "concurrency": concurrency,
        "depth1": depth1,
        "depth2": depth2,
        "speedup": speedup,
        "byte_identical": byte_identical,
        "fault": fault,
        "claims": {
            "speedup_ge_1_3": speedup >= 1.3,
            "byte_identical": byte_identical,
            "zero_lost_under_faults": zero_lost,
            "zero_steady_state_recompiles": zero_recompiles,
        },
    }
    return records, report


def bench_serve_fleet(
    requests_per_backend: int = 120,
    concurrency_per_backend: int = 32,
    service_ms: float = 50.0,
    fleet_sizes=(1, 2, 4),
):
    """Multi-host serving fleet (ISSUE 19): a wire-protocol gateway
    fanning traffic over N backend engine *processes*.

    Four phases against stub backends whose device stall is a
    calibrated sleep (the ``--serve_overlap`` discipline — measures the
    serve path, not model FLOPs; digests are pure functions of pixels
    so every identity check is exact):

    1. direct in-process engine, the reference responses;
    2. gateway over ONE backend process, same seed — responses must be
       byte-identical to (1): the wire adds routing, never bytes;
    3. weak-scaling sweep over ``fleet_sizes`` processes (requests and
       concurrency scale with N) — aggregate imgs/s vs the 1-backend
       gateway is the scale-out claim;
    4. chaos — SIGKILL one of two backends mid-load: zero lost
       requests, and every response byte-identical to the unfaulted
       2-backend run (requeued work re-executes to the same bytes).
    """
    import threading

    from mx_rcnn_tpu.serve import loadgen
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.fleet import (
        FleetGateway,
        _FleetStubRunner,
        spawn_stub_backends,
    )

    sizes = ((24, 24), (32, 48))
    n_req, conc = requests_per_backend, concurrency_per_backend

    def run_gateway(n_backends: int, collect: bool,
                    chaos_kill_at: float = 0.0):
        procs = spawn_stub_backends(n_backends, service_ms=service_ms)
        gw = FleetGateway(
            [p.addr for p in procs], fail_threshold=2
        ).start()
        killer = None
        if chaos_kill_at > 0.0:
            killer = threading.Timer(chaos_kill_at, procs[0].kill)
            killer.start()
        try:
            rep = loadgen.run_load(
                gw, num_requests=n_req * n_backends,
                concurrency=conc * n_backends, sizes=sizes, seed=0,
                collect=collect,
            )
            rep["gateway"] = gw.snapshot()
            rep["fleet"] = gw.fleet_snapshot()
            return rep
        finally:
            if killer is not None:
                killer.cancel()
            gw.stop()
            for p in procs:
                p.stop()

    # -- phase 1: the direct engine reference ------------------------
    print("# fleet phase 1: direct in-process engine", flush=True)
    engine = ServingEngine(
        _FleetStubRunner(service_ms=service_ms), max_linger=0.004,
        max_queue=512,
    )
    with engine:
        direct = loadgen.run_load(
            engine, num_requests=n_req, concurrency=conc, sizes=sizes,
            seed=0, collect=True,
        )

    # -- phase 2 + 3: gateway sweep (N=1 doubles as the identity run) -
    sweep = {}
    for n in fleet_sizes:
        print(f"# fleet phase 2/3: gateway over {n} backend "
              f"process(es)", flush=True)
        sweep[n] = run_gateway(n, collect=(n in (1, 2)))

    def outcomes_ok(rep):
        return rep["outcomes"]["ok"]

    def results_identical(a, b, n_expect):
        ra, rb = a["_results"], b["_results"]
        if len(ra) != n_expect or len(rb) != n_expect:
            return False
        for i in range(n_expect):
            ka, va = ra[i]
            kb, vb = rb[i]
            if ka != "ok" or kb != "ok" or not _dets_equal(va, vb):
                return False
        return True

    n1_identical = results_identical(direct, sweep[1], n_req)

    base_ips = sweep[1]["imgs_per_sec"]
    scaling = [
        {
            "backends": n,
            "imgs_per_sec": round(sweep[n]["imgs_per_sec"], 2),
            "speedup_x": round(sweep[n]["imgs_per_sec"] / base_ips, 3),
            "ok": outcomes_ok(sweep[n]),
            "requests": n_req * n,
        }
        for n in fleet_sizes
    ]

    # -- phase 4: SIGKILL one of two backends mid-load ---------------
    print("# fleet phase 4: chaos — SIGKILL one of 2 backends",
          flush=True)
    # kill ~25% into the unfaulted 2-backend wall time, while the
    # victim still holds a full window of in-flight requests
    kill_at = max(0.05, sweep[2]["wall_s"] * 0.25)
    chaos = run_gateway(2, collect=True, chaos_kill_at=kill_at)
    chaos_ok = outcomes_ok(chaos)
    chaos_lost = n_req * 2 - chaos_ok
    chaos_identical = results_identical(sweep[2], chaos, n_req * 2)
    chaos_gw = chaos["gateway"]["gateway"]

    claims = {
        "n1_byte_identical": bool(n1_identical),
        "scaling_2x": scaling[1]["speedup_x"] >= 1.7,
        "scaling_4x": scaling[2]["speedup_x"] >= 3.0,
        "chaos_zero_lost": chaos_lost == 0,
        "chaos_byte_identical": bool(chaos_identical),
    }

    records = [
        {"metric": f"serve_fleet_imgs_per_sec_{n}",
         "value": round(sweep[n]["imgs_per_sec"], 2), "unit": "imgs/s",
         "vs_baseline": None}
        for n in fleet_sizes
    ] + [
        {"metric": "serve_fleet_speedup_2x",
         "value": scaling[1]["speedup_x"], "unit": "x",
         "vs_baseline": None},
        {"metric": "serve_fleet_speedup_4x",
         "value": scaling[2]["speedup_x"], "unit": "x",
         "vs_baseline": None},
        {"metric": "serve_fleet_n1_byte_identical",
         "value": int(n1_identical), "unit": "bool", "vs_baseline": None},
        {"metric": "serve_fleet_chaos_lost",
         "value": chaos_lost, "unit": "requests", "vs_baseline": None},
        {"metric": "serve_fleet_chaos_requeued",
         "value": chaos_gw["requeued"], "unit": "requests",
         "vs_baseline": None},
        {"metric": "serve_fleet_chaos_byte_identical",
         "value": int(chaos_identical), "unit": "bool",
         "vs_baseline": None},
        {"metric": "serve_fleet_chaos_hedged",
         "value": chaos_gw["hedged"], "unit": "requests",
         "vs_baseline": None},
    ]
    report = {
        "stub": {"service_ms": service_ms,
                 "requests_per_backend": n_req,
                 "concurrency_per_backend": conc},
        "scaling": scaling,
        "chaos": {
            "killed_at_s": round(kill_at, 3),
            "ok": chaos_ok,
            "lost": chaos_lost,
            "requeued": chaos_gw["requeued"],
            "hedged": chaos_gw["hedged"],
            "abandoned": chaos_gw["abandoned"],
            "byte_identical": bool(chaos_identical),
            "links": chaos["gateway"]["links"],
        },
        "claims": claims,
    }
    # drop the replay payloads before the artifact is serialized
    for rep in (direct, chaos, *sweep.values()):
        rep.pop("_results", None)
        rep.pop("_times", None)
    return records, report


def bench_serve_slo(
    network: str,
    probes: int = 5,
    probe_spacing_s: float = 10.0,
    bulk_concurrency: int = 32,
    max_batch: int = 2,
    backlog_s: float = 2.0,
    bulk_age_limit: float = 2.0,
    cache_lookups: int = 8,
) -> tuple:
    """SLO-tier serving bench: sparse interactive probes against a
    saturating bulk backlog, single-lane vs two-lane.

    Two phases over ONE runner (so the compile cache spans both — the
    cross-lane zero-recompile evidence): a *baseline* phase submits the
    probes untagged (they queue FIFO behind the backlog, today's
    single-lane behavior) and a *two-lane* phase tags them
    ``interactive`` (they preempt bulk for the next device slot).  The
    probe stream is OPEN-LOOP — one probe every ``probe_spacing_s``
    regardless of completion — so both phases offer the same interactive
    arrival rate and the bulk-throughput comparison is apples-to-apples.
    Bulk is a closed loop of ``bulk_concurrency`` clients that refills
    until the probes finish (exhaustion can't deflate the baseline).

    ``probe_spacing_s`` sets the retention floor: a two-lane probe takes
    a whole batch slot (lane-pure batch-of-1) where a baseline probe
    shares one, so bulk gives up ``max_batch - 1`` image slots per probe
    — spacing must dwarf the per-batch service time for bulk throughput
    to hold within the 10% acceptance band.

    Then two short phases on the same registry: an idempotent response-
    cache phase (same image ``cache_lookups`` times; hits must be
    byte-identical to the miss) and a bf16 serve-graph phase (a second
    runner at ``precision="bfloat16"`` whose warmup runs the detection-
    parity gate against f32 — the report lands in the artifact).

    → (records, report) in the standard artifact shape.
    """
    import dataclasses as _dc
    import threading

    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve.batcher import QueueFull
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import synthetic_image
    from mx_rcnn_tpu.serve.registry import DEFAULT_MODEL, ModelRegistry
    from mx_rcnn_tpu.serve.respcache import ResponseCache
    from mx_rcnn_tpu.serve.runner import ServeRunner
    from mx_rcnn_tpu.tools.serve import random_params, small_config

    # smaller than the serve-bench small_config: scheduling contrast is
    # the point, so short service times let a deep backlog stay cheap
    cfg = small_config(network).replace(
        SHAPE_BUCKETS=((64, 96), (96, 96)),
    )
    cfg = cfg.replace(
        dataset=_dc.replace(cfg.dataset, SCALES=((64, 96),))
    )
    bulk_sizes = ((48, 64), (64, 72), (96, 64))  # 2 rungs exercised
    probe_hw = (48, 64)                          # smallest rung
    model = build_model(cfg)
    params = random_params(model, cfg, 0)
    registry = ModelRegistry()
    registry.register(DEFAULT_MODEL, model, cfg, params)
    runner = ServeRunner(registry=registry, max_batch=max_batch)
    misses_warm = runner.warmup()

    def phase(probe_lane):
        stop = threading.Event()
        bulk_ok: list = []
        bulk_failed: list = []
        lats_ms: list = []
        idx_lock = threading.Lock()
        idx = [0]

        engine = ServingEngine(
            runner, max_queue=128, in_flight=1,
            bulk_age_limit=bulk_age_limit,
        )

        def bulk_client():
            while not stop.is_set():
                with idx_lock:
                    i = idx[0]
                    idx[0] += 1
                h, w = bulk_sizes[i % len(bulk_sizes)]
                im = synthetic_image(i, h, w, seed=0)
                try:
                    fut = engine.submit(im)
                except QueueFull:
                    time.sleep(0.005)
                    continue
                except RuntimeError:
                    return  # engine stopping
                try:
                    fut.result()
                    bulk_ok.append(1)
                except Exception:  # noqa: BLE001 — counted, not fatal
                    bulk_failed.append(1)

        with engine:
            clients = [
                threading.Thread(target=bulk_client, daemon=True,
                                 name=f"slo-bulk-{t}")
                for t in range(bulk_concurrency)
            ]
            for t in clients:
                t.start()
            time.sleep(backlog_s)  # saturate before the first probe
            t_win = time.monotonic()
            n0 = len(bulk_ok)
            futs = []
            for k in range(probes):
                im = synthetic_image(1_000_000 + k, *probe_hw, seed=1)
                lkw = {} if probe_lane is None else {"lane": probe_lane}
                t0 = time.monotonic()
                f = engine.submit(im, **lkw)
                f.add_done_callback(
                    lambda _f, _t0=t0: lats_ms.append(
                        (time.monotonic() - _t0) * 1000.0
                    )
                )
                futs.append(f)
                time.sleep(probe_spacing_s)
            for f in futs:
                f.result()  # raises if any probe failed
            window = time.monotonic() - t_win
            bulk_done = len(bulk_ok) - n0
            stop.set()
        for t in clients:
            t.join(timeout=30.0)
        snap = engine.snapshot()
        r = snap["requests"]
        return {
            "probe_lane": probe_lane or "untagged(bulk)",
            "interactive_ms": {
                "p50": _pctl_ms(lats_ms, 50),
                "p99": _pctl_ms(lats_ms, 99),
                "samples": sorted(round(x, 1) for x in lats_ms),
            },
            "bulk_imgs_per_sec": round(bulk_done / window, 3),
            "bulk_completed_in_window": bulk_done,
            "bulk_failed": len(bulk_failed),
            "window_s": round(window, 3),
            "lost_requests": (
                r["submitted"] - r["completed"] - r["failed"]
                - r["expired"] - r["stopped"]
            ),
            "scheduler": snap["scheduler"],
            "lanes": snap.get("lanes", {}),
        }

    baseline = phase(None)
    two_lane = phase("interactive")
    misses_steady = runner.compile_cache.misses - misses_warm

    # --- idempotent response cache: same image again must be a hit and
    # byte-identical to what the miss computed
    cache = ResponseCache(capacity=32)
    with ServingEngine(runner, response_cache=cache) as engine:
        im = synthetic_image(424_242, *probe_hw, seed=2)
        ref = engine.submit(im).result()
        hits = [
            engine.submit(im).result() for _ in range(cache_lookups)
        ]
    cache_identical = all(_dets_equal(ref, h) for h in hits)
    cache_snap = cache.snapshot()

    # --- bf16 serve graph: second runner on the SAME registry/params;
    # its warmup runs the f32 detection-parity gate (raises on drift)
    runner_bf16 = ServeRunner(
        registry=registry, max_batch=max_batch, precision="bfloat16"
    )
    runner_bf16.warmup()
    parity = dict(
        runner_bf16.parity[f"{registry.default_model}:bf16"]
    )

    def service_s(r):
        req = r.make_request(synthetic_image(7, *probe_hw, seed=3))
        b = r.assemble([req])
        r.run(b)
        t0 = time.monotonic()
        for _ in range(3):
            r.run(b)
        return round((time.monotonic() - t0) / 3, 4)

    svc = {"f32": service_s(runner), "bf16": service_s(runner_bf16)}

    p99_base = baseline["interactive_ms"]["p99"]
    p99_two = two_lane["interactive_ms"]["p99"]
    speedup = round(p99_base / p99_two, 2) if p99_two else None
    retention = (
        round(
            two_lane["bulk_imgs_per_sec"] / baseline["bulk_imgs_per_sec"], 4
        )
        if baseline["bulk_imgs_per_sec"] else None
    )
    report = {
        "config": {
            "network": network,
            "buckets": [list(b) for b in cfg.SHAPE_BUCKETS],
            "max_batch": max_batch,
            "probes": probes,
            "probe_spacing_s": probe_spacing_s,
            "bulk_concurrency": bulk_concurrency,
            "bulk_age_limit": bulk_age_limit,
        },
        "baseline": baseline,
        "two_lane": two_lane,
        "compile": {
            "warmup_misses": misses_warm,
            "steady_state_misses": misses_steady,
        },
        "response_cache": dict(cache_snap, byte_identical=cache_identical),
        "bf16": {"parity": parity, "service_s": svc},
    }
    tag = _METRIC_NAMES[network].replace("_e2e", "")
    records = [
        {"metric": f"serve_slo_interactive_p99_ms_baseline_{tag}",
         "value": p99_base, "unit": "ms", "vs_baseline": None},
        {"metric": f"serve_slo_interactive_p99_ms_two_lane_{tag}",
         "value": p99_two, "unit": "ms", "vs_baseline": None},
        {"metric": f"serve_slo_interactive_p99_speedup_{tag}",
         "value": speedup, "unit": "x", "vs_baseline": None},
        {"metric": f"serve_slo_bulk_imgs_per_sec_baseline_{tag}",
         "value": baseline["bulk_imgs_per_sec"], "unit": "imgs/sec",
         "vs_baseline": None},
        {"metric": f"serve_slo_bulk_imgs_per_sec_two_lane_{tag}",
         "value": two_lane["bulk_imgs_per_sec"], "unit": "imgs/sec",
         "vs_baseline": None},
        {"metric": f"serve_slo_bulk_retention_{tag}",
         "value": retention, "unit": "fraction", "vs_baseline": None},
        {"metric": f"serve_slo_preemptions_{tag}",
         "value": two_lane["scheduler"]["preemptions"], "unit": "count",
         "vs_baseline": None},
        {"metric": f"serve_slo_cache_hit_rate_{tag}",
         "value": cache_snap["hit_rate"], "unit": "fraction",
         "vs_baseline": None},
        {"metric": f"serve_slo_steady_state_compile_misses_{tag}",
         "value": misses_steady, "unit": "compiles", "vs_baseline": None},
        {"metric": f"serve_slo_lost_requests_{tag}",
         "value": baseline["lost_requests"] + two_lane["lost_requests"],
         "unit": "count", "vs_baseline": None},
        {"metric": f"serve_slo_bf16_parity_max_box_delta_px_{tag}",
         "value": parity.get("max_box_delta_px"), "unit": "px",
         "vs_baseline": None},
    ]
    return records, report


# serve-fault scenario grid: one MX_RCNN_FAULTS spec per scenario.
# Ordinal 0 on every replica is its initial warmup probe, so injected
# ordinals start at 1 to land on live traffic, not warmup.
_FAULT_SCENARIOS = {
    # clean pool: the reference run the faulted runs are diffed against
    "healthy": "",
    # hard wedge past the stall watchdog on replica 1: trips DRAINING,
    # the in-flight batch requeues, the replica rewarms and rejoins
    "wedged": "replica_wedge@1.3:10",
    # replica 2 flaps: four consecutive dispatches/probes fail, tripping
    # the breaker twice (backoff doubling) before the pool readmits it
    "flapping": ("predict_fail@2.1,predict_fail@2.2,"
                 "predict_fail@2.3,predict_fail@2.4"),
}


def _recovery_s(pool_snap: dict) -> float:
    """Max DRAINING→HEALTHY-rejoin span across replicas, from the
    transition log (None when nothing tripped)."""
    spans = []
    for rep in pool_snap.get("replicas", []):
        drain_t = None
        for tr in rep["transitions"]:
            if tr["to"] == "draining" and drain_t is None:
                drain_t = tr["t"]
            elif drain_t is not None and tr["to"] == "healthy":
                spans.append(tr["t"] - drain_t)
                drain_t = None
    return round(max(spans), 3) if spans else None


def bench_serve_fault(
    network: str,
    requests: int,
    concurrency: int,
    max_batch: int,
    linger_ms: float,
    replicas: int = 3,
    small: bool = True,
) -> tuple:
    """Fault-matrix serving bench: the same deterministic load against a
    ≥3-replica pool under each ``_FAULT_SCENARIOS`` spec.

    Proves the ISSUE 6 acceptance criteria outside the unit suite: zero
    lost requests under every scenario (ok + deadline + error ==
    submitted), detections byte-identical to the healthy run for every
    index that succeeded in both, and the wedged replica's
    drain→rewarm→rejoin visible as a measured recovery time.  Runners
    are built ``deterministic=True`` so cross-replica results are
    bitwise comparable on CPU (the thunk runtime reassociates reductions
    otherwise).
    """
    import os

    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import run_load
    from mx_rcnn_tpu.serve.replica import HealthPolicy
    from mx_rcnn_tpu.serve.router import ReplicaPool
    from mx_rcnn_tpu.utils import faults

    replicas = max(3, replicas)
    _, _, _, sizes, factory = _serve_model(
        network, small, max_batch, deterministic=True
    )
    # timeouts sized to CPU service times (~1-3 s/batch on the small
    # config): hedge before the watchdog, watchdog well under the wedge
    policy = HealthPolicy(stall_timeout=6.0, breaker_backoff=0.25,
                          breaker_max_backoff=4.0)
    scenarios = {}
    baseline_ok = None
    prior = os.environ.get(faults.ENV_VAR)
    try:
        for name, spec in _FAULT_SCENARIOS.items():
            if spec:
                os.environ[faults.ENV_VAR] = spec
            else:
                os.environ.pop(faults.ENV_VAR, None)
            faults.reset()
            pool = ReplicaPool(
                factory, n_replicas=replicas, policy=policy,
                hedge_timeout=3.0,
            )
            engine = ServingEngine(
                pool, max_linger=linger_ms / 1000.0, in_flight=replicas
            )
            with engine:
                report = run_load(
                    engine, num_requests=requests,
                    concurrency=concurrency, sizes=sizes, seed=0,
                    collect=True,
                )
            # A tripped replica's drain→recompile→rewarm→rejoin usually
            # outlives the load itself on CPU (rewarm recompiles the
            # whole ladder), so wait it out — bounded — before the final
            # snapshot; otherwise recovery_s is null, not measured.
            if spec:
                t_wait = time.time()
                while time.time() - t_wait < 120.0:
                    reps = pool.snapshot()["replicas"]
                    tripped = any(
                        tr["to"] == "draining"
                        for r in reps for tr in r["transitions"]
                    )
                    if tripped and all(
                        r["state"] == "healthy" for r in reps
                    ):
                        break
                    if not tripped and time.time() - t_wait > 20.0:
                        break  # fault never fired this run
                    time.sleep(0.5)
            pool_snap = pool.snapshot()
            pool.close()
            results = report.pop("_results")
            ok = {i: r for i, (kind, r) in results.items() if kind == "ok"}
            if name == "healthy":
                baseline_ok = ok
                identical = True
            else:
                identical = all(
                    _dets_equal(baseline_ok[i], ok[i])
                    for i in ok if i in baseline_ok
                )
            out = report["outcomes"]
            resolved = out["ok"] + out["deadline"] + out["error"]
            scenarios[name] = {
                "spec": spec,
                "p50_ms": report["engine"]["latency"]["e2e"]["p50_ms"],
                "p99_ms": report["engine"]["latency"]["e2e"]["p99_ms"],
                "imgs_per_sec": report["imgs_per_sec"],
                "outcomes": out,
                "lost_requests": requests - resolved,
                "detections_match_healthy": identical,
                "recovery_s": _recovery_s(pool_snap),
                "shed": report["engine"]["requests"]["shed"],
                "routing": pool_snap["routing"],
                "transitions": {
                    rep["index"]: rep["transitions"]
                    for rep in pool_snap["replicas"]
                },
            }
    finally:
        if prior is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = prior
        faults.reset()

    tag = _METRIC_NAMES[network].replace("_e2e", "")
    records = []
    for name, s in scenarios.items():
        records.append({
            "metric": f"serve_fault_{name}_p99_ms_{tag}",
            "value": s["p99_ms"], "unit": "ms", "vs_baseline": None,
        })
        records.append({
            "metric": f"serve_fault_{name}_lost_requests_{tag}",
            "value": s["lost_requests"], "unit": "requests",
            "vs_baseline": None,
        })
    records.append({
        "metric": f"serve_fault_wedged_recovery_s_{tag}",
        "value": scenarios["wedged"]["recovery_s"], "unit": "seconds",
        "vs_baseline": None,
    })
    records.append({
        "metric": f"serve_fault_detections_match_{tag}",
        "value": int(all(
            s["detections_match_healthy"] for s in scenarios.values()
        )),
        "unit": "bool", "vs_baseline": None,
    })
    report = {
        "replicas": replicas,
        "requests": requests,
        "concurrency": concurrency,
        "policy": {"stall_timeout": policy.stall_timeout,
                   "hedge_timeout": 3.0,
                   "breaker_backoff": policy.breaker_backoff},
        "scenarios": scenarios,
    }
    return records, report


def _dets_equal(a, b) -> bool:
    """Per-class detection lists compare bitwise."""
    if len(a) != len(b):
        return False
    return all(
        np.asarray(x).shape == np.asarray(y).shape
        and np.asarray(x).tobytes() == np.asarray(y).tobytes()
        for x, y in zip(a, b)
    )


def bench_poison(
    network: str,
    requests: int,
    concurrency: int,
    max_batch: int,
    linger_ms: float,
    replicas: int = 2,
    k: int = 2,
    small: bool = True,
) -> tuple:
    """Query-of-death containment bench (ISSUE 12 acceptance evidence).

    A 2-replica pool serves a deterministic mix of ~5% well-formed
    poison (the per-size :func:`qod_image`, whose digests the fault spec
    wires to ``poison_fail``) inside healthy traffic.  One clean run
    (no faults, no quarantine) provides the byte-identity baseline; the
    poisoned run must then show the four containment claims:

    * zero healthy losses — every non-poison request resolves ok;
    * healthy detections byte-identical to the unfaulted run;
    * every poison digest quarantined after <= K independent trips
      (global trip count bounded by ``digests * (k + 1)``, the +1
      absorbing a concurrent-trip race across replicas);
    * all replicas HEALTHY at the end — the pool outlives the poison.
    """
    import os

    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import qod_image, run_load
    from mx_rcnn_tpu.serve.quarantine import QuarantineTable, request_digest
    from mx_rcnn_tpu.serve.replica import HealthPolicy
    from mx_rcnn_tpu.serve.router import ReplicaPool
    from mx_rcnn_tpu.utils import faults

    replicas = max(2, replicas)
    seed = 0
    mix = [None] * 17 + ["qod"]  # ~5% poison
    _, _, _, sizes, factory = _serve_model(
        network, small, max_batch, deterministic=True
    )
    # fail_threshold=1: a single predict failure trips the replica, so
    # every poison execution becomes an attributable trip — the regime
    # the K-trip quarantine bound is stated against (the default lenient
    # threshold lets interleaved healthy successes reset the consecutive
    # count and a qod then burns retry budget without ever tripping)
    policy = HealthPolicy(stall_timeout=6.0, fail_threshold=1,
                          breaker_backoff=0.25, breaker_max_backoff=4.0)

    # replicate run_load's rng discipline (sizes then poison, no models/
    # lanes) to learn which sizes the poisoned indices land on — that is
    # the set of digests the fault spec must target
    rng = np.random.RandomState(seed)
    req_sizes = [sizes[rng.randint(len(sizes))] for _ in range(requests)]
    req_poison = [mix[rng.randint(len(mix))] for _ in range(requests)]
    healthy_idx = [i for i, fl in enumerate(req_poison) if fl is None]
    digests = sorted({
        request_digest(qod_image(h, w, seed))
        for (h, w), fl in zip(req_sizes, req_poison) if fl == "qod"
    })
    spec = ",".join(f"poison_fail@{d[:12]}" for d in digests)

    def one_run(poisoned: bool):
        if poisoned:
            os.environ[faults.ENV_VAR] = spec
        else:
            os.environ.pop(faults.ENV_VAR, None)
        faults.reset()
        qt = QuarantineTable(k=k, ttl_s=600.0) if poisoned else None
        # budget x no_healthy_wait is the pool-outage tolerance: with 2
        # replicas and fail_threshold=1 both can be rewarming at once (a
        # full ladder recompile on CPU), and a healthy request spends one
        # resubmit per NoHealthyReplica lap — 32 laps x 5 s outlasts the
        # worst dual-rewarm window while still bounding a true qod to a
        # handful of spends before quarantine ends its circulation
        pool = ReplicaPool(
            factory, n_replicas=replicas, policy=policy,
            hedge_timeout=3.0, no_healthy_wait=5.0, quarantine=qt,
        )
        engine = ServingEngine(
            pool, max_linger=linger_ms / 1000.0, in_flight=replicas,
            retry_budget=32,
        )
        with engine:
            report = run_load(
                engine, num_requests=requests, concurrency=concurrency,
                sizes=sizes, seed=seed, collect=True, poison_mix=mix,
            )
        if poisoned:
            # wait out the tripped replicas' drain->rewarm->rejoin so
            # "all replicas healthy" is measured, not raced
            t_wait = time.time()
            while time.time() - t_wait < 120.0:
                reps = pool.snapshot()["replicas"]
                if all(r["state"] == "healthy" for r in reps):
                    break
                time.sleep(0.5)
        pool_snap = pool.snapshot()
        pool.close()
        return report, pool_snap, (qt.snapshot() if qt else None)

    prior = os.environ.get(faults.ENV_VAR)
    try:
        base_report, _, _ = one_run(poisoned=False)
        poi_report, pool_snap, q_snap = one_run(poisoned=True)
    finally:
        if prior is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = prior
        faults.reset()

    base_res = base_report.pop("_results")
    poi_res = poi_report.pop("_results")
    base_report.pop("_times", None)
    poi_report.pop("_times", None)

    healthy_lost = sum(
        1 for i in healthy_idx if poi_res.get(i, ("lost",))[0] != "ok"
    )
    byte_identical = all(
        poi_res.get(i, ("lost",))[0] == "ok"
        and base_res.get(i, ("lost",))[0] == "ok"
        and _dets_equal(base_res[i][1], poi_res[i][1])
        for i in healthy_idx
    )
    all_healthy = all(
        r["state"] == "healthy" for r in pool_snap["replicas"]
    )
    quarantined = set(q_snap["quarantined"])
    within_k = (
        all(d[:12] in quarantined for d in digests)
        and q_snap["trips"] <= len(digests) * (k + 1)
    )
    claims = {
        "zero_healthy_lost": healthy_lost == 0,
        "healthy_byte_identical": byte_identical,
        "poison_quarantined_within_k": within_k,
        "all_replicas_healthy": all_healthy,
    }

    tag = _METRIC_NAMES[network].replace("_e2e", "")
    records = [
        {"metric": f"serve_poison_healthy_lost_{tag}",
         "value": healthy_lost, "unit": "requests", "vs_baseline": None},
        {"metric": f"serve_poison_healthy_byte_identical_{tag}",
         "value": int(byte_identical), "unit": "bool", "vs_baseline": None},
        {"metric": f"serve_poison_quarantined_within_k_{tag}",
         "value": int(within_k), "unit": "bool", "vs_baseline": None},
        {"metric": f"serve_poison_replicas_healthy_{tag}",
         "value": int(all_healthy), "unit": "bool", "vs_baseline": None},
        {"metric": f"serve_poison_trips_{tag}",
         "value": q_snap["trips"], "unit": "trips", "vs_baseline": None},
        {"metric": f"serve_poison_fastfail_hits_{tag}",
         "value": q_snap["fastfail_hits"], "unit": "requests",
         "vs_baseline": None},
    ]
    report = {
        "replicas": replicas,
        "requests": requests,
        "concurrency": concurrency,
        "k": k,
        "poison_mix_rate": mix.count("qod") / len(mix),
        "poison_requests": requests - len(healthy_idx),
        "digests": [d[:12] for d in digests],
        "fault_spec": spec,
        "claims": claims,
        "baseline": {"outcomes": base_report["outcomes"]},
        "poisoned": {
            "outcomes": poi_report["outcomes"],
            "poison_outcomes": poi_report.get("poison_outcomes"),
            "engine_requests": poi_report["engine"]["requests"],
            "quarantine": q_snap,
        },
    }
    return records, report


def bench_swap(
    network: str,
    requests: int,
    concurrency: int,
    max_batch: int,
    linger_ms: float,
    small: bool = True,
    replicas: int = 2,
) -> tuple:
    """Model-lifecycle bench (ISSUE 7): live hot-swap under load, the
    fault-rollback matrix, and two-family tenancy through one batcher.

    Three scenarios, each with ``deterministic=True`` runners so results
    are bitwise comparable across waves and engines:

    * ``hot_swap`` — one engine serves three load waves: wave A pins the
      v1 reference detections, wave B runs with a background
      ``engine.swap`` firing mid-load (blocking through commit + canary),
      wave C pins v2.  Wave B requests are classified against the swap
      window via per-request timestamps: done-before must match v1
      byte-for-byte, submitted-after must match v2, straddlers must
      match one of the two.  Zero lost/failed requests and ZERO compile
      misses from warmup through the swap (the candidate warms through
      the already-compiled executables — params are a jit argument).
    * ``rollback`` — one registry takes three swap attempts faulted (by
      registry-wide swap ordinal) at verify, warm, and canary; after
      every rollback a load wave must still serve v1 bytes, and the 4th
      (unfaulted) swap must land v2.
    * ``tenancy`` — a second model family rides the same batcher/ladder;
      two identical mixed-model waves prove per-(model, bucket) compile
      hits after warmup: zero steady-state recompiles.
    """
    import os
    import tempfile
    import threading

    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.checkpoint import save_checkpoint
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import DEFAULT_SIZES, run_load
    from mx_rcnn_tpu.serve.registry import (
        DEFAULT_MODEL,
        ModelRegistry,
        SwapRolledBack,
    )
    from mx_rcnn_tpu.serve.router import ReplicaPool, make_replica_factory
    from mx_rcnn_tpu.serve.runner import ServeRunner
    from mx_rcnn_tpu.tools.serve import small_config
    from mx_rcnn_tpu.utils import faults

    if small:
        cfg = small_config(network)
        sizes = ((72, 96), (96, 128), (64, 80))
    else:
        cfg = generate_config(network, "PascalVOC")
        sizes = DEFAULT_SIZES
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]

    def init_params(seed):
        return model.init(
            {"params": jax.random.key(seed)},
            np.zeros((1, h, w, 3), np.float32),
            np.array([[h, w, 1.0]], np.float32),
            train=False,
        )["params"]

    params_v1 = init_params(0)
    # same structure/shapes, different values: the signature gate admits
    # it and the swap visibly changes detections
    ckpt_v2 = save_checkpoint(
        os.path.join(tempfile.mkdtemp(prefix="bench-swap-"), "v2"),
        {"params": init_params(1)}, 1,
    )

    def make_engine(n_replicas):
        reg = ModelRegistry()
        reg.register(DEFAULT_MODEL, model, cfg, params_v1)
        if n_replicas > 1:
            factory = make_replica_factory(
                lambda registry, device: ServeRunner(
                    registry=registry, device=device, max_batch=max_batch,
                    deterministic=True,
                ),
                registry=reg,
            )
            runner = ReplicaPool(factory, n_replicas=n_replicas)
        else:
            runner = ServeRunner(
                registry=reg, max_batch=max_batch, deterministic=True
            )
        eng = ServingEngine(
            runner, max_linger=linger_ms / 1000.0,
            in_flight=max(2, n_replicas),
        )
        return eng, runner

    def load(eng, n=requests, models=None):
        return run_load(
            eng, num_requests=n, concurrency=concurrency, sizes=sizes,
            seed=0, collect=True, models=models,
        )

    def ok_dets(report):
        return {
            i: r for i, (kind, r) in report["_results"].items() if kind == "ok"
        }

    def wave_summary(report):
        out = report["outcomes"]
        resolved = out["ok"] + out["deadline"] + out["error"]
        return {
            "outcomes": out,
            "lost_requests": report["requests"] - resolved,
            "imgs_per_sec": report["imgs_per_sec"],
            "wall_s": report["wall_s"],
        }

    # ------------------------------------------- scenario 1: hot_swap
    # the swap wave runs 2x requests: the blocking swap (dominated by
    # the host-side checkpoint restore on CPU) must RETURN while load is
    # still flowing, or no request lands entirely after the window
    n_swap = 2 * requests
    eng, runner = make_engine(max(1, replicas))
    swap_out = {}
    with eng:
        rep_a = load(eng, n=n_swap)
        ref_v1 = ok_dets(rep_a)
        misses_warm = eng.snapshot()["compile"]["misses"]
        base_done = eng.metrics.completed

        def fire_swap():
            # wait until wave B is genuinely mid-flight, then block
            # through the full verify → warm → commit → canary pipeline
            t_end = time.time() + 120.0
            while (eng.metrics.completed - base_done < max(1, requests // 3)
                   and time.time() < t_end):
                time.sleep(0.002)
            swap_out["t0"] = time.monotonic()
            try:
                swap_out["result"] = eng.swap(
                    DEFAULT_MODEL, ckpt_v2, block=True, timeout=300
                )
            except Exception as e:  # noqa: BLE001 — recorded as evidence
                swap_out["error"] = repr(e)
            swap_out["t1"] = time.monotonic()

        th = threading.Thread(target=fire_swap, name="bench-swap")
        th.start()
        rep_b = load(eng, n=n_swap)
        th.join()
        rep_c = load(eng, n=n_swap)
        ref_v2 = ok_dets(rep_c)
        snap = eng.snapshot()
    if hasattr(runner, "close"):
        runner.close()

    misses_end = snap["compile"]["misses"]
    dets_b, times_b = ok_dets(rep_b), rep_b["_times"]
    t0, t1 = swap_out.get("t0"), swap_out.get("t1")
    pre = post = straddle = 0
    pre_ok = post_ok = straddle_ok = True
    for i, (ts, td) in times_b.items():
        if i not in dets_b or t0 is None:
            continue
        if td <= t0:
            pre += 1
            pre_ok &= _dets_equal(dets_b[i], ref_v1[i])
        elif ts >= t1:
            post += 1
            post_ok &= _dets_equal(dets_b[i], ref_v2[i])
        else:
            straddle += 1
            straddle_ok &= (
                _dets_equal(dets_b[i], ref_v1[i])
                or _dets_equal(dets_b[i], ref_v2[i])
            )
    versions_changed_output = sum(
        1 for i in ref_v1 if i in ref_v2 and not _dets_equal(ref_v1[i], ref_v2[i])
    )
    waves = [wave_summary(r) for r in (rep_a, rep_b, rep_c)]
    hot_swap = {
        "replicas": max(1, replicas),
        "wave_requests": n_swap,
        "waves": waves,
        "lost_requests": sum(wv["lost_requests"] for wv in waves),
        "failed_requests": sum(
            wv["outcomes"]["error"] + wv["outcomes"]["deadline"]
            for wv in waves
        ),
        "swap": swap_out.get("result", swap_out.get("error")),
        "swap_block_wall_s": (
            round(t1 - t0, 3) if t0 is not None else None
        ),
        "window": {
            "pre": pre, "post": post, "straddle": straddle,
            "pre_byte_identical_v1": bool(pre_ok),
            "post_byte_identical_v2": bool(post_ok),
            "straddle_one_of_two": bool(straddle_ok),
        },
        "versions_changed_output": versions_changed_output,
        "compile_misses_after_warmup": misses_warm,
        "compile_misses_final": misses_end,
        "recompiles_through_swap": misses_end - misses_warm,
        "registry": snap.get("registry"),
    }

    # ------------------------------------------- scenario 2: rollback
    prior = os.environ.get(faults.ENV_VAR)
    rollback = {}
    n_check = max(8, requests // 4)
    try:
        # keyed by registry-wide swap ordinal: attempt 1 dies at verify,
        # 2 at warm, 3 at canary; attempt 4 finds no matching fault
        os.environ[faults.ENV_VAR] = (
            "swap_verify_fail@1,swap_warm_fail@2,canary_fail@3"
        )
        faults.reset()
        eng2, runner2 = make_engine(1)
        with eng2:
            for stage in ("verify", "warm", "canary"):
                entry = {"rolled_back": False}
                try:
                    eng2.swap(DEFAULT_MODEL, ckpt_v2, block=True, timeout=300)
                except SwapRolledBack as e:
                    entry["rolled_back"] = True
                    entry["stage"] = e.stage
                rep = load(eng2, n=n_check)
                dets = ok_dets(rep)
                entry["still_serving_v1_bytes"] = bool(dets) and all(
                    _dets_equal(dets[i], ref_v1[i]) for i in dets
                )
                entry.update(wave_summary(rep))
                rollback[stage] = entry
            final = eng2.swap(DEFAULT_MODEL, ckpt_v2, block=True, timeout=300)
            rep = load(eng2, n=n_check)
            dets = ok_dets(rep)
            rollback["final_swap"] = {
                "result": final,
                "serving_v2_bytes": bool(dets) and all(
                    _dets_equal(dets[i], ref_v2[i]) for i in dets
                ),
                **wave_summary(rep),
            }
            rollback["registry"] = eng2.snapshot().get("registry")
        if hasattr(runner2, "close"):
            runner2.close()
    finally:
        if prior is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = prior
        faults.reset()

    # ------------------------------------------- scenario 3: tenancy
    tenant_net = "vgg" if network != "vgg" else "resnet50"
    t_cfg = small_config(tenant_net) if small else generate_config(
        tenant_net, "PascalVOC"
    )
    t_model = build_model(t_cfg)
    th_, tw_ = t_cfg.SHAPE_BUCKETS[0]
    t_params = t_model.init(
        {"params": jax.random.key(0)},
        np.zeros((1, th_, tw_, 3), np.float32),
        np.array([[th_, tw_, 1.0]], np.float32),
        train=False,
    )["params"]
    reg3 = ModelRegistry()
    reg3.register(DEFAULT_MODEL, model, cfg, params_v1)
    reg3.register("tenant", t_model, t_cfg, t_params)
    runner3 = ServeRunner(
        registry=reg3, max_batch=max_batch, deterministic=True
    )
    eng3 = ServingEngine(
        runner3, max_linger=linger_ms / 1000.0, in_flight=2
    )
    mix = [None, "tenant"]
    with eng3:
        rep1 = load(eng3, models=mix)
        m1 = eng3.snapshot()["compile"]["misses"]
        rep2 = load(eng3, models=mix)
        snap3 = eng3.snapshot()
    tenancy = {
        "families": {DEFAULT_MODEL: network, "tenant": tenant_net},
        "waves": [wave_summary(rep1), wave_summary(rep2)],
        "per_model": snap3.get("models"),
        "compile_misses_after_first_wave": m1,
        "compile_misses_final": snap3["compile"]["misses"],
        "steady_state_recompiles": snap3["compile"]["misses"] - m1,
        "compile_hits": snap3["compile"]["hits"],
    }

    tag = _METRIC_NAMES[network].replace("_e2e", "")
    rollback_ok = all(
        rollback[s]["rolled_back"] and rollback[s]["still_serving_v1_bytes"]
        for s in ("verify", "warm", "canary")
    ) and rollback["final_swap"]["serving_v2_bytes"]
    records = [
        {
            "metric": f"swap_lost_requests_{tag}",
            "value": hot_swap["lost_requests"], "unit": "requests",
            "vs_baseline": None,
        },
        {
            "metric": f"swap_failed_requests_{tag}",
            "value": hot_swap["failed_requests"], "unit": "requests",
            "vs_baseline": None,
        },
        {
            "metric": f"swap_pre_window_byte_identical_{tag}",
            "value": int(pre_ok and pre > 0), "unit": "bool",
            "vs_baseline": None,
        },
        {
            "metric": f"swap_post_window_byte_identical_{tag}",
            "value": int(post_ok and post > 0), "unit": "bool",
            "vs_baseline": None,
        },
        {
            "metric": f"swap_recompiles_through_swap_{tag}",
            "value": hot_swap["recompiles_through_swap"], "unit": "compiles",
            "vs_baseline": None,
        },
        {
            "metric": f"swap_block_wall_s_{tag}",
            "value": hot_swap["swap_block_wall_s"], "unit": "seconds",
            "vs_baseline": None,
        },
        {
            "metric": f"swap_rollback_matrix_ok_{tag}",
            "value": int(rollback_ok), "unit": "bool", "vs_baseline": None,
        },
        {
            "metric": f"swap_tenancy_steady_state_recompiles_{tag}",
            "value": tenancy["steady_state_recompiles"], "unit": "compiles",
            "vs_baseline": None,
        },
    ]
    report = {
        "requests": requests,
        "concurrency": concurrency,
        "max_batch": max_batch,
        "hot_swap": hot_swap,
        "rollback": rollback,
        "tenancy": tenancy,
    }
    return records, report


def bench_rollout(
    network: str,
    requests: int,
    concurrency: int,
    max_batch: int,
    linger_ms: float,
    small: bool = True,
    distill_steps: int = 2,
) -> tuple:
    """Progressive-rollout bench (ISSUE 17): the full candidate
    lifecycle on the real serve stack, CPU-runnable.

    Three scenarios, all with ``deterministic=True`` runners so
    detections are bitwise comparable across waves:

    * ``split_promote`` — a faithful candidate (byte-identical weights,
      new version) rolls out under live load with a 30% traffic split
      and shadow scoring; the evaluator must promote it with zero lost
      requests, zero failed requests, every response byte-identical to
      the v1 reference, and ZERO compile misses from warmup onward
      (candidate warms through the already-compiled executables).
    * ``shadow_rollback`` — a divergent candidate (different random
      init) runs in pure shadow mode (0% split): live traffic must stay
      byte-identical to the incumbent for the whole rollout, the shadow
      comparisons must trip the divergence bounds, and the controller
      must auto-roll-back leaving v1 LIVE and the candidate RETIRED.
    * ``closed_loop`` — served detections are harvested with
      ``tools/distill.py`` into synthetic-schema records, fine-tuned
      with the existing trainer, and the resulting checkpoint is
      submitted back through the rollout — serve→train→serve, ending
      with the distilled model promoted to LIVE.
    """
    import os
    import tempfile

    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.core.checkpoint import save_checkpoint
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import DEFAULT_SIZES, run_load
    from mx_rcnn_tpu.serve.registry import DEFAULT_MODEL, ModelRegistry
    from mx_rcnn_tpu.serve.rollout import RolloutAborted, RolloutPolicy
    from mx_rcnn_tpu.serve.runner import ServeRunner
    from mx_rcnn_tpu.tools import distill
    from mx_rcnn_tpu.tools.serve import small_config

    if small:
        cfg = small_config(network)
        sizes = ((72, 96), (96, 128), (64, 80))
    else:
        cfg = generate_config(network, "PascalVOC")
        sizes = DEFAULT_SIZES
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]

    def init_params(seed):
        return model.init(
            {"params": jax.random.key(seed)},
            np.zeros((1, h, w, 3), np.float32),
            np.array([[h, w, 1.0]], np.float32),
            train=False,
        )["params"]

    params_v1 = init_params(0)
    tmp = tempfile.mkdtemp(prefix="bench-rollout-")
    # faithful candidate: byte-identical weights under a new version —
    # shadow divergence is exactly zero, the promote path is pure
    # lifecycle mechanics
    ckpt_faithful = save_checkpoint(
        os.path.join(tmp, "faithful"), {"params": params_v1}, 1
    )
    # divergent candidate: a different random init — same structure
    # (admitted by the verify gate) but wildly different detections
    ckpt_divergent = save_checkpoint(
        os.path.join(tmp, "divergent"), {"params": init_params(1)}, 1
    )

    def make_engine():
        reg = ModelRegistry()
        reg.register(DEFAULT_MODEL, model, cfg, params_v1)
        runner = ServeRunner(
            registry=reg, max_batch=max_batch, deterministic=True
        )
        eng = ServingEngine(
            runner, max_linger=linger_ms / 1000.0, in_flight=2
        )
        return eng, reg

    def load(eng, n=requests):
        return run_load(
            eng, num_requests=n, concurrency=concurrency, sizes=sizes,
            seed=0, collect=True,
        )

    def ok_dets(report):
        return {
            i: r for i, (kind, r) in report["_results"].items() if kind == "ok"
        }

    def wave_summary(report):
        out = report["outcomes"]
        resolved = out["ok"] + out["deadline"] + out["error"]
        return {
            "outcomes": out,
            "lost_requests": report["requests"] - resolved,
            "imgs_per_sec": report["imgs_per_sec"],
        }

    def wait_state(ro, timeout=180.0):
        t_end = time.time() + timeout
        while time.time() < t_end:
            if ro.state == "evaluating" or ro.done():
                return
            time.sleep(0.01)

    # all waves share seed=0, so detections are comparable by index
    n_wave = 2 * requests

    # -------------------------------------- scenario 1: split_promote
    eng, reg = make_engine()
    with eng:
        ctl = eng.attach_rollout()
        rep_ref = load(eng, n=n_wave)
        ref_v1 = ok_dets(rep_ref)
        misses_warm = eng.snapshot()["compile"]["misses"]
        ro = ctl.start(DEFAULT_MODEL, ckpt_faithful, policy=RolloutPolicy(
            split_pct=30.0, shadow=True, min_compared=4,
            min_served=max(4, requests // 8),
            min_error_samples=10**6, min_latency_samples=10**6,
            hold_s=0.2, eval_interval_s=0.02, score_thresh=0.01,
        ))
        wait_state(ro)
        rep_b = load(eng, n=n_wave)
        promote = ro.result(300)
        rep_c = load(eng, n=n_wave)
        snap = eng.snapshot()
    misses_end = snap["compile"]["misses"]
    dets_b, dets_c = ok_dets(rep_b), ok_dets(rep_c)
    # faithful weights: EVERY response — either arm, before or after
    # the flip — must match the v1 reference byte-for-byte
    split_identical = bool(dets_b) and all(
        _dets_equal(dets_b[i], ref_v1[i]) for i in dets_b
    )
    post_identical = bool(dets_c) and all(
        _dets_equal(dets_c[i], ref_v1[i]) for i in dets_c
    )
    waves = [wave_summary(r) for r in (rep_ref, rep_b, rep_c)]
    promote_lost = sum(wv["lost_requests"] for wv in waves)
    promote_failed = sum(
        wv["outcomes"]["error"] + wv["outcomes"]["deadline"] for wv in waves
    )
    split_promote = {
        "wave_requests": n_wave,
        "waves": waves,
        "lost_requests": promote_lost,
        "failed_requests": promote_failed,
        "promote": promote,
        "split_served": promote.get("split_served"),
        "split_identical_bytes": split_identical,
        "post_promote_identical_bytes": post_identical,
        "live_version": reg.live(DEFAULT_MODEL).version,
        "compile_misses_after_warmup": misses_warm,
        "compile_misses_final": misses_end,
        "recompiles_through_rollout": misses_end - misses_warm,
    }

    # ------------------------------------ scenario 2: shadow_rollback
    eng2, reg2 = make_engine()
    with eng2:
        ctl2 = eng2.attach_rollout()
        rep_ref2 = load(eng2, n=n_wave)
        ref2_v1 = ok_dets(rep_ref2)
        ro2 = ctl2.start(DEFAULT_MODEL, ckpt_divergent, policy=RolloutPolicy(
            split_pct=0.0, shadow=True, min_compared=4,
            min_error_samples=10**6, min_latency_samples=10**6,
            hold_s=3600.0, eval_interval_s=0.02, score_thresh=0.01,
        ))
        wait_state(ro2)
        rep_b2 = load(eng2, n=n_wave)
        rollback = {"aborted": False}
        try:
            ro2.result(300)
        except RolloutAborted as e:
            rollback["aborted"] = True
            rollback["stage"] = e.stage
            rollback["cause"] = str(e.cause)
        rep_c2 = load(eng2, n=n_wave)
        ctl2.stop()
    divergence = ro2.report.snapshot()
    dets_b2, dets_c2 = ok_dets(rep_b2), ok_dets(rep_c2)
    incumbent_identical = (
        bool(dets_b2) and bool(dets_c2)
        and all(_dets_equal(dets_b2[i], ref2_v1[i]) for i in dets_b2)
        and all(_dets_equal(dets_c2[i], ref2_v1[i]) for i in dets_c2)
    )
    rollback.update({
        "waves": [wave_summary(r) for r in (rep_ref2, rep_b2, rep_c2)],
        "incumbent_identical_bytes": incumbent_identical,
        "live_version": reg2.live(DEFAULT_MODEL).version,
        "divergence": divergence,
    })

    # --------------------------------------- scenario 3: closed_loop
    eng3, reg3 = make_engine()
    with eng3:
        ctl3 = eng3.attach_rollout()
        rep_h = load(eng3, n=n_wave)
        # regenerate the loadgen size stream (same rng discipline as
        # run_load) so each harvested response carries its true (h, w)
        size_rng = np.random.RandomState(0)
        req_sizes = [
            sizes[size_rng.randint(len(sizes))] for _ in range(n_wave)
        ]
        harvested = ok_dets(rep_h)
        records_in = distill.harvest(
            [(harvested[i], req_sizes[i]) for i in sorted(harvested)],
            min_score=0.05,
            num_classes=cfg.dataset.NUM_CLASSES,
        )
        rec_path = os.path.join(tmp, "distilled.jsonl")
        distill.write_records(records_in, rec_path)
        loop = {"harvested_records": len(records_in)}
        if records_in:
            ckpt_distilled = distill.fine_tune(
                distill.read_records(rec_path), network=network,
                steps=distill_steps, seed=0,
                out_dir=os.path.join(tmp, "loop"),
                init_donor=params_v1,
            )
            # a genuinely retrained candidate diverges by design: the
            # loop's gate is lifecycle evidence (split health), with the
            # divergence bounds opened up by the operator
            ro3 = ctl3.start(DEFAULT_MODEL, ckpt_distilled, policy=RolloutPolicy(
                split_pct=30.0, shadow=False, min_compared=0,
                min_served=4,
                max_box_delta_px=1e9, max_score_delta=1e9,
                max_unmatched=10**6, max_count_drift=1e9,
                min_error_samples=10**6, min_latency_samples=10**6,
                hold_s=0.2, eval_interval_s=0.02,
            ))
            wait_state(ro3)
            rep_l = load(eng3, n=n_wave)
            loop_promote = ro3.result(300)
            loop.update({
                "checkpoint": ckpt_distilled,
                "promote": loop_promote,
                "waves": [wave_summary(r) for r in (rep_h, rep_l)],
                "lost_requests": sum(
                    wave_summary(r)["lost_requests"] for r in (rep_h, rep_l)
                ),
                "live_version": reg3.live(DEFAULT_MODEL).version,
            })

    tag = _METRIC_NAMES[network].replace("_e2e", "")
    claims = {
        "zero_lost_requests": bool(
            promote_lost == 0 and promote_failed == 0
            and loop.get("lost_requests") == 0
        ),
        "control_arm_byte_identical": bool(
            split_identical and incumbent_identical
        ),
        "divergence_auto_rollback": bool(
            rollback["aborted"] and rollback.get("stage") == "evaluate"
            and rollback["live_version"] == 1
            and incumbent_identical
        ),
        "zero_steady_state_recompiles": bool(
            split_promote["recompiles_through_rollout"] == 0
        ),
        "closed_loop_promoted": bool(
            loop.get("harvested_records", 0) > 0
            and loop.get("live_version") == 2
        ),
    }
    records = [
        {
            "metric": f"rollout_split_served_{tag}",
            "value": split_promote["split_served"], "unit": "requests",
            "vs_baseline": None,
        },
        {
            "metric": f"rollout_shadow_compared_{tag}",
            "value": divergence["compared"], "unit": "comparisons",
            "vs_baseline": None,
        },
        {
            "metric": f"rollout_promote_lost_requests_{tag}",
            "value": promote_lost, "unit": "requests", "vs_baseline": None,
        },
        {
            "metric": f"rollout_rollback_incumbent_identical_{tag}",
            "value": int(incumbent_identical), "unit": "bool",
            "vs_baseline": None,
        },
        {
            "metric": f"rollout_steady_state_recompiles_{tag}",
            "value": split_promote["recompiles_through_rollout"],
            "unit": "compiles", "vs_baseline": None,
        },
        {
            "metric": f"rollout_distill_records_{tag}",
            "value": loop.get("harvested_records", 0), "unit": "records",
            "vs_baseline": None,
        },
        {
            "metric": f"rollout_loop_promoted_version_{tag}",
            "value": loop.get("live_version"), "unit": "version",
            "vs_baseline": None,
        },
    ]
    report = {
        "requests": requests,
        "concurrency": concurrency,
        "max_batch": max_batch,
        "split_promote": split_promote,
        "shadow_rollback": rollback,
        "closed_loop": loop,
        "divergence": divergence,
        "claims": claims,
    }
    return records, report


def _smoke_config(batch_images: int):
    """Tiny CPU-runnable train config (96×96 bucket, shrunk RPN/ROI
    budgets) — the same shrink the CLI smoke tests use, so the pipeline
    bench measures loop mechanics, not model size."""
    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config("resnet50", "PascalVOC")
    return cfg.replace(
        SHAPE_BUCKETS=((96, 96),),
        TRAIN=dataclasses.replace(
            cfg.TRAIN,
            RPN_PRE_NMS_TOP_N=256,
            RPN_POST_NMS_TOP_N=32,
            BATCH_ROIS=16,
            RPN_BATCH_SIZE=32,
            BATCH_IMAGES=batch_images,
        ),
        dataset=dataclasses.replace(
            cfg.dataset, SCALES=((96, 96),), MAX_GT_BOXES=8
        ),
    )


def _eval_records(report: dict) -> list:
    """Eval data-plane report (``tools/bench_eval.py ::
    data_plane_report``) → the JSON-line records (pure; the bench schema
    test builds a synthetic report and asserts the throughput, stage
    counters, and bitwise-equivalence fields are present without running
    the benchmark).

    ``vs_baseline`` on the throughput record is the overlapped/serial
    ratio measured IN THE SAME PROCESS over the identical seeded stream —
    reportable only because ``byte_identical`` holds.
    """
    over = report["overlapped"]
    assembly = over.get("assembly", {})
    completion = over.get("completion", {})
    cache = report.get("prepared_cache_stats", {})

    def rec(metric, value, unit, vs=None):
        return {"metric": metric, "value": value, "unit": unit,
                "vs_baseline": vs}

    return [
        rec("eval_data_plane_imgs_per_sec",
            report["overlapped_imgs_per_sec"], "imgs/sec",
            vs=report["speedup"]),
        rec("eval_data_plane_serial_imgs_per_sec",
            report["baseline_imgs_per_sec"], "imgs/sec"),
        rec("eval_assembly_occupancy",
            assembly.get("occupancy", 0.0), "fraction"),
        rec("eval_assembly_queue_depth_max",
            assembly.get("queue_depth_max", 0), "batches"),
        rec("eval_completion_inflight_max",
            completion.get("inflight_max", 0), "tasks"),
        rec("eval_completion_block_s",
            completion.get("block_s", 0.0), "seconds"),
        rec("eval_in_flight_window", report["in_flight"], "batches"),
        rec("eval_prepared_cache_hits", cache.get("hits", 0), "hits"),
        rec("eval_byte_identical", int(report["byte_identical"]), "bool"),
    ]


def _pipeline_records(report: dict) -> list:
    """Pipeline report → the JSON-line records (pure; the bench schema
    test builds a synthetic report and asserts the feed-occupancy and
    fetch-stall fields are present without running the model)."""
    feed = report["feed"]
    loop = report["loop"]
    def rec(metric, value, unit):
        return {"metric": metric, "value": value, "unit": unit,
                "vs_baseline": None}
    return [
        rec("pipeline_feed_occupancy", feed["occupancy"], "fraction"),
        rec("pipeline_feed_starved_steps",
            feed["feed_starved_after_first"], "steps"),
        rec("pipeline_min_staged_ahead", report["min_staged_ahead"],
            "batches"),
        rec("pipeline_aux_fetches", loop["fetches"], "fetches"),
        rec("pipeline_fetch_stalls", loop["fetch_stalls"], "stalls"),
        rec("pipeline_fetch_stall_ms", loop["fetch_stall_ms"], "ms"),
        rec("pipeline_interflush_blocking_fetches",
            report["interflush_blocking_fetches"], "fetches"),
        rec("pipeline_k1_byte_identical",
            int(report["k1_byte_identical"]), "bool"),
        rec("pipeline_train_imgs_per_sec_cpu_smoke",
            report["imgs_per_sec"], "imgs/sec"),
    ]


def bench_pipeline(
    steps: int, aux_interval: int, feed_depth: int, batch_images: int
) -> tuple:
    """Measure the device-resident step pipeline on the CPU smoke config.

    Three runs over the identical (seeded) batch stream with ONE shared
    compiled step: a synchronous GuardedLoop baseline, a PipelinedLoop
    at K=1 (byte-identical check: donation + feed must not perturb a
    single bit of the final state), and the measured PipelinedLoop at
    K=``aux_interval`` behind a depth-``feed_depth`` DeviceFeed.
    → (records, report).  CPU smoke numbers prove the MECHANISM (overlap
    counters, zero inter-flush fetches); device wins ride the next TPU
    round (ROOFLINE "host gap, revisited").
    """
    import jax

    from mx_rcnn_tpu.core.pipeline import DeviceFeed, PipelinedLoop
    from mx_rcnn_tpu.core.resilience import GuardedLoop, host_copy
    from mx_rcnn_tpu.core.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )
    from mx_rcnn_tpu.data.loader import TrainLoader
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.utils.load_data import load_gt_roidb

    cfg = _smoke_config(batch_images)
    _, roidb = load_gt_roidb(
        cfg, None, flip=False, synthetic_size=max(8, 4 * batch_images)
    )
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]
    params = model.init(
        {"params": jax.random.key(0), "sampling": jax.random.key(1)},
        images=np.zeros((1, h, w, 3), np.float32),
        im_info=np.array([[h, w, 1.0]], np.float32),
        gt_boxes=np.zeros((1, cfg.dataset.MAX_GT_BOXES, 5), np.float32),
        gt_valid=np.zeros((1, cfg.dataset.MAX_GT_BOXES), bool),
        train=True,
    )["params"]
    tx = make_optimizer(cfg, lambda s: cfg.TRAIN.LEARNING_RATE)
    # deterministic: the K=1 byte-identical check compares two runs
    # bitwise, which the default CPU thunk runtime breaks on its own —
    # it reassociates reductions across threads, so even the sync
    # baseline is not repeatable against itself (~1e-7/run drift)
    step_fn = make_train_step(model, tx, donate=True, deterministic=True)
    # owning copy, not a device_get view: both runs re-place from
    # host_params while the donating step recycles device buffers
    host_params = host_copy(params)

    def batch_stream(n):
        loader = TrainLoader(
            roidb, cfg, batch_images, shuffle=True, seed=0
        )
        got = 0
        while got < n:
            for b in loader:
                yield b
                got += 1
                if got >= n:
                    return

    def state_bytes(state):
        return b"".join(
            np.asarray(x).tobytes()
            for x in jax.tree_util.tree_leaves(jax.device_get(state))
        )

    rng = jax.random.key(0)

    def run_sync(n):
        state = create_train_state(host_params, tx)
        guard = GuardedLoop(step_fn)
        for b in batch_stream(n):
            state, _aux, _ok = guard.step(state, b, rng)
        return state_bytes(state)

    def run_pipelined(n, k):
        state = create_train_state(host_params, tx)
        loop = PipelinedLoop(step_fn, aux_interval=k)
        feed = DeviceFeed(batch_stream(n), depth=feed_depth)
        t0 = time.perf_counter()
        try:
            for b in feed:
                state, _ready, _ok = loop.step(state, b, rng)
        finally:
            stats = feed.stats()
            feed.close()
        state, _ready, _ok = loop.flush(state)
        dt = time.perf_counter() - t0
        return state_bytes(state), stats, loop, dt

    sync_bytes = run_sync(steps)  # also: compile warmup for all runs
    k1_bytes, _, _, _ = run_pipelined(steps, 1)
    _, feed_stats, loop_k, dt = run_pipelined(steps, aux_interval)

    loop_stats = loop_k.stats()
    report = {
        "steps": steps,
        "batch_images": batch_images,
        "aux_interval": aux_interval,
        "feed_depth": feed_depth,
        "feed": feed_stats,
        "loop": loop_stats,
        # every non-boundary step had >= 1 batch staged ahead iff no
        # post-first get ever blocked on the worker
        "min_staged_ahead": int(feed_stats["feed_starved_after_first"] == 0),
        # the sink only fetches inside flush(): any excess fetch over the
        # flush count would be a blocking fetch between flush points
        "interflush_blocking_fetches": max(
            0, loop_stats["fetches"] - loop_stats["flushes"]
        ),
        "k1_byte_identical": k1_bytes == sync_bytes,
        "imgs_per_sec": round(batch_images * steps / dt, 3),
    }
    return _pipeline_records(report), report


def _elastic_records(report: dict) -> list:
    """Elastic chaos report → JSON-line records (pure; the bench schema
    test builds a synthetic report and asserts the per-scenario
    zero-lost/bit-identical/recovery fields without running the matrix)."""
    def rec(metric, value, unit):
        return {"metric": metric, "value": value, "unit": unit,
                "vs_baseline": None}

    recs = [
        rec("elastic_devices", report["devices"], "replicas"),
        rec("elastic_steps", report["steps"], "steps"),
    ]
    for name, s in report["scenarios"].items():
        recs += [
            rec(f"elastic_{name}_zero_lost_steps",
                int(s["zero_lost_steps"]), "bool"),
            rec(f"elastic_{name}_bit_identical",
                int(s["bit_identical"]), "bool"),
            rec(f"elastic_{name}_recovery_s", s["recovery_s"], "seconds"),
            rec(f"elastic_{name}_final_replicas",
                s["final_replicas"], "replicas"),
        ]
    return recs


def bench_elastic(steps: int, batch_images: int) -> tuple:
    """Chaos matrix for elastic training on 8 virtual CPU devices.

    Four deterministic fault scenarios (``MX_RCNN_FAULTS`` device-phase
    injectors keyed step×replica — no sleeps-and-hope) over the same
    seeded batch stream and ONE pair of compiled executables (8-replica
    and 7-replica mesh, warmed before timing so ``recovery_s`` measures
    the drain/checkpoint/reshard path, as on a pod with a hot compile
    cache):

    - ``lose_1_of_8``: a replica dies mid-step and stays dead — the run
      shrinks to 7 and completes; its final state is compared BITWISE to
      a fresh 7-replica run restored from the emergency checkpoint and
      fed the remaining stream (the shrink-equivalence bar).
    - ``wedge``: a wedged (not dead) replica — same shrink mechanics;
      final state must equal the lose case bitwise (the loop cannot tell
      the difference, by design).
    - ``lose_then_regrow``: the wedge heals; at the next checkpoint
      boundary the mesh regrows to 8.  Run twice — recovery must be
      bit-reproducible end to end.
    - ``preempt_during_shrink``: the emergency save itself is killed
      mid-write (``save_crash``); the restarted run resumes from the
      last committed dump, hits the same fault, and must land on the
      lose case's exact bytes (resumed stream identical).

    Every scenario asserts zero lost steps beyond the pipeline window:
    each stream index's aux is delivered exactly once.
    """
    import os
    import tempfile

    import jax

    from mx_rcnn_tpu.core.checkpoint import (
        is_committed,
        load_restorable,
        save_checkpoint,
    )
    from mx_rcnn_tpu.core.resilience import host_copy
    from mx_rcnn_tpu.core.train import create_train_state, make_optimizer
    from mx_rcnn_tpu.data.loader import TrainLoader
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.parallel.elastic import ElasticLoop, make_elastic_factory
    from mx_rcnn_tpu.utils import faults
    from mx_rcnn_tpu.utils.load_data import load_gt_roidb

    base = 8
    if len(jax.devices()) < base:
        raise RuntimeError(
            f"elastic bench needs {base} devices, got {len(jax.devices())}"
        )
    if batch_images % base:
        raise ValueError("batch_images must divide by 8 replicas")
    fault_step, victim, wedge_dur = 3, 2, 2
    boundary_at = max(fault_step + wedge_dur + 1, steps - 2)
    survivors = tuple(o for o in range(base) if o != victim)

    cfg = _smoke_config(batch_images)
    _, roidb = load_gt_roidb(
        cfg, None, flip=False, synthetic_size=max(8, 2 * batch_images)
    )
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]
    params = model.init(
        {"params": jax.random.key(0), "sampling": jax.random.key(1)},
        images=np.zeros((1, h, w, 3), np.float32),
        im_info=np.array([[h, w, 1.0]], np.float32),
        gt_boxes=np.zeros((1, cfg.dataset.MAX_GT_BOXES, 5), np.float32),
        gt_valid=np.zeros((1, cfg.dataset.MAX_GT_BOXES), bool),
        train=True,
    )["params"]
    tx = make_optimizer(cfg, lambda s: cfg.TRAIN.LEARNING_RATE)
    host_params = host_copy(params)

    # the stream is precomputed so every scenario (and every fresh-mesh
    # equivalence run) consumes literally the same host arrays
    loader = TrainLoader(roidb, cfg, batch_images, shuffle=True, seed=0)
    batches = []
    while len(batches) < steps:
        for b in loader:
            batches.append(b)
            if len(batches) >= steps:
                break

    # one context per active set, shared across scenarios: the 7-mesh
    # executable compiles once, like a pod reusing its compile cache
    base_factory = make_elastic_factory(model, tx, deterministic=True)
    ctx_cache: dict = {}

    def factory(active):
        key = tuple(active)
        if key not in ctx_cache:
            ctx_cache[key] = base_factory(key)
        return ctx_cache[key]

    rng = jax.random.key(0)

    def fresh_state():
        # host_copy, not device_get: donated steps would corrupt a CPU
        # zero-copy view of these buffers
        return host_copy(create_train_state(host_params, tx))

    def state_bytes(state):
        return b"".join(
            np.asarray(x).tobytes()
            for x in jax.tree_util.tree_leaves(jax.device_get(state))
        )

    compile_s = {}
    for act in (tuple(range(base)), survivors):
        ctx = factory(act)
        st = ctx.place_state(fresh_state())
        t0 = time.perf_counter()
        ctx.step_fn(st, ctx.place_batch(batches[0]), rng)
        compile_s[len(act)] = round(time.perf_counter() - t0, 3)

    def run(prefix, spec, *, resume=False, boundary=None, reset=True):
        os.environ[faults.ENV_VAR] = spec
        if reset:
            faults.reset()

        def ckpt_fn(host_state, idx, meta):
            return save_checkpoint(prefix, host_state, 0, idx, meta=meta)

        loop = ElasticLoop(factory, base, checkpoint_fn=ckpt_fn)
        state = fresh_state()
        start = 0
        if resume:
            got = load_restorable(prefix, state)
            assert got is not None, "restart found nothing restorable"
            (_epoch, start), state = got
            assert start == 0, "bench restart resumes the epoch head"
        state = loop.ctx.place_state(state)
        delivered = []
        t0 = time.perf_counter()
        for i in range(start, steps):
            state, ready, _ok = loop.step(state, batches[i], rng)
            delivered += [idx for idx, _aux in ready]
            if boundary is not None and i == boundary - 1:
                state, ready, _ok = loop.flush(state)
                delivered += [idx for idx, _aux in ready]
                save_checkpoint(prefix, host_copy(state), 1, 0)
                state, _regrown = loop.checkpoint_boundary(state)
        state, ready, _ok = loop.flush(state)
        delivered += [idx for idx, _aux in ready]
        wall = time.perf_counter() - t0
        return {
            "loop": loop,
            "bytes": state_bytes(state),
            "delivered": delivered,
            "wall_s": round(wall, 3),
        }

    def summarize(r, bit_identical):
        loop = r["loop"]
        uniq = set(r["delivered"])
        return {
            "final_replicas": len(loop.active),
            "shrinks": loop.monitor.shrinks,
            "regrows": loop.monitor.regrows,
            "emergency_checkpoints": len(loop.emergency_ckpts),
            "emergency_committed": all(
                is_committed(p) for p in loop.emergency_ckpts
            ),
            "replayed_steps": loop.replayed_steps,
            "lost_steps": steps - len(uniq),
            "duplicate_deliveries": len(r["delivered"]) - len(uniq),
            "zero_lost_steps": (
                sorted(uniq) == list(range(steps))
                and len(r["delivered"]) == steps
            ),
            "recovery_s": round(loop.recovery_s, 4),
            "wall_s": r["wall_s"],
            "bit_identical": bool(bit_identical),
            "transitions": loop.monitor.transitions,
        }

    scenarios = {}

    # -- lose 1 of 8, down forever ------------------------------------
    with tempfile.TemporaryDirectory() as td:
        r1 = run(td, f"device_lost@{fault_step}.{victim}")
        # fresh-mesh equivalence: restore the EMERGENCY checkpoint, build
        # a 7-replica substrate from scratch, feed the remaining stream
        got = load_restorable(td, fresh_state())
        assert got is not None, "emergency checkpoint not restorable"
        (_e, anchor), anchor_state = got
        ctx = factory(survivors)
        st = ctx.place_state(anchor_state)
        for i in range(anchor, steps):
            st, _aux = ctx.step_fn(st, ctx.place_batch(batches[i]), rng)
        scenarios["lose_1_of_8"] = summarize(
            r1, state_bytes(st) == r1["bytes"]
        )
        scenarios["lose_1_of_8"]["emergency_anchor_step"] = anchor

    # -- wedged replica (indistinguishable from lost, by design) ------
    with tempfile.TemporaryDirectory() as td:
        r2 = run(td, f"device_wedge@{fault_step}.{victim}:{steps}")
        scenarios["wedge"] = summarize(r2, r2["bytes"] == r1["bytes"])

    # -- wedge heals -> regrow at the checkpoint boundary; run twice ---
    spec3 = f"device_wedge@{fault_step}.{victim}:{wedge_dur}"
    with tempfile.TemporaryDirectory() as td:
        r3a = run(td, spec3, boundary=boundary_at)
    with tempfile.TemporaryDirectory() as td:
        r3b = run(td, spec3, boundary=boundary_at)
    scenarios["lose_then_regrow"] = summarize(
        r3a, r3a["bytes"] == r3b["bytes"]
    )

    # -- the emergency save itself is killed mid-write ----------------
    with tempfile.TemporaryDirectory() as td:
        save_checkpoint(td, fresh_state(), 0, 0)  # last committed dump
        spec4 = f"device_lost@{fault_step}.{victim},save_crash@1"
        os.environ[faults.ENV_VAR] = spec4
        faults.reset()
        crashed = False
        loop_x = ElasticLoop(
            factory, base,
            checkpoint_fn=lambda s, i, m: save_checkpoint(
                td, s, 0, i, meta=m
            ),
        )
        state = loop_x.ctx.place_state(fresh_state())
        try:
            for i in range(steps):
                state, _ready, _ok = loop_x.step(state, batches[i], rng)
        except faults.SimulatedCrash:
            crashed = True
        orphan = any(d.endswith(".tmp") for d in os.listdir(td))
        # restart in the same fault registry: save_crash@1 is consumed,
        # the device fault is still live — the resumed run re-hits it,
        # shrinks cleanly, and must land on the lose case's exact bytes
        r4 = run(td, spec4, resume=True, reset=False)
        s4 = summarize(r4, r4["bytes"] == r1["bytes"])
        s4["crashed_mid_shrink"] = crashed
        s4["orphan_tmp_left"] = orphan
        scenarios["preempt_during_shrink"] = s4

    os.environ.pop(faults.ENV_VAR, None)
    faults.reset()

    report = {
        "devices": base,
        "steps": steps,
        "batch_images": batch_images,
        "fault_step": fault_step,
        "victim": victim,
        "wedge_duration": wedge_dur,
        "boundary_at": boundary_at,
        "pipeline_window": 1,
        "compile_s": compile_s,
        "scenarios": scenarios,
    }
    return _elastic_records(report), report


# -------------------------------------------------- tenant-fair front door
class _ScalePool:
    """Signal-only pool stand-in for the trace-convergence legs: the
    autoscaler sees a replicas list and add/remove with the real
    copy-on-write contract, without paying replica threads for a
    decision-loop simulation."""

    def __init__(self, n: int):
        self.replicas = [object() for _ in range(n)]

    def add_replica(self):
        r = object()
        self.replicas = self.replicas + [r]
        return r

    def remove_replica(self, replica=None, timeout=5.0):
        if len(self.replicas) <= 1:
            return None
        victim = self.replicas[-1]
        self.replicas = self.replicas[:-1]
        return victim


def _drive_trace(scaler, depths, dt: float = 0.1):
    """Feed a queue-depth series through synchronous ticks (injected
    clock — wall time never enters the convergence legs)."""
    now = 1000.0
    for d in depths:
        scaler._signal_fn = lambda d=d: {
            "queue_depth": d,
            "healthy": len(scaler.pool.replicas),
            "p99_ms": None,
        }
        scaler.tick(now=now)
        now += dt


def bench_serve_scale(
    requests: int = 60,
    aggressor_factor: int = 4,
    service_ms: float = 3.0,
) -> tuple:
    """Tenant-fair front door bench (ISSUE 16 acceptance evidence).

    Four claims over the calibrated digest-stub runner family:

    1. ``tenant_isolation`` — the victim's p99 with an aggressor
       blasting at ``aggressor_factor``x its token-bucket rate stays
       within 10% (+2ms measurement floor) of the victim-solo run,
       because the excess is rejected at the door, never queued;
    2. ``zero_loss_shrink`` — an AUTOSCALER-initiated scale-down in the
       middle of live pool load completes every request with detections
       byte-identical to a fixed-size control run;
    3. ``no_flap`` — the controller converges on a diurnal trace with a
       bounded event count and zero flaps, and the breaker engages
       (flaps detected, events suppressed) on an adversarial
       oscillating trace;
    4. ``zero_steady_state_recompiles`` — compile misses across the
       shrink leg stay at warmup level for every pool size, and a
       scale-up costs exactly one ladder warmup, never more.
    """
    from mx_rcnn_tpu.serve.autoscaler import AutoScaler, ScalePolicy
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import diurnal_arrivals
    from mx_rcnn_tpu.serve.router import ReplicaPool
    from mx_rcnn_tpu.serve.tenancy import TenantOverBudget, TenantTable

    tag = "cpu"
    service_s = service_ms / 1000.0

    def stub_factory(index: int):
        return _OverlapStubRunner(index, h2d_ms=0.0,
                                  device_ms=service_ms, fetch_ms=0.0)

    def images(n):
        return [
            np.random.RandomState(3000 + i).rand(24, 24, 3).astype(
                np.float32
            )
            for i in range(n)
        ]

    # ---- leg 1: aggressor/victim isolation -----------------------------
    def victim_run(with_aggressor: bool):
        tenants = TenantTable(strict=True)
        tenants.register("victim", weight=1.0)
        tenants.register("aggressor", weight=1.0, rate=50.0, burst=5.0)
        engine = ServingEngine(
            _OverlapStubRunner(0, h2d_ms=0.0, device_ms=service_ms,
                               fetch_ms=0.0),
            max_linger=0.0, max_queue=256, in_flight=1, tenants=tenants,
        )
        shed = 0
        lats_ms = []
        with engine:
            futs = []
            # warm phase (unmeasured): drain the aggressor's one-time
            # token-bucket burst so the measured window is the steady
            # state the isolation claim is about — the aggressor held
            # to its refill rate, the excess shed at the door
            for i, im in enumerate(images(8)):
                if with_aggressor:
                    for _ in range(aggressor_factor):
                        try:
                            futs.append(
                                engine.submit(im, tenant="aggressor",
                                              lane="bulk")
                            )
                        except TenantOverBudget:
                            shed += 1
                engine.submit(im, tenant="victim",
                              lane="interactive").result(timeout=30.0)
            for i, im in enumerate(images(requests)):
                if with_aggressor:
                    for _ in range(aggressor_factor):
                        try:
                            futs.append(
                                engine.submit(im, tenant="aggressor",
                                              lane="bulk")
                            )
                        except TenantOverBudget:
                            shed += 1
                t0 = time.monotonic()
                vf = engine.submit(im, tenant="victim",
                                   lane="interactive")
                vf.result(timeout=30.0)
                lats_ms.append((time.monotonic() - t0) * 1000.0)
            for f in futs:
                f.result(timeout=30.0)
        return _pctl_ms(lats_ms, 99), shed, engine.snapshot()

    solo_p99, _, _ = victim_run(with_aggressor=False)
    duo_p99, agg_shed, duo_snap = victim_run(with_aggressor=True)
    # the 10% bar plus one device-service quantum: the WFQ guarantees
    # at most one aggressor batch ahead of a victim release, and CPU
    # wall-clock needs a jitter floor on top of the ratio
    isolation_bar = 1.10 * solo_p99 + service_ms + 2.0
    tenant_isolation = bool(duo_p99 <= isolation_bar and agg_shed > 0)

    # ---- leg 2: autoscaler-initiated zero-loss scale-down --------------
    ims = images(requests)

    ladder_len = len(_OverlapStubRunner.LADDER)

    def pool_run(autoscale: bool):
        pool = ReplicaPool(stub_factory, 2)
        engine = ServingEngine(pool, max_linger=0.0, max_queue=256,
                               in_flight=1)
        try:
            with engine:
                futs = [engine.submit(im) for im in ims]
                scaler = None
                if autoscale:
                    # shrink-biased policy: the controller pulls the
                    # pool to min_replicas while the load is in flight
                    scaler = engine.attach_autoscaler(
                        policy=ScalePolicy(
                            min_replicas=1, max_replicas=2,
                            interval=0.005, samples=2, cooldown=0.0,
                            up_queue=1e9, down_queue=1e9,
                        )
                    )
                results = [f.result(timeout=60.0) for f in futs]
                # steady state at whatever size the pool landed on:
                # every surviving replica carries exactly its warmup
                # compiles, nothing from traffic
                extra = sum(
                    r.runner.compile_cache.misses - ladder_len
                    for r in pool.replicas
                )
                down_events = scaler.scale_downs if scaler else 0
                n_after = len(pool.replicas)
            snap = engine.snapshot()
        finally:
            pool.close()
        return results, snap, extra, down_events, n_after

    fixed_res, fixed_snap, fixed_extra, _, _ = pool_run(autoscale=False)
    (scaled_res, scaled_snap, scaled_extra,
     down_events, n_after) = pool_run(autoscale=True)
    identical = all(
        _dets_equal(a, b) for a, b in zip(fixed_res, scaled_res)
    )
    zero_loss = bool(
        identical
        and down_events >= 1
        and n_after == 1
        and scaled_snap["requests"]["completed"] == requests
        and scaled_snap["requests"]["failed"] == 0
    )
    # steady state must not compile at either pool size; a grow costs
    # exactly one ladder warmup
    shrink_recompiles = scaled_extra + fixed_extra
    pool2 = ReplicaPool(stub_factory, 1)
    try:
        pool2.warmup()
        grow_before = pool2.compile_cache.misses
        r = pool2.add_replica()
        t_end = time.monotonic() + 10.0
        while not r.routable and time.monotonic() < t_end:
            time.sleep(0.01)
        grow_delta = pool2.compile_cache.misses - grow_before
    finally:
        pool2.close()
    zero_recompiles = bool(
        shrink_recompiles == 0 and grow_delta == ladder_len
    )

    # ---- leg 3: trace convergence + breaker ----------------------------
    # diurnal day: arrivals binned to ticks -> queue-depth series
    arr = np.asarray(
        diurnal_arrivals(2000, lo_rps=4.0, hi_rps=40.0, seed=7)
    )
    bins = np.histogram(arr, bins=120)[0]  # ~arrivals per tick
    pol = ScalePolicy(min_replicas=1, max_replicas=4, samples=3,
                      up_queue=10.0, down_queue=2.0,
                      cooldown=0.5, flap_window=2.0, max_backoff=4.0)
    diurnal_pool = _ScalePool(1)
    diurnal_scaler = AutoScaler(diurnal_pool, policy=pol)
    _drive_trace(diurnal_scaler, bins.tolist(), dt=0.5)
    diurnal_events = diurnal_scaler.scale_ups + diurnal_scaler.scale_downs
    diurnal_flaps = diurnal_scaler.breaker.flaps

    osc_pool = _ScalePool(2)
    osc_scaler = AutoScaler(osc_pool, policy=ScalePolicy(
        min_replicas=1, max_replicas=4, samples=2,
        cooldown=0.5, flap_window=100.0, max_backoff=4.0,
    ))
    osc = ([100.0] * 3 + [0.0] * 3) * 10  # adversarial square wave
    _drive_trace(osc_scaler, osc, dt=0.1)
    osc_events = osc_scaler.scale_ups + osc_scaler.scale_downs
    osc_snap = osc_scaler.snapshot()["breaker"]
    no_flap = bool(
        diurnal_flaps == 0
        and 2 <= diurnal_events <= 10
        and osc_events <= 6
        and osc_snap["flaps"] >= 1
        and osc_snap["suppressed"] >= 5
    )

    records = [
        {"metric": f"serve_scale_victim_solo_p99_ms_{tag}",
         "value": solo_p99, "unit": "ms"},
        {"metric": f"serve_scale_victim_contended_p99_ms_{tag}",
         "value": duo_p99, "unit": "ms"},
        {"metric": f"serve_scale_aggressor_shed_{tag}",
         "value": agg_shed, "unit": "requests"},
        {"metric": f"serve_scale_shrink_lost_requests_{tag}",
         "value": requests - scaled_snap["requests"]["completed"],
         "unit": "requests"},
        {"metric": f"serve_scale_shrink_scale_downs_{tag}",
         "value": down_events, "unit": "events"},
        {"metric": f"serve_scale_detections_match_{tag}",
         "value": 1 if identical else 0, "unit": "bool"},
        {"metric": f"serve_scale_shrink_recompiles_{tag}",
         "value": shrink_recompiles, "unit": "compiles"},
        {"metric": f"serve_scale_grow_warmup_compiles_{tag}",
         "value": grow_delta, "unit": "compiles"},
        {"metric": f"serve_scale_diurnal_events_{tag}",
         "value": diurnal_events, "unit": "events"},
        {"metric": f"serve_scale_diurnal_flaps_{tag}",
         "value": diurnal_flaps, "unit": "flaps"},
        {"metric": f"serve_scale_oscillating_events_{tag}",
         "value": osc_events, "unit": "events"},
        {"metric": f"serve_scale_oscillating_suppressed_{tag}",
         "value": osc_snap["suppressed"], "unit": "ticks"},
    ]
    report = {
        "requests": requests,
        "aggressor_factor": aggressor_factor,
        "service_ms": service_ms,
        "isolation_bar_ms": round(isolation_bar, 3),
        "victim": {"solo_p99_ms": solo_p99, "contended_p99_ms": duo_p99},
        "aggressor": duo_snap["tenants"]["aggressor"],
        "tenancy": duo_snap["tenancy"],
        "shrink": {
            "scale_downs": down_events,
            "replicas_after": n_after,
            "completed": scaled_snap["requests"]["completed"],
            "autoscaler": scaled_snap.get("autoscaler"),
        },
        "diurnal": {"events": diurnal_events, "flaps": diurnal_flaps,
                    "replicas_final": len(diurnal_pool.replicas)},
        "oscillating": {"events": osc_events, "breaker": osc_snap},
        "claims": {
            "tenant_isolation": tenant_isolation,
            "zero_loss_shrink": zero_loss,
            "no_flap": no_flap,
            "zero_steady_state_recompiles": zero_recompiles,
        },
    }
    return records, report


def _cascade_tiny_cfg(network: str):
    """One-bucket config for the per-rung parity matrix — the smallest
    geometry each real model compiles AND executes at, so six warmups
    (2 families x 3 precisions) stay CPU-tractable.  The mask-FPN
    family takes 96x96: its batch-1 64x64 serve graph trips a oneDNN
    convolution-primitive crash on this host, 96x96 does not."""
    from mx_rcnn_tpu.config import generate_config

    cfg = generate_config(network, "PascalVOC")
    bucket = (96, 96) if cfg.network.USE_MASK else (64, 64)
    net_over = {"FIXED_PARAMS": ()}
    if not cfg.network.USE_FPN:
        net_over["ANCHOR_SCALES"] = (2, 4, 8)
    if cfg.network.depth > 50 and cfg.network.name == "resnet":
        net_over["depth"] = 50
    test_over = {
        "RPN_PRE_NMS_TOP_N": 100,
        "RPN_POST_NMS_TOP_N": 16,
        "SCORE_THRESH": 0.05,
    }
    if cfg.network.USE_MASK:
        test_over.update(DET_PER_CLASS=8, MAX_PER_IMAGE=8)
    return cfg.replace(
        SHAPE_BUCKETS=(bucket,),
        network=dataclasses.replace(cfg.network, **net_over),
        dataset=dataclasses.replace(
            cfg.dataset, NUM_CLASSES=4, SCALES=((bucket[0] - 16, bucket[0]),)
        ),
        TEST=dataclasses.replace(cfg.TEST, **test_over),
    )


def _cascade_rung_matrix() -> tuple:
    """Per-rung parity matrix: {box, mask} x {f32, bf16, int8} on REAL
    tiny models.  bf16/int8 warmups run the f32 detection-parity gate
    (mask parity included for the mask family) and raise on drift, so
    every row returned here passed the same gate serving would; f32
    rows are the reference rung (trivially ok, nothing to check).
    Also proves zero post-warmup compile-miss growth per rung."""
    import jax

    from mx_rcnn_tpu.core.quantize import quantization_stats
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve.loadgen import synthetic_image
    from mx_rcnn_tpu.serve.registry import ModelRegistry
    from mx_rcnn_tpu.serve.runner import ServeRunner

    matrix = []
    compression = {}
    steady_misses = 0
    for family, network in (("box", "resnet50"),
                            ("mask", "mask_resnet_fpn")):
        cfg = _cascade_tiny_cfg(network)
        h, w = cfg.SHAPE_BUCKETS[0]
        model = build_model(cfg)
        params = model.init(
            {"params": jax.random.key(0)},
            np.zeros((1, h, w, 3), np.float32),
            np.array([[h, w, 1.0]], np.float32),
            train=False,
        )["params"]
        # keep the raw random init: its saturated scores rank proposals
        # with wide margins, so the parity gate measures numeric drift,
        # not NMS tie-flips between near-equal scores
        registry = ModelRegistry()
        registry.register(family, model, cfg, params)
        im = synthetic_image(17, h - 8, w, seed=4)
        for precision in ("f32", "bfloat16", "int8"):
            runner = ServeRunner(
                registry=registry, max_batch=1, deterministic=True,
                precision=precision,
            )
            runner.warmup()
            tag = runner._precision_for(family)
            row = {"family": family, "precision": tag}
            report = runner.parity.get(f"{family}:{tag}")
            if report is None:  # the f32 reference rung
                row.update(ok=True, checked=False)
            else:
                row.update(
                    ok=bool(report["ok"]), checked=bool(report["checked"]),
                    max_box_delta_px=report["max_box_delta_px"],
                    max_score_delta=report["max_score_delta"],
                    unmatched_confident=report["unmatched_confident"],
                )
            # post-warmup serving must not add a single jit signature
            misses0 = runner.compile_cache.misses
            runner.run(runner.assemble([runner.make_request(im)]))
            steady_misses += runner.compile_cache.misses - misses0
            matrix.append(row)
            if tag == "int8":
                compression[family] = quantization_stats(
                    registry.live(family).params,
                    registry.quantized_tree(family),
                )
    return matrix, compression, steady_misses


def bench_cascade(requests: int = 80, hard_pct: float = 30.0) -> tuple:
    """Compression ladder + confidence-gated cascade (ISSUE 18).

    Two legs:

    1. **threshold sweep** — a two-family registry (cheap/flagship)
       behind the REAL engine + cascade router, with a stub predict
       whose per-batch device cost is MODELED (booked into the
       runner's ``device_ms_by_model`` counters, no sleeps): cheap 15
       ms/image, flagship 60 ms/image.  ``hard_pct`` of images are
       "hard": the cheap family answers them wrong and scores them low
       (0.3 vs 0.9), the flagship always answers right.  Sweeping the
       escalation threshold traces the cost-per-image vs accuracy
       curve: never-escalate (cheapest, wrong on hard images),
       escalate-on-doubt (matched accuracy at a fraction of the cost —
       THE claim), and 100% escalation (the byte-identity control arm
       vs flagship-only serving).

    2. **per-rung parity matrix** — {box, mask} x {f32, bf16, int8} on
       real tiny models: every reduced-precision rung passes the same
       f32 detection/mask-parity gate serving enforces, int8
       compression is ~4x, and no rung adds a post-warmup compile.
    """
    from mx_rcnn_tpu.serve.batcher import Request
    from mx_rcnn_tpu.serve.buckets import BucketLadder, CompileCache
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.registry import ModelRegistry

    CHEAP_MS, FLAG_MS = 15.0, 60.0

    class _CascadeStubRunner:
        """Registry-backed stub: detections are a pure function of the
        image index (encoded in the corner pixel) and the family;
        device cost per batch is booked, not slept."""

        def __init__(self, registry):
            self.registry = registry
            self.default_model = registry.default_model
            self.ladder = BucketLadder(((32, 32),))
            self.max_batch = 1  # exact per-request cost attribution
            self.cfg = None
            self.compile_cache = CompileCache()
            self.device_ms_total = 0.0
            self.device_ms_by_model = {}

        def warmup(self) -> int:
            for mid in self.registry.model_ids():
                self.compile_cache.record((mid, (1, 32, 32, 3), "f32"))
            return self.compile_cache.misses

        def make_request(self, im, deadline=None, model=None) -> Request:
            h, w = im.shape[:2]
            bh, bw = self.ladder.select(h, w)
            canvas = np.zeros((bh, bw, 3), np.float32)
            canvas[:h, :w] = im
            return Request(
                image=canvas,
                im_info=np.array([h, w, 1.0], np.float32),
                orig_hw=(h, w),
                bucket=(bh, bw),
                deadline=deadline,
                model=model,
            )

        def assemble(self, requests_):
            return {"images": np.stack([r.image for r in requests_])}

        def run(self, batch, model=None):
            mid = model or self.default_model
            self.compile_cache.record(
                (mid, batch["images"].shape, "f32")
            )
            cost = (CHEAP_MS if mid == "cheap" else FLAG_MS) * len(
                batch["images"]
            )
            self.device_ms_total += cost
            self.device_ms_by_model[mid] = (
                self.device_ms_by_model.get(mid, 0.0) + cost
            )
            # the image index rides the corner pixel (see _image)
            idx = np.round(batch["images"][:, 0, 0, 0]).astype(int)
            return {"idx": idx, "mid": mid}

        def detections_for(self, out, batch, index, orig_hw=None,
                           thresh=None, model=None):
            i = int(out["idx"][index])
            hard = _is_hard(i)
            gt_x = float(5 + (i % 13))
            if out["mid"] == "flag":
                x, score = gt_x, 0.95
            else:
                x = gt_x + (20.0 if hard else 0.0)  # wrong box when hard
                score = 0.3 if hard else 0.9
            return [
                None,
                np.array([[x, 2.0, x + 10.0, 12.0, score]], np.float32),
            ]

    def _is_hard(i: int) -> bool:
        return (i % 100) < hard_pct

    def _image(i: int) -> np.ndarray:
        im = np.full((24, 24, 3), 0.5, np.float32)
        im[0, 0, 0] = float(i)  # index channel the stub decodes
        return im

    def _accuracy(dets_list) -> float:
        good = 0
        for i, dets in enumerate(dets_list):
            gt_x = float(5 + (i % 13))
            good += int(abs(float(dets[1][0, 0]) - gt_x) < 1.0)
        return good / len(dets_list)

    def _run_leg(min_score):
        reg = ModelRegistry()
        reg.register("cheap", model=None, cfg=None, params={"w": 1})
        reg.register("flag", model=None, cfg=None, params={"w": 2})
        runner = _CascadeStubRunner(reg)
        eng = ServingEngine(runner, max_linger=0.0, max_queue=256)
        with eng:
            if min_score is not None:
                eng.attach_cascade({
                    "cheap": "cheap", "flagship": "flag",
                    "min_score": min_score,
                })
            warm_misses = runner.compile_cache.misses
            dets = [eng.submit(_image(i), model="flag").result(30)
                    for i in range(requests)]
            snap = eng.snapshot()
        casc = snap.get("cascade", {})
        return {
            "min_score": min_score,
            "accuracy": round(_accuracy(dets), 4),
            "cost_ms_per_image": round(
                runner.device_ms_total / requests, 3
            ),
            "device_ms_by_model": {
                k: round(v, 1)
                for k, v in runner.device_ms_by_model.items()
            },
            "escalations": casc.get("escalations", 0),
            "escalation_rate": casc.get("escalation_rate", 0.0),
            "first_pass_sufficient": casc.get("first_pass_sufficient", 0),
            "steady_state_compile_misses":
                runner.compile_cache.misses - warm_misses,
            "completed": snap["requests"]["completed"],
        }, [d[1].tobytes() for d in dets]

    flagship_only, base_bytes = _run_leg(None)
    sweep = []
    full_bytes = None
    for thresh in (0.0, 0.6, 1.01):
        leg, leg_bytes = _run_leg(thresh)
        sweep.append(leg)
        if thresh == 1.01:
            full_bytes = leg_bytes
    # best rung: cheapest sweep point within 1% of flagship accuracy
    matched = [s for s in sweep
               if s["accuracy"] >= flagship_only["accuracy"] - 0.01]
    best = min(matched, key=lambda s: s["cost_ms_per_image"])
    cost_reduction = round(
        flagship_only["cost_ms_per_image"] / best["cost_ms_per_image"], 2
    )
    zero_recompiles = (
        flagship_only["steady_state_compile_misses"] == 0
        and all(s["steady_state_compile_misses"] == 0 for s in sweep)
    )

    matrix, compression, rung_misses = _cascade_rung_matrix()
    int8_rows = [r for r in matrix if r["precision"] == "int8"]
    bf16_rows = [r for r in matrix if r["precision"] == "bf16"]
    claims = {
        "cost_reduction_ge_1p3x_at_matched_accuracy": bool(
            cost_reduction >= 1.3
        ),
        "full_escalation_byte_identical": bool(full_bytes == base_bytes),
        "zero_steady_state_recompiles": bool(
            zero_recompiles and rung_misses == 0
        ),
        "int8_parity_ok_box_and_mask": bool(
            len(int8_rows) == 2
            and all(r["ok"] and r["checked"] for r in int8_rows)
        ),
        "bf16_parity_ok_box_and_mask": bool(
            len(bf16_rows) == 2
            and all(r["ok"] and r["checked"] for r in bf16_rows)
        ),
    }
    report = {
        "claims": claims,
        "config": {
            "requests": requests,
            "hard_pct": hard_pct,
            "cheap_ms_per_image": CHEAP_MS,
            "flagship_ms_per_image": FLAG_MS,
        },
        "flagship_only": flagship_only,
        "sweep": sweep,
        "best": dict(best, cost_reduction_x=cost_reduction),
        "parity_matrix": matrix,
        "int8_compression": compression,
    }
    records = [
        {"metric": "serve_cascade_cost_ms_per_image_flagship_only",
         "value": flagship_only["cost_ms_per_image"], "unit": "ms",
         "vs_baseline": None},
        {"metric": "serve_cascade_cost_ms_per_image_matched",
         "value": best["cost_ms_per_image"], "unit": "ms",
         "vs_baseline": None},
        {"metric": "serve_cascade_cost_reduction_x",
         "value": cost_reduction, "unit": "x", "vs_baseline": None},
        {"metric": "serve_cascade_accuracy_flagship_only",
         "value": flagship_only["accuracy"], "unit": "fraction",
         "vs_baseline": None},
        {"metric": "serve_cascade_accuracy_matched",
         "value": best["accuracy"], "unit": "fraction",
         "vs_baseline": None},
        {"metric": "serve_cascade_escalation_rate_matched",
         "value": best["escalation_rate"], "unit": "fraction",
         "vs_baseline": None},
        {"metric": "serve_cascade_parity_rungs_ok",
         "value": sum(int(r["ok"]) for r in matrix), "unit": "rungs",
         "vs_baseline": None},
        {"metric": "serve_cascade_int8_compression_x_box",
         "value": compression["box"]["compression_x"], "unit": "x",
         "vs_baseline": None},
        {"metric": "serve_cascade_int8_compression_x_mask",
         "value": compression["mask"]["compression_x"], "unit": "x",
         "vs_baseline": None},
        {"metric": "serve_cascade_steady_state_compile_misses",
         "value": (flagship_only["steady_state_compile_misses"]
                   + sum(s["steady_state_compile_misses"] for s in sweep)
                   + rung_misses),
         "unit": "compiles", "vs_baseline": None},
    ]
    return records, report


# ------------------------------------------------------------- streaming
# chaos matrix for the streaming ordering bench (ISSUE 20): a mid-run
# predict failure (the failed batch's frames requeue off the tripped
# replica while LATER frames of the same streams are already dispatched
# elsewhere) and a stall long enough to fire the hedge — the two seams
# where a frame's result can come back out of stream order without the
# settlement gate.
_STREAM_FAULT_SCENARIOS = {
    "healthy": "",
    # unbounded fail on replica 0's batch 3: in-dispatch retries
    # exhaust, the replica trips and the batch REQUEUES onto a sibling
    # while later frames of the same streams keep dispatching — the
    # ISSUE 20 mid-stream-requeue chaos case
    "replica_trip": "predict_fail@0.3",
    # 1.5 s stall on replica 0's batch 5: past the hedge timeout
    # (0.75 s) so the duplicate dispatch wins, far under the stall
    # watchdog so nothing trips — the hedge-win ordering case
    "stall_hedge": "predict_stall@0.5:1.5",
}

# calibrated-stub priming budgets, smallest first — the sweep must show
# recall monotone in budget (latency is monotone by construction)
_PRIMING_BUDGETS = (25, 50, 100, 200, 400)


def _paste_stub_outputs(seed: int, rois_n: int, num_classes: int,
                        mask_size: int, hc: int, wc: int):
    """Flagship-shaped stub head outputs for the paste comparison.

    No backbone: the host-paste-vs-device-paste question is entirely a
    property of the fused postprocess program plus survivor geometry,
    so the stub fabricates the head tensors the program consumes —
    large instances (the workload where paste cost dominates; small
    boxes make BOTH paths RLE-bound) with mixed class scores so a
    realistic survivor population clears NMS."""
    rng = np.random.RandomState(seed)
    x1 = rng.uniform(0, wc * 0.25, rois_n).astype(np.float32)
    y1 = rng.uniform(0, hc * 0.25, rois_n).astype(np.float32)
    x2 = np.minimum(
        x1 + rng.uniform(wc * 0.5, wc * 0.75, rois_n), wc - 1.0
    ).astype(np.float32)
    y2 = np.minimum(
        y1 + rng.uniform(hc * 0.5, hc * 0.75, rois_n), hc - 1.0
    ).astype(np.float32)
    rois = np.stack([x1, y1, x2, y2], axis=1)
    cls_prob = rng.dirichlet(
        np.full(num_classes, 0.3), size=rois_n
    ).astype(np.float32)
    deltas = np.zeros((rois_n, 4 * num_classes), np.float32)
    logits = rng.uniform(
        -4.0, 4.0, (rois_n, mask_size, mask_size, num_classes)
    ).astype(np.float32)
    return {
        "rois": rois[None],
        "roi_valid": np.ones((1, rois_n), np.float32),
        "cls_prob": cls_prob[None],
        "bbox_deltas": deltas[None],
        "mask_logits": logits[None],
    }


def _stream_paste_stub(frames: int = 5, rois_n: int = 192,
                       max_det: int = 32, canvas_hw=(608, 800)) -> dict:
    """Calibrated-stub paste comparison at mask-flagship geometry.

    Runs the REAL fused postprocess program (``make_test_postprocess``)
    twice over identical stub head tensors — once with ``paste=True``
    (device canvas, host keeps only RLE) and once without (host runs
    the numpy fixed-point paste) — at flagship shapes (K=21, S=28,
    ~600×800 canvas, ``max_det`` survivors).  Per frame it measures the
    HOST wall time of the paste+RLE stage on each path and checks every
    survivor's RLE for byte identity; both jits must hold at one cached
    executable across all frames (zero steady-state recompiles)."""
    import dataclasses as _dc

    import jax

    from mx_rcnn_tpu.config import generate_config
    from mx_rcnn_tpu.eval.segm import paste_mask_canvas
    from mx_rcnn_tpu.native import rle as rle_mod
    from mx_rcnn_tpu.ops.postprocess import make_test_postprocess

    cfg = generate_config("mask_resnet_fpn", "PascalVOC")
    cfg = cfg.replace(TEST=_dc.replace(cfg.TEST, MAX_PER_IMAGE=max_det))
    num_classes = 21
    mask_size = cfg.TRAIN.MASK_SIZE
    hc, wc = canvas_hw
    max_out = 100
    pp_paste = make_test_postprocess(
        cfg, num_classes, thresh=0.05, max_out=max_out, paste=True
    )
    pp_host = make_test_postprocess(
        cfg, num_classes, thresh=0.05, max_out=max_out, paste=False
    )
    im_info = np.array([[hc, wc, 1.0]], np.float32)
    orig_hw = np.array([[hc, wc]], np.float32)
    dev_fn = jax.jit(
        lambda out, info, ohw: pp_paste(out, info, ohw, (hc, wc))
    )
    host_fn = jax.jit(pp_host)

    # The RLE encode stage is COMMON to both paths (same canvases in,
    # same counts out — that is the byte-identity bar) and unchanged by
    # this PR, so the paste-stage and RLE-stage walls are timed
    # separately: the reduction claim is about the paste stage the PR
    # moves on device ("the host keeps only RLE"); the total window is
    # reported alongside for the end-to-end picture.
    dev_paste_ms, host_paste_ms = [], []
    dev_rle_ms, host_rle_ms, dets_per_frame = [], [], []
    identical = True
    for f in range(frames):
        out = _paste_stub_outputs(f, rois_n, num_classes, mask_size, hc, wc)
        outd = jax.tree_util.tree_map(
            np.asarray, dev_fn(out, im_info, orig_hw)
        )
        outh = jax.tree_util.tree_map(
            np.asarray, host_fn(out, im_info, orig_hw)
        )
        midx = outd["det_mask_idx"][0]
        boxes = outd["det_boxes"][0]          # (K-1, max_out, 4)
        survivors = [
            (p, int(fl)) for p, fl in enumerate(midx) if fl >= 0
        ]
        dets_per_frame.append(len(survivors))

        # device leg paste stage: the canvases were pasted in the jit —
        # the remaining host-side work is materializing each survivor's
        # canvas slice for the encoder
        canvas = outd["det_canvas"][0]
        t0 = time.monotonic()
        dev_canvases = [
            np.ascontiguousarray(canvas[p]) for p, _fl in survivors
        ]
        dev_paste_ms.append((time.monotonic() - t0) * 1000.0)
        t0 = time.monotonic()
        dev_rles = [rle_mod.encode(cv) for cv in dev_canvases]
        dev_rle_ms.append((time.monotonic() - t0) * 1000.0)

        # host leg paste stage: numpy fixed-point paste per survivor
        grids = outh["det_masks"][0]
        t0 = time.monotonic()
        host_canvases = [
            paste_mask_canvas(grids[p], boxes[fl // max_out, fl % max_out],
                              hc, wc)                         # scale = 1.0
            for p, fl in survivors
        ]
        host_paste_ms.append((time.monotonic() - t0) * 1000.0)
        t0 = time.monotonic()
        host_rles = [rle_mod.encode(cv) for cv in host_canvases]
        host_rle_ms.append((time.monotonic() - t0) * 1000.0)

        identical &= len(dev_rles) == len(host_rles) and all(
            a["size"] == b["size"] and a["counts"] == b["counts"]
            for a, b in zip(dev_rles, host_rles)
        )

    # first frame pays lazy native-lib / allocator warmup on both
    # paths; the steady-state claim is the per-frame cost after it
    def _steady(xs):
        return float(np.mean(xs[1:])) if frames > 1 else xs[0]

    dev_paste = _steady(dev_paste_ms)
    host_paste = _steady(host_paste_ms)
    dev_total = dev_paste + _steady(dev_rle_ms)
    host_total = host_paste + _steady(host_rle_ms)
    return {
        "canvas_hw": [hc, wc],
        "mask_size": mask_size,
        "num_classes": num_classes,
        "rois": rois_n,
        "max_det": max_det,
        "frames": frames,
        "survivors_per_frame": dets_per_frame,
        "device_paste_ms_per_frame": round(dev_paste, 3),
        "host_paste_ms_per_frame": round(host_paste, 3),
        "reduction_x": round(host_paste / max(dev_paste, 1e-9), 2),
        "device_total_ms_per_frame": round(dev_total, 3),
        "host_total_ms_per_frame": round(host_total, 3),
        "total_reduction_x": round(host_total / max(dev_total, 1e-9), 2),
        "rle_byte_identical": bool(identical),
        "device_jit_executables": int(dev_fn._cache_size()),
        "host_jit_executables": int(host_fn._cache_size()),
    }


def _stub_rpn_proposals(rec: dict, rng, n: int = 400) -> np.ndarray:
    """Deliberately weak RPN stub for the priming sweep: per gt box a
    handful of jittered candidates buried among uniform-random boxes
    with overlapping score ranges, so small budgets genuinely miss
    objects — the regime where a frame-(N−1) seed can help."""
    h, w = float(rec["height"]), float(rec["width"])
    gts = np.asarray(rec["boxes"], np.float32)
    cand, scores = [], []
    for g in gts:
        bw, bh = g[2] - g[0] + 1.0, g[3] - g[1] + 1.0
        for _ in range(4):
            jit = rng.normal(0.0, 0.3, 4) * np.array([bw, bh, bw, bh])
            b = g + jit.astype(np.float32)
            cand.append([
                np.clip(b[0], 0, w - 1), np.clip(b[1], 0, h - 1),
                np.clip(b[2], 0, w - 1), np.clip(b[3], 0, h - 1),
            ])
            scores.append(rng.uniform(0.2, 0.9))
    n_rand = max(n - len(cand), 0)
    x1 = rng.uniform(0, w * 0.8, n_rand)
    y1 = rng.uniform(0, h * 0.8, n_rand)
    x2 = np.minimum(x1 + rng.uniform(20, w * 0.5, n_rand), w - 1)
    y2 = np.minimum(y1 + rng.uniform(20, h * 0.5, n_rand), h - 1)
    for i in range(n_rand):
        cand.append([x1[i], y1[i], x2[i], y2[i]])
        scores.append(rng.uniform(0.0, 0.7))
    props = np.concatenate(
        [np.asarray(cand, np.float32),
         np.asarray(scores, np.float32)[:, None]], axis=1
    )
    return props[np.argsort(-props[:, 4], kind="stable")]


def _proposal_stage_ms(budget: int, reps: int = 15) -> float:
    """Measured second-stage cost model for the priming latency axis: a
    (budget, 256)×(256, 256) feature transform plus a score sort — the
    per-proposal work whose linear scaling is what the budget buys
    back.  A calibrated stub (real measured wall, stub computation):
    the tradeoff table needs relative latencies, not absolute ones.
    Min-of-reps: at small budgets one timing is overhead-dominated and
    a scheduler hiccup can invert the budget ordering."""
    rng = np.random.RandomState(0)
    feats = rng.rand(budget, 256).astype(np.float32)
    w = rng.rand(256, 256).astype(np.float32)
    t = []
    for _ in range(reps + 1):
        t0 = time.monotonic()
        s = feats @ w
        np.argsort(-s[:, 0], kind="stable")
        t.append((time.monotonic() - t0) * 1000.0)
    return float(np.min(t[1:]))  # first rep pays allocator warmup


def _priming_sweep(num_streams: int = 3, frames: int = 12) -> dict:
    """Temporal proposal priming sweep over deterministic moving scenes
    (``data/synthetic.py::moving_scene``): frame N's proposal pool is
    the weak RPN stub either alone (unprimed) or seeded with frame
    N−1's detections (``serve/streams.py::prime_proposals``), recall
    via ``eval/recall.py::proposal_recall`` at each budget.  The
    simulated frame-(N−1) detector output is the previous gt lightly
    jittered with one stochastic miss — an imperfect tracker, not an
    oracle.  Frame 0 of each stream has no previous frame and is
    excluded (both arms would be identical there)."""
    from mx_rcnn_tpu.data.synthetic import moving_scene
    from mx_rcnn_tpu.eval.recall import proposal_recall
    from mx_rcnn_tpu.serve.streams import prime_proposals

    roidb, raw_props, prev_dets = [], [], []
    for s in range(num_streams):
        recs = moving_scene(1000 + s, frames, image_size=(480, 640),
                            num_objects=4)
        rng = np.random.RandomState(7000 + s)
        for f, rec in enumerate(recs):
            if f == 0:
                continue
            roidb.append(rec)
            raw_props.append(_stub_rpn_proposals(rec, rng))
            prev = np.asarray(recs[f - 1]["boxes"], np.float32)
            keep = rng.rand(len(prev)) > 0.15      # tracker misses ~15%
            jit = rng.normal(0.0, 2.0, prev.shape).astype(np.float32)
            prev_dets.append((prev + jit)[keep])

    table = []
    for budget in _PRIMING_BUDGETS:
        unprimed = [p[:budget] for p in raw_props]
        primed = [
            prime_proposals(p, d, budget)
            for p, d in zip(raw_props, prev_dets)
        ]
        r_un = proposal_recall(unprimed, roidb, top_ns=(budget,))
        r_pr = proposal_recall(primed, roidb, top_ns=(budget,))
        table.append({
            "budget": budget,
            "latency_ms": round(_proposal_stage_ms(budget), 4),
            "recall_unprimed": round(r_un[f"recall@{budget}"], 4),
            "recall_primed": round(r_pr[f"recall@{budget}"], 4),
        })

    def _monotone(key):
        vals = [row[key] for row in table]
        return all(b >= a - 1e-9 for a, b in zip(vals, vals[1:]))

    return {
        "streams": num_streams,
        "frames_per_stream": frames,
        "evaluated_frames": len(roidb),
        "table": table,
        "monotone_recall_unprimed": _monotone("recall_unprimed"),
        "monotone_recall_primed": _monotone("recall_primed"),
        "monotone_latency": _monotone("latency_ms"),
        "primed_never_worse": all(
            row["recall_primed"] >= row["recall_unprimed"] - 1e-9
            for row in table
        ),
    }


def bench_streaming(
    network: str = "resnet50",
    num_streams: int = 3,
    frames_per_stream: int = 8,
    max_batch: int = 2,
    linger_ms: float = 5.0,
) -> tuple:
    """Streaming-serve bench (ISSUE 20 acceptance evidence).

    Four phases:

    1. **paste stub** — the fused postprocess program at mask-flagship
       geometry over stub head tensors: device-canvas vs host-paste
       host ms/frame, RLE byte identity, one jit executable per path.
    2. **mask streaming serve** — the small mask family with
       ``MASK_CANVAS`` on, served as ordered streams through a
       2-replica pool with a blocking hot-swap fired mid-load; a
       host-paste comparator runner (same model/params, canvas off)
       pins RLE byte identity and the real-model paste-ms ratio.
       Zero steady-state recompiles through warmup + swap + load.
    3. **chaos ordering** — the box family on a 3-replica pool under
       ``_STREAM_FAULT_SCENARIOS``; every scenario must deliver every
       stream in frame order with zero lost frames, and ok-frame
       detections must be byte-identical to the healthy run.
    4. **priming sweep** — the train-free recall/latency tradeoff
       table (monotone in budget, primed never worse).
    """
    import os
    import tempfile

    import jax

    from mx_rcnn_tpu.core.checkpoint import save_checkpoint
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.serve.engine import ServingEngine
    from mx_rcnn_tpu.serve.loadgen import run_stream_load
    from mx_rcnn_tpu.serve.registry import ModelRegistry
    from mx_rcnn_tpu.serve.replica import HealthPolicy
    from mx_rcnn_tpu.serve.router import ReplicaPool, make_replica_factory
    from mx_rcnn_tpu.serve.runner import ServeRunner
    from mx_rcnn_tpu.utils import faults

    # ---------------------------------------- phase 1: paste stub
    stub = _stream_paste_stub()

    # ---------------------------------------- phase 2: mask streaming
    cfg = _mask_serve_cfg()
    cfg = cfg.replace(TEST=dataclasses.replace(cfg.TEST, MASK_CANVAS=True))
    sizes = ((72, 96), (96, 128))
    model = build_model(cfg)
    h0, w0 = cfg.SHAPE_BUCKETS[0]

    def init_params(seed):
        p = model.init(
            {"params": jax.random.key(seed)},
            np.zeros((1, h0, w0, 3), np.float32),
            np.array([[h0, w0, 1.0]], np.float32),
            train=False,
        )["params"]

        def _damp(path, leaf):
            name = "/".join(str(getattr(q, "key", q)) for q in path)
            for frag in ("rpn_cls_score", "rpn_bbox_pred", "cls_score",
                         "bbox_pred", "mask_logits"):
                if frag in name:
                    return leaf * 1e-2
            return leaf

        return jax.tree_util.tree_map_with_path(_damp, p)

    params = init_params(0)
    ckpt_v2 = save_checkpoint(
        os.path.join(tempfile.mkdtemp(prefix="bench-streaming-"), "v2"),
        {"params": init_params(1)}, 1,
    )
    registry = ModelRegistry()
    registry.register("masks", model, cfg, params)
    factory = make_replica_factory(
        lambda registry, device: ServeRunner(
            registry=registry, device=device, max_batch=max_batch,
            deterministic=True,
        ),
        registry=registry,
    )
    pool = ReplicaPool(factory, n_replicas=2, inflight_depth=2)
    rungs = pool.warmup()

    # host-paste comparator: same model/params/cfg, canvas OFF — the
    # pre-ISSUE-20 mask serving path (grids fetched, numpy paste)
    host = ServeRunner(
        model, params, cfg, max_batch=max_batch, deterministic=True,
        mask_canvas=False,
    )
    host.warmup()
    dev = pool.replicas[0].runner
    parity = []
    parity_ok = True
    from mx_rcnn_tpu.serve.loadgen import synthetic_image
    for i, (ih, iw) in enumerate(sizes):
        im = synthetic_image(i, ih, iw, seed=0)
        dreq = dev.make_request(im, model="masks")
        hreq = host.make_request(im)
        dout = dev.run(dev.assemble([dreq]), model="masks")
        hout = host.run(host.assemble([hreq]))
        d_dets, d_rles = dev.mask_rles_for(
            dout, {"im_info": [dreq.im_info],
                   "images": np.zeros((1,) + dreq.bucket + (3,))},
            0, orig_hw=(ih, iw), model="masks",
        )
        h_dets, h_rles = host.mask_rles_for(
            hout, {"im_info": [hreq.im_info],
                   "images": np.zeros((1,) + hreq.bucket + (3,))},
            0, orig_hw=(ih, iw),
        )
        eq = _rles_equal(d_rles, h_rles)
        parity_ok &= eq
        parity.append({
            "size": [ih, iw], "bucket": list(dreq.bucket),
            "detections": int(sum(
                len(d) for d in d_dets[1:] if d is not None
            )),
            "rles_byte_identical": eq,
        })
    model_reduction = (
        (host.paste_ms_total / max(host.pastes, 1))
        / max(dev.paste_ms_total / max(dev.pastes, 1), 1e-9)
    )

    swap_out = {}
    eng = ServingEngine(pool, max_linger=linger_ms / 1000.0, in_flight=2)
    with eng:
        base_done = eng.metrics.completed

        def fire_swap():
            t_end = time.time() + 120.0
            while (eng.metrics.completed - base_done < 4
                   and time.time() < t_end):
                time.sleep(0.01)
            try:
                swap_out["result"] = repr(eng.swap(
                    "masks", ckpt_v2, block=True, timeout=300
                ))
            except Exception as e:  # noqa: BLE001 — recorded as evidence
                swap_out["error"] = repr(e)

        swapper = threading.Thread(target=fire_swap, daemon=True)
        swapper.start()
        mask_rep = run_stream_load(
            eng, num_streams=num_streams,
            frames_per_stream=frames_per_stream, fps=2.0, sizes=sizes,
            seed=3, model="masks", masks=True, collect=False,
        )
        swapper.join(timeout=300)
    mask_snap = pool.snapshot()
    pool.close()
    steady_misses = mask_snap["compile"]["misses"] - rungs
    swap_landed = "result" in swap_out and "error" not in swap_out
    eng_snap = mask_rep["engine"]

    # ---------------------------------------- phase 3: chaos ordering
    _, _, _, box_sizes, box_factory = _serve_model(
        network, True, max_batch, deterministic=True
    )
    # generous watchdog: CPU oversubscription (3 resnet replicas plus
    # the injected stall) must not cascade into watchdog trips — the
    # only trips in this matrix are the ones the fault spec asks for
    policy = HealthPolicy(stall_timeout=30.0, breaker_backoff=0.25,
                          breaker_max_backoff=4.0)
    scenarios = {}
    healthy_ok = None
    prior = os.environ.get(faults.ENV_VAR)
    try:
        for name, spec in _STREAM_FAULT_SCENARIOS.items():
            if spec:
                os.environ[faults.ENV_VAR] = spec
            else:
                os.environ.pop(faults.ENV_VAR, None)
            faults.reset()
            cpool = ReplicaPool(box_factory, n_replicas=3, policy=policy,
                                hedge_timeout=0.75)
            cengine = ServingEngine(
                cpool, max_linger=linger_ms / 1000.0, in_flight=3
            )
            with cengine:
                rep = run_stream_load(
                    cengine, num_streams=4, frames_per_stream=8,
                    fps=4.0, sizes=box_sizes, seed=0, collect=True,
                )
            cpool.close()
            results = rep.pop("_results")
            rep.pop("_completion_seq", None)
            ok = {k: r for k, (kind, r) in results.items() if kind == "ok"}
            if name == "healthy":
                healthy_ok = ok
                identical = True
            else:
                identical = all(
                    _dets_equal(healthy_ok[k], ok[k])
                    for k in ok if k in healthy_ok
                )
            scenarios[name] = {
                "spec": spec,
                "in_order": rep["in_order"],
                "lost_frames": rep["lost_frames"],
                "outcomes": rep["outcomes"],
                "detections_match_healthy": identical,
                "streams": rep["engine"].get("streams"),
                "stream_reinserts":
                    rep["engine"]["scheduler"].get("stream_reinserts"),
            }
    finally:
        if prior is None:
            os.environ.pop(faults.ENV_VAR, None)
        else:
            os.environ[faults.ENV_VAR] = prior
        faults.reset()

    chaos_in_order = all(s["in_order"] for s in scenarios.values())
    chaos_lost = sum(s["lost_frames"] for s in scenarios.values())
    chaos_identical = all(
        s["detections_match_healthy"] for s in scenarios.values()
    )

    # ---------------------------------------- phase 4: priming sweep
    priming = _priming_sweep()

    claims = {
        "paste_rle_byte_identical": bool(
            stub["rle_byte_identical"] and parity_ok
        ),
        "paste_reduction_ge_5x": bool(stub["reduction_x"] >= 5.0),
        "zero_steady_state_recompiles": bool(
            steady_misses == 0 and swap_landed
            and stub["device_jit_executables"] == 1
            and stub["host_jit_executables"] == 1
        ),
        "stream_in_order_under_chaos": bool(
            chaos_in_order and chaos_lost == 0
        ),
        "chaos_bytes_identical": bool(chaos_identical),
        "priming_monotone_tradeoff": bool(
            priming["monotone_recall_primed"]
            and priming["monotone_recall_unprimed"]
            and priming["monotone_latency"]
            and priming["primed_never_worse"]
        ),
    }
    report = {
        "claims": claims,
        "paste": {
            "stub": stub,
            "model_parity": parity,
            "model_reduction_x": round(model_reduction, 2),
            "engine_paste": eng_snap.get("paste"),
            "pool_paste_ms": mask_snap["overlap"].get("paste_ms"),
            "pool_paste_bytes": mask_snap["overlap"].get("paste_bytes"),
        },
        "mask_stream": {
            "in_order": mask_rep["in_order"],
            "lost_frames": mask_rep["lost_frames"],
            "outcomes": mask_rep["outcomes"],
            "frames_per_sec": mask_rep["frames_per_sec"],
            "streams": eng_snap.get("streams"),
            "swap": swap_out,
            "steady_state_compile_misses": steady_misses,
            "ladder_rungs": rungs,
        },
        "chaos": scenarios,
        "priming": priming,
    }
    records = [
        {"metric": "streaming_paste_host_ms_per_frame",
         "value": stub["host_paste_ms_per_frame"], "unit": "ms",
         "vs_baseline": None},
        {"metric": "streaming_paste_device_ms_per_frame",
         "value": stub["device_paste_ms_per_frame"], "unit": "ms",
         "vs_baseline": None},
        {"metric": "streaming_paste_reduction_x",
         "value": stub["reduction_x"], "unit": "x", "vs_baseline": None},
        {"metric": "streaming_paste_rle_byte_identical",
         "value": 1.0 if claims["paste_rle_byte_identical"] else 0.0,
         "unit": "bool", "vs_baseline": None},
        {"metric": "streaming_steady_state_compile_misses",
         "value": steady_misses, "unit": "compiles", "vs_baseline": None},
        {"metric": "streaming_chaos_lost_frames",
         "value": chaos_lost, "unit": "frames", "vs_baseline": None},
        {"metric": "streaming_chaos_in_order",
         "value": 1.0 if chaos_in_order else 0.0, "unit": "bool",
         "vs_baseline": None},
        {"metric": "streaming_mask_frames_per_sec",
         "value": mask_rep["frames_per_sec"], "unit": "frames/sec",
         "vs_baseline": None},
        {"metric": "streaming_priming_recall_gain_at_50",
         "value": round(
             priming["table"][1]["recall_primed"]
             - priming["table"][1]["recall_unprimed"], 4
         ),
         "unit": "recall", "vs_baseline": None},
    ]
    return records, report


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--network", default="resnet",
        choices=sorted(_METRIC_NAMES),
    )
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--iters", type=int, default=20)
    ap.add_argument(
        "--steps_per_call", type=int, default=1,
        help="K train steps per dispatch (device-side lax.scan loop)",
    )
    ap.add_argument(
        "--all", action="store_true",
        help="bench every family; one JSON line each",
    )
    ap.add_argument(
        "--serve", action="store_true",
        help="bench the online serving engine instead of training",
    )
    # defaults chosen to SATURATE the engine (concurrency > in_flight *
    # max_batch, linger visible next to CPU service times) so the
    # occupancy number is a statement about the batcher, not the load
    ap.add_argument("--serve_requests", type=int, default=64)
    ap.add_argument("--serve_concurrency", type=int, default=16)
    ap.add_argument("--serve_max_batch", type=int, default=4)
    ap.add_argument("--serve_linger_ms", type=float, default=25.0)
    ap.add_argument("--serve_replicas", type=int, default=1,
                    help="replica-pool size for --serve (1 = the "
                         "no-regression case) / --serve_fault (min 3)")
    ap.add_argument("--inflight_depth", type=int, default=2,
                    help="per-replica in-flight dispatch window for "
                         "--serve (1 = the serial path; results are "
                         "byte-identical at any depth)")
    ap.add_argument(
        "--serve_overlap", action="store_true",
        help="overlapped-serving bench on a calibrated stub device "
             "stall: depth=1 vs depth=2 throughput + byte-identity, "
             "device-busy fraction, and the depth=2 fault matrix "
             "(zero lost, zero steady-state recompiles)",
    )
    ap.add_argument("--overlap_device_ms", type=float, default=60.0,
                    help="stub device compute per batch for "
                         "--serve_overlap")
    ap.add_argument("--overlap_fetch_ms", type=float, default=25.0,
                    help="stub D2H fetch + host postprocess per batch "
                         "for --serve_overlap")
    ap.add_argument(
        "--serve_fleet", action="store_true",
        help="multi-host fleet bench (ISSUE 19): wire-protocol gateway "
             "over N backend engine processes — N=1 byte-identity vs "
             "the direct engine, weak-scaling imgs/s at 1/2/4 backends, "
             "and a SIGKILL chaos phase (zero lost requests, surviving "
             "responses byte-identical to an unfaulted run)",
    )
    ap.add_argument("--fleet_requests", type=int, default=120,
                    help="requests PER BACKEND for --serve_fleet")
    ap.add_argument("--fleet_concurrency", type=int, default=32,
                    help="client concurrency per backend for "
                         "--serve_fleet")
    ap.add_argument("--fleet_service_ms", type=float, default=50.0,
                    help="stub backend device stall per batch for "
                         "--serve_fleet")
    ap.add_argument(
        "--serve_scale", action="store_true",
        help="tenant-fair front door bench (ISSUE 16): aggressor/victim "
             "isolation under a 4x rate-limit blast, autoscaler-"
             "initiated zero-loss scale-down (byte-identical to a "
             "fixed-size control), diurnal/oscillating trace "
             "convergence through the flap breaker, and zero steady-"
             "state recompiles at every pool size",
    )
    ap.add_argument(
        "--serve_mask", action="store_true",
        help="mask-family serving bench (ISSUE 14): device-side mask "
             "selection vs the raw-head path — per-batch fetch bytes "
             "before/after, RLE byte-identity across every bucket and "
             "padding config, p50/p99 through the replica pool, and "
             "zero steady-state recompiles",
    )
    ap.add_argument(
        "--streaming", action="store_true",
        help="streaming-serve bench (ISSUE 20): device-side mask paste "
             "vs host paste (ms/frame + RLE byte-identity at flagship "
             "geometry on the calibrated stub), per-stream in-order "
             "completion under the chaos matrix with a mid-load hot-"
             "swap, and the temporal-priming recall/latency sweep",
    )
    ap.add_argument("--stream_count", type=int, default=3,
                    help="streams in the mask streaming leg")
    ap.add_argument("--stream_frames", type=int, default=8,
                    help="frames per stream in the mask streaming leg")
    ap.add_argument(
        "--cascade", action="store_true",
        help="compression ladder + confidence-gated cascade bench "
             "(ISSUE 18): escalation-threshold sweep tracing cost-per-"
             "image vs accuracy on a modeled two-family registry "
             "(matched-accuracy cost reduction + 100%%-escalation "
             "byte-identity), plus the {box,mask} x {f32,bf16,int8} "
             "parity matrix on real tiny models",
    )
    ap.add_argument("--cascade_requests", type=int, default=80)
    ap.add_argument("--cascade_hard_pct", type=float, default=30.0,
                    help="percent of images the cheap family answers "
                         "wrong (and scores low) in --cascade")
    ap.add_argument(
        "--serve_fault", action="store_true",
        help="fault-matrix serving bench: healthy vs wedged vs flapping "
             "replica scenarios on a >=3-replica pool (zero-lost + "
             "byte-identical + recovery-time evidence)",
    )
    ap.add_argument(
        "--poison", action="store_true",
        help="query-of-death containment bench: ~5%% deterministic "
             "poison inside healthy traffic on a 2-replica pool with "
             "quarantine on (zero healthy losses, byte-identical "
             "healthy detections, <=K trips per poison digest, all "
             "replicas healthy at the end)",
    )
    ap.add_argument("--poison_k", type=int, default=2,
                    help="quarantine trip threshold K for --poison")
    ap.add_argument(
        "--slo", action="store_true",
        help="SLO-tier serving bench: sparse interactive probes vs a "
             "saturating bulk backlog, single-lane baseline vs two-lane "
             "(interactive p99 + bulk-throughput retention + zero "
             "recompiles), plus response-cache byte-identity and the "
             "bf16 serve-graph parity gate",
    )
    ap.add_argument("--slo_probes", type=int, default=5)
    ap.add_argument("--slo_probe_spacing", type=float, default=10.0)
    ap.add_argument("--slo_bulk_concurrency", type=int, default=32)
    ap.add_argument(
        "--swap", action="store_true",
        help="model-lifecycle serving bench: live hot-swap under load "
             "(zero lost, byte-identical outside the swap window, zero "
             "recompiles), verify/warm/canary rollback matrix, and "
             "two-family tenancy through one batcher",
    )
    ap.add_argument(
        "--rollout", action="store_true",
        help="progressive-rollout bench (ISSUE 17): traffic-split canary "
             "promote under load (zero lost, zero recompiles), shadow-mode "
             "divergence auto-rollback with a byte-identical incumbent, "
             "and the closed serve->distill->fine-tune->promote loop",
    )
    ap.add_argument("--distill_steps", type=int, default=2,
                    help="fine-tune steps for the closed-loop scenario")
    ap.add_argument(
        "--serve_full", action="store_true",
        help="serve at the full config (default: tiny CPU-runnable one)",
    )
    ap.add_argument(
        "--pipeline", action="store_true",
        help="bench the device-resident step pipeline (feed occupancy, "
             "fetch stalls, K=1 byte-identical check) on the CPU smoke "
             "config",
    )
    ap.add_argument(
        "--eval", dest="eval_plane", action="store_true",
        help="bench the eval host data plane (parallel assembly + "
             "prepared cache + completion pool) around a stub device at "
             "flagship image size; serial vs overlapped, bitwise check",
    )
    ap.add_argument("--eval_images", type=int, default=64)
    ap.add_argument("--eval_batch", type=int, default=8)
    ap.add_argument("--stub_device_ms", type=float, default=110.0,
                    help="stub device stall per batch (110 ms = the "
                         "73 img/s device ceiling at b8, ROOFLINE r5)")
    ap.add_argument("--assembly_workers", type=int, default=2)
    ap.add_argument("--postprocess_workers", type=int, default=2)
    ap.add_argument("--prepared_cache", type=int, default=128)
    ap.add_argument("--pipeline_steps", type=int, default=16)
    ap.add_argument("--aux_interval", type=int, default=4,
                    help="K: train aux fetched every K steps")
    ap.add_argument("--feed_depth", type=int, default=2,
                    help="device-feed double-buffer depth")
    ap.add_argument("--pipeline_batch", type=int, default=2)
    ap.add_argument(
        "--elastic", action="store_true",
        help="chaos matrix for elastic training on 8 virtual CPU devices "
             "(lose-1-of-8 / wedge / lose-then-regrow / preempt-during-"
             "shrink; zero-lost + bitwise shrink-equivalence + recovery "
             "seconds)",
    )
    ap.add_argument("--elastic_steps", type=int, default=8)
    ap.add_argument("--elastic_batch", type=int, default=8,
                    help="global batch for --elastic (must divide by 8)")
    ap.add_argument(
        "--out", default=None,
        help="also write the records as a JSON array artifact",
    )
    args = ap.parse_args()

    from mx_rcnn_tpu.utils.platform import enable_compile_cache

    if args.elastic:
        # env-only, and BEFORE enable_compile_cache touches jax: the 8
        # virtual devices must exist at backend init, and the compile
        # cache subdir is keyed on the XLA_FLAGS this sets
        from mx_rcnn_tpu.utils.platform import set_cpu_platform

        set_cpu_platform(8)

    enable_compile_cache()

    if args.elastic:
        records, report = bench_elastic(args.elastic_steps,
                                        args.elastic_batch)
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.eval_plane:
        from mx_rcnn_tpu.tools.bench_eval import data_plane_report

        report = data_plane_report(
            images=args.eval_images,
            batch=args.eval_batch,
            stub_device_ms=args.stub_device_ms,
            assembly_workers=args.assembly_workers,
            postprocess_workers=args.postprocess_workers,
            prepared_cache=args.prepared_cache,
        )
        records = _eval_records(report)
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.pipeline:
        records, report = bench_pipeline(
            args.pipeline_steps, args.aux_interval, args.feed_depth,
            args.pipeline_batch,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.slo:
        network = "resnet50" if args.network == "resnet" else args.network
        records, report = bench_serve_slo(
            network, probes=args.slo_probes,
            probe_spacing_s=args.slo_probe_spacing,
            bulk_concurrency=args.slo_bulk_concurrency,
            max_batch=args.serve_max_batch // 2 or 1,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.rollout:
        network = "resnet50" if args.network == "resnet" else args.network
        records, report = bench_rollout(
            network, args.serve_requests, args.serve_concurrency,
            args.serve_max_batch, args.serve_linger_ms,
            small=not args.serve_full, distill_steps=args.distill_steps,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.swap:
        network = "resnet50" if args.network == "resnet" else args.network
        records, report = bench_swap(
            network, args.serve_requests, args.serve_concurrency,
            args.serve_max_batch, args.serve_linger_ms,
            small=not args.serve_full, replicas=args.serve_replicas,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.poison:
        network = "resnet50" if args.network == "resnet" else args.network
        records, report = bench_poison(
            network, args.serve_requests, args.serve_concurrency,
            args.serve_max_batch, args.serve_linger_ms,
            replicas=max(2, args.serve_replicas), k=args.poison_k,
            small=not args.serve_full,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.serve_overlap:
        records, report = bench_serve_overlap(
            requests=args.serve_requests,
            concurrency=args.serve_concurrency // 2 or 8,
            device_ms=args.overlap_device_ms,
            fetch_ms=args.overlap_fetch_ms,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.cascade:
        records, report = bench_cascade(
            requests=args.cascade_requests,
            hard_pct=args.cascade_hard_pct,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.serve_fleet:
        records, report = bench_serve_fleet(
            requests_per_backend=args.fleet_requests,
            concurrency_per_backend=args.fleet_concurrency,
            service_ms=args.fleet_service_ms,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.serve_scale:
        records, report = bench_serve_scale(
            requests=args.serve_requests,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.streaming:
        network = "resnet50" if args.network == "resnet" else args.network
        records, report = bench_streaming(
            network, num_streams=args.stream_count,
            frames_per_stream=args.stream_frames,
            max_batch=args.serve_max_batch // 2 or 1,
            linger_ms=args.serve_linger_ms,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.serve_mask:
        records, report = bench_serve_mask(
            args.serve_requests, args.serve_concurrency,
            args.serve_max_batch, args.serve_linger_ms,
            replicas=args.serve_replicas,
            inflight_depth=args.inflight_depth,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.serve_fault:
        network = "resnet50" if args.network == "resnet" else args.network
        records, report = bench_serve_fault(
            network, args.serve_requests, args.serve_concurrency,
            args.serve_max_batch, args.serve_linger_ms,
            replicas=args.serve_replicas, small=not args.serve_full,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    if args.serve:
        network = "resnet50" if args.network == "resnet" else args.network
        records, report = bench_serve(
            network, args.serve_requests, args.serve_concurrency,
            args.serve_max_batch, args.serve_linger_ms,
            small=not args.serve_full, replicas=args.serve_replicas,
            inflight_depth=args.inflight_depth,
        )
        for rec in records:
            print(json.dumps(rec), flush=True)
        if args.out:
            with open(args.out, "w") as f:
                json.dump({"records": records, "report": report}, f, indent=1)
        return

    families = _ALL_FAMILIES if args.all else (args.network,)
    records = []
    for network in families:
        rec = bench_one(network, args.batch, args.iters, args.steps_per_call)
        records.append(rec)
        print(json.dumps(rec), flush=True)
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)


if __name__ == "__main__":
    main()
