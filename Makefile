# Build/test glue (reference: the repo-root Makefile that ran
# `setup.py build_ext --inplace` over rcnn/cython + rcnn/pycocotools).
# The TPU rebuild has no ahead-of-time extension build — Pallas kernels
# are JIT-compiled and the C host libraries self-build into a per-user
# cache on first import — so `make native` just forces that build and
# `make test-kernels` is the SURVEY N4 kernel-vs-oracle harness.

PY ?= python

.PHONY: native test test-kernels test-fast lint check resilience bench bench-eval eval-bench serve serve-overlap serve-fault serve-mask streaming serve-scale serve-fleet swap rollout cascade slo poison pipeline elastic chaos integration-gate clean-native

# compile native/hostops.c + native/rlelib.c into ~/.cache/mx_rcnn_tpu
native:
	$(PY) -c "from mx_rcnn_tpu.native import hostops, rle; \
	          assert hostops._lib() is not None, 'hostops build failed'; \
	          assert rle._lib() is not None, 'rlelib build failed'; \
	          print('native libraries built')"

clean-native:
	rm -f $${XDG_CACHE_HOME:-$$HOME/.cache}/mx_rcnn_tpu/*.so

# full suite (8 virtual CPU devices via tests/conftest.py); ~2h on 1
# core — the once-per-round gate.  Every test carries a wall-clock
# deadline (tests/conftest.py watchdog thread: stacks dumped, run
# aborted) so a hang fails loudly instead of stalling (VERDICT r4 #6).
test:
	$(PY) -m pytest tests/ -x -q

# Pallas kernels + geometry vs their oracles only (fast)
test-kernels:
	$(PY) -m pytest tests/test_pallas_nms.py tests/test_pallas_roi_align.py \
	      tests/test_nms.py tests/test_geometry.py tests/test_hostops.py \
	      tests/test_rle.py -q

# quick signal, <10 min on this box: the whole suite minus the
# compile-bound @slow files (parallel/distributed/gates/CLI), plus one
# named DP-correctness representative so the parallel subsystem is
# never unrepresented in the fast tier
test-fast:
	$(PY) -m pytest tests/ -m "not slow" -q

# graftlint: project-native static analysis (ANALYSIS.md) — exits
# nonzero on any unsuppressed finding, stale baseline entry, or
# unparseable BENCH_*.json artifact.  Pure stdlib-ast: no jax import.
lint:
	$(PY) tools/lint.py

# the CI gate: static analysis first (seconds), then the fast tier
check: lint test-fast
	$(PY) -m pytest "tests/test_parallel.py::test_mesh_shapes" \
	      "tests/test_parallel.py::test_dp_grads_match_single_device" -q

# fault-injection resilience suite (ISSUE 1): guarded-loop rollback,
# crash-safe checkpoint fallback, loader failure budget, step watchdog —
# all driven deterministically via MX_RCNN_FAULTS, CPU-only, <1 min
resilience:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_resilience.py \
	      tests/test_preemption.py -q

# flagship train throughput (real TPU); prints one JSON line
bench:
	$(PY) bench.py

# inference throughput (host-bound on weak dev hosts; see the docstring)
bench-eval:
	$(PY) -m mx_rcnn_tpu.tools.bench_eval

# eval host data plane bench (ISSUE 5): parallel assembly + prepared
# cache + completion pool around a stub device at flagship image size;
# serial vs overlapped img/s, stage counters, bitwise detection check;
# emits JSON lines + the BENCH_eval_cpu.json artifact
eval-bench:
	JAX_PLATFORMS=cpu $(PY) bench.py --eval --out BENCH_eval_cpu.json

# online serving load test (mixed-size synthetic traffic through the
# dynamic batcher + shape-bucket ladder; SERVING.md); CPU-runnable.
# Emits p50/p99, imgs/sec, occupancy, and the compile count proving
# zero recompiles after warmup, as JSON lines + the artifact file
serve:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve --out BENCH_serve_cpu.json

# overlapped-serving bench (ISSUE 13): split dispatch/complete predict
# path with a bounded per-replica in-flight window, measured against a
# calibrated stub device stall (model FLOPs would hide the overlap on
# CPU).  Emits depth=1 vs depth=2 throughput + speedup, stub-exact
# device-busy fraction, byte-identity, and the depth=2 fault matrix
# (zero lost, zero steady-state recompiles) as the artifact
serve-overlap:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve_overlap \
	      --out BENCH_serve_overlap_cpu.json

# mask-family serving bench (ISSUE 14): device-side mask selection —
# the jit gathers each survivor's S×S grid for its predicted class, so
# the host fetches [max_det, S, S] instead of the raw (R, S, S, K)
# stack.  Emits fetch bytes/batch raw vs device (the >=5x claim),
# per-detection RLE byte-identity vs the host path across all buckets,
# p50/p99 under mixed-size load, and the zero-steady-state-recompile
# count, as JSON lines + the BENCH_serve_mask_cpu.json artifact
serve-mask:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve_mask --serve_requests 24 \
	      --serve_concurrency 6 --serve_max_batch 4 \
	      --out BENCH_serve_mask_cpu.json

# streaming-serve bench (ISSUE 20): device-side mask paste — survivors'
# S×S grids resized/thresholded into their box footprints on the fixed
# bucket canvas INSIDE the jit, so the host keeps only RLE.  Emits the
# host-paste-ms/frame reduction at mask-flagship geometry (RLE
# byte-identity vs the numpy fixed-point mirror), per-stream in-order
# completion under the trip/stall chaos matrix with a mid-load hot-swap
# (zero lost frames, bytes identical to the unfaulted run), the
# zero-steady-state-recompile count, and the temporal-priming
# recall/latency sweep, as the BENCH_streaming_cpu.json artifact
streaming:
	JAX_PLATFORMS=cpu $(PY) bench.py --streaming --serve_max_batch 4 \
	      --out BENCH_streaming_cpu.json

# tenant-fair front door bench (ISSUE 16): aggressor/victim isolation
# with the aggressor blasting 4x its token-bucket rate (victim p99 must
# hold within 10%), an autoscaler-initiated scale-down under live load
# that loses zero requests and stays byte-identical to a fixed-size
# control, diurnal + oscillating trace convergence through the flap
# breaker, and zero steady-state recompiles at every pool size
serve-scale:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve_scale \
	      --out BENCH_serve_scale_cpu.json

# multi-host fleet bench (ISSUE 19): a wire-protocol FleetGateway over
# 1/2/4 backend engine PROCESSES (pipelined connection pools, host-
# level health/hedging, requeue-never-drop) — N=1 gateway responses
# byte-identical to the direct engine, near-linear aggregate imgs/s
# scaling, and a SIGKILL chaos phase that loses zero requests with
# surviving responses byte-identical to an unfaulted run; emits the
# BENCH_serve_fleet_cpu.json artifact `make check` then guards
serve-fleet:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve_fleet \
	      --out BENCH_serve_fleet_cpu.json

# fault-matrix serving bench (ISSUE 6): the same deterministic load
# against a 3-replica health-gated pool under healthy / wedged-replica /
# flapping-replica MX_RCNN_FAULTS scenarios; emits per-scenario p50/p99
# + throughput, drain->rewarm->rejoin recovery time, shed/hedge/requeue
# counts, and the zero-lost + byte-identical evidence, as JSON lines +
# the BENCH_serve_fault_cpu.json artifact
serve-fault:
	JAX_PLATFORMS=cpu $(PY) bench.py --serve_fault --serve_requests 24 \
	      --serve_concurrency 6 --serve_max_batch 2 \
	      --out BENCH_serve_fault_cpu.json

# model-lifecycle serving bench (ISSUE 7): live hot-swap under load on a
# 2-replica pool (zero lost requests, byte-identical detections outside
# the swap window, zero recompiles through the swap), the
# verify/warm/canary fault-rollback matrix, and two model families
# through one batcher with zero steady-state recompiles; emits JSON
# lines + the BENCH_swap_cpu.json artifact
swap:
	JAX_PLATFORMS=cpu $(PY) bench.py --swap --serve_requests 24 \
	      --serve_concurrency 6 --serve_max_batch 2 --serve_replicas 2 \
	      --out BENCH_swap_cpu.json

# progressive-rollout bench (ISSUE 17): traffic-split canary promote
# under load (zero lost, byte-identical, zero recompiles), shadow-mode
# divergence auto-rollback with the incumbent serving identical bytes
# throughout, and the closed serve->distill->fine-tune->promote loop;
# emits JSON lines + the BENCH_rollout_cpu.json artifact
rollout:
	JAX_PLATFORMS=cpu $(PY) bench.py --rollout --serve_requests 24 \
	      --serve_concurrency 6 --serve_max_batch 2 \
	      --out BENCH_rollout_cpu.json

# compression ladder + confidence-gated cascade bench (ISSUE 18):
# escalation-threshold sweep tracing cost-per-image vs matched
# accuracy (cheap-first serving with flagship escalation on doubt),
# 100%-escalation byte-identity control arm, per-rung parity matrix
# ({box,mask} x {f32,bf16,int8} on real tiny models) and int8
# compression stats; emits JSON lines + the BENCH_cascade_cpu.json
# artifact, which `make check`'s lint artifact-parse pass then guards
cascade:
	JAX_PLATFORMS=cpu $(PY) bench.py --cascade --out BENCH_cascade_cpu.json

# SLO-tier serving bench (ISSUE 11): sparse interactive probes against
# a saturating bulk backlog, single-lane baseline vs two-lane scheduling
# on ONE runner (so the compile cache spans both — the cross-lane
# zero-recompile evidence); open-loop probes keep the offered
# interactive rate identical across phases.  Emits per-lane p50/p99,
# bulk-throughput retention, preemption counts, response-cache
# byte-identity + hit rate, and the bf16 serve-graph parity report, as
# JSON lines + the BENCH_serve_slo_cpu.json artifact
slo:
	JAX_PLATFORMS=cpu $(PY) bench.py --slo --out BENCH_serve_slo_cpu.json

# query-of-death containment bench (ISSUE 12): ~5% deterministic poison
# (per-size qod_image digests wired to poison_fail) inside healthy
# traffic on a 2-replica pool with the quarantine table on; proves zero
# healthy losses, healthy detections byte-identical to the unfaulted
# run, every poison digest quarantined within <=K trips, and all
# replicas HEALTHY at the end; emits JSON lines + the
# BENCH_poison_cpu.json artifact
poison:
	JAX_PLATFORMS=cpu $(PY) bench.py --poison --serve_requests 48 \
	      --serve_concurrency 6 --serve_max_batch 2 --serve_replicas 2 \
	      --out BENCH_poison_cpu.json

# device-resident step pipeline bench (ISSUE 4): feed occupancy, fetch
# stalls, K=1 byte-identical check on the CPU smoke config; emits JSON
# lines + the BENCH_pipeline.json artifact
pipeline:
	JAX_PLATFORMS=cpu $(PY) bench.py --pipeline --out BENCH_pipeline.json

# elastic-training chaos matrix (ISSUE 9): 8 virtual CPU devices, four
# deterministic device-fault scenarios (lose 1 of 8 mid-step, wedged
# replica, lose-then-regrow at a checkpoint boundary, preemption during
# the shrink's emergency save); proves zero lost steps beyond the
# pipeline window, bitwise shrink-equivalence vs a fresh small-mesh run,
# and records recovery seconds; emits JSON lines + the
# BENCH_elastic_cpu.json artifact.  bench.py forces the 8-device CPU
# platform itself (before jax init), so no env shim is needed here.
elastic:
	$(PY) bench.py --elastic --out BENCH_elastic_cpu.json

# chaos gate (ISSUE 9 + 12): every deterministic fault-injection
# surface in one target — the elastic loop's unit matrix plus the
# preemption, resilience, and query-of-death quarantine suites, with
# the lock-order checker armed — then the poison containment bench
chaos:
	JAX_PLATFORMS=cpu MX_RCNN_LOCK_CHECK=1 $(PY) -m pytest \
	      tests/test_elastic.py tests/test_preemption.py \
	      tests/test_resilience.py tests/test_quarantine.py -q
	$(MAKE) poison

# train→eval mAP gates on synthetic data, one per model family
# (VERDICT r3 #7): C4 flagship shape, FPN, Mask (polygon gts + segm
# protocol), VGG, and a data-parallel C4 gate over 8 virtual devices.
# FPN-family lr 5e-4 = measured stability limit for random-init
# frozen-BN after moment calibration (utils/bn_calibrate.py); FPN/mask
# TARGETS are the currently-measured random-init plateaus (the stride-4
# anchor pool saturates the fg/bg IoU boundary and the head carries an
# irreducible label-churn CE floor ≈0.6 — see integration_gate.py's
# gate_cfg notes), not aspirations: raising them is open perf work.
integration-gate:
	$(PY) -m mx_rcnn_tpu.tools.integration_gate --network resnet50
	$(PY) -m mx_rcnn_tpu.tools.integration_gate --network resnet_fpn --lr 5e-4 --steps 1200 --eval_every 200 --target 0.5
	$(PY) -m mx_rcnn_tpu.tools.integration_gate --network mask_resnet_fpn --lr 5e-4 --steps 1200 --eval_every 200 --target 0.3
	$(PY) -m mx_rcnn_tpu.tools.integration_gate --network vgg --lr 1e-3 --target 0.5
	$(PY) -m mx_rcnn_tpu.tools.integration_gate --network resnet50 --cpu 8 --dp 8 --steps 200 --target 0.5
