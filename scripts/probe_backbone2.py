"""Round-4 probe, take 2: per-op timings with in-jit chaining.

probe_backbone.py timed each op as its own jitted dispatch; on the axon
relay every dispatch carries ~20 ms of host/tunnel latency, so small ops
all measured ~20 ms and the per-stage numbers summed to 3x the whole
backbone.  This probe chains N applications of the op inside ONE jit
(lax.fori_loop, input perturbed by the loop index so XLA cannot hoist
the body) and reports (t(N) - t(1)) / (N - 1): pure device time per
application, dispatch overhead cancelled.

Usage: python scripts/probe_backbone2.py [variant ...]
Variants: base stages conv0 folded all
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from mx_rcnn_tpu.utils.platform import enable_compile_cache

enable_compile_cache()

B, H, W = 8, 608, 1024
DTYPE = jnp.bfloat16
N = 9  # chained applications


def chained(fn, x, n):
    """Scalar-result jit that applies fn n times to x (loop-dependent)."""

    def run(p, xx):
        def body(i, acc):
            xi = xx + (i.astype(xx.dtype) * xx.dtype.type(1e-30))
            return acc + fn(p, xi)

        return lax.fori_loop(0, n, body, jnp.float32(0.0))

    return jax.jit(run)


def timeit(fn, *args, iters=6, warmup=2):
    for _ in range(warmup):
        r = fn(*args)
    _ = float(jnp.asarray(r).ravel()[0])  # relay-safe sync
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    _ = float(jnp.asarray(r).ravel()[0])
    return (time.perf_counter() - t0) / iters * 1000


def bench_op(tag, fn, params, x):
    t1 = timeit(chained(fn, x, 1), params, x)
    tn = timeit(chained(fn, x, N), params, x)
    per = (tn - t1) / (N - 1)
    print(f"{tag:<44s} {per:8.2f} ms  (t1={t1:.1f} tN={tn:.1f})",
          flush=True)
    return per


def main():
    variants = sys.argv[1:] or ["all"]
    if "all" in variants:
        variants = ["base", "stages", "conv0", "folded"]

    from mx_rcnn_tpu.models.resnet import ResNetBackbone, ResNetStage

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(B, H, W, 3).astype(np.float32))

    bb = ResNetBackbone(depth=101, dtype=DTYPE, frozen_prefix=2)
    params = bb.init(jax.random.key(0), x[:1])["params"]

    def fwd_scalar(p, xx):
        return bb.apply({"params": p}, xx).astype(jnp.float32).sum()

    def bwd_scalar(p, xx):
        g = jax.grad(fwd_scalar)(p, xx)
        return jax.tree_util.tree_reduce(
            lambda a, l: a + l.astype(jnp.float32).sum(), g, jnp.float32(0)
        )

    if "base" in variants:
        bench_op("backbone fwd", fwd_scalar, params, x)
        bench_op("backbone fwd+bwd", bwd_scalar, params, x)

    if "stages" in variants:
        import flax.linen as nn

        from mx_rcnn_tpu.models.layers import FrozenBatchNorm, conv

        class Conv0(nn.Module):
            @nn.compact
            def __call__(self, xx):
                xx = xx.astype(DTYPE)
                xx = conv(64, 7, 2, DTYPE, name="conv0")(xx)
                xx = FrozenBatchNorm(dtype=DTYPE, name="bn0")(xx)
                xx = nn.relu(xx)
                return nn.max_pool(
                    xx, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1))
                )

        c0 = Conv0()
        p0 = {"conv0": params["conv0"], "bn0": params["bn0"]}
        bench_op(
            "conv0+bn+pool fwd",
            lambda p, xx: c0.apply({"params": p}, xx)
            .astype(jnp.float32).sum(),
            p0, x,
        )
        y = jax.jit(lambda p, xx: c0.apply({"params": p}, xx))(p0, x)

        blocks = {"stage1": (64, 3, 1), "stage2": (128, 4, 2),
                  "stage3": (256, 23, 2)}
        for name, (filt, n, stride) in blocks.items():
            st = ResNetStage(filt, n, stride, DTYPE, name=name)
            sp = params[name]

            def sf(p, xx, st=st):
                return st.apply({"params": p}, xx).astype(jnp.float32).sum()

            def sb(p, xx, st=st, sf=sf):
                g = jax.grad(sf)(p, xx)
                return jax.tree_util.tree_reduce(
                    lambda a, l: a + l.astype(jnp.float32).sum(), g,
                    jnp.float32(0),
                )

            bench_op(f"{name} fwd (in {y.shape[1]}x{y.shape[2]})", sf, sp, y)
            bench_op(f"{name} fwd+bwd", sb, sp, y)
            y = jax.jit(lambda p, xx, st=st: st.apply({"params": p}, xx))(
                sp, y
            )

    if "conv0" in variants:
        k7 = jnp.asarray(rng.rand(7, 7, 3, 64).astype(np.float32) * 0.01,
                         DTYPE)

        def plain(_, xx):
            return lax.conv_general_dilated(
                xx.astype(DTYPE), k7, (2, 2), [(3, 3), (3, 3)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ).astype(jnp.float32).sum()

        bench_op("plain conv0 7x7s2 C3 fwd", plain, None, x)

        k4 = jnp.asarray(rng.rand(4, 4, 12, 64).astype(np.float32) * 0.01,
                         DTYPE)

        def s2d(_, xx):
            v = xx.reshape(B, H // 2, 2, W // 2, 2, 3)
            v = v.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // 2, W // 2, 12)
            return lax.conv_general_dilated(
                v.astype(DTYPE), k4, (1, 1), [(2, 1), (2, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            ).astype(jnp.float32).sum()

        bench_op("s2d conv0 4x4s1 C12 fwd", s2d, None, x)

    if "folded" in variants:
        bbf = ResNetBackbone(depth=101, dtype=DTYPE, frozen_prefix=2,
                             fold_bn=True)

        def ffwd(p, xx):
            return bbf.apply({"params": p}, xx).astype(jnp.float32).sum()

        def fbwd(p, xx):
            g = jax.grad(ffwd)(p, xx)
            return jax.tree_util.tree_reduce(
                lambda a, l: a + l.astype(jnp.float32).sum(), g,
                jnp.float32(0),
            )

        bench_op("folded-BN backbone fwd", ffwd, params, x)
        bench_op("folded-BN backbone fwd+bwd", fbwd, params, x)


if __name__ == "__main__":
    main()
