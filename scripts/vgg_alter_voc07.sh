#!/usr/bin/env bash
# VGG-16 4-stage alternate training on VOC07 (reference: script/vgg_alter_voc07.sh)
set -euo pipefail
python -m mx_rcnn_tpu.tools.train_alternate \
    --network vgg --dataset PascalVOC \
    --pretrained "${PRETRAINED:-vgg16.pth}" \
    --out_dir model/vgg_alter_voc07 "$@"
python -m mx_rcnn_tpu.tools.test --network vgg --dataset PascalVOC \
    --params model/vgg_alter_voc07/final.pkl
