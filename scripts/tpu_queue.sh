#!/bin/bash
# Round-4 TPU work queue: runs once the axon relay is back.
# Usage: PYTHONPATH=/root/.axon_site:/root/repo bash scripts/tpu_queue.sh
set -u
cd /root/repo
export JAX_PLATFORMS=axon  # a silent CPU fallback must FAIL the probe
log() { echo "[tpu_queue $(date +%H:%M:%S)] $*"; }

# wait for the relay (up to ~2h), probing with a tiny device query that
# asserts the device really is the TPU, not a fallback backend
up=0
for i in $(seq 1 240); do
    if timeout 45 python -c \
        "import jax; d = jax.devices(); assert d[0].platform != 'cpu', d" \
        >/dev/null 2>&1; then
        log "relay is up"
        up=1
        break
    fi
    sleep 30
done
if [ "$up" != 1 ]; then
    log "relay never came up — aborting queue"
    exit 1
fi

fails=0
run() {
    name=$1; shift
    log "START $name"
    timeout 4000 "$@" > "/tmp/q_$name.log" 2>&1
    rc=$?
    [ $rc -ne 0 ] && fails=$((fails + 1))
    log "DONE $name exit=$rc (log /tmp/q_$name.log)"
}

run stream_kernel python -u scripts/probe_stream_kernel.py
run bench_c4 python bench.py
run bench_fpn python bench.py --network resnet_fpn
run bench_mask python bench.py --network mask_resnet_fpn
run backbone python -u scripts/probe_backbone.py all
run fpn_gate python -m mx_rcnn_tpu.tools.integration_gate \
    --network resnet_fpn --lr 5e-4 --steps 1200 --eval_every 200 --target 0.5
log "queue complete ($fails failed)"
exit $((fails > 0))
