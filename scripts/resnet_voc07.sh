#!/usr/bin/env bash
# ResNet-101 Faster R-CNN on VOC07+12, e2e (reference: script/resnet_voc07.sh)
set -euo pipefail
python -m mx_rcnn_tpu.tools.train_end2end \
    --network resnet --dataset PascalVOC0712 \
    --pretrained "${PRETRAINED:-resnet101.pth}" \
    --compute_dtype bfloat16 \
    --epochs 10 --prefix model/resnet_voc0712 "$@"
python -m mx_rcnn_tpu.tools.test --network resnet --dataset PascalVOC0712 \
    --prefix model/resnet_voc0712
