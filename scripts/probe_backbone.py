"""Round-4 probe: where do the backbone's 58 ms go, and what helps?

Times jitted fwd and fwd+bwd of backbone(+RPN-shaped loss) at flagship
shape (b8, 608x1024, bf16, frozen conv0+stage1), then variants:
- per-stage breakdown (fwd and fwd+bwd)
- BN folded into conv (structural conv+bias twin, timing only)
- space-to-depth conv0 (7x7s2 C3 -> 4x4s1 C12 equivalent shape)
- remat (jax.checkpoint) around stages

Usage: python scripts/probe_backbone.py [variant ...]
Variants: base stages folded s2d remat all
"""
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.utils.platform import enable_compile_cache

enable_compile_cache()

B, H, W = 8, 608, 1024
DTYPE = jnp.bfloat16


def timeit(fn, *args, iters=10, warmup=2):
    for _ in range(warmup):
        r = fn(*args)
    jax.block_until_ready(r)
    # force sync through the relay with a scalar fetch
    _ = float(jnp.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    jax.block_until_ready(r)
    _ = float(jnp.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[0])
    return (time.perf_counter() - t0) / iters * 1000


def report(tag, ms):
    print(f"{tag:<40s} {ms:8.2f} ms", flush=True)


def main():
    variants = sys.argv[1:] or ["base"]
    if "all" in variants:
        variants = ["base", "stages", "folded", "s2d", "remat"]

    from mx_rcnn_tpu.models.resnet import ResNetBackbone

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.rand(B, H, W, 3).astype(np.float32))

    bb = ResNetBackbone(depth=101, dtype=DTYPE, frozen_prefix=2)
    params = bb.init(jax.random.key(0), x[:1])["params"]

    def fwd(p, xx):
        return bb.apply({"params": p}, xx).astype(jnp.float32).sum()

    def fwdbwd(p, xx):
        return jax.grad(fwd)(p, xx)

    if "base" in variants:
        report("backbone fwd", timeit(jax.jit(fwd), params, x))
        report("backbone fwd+bwd", timeit(jax.jit(fwdbwd), params, x))

    if "stages" in variants:
        # stage-by-stage: apply sub-modules through bound module access
        from mx_rcnn_tpu.models.resnet import ResNetStage
        import flax.linen as nn

        class Conv0(nn.Module):
            @nn.compact
            def __call__(self, x):
                from mx_rcnn_tpu.models.layers import FrozenBatchNorm, conv

                x = x.astype(DTYPE)
                x = conv(64, 7, 2, DTYPE, name="conv0")(x)
                x = FrozenBatchNorm(dtype=DTYPE, name="bn0")(x)
                x = nn.relu(x)
                return nn.max_pool(
                    x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1))
                )

        c0 = Conv0()
        p0 = {"conv0": params["conv0"], "bn0": params["bn0"]}
        f0 = jax.jit(lambda p, xx: c0.apply({"params": p}, xx))
        y0 = f0(p0, x)
        report("conv0+pool fwd", timeit(f0, p0, x))

        blocks = {"stage1": (64, 3, 1), "stage2": (128, 4, 2),
                  "stage3": (256, 23, 2)}
        y = y0
        for name, (filt, n, stride) in blocks.items():
            st = ResNetStage(filt, n, stride, DTYPE, name=name)
            sp = params[name]
            fs = jax.jit(lambda p, xx, st=st: st.apply({"params": p}, xx))
            gs = jax.jit(
                lambda p, xx, st=st: jax.grad(
                    lambda pp, aa: st.apply({"params": pp}, aa)
                    .astype(jnp.float32).sum()
                )(p, xx)
            )
            report(f"{name} fwd (in {y.shape[1]}x{y.shape[2]})",
                   timeit(fs, sp, y))
            report(f"{name} fwd+bwd", timeit(gs, sp, y))
            y = fs(sp, y)

    if "folded" in variants:
        # timing twin: BN affines folded into conv (conv + bias, no BN ops)
        import flax.linen as nn

        from mx_rcnn_tpu.models.layers import conv as mkconv

        class FoldedBottleneck(nn.Module):
            filters: int
            stride: int = 1

            @nn.compact
            def __call__(self, x):
                r = x
                y = mkconv(self.filters, 1, self.stride, DTYPE, name="conv1",
                           use_bias=True)(x)
                y = nn.relu(y)
                y = mkconv(self.filters, 3, 1, DTYPE, name="conv2",
                           use_bias=True)(y)
                y = nn.relu(y)
                y = mkconv(self.filters * 4, 1, 1, DTYPE, name="conv3",
                           use_bias=True)(y)
                if r.shape != y.shape:
                    r = mkconv(self.filters * 4, 1, self.stride, DTYPE,
                               name="sc", use_bias=True)(x)
                return nn.relu(y + r)

        class FoldedBackbone(nn.Module):
            @nn.compact
            def __call__(self, x):
                x = x.astype(DTYPE)
                x = mkconv(64, 7, 2, DTYPE, name="conv0", use_bias=True)(x)
                x = nn.relu(x)
                x = nn.max_pool(x, (3, 3), strides=(2, 2),
                                padding=((1, 1), (1, 1)))
                x = jax.lax.stop_gradient(x)
                for name, (f, n, s) in {
                    "stage1": (64, 3, 1), "stage2": (128, 4, 2),
                    "stage3": (256, 23, 2),
                }.items():
                    for i in range(n):
                        x = FoldedBottleneck(
                            f, s if i == 0 else 1, name=f"{name}_u{i}"
                        )(x)
                    if name == "stage1":
                        x = jax.lax.stop_gradient(x)
                return x

        fb = FoldedBackbone()
        fparams = fb.init(jax.random.key(0), x[:1])["params"]

        def ffwd(p, xx):
            return fb.apply({"params": p}, xx).astype(jnp.float32).sum()

        report("folded fwd", timeit(jax.jit(ffwd), fparams, x))
        report("folded fwd+bwd",
               timeit(jax.jit(lambda p, xx: jax.grad(ffwd)(p, xx)), fparams, x))

    if "s2d" in variants:
        # conv0 as space-to-depth + 4x4 s1 conv (shape equivalent)
        def s2d_conv0(k, xx):
            v = xx.reshape(B, H // 2, 2, W // 2, 2, 3)
            v = v.transpose(0, 1, 3, 2, 4, 5).reshape(B, H // 2, W // 2, 12)
            return jax.lax.conv_general_dilated(
                v.astype(DTYPE), k, (1, 1), [(2, 1), (2, 1)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )

        k = jnp.asarray(rng.rand(4, 4, 12, 64).astype(np.float32) * 0.01,
                        DTYPE)
        report("s2d conv0 fwd", timeit(jax.jit(s2d_conv0), k, x))

        def plain_conv0(k, xx):
            return jax.lax.conv_general_dilated(
                xx.astype(DTYPE), k, (2, 2), [(3, 3), (3, 3)],
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )

        k7 = jnp.asarray(rng.rand(7, 7, 3, 64).astype(np.float32) * 0.01,
                         DTYPE)
        report("plain conv0 fwd", timeit(jax.jit(plain_conv0), k7, x))

    if "remat" in variants:
        bb_r = ResNetBackbone(depth=101, dtype=DTYPE, frozen_prefix=2)

        def rfwd(p, xx):
            f = jax.checkpoint(
                lambda pp, aa: bb_r.apply({"params": pp}, aa)
            )
            return f(p, xx).astype(jnp.float32).sum()

        report("remat(whole) fwd+bwd",
               timeit(jax.jit(lambda p, xx: jax.grad(rfwd)(p, xx)), params, x))


if __name__ == "__main__":
    main()
