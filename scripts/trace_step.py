"""Capture + parse a device trace of the exact bench-config train step.

Round-5 roofline evidence (VERDICT r4 #5): runs the flagship bench step
(bf16, FOLD_BN, b8) under ``jax.profiler.trace``, then parses the
``.xplane.pb`` directly with TF's bundled xplane proto (the
tensorboard_plugin_profile converter in this image is protobuf-
incompatible) and prints a per-op device-time table: total ms per op
name over the captured window, grouped, sorted.  Divide by the captured
step count for per-step cost.

Usage:
  PYTHONPATH=/root/.axon_site:/root/repo \
      python scripts/trace_step.py [--steps 10] [--dir /tmp/trace_r05]
  python scripts/trace_step.py --parse-only --dir /tmp/trace_r05
"""
import argparse
import dataclasses
import glob
import os
import time
from collections import defaultdict


def capture(args):
    import jax
    import numpy as np

    from mx_rcnn_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    from __graft_entry__ import _batch, _flagship_cfg
    from mx_rcnn_tpu.core.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )
    from mx_rcnn_tpu.models import build_model

    cfg = _flagship_cfg()
    cfg = cfg.replace(
        network=dataclasses.replace(
            cfg.network, COMPUTE_DTYPE="bfloat16", FOLD_BN=True
        ),
        TRAIN=dataclasses.replace(cfg.TRAIN, BATCH_IMAGES=8),
    )
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]
    batch = _batch(cfg, 8, h, w)
    params = model.init(
        {"params": jax.random.key(0), "sampling": jax.random.key(1)},
        train=True,
        **batch,
    )["params"]
    tx = make_optimizer(cfg, lambda s: cfg.TRAIN.LEARNING_RATE)
    state = create_train_state(params, tx)
    step = make_train_step(model, tx, donate=True)
    rng = jax.random.key(0)

    # warmup/compile outside the trace window
    for _ in range(3):
        state, aux = step(state, batch, rng)
    assert np.isfinite(float(aux["loss"]))

    t0 = time.perf_counter()
    jax.profiler.start_trace(args.dir)
    for _ in range(args.steps):
        state, aux = step(state, batch, rng)
    assert np.isfinite(float(aux["loss"]))
    jax.profiler.stop_trace()
    dt = time.perf_counter() - t0
    print(f"captured {args.steps} steps in {dt:.2f}s "
          f"({8 * args.steps / dt:.1f} img/s incl. profiling overhead)",
          flush=True)


def parse(args):
    os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(
        glob.glob(os.path.join(args.dir, "**", "*.xplane.pb"),
                  recursive=True),
        key=os.path.getmtime,
    )
    if not paths:
        raise SystemExit(f"no .xplane.pb under {args.dir}")
    path = paths[-1]
    print(f"parsing {path} ({os.path.getsize(path)/1e6:.1f} MB)")
    xs = xplane_pb2.XSpace()
    with open(path, "rb") as f:
        xs.ParseFromString(f.read())

    for plane in xs.planes:
        if "TPU" not in plane.name and "tpu" not in plane.name:
            continue
        ev_meta = plane.event_metadata
        totals = defaultdict(float)  # name -> total ps
        counts = defaultdict(int)
        span_lo, span_hi = None, 0
        # aggregate the 'XLA Ops' line only: device planes can carry
        # 'XLA Modules'/'Steps' lines whose events NEST the op events —
        # summing every line would double-count busy time (ADVICE r5 #1)
        op_lines = [ln for ln in plane.lines if ln.name == "XLA Ops"]
        if not op_lines:
            print(f"(plane {plane.name}: no 'XLA Ops' line — summing "
                  f"all {len(plane.lines)} lines)")
            op_lines = list(plane.lines)
        for line in op_lines:
            for ev in line.events:
                name = ev_meta[ev.metadata_id].name
                totals[name] += ev.duration_ps
                counts[name] += 1
                lo = ev.offset_ps
                span_lo = lo if span_lo is None else min(span_lo, lo)
                span_hi = max(span_hi, lo + ev.duration_ps)
        if not totals:
            continue
        total_ms = sum(totals.values()) / 1e9
        span_ms = (span_hi - (span_lo or 0)) / 1e9
        print(f"\n== plane: {plane.name} | busy {total_ms:.1f} ms over a "
              f"{span_ms:.1f} ms span ==")
        rows = sorted(totals.items(), key=lambda kv: -kv[1])
        print(f"{'op':<72s} {'total ms':>9s} {'/step ms':>9s} "
              f"{'n':>6s} {'%':>6s}")
        for name, ps in rows[: args.top]:
            ms = ps / 1e9
            print(f"{name[:72]:<72s} {ms:9.2f} {ms/args.steps:9.3f} "
                  f"{counts[name]:6d} {100*ps/sum(totals.values()):6.1f}")
        rest = sum(ps for _, ps in rows[args.top:]) / 1e9
        rest_n = sum(counts[n] for n, _ in rows[args.top:])
        print(f"{'(everything else)':<72s} {rest:9.2f} "
              f"{rest/args.steps:9.3f} {rest_n:6d}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--dir", default="/tmp/trace_r05")
    ap.add_argument("--top", type=int, default=45)
    ap.add_argument("--parse-only", action="store_true")
    args = ap.parse_args()
    if not args.parse_only:
        capture(args)
    parse(args)


if __name__ == "__main__":
    main()
