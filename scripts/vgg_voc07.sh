#!/usr/bin/env bash
# VGG-16 Faster R-CNN on VOC07 trainval, e2e (reference: script/vgg_voc07.sh)
set -euo pipefail
python -m mx_rcnn_tpu.tools.train_end2end \
    --network vgg --dataset PascalVOC \
    --pretrained "${PRETRAINED:-vgg16.pth}" \
    --epochs 10 --prefix model/vgg_voc07 "$@"
python -m mx_rcnn_tpu.tools.test --network vgg --dataset PascalVOC \
    --prefix model/vgg_voc07
