#!/usr/bin/env bash
# Mask R-CNN ResNet-101-FPN on COCO (BASELINE config 5)
set -euo pipefail
python -m mx_rcnn_tpu.tools.train_end2end \
    --network mask_resnet_fpn --dataset coco \
    --pretrained "${PRETRAINED:-resnet101.pth}" \
    --compute_dtype bfloat16 --batch_images 2 \
    --epochs 8 --prefix model/mask_fpn_coco "$@"
python -m mx_rcnn_tpu.tools.test --network mask_resnet_fpn --dataset coco \
    --prefix model/mask_fpn_coco
