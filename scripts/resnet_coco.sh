#!/usr/bin/env bash
# ResNet-101 Faster R-CNN on COCO, e2e DP over all chips
# (reference: script/resnet_coco.sh; lr scales with the global batch)
set -euo pipefail
python -m mx_rcnn_tpu.tools.train_end2end \
    --network resnet --dataset coco \
    --pretrained "${PRETRAINED:-resnet101.pth}" \
    --compute_dtype bfloat16 --batch_images 8 \
    --epochs 8 --prefix model/resnet_coco "$@"
python -m mx_rcnn_tpu.tools.test --network resnet --dataset coco \
    --prefix model/resnet_coco
