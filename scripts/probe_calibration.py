"""Round-4 scratch probe: FrozenBN calibration effect on gate stability."""
import sys
import time

import jax
import numpy as np

from mx_rcnn_tpu.core.train import create_train_state, make_optimizer, make_train_step
from mx_rcnn_tpu.data.loader import TrainLoader
from mx_rcnn_tpu.data.synthetic import SyntheticDataset
from mx_rcnn_tpu.models import build_model
from mx_rcnn_tpu.tools.integration_gate import gate_cfg
from mx_rcnn_tpu.utils.bn_calibrate import calibrate_frozen_bn

network = sys.argv[1] if len(sys.argv) > 1 else "mask_resnet_fpn"
lr = float(sys.argv[2]) if len(sys.argv) > 2 else 2e-3
steps = int(sys.argv[3]) if len(sys.argv) > 3 else 20

cfg = gate_cfg(network)
imdb = SyntheticDataset(
    num_images=4, num_classes=4, image_size=(128, 128), max_boxes=2,
    seed=0, with_masks=cfg.network.USE_MASK,
)
roidb = imdb.gt_roidb()
model = build_model(cfg)
loader = TrainLoader(roidb, cfg, 2, shuffle=True, seed=0)
b0 = next(iter(loader))
t0 = time.time()
params = model.init(
    {"params": jax.random.key(0), "sampling": jax.random.key(1)},
    train=True, **b0,
)["params"]
print("init", round(time.time() - t0, 1), flush=True)


def probe_loss(p, tag):
    loss, aux = model.apply(
        {"params": p}, train=True, rngs={"sampling": jax.random.key(2)}, **b0
    )
    print(tag, "loss", round(float(loss), 2),
          "RPNLog", round(float(aux["RPNLogLoss"]), 2),
          "RCNNLog", round(float(aux["RCNNLogLoss"]), 2), flush=True)


probe_loss(params, "pre-cal ")
t0 = time.time()
params = calibrate_frozen_bn(model, params, b0)
print("calibrate", round(time.time() - t0, 1), flush=True)
probe_loss(params, "post-cal")

tx = make_optimizer(cfg, lambda s: lr)
state = create_train_state(params, tx)
step = make_train_step(model, tx, donate=False)
losses = []
it = iter(loader)
i = 0
while i < steps:
    try:
        batch = next(it)
    except StopIteration:
        it = iter(loader)
        continue
    state, aux = step(state, batch, jax.random.key(123))
    losses.append(round(float(aux["loss"]), 2))
    i += 1
print("losses", losses, flush=True)
