"""Round-5 probe: why does the SGD update cost ~15 ms (13% of the step)?

Methodology: probe_backbone2's in-jit chaining — N updates under ONE
lax.fori_loop dispatch, report (t(N) - t(1)) / (N - 1), so relay
dispatch latency cancels exactly.

The flagship tree has 530 leaves (103 trainable after the FIXED_PARAMS
mask, 47.1M params).  Roofline: the update reads g/p/m and writes p/m
≈ 5 x 188 MB ≈ 1.2 ms at v5e HBM bandwidth.  Candidates:

  chain    the production make_optimizer path (baseline)
  fused    handwritten one-tree_map SGD, same math
  flat     ravel-based: momentum + update math on ONE concatenated f32
           vector, sliced back out per leaf

Usage: PYTHONPATH=/root/.axon_site:/root/repo python scripts/probe_opt.py
"""
import dataclasses
import time

import flax
import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from mx_rcnn_tpu.utils.platform import enable_compile_cache

enable_compile_cache()

from __graft_entry__ import _batch, _flagship_cfg  # noqa: E402
from mx_rcnn_tpu.core.train import (  # noqa: E402
    create_train_state,
    is_frozen_path,
    make_optimizer,
)
from mx_rcnn_tpu.models import build_model  # noqa: E402

N = 9


def timeit(fn, *args, iters=6, warmup=2):
    for _ in range(warmup):
        r = fn(*args)
    _ = float(np.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    _ = float(np.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[0])
    return (time.perf_counter() - t0) / iters * 1000


def bench_chained(tag, one_step, carry0):
    """one_step: carry -> carry.  Chains n applications inside one jit."""

    def runner(n):
        @jax.jit
        def run(carry):
            return lax.fori_loop(0, n, lambda i, c: one_step(c), carry)

        return run

    t1 = timeit(runner(1), carry0)
    tn = timeit(runner(N), carry0)
    per = (tn - t1) / (N - 1)
    print(f"{tag:<32s} {per:8.2f} ms  (t1={t1:.1f} tN={tn:.1f})", flush=True)
    return per


def main():
    cfg = _flagship_cfg()
    cfg = cfg.replace(
        network=dataclasses.replace(
            cfg.network, COMPUTE_DTYPE="bfloat16", FOLD_BN=True
        ),
        TRAIN=dataclasses.replace(cfg.TRAIN, BATCH_IMAGES=8),
    )
    model = build_model(cfg)
    h, w = cfg.SHAPE_BUCKETS[0]
    batch = _batch(cfg, 8, h, w)
    params = model.init(
        {"params": jax.random.key(0), "sampling": jax.random.key(1)},
        train=True,
        **batch,
    )["params"]
    n_leaves = len(jax.tree_util.tree_leaves(params))
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    print(f"tree: {n_leaves} leaves, {n_params/1e6:.1f}M params", flush=True)

    t = cfg.TRAIN
    g0 = jax.tree_util.tree_map(lambda p: jnp.full_like(p, 1e-6), params)

    # --- 1. production chain
    tx = make_optimizer(cfg, lambda s: t.LEARNING_RATE)
    st0 = create_train_state(params, tx)

    def step_chain(st):
        updates, opt_state = tx.update(g0, st.opt_state, st.params)
        return st._replace(
            step=st.step + 1,
            params=optax.apply_updates(st.params, updates),
            opt_state=opt_state,
        )

    bench_chained("chain (production optax)", step_chain, st0)

    # --- shared freeze mask
    flat = flax.traverse_util.flatten_dict(params)
    fixed = cfg.network.FIXED_PARAMS
    gf = flax.traverse_util.flatten_dict(g0)
    train_keys = sorted(k for k in flat if not is_frozen_path(k, fixed))
    print(f"trainable: {len(train_keys)} leaves, "
          f"{sum(flat[k].size for k in train_keys)/1e6:.1f}M", flush=True)

    # --- 2. handwritten fused tree_map (one kernel per trainable leaf)
    mom0 = {k: jnp.zeros_like(flat[k]) for k in train_keys}

    def step_fused(carry):
        p, m = carry
        new_p, new_m = dict(p), dict(m)
        for k in train_keys:
            gk = jnp.clip(gf[k], -t.CLIP_GRADIENT, t.CLIP_GRADIENT)
            gk = gk + t.WD * p[k]
            mk2 = t.MOMENTUM * m[k] + gk
            new_m[k] = mk2
            new_p[k] = p[k] - t.LEARNING_RATE * mk2
        return new_p, new_m

    bench_chained("fused tree_map SGD", step_fused, (dict(flat), mom0))

    # --- 3. flat ravel-based
    sizes = [int(flat[k].size) for k in train_keys]
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    flat_p0 = jnp.concatenate([flat[k].ravel() for k in train_keys])
    flat_m0 = jnp.zeros_like(flat_p0)
    fg_const = jnp.concatenate([gf[k].ravel() for k in train_keys])

    def step_flat(carry):
        fp, fm = carry
        g = jnp.clip(fg_const, -t.CLIP_GRADIENT, t.CLIP_GRADIENT) + t.WD * fp
        fm2 = t.MOMENTUM * fm + g
        return fp - t.LEARNING_RATE * fm2, fm2

    bench_chained("flat SGD (pre-raveled grads)", step_flat,
                  (flat_p0, flat_m0))

    # flat including ravel of the incoming grad tree + slice-back for the
    # model tree — the full cost a flat optimizer would add to the step
    def step_flat_full(carry):
        fp, fm = carry
        fg = jnp.concatenate([gf[k].ravel() for k in train_keys])
        g = jnp.clip(fg, -t.CLIP_GRADIENT, t.CLIP_GRADIENT) + t.WD * fp
        fm2 = t.MOMENTUM * fm + g
        fp2 = fp - t.LEARNING_RATE * fm2
        # slice every leaf back out and fold a value in so nothing DCEs
        acc = jnp.float32(0)
        for i, k in enumerate(train_keys):
            leaf = lax.dynamic_slice(fp2, (int(offsets[i]),), (sizes[i],))
            acc = acc + leaf[0].astype(jnp.float32)
        return fp2 + 0 * acc.astype(fp2.dtype), fm2

    bench_chained("flat SGD + ravel + slice-back", step_flat_full,
                  (flat_p0, flat_m0))


if __name__ == "__main__":
    main()
