"""Label-churn ablation for the FPN/Mask gate plateau (VERDICT r4 #2).

Hypothesis under test (integration_gate.gate_cfg notes): random-init
FPN-family gates plateau at ~0.5 box / ~0.45 segm-AP50 because per-step
roi resampling on the dense stride-4 proposal pool keeps flipping
near-boundary fg/bg labels, leaving the RCNN head an irreducible CE
floor.  This probe removes the churn with machinery that already exists
and measures where the ceiling really is:

  phase 1  train the mask gate normally for --warmup steps
  dump     freeze the proposal set: generate_proposals() from the
           phase-1 RPN (the test_rpn --dump → ROIIter path)
  phase 2a CONTROL — keep training live-RPN + per-step resampling
  phase 2b FROZEN  — same steps, same init, but proposals fixed to the
           dump AND the sampling rng constant (fold_step_rng=False):
           every image's roi set and labels are identical every step

Both phases report box mAP / segm AP50 (full eval stack) and the
decoupled mask-IoU at gt boxes.  (frozen − control) at equal budget is
the fraction of the plateau the churn explains.

Usage:
  PYTHONPATH=/root/.axon_site:/root/repo python scripts/probe_mask_churn.py \
      [--warmup 600] [--steps 600] [--eval_every 200]
Prints one JSON line per phase and a final summary line.
"""

from __future__ import annotations

import argparse
import dataclasses
import json

import jax
import numpy as np
import optax


def train_steps(model, state, loader, step_fn, rng, n, eval_fn, eval_every, tag):
    done, history = 0, []
    it = iter(loader)
    while done < n:
        try:
            batch = next(it)
        except StopIteration:
            it = iter(loader)
            continue
        state, aux = step_fn(state, batch, rng)
        done += 1
        if done % eval_every == 0 or done == n:
            m = eval_fn(state)
            m["step"] = done
            history.append(m)
            print(json.dumps({"phase": tag, **m}), flush=True)
    return state, history


def main():
    from mx_rcnn_tpu.utils.platform import cli_bootstrap

    cli_bootstrap()

    ap = argparse.ArgumentParser()
    ap.add_argument("--warmup", type=int, default=600)
    ap.add_argument("--steps", type=int, default=600)
    ap.add_argument("--eval_every", type=int, default=200)
    ap.add_argument("--lr", type=float, default=5e-4)
    ap.add_argument("--num_images", type=int, default=8)
    ap.add_argument("--cpu", type=int, default=0)
    args = ap.parse_args()
    if args.cpu:
        from mx_rcnn_tpu.utils.platform import force_cpu

        force_cpu(args.cpu)

    from mx_rcnn_tpu.core.tester import Predictor, generate_proposals, pred_eval
    from mx_rcnn_tpu.core.train import (
        create_train_state,
        make_optimizer,
        make_train_step,
    )
    from mx_rcnn_tpu.data.loader import TestLoader, TrainLoader
    from mx_rcnn_tpu.data.synthetic import SyntheticDataset
    from mx_rcnn_tpu.models import build_model
    from mx_rcnn_tpu.tools.integration_gate import gate_cfg, mask_iou_eval
    from mx_rcnn_tpu.utils.bn_calibrate import calibrate_frozen_bn

    cfg = gate_cfg("mask_resnet_fpn")
    imdb = SyntheticDataset(
        num_images=args.num_images,
        num_classes=cfg.dataset.NUM_CLASSES,
        image_size=(128, 128),
        max_boxes=2,
        seed=0,
        with_masks=True,
    )
    roidb = imdb.gt_roidb()
    model = build_model(cfg)

    loader = TrainLoader(roidb, cfg, cfg.TRAIN.BATCH_IMAGES, shuffle=True, seed=0)
    batch0 = next(iter(loader))
    params = model.init(
        {"params": jax.random.key(0), "sampling": jax.random.key(1)},
        train=True,
        **batch0,
    )["params"]
    params = calibrate_frozen_bn(model, params, batch0)
    # constant lr through warmup; phases 2a/2b share one 10x-decayed lr
    tx = make_optimizer(cfg, lambda s: args.lr)
    tx2 = make_optimizer(cfg, lambda s: args.lr * 0.1)

    def eval_fn(state):
        p = jax.device_get(state.params)
        predictor = Predictor(model, p)
        _, results = pred_eval(predictor, TestLoader(roidb, cfg), imdb, cfg)
        return {
            "mAP": round(float(results["mAP"]), 4),
            "segm_AP50": round(float(results.get("segm_AP50", 0.0)), 4),
            "mask_iou": round(mask_iou_eval(model, p, cfg, roidb), 4),
        }

    rng = jax.random.key(123)
    state = create_train_state(params, tx)
    step = make_train_step(model, tx, donate=False)
    state, _ = train_steps(
        model, state, loader, step, rng, args.warmup, eval_fn,
        args.eval_every, "warmup",
    )
    warm_params = jax.device_get(state.params)

    # freeze the proposal set from the warmed-up RPN (original-image
    # coords; make_batch re-scales per bucket like any ROIIter batch)
    props = generate_proposals(
        Predictor(model, warm_params),
        TestLoader(roidb, cfg, batch_size=2),
        cfg,
    )
    for rec, dets in zip(roidb, props):
        rec["proposals"] = dets[:, :4]

    # phase 2a CONTROL: live RPN + per-step resampling, as today
    ctl_state = create_train_state(warm_params, tx2)
    ctl_state, ctl_hist = train_steps(
        model, ctl_state, loader, make_train_step(model, tx2, donate=False),
        rng, args.steps, eval_fn, args.eval_every, "control",
    )

    # phase 2b FROZEN: fixed proposals + constant sampling rng
    frozen_loader = TrainLoader(
        roidb, cfg, cfg.TRAIN.BATCH_IMAGES, shuffle=True, seed=0,
        proposal_count=cfg.TRAIN.RPN_POST_NMS_TOP_N,
    )
    frz_state = create_train_state(warm_params, tx2)
    frz_state, frz_hist = train_steps(
        model, frz_state, frozen_loader,
        make_train_step(model, tx2, donate=False, fold_step_rng=False),
        rng, args.steps, eval_fn, args.eval_every, "frozen",
    )

    best = lambda h, k: max(m[k] for m in h)  # noqa: E731
    print(json.dumps({
        "summary": "churn_ablation",
        "control": {k: best(ctl_hist, k) for k in ("mAP", "segm_AP50", "mask_iou")},
        "frozen": {k: best(frz_hist, k) for k in ("mAP", "segm_AP50", "mask_iou")},
        "churn_explains_box": round(
            best(frz_hist, "mAP") - best(ctl_hist, "mAP"), 4
        ),
        "churn_explains_segm": round(
            best(frz_hist, "segm_AP50") - best(ctl_hist, "segm_AP50"), 4
        ),
    }))


if __name__ == "__main__":
    main()
