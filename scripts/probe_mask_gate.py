"""Round-4 scratch probe: mask-gate stability with frozen prefix vs lr."""
import dataclasses
import sys
import time

import jax
import numpy as np

from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import create_train_state, make_optimizer, make_train_step
from mx_rcnn_tpu.data.loader import TrainLoader
from mx_rcnn_tpu.data.synthetic import SyntheticDataset
from mx_rcnn_tpu.models import build_model

freeze = sys.argv[1] == "freeze" if len(sys.argv) > 1 else True
lr = float(sys.argv[2]) if len(sys.argv) > 2 else 2e-3
steps = int(sys.argv[3]) if len(sys.argv) > 3 else 60

cfg = generate_config("mask_resnet_fpn", "PascalVOC")
net_over = dict(depth=50)
if not freeze:
    net_over["FIXED_PARAMS"] = ()
cfg = cfg.replace(
    SHAPE_BUCKETS=((128, 128),),
    network=dataclasses.replace(cfg.network, **net_over),
    dataset=dataclasses.replace(
        cfg.dataset, NUM_CLASSES=4, SCALES=((128, 128),), MAX_GT_BOXES=8
    ),
    TRAIN=dataclasses.replace(
        cfg.TRAIN, RPN_PRE_NMS_TOP_N=400, RPN_POST_NMS_TOP_N=64,
        BATCH_ROIS=32, RPN_BATCH_SIZE=64, BATCH_IMAGES=2, FLIP=False,
    ),
    TEST=dataclasses.replace(
        cfg.TEST, RPN_PRE_NMS_TOP_N=200, RPN_POST_NMS_TOP_N=32,
        SCORE_THRESH=0.05,
    ),
)
imdb = SyntheticDataset(
    num_images=8, num_classes=4, image_size=(128, 128), max_boxes=2,
    seed=0, with_masks=True,
)
roidb = imdb.gt_roidb()
model = build_model(cfg)
loader = TrainLoader(roidb, cfg, cfg.TRAIN.BATCH_IMAGES, shuffle=True, seed=0)
b0 = next(iter(loader))
t0 = time.time()
params = model.init(
    {"params": jax.random.key(0), "sampling": jax.random.key(1)},
    train=True, **b0,
)["params"]
print("init done", round(time.time() - t0, 1), flush=True)
tx = make_optimizer(cfg, lambda s: lr)
state = create_train_state(params, tx)
step = make_train_step(model, tx, donate=False)
rng = jax.random.key(123)
it = iter(loader)
losses = []
t0 = time.time()
i = 0
while i < steps:
    try:
        batch = next(it)
    except StopIteration:
        it = iter(loader)
        continue
    state, aux = step(state, batch, rng)
    losses.append(float(aux["loss"]))
    if i < 2 or i % 10 == 0:
        print(i, round(time.time() - t0, 1), "s | loss", round(losses[-1], 2),
              "mask", round(float(aux["MaskBCELoss"]), 3), flush=True)
    i += 1
print("last5", np.round(losses[-5:], 2), flush=True)
