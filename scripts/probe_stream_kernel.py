"""Round-4 probe: streaming ROIAlign on real TPU at FPN P2 shapes.

Validates Mosaic compilation (interpret mode cannot catch relayout
bugs) and times fwd/bwd vs the chunked-gather fallback.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.utils.platform import enable_compile_cache

enable_compile_cache()

B, H, W, C = 8, 152, 256, 256  # P2 at 608x1024, FPN_CHANNELS=256
R = 512
POOLED = (7, 7)
SCALE = 0.25


def timeit(fn, *args, iters=10):
    r = fn(*args)
    jax.block_until_ready(r)
    _ = float(jnp.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[0])
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    jax.block_until_ready(r)
    _ = float(jnp.asarray(jax.tree_util.tree_leaves(r)[0]).ravel()[0])
    return (time.perf_counter() - t0) / iters * 1000


def main():
    rng = np.random.RandomState(0)
    feat = jnp.asarray(rng.rand(B, H, W, C).astype(np.float32)).astype(
        jnp.bfloat16
    )
    rois = np.zeros((B, R, 4), np.float32)
    for b in range(B):
        x1 = rng.rand(R) * (W * 4 - 120)
        y1 = rng.rand(R) * (H * 4 - 120)
        ww = 30 + rng.rand(R) * 300
        hh = 30 + rng.rand(R) * 300
        rois[b] = np.stack(
            [x1, y1, np.minimum(x1 + ww, W * 4 - 1),
             np.minimum(y1 + hh, H * 4 - 1)], axis=1
        )
    rois = jnp.asarray(rois)
    cot = jnp.asarray(
        rng.rand(B, R, POOLED[0], POOLED[1], C).astype(np.float32)
    ).astype(jnp.bfloat16)

    from mx_rcnn_tpu.ops.pallas.roi_align_stream import roi_align_stream
    from mx_rcnn_tpu.ops.roi_align import extract_roi_features

    def stream_fwd(f, r):
        return roi_align_stream(f, r, POOLED, SCALE, 2)

    def stream_bwd(f, r):
        return jax.grad(
            lambda ff: (roi_align_stream(ff, r, POOLED, SCALE, 2)
                        .astype(jnp.float32) * cot.astype(jnp.float32)).sum()
        )(f)

    def gather_fwd(f, r):
        return jax.vmap(
            lambda ff, rr: extract_roi_features(
                ff, rr, "roi_align", POOLED, SCALE, 2
            )
        )(f, r)

    def gather_bwd(f, r):
        return jax.grad(
            lambda ff: (gather_fwd(ff, r).astype(jnp.float32)
                        * cot.astype(jnp.float32)).sum()
        )(f)

    # correctness on-device vs the gather path (bf16 tolerance)
    a = jax.jit(stream_fwd)(feat, rois)
    bref = jax.jit(gather_fwd)(feat, rois)
    err = float(jnp.abs(a.astype(jnp.float32) - bref.astype(jnp.float32)).max())
    print("fwd max|err| vs gather:", err, flush=True)
    assert err < 0.1, err

    print("stream fwd  ", round(timeit(jax.jit(stream_fwd), feat, rois), 2),
          "ms", flush=True)
    print("gather fwd  ", round(timeit(jax.jit(gather_fwd), feat, rois), 2),
          "ms", flush=True)
    print("stream f+b  ", round(timeit(jax.jit(stream_bwd), feat, rois), 2),
          "ms", flush=True)
    print("gather f+b  ", round(timeit(jax.jit(gather_bwd), feat, rois), 2),
          "ms", flush=True)


if __name__ == "__main__":
    main()
