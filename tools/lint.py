#!/usr/bin/env python
"""Repo-root graftlint entry point: ``python tools/lint.py`` (see
ANALYSIS.md).  Keeps the analyzer importable without installing the
package."""

import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from mx_rcnn_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
