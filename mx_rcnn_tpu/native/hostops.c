/* Host-side detection ops: greedy NMS and box-overlap matrix.
 *
 * Reference roles: rcnn/cython/cpu_nms.pyx (greedy O(n^2) suppression)
 * and rcnn/cython/bbox.pyx (bbox_overlaps IoU matrix) — the hot inner
 * loops of the reference's host-side eval path, shipped there as Cython
 * extensions.  Here: plain C compiled per-machine and bound via ctypes
 * (no pybind11 in this image); the TPU in-graph NMS lives in
 * ops/nms.py / ops/pallas/nms.py — this library only serves the
 * host-side per-class filtering in core/tester.py :: pred_eval and the
 * dataset/eval utilities, where the data is already on host as numpy.
 *
 * Box convention matches the framework throughout: inclusive pixel
 * coordinates, width = x2 - x1 + 1.
 */

#include <stdlib.h>

typedef struct {
    float score;
    int idx;
} score_idx;

static int cmp_score_desc(const void *a, const void *b) {
    float sa = ((const score_idx *)a)->score;
    float sb = ((const score_idx *)b)->score;
    if (sa < sb) return 1;
    if (sa > sb) return -1;
    /* tie-break on original index DESCENDING: the python oracle orders by
     * scores.argsort()[::-1], whose reversal puts equal scores in
     * reverse index order */
    return ((const score_idx *)b)->idx - ((const score_idx *)a)->idx;
}

/* dets: (n, 5) row-major [x1, y1, x2, y2, score]; keep: out buffer of
 * capacity n (kept indices, score-descending).  Returns #kept, or -1 on
 * allocation failure (callers must not conflate that with "no boxes
 * kept" — the Python binding falls back to the numpy path). */
int cpu_nms(const float *dets, int n, float thresh, int *keep) {
    if (n <= 0) return 0;
    score_idx *order = (score_idx *)malloc((size_t)n * sizeof(score_idx));
    float *areas = (float *)malloc((size_t)n * sizeof(float));
    char *dead = (char *)calloc((size_t)n, 1);
    int n_keep = 0;
    if (!order || !areas || !dead) {
        n_keep = -1;
        goto done;
    }

    for (int i = 0; i < n; i++) {
        const float *d = dets + 5 * i;
        order[i].score = d[4];
        order[i].idx = i;
        areas[i] = (d[2] - d[0] + 1.0f) * (d[3] - d[1] + 1.0f);
    }
    qsort(order, (size_t)n, sizeof(score_idx), cmp_score_desc);

    for (int oi = 0; oi < n; oi++) {
        int i = order[oi].idx;
        if (dead[i]) continue;
        keep[n_keep++] = i;
        const float *di = dets + 5 * i;
        for (int oj = oi + 1; oj < n; oj++) {
            int j = order[oj].idx;
            if (dead[j]) continue;
            const float *dj = dets + 5 * j;
            float xx1 = di[0] > dj[0] ? di[0] : dj[0];
            float yy1 = di[1] > dj[1] ? di[1] : dj[1];
            float xx2 = di[2] < dj[2] ? di[2] : dj[2];
            float yy2 = di[3] < dj[3] ? di[3] : dj[3];
            float w = xx2 - xx1 + 1.0f;
            float h = yy2 - yy1 + 1.0f;
            if (w <= 0.0f || h <= 0.0f) continue;
            float inter = w * h;
            float ovr = inter / (areas[i] + areas[j] - inter);
            if (ovr > thresh) dead[j] = 1;
        }
    }
done:
    free(order);
    free(areas);
    free(dead);
    return n_keep;
}

/* boxes: (n, 4), query: (k, 4) → out: (n, k) IoU matrix. */
void bbox_overlaps(const float *boxes, int n, const float *query, int k,
                   float *out) {
    for (int j = 0; j < k; j++) {
        const float *q = query + 4 * j;
        float qa = (q[2] - q[0] + 1.0f) * (q[3] - q[1] + 1.0f);
        for (int i = 0; i < n; i++) {
            const float *b = boxes + 4 * i;
            float xx1 = b[0] > q[0] ? b[0] : q[0];
            float yy1 = b[1] > q[1] ? b[1] : q[1];
            float xx2 = b[2] < q[2] ? b[2] : q[2];
            float yy2 = b[3] < q[3] ? b[3] : q[3];
            float w = xx2 - xx1 + 1.0f;
            float h = yy2 - yy1 + 1.0f;
            float inter = (w > 0.0f && h > 0.0f) ? w * h : 0.0f;
            float ba = (b[2] - b[0] + 1.0f) * (b[3] - b[1] + 1.0f);
            float u = ba + qa - inter;
            out[(size_t)i * k + j] = u > 0.0f ? inter / u : 0.0f;
        }
    }
}
