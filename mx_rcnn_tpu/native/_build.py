"""Shared build-and-load helper for the native C libraries.

One compile-cache-dlopen path for ``rlelib.c`` and ``hostops.c``: the
cache lives under a 0700 per-user directory (never a shared
world-writable path another user could pre-seed), and the build writes
to a unique temp name + atomic rename so concurrent processes never
dlopen a half-written file.  Returns None on any failure — callers keep
a pure-numpy fallback so nothing hard-fails without a compiler.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
from typing import Optional

logger = logging.getLogger(__name__)


def build_and_load(src_path: str, so_name: str) -> Optional[ctypes.CDLL]:
    """Compile ``src_path`` → ``~/.cache/mx_rcnn_tpu/<so_name>`` (rebuilt
    when the source is newer) and dlopen it."""
    cache_dir = os.environ.get(
        "XDG_CACHE_HOME", os.path.join(os.path.expanduser("~"), ".cache")
    )
    cache_dir = os.path.join(cache_dir, "mx_rcnn_tpu")
    so_path = os.path.join(cache_dir, so_name)
    try:
        # The ownership/mode gate must run BEFORE the freshness test:
        # chmod only inside the rebuild branch would still dlopen an
        # up-to-date pre-seeded .so without ever re-asserting the mode.
        # makedirs mode applies only on creation (and is umask-filtered),
        # so re-assert 0700 — via an O_NOFOLLOW fd so the islink/stat/
        # chmod sequence cannot be raced with a planted symlink (path
        # chmod follows symlinks and would re-mode a victim directory).
        os.makedirs(cache_dir, mode=0o700, exist_ok=True)
        dfd = os.open(
            cache_dir,
            os.O_RDONLY | getattr(os, "O_DIRECTORY", 0)
            | getattr(os, "O_NOFOLLOW", 0),
        )
        try:
            st = os.fstat(dfd)
            if hasattr(os, "getuid") and st.st_uid != os.getuid():
                raise RuntimeError(f"cache dir {cache_dir} not owned by us")
            os.fchmod(dfd, 0o700)
        finally:
            os.close(dfd)
        if (not os.path.exists(so_path)) or (
            os.path.getmtime(so_path) < os.path.getmtime(src_path)
        ):
            cc = os.environ.get("CC", "cc")
            fd, tmp = tempfile.mkstemp(suffix=".so", dir=cache_dir)
            os.close(fd)
            subprocess.run(
                [cc, "-O3", "-shared", "-fPIC", src_path, "-o", tmp],
                check=True, capture_output=True,
            )
            os.replace(tmp, so_path)
        return ctypes.CDLL(so_path)
    except Exception as e:  # no compiler / load failure → numpy fallback
        logger.warning(
            "native %s unavailable (%s); using numpy fallback", so_name, e
        )
        return None
