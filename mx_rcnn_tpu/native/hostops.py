"""Host-side detection ops: ctypes binding of hostops.c + numpy fallback.

Reference: ``rcnn/cython/cpu_nms.pyx`` and ``rcnn/cython/bbox.pyx`` — the
reference compiled these host inner loops to Cython extensions because
the pure-python versions dominated eval time at COCO scale (5k images ×
80 classes of per-class NMS).  Same stance here with plain C (no
pybind11 in this image; ctypes like ``native/rle.py``), and a numpy
fallback so nothing hard-fails without a compiler.

The TPU in-graph NMS (``ops/nms.py``, ``ops/pallas/nms.py``) is the
training/inference path; these functions only serve code that already
holds numpy on the host (eval, demo, dataset utilities).
"""

from __future__ import annotations

import ctypes
import logging
import os
from typing import List, Optional

import numpy as np

from mx_rcnn_tpu.native._build import build_and_load

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "hostops.c")
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    lib = build_and_load(_SRC, "hostops.so")
    if lib is None:
        return None
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    lib.cpu_nms.restype = ctypes.c_int
    lib.cpu_nms.argtypes = [f32p, ctypes.c_int, ctypes.c_float, i32p]
    lib.bbox_overlaps.restype = None
    lib.bbox_overlaps.argtypes = [f32p, ctypes.c_int, f32p, ctypes.c_int, f32p]
    return lib


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if not _TRIED:
        _LIB = _build_and_load()
        _TRIED = True
    return _LIB


def nms_host(dets: np.ndarray, thresh: float) -> List[int]:
    """Greedy NMS on (N, 5) [x1, y1, x2, y2, score] → kept indices in
    score-descending order.  Exact twin of ``ops.nms.nms_numpy``
    (including its reversed-argsort tie order), ~50× faster at COCO
    per-class sizes through the C path."""
    n = int(dets.shape[0])
    if n == 0:
        return []
    lib = _lib()
    if lib is None:
        from mx_rcnn_tpu.ops.nms import nms_numpy

        return nms_numpy(dets, thresh)
    dets32 = np.ascontiguousarray(dets[:, :5], dtype=np.float32)
    keep = np.empty(n, np.int32)
    n_keep = lib.cpu_nms(dets32, n, float(thresh), keep)
    if n_keep < 0:  # allocation failure inside the C path
        from mx_rcnn_tpu.ops.nms import nms_numpy

        return nms_numpy(dets, thresh)
    return keep[:n_keep].tolist()


def bbox_overlaps_host(boxes: np.ndarray, query: np.ndarray) -> np.ndarray:
    """(N, 4) × (K, 4) → (N, K) IoU matrix (inclusive-pixel convention),
    C-accelerated with a numpy fallback."""
    n, k = int(boxes.shape[0]), int(query.shape[0])
    out = np.zeros((n, k), np.float32)
    if n == 0 or k == 0:
        return out
    lib = _lib()
    if lib is None:
        bx = boxes.astype(np.float32)
        qx = query.astype(np.float32)
        ba = (bx[:, 2] - bx[:, 0] + 1) * (bx[:, 3] - bx[:, 1] + 1)
        qa = (qx[:, 2] - qx[:, 0] + 1) * (qx[:, 3] - qx[:, 1] + 1)
        iw = np.minimum(bx[:, None, 2], qx[None, :, 2]) - np.maximum(
            bx[:, None, 0], qx[None, :, 0]
        ) + 1
        ih = np.minimum(bx[:, None, 3], qx[None, :, 3]) - np.maximum(
            bx[:, None, 1], qx[None, :, 1]
        ) + 1
        inter = np.maximum(iw, 0) * np.maximum(ih, 0)
        union = ba[:, None] + qa[None, :] - inter
        return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0).astype(
            np.float32
        )
    b32 = np.ascontiguousarray(boxes[:, :4], dtype=np.float32)
    q32 = np.ascontiguousarray(query[:, :4], dtype=np.float32)
    lib.bbox_overlaps(b32, n, q32, k, out)
    return out
