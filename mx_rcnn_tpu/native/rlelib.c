/* Run-length-encoded binary mask operations.
 *
 * TPU-native rebuild of the reference's vendored COCO mask C API
 * (rcnn/pycocotools/maskApi.c, SURVEY N5) - reimplemented from the
 * published RLE format description: masks are stored column-major as
 * alternating run lengths starting with a zero-run.  This library backs
 * the host-side segm evaluation path; mask *training* targets are
 * produced in-graph (ops/mask_targets.py) and never touch this code.
 *
 * Built by utils/native_build.py with the image's cc toolchain and bound
 * via ctypes (no pybind11 in this environment).
 */

#include <stdlib.h>
#include <string.h>

typedef unsigned int uint;
typedef unsigned char byte;

/* encode one h*w column-major binary mask into run lengths.
 * cnts must hold h*w+1 entries; returns the run count. */
int rle_encode(const byte *m, int h, int w, uint *cnts) {
    long n = (long)h * w;
    int k = 0;
    byte prev = 0;
    uint run = 0;
    for (long i = 0; i < n; i++) {
        byte v = m[i] ? 1 : 0;
        if (v != prev) {
            cnts[k++] = run;
            run = 0;
            prev = v;
        }
        run++;
    }
    cnts[k++] = run;
    return k;
}

/* decode run lengths into an h*w column-major binary mask. */
void rle_decode(const uint *cnts, int k, byte *m) {
    byte v = 0;
    long pos = 0;
    for (int i = 0; i < k; i++) {
        memset(m + pos, v, cnts[i]);
        pos += cnts[i];
        v = !v;
    }
}

/* total foreground area of an RLE. */
double rle_area(const uint *cnts, int k) {
    double a = 0;
    for (int i = 1; i < k; i += 2) a += cnts[i];
    return a;
}

/* run-length sweep intersection area of two RLEs. */
static double rle_inter(const uint *a, int ka, const uint *b, int kb) {
    double inter = 0;
    long ca = a[0], cb = b[0];
    int ia = 0, ib = 0;       /* index of the CURRENT run in each mask */
    byte va = 0, vb = 0;      /* value of the current run */
    while (ia < ka && ib < kb) {
        long step = ca < cb ? ca : cb;
        if (va && vb) inter += step;
        ca -= step;
        cb -= step;
        if (ca == 0 && ++ia < ka) { ca = a[ia]; va = !va; }
        if (cb == 0 && ++ib < kb) { cb = b[ib]; vb = !vb; }
    }
    return inter;
}

/* IoU matrix between n dt and m gt RLEs (all padded into one buffer of
 * stride max_k with per-mask run counts).  iscrowd gt: inter/dt_area. */
void rle_iou(const uint *dt, const int *dt_k, int n,
             const uint *gt, const int *gt_k, int m,
             const byte *iscrowd, int max_k, double *out) {
    for (int i = 0; i < n; i++) {
        const uint *a = dt + (long)i * max_k;
        double area_a = rle_area(a, dt_k[i]);
        for (int j = 0; j < m; j++) {
            const uint *b = gt + (long)j * max_k;
            double inter = rle_inter(a, dt_k[i], b, gt_k[j]);
            double u;
            if (iscrowd[j]) {
                u = area_a;
            } else {
                u = area_a + rle_area(b, gt_k[j]) - inter;
            }
            out[(long)i * m + j] = u > 0 ? inter / u : 0.0;
        }
    }
}

/* union-merge of n RLEs (same h*w) into out counts; returns run count. */
int rle_merge(const uint *rles, const int *ks, int n, int max_k,
              long hw, uint *out) {
    /* simple approach: decode-or into a scratch mask, re-encode */
    byte *scratch = (byte *)calloc(hw, 1);
    byte *tmp = (byte *)malloc(hw);
    if (!scratch || !tmp) { free(scratch); free(tmp); return -1; }
    for (int i = 0; i < n; i++) {
        rle_decode(rles + (long)i * max_k, ks[i], tmp);
        for (long p = 0; p < hw; p++) scratch[p] |= tmp[p];
    }
    int k = rle_encode(scratch, 1, (int)hw, out);
    free(scratch);
    free(tmp);
    return k;
}

/* rasterize a closed polygon (xy pairs, image h*w) into a column-major
 * mask via even-odd scanline fill on pixel centers; OR-ed into m. */
void poly_fill(const double *xy, int npts, int h, int w, byte *m) {
    if (npts < 3) return;
    for (int col = 0; col < w; col++) {
        double px = col + 0.5;
        /* gather crossings of the vertical line x=px */
        double ys[4096];
        int nys = 0;
        for (int i = 0; i < npts && nys < 4096; i++) {
            int j = (i + 1) % npts;
            double x0 = xy[2 * i], y0 = xy[2 * i + 1];
            double x1 = xy[2 * j], y1 = xy[2 * j + 1];
            if ((x0 <= px && x1 > px) || (x1 <= px && x0 > px)) {
                double t = (px - x0) / (x1 - x0);
                ys[nys++] = y0 + t * (y1 - y0);
            }
        }
        /* sort crossings (insertion: counts are tiny) */
        for (int i = 1; i < nys; i++) {
            double v = ys[i];
            int j = i - 1;
            while (j >= 0 && ys[j] > v) { ys[j + 1] = ys[j]; j--; }
            ys[j + 1] = v;
        }
        /* fill rows whose pixel center lies between alternate pairs */
        for (int i = 0; i + 1 < nys; i += 2) {
            int r0 = (int)(ys[i]);          /* first r with r+0.5 >= ys[i] */
            if (r0 + 0.5 < ys[i]) r0++;
            int r1 = (int)(ys[i + 1]);      /* last r with r+0.5 <= ys[i+1] */
            if (r1 + 0.5 > ys[i + 1]) r1--;
            if (r0 < 0) r0 = 0;
            if (r1 >= h) r1 = h - 1;
            for (int r = r0; r <= r1; r++)
                m[(long)col * h + r] = 1;
        }
    }
}
