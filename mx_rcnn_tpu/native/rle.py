"""RLE mask API: ctypes binding of the native library + numpy fallback.

Reference: ``rcnn/pycocotools/{maskApi.c,_mask.pyx}`` (SURVEY N5) — the
reference shipped a Cython extension; here the C core (``rlelib.c``) is
compiled once per machine with the system compiler and loaded via
ctypes (this image has no pybind11), with a pure-numpy fallback when no
compiler is available so eval never hard-fails.

Format: column-major alternating run lengths starting with a zero-run —
the uncompressed pycocotools convention.  ``encode``/``decode`` use the
{"size": [h, w], "counts": [..]} dict shape throughout.
"""

from __future__ import annotations

import ctypes
import logging
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "rlelib.c")
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    from mx_rcnn_tpu.native._build import build_and_load

    lib = build_and_load(_SRC, "rlelib.so")
    if lib is None:
        return None
    u32p = np.ctypeslib.ndpointer(np.uint32)
    i32p = np.ctypeslib.ndpointer(np.int32)
    u8p = np.ctypeslib.ndpointer(np.uint8)
    f64p = np.ctypeslib.ndpointer(np.float64)
    lib.rle_encode.restype = ctypes.c_int
    lib.rle_encode.argtypes = [u8p, ctypes.c_int, ctypes.c_int, u32p]
    lib.rle_decode.restype = None
    lib.rle_decode.argtypes = [u32p, ctypes.c_int, u8p]
    lib.rle_area.restype = ctypes.c_double
    lib.rle_area.argtypes = [u32p, ctypes.c_int]
    lib.rle_iou.restype = None
    lib.rle_iou.argtypes = [u32p, i32p, ctypes.c_int, u32p, i32p,
                            ctypes.c_int, u8p, ctypes.c_int, f64p]
    lib.rle_merge.restype = ctypes.c_int
    lib.rle_merge.argtypes = [u32p, i32p, ctypes.c_int, ctypes.c_int,
                              ctypes.c_long, u32p]
    lib.poly_fill.restype = None
    lib.poly_fill.argtypes = [f64p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
                              u8p]
    return lib


def _lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if not _TRIED:
        _LIB = _build_and_load()
        _TRIED = True
    return _LIB


# ------------------------------------------------------------------ public
def counts_from_string(s: str) -> List[int]:
    """Decode the COCO compressed-RLE counts string (LEB128-style 6-bit
    chunks, deltas from counts[m-2]) into plain run lengths — real COCO
    jsons store crowd masks this way."""
    cnts: List[int] = []
    i = 0
    while i < len(s):
        x = 0
        k = 0
        more = True
        while more:
            c = ord(s[i]) - 48
            x |= (c & 0x1F) << (5 * k)
            more = bool(c & 0x20)
            i += 1
            k += 1
            if not more and (c & 0x10):
                x |= -1 << (5 * k)
        if len(cnts) > 2:
            x += cnts[-2]
        cnts.append(x)
    return cnts


def ensure_list_counts(r: Dict) -> Dict:
    """Normalize an RLE dict to plain list-of-int counts (decoding the
    compressed string form if needed)."""
    if isinstance(r.get("counts"), (bytes, str)):
        s = r["counts"]
        if isinstance(s, bytes):
            s = s.decode("ascii")
        return {"size": list(r["size"]), "counts": counts_from_string(s)}
    return r


def encode(mask: np.ndarray) -> Dict:
    """(h, w) binary mask → RLE dict."""
    h, w = mask.shape
    flat = np.asfortranarray(mask.astype(np.uint8)).reshape(-1, order="F")
    flat = np.ascontiguousarray(flat)
    lib = _lib()
    if lib is not None:
        cnts = np.empty(h * w + 1, np.uint32)
        k = lib.rle_encode(flat, h, w, cnts)
        counts = cnts[:k].tolist()
    else:
        change = np.flatnonzero(np.diff(flat)) + 1
        runs = np.diff(np.concatenate([[0], change, [flat.size]]))
        counts = runs.tolist()
        if flat[0]:  # counts must start with a (possibly empty) zero-run
            counts = [0] + counts
    return {"size": [h, w], "counts": [int(c) for c in counts]}


def decode(rle: Dict) -> np.ndarray:
    """RLE dict → (h, w) uint8 mask."""
    h, w = rle["size"]
    cnts = np.asarray(rle["counts"], np.uint32)
    lib = _lib()
    if lib is not None:
        out = np.empty(h * w, np.uint8)
        lib.rle_decode(np.ascontiguousarray(cnts), len(cnts), out)
    else:
        vals = np.arange(len(cnts)) % 2
        out = np.repeat(vals.astype(np.uint8), cnts)
    return out.reshape((h, w), order="F")


def area(rle: Dict) -> float:
    cnts = np.asarray(rle["counts"], np.uint32)
    lib = _lib()
    if lib is not None:
        return float(lib.rle_area(np.ascontiguousarray(cnts), len(cnts)))
    return float(cnts[1::2].sum())


def _pack(rles: Sequence[Dict]):
    ks = np.asarray([len(r["counts"]) for r in rles], np.int32)
    max_k = int(ks.max()) if len(ks) else 1
    buf = np.zeros((len(rles), max_k), np.uint32)
    for i, r in enumerate(rles):
        buf[i, : ks[i]] = r["counts"]
    return np.ascontiguousarray(buf), ks, max_k


def iou(dt: Sequence[Dict], gt: Sequence[Dict], iscrowd: Sequence[int]) -> np.ndarray:
    """(n_dt, n_gt) mask IoU; crowd gt → intersection / dt area."""
    if not dt or not gt:
        return np.zeros((len(dt), len(gt)))
    lib = _lib()
    crowd = np.asarray(iscrowd, np.uint8)
    if lib is not None:
        dbuf, dk, mk1 = _pack(dt)
        gbuf, gk, mk2 = _pack(gt)
        mk = max(mk1, mk2)
        if mk1 < mk:
            dbuf = np.pad(dbuf, ((0, 0), (0, mk - mk1)))
        if mk2 < mk:
            gbuf = np.pad(gbuf, ((0, 0), (0, mk - mk2)))
        out = np.zeros((len(dt), len(gt)), np.float64)
        lib.rle_iou(np.ascontiguousarray(dbuf), dk, len(dt),
                    np.ascontiguousarray(gbuf), gk, len(gt),
                    crowd, mk, out)
        return out
    # numpy fallback: decode and compare
    dm = np.stack([decode(r).reshape(-1) for r in dt]).astype(np.float64)
    gm = np.stack([decode(r).reshape(-1) for r in gt]).astype(np.float64)
    inter = dm @ gm.T
    da = dm.sum(1)[:, None]
    ga = gm.sum(1)[None, :]
    union = np.where(crowd[None, :], da, da + ga - inter)
    return np.where(union > 0, inter / np.maximum(union, 1e-12), 0.0)


def merge(rles: Sequence[Dict]) -> Dict:
    """Union-merge RLEs of one image."""
    assert rles, "merge of zero masks"
    h, w = rles[0]["size"]
    lib = _lib()
    if lib is not None:
        buf, ks, mk = _pack(rles)
        out = np.empty(h * w + 1, np.uint32)
        k = lib.rle_merge(buf, ks, len(rles), mk, h * w, out)
        assert k > 0, "rle_merge allocation failure"
        return {"size": [h, w], "counts": out[:k].tolist()}
    m = np.zeros((h, w), np.uint8)
    for r in rles:
        m |= decode(r)
    return encode(m)


def from_polygons(polys: Sequence[Sequence[float]], h: int, w: int) -> Dict:
    """COCO polygon list ([[x1, y1, x2, y2, ...], ...]) → merged RLE."""
    m = np.zeros(h * w, np.uint8)
    lib = _lib()
    for poly in polys:
        xy = np.ascontiguousarray(np.asarray(poly, np.float64))
        if lib is not None:
            lib.poly_fill(xy, len(xy) // 2, h, w, m)
        else:
            m |= _poly_fill_np(xy.reshape(-1, 2), h, w).reshape(-1, order="F")
    return encode(m.reshape((h, w), order="F"))


def _poly_fill_np(pts: np.ndarray, h: int, w: int) -> np.ndarray:
    """Even-odd scanline fill on pixel centers (numpy fallback)."""
    m = np.zeros((h, w), np.uint8)
    n = len(pts)
    for col in range(w):
        px = col + 0.5
        ys = []
        for i in range(n):
            x0, y0 = pts[i]
            x1, y1 = pts[(i + 1) % n]
            if (x0 <= px < x1) or (x1 <= px < x0):
                t = (px - x0) / (x1 - x0)
                ys.append(y0 + t * (y1 - y0))
        ys.sort()
        for a, b in zip(ys[0::2], ys[1::2]):
            r0 = max(int(np.ceil(a - 0.5)), 0)
            r1 = min(int(np.floor(b - 0.5)), h - 1)
            if r1 >= r0:  # crossings fully off-image must fill nothing
                m[r0 : r1 + 1, col] = 1
    return m
