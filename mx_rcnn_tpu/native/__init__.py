"""Native (C) runtime components, built on demand with the image's cc
toolchain and bound via ctypes.  Currently: the RLE mask library
(``rlelib.c``) replacing the reference's vendored ``maskApi.c``."""
