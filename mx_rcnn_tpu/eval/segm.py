"""Mask post-processing: paste per-roi mask logits into image-space RLEs.

Reference: the descendant Mask R-CNN eval pipelines over
``rcnn/pycocotools`` — per detection, the S×S mask probability grid is
resized to the box extent, thresholded, pasted into the full image, and
RLE-encoded for segm COCOeval (``eval/coco_eval.py`` with
``iou_type='segm'``).
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def paste_mask(
    mask: np.ndarray, box: np.ndarray, h: int, w: int, thresh: float = 0.5
) -> np.ndarray:
    """(S, S) probability grid + [x1, y1, x2, y2] image box → (h, w) u8.

    Bilinear resize to the box's pixel extent (+1 convention), threshold,
    paste at the clipped location.
    """
    import cv2

    x1 = int(np.floor(box[0]))
    y1 = int(np.floor(box[1]))
    x2 = int(np.ceil(box[2]))
    y2 = int(np.ceil(box[3]))
    bw = max(x2 - x1 + 1, 1)
    bh = max(y2 - y1 + 1, 1)
    resized = cv2.resize(mask.astype(np.float32), (bw, bh))
    binary = (resized >= thresh).astype(np.uint8)
    out = np.zeros((h, w), np.uint8)
    ox1, oy1 = max(x1, 0), max(y1, 0)
    ox2, oy2 = min(x2, w - 1), min(y2, h - 1)
    if ox2 >= ox1 and oy2 >= oy1:
        out[oy1 : oy2 + 1, ox1 : ox2 + 1] = binary[
            oy1 - y1 : oy2 - y1 + 1, ox1 - x1 : ox2 - x1 + 1
        ]
    return out


def mask_to_rle(mask_prob: np.ndarray, box: np.ndarray, h: int, w: int,
                thresh: float = 0.5) -> Dict:
    """Probability grid + box → image-space RLE dict."""
    from mx_rcnn_tpu.native import rle

    return rle.encode(paste_mask(mask_prob, box, h, w, thresh))


def rles_for_detections(
    mask_probs: np.ndarray, dets: np.ndarray, h: int, w: int,
    thresh: float = 0.5,
) -> list:
    """One class's (n, S, S) probability grids + (n, 5) detections →
    list of image-space RLEs.  The unit of completion-pool work in
    ``pred_eval``: paste + threshold + RLE-encode dominates segm eval
    host cost, and this whole list is independent per (image, class)."""
    return [
        mask_to_rle(p, d[:4], h, w, thresh)
        for p, d in zip(mask_probs, dets)
    ]
