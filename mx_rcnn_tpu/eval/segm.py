"""Mask post-processing: paste per-roi mask logits into image-space RLEs.

Reference: the descendant Mask R-CNN eval pipelines over
``rcnn/pycocotools`` — per detection, the S×S mask probability grid is
resized to the box extent, thresholded, pasted into the full image, and
RLE-encoded for segm COCOeval (``eval/coco_eval.py`` with
``iou_type='segm'``).
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def paste_mask(
    mask: np.ndarray, box: np.ndarray, h: int, w: int, thresh: float = 0.5
) -> np.ndarray:
    """(S, S) probability grid + [x1, y1, x2, y2] image box → (h, w) u8.

    Bilinear resize to the box's pixel extent (+1 convention), threshold,
    paste at the clipped location.
    """
    import cv2

    x1 = int(np.floor(box[0]))
    y1 = int(np.floor(box[1]))
    x2 = int(np.ceil(box[2]))
    y2 = int(np.ceil(box[3]))
    bw = max(x2 - x1 + 1, 1)
    bh = max(y2 - y1 + 1, 1)
    resized = cv2.resize(mask.astype(np.float32), (bw, bh))
    binary = (resized >= thresh).astype(np.uint8)
    out = np.zeros((h, w), np.uint8)
    ox1, oy1 = max(x1, 0), max(y1, 0)
    ox2, oy2 = min(x2, w - 1), min(y2, h - 1)
    if ox2 >= ox1 and oy2 >= oy1:
        out[oy1 : oy2 + 1, ox1 : ox2 + 1] = binary[
            oy1 - y1 : oy2 - y1 + 1, ox1 - x1 : ox2 - x1 + 1
        ]
    return out


def paste_mask_canvas(
    logits: np.ndarray, box: np.ndarray, hc: int, wc: int
) -> np.ndarray:
    """(S, S) LOGIT grid + CANVAS-space box → (hc, wc) u8 binary mask.

    Numpy mirror of the device canvas paste
    (``ops/postprocess.py :: make_test_postprocess(paste=True)``) —
    every arithmetic step matches op-for-op: clip box to the canvas,
    floor/ceil footprint (+1 convention), cv2-style half-pixel source
    mapping, then a bilinear blend in int32 FIXED POINT (logits
    quantized to 8 fractional bits, weights to 7) thresholded at logit
    0 (= probability 0.5).  Integer arithmetic is exact on every
    backend, so this function and the device canvas are bitwise equal
    by construction — the streaming bench's RLE byte-identity bar.
    """
    s = logits.shape[0]
    x1 = np.clip(np.float32(box[0]), 0.0, wc - 1.0)
    y1 = np.clip(np.float32(box[1]), 0.0, hc - 1.0)
    x2 = np.clip(np.float32(box[2]), 0.0, wc - 1.0)
    y2 = np.clip(np.float32(box[3]), 0.0, hc - 1.0)
    x1i = int(np.floor(x1))
    y1i = int(np.floor(y1))
    x2i = int(np.ceil(x2))
    y2i = int(np.ceil(y2))
    bw = max(x2i - x1i + 1, 1)
    bh = max(y2i - y1i + 1, 1)
    q = np.round(
        np.clip(logits.astype(np.float32), -60.0, 60.0) * np.float32(256.0)
    ).astype(np.int32)

    def axis(n):
        t = (np.arange(n, dtype=np.float32) + np.float32(0.5)) \
            * np.float32(s) / np.float32(n) - np.float32(0.5)
        sc = np.clip(t, 0.0, s - 1.0).astype(np.float32)
        i0 = np.floor(sc).astype(np.int32)
        i1 = np.minimum(i0 + 1, s - 1)
        w = np.round(
            (sc - i0.astype(np.float32)) * np.float32(128.0)
        ).astype(np.int32)
        return i0, i1, w

    x0, x1b, wx = axis(bw)
    y0, y1b, wy = axis(bh)
    val = (128 - wy)[:, None] * (
        (128 - wx)[None, :] * q[y0][:, x0] + wx[None, :] * q[y0][:, x1b]
    ) + wy[:, None] * (
        (128 - wx)[None, :] * q[y1b][:, x0] + wx[None, :] * q[y1b][:, x1b]
    )
    out = np.zeros((hc, wc), np.uint8)
    out[y1i : y2i + 1, x1i : x2i + 1] = (val >= 0).astype(np.uint8)
    return out


def canvas_rles(
    grids: np.ndarray, dets: np.ndarray, scale: float, hc: int, wc: int
) -> list:
    """One class's (n, S, S) LOGIT grids + (n, 5) ORIGINAL-coordinate
    detections → list of CANVAS-space RLEs (the host half of the
    streaming mask contract when the device canvas is off).  Boxes map
    to canvas coordinates by the image scale, exactly as on device."""
    from mx_rcnn_tpu.native import rle

    return [
        rle.encode(
            paste_mask_canvas(
                g, np.asarray(d[:4], np.float32) * np.float32(scale), hc, wc
            )
        )
        for g, d in zip(grids, dets)
    ]


def mask_to_rle(mask_prob: np.ndarray, box: np.ndarray, h: int, w: int,
                thresh: float = 0.5) -> Dict:
    """Probability grid + box → image-space RLE dict."""
    from mx_rcnn_tpu.native import rle

    return rle.encode(paste_mask(mask_prob, box, h, w, thresh))


def rles_for_detections(
    mask_probs: np.ndarray, dets: np.ndarray, h: int, w: int,
    thresh: float = 0.5,
) -> list:
    """One class's (n, S, S) probability grids + (n, 5) detections →
    list of image-space RLEs.  The unit of completion-pool work in
    ``pred_eval``: paste + threshold + RLE-encode dominates segm eval
    host cost, and this whole list is independent per (image, class)."""
    return [
        mask_to_rle(p, d[:4], h, w, thresh)
        for p, d in zip(mask_probs, dets)
    ]
