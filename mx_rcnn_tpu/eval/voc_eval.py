"""Pascal VOC detection AP.

Reference: ``rcnn/dataset/pascal_voc_eval.py :: voc_eval`` — per-class PR
curve with greedy one-to-one matching at IoU ≥ 0.5, difficult-box
handling (matches to difficult gt count as neither TP nor FP), and both
the 2007 11-point metric and the later continuous integral metric.  The
math is identical; the interface is in-memory (dets/annots dicts) instead
of files on disk.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np


def voc_ap(rec: np.ndarray, prec: np.ndarray, use_07_metric: bool = False) -> float:
    if use_07_metric:
        ap = 0.0
        for t in np.arange(0.0, 1.1, 0.1):
            p = float(np.max(prec[rec >= t])) if np.any(rec >= t) else 0.0
            ap += p / 11.0
        return ap
    mrec = np.concatenate(([0.0], rec, [1.0]))
    mpre = np.concatenate(([0.0], prec, [0.0]))
    for i in range(mpre.size - 1, 0, -1):
        mpre[i - 1] = max(mpre[i - 1], mpre[i])
    i = np.where(mrec[1:] != mrec[:-1])[0]
    return float(np.sum((mrec[i + 1] - mrec[i]) * mpre[i + 1]))


def voc_eval(
    dets_by_img: Dict[str, np.ndarray],
    annots: Dict[str, Dict],
    cls_idx: int,
    ovthresh: float = 0.5,
    use_07_metric: bool = False,
) -> Tuple[np.ndarray, np.ndarray, float]:
    """dets_by_img[img] = (n, 5) [x1, y1, x2, y2, score] for one class;
    annots[img] = {boxes, gt_classes, difficult}.  → (recall, precision, AP).
    """
    # per-image gt for this class
    class_gt = {}
    npos = 0
    for img, ann in annots.items():
        mask = ann["gt_classes"] == cls_idx
        boxes = ann["boxes"][mask]
        difficult = (
            ann["difficult"][mask]
            if "difficult" in ann
            else np.zeros(mask.sum(), bool)
        )
        class_gt[img] = {
            "boxes": boxes,
            "difficult": difficult,
            "matched": np.zeros(len(boxes), bool),
        }
        npos += int((~difficult).sum())

    # flatten detections, sort by confidence
    all_imgs, all_dets = [], []
    for img, d in dets_by_img.items():
        d = np.asarray(d).reshape(-1, 5)
        all_imgs.extend([img] * len(d))
        all_dets.append(d)
    if not all_dets or sum(len(d) for d in all_dets) == 0:
        return np.array([]), np.array([]), 0.0
    all_dets = np.concatenate(all_dets, axis=0)
    order = np.argsort(-all_dets[:, 4])
    all_dets = all_dets[order]
    all_imgs = [all_imgs[i] for i in order]

    nd = len(all_dets)
    tp = np.zeros(nd)
    fp = np.zeros(nd)
    for i in range(nd):
        gt = class_gt.get(all_imgs[i])
        bb = all_dets[i, :4]
        ovmax, jmax = -np.inf, -1
        if gt is not None and len(gt["boxes"]):
            g = gt["boxes"]
            ixmin = np.maximum(g[:, 0], bb[0])
            iymin = np.maximum(g[:, 1], bb[1])
            ixmax = np.minimum(g[:, 2], bb[2])
            iymax = np.minimum(g[:, 3], bb[3])
            iw = np.maximum(ixmax - ixmin + 1.0, 0.0)
            ih = np.maximum(iymax - iymin + 1.0, 0.0)
            inter = iw * ih
            union = (
                (bb[2] - bb[0] + 1.0) * (bb[3] - bb[1] + 1.0)
                + (g[:, 2] - g[:, 0] + 1.0) * (g[:, 3] - g[:, 1] + 1.0)
                - inter
            )
            overlaps = inter / union
            jmax = int(np.argmax(overlaps))
            ovmax = overlaps[jmax]
        if ovmax > ovthresh:
            if gt["difficult"][jmax]:
                continue  # neither tp nor fp
            if not gt["matched"][jmax]:
                tp[i] = 1.0
                gt["matched"][jmax] = True
            else:
                fp[i] = 1.0
        else:
            fp[i] = 1.0

    fp = np.cumsum(fp)
    tp = np.cumsum(tp)
    rec = tp / max(float(npos), np.finfo(np.float64).eps)
    prec = tp / np.maximum(tp + fp, np.finfo(np.float64).eps)
    return rec, prec, voc_ap(rec, prec, use_07_metric)
