"""Proposal recall evaluation.

Reference: the recall printout of ``rcnn/tools/test_rpn.py`` — after
generating proposals, report the fraction of gt boxes covered by at least
one proposal at IoU ≥ thresh, for several proposal budgets.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from mx_rcnn_tpu.utils.bbox_stats import np_overlaps


def proposal_recall(
    proposals: List[np.ndarray],
    roidb: List[Dict],
    top_ns: Sequence[int] = (300, 1000, 2000),
    iou_thresh: float = 0.5,
) -> Dict[str, float]:
    """recall@N over a dataset.

    ``proposals[i]`` = (P_i, 5) [x1, y1, x2, y2, score] in original image
    coordinates, score-descending (the ``generate_proposals`` dump
    format); ``roidb[i]['boxes']`` = gt boxes.
    """
    assert len(proposals) == len(roidb)
    out = {}
    for n in top_ns:
        covered = total = 0
        for props, rec in zip(proposals, roidb):
            gts = np.asarray(rec["boxes"], np.float32)
            if len(gts) == 0:
                continue
            total += len(gts)
            boxes = np.asarray(props, np.float32)[:n, :4]
            if len(boxes) == 0:
                continue
            ov = np_overlaps(gts, boxes)                 # (G, P)
            covered += int((ov.max(axis=1) >= iou_thresh).sum())
        out[f"recall@{n}"] = covered / max(total, 1)
    return out
