"""COCO bbox evaluation protocol in pure numpy.

Reference: the vendored ``rcnn/pycocotools/cocoeval.py :: COCOeval``
(evaluate/accumulate/summarize) — reimplemented from the published
protocol because this environment has no pycocotools wheel and the
vendored copy may not be copied (SURVEY N5).  Faithful to the protocol:

- 10 IoU thresholds 0.50:0.05:0.95, 101 recall points,
- area ranges all/small/medium/large, maxDets 1/10/100,
- greedy score-descending matching, crowd gts as ignore regions with
  intersection-over-det-area IoU, unmatched dets on ignored gt ignored,
- 12 summary statistics in the standard order.

This module evaluates bbox detections; segm evaluation lives in
:func:`coco_eval` via ``iou_type='segm'`` once mask support lands.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

IOU_THRS = np.linspace(0.5, 0.95, 10)
REC_THRS = np.linspace(0.0, 1.0, 101)
AREA_RNGS = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}
MAX_DETS = (1, 10, 100)


def _iou_xywh(dets: np.ndarray, gts: np.ndarray, iscrowd: np.ndarray) -> np.ndarray:
    """IoU between (D, 4) and (G, 4) xywh boxes; crowd gt → inter/det_area."""
    if len(dets) == 0 or len(gts) == 0:
        return np.zeros((len(dets), len(gts)))
    dx1, dy1 = dets[:, 0], dets[:, 1]
    dx2, dy2 = dets[:, 0] + dets[:, 2], dets[:, 1] + dets[:, 3]
    gx1, gy1 = gts[:, 0], gts[:, 1]
    gx2, gy2 = gts[:, 0] + gts[:, 2], gts[:, 1] + gts[:, 3]
    iw = np.minimum(dx2[:, None], gx2[None, :]) - np.maximum(dx1[:, None], gx1[None, :])
    ih = np.minimum(dy2[:, None], gy2[None, :]) - np.maximum(dy1[:, None], gy1[None, :])
    inter = np.clip(iw, 0, None) * np.clip(ih, 0, None)
    d_area = (dets[:, 2] * dets[:, 3])[:, None]
    g_area = (gts[:, 2] * gts[:, 3])[None, :]
    union = np.where(iscrowd[None, :], d_area, d_area + g_area - inter)
    return inter / np.maximum(union, 1e-12)


class COCOEvalBbox:
    def __init__(self, dataset: Dict, results: List[Dict], iou_type: str = "bbox"):
        """``dataset``: the loaded instances json (images/annotations/
        categories); ``results``: list of {image_id, category_id, bbox
        (xywh), score} detection dicts — for ``iou_type='segm'`` each
        result additionally carries ``segmentation`` (RLE dict) and gt
        annotations carry polygon or RLE ``segmentation`` (matched with
        the native RLE library, ``mx_rcnn_tpu/native/rle.py``)."""
        assert iou_type in ("bbox", "segm")
        self.iou_type = iou_type
        self._img_hw = {
            im["id"]: (im.get("height", 0), im.get("width", 0))
            for im in dataset["images"]
        }
        self.img_ids = sorted({im["id"] for im in dataset["images"]})
        self.cat_ids = sorted({c["id"] for c in dataset["categories"]})
        self._gts: Dict = {(i, c): [] for i in self.img_ids for c in self.cat_ids}
        for ann in dataset["annotations"]:
            key = (ann["image_id"], ann["category_id"])
            if key in self._gts:
                self._gts[key].append(ann)
        self._dts: Dict = {(i, c): [] for i in self.img_ids for c in self.cat_ids}
        for det in results:
            key = (det["image_id"], det["category_id"])
            if key in self._dts:
                self._dts[key].append(det)

    def _evaluate_img(self, img_id, cat_id, area_rng, max_det):
        """Match one (image, category) pair under one area range.

        Greedy score-descending matching; the threshold axis (T=10) runs
        vectorized — only the det axis is a Python loop (the greedy
        sequential dependency).  Truncation to ``max_det`` happens here
        for the standalone call; ``_accumulate`` instead slices cached
        max-budget results (valid because the match of det *i* never
        depends on later dets).
        """
        out = self._match_pair(img_id, cat_id, area_rng)
        if out is None or max_det >= out["dt_matches"].shape[1]:
            return out
        return {
            "dt_matches": out["dt_matches"][:, :max_det],
            "dt_scores": out["dt_scores"][:max_det],
            "dt_ignore": out["dt_ignore"][:, :max_det],
            "gt_ignore": out["gt_ignore"],
            "num_gt": out["num_gt"],
        }

    def _match_pair(self, img_id, cat_id, area_rng):
        gts = self._gts[(img_id, cat_id)]
        dts = sorted(self._dts[(img_id, cat_id)], key=lambda d: -d["score"])
        dts = dts[: max(MAX_DETS)]
        if not gts and not dts:
            return None

        g_boxes = np.array([g["bbox"] for g in gts]).reshape(-1, 4)
        g_crowd = np.array([g.get("iscrowd", 0) for g in gts], bool)
        g_area = np.array(
            [g.get("area", g["bbox"][2] * g["bbox"][3]) for g in gts]
        )
        g_ignore = g_crowd | (g_area < area_rng[0]) | (g_area > area_rng[1])
        # sort gts: non-ignored first (protocol requirement)
        g_order = np.argsort(g_ignore, kind="stable")
        g_boxes, g_crowd, g_ignore = (
            g_boxes[g_order], g_crowd[g_order], g_ignore[g_order]
        )

        d_boxes = np.array([d["bbox"] for d in dts]).reshape(-1, 4)
        d_scores = np.array([d["score"] for d in dts])
        if self.iou_type == "segm":
            ious, d_area = self._segm_iou(img_id, cat_id, dts, gts)
            ious = ious[:, g_order]
        else:
            ious = _iou_xywh(d_boxes, g_boxes, g_crowd)
            d_area = d_boxes[:, 2] * d_boxes[:, 3]

        T, D, G = len(IOU_THRS), len(dts), len(gts)
        thr = np.minimum(IOU_THRS, 1 - 1e-10)                       # (T,)
        dt_m = -np.ones((T, D), int)
        dt_ig = np.zeros((T, D), bool)
        if G:
            avail = np.ones((T, G), bool)
            ni = ~g_ignore[None, :]                                 # (1, G)
            for di in range(D):
                r = ious[di]                                        # (G,)
                cand = avail & (r[None, :] >= thr[:, None])         # (T, G)
                # a non-ignored match (any iou) outranks every ignored gt;
                # within a class, max iou wins — LAST gt on ties, matching
                # the pycocotools loop's >= update (argmax on the reversed
                # axis picks the last maximum)
                r_ni = np.where(cand & ni, r[None, :], -1.0)
                r_ig = np.where(cand & ~ni, r[None, :], -1.0)
                has_ni = r_ni.max(axis=1) > -1.0
                has_ig = r_ig.max(axis=1) > -1.0
                last_ni = G - 1 - r_ni[:, ::-1].argmax(axis=1)
                last_ig = G - 1 - r_ig[:, ::-1].argmax(axis=1)
                best = np.where(
                    has_ni, last_ni, np.where(has_ig, last_ig, -1)
                )                                                   # (T,)
                matched = best >= 0
                dt_m[:, di] = best
                dt_ig[matched, di] = g_ignore[best[matched]]
                # matched non-crowd gts leave the pool (crowds absorb many)
                take = matched & ~g_crowd[np.clip(best, 0, G - 1)]
                avail[take, best[take]] = False
        # unmatched dets outside the area range are ignored
        d_out = (d_area < area_rng[0]) | (d_area > area_rng[1])
        dt_ig |= (dt_m == -1) & d_out[None, :]
        return {
            "dt_matches": dt_m,
            "dt_scores": d_scores,
            "dt_ignore": dt_ig,
            "gt_ignore": g_ignore,
            "num_gt": int((~g_ignore).sum()),
        }

    def _gt_rle(self, ann, img_id):
        """gt segmentation → RLE dict (polygons rasterized via the native
        library, compressed crowd strings decoded; cached on the ann)."""
        if "_rle" not in ann:
            from mx_rcnn_tpu.native import rle as rle_api

            seg = ann["segmentation"]
            h, w = self._img_hw[img_id]
            if isinstance(seg, dict):
                ann["_rle"] = rle_api.ensure_list_counts(seg)
            else:
                ann["_rle"] = rle_api.from_polygons(seg, h, w)
        return ann["_rle"]

    def _segm_iou(self, img_id, cat_id, dts, gts):
        """(ious (D, G) in ORIGINAL gt order, det mask areas (D,)) —
        area-range independent, cached per (img, cat) since _match_pair
        runs once per area range."""
        if not hasattr(self, "_segm_cache"):
            self._segm_cache = {}
        key = (img_id, cat_id)
        if key not in self._segm_cache:
            from mx_rcnn_tpu.native import rle as rle_api

            crowd = [int(g.get("iscrowd", 0)) for g in gts]
            gt_rles = [self._gt_rle(g, img_id) for g in gts]
            dt_rles = [d["segmentation"] for d in dts]
            ious = rle_api.iou(dt_rles, gt_rles, crowd)
            d_area = np.array([rle_api.area(r) for r in dt_rles])
            self._segm_cache[key] = (ious, d_area)
        return self._segm_cache[key]

    def _pair_evals(self, area_rng_key):
        """Cached per-(img, cat) match results at the max det budget for
        one area range — shared by every maxDet setting."""
        if not hasattr(self, "_pair_cache"):
            self._pair_cache = {}
        if area_rng_key not in self._pair_cache:
            area_rng = AREA_RNGS[area_rng_key]
            by_cat = {c: [] for c in self.cat_ids}
            for (img_id, cat_id), dts in self._dts.items():
                if not dts and not self._gts[(img_id, cat_id)]:
                    continue
                e = self._match_pair(img_id, cat_id, area_rng)
                if e is not None:
                    by_cat[cat_id].append(e)
            self._pair_cache[area_rng_key] = by_cat
        return self._pair_cache[area_rng_key]

    def _accumulate(self, area_rng_key, max_det):
        """→ precision (T, R, K), recall (T, K) over categories K."""
        T, R, K = len(IOU_THRS), len(REC_THRS), len(self.cat_ids)
        precision = -np.ones((T, R, K))
        recall = -np.ones((T, K))
        by_cat = self._pair_evals(area_rng_key)
        for ki, cat_id in enumerate(self.cat_ids):
            evals = by_cat[cat_id]
            if not evals:
                continue
            # top-max_det slice per image, then merge score-descending
            scores = np.concatenate([e["dt_scores"][:max_det] for e in evals])
            order = np.argsort(-scores, kind="mergesort")
            dt_m = np.concatenate(
                [e["dt_matches"][:, :max_det] for e in evals], axis=1
            )[:, order]
            dt_ig = np.concatenate(
                [e["dt_ignore"][:, :max_det] for e in evals], axis=1
            )[:, order]
            npig = sum(e["num_gt"] for e in evals)
            if npig == 0:
                continue
            tps = (dt_m >= 0) & ~dt_ig
            fps = (dt_m == -1) & ~dt_ig
            tp_sum = np.cumsum(tps, axis=1).astype(float)
            fp_sum = np.cumsum(fps, axis=1).astype(float)
            nd = tp_sum.shape[1]
            if nd == 0:
                recall[:, ki] = 0.0
                precision[:, :, ki] = 0.0
                continue
            rc = tp_sum / npig                                       # (T, nd)
            pr = tp_sum / np.maximum(
                tp_sum + fp_sum, np.finfo(np.float64).eps
            )
            recall[:, ki] = rc[:, -1]
            # precision envelope (monotone decreasing), vectorized over T
            env = np.maximum.accumulate(pr[:, ::-1], axis=1)[:, ::-1]
            for ti in range(T):
                inds = np.searchsorted(rc[ti], REC_THRS, side="left")
                valid = inds < nd
                q = np.zeros(R)
                q[valid] = env[ti, inds[valid]]
                precision[ti, :, ki] = q
        return precision, recall

    @staticmethod
    def _mean_valid(x: np.ndarray) -> float:
        valid = x[x > -1]
        return float(np.mean(valid)) if valid.size else -1.0

    def evaluate(self, verbose: bool = True) -> Dict[str, float]:
        """Run the full protocol; returns the 12 standard stats."""
        cache: Dict = {}

        def acc(name: str, md: int):
            key = (name, md)
            if key not in cache:
                cache[key] = self._accumulate(name, md)
            return cache[key]

        p_all, r_all = acc("all", 100)
        stats = {
            "AP": self._mean_valid(p_all),
            "AP50": self._mean_valid(p_all[np.isclose(IOU_THRS, 0.5)]),
            "AP75": self._mean_valid(p_all[np.isclose(IOU_THRS, 0.75)]),
        }
        for name in ("small", "medium", "large"):
            stats[f"AP_{name}"] = self._mean_valid(acc(name, 100)[0])
        for md in MAX_DETS:
            stats[f"AR_{md}"] = self._mean_valid(acc("all", md)[1])
        for name in ("small", "medium", "large"):
            stats[f"AR_{name}"] = self._mean_valid(acc(name, 100)[1])
        if verbose:
            for k, v in stats.items():
                print(f" {k:<10s} = {v:.3f}")
        return stats
