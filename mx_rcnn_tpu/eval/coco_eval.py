"""COCO bbox evaluation protocol in pure numpy.

Reference: the vendored ``rcnn/pycocotools/cocoeval.py :: COCOeval``
(evaluate/accumulate/summarize) — reimplemented from the published
protocol because this environment has no pycocotools wheel and the
vendored copy may not be copied (SURVEY N5).  Faithful to the protocol:

- 10 IoU thresholds 0.50:0.05:0.95, 101 recall points,
- area ranges all/small/medium/large, maxDets 1/10/100,
- greedy score-descending matching, crowd gts as ignore regions with
  intersection-over-det-area IoU, unmatched dets on ignored gt ignored,
- 12 summary statistics in the standard order.

Mask (segm) evaluation is out of scope here; the native RLE mask API
lives in ``mx_rcnn_tpu/native`` for the Mask R-CNN extension.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

IOU_THRS = np.linspace(0.5, 0.95, 10)
REC_THRS = np.linspace(0.0, 1.0, 101)
AREA_RNGS = {
    "all": (0.0, 1e10),
    "small": (0.0, 32.0**2),
    "medium": (32.0**2, 96.0**2),
    "large": (96.0**2, 1e10),
}
MAX_DETS = (1, 10, 100)


def _iou_xywh(dets: np.ndarray, gts: np.ndarray, iscrowd: np.ndarray) -> np.ndarray:
    """IoU between (D, 4) and (G, 4) xywh boxes; crowd gt → inter/det_area."""
    if len(dets) == 0 or len(gts) == 0:
        return np.zeros((len(dets), len(gts)))
    dx1, dy1 = dets[:, 0], dets[:, 1]
    dx2, dy2 = dets[:, 0] + dets[:, 2], dets[:, 1] + dets[:, 3]
    gx1, gy1 = gts[:, 0], gts[:, 1]
    gx2, gy2 = gts[:, 0] + gts[:, 2], gts[:, 1] + gts[:, 3]
    iw = np.minimum(dx2[:, None], gx2[None, :]) - np.maximum(dx1[:, None], gx1[None, :])
    ih = np.minimum(dy2[:, None], gy2[None, :]) - np.maximum(dy1[:, None], gy1[None, :])
    inter = np.clip(iw, 0, None) * np.clip(ih, 0, None)
    d_area = (dets[:, 2] * dets[:, 3])[:, None]
    g_area = (gts[:, 2] * gts[:, 3])[None, :]
    union = np.where(iscrowd[None, :], d_area, d_area + g_area - inter)
    return inter / np.maximum(union, 1e-12)


class COCOEvalBbox:
    def __init__(self, dataset: Dict, results: List[Dict]):
        """``dataset``: the loaded instances json (images/annotations/
        categories); ``results``: list of {image_id, category_id, bbox
        (xywh), score} detection dicts."""
        self.img_ids = sorted({im["id"] for im in dataset["images"]})
        self.cat_ids = sorted({c["id"] for c in dataset["categories"]})
        self._gts: Dict = {(i, c): [] for i in self.img_ids for c in self.cat_ids}
        for ann in dataset["annotations"]:
            key = (ann["image_id"], ann["category_id"])
            if key in self._gts:
                self._gts[key].append(ann)
        self._dts: Dict = {(i, c): [] for i in self.img_ids for c in self.cat_ids}
        for det in results:
            key = (det["image_id"], det["category_id"])
            if key in self._dts:
                self._dts[key].append(det)

    def _evaluate_img(self, img_id, cat_id, area_rng, max_det):
        gts = self._gts[(img_id, cat_id)]
        dts = sorted(
            self._dts[(img_id, cat_id)], key=lambda d: -d["score"]
        )[:max_det]
        if not gts and not dts:
            return None

        g_boxes = np.array([g["bbox"] for g in gts]).reshape(-1, 4)
        g_crowd = np.array([g.get("iscrowd", 0) for g in gts], bool)
        g_area = np.array(
            [g.get("area", g["bbox"][2] * g["bbox"][3]) for g in gts]
        )
        g_ignore = g_crowd | (g_area < area_rng[0]) | (g_area > area_rng[1])
        # sort gts: non-ignored first (protocol requirement)
        g_order = np.argsort(g_ignore, kind="stable")
        g_boxes, g_crowd, g_ignore = g_boxes[g_order], g_crowd[g_order], g_ignore[g_order]

        d_boxes = np.array([d["bbox"] for d in dts]).reshape(-1, 4)
        d_scores = np.array([d["score"] for d in dts])
        ious = _iou_xywh(d_boxes, g_boxes, g_crowd)

        T, D, G = len(IOU_THRS), len(dts), len(gts)
        dt_m = -np.ones((T, D), int)
        gt_m = -np.ones((T, G), int)
        dt_ig = np.zeros((T, D), bool)
        for ti, t in enumerate(IOU_THRS):
            for di in range(D):
                best_iou = min(t, 1 - 1e-10)
                best_g = -1
                for gi in range(G):
                    if gt_m[ti, gi] >= 0 and not g_crowd[gi]:
                        continue  # taken (crowd can absorb many dets)
                    # stop at ignored gts once a non-ignored match exists
                    if best_g >= 0 and not g_ignore[best_g] and g_ignore[gi]:
                        break
                    if ious[di, gi] < best_iou:
                        continue
                    best_iou = ious[di, gi]
                    best_g = gi
                if best_g >= 0:
                    dt_m[ti, di] = best_g
                    gt_m[ti, best_g] = di
                    dt_ig[ti, di] = g_ignore[best_g]
        # unmatched dets outside the area range are ignored
        d_area = d_boxes[:, 2] * d_boxes[:, 3]
        d_out = (d_area < area_rng[0]) | (d_area > area_rng[1])
        dt_ig |= (dt_m == -1) & d_out[None, :]
        return {
            "dt_matches": dt_m,
            "dt_scores": d_scores,
            "dt_ignore": dt_ig,
            "gt_ignore": g_ignore,
            "num_gt": int((~g_ignore).sum()),
        }

    def _accumulate(self, area_rng, max_det):
        """→ precision (T, R, K), recall (T, K) over categories K."""
        T, R, K = len(IOU_THRS), len(REC_THRS), len(self.cat_ids)
        precision = -np.ones((T, R, K))
        recall = -np.ones((T, K))
        for ki, cat_id in enumerate(self.cat_ids):
            evals = [
                self._evaluate_img(i, cat_id, area_rng, max_det)
                for i in self.img_ids
            ]
            evals = [e for e in evals if e is not None]
            if not evals:
                continue
            scores = np.concatenate([e["dt_scores"] for e in evals])
            order = np.argsort(-scores, kind="mergesort")
            dt_m = np.concatenate([e["dt_matches"] for e in evals], axis=1)[:, order]
            dt_ig = np.concatenate([e["dt_ignore"] for e in evals], axis=1)[:, order]
            npig = sum(e["num_gt"] for e in evals)
            if npig == 0:
                continue
            tps = (dt_m >= 0) & ~dt_ig
            fps = (dt_m == -1) & ~dt_ig
            tp_sum = np.cumsum(tps, axis=1).astype(float)
            fp_sum = np.cumsum(fps, axis=1).astype(float)
            for ti in range(T):
                tp, fp = tp_sum[ti], fp_sum[ti]
                nd = len(tp)
                rc = tp / npig
                pr = tp / np.maximum(tp + fp, np.finfo(np.float64).eps)
                recall[ti, ki] = rc[-1] if nd else 0.0
                # precision envelope (monotone decreasing)
                q = np.zeros(R)
                pr = pr.tolist()
                for i in range(nd - 1, 0, -1):
                    if pr[i] > pr[i - 1]:
                        pr[i - 1] = pr[i]
                inds = np.searchsorted(rc, REC_THRS, side="left")
                for ri, pi in enumerate(inds):
                    if pi < nd:
                        q[ri] = pr[pi]
                precision[ti, :, ki] = q
        return precision, recall

    @staticmethod
    def _mean_valid(x: np.ndarray) -> float:
        valid = x[x > -1]
        return float(np.mean(valid)) if valid.size else -1.0

    def evaluate(self, verbose: bool = True) -> Dict[str, float]:
        """Run the full protocol; returns the 12 standard stats."""
        cache: Dict = {}

        def acc(name: str, md: int):
            key = (name, md)
            if key not in cache:
                cache[key] = self._accumulate(AREA_RNGS[name], md)
            return cache[key]

        p_all, r_all = acc("all", 100)
        stats = {
            "AP": self._mean_valid(p_all),
            "AP50": self._mean_valid(p_all[np.isclose(IOU_THRS, 0.5)]),
            "AP75": self._mean_valid(p_all[np.isclose(IOU_THRS, 0.75)]),
        }
        for name in ("small", "medium", "large"):
            stats[f"AP_{name}"] = self._mean_valid(acc(name, 100)[0])
        for md in MAX_DETS:
            stats[f"AR_{md}"] = self._mean_valid(acc("all", md)[1])
        for name in ("small", "medium", "large"):
            stats[f"AR_{name}"] = self._mean_valid(acc(name, 100)[1])
        if verbose:
            for k, v in stats.items():
                print(f" {k:<10s} = {v:.3f}")
        return stats
