from mx_rcnn_tpu.parallel import distributed
from mx_rcnn_tpu.parallel.elastic import (
    ElasticContext,
    ElasticLoop,
    MeshMonitor,
    NoSurvivorsError,
    RegrowPolicy,
    make_elastic_factory,
)
from mx_rcnn_tpu.parallel.mesh import (
    make_mesh,
    make_parallel_train_step,
    replica_slices,
    replicate,
    shard_batch,
    take_replica_rows,
)
