from mx_rcnn_tpu.parallel import distributed
from mx_rcnn_tpu.parallel.mesh import (
    make_mesh,
    make_parallel_train_step,
    replicate,
    shard_batch,
)
