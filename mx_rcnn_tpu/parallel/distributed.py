"""Multi-host (multi-process) distributed training support.

Reference: MXNet KVStore's ``dist_sync`` mode — the parameter-server path
``train_end2end.py`` never used (it hardcodes ``kvstore='device'``,
SURVEY §3.3 "Multi-node distributed: capability exists but unused").
This module is where the rebuild *exceeds* the reference: the same
``shard_map`` train step scales from one chip to a multi-host pod because
the mesh may span processes — XLA lowers the gradient ``psum`` to ICI
all-reduces within a slice and DCN collectives across slices; there is no
parameter server, no NCCL/MPI plumbing, no rank-conditional code in the
train loop.

The host-side contract for multi-process JAX:

- every process calls :func:`initialize` first (GRPC coordinator), then
  ``jax.devices()`` returns the *global* device list and the mesh built
  over it spans the pod;
- every process runs the SAME program over the same global batch
  *specification*, but only materialises the shard of the data its local
  devices own — :func:`globalize_batch` assembles a global
  ``jax.Array`` view from process-local numpy shards
  (``jax.make_array_from_process_local_data``);
- :func:`process_slice` tells the data loader which slice of the global
  batch this process must produce.  Determinism: every process computes
  the identical global shuffle plan (seeded per epoch) and takes its
  slice, so the global batch order is independent of process count — the
  same invariant the single-chip/DP-equivalence tests assert for devices.

On a single process all of this degrades to plain ``device_put`` with no
coordinator, so the e2e trainer uses one code path everywhere.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


def initialize(
    coordinator: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join the multi-process JAX runtime (no-op when single-process).

    ``coordinator`` is ``host:port`` of process 0.  On TPU pods the three
    arguments are usually discovered from the environment and may all be
    None; on CPU/GPU fleets pass them explicitly.  Must be called before
    the first ``jax.devices()``.
    """
    if coordinator is None and num_processes is None:
        if process_id is not None:
            raise ValueError(
                "distributed: --dist_procid given without "
                "--dist_coordinator/--dist_nprocs — refusing to train as "
                "an independent single process"
            )
        return  # single-process run
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "distributed: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )


def process_slice(global_batch: int) -> slice:
    """The [start, stop) rows of the global batch this process loads.

    The global batch is laid out contiguously by process: with P
    processes each owning L = global/P addressable rows, process p loads
    rows [p*L, (p+1)*L).  Matches the row→device placement
    :func:`globalize_batch` produces.
    """
    pc, pi = jax.process_count(), jax.process_index()
    if global_batch % pc:
        raise ValueError(f"global batch {global_batch} not divisible by {pc} processes")
    local = global_batch // pc
    return slice(pi * local, (pi + 1) * local)


def globalize_batch(
    local_batch: Dict[str, np.ndarray], mesh: Mesh
) -> Dict[str, jax.Array]:
    """Per-process numpy shards → one global jax.Array batch on the mesh.

    Each array's leading axis is the *local* batch; the result's leading
    axis is the global batch, sharded over the mesh's 'data' axis.  On a
    single process this is exactly ``device_put`` with a P('data') spec.
    """
    sharding = NamedSharding(mesh, P("data"))
    return {
        k: jax.make_array_from_process_local_data(sharding, v)
        for k, v in local_batch.items()
    }


def agree_on_down(down, n_replicas: int) -> frozenset:
    """Cross-process UNION of locally-suspected dead replica ordinals.

    A device fault is observed by the process hosting the replica (or by
    whoever's collective timed out first); every process must shrink to
    the IDENTICAL survivor set or the rebuilt meshes disagree and the
    next collective deadlocks — the same reasoning as
    ``train_end2end.py``'s preemption stop vote, but for membership.
    Single-process (the CPU chaos matrix) this is the identity; multi-
    host it is one blocking allgather of an ``n_replicas``-bit mask,
    paid only on the shrink path.
    """
    down = frozenset(int(d) for d in down)
    if jax.process_count() == 1:
        return down
    from jax.experimental import multihost_utils

    mask = np.zeros((n_replicas,), np.int32)
    for d in down:
        mask[d] = 1
    votes = np.asarray(multihost_utils.process_allgather(mask))
    return frozenset(int(i) for i in np.nonzero(votes.any(axis=0))[0])


def local_global_batch_sizes(per_chip: int) -> tuple[int, int]:
    """(local, global) batch sizes for ``per_chip`` images per device."""
    return (
        per_chip * jax.local_device_count(),
        per_chip * jax.device_count(),
    )
