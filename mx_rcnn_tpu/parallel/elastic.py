"""Elastic data parallelism: survive device loss mid-run.

The reference trainer (``train_end2end.py``'s ``Module.fit`` over
KVStore('device')) died whole-job on any device error; our static
``parallel/mesh.py`` mesh kept that failure mode — one lost or wedged
replica aborts the run and throws away the surviving chips.  This module
makes the mesh a *membership*:

- :class:`MeshMonitor` — replica bookkeeping.  Detection is the per-step
  heartbeat the DP step already is: every train step ends in a pmean
  over ``'data'``, so a dead or wedged replica surfaces as the dispatch
  raising (injected deterministically via ``MX_RCNN_FAULTS``
  ``device_lost@STEP.REPLICA`` / ``device_wedge@STEP.REPLICA:DUR``, or a
  real XlaRuntimeError).  Health probes for regrow come from
  ``faults.down_replicas`` — a pure function of (spec, step), never wall
  clock — and regrow is gated behind the PR 6 circuit-breaker idiom:
  cooldown counted in checkpoint boundaries, doubled per flap, capped.
- :class:`ElasticLoop` — wraps the PR 4 :class:`PipelinedLoop`.  On a
  device fault it drains nothing from the broken mesh: the in-flight
  window's device aux handles are discarded, an **emergency committed
  checkpoint** is written from the loop's host-side window anchor, the
  execution context is rebuilt over the survivors (pmean renormalizes
  itself — ``make_train_step`` divides grads by a runtime
  ``psum(1, 'data')``), state is re-placed from the anchor, and the
  window **including the poison step** is replayed at the same stream
  coordinates.  Replay is bit-identical to a fresh run started on the
  small mesh at the anchor (the PR 2/PR 4 byte-equivalence bar): the
  sampling rng folds ``state.step``, the anchor restores it, and
  :func:`~mx_rcnn_tpu.parallel.mesh.take_replica_rows` keeps the batch a
  pure function of the survivor COUNT.  At most the K-step pipeline
  window is re-executed; no step is lost.
- :func:`make_elastic_factory` — builds the real shard_map substrate for
  an active-ordinal set; tests drive :class:`ElasticLoop` with cheap
  numpy factories through the same interface.

Multi-host, the survivor set is agreed through
``distributed.agree_on_down`` (one allgather on the shrink path) so
every process rebuilds the identical mesh.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from mx_rcnn_tpu.core.pipeline import PipelinedLoop
from mx_rcnn_tpu.core.resilience import (
    DivergencePolicy,
    StepWatchdog,
    host_copy,
)
from mx_rcnn_tpu.parallel import distributed
from mx_rcnn_tpu.utils import faults

logger = logging.getLogger(__name__)


class NoSurvivorsError(RuntimeError):
    """A device fault left no replicas to shrink onto (or the victim
    could not be identified) — the run cannot continue degraded."""


@dataclass(frozen=True)
class ElasticContext:
    """Execution substrate for one active-replica set: the jitted step,
    state placement (replicate onto the survivor mesh), and batch
    placement (truncate the base-sized global batch, then shard)."""

    active: Tuple[int, ...]
    step_fn: Callable
    place_state: Callable[[Any], Any]
    place_batch: Callable[[Any], Any]
    mesh: Any = None


def classify_device_fault(exc: BaseException):
    """``(kind, victim_ordinal_or_None)`` when ``exc`` is a device-level
    failure the elastic loop should absorb, else None (the exception is
    not ours — divergence, watchdog, injection of another phase — and
    must propagate to the resilience layer that owns it)."""
    if isinstance(exc, faults.InjectedDeviceFault):
        return exc.fault_kind, exc.replica
    if type(exc).__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
        msg = str(exc).lower()
        if any(
            t in msg
            for t in ("device", "halted", "ici", "dcn", "collective",
                      "slice health", "preempted worker")
        ):
            return "device_lost", None
    return None


@dataclass(frozen=True)
class RegrowPolicy:
    """Circuit-breaker gating for mesh re-expansion.

    Counted in checkpoint BOUNDARIES — deterministic run coordinates,
    the elastic twin of ``serve/replica.py``'s wall-clock breaker
    (backoff doubled per trip inside a flap window, capped).  A "flap"
    is a shrink that lands within ``flap_window`` boundaries of a
    regrow: the replica came back, rejoined, and died again — each flap
    doubles the boundary cooldown up to ``max_backoff``.
    """

    cooldown: int = 1
    flap_window: int = 8
    max_backoff: int = 8


class MeshMonitor:
    """Replica membership, health probing, and the regrow breaker.

    ``probe_fn(step) -> iterable of down ordinals`` defaults to the
    deterministic ``faults.down_replicas`` injector probe; a real
    deployment can wire a hardware health source with the same shape.
    """

    def __init__(
        self,
        n_replicas: int,
        policy: Optional[RegrowPolicy] = None,
        probe_fn: Optional[Callable[[int], Sequence[int]]] = None,
    ):
        self.base = tuple(range(int(n_replicas)))
        self.active = self.base
        self.policy = policy or RegrowPolicy()
        self._probe = probe_fn or (lambda step: faults.down_replicas(step))
        self.transitions: List[Dict[str, Any]] = []
        self.boundaries = 0
        self.shrinks = 0
        self.regrows = 0
        self.flaps = 0
        self._last_shrink_boundary: Optional[int] = None
        self._last_regrow_boundary: Optional[int] = None
        self._last_flap_boundary: Optional[int] = None
        self._backoff = self.policy.cooldown

    @property
    def degraded(self) -> bool:
        return len(self.active) < len(self.base)

    def probe_down(self, step: int) -> frozenset:
        """Base ordinals reported down at stream position ``step``."""
        return frozenset(int(r) for r in self._probe(step))

    def note_shrink(self, step: int, lost, kind: str) -> None:
        survivors = tuple(o for o in self.active if o not in lost)
        if not survivors:
            raise NoSurvivorsError(
                f"step {step}: {sorted(lost)} lost and no replicas remain"
            )
        self.active = survivors
        self.shrinks += 1
        if (
            self._last_regrow_boundary is not None
            and self.boundaries - self._last_regrow_boundary
            <= self.policy.flap_window
        ):
            # the replica flapped: rejoined at a boundary, died again —
            # double the boundary cooldown before the next attempt
            self.flaps += 1
            self._last_flap_boundary = self.boundaries
            self._backoff = min(self._backoff * 2, self.policy.max_backoff)
        self._last_shrink_boundary = self.boundaries
        self.transitions.append(
            {"step": step, "event": "shrink", "kind": kind,
             "lost": sorted(int(o) for o in lost),
             "active": list(self.active)}
        )

    def note_boundary(self) -> None:
        self.boundaries += 1
        if (
            self._last_flap_boundary is not None
            and self.boundaries - self._last_flap_boundary
            > self.policy.flap_window
        ):
            # flap history aged out: the breaker closes back down
            self._last_flap_boundary = None
            self._backoff = self.policy.cooldown

    def want_regrow(self, step: int) -> Optional[Tuple[int, ...]]:
        """The target active set when a regrow is allowed at this
        boundary, else None (still down, or the breaker is open)."""
        missing = set(self.base) - set(self.active)
        if not missing:
            return None
        back = missing - self.probe_down(step)
        if not back:
            return None
        if (
            self._last_shrink_boundary is not None
            and self.boundaries - self._last_shrink_boundary < self._backoff
        ):
            return None
        return tuple(sorted(set(self.active) | back))

    def note_regrow(self, step: int, active: Tuple[int, ...]) -> None:
        self.active = tuple(sorted(active))
        self.regrows += 1
        self._last_regrow_boundary = self.boundaries
        self.transitions.append(
            {"step": step, "event": "regrow", "active": list(self.active)}
        )

    def stats(self) -> Dict[str, Any]:
        return {
            "base_replicas": len(self.base),
            "active_replicas": len(self.active),
            "shrinks": self.shrinks,
            "regrows": self.regrows,
            "flaps": self.flaps,
            "boundaries": self.boundaries,
            "transitions": list(self.transitions),
        }


class ElasticLoop:
    """A :class:`PipelinedLoop` that survives device loss.

    ``factory(active) -> ElasticContext`` builds the execution substrate
    for an active-ordinal tuple; the loop rebuilds it on every
    membership change.  ``checkpoint_fn(host_state, stream_step, meta)``
    (optional) writes the emergency committed checkpoint on shrink and
    returns its path.

    Recovery contract: a fault at stream step S inside a window anchored
    at W costs re-executing steps [W, S] on the survivor mesh — with the
    default ``aux_interval=1`` the anchor IS the poison step, so exactly
    one step replays.  The replay is bit-identical to a fresh run
    started on the small mesh from the emergency checkpoint (the chaos
    bench asserts this bytewise with ``deterministic=True`` steps).

    Call :meth:`flush` then :meth:`checkpoint_boundary` wherever the
    trainer checkpoints; regrow happens only there, behind the monitor's
    breaker.
    """

    def __init__(
        self,
        factory: Callable[[Tuple[int, ...]], ElasticContext],
        n_replicas: int,
        *,
        policy: Optional[DivergencePolicy] = None,
        watchdog: Optional[StepWatchdog] = None,
        aux_interval: int = 1,
        regrow: Optional[RegrowPolicy] = None,
        monitor: Optional[MeshMonitor] = None,
        checkpoint_fn: Optional[Callable[[Any, int, Dict], Optional[str]]] = None,
        agree_fn: Optional[Callable[[Any], frozenset]] = None,
    ):
        self.factory = factory
        self.monitor = monitor or MeshMonitor(n_replicas, policy=regrow)
        self.ctx = factory(self.monitor.active)
        # snapshot_every=1: the guard's own snapshot is never the elastic
        # anchor (the loop keeps its own), but exact per-step snapshots
        # keep the divergence-retry path's rollback exact too
        self.pipe = PipelinedLoop(
            self.ctx.step_fn,
            policy=policy,
            watchdog=watchdog,
            snapshot_every=1,
            place_fn=self.ctx.place_state,
            aux_interval=aux_interval,
        )
        self._ckpt = checkpoint_fn
        self._agree = agree_fn or (
            lambda down: distributed.agree_on_down(down, n_replicas)
        )
        # dispatched-but-uncommitted (idx, host batch, rng), re-playable
        # against the host anchor — never device handles
        self._window: List[Tuple[int, Any, Any]] = []
        self._anchor: Any = None
        self._anchor_idx = 0
        self.emergency_ckpts: List[str] = []
        self.replayed_steps = 0
        self.recovery_s = 0.0
        self.last_recovery_s = 0.0

    @property
    def active(self) -> Tuple[int, ...]:
        return self.monitor.active

    @property
    def degraded(self) -> bool:
        return self.monitor.degraded

    # -- stepping ------------------------------------------------------
    def step(self, state, batch, rng):
        """Guarded elastic step; same ``(state, ready, ok)`` contract as
        :class:`PipelinedLoop`.  ``batch`` is the HOST global batch at
        the base size; placement (truncate + shard) happens here so a
        replay re-places against whatever mesh is current."""
        if not self._window:
            # anchor BEFORE the first dispatch of a window, as an owning
            # copy: the step donates the buffers a device_get view of
            # this state would alias
            self._anchor = host_copy(state)
            self._anchor_idx = self.pipe.next_index
        self._window.append((self.pipe.next_index, batch, rng))
        return self._drain(state, len(self._window) - 1)

    def flush(self, state):
        """Flush the pipeline window (epoch end / pre-checkpoint)."""
        try:
            state, ready, ok = self.pipe.flush(state)
        except Exception as e:  # noqa: BLE001 — classified below
            got = classify_device_fault(e)
            if got is None:
                raise
            self.replayed_steps += len(self._window)
            state = self._shrink(state, got[0], got[1],
                                 at_step=self.pipe.next_index)
            state, ready, ok = self._drain(state, 0)
            state, r2, ok2 = self.pipe.flush(state)
            ready, ok = ready + r2, ok and ok2
        if self.pipe.pending == 0:
            self._window.clear()
        return state, ready, ok

    def _drain(self, state, start: int):
        """Dispatch window entries from position ``start``; on a device
        fault, shrink and restart from the anchor (position 0)."""
        ready_out: List[Tuple[int, Dict]] = []
        ok_out = True
        i = start
        while i < len(self._window):
            idx, batch, rng = self._window[i]
            try:
                # the injected heartbeat: a dead replica fails its step
                faults.device_fault(idx, active=self.monitor.active)
                state, ready, ok = self.pipe.step(
                    state, self.ctx.place_batch(batch), rng
                )
            except Exception as e:  # noqa: BLE001 — classified below
                got = classify_device_fault(e)
                if got is None:
                    raise
                self.replayed_steps += i
                state = self._shrink(state, got[0], got[1], at_step=idx)
                i = 0
                continue
            ready_out.extend(ready)
            ok_out = ok_out and ok
            i += 1
        if self.pipe.pending == 0:
            self._window.clear()
        return state, ready_out, ok_out

    # -- membership changes --------------------------------------------
    def _shrink(self, state, kind: str, victim: Optional[int], at_step: int):
        t0 = time.perf_counter()
        down = {victim} if victim is not None else set(
            self.monitor.probe_down(at_step)
        ) & set(self.monitor.active)
        down = self._agree(down)
        if not down:
            raise NoSurvivorsError(
                f"step {at_step}: {kind} with unidentifiable victim — "
                f"cannot choose a survivor set"
            )
        prev = self.monitor.active
        self.monitor.note_shrink(at_step, down, kind)
        logger.warning(
            "elastic: %s at step %d — lost replica(s) %s; shrinking mesh "
            "%s -> %s and replaying the window from step %d",
            kind, at_step, sorted(down), list(prev),
            list(self.monitor.active), self._anchor_idx,
        )
        # emergency committed checkpoint from the HOST anchor — device
        # buffers on the broken mesh are never trusted, and the anchor's
        # stream position is exactly where a restarted run would resume
        if self._ckpt is not None:
            path = self._ckpt(
                self._anchor, self._anchor_idx,
                {"event": "shrink", "kind": kind,
                 "lost": sorted(int(o) for o in down), "step": at_step,
                 "active": list(self.monitor.active)},
            )
            if path:
                self.emergency_ckpts.append(path)
        self.ctx = self.factory(self.monitor.active)
        self.pipe.rebind(self.ctx.step_fn, self.ctx.place_state)
        self.pipe.rewind(self._anchor_idx)
        state = self.ctx.place_state(self._anchor)
        dt = time.perf_counter() - t0
        self.last_recovery_s = dt
        self.recovery_s += dt
        return state

    def checkpoint_boundary(self, state, step: Optional[int] = None):
        """Count a checkpoint boundary and regrow when allowed.

        Call AFTER :meth:`flush` (a pending window would straddle the
        mesh change).  Returns ``(state, regrown)``; on regrow the state
        was host-copied and re-placed on the expanded mesh, so the next
        step compiles (or cache-hits) the full-mesh executable.
        """
        if self.pipe.pending:
            raise RuntimeError(
                "checkpoint_boundary called with a pending pipeline "
                "window — flush first"
            )
        self.monitor.note_boundary()
        step = self.pipe.next_index if step is None else step
        target = self.monitor.want_regrow(step)
        if target is None:
            return state, False
        t0 = time.perf_counter()
        snap = host_copy(state)
        prev = self.monitor.active
        self.ctx = self.factory(target)
        self.pipe.rebind(self.ctx.step_fn, self.ctx.place_state)
        self.pipe.rewind(self.pipe.next_index)
        state = self.ctx.place_state(snap)
        self.monitor.note_regrow(step, target)
        self._window.clear()
        self._anchor, self._anchor_idx = snap, step
        dt = time.perf_counter() - t0
        self.last_recovery_s = dt
        self.recovery_s += dt
        logger.info(
            "elastic: regrow at boundary %d (step %d): %s -> %s",
            self.monitor.boundaries, step, list(prev), list(target),
        )
        return state, True

    # -- reporting -----------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        return {
            **self.monitor.stats(),
            "replayed_steps": self.replayed_steps,
            "emergency_checkpoints": len(self.emergency_ckpts),
            "recovery_s": round(self.recovery_s, 4),
            "pipeline": self.pipe.stats(),
        }


def make_elastic_factory(
    model,
    tx,
    *,
    devices=None,
    accum_steps: int = 1,
    donate: bool = True,
    deterministic: bool = False,
) -> Callable[[Tuple[int, ...]], ElasticContext]:
    """Real shard_map substrate for :class:`ElasticLoop`.

    ``devices`` fixes the base ordinal→device assignment (default: all
    of ``jax.devices()``); ``factory(active)`` builds the survivor mesh
    over exactly those devices, the DP train step on it (whose runtime
    ``psum(1, 'data')`` renormalizes the pmean to the new replica
    count), and placement functions that replicate state / truncate +
    shard the base-sized global batch.
    """
    import jax

    from mx_rcnn_tpu.parallel.mesh import (
        make_mesh,
        make_parallel_train_step,
        replicate,
        shard_batch,
        take_replica_rows,
    )

    devices = list(devices if devices is not None else jax.devices())
    n_base = len(devices)

    def factory(active: Tuple[int, ...]) -> ElasticContext:
        active = tuple(int(o) for o in active)
        mesh = make_mesh(
            n_data=len(active), n_model=1,
            devices=[devices[o] for o in active],
        )
        step_fn = make_parallel_train_step(
            model, tx, mesh, accum_steps=accum_steps, donate=donate,
            deterministic=deterministic,
        )

        def place_batch(batch):
            return shard_batch(
                take_replica_rows(batch, len(active), n_base), mesh
            )

        return ElasticContext(
            active=active,
            step_fn=step_fn,
            place_state=lambda tree: replicate(tree, mesh),
            place_batch=place_batch,
            mesh=mesh,
        )

    return factory
