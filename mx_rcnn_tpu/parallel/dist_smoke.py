"""Two-process jax.distributed smoke, shared by tests and the dryrun.

Reference role: the multi-machine launch path (upstream mx-rcnn trained
multi-GPU single-host via MXNet kvstore('device'); SURVEY §5.8 scopes the
multi-host analog).  Here two OS processes join a jax.distributed
coordinator on localhost, each exposing 2 virtual CPU devices, and run
one DP train step over the 4-device global mesh via the exact
``train_end2end`` plumbing (process-sliced loader rows →
``globalize_batch`` → shard_map step).  Both processes must report the
same replicated loss.

VERDICT r3 weak #3: this must run every round, not ship on trust —
``__graft_entry__.dryrun_multichip`` invokes :func:`run_two_process_smoke`
and the pytest twin (``tests/test_distributed.py``) runs by default in
``make test``; set ``SKIP_DIST_TESTS=1`` to opt out on constrained boxes.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys
from typing import List, Tuple

_WORKER = r"""
import os, sys
proc_id = int(sys.argv[1])
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

# order matters: platform override (sitecustomize pins jax_platforms to
# the axon plugin, env vars are ignored) THEN distributed init, both
# before anything touches the backend
import jax
jax.config.update("jax_platforms", "cpu")
from mx_rcnn_tpu.utils.platform import enable_compile_cache
enable_compile_cache()  # the ~2-min train-step compile amortizes across runs
jax.distributed.initialize("127.0.0.1:{port}", 2, proc_id)

import numpy as np
from mx_rcnn_tpu.parallel import distributed

assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()

import dataclasses
from mx_rcnn_tpu.config import generate_config
from mx_rcnn_tpu.core.train import create_train_state, make_optimizer
from mx_rcnn_tpu.models import FasterRCNN
from mx_rcnn_tpu.parallel import make_mesh, make_parallel_train_step, replicate

cfg = generate_config("resnet50", "PascalVOC")
cfg = cfg.replace(
    TRAIN=dataclasses.replace(
        cfg.TRAIN, RPN_PRE_NMS_TOP_N=128, RPN_POST_NMS_TOP_N=16,
        BATCH_ROIS=8, RPN_BATCH_SIZE=16,
    ),
)
model = FasterRCNN(cfg)

g = 4  # global batch: one image per global device
rng = np.random.RandomState(0)
imgs = rng.rand(g, 64, 64, 3).astype(np.float32)
info = np.tile([64, 64, 1.0], (g, 1)).astype(np.float32)
gt = np.zeros((g, 4, 5), np.float32)
gt[:, 0] = [8, 8, 40, 40, 1]
gtv = np.zeros((g, 4), bool)
gtv[:, 0] = True
seeds = np.arange(g, dtype=np.int32)

params = model.init(
    {"params": jax.random.key(0), "sampling": jax.random.key(1)},
    imgs[:1], info[:1], gt[:1], gtv[:1], train=True,
)["params"]
tx = make_optimizer(cfg, lambda s: 0.001)
mesh = make_mesh(n_data=4, n_model=1)
state = replicate(create_train_state(params, tx), mesh)
step = make_parallel_train_step(model, tx, mesh)

# every process materialises ONLY its rows, as the trainer's loader does
rows = distributed.process_slice(g)
local = {
    "images": imgs[rows], "im_info": info[rows],
    "gt_boxes": gt[rows], "gt_valid": gtv[rows], "sample_seeds": seeds[rows],
}
batch = distributed.globalize_batch(local, mesh)
new_state, aux = step(state, batch, jax.random.key(7))
loss = float(aux["loss"])
assert np.isfinite(loss), loss
assert int(jax.device_get(new_state.step)) == 1
print(f"proc {proc_id}: loss={loss:.5f}", flush=True)
"""


def free_port() -> int:
    """A hardcoded port collides with stale listeners or parallel CI
    jobs on the same host."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_two_process_smoke(timeout: int = 900) -> Tuple[List[int], List[str]]:
    """Spawn both workers; → (returncodes, outputs).  Raises on rc != 0
    or on loss disagreement between the processes;
    ``subprocess.TimeoutExpired`` if the deadline passes (callers with a
    wall-clock budget — ``__graft_entry__.dryrun_multichip`` — catch it
    and report a bounded skip instead of being hard-killed)."""
    code = _WORKER.replace("{port}", str(free_port()))
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("XLA_FLAGS", None)
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code, str(i)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, cwd=repo_root,
        )
        for i in range(2)
    ]
    outs = []
    # ONE shared deadline: a per-process communicate(timeout=...) would
    # let the worst case run ~2× the requested budget (each process gets
    # a fresh window), re-exposing the driver rc=124 the budget exists
    # to prevent
    import time

    deadline = time.monotonic() + timeout
    try:
        for p in procs:
            out, _ = p.communicate(timeout=max(deadline - time.monotonic(), 1.0))
            outs.append(out.decode())
    finally:
        # a worker wedged on the jax.distributed barrier (peer died
        # pre-init) must not outlive the smoke and spin on the host CPU
        # for the rest of the suite
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    for i, (p, out) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            raise RuntimeError(f"dist smoke proc {i} failed:\n{out}")
    losses = sorted(
        line.split("loss=")[1]
        for out in outs for line in out.splitlines() if "loss=" in line
    )
    if len(losses) != 2 or losses[0] != losses[1]:
        raise RuntimeError(f"dist smoke loss mismatch: {losses}")
    return [p.returncode for p in procs], outs
