"""Data parallelism over a device mesh: the KVStore('device') replacement.

Reference: MXNet ``kvstore='device'`` single-node gradient allreduce +
``AnchorLoader``'s per-GPU batch slicing (SURVEY §3.3, §5.8).  Here the
whole trainer is one ``shard_map`` over a ``Mesh(('data',))``: each chip
runs the identical train step on its batch shard, gradients/metrics are
``pmean``-ed — XLA lowers that to an ICI all-reduce within a slice and
DCN collectives across slices, so the same ten lines scale from 1 chip to
a multi-host pod (where the reference was hardcoded single-node).

Axis layout (scaling-book recipe): batch sharded on ``'data'``; params
and optimizer state replicated.  The mesh carries reserved axes for
tensor/pipeline extensions (`model`) so configs can evolve without
re-plumbing.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from mx_rcnn_tpu.core.train import TrainState, make_train_step

# jax promoted shard_map out of jax.experimental; accept either spelling
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map


def make_mesh(
    n_data: Optional[int] = None, n_model: int = 1, devices=None
) -> Mesh:
    """('data', 'model') mesh over all (or the given) devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    if n_data is None:
        n_data = devices.size // n_model
    assert n_data * n_model == devices.size, (
        f"{devices.size} devices cannot form ({n_data}, {n_model}) mesh"
    )
    return Mesh(devices.reshape(n_data, n_model), ("data", "model"))


def replica_slices(n_replicas: Optional[int] = None, devices=None) -> list:
    """Device assignment for a serving replica pool: replica i runs on
    ``slices[i % len(slices)]``.

    Serving replication is the transpose of the training mesh: training
    shards ONE batch across all devices, a replica pool pins ONE
    independent predictor per device (params committed via
    ``jax.device_put(params, device)``, so every jit it traces executes
    there).  With ``n_replicas`` ≤ device count each replica owns a
    device exclusively; beyond that they round-robin share (the CPU test
    topology: 8 virtual devices, pools of any size).
    """
    devs = list(devices if devices is not None else jax.devices())
    if n_replicas is None or n_replicas >= len(devs):
        return devs
    return devs[:n_replicas]


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (params/opt state) across the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_batch(batch: Dict[str, np.ndarray], mesh: Mesh):
    """Shard the leading (batch) axis of every array across 'data'."""
    sharding = NamedSharding(mesh, P("data"))
    return jax.device_put(batch, sharding)


def take_replica_rows(batch: Dict, n_active: int, n_base: int) -> Dict:
    """Truncate a base-mesh global batch to ``n_active`` of ``n_base``
    replicas' worth of leading-axis rows.

    The elastic shrink path (``parallel/elastic.py``) keeps the data
    loader's plan at the BASE global batch size — re-planning mid-epoch
    would invalidate the deterministic shuffle/bucketing stream — and
    instead drops the tail rows of each global batch.  Always the tail,
    never the dead replica's slice: the kept prefix is then a pure
    function of the survivor COUNT, so a fresh small-mesh run fed the
    same stream consumes bit-identical batches regardless of which
    ordinal died.
    """
    if n_active == n_base:
        return batch
    out = {}
    for k, v in batch.items():
        rows = np.shape(v)[0]
        if rows % n_base:
            raise ValueError(
                f"batch key {k!r}: {rows} rows not divisible by the "
                f"{n_base}-replica base mesh"
            )
        out[k] = v[: rows * n_active // n_base]
    return out


def make_parallel_train_step(
    model, tx, mesh: Mesh, accum_steps: int = 1, donate: bool = True,
    deterministic: bool = False,
):
    """The DP train step: per-chip compute + pmean on grads/metrics.

    Batch arrays arrive sharded on 'data'; state replicated.  Since the
    grads are pmean-ed inside, the updated state stays replicated — the
    invariant KVStore maintained with explicit broadcasts.
    ``accum_steps`` applies per chip (each shard is scanned into that
    many microbatches before its gradient joins the all-reduce).
    ``donate`` mirrors ``make_train_step``'s knob (same default: the
    input state is donated; rollback paths re-place from host
    snapshots, never reuse a donated buffer).  ``deterministic`` mirrors
    it too: on CPU it pins the legacy run-order-stable XLA runtime so
    two runs over identical inputs compare BITWISE — required by the
    elastic chaos bench's shrink-equivalence check.
    """
    inner = make_train_step(model, tx, pmean_axis="data", accum_steps=accum_steps)

    state_spec = P()   # replicated
    batch_spec = P("data")

    @partial(
        _shard_map,
        mesh=mesh,
        in_specs=(state_spec, batch_spec, state_spec, P()),
        out_specs=(state_spec, state_spec),
        # the rep checker can't see through the optimizer update that the
        # pmean-ed grads keep the state replicated; test_dp_grads_match_
        # single_device asserts that invariant numerically instead
        check_rep=False,
    )
    def sharded_step(state: TrainState, batch, rng, lr_scale):
        # sampling decorrelation across chips: batches carrying per-image
        # sample_seeds decorrelate by construction (and identically to a
        # single-chip run — the DP-equivalence invariant); seedless batches
        # fall back to folding in the chip index
        if "sample_seeds" not in batch:
            rng = jax.random.fold_in(rng, jax.lax.axis_index("data"))
        return inner(state, batch, rng, lr_scale)

    jit_kwargs: Dict[str, object] = {
        "donate_argnums": (0,) if donate else ()
    }
    # same rationale as make_train_step: the default CPU thunk runtime
    # reassociates reductions across threads, so even one executable on
    # identical inputs drifts ~1e-7 run-to-run
    if deterministic and jax.default_backend() == "cpu":
        jit_kwargs["compiler_options"] = {"xla_cpu_use_thunk_runtime": False}
    jitted = jax.jit(sharded_step, **jit_kwargs)

    def step(state: TrainState, batch, rng, lr_scale=1.0):
        # lr_scale: one-step effective-LR override (replicated scalar) —
        # the guarded loop's divergence-retry backoff.  ×1.0 is exact in
        # f32, so the default path is bit-identical to the unscaled step.
        return jitted(state, batch, rng, jnp.float32(lr_scale))

    return step
