"""Spatial (context) parallelism: shard the image plane across chips.

SURVEY §5.7: the reference's analog of sequence-length scaling is input
resolution, which it handled per-GPU only.  Here very large inputs can be
sharded along H over the mesh's ``model`` axis: convolutions under jit
with a spatial input sharding make XLA insert the halo exchanges
(collective-permutes of the kernel-overlap rows) automatically — the
image-domain equivalent of ring/all-to-all sequence parallelism, with
the compiler as the communication backend (no hand-written NCCL ring).

Usage::

    mesh = make_mesh(n_data=2, n_model=4)
    fn = spatial_sharded_backbone(backbone.apply, mesh)
    feat = fn(params, images)        # images sharded (data, model) on (B, H)

The backbone is closed over by jit with explicit in/out shardings; the
output feature map comes back sharded the same way, ready for sharded
RPN heads or a gather before roi pooling.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def spatial_shardings(mesh: Mesh):
    """(image_sharding, replicated) — batch on 'data', H on 'model'."""
    return (
        NamedSharding(mesh, P("data", "model", None, None)),
        NamedSharding(mesh, P()),
    )


def spatial_sharded_backbone(apply_fn, mesh: Mesh):
    """jit ``apply_fn(params, images)`` with (B, H) sharded in/out.

    XLA partitions every conv spatially and inserts halo exchanges on the
    ``model`` axis for the kernel overlaps; params stay replicated.
    """
    img_sharding, rep = spatial_shardings(mesh)

    return jax.jit(
        apply_fn,
        in_shardings=(rep, img_sharding),
        out_shardings=img_sharding,
    )


def shard_images_spatial(images, mesh: Mesh):
    """Place (B, H, W, C) images with B on 'data' and H on 'model'."""
    img_sharding, _ = spatial_shardings(mesh)
    return jax.device_put(images, img_sharding)


def shard_batch_spatial(batch, mesh: Mesh):
    """Place a full train batch for context-parallel training: images
    sharded (B→'data', H→'model'), every other array (gt, im_info,
    seeds) batch-sharded only.

    Feeding this placement to the ordinary jitted train step is the whole
    mechanism: jit propagates input shardings, so XLA spatially partitions
    every backbone/RPN conv (halo exchanges on 'model') and inserts the
    gather where the proposal top-k needs the full feature map — the same
    graph scales past single-chip activation memory with no model-code
    changes.  The detector analog of sequence/context parallelism for
    long sequences (SURVEY §5.7).
    """
    img_sharding, _ = spatial_shardings(mesh)
    row_sharding = NamedSharding(mesh, P("data"))
    return {
        k: jax.device_put(v, img_sharding if k == "images" else row_sharding)
        for k, v in batch.items()
    }
