"""Checkpoint save/restore via Orbax, plus preemption handling.

Reference: ``rcnn/core/callback.py :: do_checkpoint`` +
``rcnn/utils/{save_model,load_model}.py`` — MXNet json+params pairs with
the bbox-weight de-normalization quirk (SURVEY §5.5).  Here: raw pytree
state (params + optimizer + step) via Orbax, normalization never folded
into weights, and resume restores momentum too (the reference restarted
momentum cold — a known wart we fix).

Failure recovery (SURVEY §5.4 — the reference had none: a GPU failure
killed the run, restart was manual from the last *epoch*): a
:class:`PreemptionGuard` turns SIGTERM/SIGINT into a mid-epoch
checkpoint (``step_EEEE_SSSSSS``) that resume continues from exactly —
the loader's deterministic epoch plan makes skip-to-batch sound, so a
preempted-and-resumed run consumes the identical data stream as an
uninterrupted one.
"""

from __future__ import annotations

import os
import signal
from typing import Any, Optional, Tuple

import jax
import orbax.checkpoint as ocp

from mx_rcnn_tpu.core.train import TrainState


def _ckpt_name(epoch: int, batch_in_epoch: int) -> str:
    """epoch boundary → ``epoch_EEEE`` (the reference's
    ``prefix-%04d.params`` role); mid-epoch (preemption) →
    ``step_EEEE_BBBBBB``."""
    if batch_in_epoch == 0:
        return f"epoch_{epoch:04d}"
    return f"step_{epoch:04d}_{batch_in_epoch:06d}"


def _parse_ckpt_name(name: str) -> Optional[Tuple[int, int]]:
    parts = name.split("_")
    if name.startswith("epoch_") and len(parts) == 2 and parts[1].isdigit():
        return int(parts[1]), 0
    if (
        name.startswith("step_")
        and len(parts) == 3
        and parts[1].isdigit()
        and parts[2].isdigit()
    ):
        return int(parts[1]), int(parts[2])
    return None


def save_checkpoint(
    prefix: str, state: TrainState, epoch: int, batch_in_epoch: int = 0
) -> str:
    path = os.path.abspath(
        os.path.join(prefix, _ckpt_name(epoch, batch_in_epoch))
    )
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, jax.device_get(state), force=True)
    ckptr.wait_until_finished()
    return path


def load_checkpoint(
    prefix: str, epoch: int, target: TrainState, batch_in_epoch: int = 0
) -> TrainState:
    path = os.path.abspath(
        os.path.join(prefix, _ckpt_name(epoch, batch_in_epoch))
    )
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, target=jax.device_get(target))


def latest_epoch(prefix: str) -> Optional[int]:
    if not os.path.isdir(prefix):
        return None
    epochs = [
        int(d.split("_")[1])
        for d in os.listdir(prefix)
        if d.startswith("epoch_") and d.split("_")[1].isdigit()
    ]
    return max(epochs) if epochs else None


def latest_checkpoint(prefix: str) -> Optional[Tuple[int, int]]:
    """(epoch, batch_in_epoch) of the newest checkpoint, epoch- or
    mid-epoch; batch 0 means an epoch boundary.  A ``step_E_B`` dump is
    newer than ``epoch_E`` (it was taken inside epoch E after the
    boundary save of epoch E) but older than ``epoch_{E+1}``."""
    if not os.path.isdir(prefix):
        return None
    found = [
        parsed for d in os.listdir(prefix)
        if (parsed := _parse_ckpt_name(d)) is not None
    ]
    if not found:
        return None
    # (epoch, batch) lexicographic is exactly the resume order because a
    # step dump inside epoch E carries epoch index E while the boundary
    # save at the END of epoch E is named epoch_{E+1}
    return max(found)


def prune_step_checkpoints(prefix: str, up_to_epoch: int) -> None:
    """Delete ``step_E_B`` preemption dumps with E ≤ ``up_to_epoch`` —
    they are superseded once ``epoch_{E+1}`` exists.  Without pruning, a
    long run on a preemptible pool accumulates one full params+momentum
    dump per preemption."""
    import shutil

    if not os.path.isdir(prefix):
        return
    for d in os.listdir(prefix):
        parsed = _parse_ckpt_name(d)
        if parsed is None or parsed[1] == 0:
            continue
        if parsed[0] <= up_to_epoch:
            shutil.rmtree(os.path.join(prefix, d), ignore_errors=True)


class PreemptionGuard:
    """Convert SIGTERM/SIGINT into a clean 'stop after this step' flag.

    Usage::

        guard = PreemptionGuard()          # installs handlers
        for batch in loader:
            ...
            if guard.should_stop:
                save_checkpoint(prefix, state, epoch, batch_idx)
                return

    The first signal sets the flag; a second signal falls through to the
    previous handler (so a stuck run can still be killed).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.should_stop = False
        self._prev = {}
        for s in signals:
            self._prev[s] = signal.signal(s, self._handle)

    def _handle(self, signum, frame):
        if self.should_stop:  # second signal: escalate
            prev = self._prev.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            os.kill(os.getpid(), signum)
            return
        self.should_stop = True

    def uninstall(self):
        for s, h in self._prev.items():
            signal.signal(s, h)
