"""Checkpoint save/restore via Orbax.

Reference: ``rcnn/core/callback.py :: do_checkpoint`` +
``rcnn/utils/{save_model,load_model}.py`` — MXNet json+params pairs with
the bbox-weight de-normalization quirk (SURVEY §5.5).  Here: raw pytree
state (params + optimizer + step) via Orbax, normalization never folded
into weights, and resume restores momentum too (the reference restarted
momentum cold — a known wart we fix).
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from mx_rcnn_tpu.core.train import TrainState


def save_checkpoint(prefix: str, state: TrainState, epoch: int) -> str:
    """Save to ``{prefix}/epoch_{epoch:04d}`` (one dir per epoch, like the
    reference's ``prefix-%04d.params`` naming)."""
    path = os.path.abspath(os.path.join(prefix, f"epoch_{epoch:04d}"))
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, jax.device_get(state), force=True)
    ckptr.wait_until_finished()
    return path


def load_checkpoint(prefix: str, epoch: int, target: TrainState) -> TrainState:
    path = os.path.abspath(os.path.join(prefix, f"epoch_{epoch:04d}"))
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, target=jax.device_get(target))


def latest_epoch(prefix: str) -> Optional[int]:
    if not os.path.isdir(prefix):
        return None
    epochs = [
        int(d.split("_")[1])
        for d in os.listdir(prefix)
        if d.startswith("epoch_") and d.split("_")[1].isdigit()
    ]
    return max(epochs) if epochs else None
