"""Checkpoint save/restore via Orbax, plus preemption handling.

Reference: ``rcnn/core/callback.py :: do_checkpoint`` +
``rcnn/utils/{save_model,load_model}.py`` — MXNet json+params pairs with
the bbox-weight de-normalization quirk (SURVEY §5.5).  Here: raw pytree
state (params + optimizer + step) via Orbax, normalization never folded
into weights, and resume restores momentum too (the reference restarted
momentum cold — a known wart we fix).

Failure recovery (SURVEY §5.4 — the reference had none: a GPU failure
killed the run, restart was manual from the last *epoch*): a
:class:`PreemptionGuard` turns SIGTERM/SIGINT into a mid-epoch
checkpoint (``step_EEEE_SSSSSS``) that resume continues from exactly —
the loader's deterministic epoch plan makes skip-to-batch sound, so a
preempted-and-resumed run consumes the identical data stream as an
uninterrupted one.

Crash safety: a save writes into ``<name>.tmp``, records a
``manifest.json`` (step/epoch, per-file sizes, param-tree checksum)
inside it, then atomically renames to ``<name>`` — a process killed
mid-save leaves only an orphaned ``.tmp`` that no reader ever selects.
``latest_checkpoint``/``restorable_checkpoints`` verify the manifest
(presence + file sizes) and fall back past corrupt, truncated, or
uncommitted dumps to the newest verifiable one; ``load_checkpoint``
additionally re-checksums the restored tree.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import signal
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from mx_rcnn_tpu.core.train import TrainState
from mx_rcnn_tpu.utils import faults

logger = logging.getLogger(__name__)

MANIFEST = "manifest.json"
MANIFEST_FORMAT = 1


class CheckpointCorrupt(RuntimeError):
    """A restored tree's checksum disagrees with its manifest."""


def _ckpt_name(epoch: int, batch_in_epoch: int) -> str:
    """epoch boundary → ``epoch_EEEE`` (the reference's
    ``prefix-%04d.params`` role); mid-epoch (preemption) →
    ``step_EEEE_BBBBBB``."""
    if batch_in_epoch == 0:
        return f"epoch_{epoch:04d}"
    return f"step_{epoch:04d}_{batch_in_epoch:06d}"


def _parse_ckpt_name(name: str) -> Optional[Tuple[int, int]]:
    parts = name.split("_")
    if name.startswith("epoch_") and len(parts) == 2 and parts[1].isdigit():
        return int(parts[1]), 0
    if (
        name.startswith("step_")
        and len(parts) == 3
        and parts[1].isdigit()
        and parts[2].isdigit()
    ):
        return int(parts[1]), int(parts[2])
    return None


def tree_checksum(tree: Any) -> str:
    """Deterministic sha256 over a pytree's structure + leaf bytes.

    Path strings and dtype/shape are hashed alongside the raw bytes so a
    silently reshaped or re-typed leaf can't collide with the original.
    """
    h = hashlib.sha256()
    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    for path, leaf in leaves:
        arr = np.asarray(jax.device_get(leaf))
        h.update(jax.tree_util.keystr(path).encode())
        h.update(f"{arr.dtype}{arr.shape}".encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _manifest_files(root: str) -> Dict[str, int]:
    """relpath → size for every regular file under ``root`` (excluding
    the manifest itself)."""
    out: Dict[str, int] = {}
    for dirpath, _, files in os.walk(root):
        for f in files:
            full = os.path.join(dirpath, f)
            rel = os.path.relpath(full, root)
            if rel == MANIFEST:
                continue
            out[rel] = os.path.getsize(full)
    return out


def save_checkpoint(
    prefix: str,
    state: TrainState,
    epoch: int,
    batch_in_epoch: int = 0,
    meta: Optional[Dict[str, Any]] = None,
) -> str:
    """Crash-safe save: write ``<name>.tmp``, fsync a manifest into it,
    atomically rename to ``<name>``.  A kill at ANY point leaves either
    the previous committed dump intact or an orphaned ``.tmp`` that
    every reader skips (and ``prune_step_checkpoints`` removes).

    ``meta`` (optional, JSON-serializable) is recorded verbatim under
    ``manifest["meta"]`` — the elastic shrink path stamps its emergency
    dumps with the fault kind, lost ordinals, and survivor set so a
    post-mortem can reconstruct the membership history from disk."""
    import shutil

    final = os.path.abspath(
        os.path.join(prefix, _ckpt_name(epoch, batch_in_epoch))
    )
    tmp = final + ".tmp"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)
    host_state = jax.device_get(state)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(tmp, host_state, force=True)
    ckptr.wait_until_finished()
    manifest = {
        "format": MANIFEST_FORMAT,
        "epoch": epoch,
        "batch_in_epoch": batch_in_epoch,
        "step": int(np.asarray(host_state.step)) if hasattr(host_state, "step") else None,
        "checksum": tree_checksum(host_state),
        "files": _manifest_files(tmp),
    }
    if meta is not None:
        manifest["meta"] = meta
    # injection point: a SIGKILL between the data write and the commit
    faults.crash_save()
    mpath = os.path.join(tmp, MANIFEST)
    with open(mpath, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    if os.path.isdir(final):
        shutil.rmtree(final)
    os.rename(tmp, final)
    # durability of the rename itself
    try:
        dfd = os.open(os.path.dirname(final), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # pragma: no cover — exotic filesystems
        pass
    return final


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    try:
        with open(os.path.join(path, MANIFEST)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_committed(path: str) -> bool:
    """Cheap integrity check: the manifest exists and every file it
    recorded is present with the recorded size — catches uncommitted
    (killed mid-save), truncated, and partially deleted dumps without
    the cost of a restore."""
    man = read_manifest(path)
    if man is None or not isinstance(man.get("files"), dict):
        return False
    for rel, size in man["files"].items():
        full = os.path.join(path, rel)
        try:
            if os.path.getsize(full) != int(size):
                return False
        except OSError:
            return False
    return True


def restore_tree(path: str) -> Any:
    """Host-side restore of a committed dump WITHOUT a target tree: Orbax
    reconstructs the saved pytree as numpy leaves, so nothing lands on a
    device.  This is the registry's background checkpoint load (ISSUE 7)
    — a candidate model's params stay host-resident until its warmup
    stage stages them deliberately."""
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(os.path.abspath(path))


def verify_manifest(path: str, tree: Any = None) -> Dict[str, Any]:
    """Full verification gate for one committed dump: manifest present,
    every recorded file at its recorded size (:func:`is_committed`), and
    the tree digest equal to the manifest checksum.  ``tree`` skips the
    redundant re-restore when the caller already holds the restored
    (host) tree; otherwise the digest check restores host-side via
    :func:`restore_tree` — params never touch a device either way.

    Returns the manifest dict; raises :class:`CheckpointCorrupt` on any
    failure.  This is the ONE digest path shared by ``load_checkpoint``
    and the serving registry's swap gate."""
    path = os.path.abspath(path)
    man = read_manifest(path)
    if man is None:
        raise CheckpointCorrupt(f"{path}: missing or unreadable manifest")
    if not is_committed(path):
        raise CheckpointCorrupt(
            f"{path}: uncommitted or truncated dump (manifest file sizes "
            f"disagree with what is on disk)"
        )
    if man.get("checksum"):
        if tree is None:
            tree = restore_tree(path)
        got = tree_checksum(tree)
        if got != man["checksum"]:
            raise CheckpointCorrupt(
                f"{path}: restored tree checksum {got[:12]}… does not "
                f"match manifest {str(man['checksum'])[:12]}…"
            )
    return man


def load_checkpoint(
    prefix: str,
    epoch: int,
    target: TrainState,
    batch_in_epoch: int = 0,
    verify: bool = True,
) -> TrainState:
    path = os.path.abspath(
        os.path.join(prefix, _ckpt_name(epoch, batch_in_epoch))
    )
    ckptr = ocp.StandardCheckpointer()
    restored = ckptr.restore(path, target=jax.device_get(target))
    # manifest-less dumps (legacy/external) load unverified by design;
    # anything WITH a checksum goes through the shared verification gate
    if verify and read_manifest(path) is not None:
        verify_manifest(path, tree=restored)
    return restored


def latest_epoch(prefix: str) -> Optional[int]:
    epochs = [e for e, b in restorable_checkpoints(prefix) if b == 0]
    return max(epochs) if epochs else None


def restorable_checkpoints(prefix: str) -> List[Tuple[int, int]]:
    """All verifiable checkpoints, newest first.  Uncommitted ``.tmp``
    dirs never parse as checkpoint names; committed-looking dirs whose
    manifest is missing or whose files are truncated are skipped — the
    fallback-past-corruption guarantee."""
    if not os.path.isdir(prefix):
        return []
    found = []
    for d in os.listdir(prefix):
        parsed = _parse_ckpt_name(d)
        if parsed is None:
            continue
        if not is_committed(os.path.join(prefix, d)):
            logger.warning(
                "skipping unverifiable checkpoint %s (missing/corrupt "
                "manifest or truncated files)", os.path.join(prefix, d)
            )
            continue
        found.append(parsed)
    # (epoch, batch) lexicographic is exactly the resume order because a
    # step dump inside epoch E carries epoch index E while the boundary
    # save at the END of epoch E is named epoch_{E+1}
    return sorted(found, reverse=True)


def latest_checkpoint(prefix: str) -> Optional[Tuple[int, int]]:
    """(epoch, batch_in_epoch) of the newest VERIFIABLE checkpoint,
    epoch- or mid-epoch; batch 0 means an epoch boundary.  A ``step_E_B``
    dump is newer than ``epoch_E`` (it was taken inside epoch E after the
    boundary save of epoch E) but older than ``epoch_{E+1}``.  Corrupt or
    uncommitted dumps are skipped in favor of the newest good one."""
    found = restorable_checkpoints(prefix)
    return found[0] if found else None


def load_restorable(
    prefix: str, target: TrainState
) -> Optional[Tuple[Tuple[int, int], TrainState]]:
    """Restore the newest checkpoint that actually loads and verifies,
    falling back past corrupt dumps (manifest-valid but checksum-bad, or
    unreadable) to older ones.  Returns ``((epoch, batch), state)`` or
    None when nothing is restorable."""
    for epoch, batch in restorable_checkpoints(prefix):
        try:
            state = load_checkpoint(prefix, epoch, target, batch)
            return (epoch, batch), state
        except Exception as e:  # noqa: BLE001 — fall back to the previous dump
            logger.warning(
                "checkpoint (epoch %d, batch %d) failed to restore (%r) — "
                "falling back to the previous dump", epoch, batch, e
            )
    return None


def prune_step_checkpoints(prefix: str, up_to_epoch: int) -> None:
    """Delete ``step_E_B`` preemption/emergency dumps with E ≤
    ``up_to_epoch`` — they are superseded once ``epoch_{E+1}`` exists —
    plus ANY orphaned ``.tmp`` dir (an interrupted save that will never
    be committed).  Without pruning, a long run on a preemptible pool
    accumulates one full params+momentum dump per preemption.

    Retain guard: the NEWEST COMMITTED step dump is never deleted, even
    when its epoch is ≤ ``up_to_epoch``.  The boundary save that
    supersedes it can itself be lost or corrupt (the scenario
    ``load_restorable`` falls back past), and pruning must not shorten
    that fallback chain below one verifiable mid-epoch dump.  Committed
    is the :func:`is_committed` bar, so a corrupt dump that happens to
    sort newest does not shadow the real survivor — the guard keys on
    the newest dump a resume could actually use."""
    import shutil

    if not os.path.isdir(prefix):
        return
    step_dumps = []
    for d in os.listdir(prefix):
        parsed = _parse_ckpt_name(d)
        if parsed is not None and parsed[1] != 0:
            step_dumps.append((parsed, d))
    keep = max(
        (pd for pd in step_dumps if is_committed(os.path.join(prefix, pd[1]))),
        default=None,
    )
    for d in os.listdir(prefix):
        full = os.path.join(prefix, d)
        if d.endswith(".tmp") and os.path.isdir(full):
            logger.info("pruning orphaned partial checkpoint %s", full)
            shutil.rmtree(full, ignore_errors=True)
            continue
        parsed = _parse_ckpt_name(d)
        if parsed is None or parsed[1] == 0:
            continue
        if keep is not None and d == keep[1]:
            continue
        if parsed[0] <= up_to_epoch:
            shutil.rmtree(full, ignore_errors=True)


class PreemptionGuard:
    """Convert SIGTERM/SIGINT into a clean 'stop after this step' flag.

    Usage::

        guard = PreemptionGuard()          # installs handlers
        for batch in loader:
            ...
            if guard.should_stop:
                save_checkpoint(prefix, state, epoch, batch_idx)
                return

    The first signal sets the flag; a second signal falls through to the
    previous handler (so a stuck run can still be killed).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.should_stop = False
        self._prev = {}
        for s in signals:
            self._prev[s] = signal.signal(s, self._handle)

    def _handle(self, signum, frame):
        if self.should_stop:  # second signal: escalate
            prev = self._prev.get(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            os.kill(os.getpid(), signum)
            return
        self.should_stop = True

    def uninstall(self):
        for s, h in self._prev.items():
            signal.signal(s, h)
