"""Running metric aggregation + throughput logging.

Reference: ``rcnn/core/metric.py`` (six EvalMetrics) and
``rcnn/core/callback.py :: Speedometer``.  The metric *values* are
computed inside the jitted train step (``FasterRCNN.train_forward`` aux
dict, same names); this module only accumulates host-side scalars and
prints in the reference's log format so runs are comparable line-by-line.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, Iterable

logger = logging.getLogger(__name__)

METRIC_NAMES = (
    "RPNAcc",
    "RPNLogLoss",
    "RPNL1Loss",
    "RCNNAcc",
    "RCNNLogLoss",
    "RCNNL1Loss",
)


class MetricTracker:
    """Running means, reset per logging interval (EvalMetric twin)."""

    def __init__(self, names: Iterable[str] = METRIC_NAMES):
        self.names = tuple(names)
        self.reset()

    def reset(self) -> None:
        self._sums = {n: 0.0 for n in self.names}
        self._count = 0

    def update(self, aux: Dict[str, float]) -> None:
        for n in self.names:
            if n in aux:
                self._sums[n] += float(aux[n])
        self._count += 1

    def get(self) -> Dict[str, float]:
        c = max(self._count, 1)
        return {n: self._sums[n] / c for n in self.names}

    def format(self) -> str:
        return ",\t".join(f"{n}={v:.6f}" for n, v in self.get().items())


class Speedometer:
    """imgs/sec logging every ``frequent`` batches (callback.py twin).

    ``jsonl_path`` additionally appends one machine-readable JSON line
    per log event — the SURVEY §5.6 structured-scalar-logging upgrade
    (the reference had only the human-format log line)."""

    def __init__(
        self, batch_size: int, frequent: int = 20, jsonl_path: str | None = None
    ):
        self.batch_size = batch_size
        self.frequent = frequent
        self.jsonl_path = jsonl_path
        self._tic = time.time()
        self._last = 0

    def __call__(self, epoch: int, step: int, tracker: MetricTracker) -> None:
        if step % self.frequent != 0 or step == self._last:
            return
        elapsed = time.time() - self._tic
        speed = self.frequent * self.batch_size / max(elapsed, 1e-9)
        logger.info(
            "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t%s",
            epoch,
            step,
            speed,
            tracker.format(),
        )
        if self.jsonl_path:
            import json

            rec = {
                "time": time.time(),
                "epoch": epoch,
                "step": step,
                "samples_per_sec": round(speed, 3),
                **{k: round(v, 6) for k, v in tracker.get().items()},
            }
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        tracker.reset()
        self._tic = time.time()
        self._last = step
