"""Inference: Predictor, im_detect, pred_eval, generate_proposals.

Reference: ``rcnn/core/tester.py`` — ``Predictor`` (bound forward-only
module), ``im_detect`` (decode + clip + unscale), ``pred_eval`` (dataset
loop → per-class NMS → ``imdb.evaluate_detections``), and
``generate_proposals`` (dump RPN proposals for alternate training).

The device side is one jitted test forward per shape bucket; the host
side (per-class thresholding/NMS, detection accumulation) stays on the
host exactly like the reference, with the NMS inner loop in native C
(``native/hostops.c`` — the reference's ``cpu_nms.pyx`` role).
"""

from __future__ import annotations

import logging
import pickle
import time
from typing import Dict, List, Optional

import jax
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.utils.bbox_stats import np_bbox_pred, np_clip_boxes

logger = logging.getLogger(__name__)


class Predictor:
    """Jitted forward-only wrapper (Predictor twin).  One compile per
    shape bucket — the TPU replacement for MutableModule max-shape
    binding.

    ``postprocess`` (ops/postprocess.py): fuses per-class decode+NMS
    into the same jit, so only keep lists cross the device→host link
    instead of the full (B, R, K)+(B, R, 4K) head outputs.  Mask models
    get the same treatment: the postprocess gathers each survivor's
    class-channel S×S grid on device (``det_masks``), so the raw
    ``(B, R, S, S, K)`` stack never crosses the link — host workers
    only sigmoid + paste + RLE-encode."""

    def __init__(self, model, params, postprocess=None, donate: bool = False,
                 deterministic: bool = False, params_transform=None):
        self.model = model
        self.params = params

        # batch keys match the model __call__ kwargs (gt keys are accepted
        # and ignored by test forwards; FastRCNN additionally consumes
        # proposals/prop_valid)
        #
        # params_transform (int8 rung, core/quantize.py): a jit-traceable
        # tree→tree map applied to the params argument INSIDE the jit —
        # the bound tree can then be a compressed form (int8 q + scale
        # leaves) that dequantizes on use, with XLA fusing the broadcast
        # multiply into each weight's consumer.  Params stay a traced
        # argument, so hot-swap pointer flips still reuse the executable.
        def fwd(p, batch):
            if params_transform is not None:
                p = params_transform(p)
            batch = dict(batch)
            orig_hw = batch.pop("orig_hw", None)
            out = model.apply({"params": p}, train=False, **batch)
            if postprocess is not None and orig_hw is not None:
                if getattr(postprocess, "wants_canvas", False):
                    # canvas-paste postprocess (streaming mask serving):
                    # the paste canvas is the padded bucket extent —
                    # static under the trace, so one canvas shape per
                    # (model, bucket) rung and the compile ladder is
                    # untouched
                    return postprocess(
                        out, batch["im_info"], orig_hw,
                        tuple(batch["images"].shape[1:3]),
                    )
                return postprocess(out, batch["im_info"], orig_hw)
            return out

        # donate=True hands the input batch buffers to XLA (serving: the
        # engine never reuses a dispatched batch, so the device can write
        # outputs in place).  Off by default — the CPU runtime can't use
        # donations and would log a warning per compile.
        jit_kwargs = {}
        if donate:
            jit_kwargs["donate_argnums"] = (1,)
        # deterministic=True (CPU): compile with the legacy XLA:CPU
        # runtime, whose Eigen kernels accumulate each output cell's
        # reduction serially — a SHAPE-INDEPENDENT order, so the same
        # valid pixels produce bitwise-identical features on every
        # shape-bucket canvas.  The default thunk runtime reassociates
        # reductions per shape (~1e-6 on head outputs across buckets).
        # Accelerator backends ignore the option (it is cpu-namespaced).
        if deterministic and jax.default_backend() == "cpu":
            jit_kwargs["compiler_options"] = {
                "xla_cpu_use_thunk_runtime": False
            }
        self._fn = jax.jit(fwd, **jit_kwargs)

    def predict(self, batch: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return jax.device_get(self.predict_async(batch))

    def predict_async(self, batch: Dict[str, np.ndarray]):
        """Dispatch the forward and return the ON-DEVICE outputs without
        materializing them (``jax.device_get`` forces).  NOTE: on the
        relay-attached TPU this buys nothing for eval overlap — the
        relay does not overlap stages of successive one-thread
        dispatches (measured in ``pipelined``'s docstring) — so eval
        overlap uses threads calling blocking :meth:`predict` instead.
        Kept for callers that want dispatch/force split points."""
        return self._fn(self.params, batch)

    def predict_with(
        self, params, batch: Dict[str, np.ndarray]
    ) -> Dict[str, np.ndarray]:
        """Blocking forward with CALLER-supplied params instead of the
        bound ones.  Params are a traced jit argument, so a same-
        structure/shape/dtype tree reuses the compiled executable — this
        is what makes a hot-swap warmup (ISSUE 7) a validation pass, not
        a recompile: the registry drives a candidate version through
        every warmed bucket off the live path, then the swap itself is a
        pointer assignment to :attr:`params` between batches."""
        return jax.device_get(self._fn(params, batch))

    def input_layouts(self, batch: Dict[str, np.ndarray]):
        """Compiled layouts of the batch argument for this batch's
        shapes, usable as a ``jax.device_put`` target so the transfer
        lands device-native and XLA inserts no input relayout copy
        (ROOFLINE: ~1.1 ms/step on the flagship for the image tensor).
        None when the runtime doesn't expose layouts."""
        from mx_rcnn_tpu.core.pipeline import input_layouts_for, shape_structs

        return input_layouts_for(
            self._fn, (shape_structs(self.params), shape_structs(batch)),
            argnum=1,
        )


def pipelined(
    predictor: Predictor,
    batches,
    in_flight: int = 2,
    feed_depth: int = 2,
    stats_out: Optional[Dict] = None,
    mode: str = "auto",
):
    """Overlapped eval pipeline shared by pred_eval / generate_proposals
    / bench_eval: keeps ``in_flight`` forwards in motion and yields
    ``(payload, batch, outputs)`` in input order.

    Two dispatch modes, selected by ``mode`` (``"auto"`` picks per
    backend):

    * ``"threads"`` (non-CPU default): ``in_flight`` blocking
      :meth:`Predictor.predict` calls in a small thread pool.  On a
      relay-attached TPU the per-batch serial chain is upload → compute
      → fetch (measured b8 flagship: 135 + 72 + ~130 ms) and the relay
      does NOT overlap stages of successive one-thread dispatches
      (depth-2 async dispatch measured 0% faster) — but two concurrent
      requests from separate threads DO overlap (the GIL drops during
      relay I/O): measured 424 → 279 ms/batch device-side (3 threads:
      266).
    * ``"async"`` (CPU default): :meth:`Predictor.predict_async` from
      the dispatch thread with a bounded in-flight window, forcing
      (``jax.device_get``) only when a result is consumed — no predict
      threads, so the dispatch thread stays free to run the completion
      pool's backpressure and local runtimes queue the window natively.

    Either way results are consumed in submission order, so downstream
    accumulation is order-identical to the serial loop
    (``tests/test_postprocess.py`` equivalence).

    Eval draws device-feed from the same pipeline stage as training:
    ``feed_depth`` > 0 stacks a :class:`~mx_rcnn_tpu.core.pipeline
    .DeviceFeed` between the host batches and the predict stage, so
    batch N+1's H2D transfer overlaps batch N's forward (0 disables —
    the batches then reach jit as host numpy).  ``stats_out``, if given,
    receives the feed's occupancy counters plus the resolved mode on
    exit.
    """
    from collections import deque
    from concurrent.futures import ThreadPoolExecutor

    from mx_rcnn_tpu.core.pipeline import DeviceFeed

    if mode == "auto":
        mode = "async" if jax.default_backend() == "cpu" else "threads"
    if mode not in ("async", "threads"):
        raise ValueError(f"unknown pipelined mode {mode!r}")
    feed = None
    source = batches
    if feed_depth > 0:
        feed = DeviceFeed(
            batches,
            # stage only the batch; the payload (indices/records) is host
            # bookkeeping
            place_fn=lambda pair: (pair[0], jax.device_put(pair[1])),
            depth=feed_depth,
            name="eval-device-feed",
        )
        source = feed
    window = max(in_flight, 1)
    q: deque = deque()
    ex = None
    try:
        if mode == "async":
            for payload, batch in source:
                q.append((payload, batch, predictor.predict_async(batch)))
                while len(q) > window:
                    p, b, o = q.popleft()
                    yield p, b, jax.device_get(o)
            while q:
                p, b, o = q.popleft()
                yield p, b, jax.device_get(o)
        else:
            ex = ThreadPoolExecutor(max_workers=window)
            for payload, batch in source:
                q.append(
                    (payload, batch, ex.submit(predictor.predict, batch))
                )
                while len(q) > window:
                    p, b, f = q.popleft()
                    yield p, b, f.result()
            while q:
                p, b, f = q.popleft()
                yield p, b, f.result()
    finally:
        if ex is not None:
            # wait=True: on early abandonment (consumer raised/broke
            # out), drain the in-flight predicts (~one batch chain)
            # rather than leaving orphan threads driving the relay under
            # whatever the caller does next; queued-but-unstarted work
            # is cancelled
            ex.shutdown(wait=True, cancel_futures=True)
        if stats_out is not None:
            stats_out["mode"] = mode
            stats_out["in_flight"] = window
        if feed is not None:
            if stats_out is not None:
                stats_out.update(feed.stats())
            feed.close()


def im_detect(
    output: Dict[str, np.ndarray], im_info: np.ndarray, orig_hw, index: int = 0
) -> Dict[str, np.ndarray]:
    """Decode one image's raw head outputs into image-space detections.

    Reference: ``rcnn/core/tester.py :: im_detect`` — class-specific
    delta decode, clip to the *resized* image, then divide by scale back
    to original coordinates.  ``index`` selects the image within a
    batched forward's outputs.
    """
    rois = output["rois"][index]
    valid = output["roi_valid"][index].astype(bool)
    scores = output["cls_prob"][index]
    deltas = output["bbox_deltas"][index]
    scale = float(im_info[2])

    # host numpy decode, like the reference's nonlinear_pred: a jnp call
    # here would pay a device dispatch per image during the eval loop
    boxes = np_bbox_pred(np.asarray(rois), np.asarray(deltas))
    boxes = np_clip_boxes(boxes, (float(im_info[0]), float(im_info[1])))
    boxes = boxes / scale
    # final clip to the original image extent
    h, w = orig_hw
    boxes = np_clip_boxes(boxes, (float(h), float(w)))
    det = {"scores": scores[valid], "boxes": boxes[valid]}
    if "mask_logits" in output:  # Mask R-CNN branch: per-roi (S, S, K)
        det["mask_probs"] = 1.0 / (
            1.0 + np.exp(-np.asarray(output["mask_logits"][index][valid]))
        )
    return det


def pred_eval(
    predictor: Predictor,
    loader,
    imdb,
    cfg: Config,
    thresh: Optional[float] = None,
    vis: Optional[str] = None,
    dump_path: Optional[str] = None,
    vis_thresh: float = 0.7,
    postprocess_workers: Optional[int] = None,
    assembly_workers: Optional[int] = None,
    stats_out: Optional[Dict] = None,
):
    """Full-dataset evaluation loop (pred_eval twin).

    Returns (all_boxes, eval_results) where
    ``all_boxes[cls][img] = (n, 5)``.  ``dump_path`` writes the all_boxes
    pickle that ``tools/reeval.py`` re-scores (the reference's
    detections.pkl); ``vis`` names a directory that receives per-image
    detection overlays (vis_all_detection twin).

    Host data plane (ISSUE 5): assembly can run in a worker pool
    (``assembly_workers``, batched loaders only) and the per-image
    postprocess — detections, capping, mask RLE encoding — runs in a
    :class:`~mx_rcnn_tpu.data.assembler.CompletionPool`
    (``postprocess_workers``; None → ``MX_RCNN_POSTPROCESS_WORKERS``,
    default 0 = inline on the dispatch thread).  Accumulation is
    index-addressed (``all_boxes[cls][img]``), so the result is
    identical no matter which worker finishes first; worker errors
    re-raise at the final ``drain``.  ``stats_out`` receives the
    completion-pool counters.
    """
    import os as _os
    import threading

    from mx_rcnn_tpu.data.assembler import CompletionPool

    te = cfg.TEST
    thresh = te.SCORE_THRESH if thresh is None else thresh
    num_classes = imdb.num_classes
    num_images = len(loader)
    if te.DEVICE_POSTPROCESS:
        from mx_rcnn_tpu.ops.postprocess import make_test_postprocess

        predictor = Predictor(
            predictor.model,
            predictor.params,
            postprocess=make_test_postprocess(
                cfg, num_classes, thresh, max_out=te.DET_PER_CLASS
            ),
        )
    all_boxes: List[List[np.ndarray]] = [
        [np.zeros((0, 5), np.float32) for _ in range(num_images)]
        for _ in range(num_classes)
    ]
    all_masks: Optional[List[List[list]]] = None
    t0 = time.time()
    done = 0
    # all_boxes/all_masks slot writes are disjoint per image index; the
    # lock covers the only cross-image state (lazy all_masks creation
    # and the progress counter)
    acc_lock = threading.Lock()

    def process_image(i: int, rec: Dict, out, batch, k: int = 0):
        """Accumulate detections for dataset image ``i`` from the
        ``k``-th slot of a (possibly batched) forward's outputs.  Pure
        per image except the index-addressed slot writes — safe from
        any completion worker."""
        nonlocal all_masks, done
        # the canonical per-image postprocess lives in serve/runner.py
        # (one decode path shared by eval, demo, and the serving engine);
        # function-level import: serve imports this module at top level
        from mx_rcnn_tpu.serve.runner import (
            cap_detections,
            detections_from_output,
        )

        cls_dets, mask_probs = detections_from_output(
            out, batch["im_info"][k], (rec["height"], rec["width"]),
            cfg, num_classes, index=k, thresh=thresh,
        )
        # cap detections per image across classes (COCO: 100) BEFORE mask
        # encoding — full-image mask work for detections the cap then
        # discards dominated segm eval cost
        cls_dets, mask_probs = cap_detections(
            cls_dets, te.MAX_PER_IMAGE, mask_probs
        )
        rles = None
        if mask_probs is not None:
            from mx_rcnn_tpu.eval.segm import rles_for_detections

            rles = {
                j: rles_for_detections(
                    mask_probs[j], cls_dets[j], rec["height"], rec["width"]
                )
                for j in range(1, num_classes)
            }
        for j in range(1, num_classes):
            all_boxes[j][i] = cls_dets[j]
        if rles is not None:
            with acc_lock:
                if all_masks is None:
                    all_masks = [
                        [[] for _ in range(num_images)]
                        for _ in range(num_classes)
                    ]
            for j in range(1, num_classes):
                all_masks[j][i] = rles[j]
        if vis:
            from mx_rcnn_tpu.data.loader import _load_record_image
            from mx_rcnn_tpu.utils.visualize import draw_detections, save_image

            _os.makedirs(vis, exist_ok=True)
            dets_by_class = {
                imdb.classes[j]: all_boxes[j][i] for j in range(1, num_classes)
            }
            im = draw_detections(_load_record_image(rec), dets_by_class, vis_thresh)
            save_image(_os.path.join(vis, f"det_{i:06d}.png"), im)
        with acc_lock:
            done += 1
            n_done = done
        if n_done % 100 == 0:
            logger.info(
                "im_detect %d/%d %.3fs/im", n_done, num_images,
                (time.time() - t0) / n_done,
            )

    workers = (
        max(0, int(_os.environ.get("MX_RCNN_POSTPROCESS_WORKERS", "0")))
        if postprocess_workers is None
        else max(0, int(postprocess_workers))
    )
    completion = CompletionPool(workers, name="eval-complete")
    try:
        if getattr(loader, "batch_size", 1) > 1:
            # batched device forwards (beyond-reference: the reference
            # tester is batch=1); dataset order is restored through the
            # indices, so completion can run out of order
            for (idxs, recs), batch, out in pipelined(
                predictor,
                (
                    ((idxs, recs), batch)
                    for idxs, recs, batch in loader.iter_batched(
                        assembly_workers=assembly_workers
                    )
                ),
            ):
                for k, (i, rec) in enumerate(zip(idxs, recs)):
                    completion.submit(process_image, i, rec, out, batch, k)
        else:
            for (i, rec), batch, out in pipelined(
                predictor,
                (((i, rec), batch) for i, (rec, batch) in enumerate(loader)),
            ):
                completion.submit(process_image, i, rec, out, batch)
        completion.drain()
    finally:
        completion.close()
        if stats_out is not None:
            stats_out["completion"] = completion.stats()
    if dump_path:
        with open(dump_path, "wb") as f:
            pickle.dump(all_boxes, f, pickle.HIGHEST_PROTOCOL)
    if all_masks is not None:
        import inspect

        sig = inspect.signature(imdb.evaluate_detections)
        if "all_masks" in sig.parameters:
            results = imdb.evaluate_detections(all_boxes, all_masks=all_masks)
        else:  # dataset without segm support: bbox-only
            logger.warning(
                "%s.evaluate_detections has no all_masks support — "
                "dropping segm results", type(imdb).__name__
            )
            results = imdb.evaluate_detections(all_boxes)
    else:
        results = imdb.evaluate_detections(all_boxes)
    return all_boxes, results


def generate_proposals(
    predictor: Predictor, loader, cfg: Config, dump_path: Optional[str] = None
) -> List[np.ndarray]:
    """Run the RPN over a dataset and keep proposals per image, for the
    alternate-training pipeline and proposal-recall eval.

    Reference: ``rcnn/core/tester.py :: generate_proposals`` (+ the
    ``.pkl`` dump consumed by ``load_proposal_roidb``).
    """
    proposals: List[Optional[np.ndarray]] = [None] * len(loader)
    for idxs, batch, out in pipelined(
        predictor, ((idxs, batch) for idxs, recs, batch in loader.iter_batched())
    ):
        for k, i in enumerate(idxs):
            rois = out["rois"][k]
            valid = out["roi_valid"][k].astype(bool)
            scale = float(batch["im_info"][k][2])
            boxes = rois[valid] / scale
            scores = np.asarray(out["roi_scores"][k])[valid]
            dets = np.hstack([boxes, scores[:, None]]).astype(np.float32)
            proposals[i] = dets
    if dump_path:
        with open(dump_path, "wb") as f:
            pickle.dump(proposals, f, pickle.HIGHEST_PROTOCOL)
    return proposals
