"""int8 weight-only quantization for the serve-graph compression ladder.

The second rung below bf16 (ISSUE 18): every weight matrix/kernel is
stored as int8 with a per-output-channel symmetric scale, and the serve
graph dequantizes on use — ``q.astype(f32) * scale`` runs INSIDE the
jit, on device, as the first op touching each weight.  Activations and
accumulation stay f32 (weight-only quantization), so the numerics are
the f32 graph's with ~2^-7 relative weight error — small enough to pass
the same warmup detection/mask parity gate that guards bf16, which is
exactly the contract: a rung that drifts refuses to serve.

Quantization layout
-------------------

Flax puts the output-channel axis LAST on every kernel this repo builds
(conv ``(kh, kw, in, out)``, dense ``(in, out)``), so the scale is the
per-last-axis absmax over 127 with ``keepdims=True`` — dequantization is
a plain broadcast multiply for any rank.  Only floating leaves with
``ndim >= 2`` quantize (the weights); biases, BN affine/stats, and other
vectors stay f32 untouched — they are a rounding error of the tree's
bytes and per-channel scaling of a 1-D leaf would be a no-op identity
anyway.

A quantized leaf is a plain dict ``{"int8_q": int8[...], "int8_scale":
f32[..., 1-per-channel]}`` — a pytree CONTAINER, not a custom node, so
the quantized tree flattens/maps/device_puts with stock jax utilities
and ``jax.jit`` traces both arrays as ordinary arguments.  The tree's
structure is therefore a pure function of the f32 tree's structure:
the registry's swap-time structure gate (f32 vs f32) remains the single
source of truth, and every runner quantizing the same version gets the
same treedef (compile-cache keys stay stable across hot-swaps).

Scales are computed and folded once at registry load/restore
(:meth:`~mx_rcnn_tpu.serve.registry.ModelRegistry.quantized_tree`
caches per ``(model, version)``), never per replica and never on the
predict path.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

#: the two keys that make a dict a quantized-leaf container — checked
#: exactly (a params sub-dict that happened to carry these names would
#: be a collision; no flax module in this repo names params this way)
QKEYS = frozenset({"int8_q", "int8_scale"})


def is_quantized_leaf(x: Any) -> bool:
    """True for the ``{"int8_q", "int8_scale"}`` container produced by
    :func:`quantize_leaf` (usable as a ``tree_map`` ``is_leaf``)."""
    return isinstance(x, dict) and set(x.keys()) == QKEYS


def quantize_leaf(w: np.ndarray) -> Dict[str, np.ndarray]:
    """One weight array → per-output-channel symmetric int8.

    ``scale[c] = absmax(w[..., c]) / 127`` (keepdims, so dequantization
    broadcasts for any rank); zero channels get scale 1.0 so the
    round-trip is exact zeros instead of 0/0."""
    w = np.asarray(w, np.float32)
    reduce_axes = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=reduce_axes, keepdims=True)
    scale = (amax / 127.0).astype(np.float32)
    scale = np.where(scale > 0.0, scale, np.float32(1.0)).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {"int8_q": q, "int8_scale": scale}


def _should_quantize(leaf: Any) -> bool:
    arr = np.asarray(leaf)
    return arr.ndim >= 2 and np.issubdtype(arr.dtype, np.floating)


def quantize_tree(params: Any) -> Any:
    """f32 params tree → mixed tree: every ``ndim >= 2`` float leaf
    becomes a quantized-leaf container, everything else passes through
    as float32 numpy (host-side — device placement is the caller's job,
    same as the f32 restore path)."""
    import jax

    def q(leaf):
        if _should_quantize(leaf):
            return quantize_leaf(np.asarray(leaf))
        return np.asarray(leaf)

    return jax.tree_util.tree_map(q, params)


def dequantize_tree(params: Any) -> Any:
    """Mixed quantized tree → f32 tree, jit-traceable: inside a jit the
    multiply lowers to one broadcast op per weight, fused by XLA into
    the consuming conv/matmul — this is the serve graph's
    dequantize-on-use."""
    import jax

    def dq(x):
        if is_quantized_leaf(x):
            return x["int8_q"].astype(np.float32) * x["int8_scale"]
        return x

    return jax.tree_util.tree_map(dq, params, is_leaf=is_quantized_leaf)


def quantization_stats(params: Any, qtree: Any) -> Dict[str, Any]:
    """Byte accounting + worst-case round-trip error of a quantized
    tree vs its f32 source — the compression-ladder evidence the bench
    records (int8 rung ≈ 4x smaller weights)."""
    import jax

    f32_bytes = sum(
        int(np.asarray(leaf).nbytes)
        for leaf in jax.tree_util.tree_leaves(params)
    )
    q_bytes = 0
    max_rel_err = 0.0
    quantized = 0
    for leaf in jax.tree_util.tree_leaves(qtree, is_leaf=is_quantized_leaf):
        if is_quantized_leaf(leaf):
            quantized += 1
            q_bytes += int(leaf["int8_q"].nbytes + leaf["int8_scale"].nbytes)
            # per-leaf worst-case |dequant - orig| <= scale/2 by
            # construction; report the bound relative to the leaf absmax
            amax = float(np.max(leaf["int8_scale"]) * 127.0)
            if amax > 0:
                max_rel_err = max(
                    max_rel_err, float(np.max(leaf["int8_scale"])) / 2.0 / amax
                )
        else:
            q_bytes += int(np.asarray(leaf).nbytes)
    return {
        "f32_bytes": f32_bytes,
        "int8_bytes": q_bytes,
        "compression_x": round(f32_bytes / q_bytes, 3) if q_bytes else None,
        "quantized_leaves": quantized,
        "max_rel_round_err_bound": round(max_rel_err, 6),
    }
