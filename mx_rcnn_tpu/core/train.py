"""Trainer: optimizer, parameter freezing, jitted train step.

Reference: the ``MutableModule.fit`` + SGD + KVStore('device') stack of
``train_end2end.py :: train_net`` and ``rcnn/core/module.py`` (SURVEY
§4.1).  TPU-native shape: one pure ``train_step`` (value_and_grad →
element-wise clip → wd → momentum → piecewise lr), jitted per shape
bucket; data parallelism is the same function under ``shard_map`` with a
``psum`` on grads (``mx_rcnn_tpu/parallel``) — the comm backend is the
compiler.

Optimizer semantics follow MXNet SGD:
- gradient clipped element-wise to ±CLIP_GRADIENT (MXNet ``clip_gradient``),
- weight decay added to the gradient *before* momentum (MXNet SGD),
- momentum 0.9, piecewise-constant lr (MultiFactorScheduler),
- frozen params (FIXED_PARAMS) get zero updates via an optax mask.
One knowing deviation: lr is applied *after* the momentum accumulator
(optax.trace then scale), while MXNet folds lr into the momentum update —
at an LR_FACTOR boundary the existing momentum buffer is rescaled by the
new lr here, so the two diverge transiently for ~1/(1-momentum) steps.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Sequence, Tuple

import flax
import jax
import jax.numpy as jnp
import optax

from mx_rcnn_tpu.config import Config


class TrainState(NamedTuple):
    step: jnp.ndarray
    params: Any
    opt_state: Any


def is_frozen_path(path: Tuple[str, ...], fixed_params: Sequence[str]) -> bool:
    """Reference FIXED_PARAMS semantics: freeze whole subtrees by name
    prefix (conv0/stage1/conv1...) plus every BN affine/stat network-wide
    (the reference lists gamma/beta; our FrozenBatchNorm names them
    scale/bias/mean/var under modules containing 'bn')."""
    for comp in path:
        for pat in fixed_params:
            if pat == "bn":
                if "bn" in comp:
                    return True
            elif comp == pat or comp.startswith(pat):
                return True
    # running stats are never trainable regardless of config
    return path[-1] in ("mean", "var")


def make_optimizer(
    cfg: Config,
    lr_schedule: Callable[[jnp.ndarray], jnp.ndarray],
    fixed_params: tuple | None = None,
) -> optax.GradientTransformation:
    """``fixed_params`` overrides the freeze set (stage-2 alternate
    training freezes FIXED_PARAMS_SHARED instead of FIXED_PARAMS)."""
    t = cfg.TRAIN
    fixed = cfg.network.FIXED_PARAMS if fixed_params is None else fixed_params
    sgd = optax.chain(
        optax.clip(t.CLIP_GRADIENT),
        optax.add_decayed_weights(t.WD),
        optax.trace(decay=t.MOMENTUM, nesterov=False),
        optax.scale_by_schedule(lambda step: -lr_schedule(step)),
    )

    def label_fn(params):
        flat = flax.traverse_util.flatten_dict(params)
        labels = {
            k: "frozen" if is_frozen_path(k, fixed) else "train"
            for k in flat
        }
        return flax.traverse_util.unflatten_dict(labels)

    return optax.multi_transform(
        {"train": sgd, "frozen": optax.set_to_zero()}, label_fn
    )


def make_lr_schedule(cfg: Config, steps_per_epoch: int) -> Callable:
    """MultiFactorScheduler twin: lr × LR_FACTOR at each LR_STEP epoch."""
    t = cfg.TRAIN
    boundaries = {
        int(e * steps_per_epoch): t.LR_FACTOR for e in t.LR_STEP_EPOCHS
    }
    return optax.piecewise_constant_schedule(t.LEARNING_RATE, boundaries)


def create_train_state(params, tx: optax.GradientTransformation) -> TrainState:
    return TrainState(jnp.zeros((), jnp.int32), params, tx.init(params))


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    donate: bool = True,
    pmean_axis: str | None = None,
    accum_steps: int = 1,
    fold_step_rng: bool = True,
    steps_per_call: int = 1,
    deterministic: bool = False,
):
    """Build the jitted train step.

    ``pmean_axis``: when running under shard_map/pmap, the named mesh axis
    to average grads/metrics over (the KVStore('device') replacement);
    None for single-chip.

    ``accum_steps`` > 1 splits the batch's leading axis into that many
    microbatches and averages their gradients under ``lax.scan`` before
    the single optimizer update — the big-effective-batch path when
    activations don't fit (the reference had no analog).  With per-image
    ``sample_seeds`` in the batch the update equals the unaccumulated
    step exactly (same linearity argument as DP equivalence).

    ``steps_per_call`` > 1 runs that many FULL optimizer steps under one
    ``lax.scan`` per jit dispatch, over a batch pytree with an extra
    leading ``steps_per_call`` axis (stack per-step batches with
    :func:`stack_batches`).  Exactly equivalent to the same number of
    single-step calls — each scan iteration folds the advancing
    ``state.step`` into the sampling rng — but the host dispatches once
    per K steps.  This is the device-side training loop: on
    relay/tunnel-attached TPUs a dispatch carries ~17 ms of host latency
    (measured: the 0.5 ms SGD update times at 17.5 ms as its own
    dispatch — ``scripts/probe_opt.py``), which K amortizes; it is also
    how a production TPU trainer should run (the host's only per-K-step
    job is feeding the next stacked batch).  Aux metrics come back
    stacked ``[K, ...]`` so per-step logging survives.

    ``fold_step_rng=False`` keeps the sampling rng CONSTANT across steps
    (no fold_in of state.step): with per-image ``sample_seeds`` every
    image's roi/anchor subsample is then identical every step — the
    zero-label-churn ablation mode (scripts/probe_mask_churn.py).

    The returned step additionally accepts an optional ``lr_scale``
    keyword (default None = untouched): a scalar multiplied into the
    final updates, i.e. a one-step effective-LR override.  The guarded
    loop (core/resilience.py) uses it for exponential LR backoff when
    retrying a diverged step; momentum accumulation is deliberately NOT
    rescaled (the retry should damp this step, not rewrite history).
    """
    if steps_per_call > 1 and pmean_axis is not None:
        raise ValueError(
            "steps_per_call > 1 under a pmean_axis is unsupported: "
            "shard_map callers shard the batch's leading axis, which here "
            "would silently be the K-steps axis — keep steps_per_call=1 "
            "under data parallelism until the combo is tested"
        )

    def _grads_and_aux(params, batch, rng):
        def loss_fn(p):
            # batch keys match the model __call__ signature (images,
            # im_info, gt_boxes, gt_valid [, proposals, prop_valid]) so
            # one step builder serves FasterRCNN / RPNOnly / FastRCNN
            loss, aux = model.apply(
                {"params": p}, train=True, rngs={"sampling": rng}, **batch
            )
            return loss, aux

        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        aux = dict(aux)
        aux["loss"] = loss
        return grads, aux

    def step_fn(
        state: TrainState,
        batch: Dict[str, jnp.ndarray],
        rng: jax.Array,
        lr_scale=None,
    ):
        if fold_step_rng:
            rng = jax.random.fold_in(rng, state.step)

        if accum_steps == 1:
            grads, aux = _grads_and_aux(state.params, batch, rng)
        else:
            micro = jax.tree_util.tree_map(
                lambda x: x.reshape((accum_steps, -1) + x.shape[1:]), dict(batch)
            )
            # same DP-equivalence convention as parallel/mesh.py: batches
            # carrying per-image sample_seeds draw identically to the
            # unaccumulated step from ONE shared rng; seedless batches
            # decorrelate microbatches by folding in the index
            if "sample_seeds" in batch:
                rngs = jnp.broadcast_to(
                    jax.random.key_data(rng),
                    (accum_steps,) + jax.random.key_data(rng).shape,
                )
                rngs = jax.vmap(jax.random.wrap_key_data)(rngs)
            else:
                rngs = jax.vmap(lambda i: jax.random.fold_in(rng, i))(
                    jnp.arange(accum_steps)
                )

            def body(_, inp):
                mb, r = inp
                g, aux = _grads_and_aux(state.params, mb, r)
                aux = {k: v.astype(jnp.float32) for k, v in aux.items()}
                return None, (g, aux)

            _, (g_stack, aux_stack) = jax.lax.scan(body, None, (micro, rngs))
            grads = jax.tree_util.tree_map(lambda g: g.mean(0), g_stack)
            aux = jax.tree_util.tree_map(lambda a: a.mean(0), aux_stack)
        if pmean_axis is not None:
            # Under shard_map, params arrive replicated (device-invariant)
            # while the loss is device-varying, so autodiff's transpose
            # rule has ALREADY psum-med the param cotangents across the
            # axis — an explicit pmean here would be a no-op on the sum,
            # silently training with sum-reduced (axis_size×) gradients.
            # Divide by the axis size to get the mean; the exact
            # DP-vs-single-device equality test guards this invariant.
            n = jax.lax.psum(1, pmean_axis)
            grads = jax.tree_util.tree_map(lambda g: g / n, grads)
            aux = jax.lax.pmean(
                {k: v.astype(jnp.float32) for k, v in aux.items()}, pmean_axis
            )
        updates, opt_state = tx.update(grads, state.opt_state, state.params)
        if lr_scale is not None:
            s = jnp.asarray(lr_scale, jnp.float32)
            updates = jax.tree_util.tree_map(
                lambda u: u * s.astype(u.dtype), updates
            )
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(state.step + 1, params, opt_state)
        return new_state, aux

    if steps_per_call > 1:
        def multi_fn(state, batches, rng, lr_scale=None):
            def body(st, mb):
                return step_fn(st, mb, rng, lr_scale)

            return jax.lax.scan(body, state, batches)

        fn = multi_fn
    else:
        fn = step_fn
    if pmean_axis is not None:
        return fn  # caller wraps in shard_map then jit
    jit_kwargs: Dict[str, Any] = {"donate_argnums": (0,) if donate else ()}
    # deterministic=True (CPU): legacy XLA:CPU runtime, whose reductions
    # accumulate serially in a RUN-INDEPENDENT order — the default thunk
    # runtime reassociates across threads, so even the same executable on
    # the same inputs drifts ~1e-7 between calls.  Required wherever two
    # runs must be compared BITWISE (bench.py's pipeline K=1 check);
    # accelerator backends ignore the cpu-namespaced option.
    if deterministic and jax.default_backend() == "cpu":
        jit_kwargs["compiler_options"] = {"xla_cpu_use_thunk_runtime": False}
    return jax.jit(fn, **jit_kwargs)


def stack_batches(batches: Sequence[Dict[str, jnp.ndarray]]) -> Dict[str, Any]:
    """Stack K per-step batches along a new leading axis for a
    ``steps_per_call=K`` train step (host-side numpy stack: the result
    crosses host→device once, as one transfer)."""
    import numpy as np

    return {
        k: np.stack([np.asarray(b[k]) for b in batches])
        for k in batches[0]
    }
