"""Device-resident step pipeline: double-buffered host→device feed,
K-late aux fetch, and a guarded loop that keeps training device-bound.

ROOFLINE.md reconciles the flagship step to 103.9 ms device-busy plus
**~5.4 ms/step of un-hidden host work** — aux fetch, loader hand-off,
dispatch residue.  The reference paper hid that slice behind MXNet's
async dependency engine (``rcnn/core/loader.py``'s prefetching
``AnchorLoader`` + KVStore); our loader stopped at host-side numpy
prefetch and every step blocked on a device→host ``aux`` fetch.  This
module closes the gap with three cooperating pieces:

- :class:`DeviceFeed` — extends the host prefetcher with a second,
  device-facing stage: a worker thread runs ``place_fn`` (sharding- and
  layout-aware ``jax.device_put``) on batch N+1 while the consumer's
  step N executes, keeping ``depth`` batches staged on device.  JAX
  transfers are async, so the H2D copy itself overlaps device compute;
  the staged queue keeps the *dispatch* path free of host assembly too.
  Occupancy counters (staged hits, feed-starved gets) turn "is the feed
  keeping up" into a measured number (``bench.py --pipeline``).
- :class:`AsyncAuxSink` — the non-blocking metrics half: train steps
  return ``aux`` as device arrays and the sink fetches them in one
  batched ``device_get`` per flush instead of one blocking fetch per
  step, counting fetches and fetch *stalls* (a flush that had to wait
  on device results).
- :class:`PipelinedLoop` — :class:`~mx_rcnn_tpu.core.resilience
  .GuardedLoop` semantics with the aux check deferred ``aux_interval``
  steps: the NaN/spike guard still fires, merely K steps late, against
  the retained window snapshot.  On a flagged step the loop rolls back,
  *replays* the verified prefix (deterministic: the sampling rng folds
  ``state.step``, which the rollback restores), retries the poison step
  synchronously through the guard (LR backoff → skip, budgets intact),
  and re-runs the suffix that had executed on the poisoned lineage.
  ``aux_interval=1`` delegates to the guard directly and is
  byte-identical to the synchronous path (pinned by
  ``tests/test_pipeline.py``).

Placement is unified across entry points through :func:`make_place_fn`:
single chip → ``jax.device_put`` (optionally into the compiled step's
input layouts, killing the input relayout copy), DP mesh →
``parallel/mesh.py :: shard_batch``, multi-host →
``parallel/distributed.py :: globalize_batch``.  ``core/fit.py``,
``tools/train_end2end.py``, ``core/tester.py :: pipelined``,
``tools/bench_eval.py`` and ``serve/runner.py`` all draw device-feed
from here.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu.core.resilience import (
    DivergencePolicy,
    GuardedLoop,
    StepWatchdog,
    _supports_lr_scale,
    host_copy,
)
from mx_rcnn_tpu.utils import faults

logger = logging.getLogger(__name__)


# ----------------------------------------------------------------- placement
def make_place_fn(mesh=None, layouts=None) -> Callable[[Any], Any]:
    """One placement path for every feed consumer.

    ``mesh`` None → plain ``jax.device_put`` (into ``layouts`` — a pytree
    of ``jax.experimental.layout.Layout`` matching the batch — when
    given, so the transfer lands in the layout the compiled step expects
    and XLA inserts no input relayout copy).  With a mesh: single
    process shards the leading axis (``shard_batch``); multi-process
    assembles the global array view (``globalize_batch``).
    """
    import jax

    if mesh is not None:
        from mx_rcnn_tpu.parallel import distributed
        from mx_rcnn_tpu.parallel.mesh import shard_batch

        if jax.process_count() > 1:
            return lambda batch: distributed.globalize_batch(batch, mesh)
        return lambda batch: shard_batch(batch, mesh)
    if layouts is not None:
        return lambda batch: jax.device_put(batch, layouts)
    return jax.device_put


def input_layouts_for(jitted, args, argnum: int = 1):
    """The compiled input layouts of ``jitted``'s ``argnum``-th argument.

    ``args`` may be real arrays or ``jax.ShapeDtypeStruct`` trees (no
    data needed — lowering is abstract).  Feeding ``device_put`` these
    layouts makes the host→device transfer deliver device-native tiling
    directly, so XLA stops inserting the input relayout copy that the
    ROOFLINE layout-copy row charges ~1.1 ms/step to.  Returns None when
    the runtime doesn't expose layouts (older jax) or lowering fails —
    callers fall back to plain ``device_put``.
    """
    try:
        compiled = jitted.lower(*args).compile()
        in_args, _kwargs = compiled.input_layouts
        return in_args[argnum]
    except Exception as e:  # noqa: BLE001 — layout feed is best-effort
        logger.debug("input_layouts_for: falling back to plain put (%r)", e)
        return None


def shape_structs(tree):
    """Pytree of arrays → matching ``jax.ShapeDtypeStruct`` tree (for
    abstract lowering in :func:`input_layouts_for`)."""
    import jax

    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype), tree
    )


# ---------------------------------------------------------------- DeviceFeed
class DeviceFeed:
    """Double-buffered host→device staging iterator.

    A daemon worker drains ``source`` and runs ``place_fn`` on each item
    ``depth`` items ahead of the consumer, so batch N+1's H2D transfer
    (async under JAX) overlaps batch N's step.  Composes with the
    loader's own host prefetch thread: decode/assembly → host queue →
    this worker (placement) → staged queue → consumer.

    Lifecycle: sentinel-based shutdown — :meth:`close` (or the context
    manager / GC) wakes the worker, drains staged references, joins the
    thread, and closes the source; worker exceptions re-raise in the
    consumer (a swallowed placement error would silently truncate an
    epoch).  Counters make feed health measurable:

    - ``fed`` — items handed to the consumer;
    - ``staged_hits`` — gets served from an already-staged item (the
      next batch was on device before the previous step retired);
    - ``feed_starved`` / ``feed_starved_after_first`` — gets that had to
      wait on the worker (the first get always waits: nothing has been
      staged yet when the consumer arrives instantly).
    """

    def __init__(
        self,
        source,
        place_fn: Optional[Callable[[Any], Any]] = None,
        depth: int = 2,
        name: str = "device-feed",
    ):
        import jax

        self._source = source
        self._place = place_fn if place_fn is not None else jax.device_put
        self.depth = max(1, int(depth))
        self._q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self._closed = threading.Event()
        self._done = False
        self.fed = 0
        self.staged_hits = 0
        self.feed_starved = 0
        self.feed_starved_after_first = 0
        self._thread = threading.Thread(
            target=self._worker, name=name, daemon=True
        )
        self._thread.start()

    # -- worker side
    def _put(self, msg) -> bool:
        """Bounded put that gives up once the consumer is gone (same
        discipline as the loader's prefetch thread — a plain ``put``
        would park the worker forever on abandonment, leaking the thread
        plus ``depth`` staged batches)."""
        while not self._closed.is_set():
            try:
                self._q.put(msg, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            for item in self._source:
                staged = self._place(item)
                if not self._put(("item", staged)):
                    return
            self._put(("stop", None))
        except BaseException as e:  # noqa: BLE001 — handed to the consumer
            self._put(("err", e))

    # -- consumer side
    def __iter__(self):
        return self

    def __next__(self):
        if self._closed.is_set() or self._done:
            raise StopIteration
        try:
            kind, payload = self._q.get_nowait()
            staged = True
        except queue.Empty:
            staged = False
            while True:
                try:
                    kind, payload = self._q.get(timeout=0.2)
                    break
                except queue.Empty:
                    if self._closed.is_set():
                        raise StopIteration from None
        if kind == "stop":
            self._done = True
            raise StopIteration
        if kind == "err":
            self._done = True
            raise payload
        if staged:
            self.staged_hits += 1
        else:
            self.feed_starved += 1
            if self.fed > 0:
                self.feed_starved_after_first += 1
        self.fed += 1
        return payload

    def wait_staged(self, n: int = 1, timeout: float = 10.0) -> bool:
        """Block until ≥ ``n`` items are staged (or the stream ended /
        timed out).  Lets a consumer give the feed a deterministic head
        start; tests use it to make overlap assertions timing-free."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self._q.qsize() >= n or self._done or not self._thread.is_alive():
                return self._q.qsize() >= n
            time.sleep(0.005)
        return False

    def stats(self) -> Dict[str, Any]:
        fed = max(self.fed, 1)
        return {
            "fed": self.fed,
            "depth": self.depth,
            "staged_hits": self.staged_hits,
            "feed_starved": self.feed_starved,
            "feed_starved_after_first": self.feed_starved_after_first,
            "occupancy": round(self.staged_hits / fed, 4),
        }

    def close(self, timeout: float = 5.0) -> None:
        """Idempotent shutdown: signal the worker, drain staged
        references (frees pinned device buffers), join, close source."""
        self._closed.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread.is_alive():
            self._thread.join(timeout)
        close = getattr(self._source, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 — best-effort source close
                pass

    def __enter__(self) -> "DeviceFeed":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # abandoned without close(): still reclaim
        try:
            self.close(timeout=0.2)
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass


# -------------------------------------------------------------- AsyncAuxSink
class AsyncAuxSink:
    """Batched, non-blocking aux fetcher.

    The synchronous loop pays one device→host fetch per step; the sink
    fetches a whole window of device aux trees in ONE ``device_get`` at
    flush points.  ``fetch_stalls`` counts flushes that had to wait on
    results still materializing (detected via ``Array.is_ready`` where
    the runtime exposes it) and ``fetch_stall_s`` accumulates the wait —
    the per-step host gap becomes a measured, regression-checked number.
    """

    def __init__(self):
        self.pushes = 0  # aux trees deferred instead of fetched
        self.fetches = 0  # batched device_get calls
        self.fetched_trees = 0
        self.fetch_stalls = 0
        self.fetch_stall_s = 0.0

    def defer(self, n: int = 1) -> None:
        self.pushes += n

    @staticmethod
    def _ready(trees) -> bool:
        import jax

        try:
            leaves = jax.tree_util.tree_leaves(trees)
            return all(
                x.is_ready() for x in leaves if hasattr(x, "is_ready")
            )
        except Exception:  # noqa: BLE001 — readiness probe is advisory
            return True

    def fetch(self, trees: List[Any]) -> List[Any]:
        """One batched device→host fetch of ``trees``; returns host
        copies in order."""
        import jax

        if not trees:
            return []
        self.fetches += 1
        self.fetched_trees += len(trees)
        stalled = not self._ready(trees)
        t0 = time.perf_counter()
        out = jax.device_get(list(trees))
        dt = time.perf_counter() - t0
        if stalled:
            self.fetch_stalls += 1
            self.fetch_stall_s += dt
        return out

    def stats(self) -> Dict[str, Any]:
        return {
            "pushes": self.pushes,
            "fetches": self.fetches,
            "fetched_trees": self.fetched_trees,
            "fetch_stalls": self.fetch_stalls,
            "fetch_stall_ms": round(self.fetch_stall_s * 1e3, 3),
        }


# ------------------------------------------------------------- PipelinedLoop
@dataclass
class _Entry:
    idx: int
    batch: Any
    rng: Any
    aux: Any  # device aux tree, unfetched


class PipelinedLoop:
    """Guarded training loop with the aux fetch deferred K steps.

    ``aux_interval=1`` delegates every step to the wrapped
    :class:`GuardedLoop` — byte-identical to the synchronous path.
    ``aux_interval=K>1`` dispatches K steps back-to-back (the device
    never waits on a host fetch between them), then flushes: one batched
    aux fetch, losses checked **in stream order** against the guard's
    EMA/NaN policy.  A flagged step triggers rollback to the window
    snapshot, deterministic replay of the verified prefix, a synchronous
    guarded retry of the poison step (LR backoff → rollback → skip, the
    usual budgets), and a fresh re-run of the suffix that had executed
    on the poisoned lineage — so divergence recovery is merely K steps
    delayed, never weakened.

    ``step_fn`` may donate its input state (the flagship step does):
    every rollback re-places from the host-side window snapshot and no
    state object is ever passed to the device twice
    (``tests/test_pipeline.py`` pins this with real CPU donation).

    Callers must :meth:`flush` at epoch ends and before checkpoints /
    divergence decisions; ``step``/``flush`` return
    ``(state, ready, ok)`` where ``ready`` is a list of
    ``(step_index, host_aux)`` for newly verified steps (empty between
    flush points) and ``ok`` is False when a poison batch was skipped.
    """

    def __init__(
        self,
        step_fn: Callable,
        policy: Optional[DivergencePolicy] = None,
        watchdog: Optional[StepWatchdog] = None,
        snapshot_every: int = 1,
        place_fn: Optional[Callable[[Any], Any]] = None,
        aux_interval: int = 1,
    ):
        self._step_fn = step_fn
        self.aux_interval = max(1, int(aux_interval))
        self.guard = GuardedLoop(
            step_fn,
            policy=policy,
            watchdog=watchdog,
            snapshot_every=snapshot_every,
            place_fn=place_fn,
        )
        self._place = place_fn or (lambda tree: tree)
        self.sink = AsyncAuxSink()
        self._entries: List[_Entry] = []
        self._win_snapshot = None
        self._idx = 0
        # pipeline-specific counters (guard counters stay on self.guard)
        self.window_rollbacks = 0
        self.replayed_steps = 0
        self.flushes = 0

    # -- delegated counters / snapshot surface (watchdog dumps, summaries)
    @property
    def watchdog(self):
        return self.guard.watchdog

    @watchdog.setter
    def watchdog(self, wd):
        self.guard.watchdog = wd

    @property
    def retried_steps(self) -> int:
        return self.guard.retried_steps

    @property
    def rollbacks(self) -> int:
        return self.guard.rollbacks + self.window_rollbacks

    @property
    def skipped_batches(self) -> int:
        return self.guard.skipped_batches

    @property
    def last_loss(self) -> float:
        return self.guard.last_loss

    @property
    def last_snapshot(self):
        if self.aux_interval > 1:
            return self._win_snapshot or self.guard.last_snapshot
        return self.guard.last_snapshot

    @property
    def steps_since_snapshot(self) -> int:
        if self.aux_interval > 1:
            return len(self._entries)
        return self.guard.steps_since_snapshot

    @property
    def pending(self) -> int:
        """Dispatched-but-unverified steps in the current window."""
        return len(self._entries)

    @property
    def next_index(self) -> int:
        """Stream index the next ``step`` call will dispatch at."""
        return self._idx if self.aux_interval > 1 else self.guard.step_index

    # -- elastic mesh-swap surface (parallel/elastic.py)
    def rebind(self, step_fn: Callable,
               place_fn: Optional[Callable[[Any], Any]] = None) -> None:
        """Swap the step/placement functions in place — the elastic loop
        rebuilds both against a shrunken or regrown mesh and the loop
        (and its guard's retry path) must dispatch through the new ones.
        Counters, divergence EMA, and budgets deliberately survive: the
        run continues, only the execution substrate changed."""
        self._step_fn = step_fn
        self.guard._step_fn = step_fn
        self.guard._lr_scale_ok = _supports_lr_scale(step_fn)
        if place_fn is not None:
            self._place = place_fn
            self.guard._place = place_fn

    def rewind(self, idx: int) -> None:
        """Drop every in-flight (unverified) window entry and reset the
        stream coordinate to ``idx``.  Used after a device fault: the
        window's device aux handles belong to the broken mesh and must
        never be fetched; the elastic loop re-places state from ITS host
        anchor snapshot and re-dispatches the window's batches through
        the rebound step, so the coordinates line up again."""
        self._entries = []
        self._win_snapshot = None
        self._idx = idx
        self.guard.step_index = idx
        self.guard._snapshot = None
        self.guard._since_snapshot = 0

    # -- step execution
    def _dispatch(self, state, batch, rng, tag: str):
        wd = self.guard.watchdog
        if wd is not None:
            wd.arm(tag=tag)
        try:
            return self._step_fn(state, batch, rng)
        finally:
            if wd is not None:
                wd.disarm()

    def step(
        self, state: Any, batch: Dict[str, Any], rng: Any
    ) -> Tuple[Any, List[Tuple[int, Dict[str, Any]]], bool]:
        if self.aux_interval <= 1:
            idx = self.guard.step_index
            state, aux, ok = self.guard.step(state, batch, rng)
            return state, ([(idx, aux)] if ok else []), ok
        idx = self._idx
        self._idx += 1
        self.guard.step_index = self._idx  # shared step coordinate space
        if self._win_snapshot is None:
            # BEFORE the first dispatch of a window, as an owning copy:
            # the step may donate the buffers a device_get view aliases
            self._win_snapshot = host_copy(state)
        faults.stall(idx)  # test injection, no-op in production
        state, aux = self._dispatch(state, batch, rng, tag=str(idx))
        self._entries.append(_Entry(idx, batch, rng, aux))
        self.sink.defer()
        if len(self._entries) >= self.aux_interval:
            return self._flush(state)
        return state, [], True

    def flush(
        self, state: Any
    ) -> Tuple[Any, List[Tuple[int, Dict[str, Any]]], bool]:
        """Force a fetch/verify of all pending steps (epoch end,
        checkpoint, explicit divergence check)."""
        if self.aux_interval <= 1 or not self._entries:
            return state, [], True
        return self._flush(state)

    def _flush(self, state):
        self.flushes += 1
        ready: List[Tuple[int, Dict[str, Any]]] = []
        ok = True
        entries, self._entries = self._entries, []
        while entries:
            wd = self.guard.watchdog
            if wd is not None:
                wd.arm(tag=f"flush@{entries[0].idx}")
            try:
                hosts = self.sink.fetch([e.aux for e in entries])
            finally:
                if wd is not None:
                    wd.disarm()
            bad_at, why = -1, ""
            for i, (e, ah) in enumerate(zip(entries, hosts)):
                ah = dict(ah)
                loss = float(np.mean(np.asarray(ah.get("loss", np.nan))))
                loss = faults.corrupt_loss(e.idx, loss)
                ah["loss"] = loss
                bad, why = self.guard.check_loss(loss)
                if bad:
                    bad_at = i
                    break
                self.guard.note_good(loss)
                ready.append((e.idx, ah))
            if bad_at < 0:
                break
            e_bad = entries[bad_at]
            logger.warning(
                "pipelined flush: step %d diverged (%s) — rolling back "
                "the window, replaying %d verified step(s), retrying the "
                "poison step synchronously",
                e_bad.idx, why, bad_at,
            )
            self.window_rollbacks += 1
            state = self._place(self._win_snapshot)
            # deterministic replay of the verified prefix: state.step is
            # restored by the rollback, so the in-graph rng fold
            # reproduces the identical draws — no progress is lost
            for e in entries[:bad_at]:
                state, _ = self._dispatch(state, e.batch, e.rng,
                                          tag=f"replay@{e.idx}")
                self.replayed_steps += 1
            # synchronous guarded retry at the SAME step coordinate so
            # fault injection / logging line up with the stream position
            self.guard.step_index = e_bad.idx
            self.guard._snapshot = None  # guard re-snapshots healthy state
            state, ah, step_ok = self.guard.step(state, e_bad.batch, e_bad.rng)
            self.guard.step_index = self._idx
            if step_ok:
                ready.append((e_bad.idx, ah))
            else:
                ok = False
            # the suffix ran on the poisoned lineage — re-dispatch fresh
            redo, entries = entries[bad_at + 1:], []
            for e in redo:
                state, aux = self._dispatch(state, e.batch, e.rng,
                                            tag=f"redo@{e.idx}")
                self.replayed_steps += 1
                entries.append(_Entry(e.idx, e.batch, e.rng, aux))
        # window verified end-to-end: retain its snapshot for the next one
        self._win_snapshot = host_copy(state)
        return state, ready, ok

    def stats(self) -> Dict[str, Any]:
        return {
            "aux_interval": self.aux_interval,
            "steps": self._idx if self.aux_interval > 1 else self.guard.step_index,
            "flushes": self.flushes,
            "window_rollbacks": self.window_rollbacks,
            "replayed_steps": self.replayed_steps,
            "retried_steps": self.guard.retried_steps,
            "skipped_batches": self.guard.skipped_batches,
            **self.sink.stats(),
        }
