"""Shared stage-training loop for the alternate pipeline tools.

Reference: the per-stage ``train_net`` bodies of
``rcnn/tools/train_rpn.py`` / ``rcnn/tools/train_rcnn.py`` (each rebuilt
the Module.fit plumbing); here one ``fit`` serves every stage graph since
``make_train_step`` dispatches on batch keys.  The end2end CLI keeps its
own richer loop (resume, DP mesh) in ``tools/train_end2end.py``.
"""

from __future__ import annotations

import hashlib
import logging
from typing import Dict, List, Optional

import jax
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.core.metrics import MetricTracker, Speedometer
from mx_rcnn_tpu.core.pipeline import DeviceFeed, PipelinedLoop
from mx_rcnn_tpu.core.resilience import (
    DivergencePolicy,
    StepWatchdog,
    host_copy,
)
from mx_rcnn_tpu.core.train import (
    create_train_state,
    make_lr_schedule,
    make_optimizer,
    make_train_step,
)
from mx_rcnn_tpu.data.loader import TrainLoader

logger = logging.getLogger(__name__)


def merge_params(init_params: Dict, donor: Dict) -> Dict:
    """Copy matching top-level subtrees (backbone/top_head/rpn/rcnn) from
    ``donor`` into a fresh copy of ``init_params``.

    The stage models share subtree names by construction
    (``models/stage_models.py``), so transferring e.g. an RPNOnly
    checkpoint into a FastRCNN init is a dict update on the intersection.
    """
    # host_copy, not device_get: a view of buffers a later donating step
    # reclaims would silently corrupt the merged tree (CPU device_get is
    # zero-copy)
    out = dict(host_copy(init_params))
    for k in out:
        if k in donor:
            out[k] = host_copy(donor[k])
    return out


def batch_digest(batch: Dict[str, np.ndarray]) -> str:
    """Order-stable sha256 over a host batch's keys + array bytes.

    One digest line per consumed batch is the cheap observable the
    preemption/resume integration test compares: a preempted-then-resumed
    run is correct iff its concatenated digest stream equals an
    uninterrupted run's — bit-identical data, in order, no gaps, no
    repeats."""
    h = hashlib.sha256()
    for k in sorted(batch):
        arr = np.asarray(batch[k])
        h.update(k.encode())
        h.update(f"{arr.dtype}{arr.shape}".encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def fit(
    model,
    cfg: Config,
    roidb: List[Dict],
    *,
    epochs: int,
    seed: int = 0,
    proposal_count: int = 0,
    fixed_params: Optional[tuple] = None,
    init_donor: Optional[Dict] = None,
    frequent: int = 20,
    max_steps: int = 0,
    guard_policy: Optional[DivergencePolicy] = None,
    step_timeout: float = 0.0,
    aux_interval: int = 1,
    feed_depth: int = 2,
    prefix: Optional[str] = None,
    resume: bool = False,
    stream_log: Optional[str] = None,
) -> Dict:
    """Train ``model`` on ``roidb`` and return the final params.

    ``init_donor``: param tree whose matching subtrees seed the init
    (pretrained backbone / previous stage).  ``fixed_params``: freeze-set
    override (FIXED_PARAMS_SHARED for stage-2).

    Every step runs under a :class:`PipelinedLoop` (``guard_policy``
    overrides the divergence defaults): a NaN/Inf or spiking loss is
    retried with LR backoff, then rolled back and the poison batch
    skipped, instead of the pre-resilience behavior of finishing the
    whole run and *warning* about the destroyed loss at the end.
    ``step_timeout`` > 0 additionally arms a watchdog that aborts a hung
    step with :data:`~mx_rcnn_tpu.core.resilience.WATCHDOG_EXIT_CODE`.

    Batches reach the device through a :class:`DeviceFeed` of depth
    ``feed_depth`` (batch N+1's transfer overlaps step N) and the train
    step donates its input state.  ``aux_interval`` > 1 defers the aux
    fetch K steps (flushed at epoch end); the default 1 keeps the
    per-step check byte-identical to the synchronous loop.

    ``prefix`` enables checkpointing (epoch-boundary saves + prune) and
    installs a :class:`~mx_rcnn_tpu.core.checkpoint.PreemptionGuard`:
    SIGTERM/SIGINT flushes the pipeline, writes a committed mid-epoch
    ``step_E_B`` dump, and returns early.  ``resume=True`` restores the
    newest restorable checkpoint under ``prefix`` and continues the
    exact batch stream (the loader's deterministic per-(seed, epoch)
    plan plus ``skip_batches``).  ``stream_log`` appends one
    ``epoch batch digest`` line per consumed batch — the observable the
    resume integration test compares bit-for-bit.
    """
    loader = TrainLoader(
        roidb, cfg, cfg.TRAIN.BATCH_IMAGES,
        shuffle=cfg.TRAIN.SHUFFLE, seed=seed,
        proposal_count=proposal_count,
    )
    steps_per_epoch = max(len(loader), 1)
    # init batch built directly — peeking the loader's iterator would leak
    # its prefetch thread and consume the epoch-0 shuffle plan
    from mx_rcnn_tpu.data.loader import _orientation_bucket, make_batch

    first = [roidb[0]] * cfg.TRAIN.BATCH_IMAGES  # one record: shapes only
    batch0 = make_batch(
        first, cfg, _orientation_bucket(first[0], cfg.SHAPE_BUCKETS),
        proposal_count=proposal_count, seeds=list(range(len(first))),
        with_masks=cfg.network.USE_MASK,
    )
    params = model.init(
        {"params": jax.random.key(seed), "sampling": jax.random.key(seed + 1)},
        train=True,
        **batch0,
    )["params"]
    if init_donor is not None:
        params = merge_params(params, init_donor)

    tx = make_optimizer(
        cfg, make_lr_schedule(cfg, steps_per_epoch), fixed_params=fixed_params
    )
    state = create_train_state(params, tx)

    begin_epoch, begin_batch = 0, 0
    if prefix and resume:
        from mx_rcnn_tpu.core.checkpoint import load_restorable

        got = load_restorable(prefix, state)
        if got is not None:
            (begin_epoch, begin_batch), state = got
            logger.info(
                "fit: resuming from epoch %d batch %d", begin_epoch,
                begin_batch,
            )

    # donation unified with the end2end/mesh entry points: rollback
    # re-places from the guard's host snapshot, never a donated buffer
    step_fn = make_train_step(model, tx, donate=True)
    rng = jax.random.key(seed + 123)

    tracker = MetricTracker()
    speedo = Speedometer(cfg.TRAIN.BATCH_IMAGES, frequent)
    watchdog = StepWatchdog(step_timeout) if step_timeout > 0 else None
    pipeline = PipelinedLoop(
        step_fn, policy=guard_policy, watchdog=watchdog,
        aux_interval=aux_interval,
    )

    def deliver(ready):
        for _idx, aux in ready:
            tracker.update({k: float(v) for k, v in aux.items()})

    guard = None
    log_f = open(stream_log, "a") if stream_log else None
    if prefix:
        from mx_rcnn_tpu.core.checkpoint import (
            PreemptionGuard,
            prune_step_checkpoints,
            save_checkpoint,
        )

        guard = PreemptionGuard()
    loader.epoch = begin_epoch
    loader.skip_batches = begin_batch

    total_steps = 0
    preempted = False
    try:
        for epoch in range(begin_epoch, epochs):
            # position within the epoch's deterministic plan (resume skips
            # the first skip_batches entries, so enumeration is offset)
            pos = begin_batch if epoch == begin_epoch else 0
            feed = DeviceFeed(iter(loader), depth=feed_depth)
            try:
                for batch in feed:
                    if log_f is not None:
                        line = f"{epoch} {pos} {batch_digest(batch)}\n"
                        log_f.write(line)
                        log_f.flush()
                    state, ready, _ok = pipeline.step(state, batch, rng)
                    deliver(ready)
                    total_steps += 1
                    pos += 1
                    speedo(epoch, total_steps, tracker)
                    if guard is not None and guard.should_stop:
                        preempted = True
                        break
                    if max_steps and total_steps >= max_steps:
                        break
            finally:
                feed.close()
            state, ready, _ok = pipeline.flush(state)
            deliver(ready)
            if preempted:
                if pos > 0:
                    save_checkpoint(prefix, state, epoch, pos)
                    logger.warning(
                        "fit: preempted — saved step checkpoint at epoch "
                        "%d batch %d", epoch, pos,
                    )
                break
            if max_steps and total_steps >= max_steps:
                break
            if prefix:
                save_checkpoint(prefix, state, epoch + 1)
                prune_step_checkpoints(prefix, epoch)
    finally:
        if guard is not None:
            guard.uninstall()
        if log_f is not None:
            log_f.close()
    last_loss = pipeline.last_loss if total_steps else float("nan")
    logger.info("fit done: %d steps, last loss %.4f", total_steps, last_loss)
    if pipeline.skipped_batches:
        logger.warning(
            "fit skipped %d poison batch(es) after rollback "
            "(%d retried steps)",
            pipeline.skipped_batches, pipeline.retried_steps,
        )
    if total_steps and not np.isfinite(last_loss):
        logger.warning("fit finished with non-finite loss")
    # owning copy: the caller's tree must survive this state's buffers
    # (the next alternate stage donates its own state into reused memory)
    return host_copy(state.params)
