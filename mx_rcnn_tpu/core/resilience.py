"""Resilience layer: guarded train loop, retry policy, step watchdog.

The reference framework had no failure recovery at all (SURVEY §5.4: a
GPU failure killed the run; restart was manual from the last epoch), and
the preemption-only story here left three live gaps: a non-finite loss
was only *warned about* after the run was destroyed, a hung step stalled
until an external ``timeout -k`` (the exact ``MULTICHIP_r04`` rc=124
failure), and nothing rolled training back past a poison batch.  This
module closes them:

- :class:`RetryPolicy` — deterministic (jitter-free) bounded retry,
  shared by the guarded loop and the data loader.
- :class:`GuardedLoop` — wraps a train ``step_fn``; per-step finite-loss
  and loss-spike checks on the already-fetched aux, retry with
  exponential LR backoff, rollback to the last good in-memory snapshot,
  and skip-forward past the poison batch, with a bad-batch budget so
  silent divergence can't masquerade as training.
- :class:`StepWatchdog` — wall-clock timer per step; on expiry dumps the
  last good snapshot as a resumable checkpoint and aborts the process
  with :data:`WATCHDOG_EXIT_CODE` (distinct from ``timeout``'s 124), so
  the scheduler can tell "hung step" from "killed externally".

Fault injection for all of these lives in ``mx_rcnn_tpu/utils/faults.py``
(env-driven, deterministic); ``tests/test_resilience.py`` exercises every
recovery path on CPU.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from mx_rcnn_tpu.utils import faults

logger = logging.getLogger(__name__)

# exit status of a watchdog abort.  75 = EX_TEMPFAIL ("try again later"):
# the run dumped a resumable checkpoint, so a supervisor should restart
# with --resume.  Distinct from timeout(1)'s 124 and the test harness's 70.
WATCHDOG_EXIT_CODE = 75

# exit status of a run that COMPLETED but on a shrunken mesh (elastic
# degraded-continue: a replica was lost mid-run and never regrew).  The
# work finished — checkpoints are valid — but throughput and the
# effective global batch were reduced, so a supervisor may want to
# reschedule at full size.  Distinct from 75 ("restart me") and 0.
DEGRADED_EXIT_CODE = 76


class TrainingDiverged(RuntimeError):
    """Raised when the bad-batch budget is exhausted: the run is not
    recovering by skipping, so continuing would silently train garbage."""


def host_copy(tree):
    """Host-side snapshot of ``tree`` that OWNS its memory.

    ``jax.device_get`` on the CPU backend returns zero-copy numpy VIEWS
    of the runtime buffers.  A donating train step hands exactly those
    buffers back to XLA for reuse, so a snapshot (or a returned param
    tree) taken as a bare ``device_get`` silently mutates under the
    caller — or segfaults once the buffer is unmapped.  Every host tree
    that must outlive the device state (rollback snapshots, ``fit``'s
    returned params, best-checkpoint captures) goes through this copy;
    re-placement is safe because ``device_put`` copies host memory.
    """
    import jax

    return jax.tree_util.tree_map(np.array, jax.device_get(tree))


@dataclass(frozen=True)
class RetryPolicy:
    """Bounded deterministic retry — no jitter by design, so a replayed
    run retries at the identical points and the fault-injection tests are
    exactly reproducible.

    ``tries`` is the total attempt count; ``delay`` sleeps before retry
    ``k`` for ``delay * backoff**(k-1)`` seconds (0 = no sleep, the
    default: loader retries are disk/NFS hiccups where immediate retry is
    right, and tests must not sleep).
    """

    tries: int = 3
    delay: float = 0.0
    backoff: float = 2.0

    def run(self, fn: Callable[[int], Any]) -> Any:
        """Call ``fn(attempt)`` until it returns; re-raise the last
        exception once ``tries`` attempts failed."""
        for attempt in range(max(1, self.tries)):
            try:
                return fn(attempt)
            except Exception:
                if attempt + 1 >= max(1, self.tries):
                    raise
                if self.delay:
                    time.sleep(self.delay * self.backoff**attempt)


# The one retry tuning surface (ISSUE 6 satellite): every bounded-retry
# site — the loader's record reads, the serving engine's single-runner
# batch retry, and a pool replica's in-place predict retry — constructs
# its policy here, so serve and train faults share one set of constants
# instead of the per-module literals they used to duplicate.
#
# "replica" is deliberately tighter than "serve": a pooled dispatch that
# keeps failing should fail over to ANOTHER replica (the router's job)
# rather than burn its latency budget retrying in place.
RETRY_PRESETS: Dict[str, RetryPolicy] = {
    "loader": RetryPolicy(tries=3, delay=0.0),
    "serve": RetryPolicy(tries=3, delay=0.0),
    "replica": RetryPolicy(tries=2, delay=0.0),
}


def make_retry_policy(kind: str, **overrides) -> RetryPolicy:
    """Preset :class:`RetryPolicy` by site kind, with per-call field
    overrides (``make_retry_policy("replica", tries=1)``)."""
    import dataclasses

    base = RETRY_PRESETS[kind]
    return dataclasses.replace(base, **overrides) if overrides else base


class StepWatchdog:
    """Wall-clock guard for a single train step.

    Arm before the step, disarm after; if the step wedges (device hang,
    deadlocked collective), the timer thread dumps the caller-provided
    checkpoint and ``os._exit``s with a distinct code instead of hanging
    until an external ``timeout -k`` (MULTICHIP_r04's rc=124).  A thread
    timer rather than SIGALRM: the signal would only be delivered at a
    Python bytecode boundary, which never comes while the main thread is
    wedged inside native XLA code (same reasoning as the test harness
    watchdog in ``tests/conftest.py``).

    ``dump_fn`` runs in the timer thread and must not touch the (possibly
    wedged) device — dump a host-side snapshot, not live device state.
    """

    def __init__(
        self,
        timeout: float,
        dump_fn: Optional[Callable[[], Any]] = None,
        exit_code: int = WATCHDOG_EXIT_CODE,
        exit_fn: Optional[Callable[[int], None]] = None,
    ):
        import os

        self.timeout = float(timeout)
        self.dump_fn = dump_fn
        self.exit_code = exit_code
        self._exit = exit_fn if exit_fn is not None else os._exit
        self._timer: Optional[threading.Timer] = None

    def arm(self, tag: str = "") -> None:
        self.disarm()
        t = threading.Timer(self.timeout, self._expired, args=(tag,))
        t.daemon = True
        t.start()
        self._timer = t

    def disarm(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None

    def _expired(self, tag: str) -> None:
        import faulthandler

        sys.stderr.write(
            f"\n=== StepWatchdog: step {tag or '?'} exceeded "
            f"{self.timeout:.1f}s — dumping checkpoint and aborting "
            f"(exit {self.exit_code}) ===\n"
        )
        try:
            if self.dump_fn is not None:
                path = self.dump_fn()
                if path:
                    sys.stderr.write(f"watchdog checkpoint -> {path}\n")
        except Exception as e:  # noqa: BLE001 — must still exit
            sys.stderr.write(f"watchdog checkpoint dump failed: {e!r}\n")
        faulthandler.dump_traceback(file=sys.stderr)
        sys.stderr.flush()
        self._exit(self.exit_code)


@dataclass(frozen=True)
class DivergencePolicy:
    """What the guarded loop does when a step's loss is NaN/Inf or spikes
    above ``spike_factor ×`` the running EMA.

    A bad step is retried ``retries`` times from the last snapshot, each
    retry with a fresh sampling rng and the step's effective LR scaled by
    ``lr_backoff**attempt`` (exponential backoff; a transient spike from
    a hard batch usually survives a smaller step).  Retries exhausted →
    roll back to the last good snapshot and skip the poison batch; the
    data stream continues past it.  More than ``max_bad_batches`` skips
    raise :class:`TrainingDiverged` — bounded data loss, never silent.
    """

    retries: int = 2
    lr_backoff: float = 0.5
    spike_factor: float = 20.0
    ema_decay: float = 0.9
    warmup_steps: int = 5
    max_bad_batches: int = 8


def _supports_lr_scale(fn) -> bool:
    import inspect

    try:
        return "lr_scale" in inspect.signature(fn).parameters
    except (TypeError, ValueError):
        return False


class GuardedLoop:
    """Wrap a functional train step with divergence recovery.

    Usage (both ``core/fit.py`` and ``tools/train_end2end.py``)::

        guard = GuardedLoop(step_fn, policy=DivergencePolicy(), ...)
        for batch in loader:
            state, aux, ok = guard.step(state, batch, rng)
            if not ok:      # batch skipped after rollback
                continue    # aux/state are from the rolled-back point

    ``step_fn(state, batch, rng[, lr_scale])`` may donate its input state
    (the flagship step does), so rollback cannot simply reuse the caller's
    ``state`` — the loop keeps a host-side snapshot refreshed every
    ``snapshot_every`` accepted steps and restores from it.  A rollback
    therefore loses at most ``snapshot_every - 1`` steps of progress; the
    default of 1 is exact (and cheap on CPU); raise it on relay-attached
    TPUs where a full-state device→host fetch per step is the bottleneck.

    ``place_fn`` re-places a host snapshot for the device step (e.g.
    ``lambda t: replicate(t, mesh)`` under data parallelism; the default
    hands numpy arrays straight to jit, which commits them itself).
    """

    def __init__(
        self,
        step_fn: Callable,
        policy: Optional[DivergencePolicy] = None,
        watchdog: Optional[StepWatchdog] = None,
        snapshot_every: int = 1,
        place_fn: Optional[Callable[[Any], Any]] = None,
    ):
        self._step_fn = step_fn
        self.policy = policy or DivergencePolicy()
        self.watchdog = watchdog
        self.snapshot_every = max(1, int(snapshot_every))
        self._place = place_fn or (lambda tree: tree)
        self._lr_scale_ok = _supports_lr_scale(step_fn)
        self._snapshot = None
        self._since_snapshot = 0
        self._ema: Optional[float] = None
        self._seen = 0
        # counters (read by callers / tests)
        self.step_index = 0
        self.retried_steps = 0
        self.rollbacks = 0
        self.skipped_batches = 0
        self.last_loss = float("nan")

    @property
    def last_snapshot(self):
        """Newest host-side good state — what the watchdog dumps."""
        return self._snapshot

    @property
    def steps_since_snapshot(self) -> int:
        """Accepted steps since the snapshot was taken — lets a watchdog
        dump name the stream position the snapshot actually corresponds
        to (resume re-consumes, never silently skips ahead)."""
        return self._since_snapshot

    def _is_bad(self, loss: float) -> Tuple[bool, str]:
        if not np.isfinite(loss):
            return True, "non-finite"
        if (
            self._ema is not None
            and self._seen >= self.policy.warmup_steps
            and loss > self.policy.spike_factor * self._ema
        ):
            return True, f"spike {loss:.4g} > {self.policy.spike_factor}x ema {self._ema:.4g}"
        return False, ""

    # The check/accept pair is public so core/pipeline.py's deferred
    # flush applies the IDENTICAL divergence policy K steps late.
    def check_loss(self, loss: float) -> Tuple[bool, str]:
        """Divergence check against the current EMA/warmup state; returns
        ``(bad, reason)`` without mutating anything."""
        return self._is_bad(loss)

    def note_good(self, loss: float) -> None:
        """Record an accepted loss: advance the EMA, warmup counter, and
        snapshot age exactly as an accepted in-loop step would."""
        self._seen += 1
        self._since_snapshot += 1
        self._ema = (
            loss
            if self._ema is None
            else self.policy.ema_decay * self._ema
            + (1.0 - self.policy.ema_decay) * loss
        )
        self.last_loss = loss

    def step(
        self, state: Any, batch: Dict[str, Any], rng: Any
    ) -> Tuple[Any, Dict[str, Any], bool]:
        """Run one guarded step.  Returns ``(state, host_aux, accepted)``;
        on a skipped (poison) batch, ``state`` is the rolled-back state
        and ``accepted`` is False."""
        import jax

        idx = self.step_index
        self.step_index += 1
        if self._snapshot is None or self._since_snapshot >= self.snapshot_every:
            # BEFORE the step, and as an owning copy: the step may donate
            # these buffers, and a device_get view would alias them
            self._snapshot = host_copy(state)
            self._since_snapshot = 0

        aux_host: Dict[str, Any] = {}
        try:
            if self.watchdog is not None:
                self.watchdog.arm(tag=str(idx))
            for attempt in range(self.policy.retries + 1):
                if attempt == 0:
                    a_state, a_rng = state, rng
                else:
                    # fresh in-graph sampling draw; restart from snapshot
                    # (the failed attempt may have consumed donated buffers)
                    a_state = self._place(self._snapshot)
                    a_rng = jax.random.fold_in(rng, 7919 + attempt)
                kwargs = {}
                if attempt > 0 and self._lr_scale_ok:
                    kwargs["lr_scale"] = self.policy.lr_backoff**attempt
                faults.stall(idx)
                new_state, aux = self._step_fn(a_state, batch, a_rng, **kwargs)
                aux_host = dict(jax.device_get(aux))
                loss = float(np.mean(np.asarray(aux_host.get("loss", np.nan))))
                loss = faults.corrupt_loss(idx, loss)
                aux_host["loss"] = loss
                bad, why = self._is_bad(loss)
                if not bad:
                    self.note_good(loss)
                    return new_state, aux_host, True
                self.retried_steps += 1
                logger.warning(
                    "guarded step %d attempt %d diverged (%s)%s",
                    idx, attempt, why,
                    "" if attempt >= self.policy.retries
                    else f" — retrying with lr x{self.policy.lr_backoff**(attempt + 1):g}",
                )
        finally:
            if self.watchdog is not None:
                self.watchdog.disarm()

        # retries exhausted: roll back and skip the poison batch
        self.rollbacks += 1
        self.skipped_batches += 1
        logger.error(
            "guarded step %d: retries exhausted — rolling back to last "
            "snapshot and skipping the batch (%d/%d skips used)",
            idx, self.skipped_batches, self.policy.max_bad_batches,
        )
        if self.skipped_batches > self.policy.max_bad_batches:
            raise TrainingDiverged(
                f"{self.skipped_batches} batches skipped after rollback "
                f"(budget {self.policy.max_bad_batches}) — loss is not "
                f"recovering; aborting instead of silently training garbage"
            )
        return self._place(self._snapshot), aux_host, False
