"""Synthetic dataset: deterministic random images + boxes, no files.

No reference twin — this is the rebuild's "fake backend" for tests,
smoke-training, and benchmarking in environments without VOC/COCO on disk
(SURVEY §5.1's do-better-cheaply test strategy).  Images are generated in
memory with colored shapes on noise so a detector can genuinely overfit
them; boxes are the shape bounding boxes.

``with_masks=True`` additionally emits COCO-style polygon
``segmentation`` gts (ellipses / triangles / rectangles inscribed in
each box) and renders the POLYGON region, not the box — the visual
signal matches the mask gt, so a Mask R-CNN head can genuinely learn
non-rectangular shapes and the segm eval stack can be gated end-to-end.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.imdb import IMDB


def class_color(cls: int) -> np.ndarray:
    """Saturated, well-separated class color: every class must be clearly
    distinguishable from the 90-150 gray noise background AND from every
    other class, or overfit gates hit an invisible-object mAP ceiling.
    Golden-ratio hue spacing keeps arbitrary class counts distinct."""
    hue = ((cls - 1) * 0.61803398875) % 1.0
    i = int(hue * 6.0)
    f = hue * 6.0 - i
    v, s = 235.0, 0.85
    p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    rgb = [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v), (v, p, q)][i % 6]
    return np.asarray(rgb, np.float32)


def shape_polygon(kind: str, box, t: float = 0.5) -> List[float]:
    """One polygon ([x1, y1, x2, y2, ...] continuous coords) of ``kind``
    inscribed in ``box`` (inclusive pixel indices) with a tight bbox.

    ``t`` ∈ (0, 1) parameterizes the triangle apex position.
    """
    x1, y1, x2, y2 = (float(v) for v in box[:4])
    # continuous extents: pixel p covers [p, p+1)
    cx2, cy2 = x2 + 1.0, y2 + 1.0
    if kind == "rect":
        return [x1, y1, cx2, y1, cx2, cy2, x1, cy2]
    if kind == "triangle":
        apex_x = x1 + t * (cx2 - x1)
        return [x1, cy2, cx2, cy2, apex_x, y1]
    # ellipse inscribed in the box (24-gon approximation)
    mx, my = (x1 + cx2) / 2.0, (y1 + cy2) / 2.0
    rx, ry = (cx2 - x1) / 2.0, (cy2 - y1) / 2.0
    th = np.linspace(0.0, 2.0 * np.pi, 24, endpoint=False)
    pts = np.stack([mx + rx * np.cos(th), my + ry * np.sin(th)], axis=1)
    return pts.reshape(-1).tolist()


def synthetic_image(rec: Dict, seed: int) -> np.ndarray:
    """Render the record: noise background + filled class-colored shapes.

    Renders from the record's OWN (possibly flipped) geometry — the
    loader must NOT flip the result again (see
    ``data/loader.py::_load_record_image``): flipping an image rendered
    from already-flipped boxes would cancel out and desynchronize pixels
    from gt.
    """
    rng = np.random.RandomState(seed)
    h, w = rec["height"], rec["width"]
    im = rng.rand(h, w, 3).astype(np.float32) * 60.0 + 90.0
    segms = rec.get("segmentation")
    for i, (box, cls) in enumerate(zip(rec["boxes"], rec["gt_classes"])):
        x1, y1, x2, y2 = box.astype(int)
        color = class_color(int(cls))
        block = color + rng.rand(y2 - y1 + 1, x2 - x1 + 1, 3).astype(np.float32) * 10.0
        segm = segms[i] if segms is not None else None
        if segm is None:
            im[y1 : y2 + 1, x1 : x2 + 1] = block
        else:
            from mx_rcnn_tpu.native import rle as rlelib

            full = rlelib.decode(rlelib.from_polygons(segm, h, w))
            m = full[y1 : y2 + 1, x1 : x2 + 1].astype(bool)
            region = im[y1 : y2 + 1, x1 : x2 + 1]
            region[m] = block[m]
    return im


def moving_scene(
    stream_seed: int,
    num_frames: int,
    image_size=(480, 640),
    num_objects: int = 3,
    num_classes: int = 21,
    max_step: float = 8.0,
    with_masks: bool = False,
) -> List[Dict]:
    """Deterministic moving scene for streaming serve (ISSUE 20): one
    record per frame, same objects throughout, constant per-object
    velocity with elastic bounces off the canvas edges.

    Each record is ``synthetic_image``-renderable (its
    ``synthetic_seed`` is a pure function of ``(stream_seed, frame)``,
    so frame pixels are reproducible independently) and roidb-shaped
    (``boxes``/``gt_classes``/``height``/``width``), so the priming
    sweep can feed it straight to ``eval/recall.py::proposal_recall``.
    Frame-to-frame box displacement is bounded by ``max_step`` pixels —
    the temporal coherence that makes frame N−1's detections a useful
    proposal seed for frame N."""
    rng = np.random.RandomState(stream_seed)
    h, w = image_size
    sizes, vels, pos, classes, kinds = [], [], [], [], []
    for _ in range(num_objects):
        bw = rng.randint(60, w // 2)
        bh = rng.randint(60, h // 2)
        sizes.append((bw, bh))
        pos.append((
            float(rng.randint(0, w - bw)), float(rng.randint(0, h - bh))
        ))
        # uniform speed in [max_step/2, max_step], uniform heading —
        # every object genuinely moves (a zero-velocity draw would make
        # priming trivially perfect on that object)
        speed = rng.uniform(max_step / 2.0, max_step)
        theta = rng.uniform(0.0, 2.0 * np.pi)
        vels.append((speed * np.cos(theta), speed * np.sin(theta)))
        classes.append(int(rng.randint(1, num_classes)))
        kinds.append(("ellipse", "triangle", "rect")[rng.randint(3)])
    tris = [rng.uniform(0.25, 0.75) for _ in range(num_objects)]
    frames = []
    pos = [list(p) for p in pos]
    vels = [list(v) for v in vels]
    for f in range(num_frames):
        boxes, segms = [], []
        for i, (bw, bh) in enumerate(sizes):
            x, y = pos[i]
            x1, y1 = int(round(x)), int(round(y))
            box = [x1, y1, x1 + bw - 1, y1 + bh - 1]
            boxes.append(box)
            if with_masks:
                segms.append([shape_polygon(kinds[i], box, t=tris[i])])
            # advance + bounce (reflect position AND velocity so the
            # object stays fully inside the canvas)
            for axis, extent, size in ((0, w, bw), (1, h, bh)):
                p = pos[i][axis] + vels[i][axis]
                if p < 0:
                    p = -p
                    vels[i][axis] = -vels[i][axis]
                hi = extent - size
                if p > hi:
                    p = 2 * hi - p
                    vels[i][axis] = -vels[i][axis]
                pos[i][axis] = p
        rec = {
            "image": f"synthetic://stream{stream_seed}/{f}",
            "height": h,
            "width": w,
            "boxes": np.asarray(boxes, np.float32),
            "gt_classes": np.asarray(classes, np.int32),
            "flipped": False,
            "frame": f,
            "synthetic_seed": stream_seed * 100003 + f,
        }
        if with_masks:
            rec["segmentation"] = segms
        frames.append(rec)
    return frames


class SyntheticDataset(IMDB):
    def __init__(
        self,
        num_images: int = 32,
        num_classes: int = 21,
        image_size=(480, 640),
        max_boxes: int = 4,
        seed: int = 0,
        with_masks: bool = False,
    ):
        super().__init__(f"synthetic_{num_images}", root_path="/tmp")
        self.classes = ["__background__"] + [
            f"class{i}" for i in range(1, num_classes)
        ]
        self.image_set_index = list(range(num_images))
        self.seed = seed
        self.image_size = image_size
        self.max_boxes = max_boxes
        self.with_masks = with_masks

    def gt_roidb(self) -> List[Dict]:
        rng = np.random.RandomState(self.seed)
        h, w = self.image_size
        roidb = []
        for i in self.image_set_index:
            n = rng.randint(1, self.max_boxes + 1)
            boxes, classes, segms = [], [], []
            for _ in range(n):
                bw = rng.randint(60, w // 2)
                bh = rng.randint(60, h // 2)
                x1 = rng.randint(0, w - bw)
                y1 = rng.randint(0, h - bh)
                box = [x1, y1, x1 + bw - 1, y1 + bh - 1]
                boxes.append(box)
                classes.append(rng.randint(1, self.num_classes))
                if self.with_masks:
                    kind = ("ellipse", "triangle", "rect")[rng.randint(3)]
                    segms.append(
                        [shape_polygon(kind, box, t=rng.uniform(0.25, 0.75))]
                    )
            rec = {
                "image": f"synthetic://{i}",
                "height": h,
                "width": w,
                "boxes": np.asarray(boxes, np.float32),
                "gt_classes": np.asarray(classes, np.int32),
                "flipped": False,
                "synthetic_seed": self.seed + 1000 + i,
            }
            if self.with_masks:
                rec["segmentation"] = segms
            roidb.append(rec)
        return roidb

    def as_coco_dict(self) -> Dict:
        """COCO-format instances dict over the synthetic gt — feeds the
        reimplemented COCOeval so the Mask R-CNN gate runs the REAL segm
        protocol (polygon gt → RLE IoU → 12 metrics) end-to-end."""
        roidb = self.gt_roidb()
        images, annotations = [], []
        ann_id = 1
        for i, rec in enumerate(roidb):
            images.append(
                {"id": i, "height": rec["height"], "width": rec["width"]}
            )
            segms = rec.get("segmentation")
            for j, (box, cls) in enumerate(zip(rec["boxes"], rec["gt_classes"])):
                x1, y1, x2, y2 = (float(v) for v in box)
                ann = {
                    "id": ann_id,
                    "image_id": i,
                    "category_id": int(cls),
                    "bbox": [x1, y1, x2 - x1 + 1.0, y2 - y1 + 1.0],
                    "area": (x2 - x1 + 1.0) * (y2 - y1 + 1.0),
                    "iscrowd": 0,
                }
                if segms is not None:
                    from mx_rcnn_tpu.native import rle as rlelib

                    ann["segmentation"] = segms[j]
                    # protocol: segm area-range bucketing uses the MASK
                    # area, not the box area (a thin triangle can land
                    # in a smaller bucket than its box)
                    ann["area"] = rlelib.area(
                        rlelib.from_polygons(
                            segms[j], rec["height"], rec["width"]
                        )
                    )
                annotations.append(ann)
                ann_id += 1
        return {
            "images": images,
            "annotations": annotations,
            "categories": [
                {"id": c, "name": self.classes[c]}
                for c in range(1, self.num_classes)
            ],
        }

    def evaluate_detections(self, detections, all_masks=None, **kw):
        """VOC-style box mAP against the synthetic gt (integral metric);
        with ``all_masks`` additionally runs the COCO segm protocol and
        reports its stats under ``segm_*`` keys."""
        from mx_rcnn_tpu.eval.voc_eval import voc_eval

        roidb = self.gt_roidb()
        annots = {
            i: {"boxes": r["boxes"], "gt_classes": r["gt_classes"]}
            for i, r in enumerate(roidb)
        }
        aps = {}
        for cls_idx in range(1, self.num_classes):
            # classes absent from the gt have undefined AP and are skipped;
            # classes WITH gt but no detections score 0 (they must count
            # against mAP or a near-blind model would look good)
            if not any((r["gt_classes"] == cls_idx).any() for r in roidb):
                continue
            dets_by_img = {
                i: detections[cls_idx][i] for i in range(len(roidb))
            }
            _, _, ap = voc_eval(dets_by_img, annots, cls_idx, 0.5, False)
            aps[f"class{cls_idx}"] = ap
        vals = [v for v in aps.values()]
        aps["mAP"] = float(np.mean(vals)) if vals else 0.0

        if all_masks is not None:
            from mx_rcnn_tpu.eval.coco_eval import COCOEvalBbox

            results = []
            for cls_idx in range(1, self.num_classes):
                for i in range(len(roidb)):
                    dets = np.asarray(detections[cls_idx][i]).reshape(-1, 5)
                    for d, (x1, y1, x2, y2, score) in enumerate(dets):
                        results.append(
                            {
                                "image_id": i,
                                "category_id": cls_idx,
                                "bbox": [
                                    float(x1),
                                    float(y1),
                                    float(x2 - x1 + 1),
                                    float(y2 - y1 + 1),
                                ],
                                "score": float(score),
                                "segmentation": all_masks[cls_idx][i][d],
                            }
                        )
            segm_stats = COCOEvalBbox(
                self.as_coco_dict(), results, iou_type="segm"
            ).evaluate(verbose=False)
            aps.update({f"segm_{k}": v for k, v in segm_stats.items()})
        return aps
