"""Synthetic dataset: deterministic random images + boxes, no files.

No reference twin — this is the rebuild's "fake backend" for tests,
smoke-training, and benchmarking in environments without VOC/COCO on disk
(SURVEY §5.1's do-better-cheaply test strategy).  Images are generated in
memory with colored rectangles on noise so a detector can genuinely
overfit them; boxes are the rectangle coordinates.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.imdb import IMDB


def class_color(cls: int) -> np.ndarray:
    """Saturated, well-separated class color: every class must be clearly
    distinguishable from the 90-150 gray noise background AND from every
    other class, or overfit gates hit an invisible-object mAP ceiling.
    Golden-ratio hue spacing keeps arbitrary class counts distinct."""
    hue = ((cls - 1) * 0.61803398875) % 1.0
    i = int(hue * 6.0)
    f = hue * 6.0 - i
    v, s = 235.0, 0.85
    p, q, t = v * (1 - s), v * (1 - s * f), v * (1 - s * (1 - f))
    rgb = [(v, t, p), (q, v, p), (p, v, t), (p, q, v), (t, p, v), (v, p, q)][i % 6]
    return np.asarray(rgb, np.float32)


def synthetic_image(rec: Dict, seed: int) -> np.ndarray:
    """Render the record: noise background + filled class-colored boxes."""
    rng = np.random.RandomState(seed)
    h, w = rec["height"], rec["width"]
    im = rng.rand(h, w, 3).astype(np.float32) * 60.0 + 90.0
    for box, cls in zip(rec["boxes"], rec["gt_classes"]):
        x1, y1, x2, y2 = box.astype(int)
        color = class_color(int(cls))
        im[y1 : y2 + 1, x1 : x2 + 1] = color + rng.rand(
            y2 - y1 + 1, x2 - x1 + 1, 3
        ).astype(np.float32) * 10.0
    return im


class SyntheticDataset(IMDB):
    def __init__(
        self,
        num_images: int = 32,
        num_classes: int = 21,
        image_size=(480, 640),
        max_boxes: int = 4,
        seed: int = 0,
    ):
        super().__init__(f"synthetic_{num_images}", root_path="/tmp")
        self.classes = ["__background__"] + [
            f"class{i}" for i in range(1, num_classes)
        ]
        self.image_set_index = list(range(num_images))
        self.seed = seed
        self.image_size = image_size
        self.max_boxes = max_boxes

    def gt_roidb(self) -> List[Dict]:
        rng = np.random.RandomState(self.seed)
        h, w = self.image_size
        roidb = []
        for i in self.image_set_index:
            n = rng.randint(1, self.max_boxes + 1)
            boxes, classes = [], []
            for _ in range(n):
                bw = rng.randint(60, w // 2)
                bh = rng.randint(60, h // 2)
                x1 = rng.randint(0, w - bw)
                y1 = rng.randint(0, h - bh)
                boxes.append([x1, y1, x1 + bw - 1, y1 + bh - 1])
                classes.append(rng.randint(1, self.num_classes))
            roidb.append(
                {
                    "image": f"synthetic://{i}",
                    "height": h,
                    "width": w,
                    "boxes": np.asarray(boxes, np.float32),
                    "gt_classes": np.asarray(classes, np.int32),
                    "flipped": False,
                    "synthetic_seed": self.seed + 1000 + i,
                }
            )
        return roidb

    def evaluate_detections(self, detections, **kw):
        """VOC-style mAP against the synthetic gt (integral metric)."""
        from mx_rcnn_tpu.eval.voc_eval import voc_eval

        roidb = self.gt_roidb()
        annots = {
            i: {"boxes": r["boxes"], "gt_classes": r["gt_classes"]}
            for i, r in enumerate(roidb)
        }
        aps = {}
        for cls_idx in range(1, self.num_classes):
            # classes absent from the gt have undefined AP and are skipped;
            # classes WITH gt but no detections score 0 (they must count
            # against mAP or a near-blind model would look good)
            if not any((r["gt_classes"] == cls_idx).any() for r in roidb):
                continue
            dets_by_img = {
                i: detections[cls_idx][i] for i in range(len(roidb))
            }
            _, _, ap = voc_eval(dets_by_img, annots, cls_idx, 0.5, False)
            aps[f"class{cls_idx}"] = ap
        vals = [v for v in aps.values()]
        aps["mAP"] = float(np.mean(vals)) if vals else 0.0
        return aps
