"""Dataset base class (IMDB) and the roidb record format.

Reference: ``rcnn/dataset/imdb.py :: IMDB`` — name/classes/image index,
pickle roidb cache under ``data/cache``, ``append_flipped_images`` (x-flip
boxes with validity asserts), abstract ``evaluate_detections``.

roidb record keys (superset of the reference's, minus the
selective-search legacy fields):
  image (path), height, width, boxes (n, 4) f32, gt_classes (n,) i32,
  flipped (bool), and optionally segmentation (len-n list of COCO
  polygon lists / RLE dicts / None, parallel to boxes — Mask R-CNN gt).
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, List

import numpy as np


class IMDB:
    def __init__(self, name: str, root_path: str):
        self.name = name
        self.root_path = root_path
        self.classes: List[str] = []
        self.image_set_index: List[str] = []

    @property
    def num_classes(self) -> int:
        return len(self.classes)

    @property
    def num_images(self) -> int:
        return len(self.image_set_index)

    @property
    def cache_path(self) -> str:
        path = os.path.join(self.root_path, "cache")
        os.makedirs(path, exist_ok=True)
        return path

    # -- roidb ------------------------------------------------------------
    def gt_roidb(self) -> List[Dict]:
        raise NotImplementedError

    def load_cached(self, tag: str, build_fn):
        """Pickle cache identical in spirit to the reference's
        ``data/cache/{name}_{tag}.pkl`` files."""
        cache_file = os.path.join(self.cache_path, f"{self.name}_{tag}.pkl")
        if os.path.exists(cache_file):
            with open(cache_file, "rb") as f:
                return pickle.load(f)
        data = build_fn()
        with open(cache_file, "wb") as f:
            pickle.dump(data, f, pickle.HIGHEST_PROTOCOL)
        return data

    def evaluate_detections(self, detections, **kwargs):
        """``detections[cls][img]`` = (n, 5) [x1, y1, x2, y2, score]."""
        raise NotImplementedError

    # -- augmentation -----------------------------------------------------
    @staticmethod
    def append_flipped_images(roidb: List[Dict]) -> List[Dict]:
        """Double the roidb with x-flipped copies.

        Reference: ``rcnn/dataset/imdb.py :: append_flipped_images``
        (including its box-validity assertion).
        """
        flipped = []
        for rec in roidb:
            boxes = rec["boxes"].copy()
            if len(boxes):
                oldx1 = boxes[:, 0].copy()
                oldx2 = boxes[:, 2].copy()
                boxes[:, 0] = rec["width"] - oldx2 - 1
                boxes[:, 2] = rec["width"] - oldx1 - 1
                assert (boxes[:, 2] >= boxes[:, 0]).all()
            new_rec = dict(rec)
            new_rec["boxes"] = boxes
            new_rec["flipped"] = True
            if rec.get("segmentation") is not None:
                from mx_rcnn_tpu.data.masks import flip_segmentations

                new_rec["segmentation"] = flip_segmentations(
                    rec["segmentation"], rec["width"]
                )
            if "proposals" in rec and len(rec["proposals"]):
                props = rec["proposals"].copy()
                oldx1 = props[:, 0].copy()
                oldx2 = props[:, 2].copy()
                props[:, 0] = rec["width"] - oldx2 - 1
                props[:, 2] = rec["width"] - oldx1 - 1
                new_rec["proposals"] = props
            flipped.append(new_rec)
        return list(roidb) + flipped


def filter_roidb(roidb: List[Dict]) -> List[Dict]:
    """Drop images without any gt box (reference:
    ``rcnn/utils/load_data.py :: filter_roidb``)."""
    kept = [r for r in roidb if len(r["boxes"]) > 0]
    return kept


def merge_roidbs(roidbs: List[List[Dict]]) -> List[Dict]:
    """Concatenate roidbs of multiple image sets (07+12 training;
    reference: ``rcnn/utils/load_data.py :: merge_roidb``)."""
    out: List[Dict] = []
    for r in roidbs:
        out.extend(r)
    return out
