"""Host-side gt mask rasterization into box-frame bitmaps.

Reference: the mask plumbing of the ``rcnn/pycocotools`` lineage
(``maskApi.c`` / ``_mask.pyx`` — SURVEY N5): upstream descendants decode
COCO polygons/RLE to full-image bitmaps and crop per roi on device.  The
TPU-first rework avoids full-image mask tensors entirely: each gt is
rasterized ONCE, at roidb-load/batch time, into a small M×M bitmap over
its own gt box ("box frame"), and the in-graph target op
(``ops/mask_targets.py::crop_resize_masks``) bilinearly resamples that
bitmap under each matched roi's S×S grid.  A (B, G, M, M) uint8 tensor
replaces (B, G, H, W) — ~100× less HBM/relay traffic at M=64 — and the
device-side crop is two matmuls per roi instead of gathers.

Supported ``segmentation`` record formats (the COCO instance formats):
- list of polygons ``[[x1, y1, x2, y2, ...], ...]`` (continuous image
  coordinates, pixel p covering [p, p+1));
- an RLE dict ``{"size": [h, w], "counts": [...]}`` (crowd regions —
  excluded from training by ``data/coco.py``, handled here anyway for
  completeness).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from mx_rcnn_tpu.native import rle as rlelib


def polygons_to_box_frame(
    segm, box: Sequence[float], m: int
) -> np.ndarray:
    """One gt's ``segmentation`` → (m, m) uint8 bitmap over its own box.

    ``box`` = [x1, y1, x2, y2] inclusive pixel indices (+1 widths).  The
    bitmap's cell (r, c) covers the continuous region
    [x1 + c/m·w, x1 + (c+1)/m·w) × [y1 + r/m·h, ...): polygon vertices
    are affinely mapped into that frame and filled by the native even-odd
    scanline rasterizer on cell centers — the same convention
    ``crop_resize_masks`` samples under.
    """
    x1, y1, x2, y2 = (float(v) for v in box[:4])
    w = max(x2 - x1 + 1.0, 1.0)
    h = max(y2 - y1 + 1.0, 1.0)
    if isinstance(segm, dict):  # RLE: decode, crop, nearest-resize
        full = rle_to_bitmap(segm)
        return _crop_resize_bitmap(full, (x1, y1, x2, y2), m)
    polys = []
    for poly in segm:
        p = np.asarray(poly, np.float64).reshape(-1, 2)
        if len(p) < 3:
            continue
        q = np.empty_like(p)
        q[:, 0] = (p[:, 0] - x1) / w * m
        q[:, 1] = (p[:, 1] - y1) / h * m
        polys.append(q.reshape(-1))
    if not polys:
        return np.ones((m, m), np.uint8)  # degenerate → rectangle fallback
    return rlelib.decode(rlelib.from_polygons(polys, m, m))


def rle_to_bitmap(segm: Dict) -> np.ndarray:
    """RLE dict → (h, w) uint8 bitmap.  Handles compressed string counts
    (``ensure_list_counts``) and the lazy ``hflip`` tag
    ``flip_segmentations`` sets instead of eagerly re-encoding."""
    norm = rlelib.ensure_list_counts(
        {"size": segm["size"], "counts": segm["counts"]}
    )
    full = rlelib.decode(norm)
    if segm.get("hflip"):
        full = full[:, ::-1]
    return full


def _crop_resize_bitmap(full: np.ndarray, box, m: int) -> np.ndarray:
    """Nearest-neighbor crop-resize of a full-image bitmap to the box
    frame (the RLE-crowd path; polygons never take this)."""
    x1, y1, x2, y2 = box
    hh, ww = full.shape
    w = max(x2 - x1 + 1.0, 1.0)
    h = max(y2 - y1 + 1.0, 1.0)
    cols = np.clip((x1 + (np.arange(m) + 0.5) / m * w).astype(int), 0, ww - 1)
    rows = np.clip((y1 + (np.arange(m) + 0.5) / m * h).astype(int), 0, hh - 1)
    return full[np.ix_(rows, cols)].astype(np.uint8)


def record_gt_masks(
    rec: Dict, max_gt: int, m: int
) -> Optional[np.ndarray]:
    """roidb record → (max_gt, m, m) uint8 box-frame bitmaps, or None
    when the record carries no ``segmentation`` (box-only dataset — the
    model then falls back to rectangle targets).

    Boxes and polygons are both stored pre-flipped by
    ``append_flipped_images``, so no flip handling is needed here; the
    bitmaps are resolution-independent (the box frame is relative), so
    the loader's resize scale does not touch them.

    Rasterization runs once per batch assembly (not cached on the
    record): the native scanline fill costs a few µs per gt at M=64,
    ~1000× less than the JPEG decode sharing the same prefetch path,
    while caching bitmaps across a COCO-scale roidb would pin GBs of
    host RAM.
    """
    segms = rec.get("segmentation")
    if segms is None:
        return None
    out = np.zeros((max_gt, m, m), np.uint8)
    for i, (segm, box) in enumerate(zip(segms, rec["boxes"])):
        if i >= max_gt:
            break
        if segm is None:
            out[i] = 1  # this gt has no mask → rectangle
        else:
            out[i] = polygons_to_box_frame(segm, box, m)
    return out


def flip_segmentations(segms, width: int):
    """x-flip a record's segmentation list.  Polygons flip eagerly
    (x ↦ width − x in continuous coordinates — an array op); RLE dicts
    flip LAZILY via an ``hflip`` tag consumed by :func:`rle_to_bitmap`,
    so flip-time roidb preparation never pays a full-image decode +
    re-encode per annotation.  The even-odd fill is winding-insensitive,
    so reversed polygon orientation after flipping is harmless."""
    if segms is None:
        return None
    out = []
    for segm in segms:
        if segm is None:
            out.append(None)
        elif isinstance(segm, dict):
            out.append(
                {
                    "size": segm["size"],
                    "counts": segm["counts"],
                    "hflip": not segm.get("hflip", False),
                }
            )
        else:
            flipped = []
            for poly in segm:
                p = np.asarray(poly, np.float64).copy()
                p[0::2] = width - p[0::2]
                flipped.append(p)
            out.append(flipped)
    return out
