"""MS COCO dataset without pycocotools.

Reference: ``rcnn/dataset/coco.py`` + the vendored
``rcnn/pycocotools/{coco,cocoeval}.py``.  This environment has no
pycocotools wheel, so the instances JSON is parsed directly (it's plain
JSON) and bbox evaluation uses our own COCOeval-equivalent
(``mx_rcnn_tpu/eval/coco_eval.py``), golden-tested against the published
protocol.  Crowd regions (iscrowd=1) are excluded from training rois and
handled as ignore regions in eval, as upstream does.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.imdb import IMDB
from mx_rcnn_tpu.eval.coco_eval import COCOEvalBbox


class COCO(IMDB):
    """``image_set`` like 'train2017' / 'val2017'."""

    def __init__(self, image_set: str, root_path: str, data_path: str):
        super().__init__(f"coco_{image_set}", root_path)
        self.image_set = image_set
        self.data_path = data_path
        ann_file = os.path.join(
            data_path, "annotations", f"instances_{image_set}.json"
        )
        with open(ann_file) as f:
            self._dataset = json.load(f)

        cats = sorted(self._dataset["categories"], key=lambda c: c["id"])
        self.classes = ["__background__"] + [c["name"] for c in cats]
        self._cat_id_to_class = {
            c["id"]: i + 1 for i, c in enumerate(cats)
        }
        self._class_to_cat_id = {v: k for k, v in self._cat_id_to_class.items()}

        self._images = {im["id"]: im for im in self._dataset["images"]}
        self.image_set_index = sorted(self._images.keys())

        self._anns_by_image: Dict[int, List[dict]] = {i: [] for i in self._images}
        for ann in self._dataset["annotations"]:
            if ann["image_id"] in self._anns_by_image:
                self._anns_by_image[ann["image_id"]].append(ann)

    def image_path(self, index: int) -> str:
        file_name = self._images[index]["file_name"]
        return os.path.join(self.data_path, self.image_set, file_name)

    def _load_annotation(self, index: int) -> Dict:
        im = self._images[index]
        width, height = im["width"], im["height"]
        boxes, classes, segms = [], [], []
        for ann in self._anns_by_image[index]:
            if ann.get("iscrowd", 0):
                continue
            x, y, w, h = ann["bbox"]
            # xywh → x1y1x2y2, clipped (reference coco.py does the same)
            x1 = max(0.0, x)
            y1 = max(0.0, y)
            x2 = min(width - 1.0, x1 + max(0.0, w - 1.0))
            y2 = min(height - 1.0, y1 + max(0.0, h - 1.0))
            if ann.get("area", 1) > 0 and x2 >= x1 and y2 >= y1:
                boxes.append([x1, y1, x2, y2])
                classes.append(self._cat_id_to_class[ann["category_id"]])
                # polygons (list) or uncompressed RLE dict; absent or
                # malformed → None, trained as a rectangle target
                segm = ann.get("segmentation")
                if not (isinstance(segm, (list, dict)) and segm):
                    segm = None
                segms.append(segm)
        return {
            "image": self.image_path(index),
            "height": height,
            "width": width,
            "boxes": np.asarray(boxes, np.float32).reshape(-1, 4),
            "gt_classes": np.asarray(classes, np.int32),
            "segmentation": segms,
            "flipped": False,
        }

    def gt_roidb(self) -> List[Dict]:
        return self.load_cached(
            "gt_roidb",
            lambda: [self._load_annotation(ix) for ix in self.image_set_index],
        )

    # -- evaluation -------------------------------------------------------
    def evaluate_detections(self, detections, save_json: str | None = None,
                            all_masks=None):
        """detections[cls][img_i] = (n, 5).  Runs the 12-metric COCO bbox
        protocol; returns the stats dict (mAP@[.5:.95] under 'AP').

        ``all_masks[cls][img_i]`` = list of image-space RLE dicts parallel
        to the detections (Mask R-CNN) additionally runs the segm protocol
        and returns its stats under ``segm_*`` keys.
        """
        results = []
        for cls_idx in range(1, self.num_classes):
            cat_id = self._class_to_cat_id[cls_idx]
            for i, img_id in enumerate(self.image_set_index):
                dets = np.asarray(detections[cls_idx][i]).reshape(-1, 5)
                for d, (x1, y1, x2, y2, score) in enumerate(dets):
                    res = {
                        "image_id": int(img_id),
                        "category_id": int(cat_id),
                        "bbox": [
                            float(x1),
                            float(y1),
                            float(x2 - x1 + 1),
                            float(y2 - y1 + 1),
                        ],
                        "score": float(score),
                    }
                    if all_masks is not None:
                        res["segmentation"] = all_masks[cls_idx][i][d]
                    results.append(res)
        if save_json:
            with open(save_json, "w") as f:
                json.dump(results, f)
        stats = COCOEvalBbox(self._dataset, results).evaluate()
        if all_masks is not None:
            segm_stats = COCOEvalBbox(
                self._dataset, results, iou_type="segm"
            ).evaluate(verbose=False)
            stats.update({f"segm_{k}": v for k, v in segm_stats.items()})
        return stats
