"""Image loading, resizing, normalization, bucket padding.

Reference: ``rcnn/io/image.py`` (cv2 BGR→RGB read, short-side/long-cap
``resize``, mean-subtract ``transform``, ragged ``tensor_vstack``).  The
TPU twist: instead of stacking to the max shape in each batch (which gives
unbounded distinct shapes → unbounded XLA recompiles, the problem
``MutableModule`` re-binding solved on GPU), every image lands in one of a
small static set of (H, W) *buckets* (SURVEY §5.7); ``im_info`` carries
the true pre-padding size so in-graph ops mask the padding.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import cv2
import numpy as np


def load_image(path: str) -> np.ndarray:
    """Read RGB float32 HWC (reference reads BGR then flips to RGB)."""
    im = cv2.imread(path, cv2.IMREAD_COLOR)
    if im is None:
        raise FileNotFoundError(path)
    return cv2.cvtColor(im, cv2.COLOR_BGR2RGB).astype(np.float32)


def resize_im(
    im: np.ndarray, target_size: int, max_size: int
) -> Tuple[np.ndarray, float]:
    """Short side → ``target_size`` capped so long side ≤ ``max_size``.

    Reference: ``rcnn/io/image.py :: resize``.
    """
    h, w = im.shape[:2]
    short, long_ = min(h, w), max(h, w)
    scale = float(target_size) / short
    if round(scale * long_) > max_size:
        scale = float(max_size) / long_
    im = cv2.resize(im, None, fx=scale, fy=scale, interpolation=cv2.INTER_LINEAR)
    return im, scale


def normalize(im: np.ndarray, pixel_means, pixel_stds) -> np.ndarray:
    """(H, W, 3) RGB → normalized float32 (transform() twin, NHWC not NCHW)."""
    return (im - np.asarray(pixel_means, np.float32)) / np.asarray(
        pixel_stds, np.float32
    )


def denormalize(im: np.ndarray, pixel_means, pixel_stds) -> np.ndarray:
    """transform_inverse() twin, for visualization."""
    out = im * np.asarray(pixel_stds, np.float32) + np.asarray(
        pixel_means, np.float32
    )
    return np.clip(out, 0, 255).astype(np.uint8)


def quantize_uint8(im: np.ndarray) -> np.ndarray:
    """Resized float RGB → rounded uint8 (TEST.UINT8_TRANSFER: 4× less
    host→device traffic, the model normalizes on device).  One
    definition shared by the offline loader and the serving prepare
    path so their ≤0.5-LSB quantization can never drift apart."""
    return np.clip(np.rint(im), 0, 255).astype(np.uint8)


def pick_bucket(
    h: int, w: int, buckets: Sequence[Tuple[int, int]]
) -> Tuple[int, int]:
    """Smallest bucket that contains (h, w); falls back to the largest-area
    bucket (callers guarantee resized images fit by construction)."""
    fitting = [b for b in buckets if b[0] >= h and b[1] >= w]
    if fitting:
        return min(fitting, key=lambda b: b[0] * b[1])
    return max(buckets, key=lambda b: b[0] * b[1])


def pad_to_bucket(im: np.ndarray, bucket: Tuple[int, int]) -> np.ndarray:
    """Zero-pad bottom/right to the bucket shape (boxes stay valid)."""
    h, w = im.shape[:2]
    bh, bw = bucket
    if h > bh or w > bw:
        raise ValueError(
            f"image ({h}, {w}) exceeds bucket ({bh}, {bw}) — SCALES and "
            f"SHAPE_BUCKETS are inconsistent (silent cropping would drop "
            f"gt boxes)"
        )
    out = np.zeros((bh, bw) + im.shape[2:], dtype=im.dtype)
    out[:h, :w] = im
    return out


def prepare_image(
    im: np.ndarray,
    target_size: int,
    max_size: int,
    pixel_means,
    pixel_stds,
    buckets: Sequence[Tuple[int, int]],
    uint8_out: bool = False,
) -> Tuple[np.ndarray, np.ndarray]:
    """Full per-image path: resize → normalize → bucket-pad.

    Returns (padded image, im_info=(resized_h, resized_w, scale)).

    ``uint8_out`` skips host normalization and emits rounded uint8 RGB
    (TEST.UINT8_TRANSFER: 4× less host→device traffic; the model
    normalizes on device — a ≤0.5-LSB quantization of resized pixels).
    """
    im, scale = resize_im(im, target_size, max_size)
    h, w = im.shape[:2]
    if uint8_out:
        im = quantize_uint8(im)
    else:
        im = normalize(im, pixel_means, pixel_stds)
    im = pad_to_bucket(im, pick_bucket(h, w, buckets))
    return im, np.array([h, w, scale], np.float32)
