"""Parallel host data plane: worker pools for the two host-side stages
that bracket the device in eval/serving — batch ASSEMBLY (decode /
resize / quantize / pad, upstream of the forward) and COMPLETION
(per-class NMS accumulation, detection capping, mask RLE encoding,
downstream of the fetch).

Reference anchor: the MXNet reference relied on the engine's async
executor to hide ``rcnn/core/loader.py`` costs and ran the entire
``pred_eval`` postprocess serially on the driver thread.  Here both
stages are explicit sized pools with the same counter discipline as
``core/pipeline.py :: DeviceFeed``, so ``bench_eval`` reports where
eval time goes instead of re-estimating it.

Determinism is structural, not best-effort:

* :meth:`AssemblyPool.imap` yields results in SUBMISSION order no
  matter which worker finishes first, and the work functions it runs
  (``make_batch`` / ``TrainLoader.build``) are pure per item — so a
  parallel assembly stream is bit-identical to the serial one for the
  same seed (pinned in ``tests/test_assembler.py``).
* :class:`CompletionPool` callers write results into index-addressed
  slots (``all_boxes[cls][img]``), so accumulation is order-free;
  ``drain`` is the only ordering point and re-raises the first worker
  error instead of swallowing it.

``workers == 0`` degrades both pools to inline execution on the caller
thread — the exact legacy serial path, kept as the default on boxes
where threading can't win (this dev box has one core) and as the
reference side of the equivalence tests.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

__all__ = [
    "AssemblyPool",
    "CompletionPool",
    "default_assembly_workers",
]


def default_assembly_workers() -> int:
    """Pool size when the caller passes ``None``: the
    ``MX_RCNN_ASSEMBLY_WORKERS`` env var, else 0 (serial).  Serial is
    the right default on a 1-core host — threads only conserve total
    CPU work — and keeps every existing caller bit-identical; multi-core
    hosts opt in per run or via the env."""
    return max(0, int(os.environ.get("MX_RCNN_ASSEMBLY_WORKERS", "0")))


class _OrderedResults:
    """Closeable iterator over :meth:`AssemblyPool.imap` results.

    Same lifecycle contract as ``data/loader.py :: PrefetchIterator``:
    ``close()`` (also context manager and, as a GC backstop,
    ``__del__``) stops submission, drops pending work, and leaves no
    worker parked — an abandoned eval sweep must not leak ``window``
    in-flight batches.
    """

    def __init__(self, pool: "AssemblyPool", fn: Callable, items: Iterable,
                 window: int):
        self._pool = pool
        self._fn = fn
        self._items = iter(items)
        self._window = max(1, int(window))
        self._q: deque = deque()
        self._closed = False

    def _fill(self) -> None:
        while not self._closed and len(self._q) < self._window:
            try:
                item = next(self._items)
            except StopIteration:
                return
            self._q.append(self._pool._submit_counted(self._fn, item))

    def __iter__(self) -> "_OrderedResults":
        return self

    def __next__(self) -> Any:
        self._fill()
        if not self._q:
            raise StopIteration
        fut = self._q.popleft()
        t0 = time.perf_counter()
        ready = fut.done()
        out = fut.result()  # re-raises the worker exception in order
        self._pool._account_get(ready, time.perf_counter() - t0,
                                len(self._q))
        return out

    def close(self) -> None:
        """Idempotent: stop submitting, cancel queued work, drain the
        in-flight remainder so no worker outlives the consumer."""
        self._closed = True
        while self._q:
            fut = self._q.popleft()
            if not fut.cancel():
                try:
                    fut.result()
                except Exception:  # noqa: BLE001 — abandoned on purpose
                    pass

    def __enter__(self) -> "_OrderedResults":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass


class _InlineResults:
    """``workers == 0`` twin of :class:`_OrderedResults`: a plain lazy
    map on the caller thread, with the same close/ctx interface so
    consumers are pool-size agnostic."""

    def __init__(self, pool: "AssemblyPool", fn: Callable, items: Iterable):
        self._pool = pool
        self._fn = fn
        self._items = iter(items)
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        if self._closed:
            raise StopIteration
        item = next(self._items)
        self._pool.submitted += 1
        t0 = time.perf_counter()
        out = self._fn(item)
        self._pool.completed += 1
        self._pool._account_get(False, time.perf_counter() - t0, 0)
        return out

    def close(self) -> None:
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class AssemblyPool:
    """Sized worker pool for host batch assembly.

    One instance fronts one stream (an eval sweep, a train epoch, a
    bench run); the heavy shared state — the render LRU, the prepared
    canvas LRU, the loader fault budget — lives with its owners and is
    already locked, so N workers decode/resize/pad concurrently without
    coordination here.

    Counters follow ``DeviceFeed.stats()``'s vocabulary so the bench
    can print both stages side by side: ``ready_hits`` — results that
    were already finished when the consumer asked (the pool ran ahead);
    ``starved`` / ``starved_after_first`` — gets that had to wait on a
    worker (after the pipeline-fill get, each one is assembly time the
    consumer ate); ``occupancy`` — ready_hits / yields.
    """

    def __init__(self, workers: Optional[int] = None,
                 name: str = "assembly"):
        self.workers = (
            default_assembly_workers() if workers is None
            else max(0, int(workers))
        )
        self.name = name
        self._ex: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(
                max_workers=self.workers, thread_name_prefix=name
            )
            if self.workers else None
        )
        self._lock = threading.Lock()
        self.submitted = 0
        self.completed = 0
        self.yielded = 0
        self.ready_hits = 0
        self.starved = 0
        self.starved_after_first = 0
        self.wait_s = 0.0
        self.queue_depth_max = 0

    # ------------------------------------------------------------ internals
    def _submit_counted(self, fn: Callable, item: Any):
        def run(it):
            out = fn(it)
            with self._lock:
                self.completed += 1
            return out

        with self._lock:
            self.submitted += 1
        return self._ex.submit(run, item)

    def _account_get(self, ready: bool, waited_s: float, depth: int) -> None:
        with self._lock:
            if ready:
                self.ready_hits += 1
            else:
                self.starved += 1
                if self.yielded > 0:
                    self.starved_after_first += 1
            self.yielded += 1
            self.wait_s += waited_s
            if depth > self.queue_depth_max:
                self.queue_depth_max = depth

    # ------------------------------------------------------------------ api
    def imap(self, fn: Callable[[Any], Any], items: Iterable,
             window: Optional[int] = None) -> Iterator:
        """Ordered streaming map: keeps up to ``window`` (default
        ``workers + 2``) items in flight and yields results in input
        order; the returned iterator is closeable (see
        :class:`_OrderedResults`).  With ``workers == 0`` this is a
        plain serial map with the same interface."""
        if self._ex is None:
            return _InlineResults(self, fn, items)
        return _OrderedResults(
            self, fn, items,
            self.workers + 2 if window is None else window,
        )

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            yielded = max(self.yielded, 1)
            return {
                "workers": self.workers,
                "submitted": self.submitted,
                "completed": self.completed,
                "yielded": self.yielded,
                "ready_hits": self.ready_hits,
                "starved": self.starved,
                "starved_after_first": self.starved_after_first,
                "occupancy": round(self.ready_hits / yielded, 4),
                "wait_s": round(self.wait_s, 4),
                "queue_depth_max": self.queue_depth_max,
            }

    def close(self) -> None:
        if self._ex is not None:
            self._ex.shutdown(wait=True, cancel_futures=True)

    def __enter__(self) -> "AssemblyPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class CompletionPool:
    """Bounded pool for the post-fetch stage: per-image detections,
    capping, mask RLE encoding — work the dispatch thread used to eat
    between predict calls.

    ``submit`` BLOCKS once ``depth`` tasks are in flight (a semaphore,
    the same discipline the serving engine used to keep device-side
    queueing bounded), so a slow postprocess applies backpressure
    instead of piling unbounded futures.  Submitted functions write
    their results into caller-owned index-addressed slots; the pool
    itself returns nothing.  ``drain`` waits for everything submitted
    so far and re-raises the FIRST worker error — a swallowed
    postprocess exception would silently corrupt mAP.

    ``workers == 0`` runs every submit inline on the caller thread (the
    legacy serial path, bit-identical by construction).
    """

    def __init__(self, workers: int, depth: Optional[int] = None,
                 name: str = "completion"):
        self.workers = max(0, int(workers))
        self.depth = (
            max(1, int(depth)) if depth is not None
            else max(1, 2 * self.workers)
        )
        self._ex: Optional[ThreadPoolExecutor] = (
            ThreadPoolExecutor(
                max_workers=self.workers, thread_name_prefix=name
            )
            if self.workers else None
        )
        self._sem = threading.Semaphore(self.depth)
        self._lock = threading.Lock()
        self._pending: set = set()
        self._first_error: Optional[BaseException] = None
        self.submitted = 0
        self.completed = 0
        self.errors = 0
        self.inflight_max = 0
        self.block_s = 0.0

    def submit(self, fn: Callable, *args, **kwargs) -> None:
        if self._ex is None:
            with self._lock:
                self.submitted += 1
            try:
                fn(*args, **kwargs)
                with self._lock:
                    self.completed += 1
            except BaseException as e:  # noqa: BLE001 — kept for drain()
                with self._lock:
                    self.errors += 1
                    if self._first_error is None:
                        self._first_error = e
                raise
            return
        t0 = time.perf_counter()
        self._sem.acquire()
        blocked = time.perf_counter() - t0

        def run():
            try:
                fn(*args, **kwargs)
                with self._lock:
                    self.completed += 1
            except BaseException as e:  # noqa: BLE001 — re-raised by drain
                with self._lock:
                    self.errors += 1
                    if self._first_error is None:
                        self._first_error = e
            finally:
                self._sem.release()

        fut = self._ex.submit(run)
        with self._lock:
            self.submitted += 1
            self.block_s += blocked
            self._pending = {f for f in self._pending if not f.done()}
            self._pending.add(fut)
            if len(self._pending) > self.inflight_max:
                self.inflight_max = len(self._pending)

    def drain(self) -> None:
        """Wait for every submitted task; re-raise the first error."""
        with self._lock:
            pending = list(self._pending)
        for f in pending:
            f.result()
        with self._lock:
            self._pending = {f for f in self._pending if not f.done()}
            err = self._first_error
        if err is not None:
            raise err

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "workers": self.workers,
                "depth": self.depth,
                "submitted": self.submitted,
                "completed": self.completed,
                "errors": self.errors,
                "inflight_max": self.inflight_max,
                "block_s": round(self.block_s, 4),
            }

    def close(self, raise_errors: bool = False) -> None:
        """Shut the pool down after finishing in-flight work.  The
        serving engine closes with ``raise_errors=False`` (request
        futures already carry their errors); eval drains explicitly."""
        if self._ex is not None:
            self._ex.shutdown(wait=True, cancel_futures=False)
        if raise_errors:
            self.drain()

    def __enter__(self) -> "CompletionPool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
