"""Batch loaders: roidb → padded device-ready numpy batches.

Reference: ``rcnn/core/loader.py`` (``AnchorLoader`` / ``ROIIter`` /
``TestLoader``).  Radically simpler here because anchor-target assignment
and roi sampling moved *inside* the jitted graph: the loader only decodes
images, resizes into shape buckets, and pads gt boxes — no
``feat_sym.infer_shape``, no per-image ``assign_anchor`` on host, no
per-GPU slicing (sharding is a jax.sharding concern, not a loader
concern).

Keeps the reference's aspect-ratio grouping trick (``AnchorLoader``'s
aspect grouping): batches are drawn from one orientation bucket at a
time so every image in a batch pads into the same (H, W) bucket and the
jit cache stays bounded at #buckets graphs.

A small background-thread prefetcher overlaps cv2 decode with TPU steps
(the reference relied on MXNet's async engine for the same overlap).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
from collections import OrderedDict
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.core.resilience import RetryPolicy, make_retry_policy
from mx_rcnn_tpu.data.assembler import AssemblyPool, default_assembly_workers
from mx_rcnn_tpu.data.image import load_image, pick_bucket, prepare_image
from mx_rcnn_tpu.utils import faults

logger = logging.getLogger(__name__)


class LoaderFaultBudgetExceeded(RuntimeError):
    """More records failed to load than the configured budget — aborting
    so silent data loss can't masquerade as training."""

class _RenderLRU:
    """Locked LRU of rendered synthetic images, keyed by
    ``(uri, flipped, seed)``.

    Bounds render-cache memory (~7 MB/entry at flagship size, cap via
    ``MX_RCNN_RENDER_CACHE``) while keeping the gate/bench sets — which
    revisit the same few images every epoch/sweep — fully cached.  An
    LRU rather than the old first-come soft cap: that counter was
    unsynchronized across prefetch threads and never reclaimed, so a
    >1024-record train roidb permanently starved every later sweep back
    to re-rendering.  Recency eviction keeps whatever the CURRENT sweep
    touches hot instead.  Keying by value (not on the record dict) also
    makes flip-safety structural: a flipped twin shallow-copied from its
    source record (``append_flipped_images``) simply has a different
    key, so it can never be served the unflipped pixels.
    """

    def __init__(self, max_entries: int):
        self.max_entries = max(0, int(max_entries))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple, np.ndarray]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key) -> Optional[np.ndarray]:
        with self._lock:
            im = self._entries.get(key)
            if im is None:
                self.misses += 1
            else:
                self._entries.move_to_end(key)
                self.hits += 1
            return im

    def put(self, key, im: np.ndarray) -> None:
        if self.max_entries <= 0:
            return
        with self._lock:
            self._entries[key] = im
            self._entries.move_to_end(key)
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits = self.misses = self.evictions = 0


_RENDER_CACHE = _RenderLRU(int(os.environ.get("MX_RCNN_RENDER_CACHE", "1024")))

# Prepared-canvas LRU: the (padded image, im_info) PAIR after resize /
# normalize-or-quantize / bucket-pad — the ~80 ms/img assembly tail the
# render cache doesn't cover.  Eval sweeps and the bench revisit the
# same records every pass, so the second pass skips assembly entirely.
# Keyed by record identity AND every input of the prep math (scales,
# bucket, uint8 flag, normalization constants), so a hit is bit-identical
# to recomputation by construction.  Default OFF (entries=0): a train
# stream with flip augmentation rarely revisits a key before eviction,
# and a flagship canvas is ~3 MB — opt in via MX_RCNN_PREPARED_CACHE or
# :func:`set_prepared_cache` where revisits are the workload (bench,
# repeated eval).
_PREPARED_CACHE = _RenderLRU(int(os.environ.get("MX_RCNN_PREPARED_CACHE", "0")))


def set_prepared_cache(max_entries: int) -> None:
    """Resize (and clear) the prepared-canvas LRU at runtime — the
    bench/tools hook; the env var covers child processes."""
    _PREPARED_CACHE.clear()
    _PREPARED_CACHE.max_entries = max(0, int(max_entries))


def _prepared_key(rec: Dict, scales, bucket, uint8: bool, means, stds):
    """Cache key = record identity + every parameter of the prep math."""
    base = (rec["image"], bool(rec.get("flipped")))
    if "synthetic_seed" in rec:
        base += (rec["synthetic_seed"],)
    norm = (
        None if uint8
        else (tuple(np.ravel(means).tolist()), tuple(np.ravel(stds).tolist()))
    )
    return base + (tuple(scales), tuple(bucket), uint8, norm)


def _load_record_image(rec: Dict) -> np.ndarray:
    if str(rec["image"]).startswith("synthetic://"):
        from mx_rcnn_tpu.data.synthetic import synthetic_image

        # synthetic records render from their OWN (already-flipped)
        # geometry — flipping again would move pixels back to the
        # unflipped positions while gt stays flipped, silently training
        # half the flip-augmented epoch on mismatched targets.  The
        # render is deterministic per (uri, flipped, seed), so the LRU
        # key is exactly that triple; at ~17 ms/render (noise
        # generation) on a 1-core box re-rendering was the e2e eval
        # bottleneck once the relay pipeline overlapped (disk-backed
        # datasets get the same effect from the OS page cache).
        # Read-only downstream: prepare_image copies.
        key = (rec["image"], bool(rec.get("flipped")), rec["synthetic_seed"])
        im = _RENDER_CACHE.get(key)
        if im is None:
            im = synthetic_image(rec, rec["synthetic_seed"])
            _RENDER_CACHE.put(key, im)
        return im
    im = load_image(rec["image"])
    if rec.get("flipped"):
        im = im[:, ::-1]
    return im


def make_batch(
    records: Sequence[Dict],
    cfg: Config,
    bucket: Tuple[int, int],
    images: Optional[Sequence[np.ndarray]] = None,
    proposal_count: int = 0,
    seeds: Optional[Sequence[int]] = None,
    with_masks: bool = False,
    uint8_images: bool = False,
) -> Dict[str, np.ndarray]:
    """Assemble one padded train batch from roidb records.

    Boxes are scaled by the resize factor (the reference scales gt_boxes by
    im_scale in ``get_rpn_batch``); gt arrays padded to MAX_GT_BOXES.

    ``proposal_count`` > 0 additionally emits ``proposals``/``prop_valid``
    padded to that count from each record's ``proposals`` field (the
    ROIIter role: Fast-RCNN batches from a proposal roidb,
    ``rcnn/io/rcnn.py :: get_rcnn_batch``).

    ``with_masks`` emits ``gt_masks`` (n, G, M, M) uint8 box-frame
    bitmaps (M = TRAIN.MASK_GT_SIZE) for Mask R-CNN training — records
    without a ``segmentation`` field get all-ones bitmaps (rectangle
    targets, the box-only convention).  Bitmaps are box-relative, so the
    resize scale does not affect them.
    """
    scales = cfg.dataset.SCALES[0]
    g = cfg.dataset.MAX_GT_BOXES
    n = len(records)
    bh, bw = bucket
    out_images = np.zeros(
        (n, bh, bw, 3), np.uint8 if uint8_images else np.float32
    )
    im_info = np.zeros((n, 3), np.float32)
    gt_boxes = np.zeros((n, g, 5), np.float32)
    gt_valid = np.zeros((n, g), bool)
    if with_masks:
        from mx_rcnn_tpu.data.masks import record_gt_masks

        msize = cfg.TRAIN.MASK_GT_SIZE
        gt_masks = np.zeros((n, g, msize, msize), np.uint8)
    if proposal_count:
        proposals = np.zeros((n, proposal_count, 4), np.float32)
        prop_valid = np.zeros((n, proposal_count), bool)
    for i, rec in enumerate(records):
        # prepared-canvas cache: only for loader-owned loads (a caller
        # passing ``images`` may have substituted fault slots, whose
        # pixels no longer match the record key)
        key = None
        prepared = None
        if images is None and _PREPARED_CACHE.max_entries > 0:
            key = _prepared_key(
                rec, scales, bucket, uint8_images,
                cfg.network.PIXEL_MEANS, cfg.network.PIXEL_STDS,
            )
            prepared = _PREPARED_CACHE.get(key)
        if prepared is None:
            im = images[i] if images is not None else _load_record_image(rec)
            prepared = prepare_image(
                im,
                scales[0],
                scales[1],
                cfg.network.PIXEL_MEANS,
                cfg.network.PIXEL_STDS,
                [bucket],
                uint8_out=uint8_images,
            )
            if key is not None:
                _PREPARED_CACHE.put(key, prepared)
        padded, info = prepared
        out_images[i] = padded
        im_info[i] = info
        boxes = rec["boxes"] * info[2]
        k = min(len(boxes), g)
        gt_boxes[i, :k, :4] = boxes[:k]
        gt_boxes[i, :k, 4] = rec["gt_classes"][:k]
        gt_valid[i, :k] = True
        if with_masks:
            rec_masks = record_gt_masks(rec, g, msize)
            gt_masks[i] = 1 if rec_masks is None else rec_masks
        if proposal_count:
            p = np.asarray(rec["proposals"], np.float32) * info[2]
            k = min(len(p), proposal_count)
            proposals[i, :k] = p[:k]
            prop_valid[i, :k] = True
    out = {
        "images": out_images,
        "im_info": im_info,
        "gt_boxes": gt_boxes,
        "gt_valid": gt_valid,
    }
    if with_masks:
        out["gt_masks"] = gt_masks
    if seeds is not None:
        # per-image sampling seeds: in-graph roi/anchor subsampling keys
        # derive from these, making draws identical across DP topologies
        out["sample_seeds"] = np.asarray(seeds, np.int32)
    if proposal_count:
        out["proposals"] = proposals
        out["prop_valid"] = prop_valid
    return out


def _orientation_bucket(rec: Dict, buckets) -> Tuple[int, int]:
    """Pick the bucket a record will land in post-resize (h<=w → wide)."""
    wide = rec["width"] >= rec["height"]
    for b in buckets:
        if (b[1] >= b[0]) == wide:
            return tuple(b)
    return tuple(buckets[0])


class PrefetchIterator:
    """Closeable host-prefetch stage: drains ``source`` through a daemon
    thread with a bounded queue so host batch assembly overlaps the
    consumer's device work.

    Worker exceptions are re-raised in the consumer — a swallowed decode
    error would silently truncate an epoch (or an eval sweep, corrupting
    mAP).  Shutdown is sentinel-based: :meth:`close` (also the context
    manager and, as a backstop, GC) signals the worker, drains queued
    batches, and joins the thread — an abandoned iterator no longer
    leaks the worker plus ``prefetch + 1`` pinned batches.  Shared by
    ``TrainLoader.__iter__`` and ``TestLoader.iter_batched``; the
    device-feed stage (``core/pipeline.py :: DeviceFeed``) stacks on top
    and closes its source through the same interface.

    ``prefetch <= 0`` degrades to a plain synchronous pass-through (no
    thread), keeping the deterministic no-thread path tests rely on.
    """

    def __init__(self, source, prefetch: int):
        self._closed = threading.Event()
        self._done = False
        if prefetch <= 0:
            self._it = iter(source)
            self._thread = None
            return
        self._it = None
        self._source = source
        self._q: "queue.Queue" = queue.Queue(maxsize=prefetch)
        self._thread = threading.Thread(
            target=self._worker, name="loader-prefetch", daemon=True
        )
        self._thread.start()

    def _put(self, msg) -> bool:
        # bounded put that gives up once the consumer is gone — a plain
        # q.put would park this thread forever when the iterator is
        # abandoned mid-iteration (exception in the consumer, partial
        # eval, GC), leaking the thread plus prefetch+1 pinned batches
        while not self._closed.is_set():
            try:
                self._q.put(msg, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            for item in self._source:
                if not self._put(("item", item)):
                    return
            self._put(("stop", None))
        except BaseException as e:  # noqa: BLE001 — handed to the consumer
            self._put(("err", e))

    def __iter__(self):
        return self

    def __next__(self):
        if self._done or self._closed.is_set():
            raise StopIteration
        if self._thread is None:
            return next(self._it)
        while True:
            try:
                kind, payload = self._q.get(timeout=0.2)
                break
            except queue.Empty:
                if self._closed.is_set():
                    raise StopIteration from None
        if kind == "stop":
            self._done = True
            raise StopIteration
        if kind == "err":
            self._done = True
            raise payload
        return payload

    def close(self, timeout: float = 5.0) -> None:
        """Idempotent: stop the worker, drop queued batches, join."""
        self._closed.set()
        if self._thread is None:
            return
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        if self._thread.is_alive():
            self._thread.join(timeout)

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # abandoned without close(): still reclaim
        try:
            self.close(timeout=0.2)
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass


def _prefetch_iter(source, prefetch: int):
    """Back-compat alias for :class:`PrefetchIterator`."""
    return PrefetchIterator(source, prefetch)


class _AssembledStream:
    """Closeable iterator over pool-assembled batches — the
    ``assembly_workers > 0`` twin of :class:`PrefetchIterator`, so
    consumers (DeviceFeed, ``pipelined``, early-stopping eval) tear
    down either path through the same ``close()``.

    Drops ``None`` results (whole-batch failures already accounted by
    the loader's fault counters); worker exceptions — including
    :class:`LoaderFaultBudgetExceeded` — surface at their submission
    position, exactly where the serial loop would have raised.
    ``stats()`` exposes the pool's occupancy counters for the bench.
    """

    def __init__(self, pool: AssemblyPool, results):
        self._pool = pool
        self._results = results

    def __iter__(self) -> "_AssembledStream":
        return self

    def __next__(self):
        while True:
            try:
                out = next(self._results)
            except StopIteration:
                self._pool.close()
                raise
            if out is not None:
                return out

    def stats(self) -> Dict:
        return self._pool.stats()

    def close(self, timeout: float = 5.0) -> None:
        self._results.close()
        self._pool.close()

    def __enter__(self) -> "_AssembledStream":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 — interpreter may be tearing down
            pass


class TrainLoader:
    """AnchorLoader twin: shuffled, aspect-grouped, bucket-padded batches.

    Fault tolerance: a record whose image fails to load (missing file,
    corrupt decode, NFS hiccup) no longer kills the prefetch worker — the
    read is retried per ``retry`` (deterministic, jitter-free), then the
    record is dropped from the batch plan: its slot is filled by the
    batch's first good record (shapes must stay fixed for the jit cache)
    and ``substituted_records``/``record_failures`` count the damage.  A
    batch with NO loadable record is dropped whole.  More failures than
    ``failure_budget`` abort the run with
    :class:`LoaderFaultBudgetExceeded` — bounded, loud data loss.
    """

    def __init__(
        self,
        roidb: List[Dict],
        cfg: Config,
        batch_size: int,
        shuffle: bool = True,
        seed: int = 0,
        prefetch: int = 2,
        proposal_count: int = 0,
        row_slice: Optional[slice] = None,
        retry: Optional[RetryPolicy] = None,
        failure_budget: Optional[int] = None,
        assembly_workers: Optional[int] = None,
    ):
        self.roidb = roidb
        self.cfg = cfg
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.seed = seed
        self.prefetch = prefetch
        self.proposal_count = proposal_count
        # multi-host: every process computes the identical (seeded) global
        # plan, then loads only its rows of each global batch — the global
        # data order is process-count-invariant (parallel/distributed.py)
        self.row_slice = row_slice
        self.epoch = 0
        # consumed by the next __iter__: resume-from-preemption skips the
        # batches already trained this epoch (the plan is deterministic
        # per (seed, epoch), so skipping reproduces the exact stream)
        self.skip_batches = 0
        self.retry = retry or make_retry_policy("loader")
        # default budget: 1% of the roidb, floored so tiny smoke runs
        # aren't aborted by a single flaky read
        self.failure_budget = (
            failure_budget if failure_budget is not None
            else max(32, len(roidb) // 100)
        )
        self.record_failures = 0
        self.substituted_records = 0
        self.dropped_batches = 0
        # None → MX_RCNN_ASSEMBLY_WORKERS (default 0 = the serial
        # prefetch path); > 0 assembles batches in an AssemblyPool
        self.assembly_workers = assembly_workers
        # fault accounting is shared mutable state once assembly goes
        # parallel: counters and the budget check update atomically
        self._fault_lock = threading.Lock()

    def _load_guarded(self, i: int) -> Optional[np.ndarray]:
        """Load record ``i``'s image with bounded retry; None = the
        record is skipped (budget permitting)."""
        rec = self.roidb[i]

        def attempt(_k: int) -> np.ndarray:
            faults.fail_record(i)  # test injection, no-op in production
            return _load_record_image(rec)

        try:
            return self.retry.run(attempt)
        except Exception as e:  # noqa: BLE001 — any read/decode failure
            with self._fault_lock:
                self.record_failures += 1
                failures = self.record_failures
            logger.warning(
                "record %d (%s) failed after %d attempts: %r — dropped "
                "(%d/%d failure budget)",
                i, rec.get("image"), self.retry.tries, e,
                failures, self.failure_budget,
            )
            if failures > self.failure_budget:
                raise LoaderFaultBudgetExceeded(
                    f"{failures} records failed to load "
                    f"(budget {self.failure_budget}); latest: record {i} "
                    f"({rec.get('image')}): {e!r}"
                ) from e
            return None

    def __len__(self) -> int:
        return len(self.roidb) // self.batch_size

    def _epoch_plan(self, epoch: int) -> List[Tuple[Tuple[int, int], List[int]]]:
        """Group indices by orientation bucket, shuffle within groups,
        emit whole batches (dropping the ragged tail like the reference's
        ``pad`` handling drops/wraps)."""
        rng = np.random.RandomState(self.seed + epoch)
        groups: Dict[Tuple[int, int], List[int]] = {}
        for i, rec in enumerate(self.roidb):
            b = _orientation_bucket(rec, self.cfg.SHAPE_BUCKETS)
            groups.setdefault(b, []).append(i)
        plan = []
        for b, idxs in groups.items():
            idxs = np.asarray(idxs)
            if self.shuffle:
                rng.shuffle(idxs)
            for s in range(0, len(idxs) - self.batch_size + 1, self.batch_size):
                plan.append((b, idxs[s : s + self.batch_size].tolist()))
        if self.shuffle:
            order = rng.permutation(len(plan))
            plan = [plan[i] for i in order]
        return plan

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        plan = self._epoch_plan(self.epoch)
        self.epoch += 1
        if self.skip_batches:
            plan = plan[self.skip_batches:]
            self.skip_batches = 0
        if self.row_slice is not None:
            plan = [(b, idxs[self.row_slice]) for b, idxs in plan]
        pc = self.proposal_count

        def build(bucket, idxs):
            images = [self._load_guarded(i) for i in idxs]
            good = [(i, im) for i, im in zip(idxs, images) if im is not None]
            if not good:
                with self._fault_lock:
                    self.dropped_batches += 1
                logger.warning(
                    "dropping whole batch %s — no loadable record", idxs
                )
                return None
            # deterministic skip: a failed slot is filled with the batch's
            # first good record (record + pixels + seed stay consistent),
            # keeping the batch shape fixed for the jit cache
            filled, imgs = [], []
            for i, im in zip(idxs, images):
                if im is None:
                    i, im = good[0]
                    with self._fault_lock:
                        self.substituted_records += 1
                filled.append(i)
                imgs.append(im)
            return make_batch(
                [self.roidb[i] for i in filled], self.cfg, bucket,
                images=imgs, proposal_count=pc, seeds=filled,
                with_masks=self.cfg.network.USE_MASK,
            )

        workers = (
            default_assembly_workers() if self.assembly_workers is None
            else max(0, int(self.assembly_workers))
        )
        if workers > 0:
            # parallel assembly: ``build`` is pure per plan entry (its
            # only shared state — render/prepared LRUs, fault counters —
            # is locked), so the ordered pool stream is bit-identical to
            # the serial one for the same seed; the pool's run-ahead
            # window doubles as the prefetch stage
            pool = AssemblyPool(workers, name="train-assembly")
            return _AssembledStream(
                pool,
                pool.imap(
                    lambda entry: build(*entry), plan,
                    window=max(self.prefetch, workers + 2),
                ),
            )
        source = (
            batch
            for bucket, idxs in plan
            if (batch := build(bucket, idxs)) is not None
        )
        # a real PrefetchIterator (not a generator) so consumers that
        # stop early — or the DeviceFeed stage stacked on top — can
        # close() it deterministically instead of waiting on GC
        return PrefetchIterator(source, self.prefetch)


class TestLoader:
    """Inference iterator (TestLoader twin); also yields the roidb record
    so eval can undo the resize scale.  ``proposal_count`` > 0 emits each
    record's dumped proposals too (Fast-RCNN test mode).

    ``batch_size`` > 1 batches same-orientation-bucket images onto the
    device in one forward — a beyond-reference upgrade (the reference
    tester is hardwired batch=1); iterate with :meth:`iter_batched`,
    which yields ``(dataset_indices, records, batch)``.  The ragged tail
    of each bucket group runs at its own (smaller) batch size, so the jit
    cache stays at ≤ 2 graphs per bucket.
    """

    def __init__(
        self,
        roidb: List[Dict],
        cfg: Config,
        proposal_count: int = 0,
        batch_size: int = 1,
    ):
        self.roidb = roidb
        self.cfg = cfg
        self.proposal_count = proposal_count
        self.batch_size = batch_size

    def __len__(self) -> int:
        return len(self.roidb)

    def __iter__(self):
        for rec in self.roidb:
            bucket = _orientation_bucket(rec, self.cfg.SHAPE_BUCKETS)
            batch = make_batch(
                [rec], self.cfg, bucket, proposal_count=self.proposal_count,
                uint8_images=self.cfg.TEST.UINT8_TRANSFER,
            )
            batch["orig_hw"] = np.asarray(
                [[rec["height"], rec["width"]]], np.float32
            )
            yield rec, batch

    def iter_batched(
        self, prefetch: int = 2, assembly_workers: Optional[int] = None
    ):
        """Yields ``(dataset_indices, records, batch)``; a background
        thread overlaps host image assembly with the consumer's device
        forward + fetch (same prefetcher discipline as TrainLoader —
        host decode/resize is the eval bottleneck, not the TPU).

        ``assembly_workers`` (None → ``MX_RCNN_ASSEMBLY_WORKERS``,
        default 0): > 0 assembles batches concurrently in an
        :class:`~mx_rcnn_tpu.data.assembler.AssemblyPool` instead of the
        single prefetch thread — same yield order and bit-identical
        batches, ``stats()`` on the returned stream reports occupancy."""
        groups: Dict[Tuple[int, int], List[int]] = {}
        for i, rec in enumerate(self.roidb):
            b = _orientation_bucket(rec, self.cfg.SHAPE_BUCKETS)
            groups.setdefault(b, []).append(i)
        plan = [
            (bucket, idxs[s : s + self.batch_size])
            for bucket, idxs in groups.items()
            for s in range(0, len(idxs), self.batch_size)
        ]

        def build(bucket, chunk):
            recs = [self.roidb[i] for i in chunk]
            batch = make_batch(
                recs, self.cfg, bucket, proposal_count=self.proposal_count,
                uint8_images=self.cfg.TEST.UINT8_TRANSFER,
            )
            batch["orig_hw"] = np.asarray(
                [[r["height"], r["width"]] for r in recs], np.float32
            )
            return chunk, recs, batch

        workers = (
            default_assembly_workers() if assembly_workers is None
            else max(0, int(assembly_workers))
        )
        if workers > 0:
            pool = AssemblyPool(workers, name="test-assembly")
            return _AssembledStream(
                pool,
                pool.imap(
                    lambda entry: build(*entry), plan,
                    window=max(prefetch, workers + 2),
                ),
            )
        source = (build(bucket, chunk) for bucket, chunk in plan)
        return PrefetchIterator(source, prefetch)
