"""Small roidb box utilities.

Reference: ``rcnn/dataset/ds_utils.py`` — ``unique_boxes`` (hash-dedup)
and ``filter_small_boxes``, used by the selective-search legacy paths and
proposal post-processing.
"""

from __future__ import annotations

import numpy as np


def unique_boxes(boxes: np.ndarray, scale: float = 1.0) -> np.ndarray:
    """Indices of unique boxes (first occurrence kept, original order).

    Reference hashes ``round(box * scale)`` with a dot-product; numpy's
    structured unique on the rounded coords is collision-free and
    order-preserving via the returned first indices.
    """
    if len(boxes) == 0:
        return np.zeros((0,), np.int64)
    v = np.round(np.asarray(boxes, np.float64) * scale).astype(np.int64)
    _, index = np.unique(v, axis=0, return_index=True)
    return np.sort(index)


def filter_small_boxes(boxes: np.ndarray, min_size: float) -> np.ndarray:
    """Indices of boxes with both sides ≥ min_size (+1 convention)."""
    if len(boxes) == 0:
        return np.zeros((0,), np.int64)
    w = boxes[:, 2] - boxes[:, 0] + 1
    h = boxes[:, 3] - boxes[:, 1] + 1
    return np.where((w >= min_size) & (h >= min_size))[0]
