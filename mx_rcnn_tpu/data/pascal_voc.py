"""Pascal VOC dataset.

Reference: ``rcnn/dataset/pascal_voc.py :: PascalVOC`` — XML annotation
parsing → gt_roidb; detection writing + ``voc_eval`` mAP in
``evaluate_detections`` (the selective-search legacy path is intentionally
dropped; it was dead weight even upstream).
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET
from typing import Dict, List

import numpy as np

from mx_rcnn_tpu.data.imdb import IMDB
from mx_rcnn_tpu.eval.voc_eval import voc_eval

CLASSES = (
    "__background__",
    "aeroplane", "bicycle", "bird", "boat", "bottle",
    "bus", "car", "cat", "chair", "cow",
    "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor",
)


class PascalVOC(IMDB):
    """``image_set`` like '2007_trainval' / '2007_test'."""

    def __init__(self, image_set: str, root_path: str, devkit_path: str):
        year, split = image_set.split("_")
        super().__init__(f"voc_{year}_{split}", root_path)
        self.year = year
        self.split = split
        self.devkit_path = devkit_path
        self.data_path = os.path.join(devkit_path, f"VOC{year}")
        self.classes = list(CLASSES)
        self.image_set_index = self._load_image_set_index()

    def _load_image_set_index(self) -> List[str]:
        index_file = os.path.join(
            self.data_path, "ImageSets", "Main", f"{self.split}.txt"
        )
        with open(index_file) as f:
            return [line.strip() for line in f if line.strip()]

    def image_path(self, index: str) -> str:
        return os.path.join(self.data_path, "JPEGImages", f"{index}.jpg")

    def annotation_path(self, index: str) -> str:
        return os.path.join(self.data_path, "Annotations", f"{index}.xml")

    def _load_annotation(self, index: str) -> Dict:
        tree = ET.parse(self.annotation_path(index))
        size = tree.find("size")
        width = int(size.find("width").text)
        height = int(size.find("height").text)
        boxes, classes = [], []
        for obj in tree.findall("object"):
            cls_name = obj.find("name").text.lower().strip()
            if cls_name not in self.classes:
                continue
            diff = obj.find("difficult")
            is_diff = int(diff.text) if diff is not None else 0
            if is_diff:
                continue  # difficult boxes train nothing; eval reloads them
            bb = obj.find("bndbox")
            # VOC is 1-indexed; reference subtracts 1
            boxes.append(
                [
                    float(bb.find("xmin").text) - 1,
                    float(bb.find("ymin").text) - 1,
                    float(bb.find("xmax").text) - 1,
                    float(bb.find("ymax").text) - 1,
                ]
            )
            classes.append(self.classes.index(cls_name))
        return {
            "image": self.image_path(index),
            "height": height,
            "width": width,
            "boxes": np.asarray(boxes, np.float32).reshape(-1, 4),
            "gt_classes": np.asarray(classes, np.int32),
            "flipped": False,
        }

    def gt_roidb(self) -> List[Dict]:
        return self.load_cached(
            "gt_roidb",
            lambda: [self._load_annotation(ix) for ix in self.image_set_index],
        )

    # -- evaluation -------------------------------------------------------
    def evaluate_detections(self, detections, use_07_metric: bool | None = None):
        """detections[cls][img] = (n, 5).  Returns {class: AP, 'mAP': m}.

        Reference: ``pascal_voc.py :: evaluate_detections`` → write
        ``comp4_det_*`` files → ``voc_eval`` per class; here the handoff
        is in-memory but the AP math is the same (07 11-point metric for
        year 2007 unless overridden).
        """
        if use_07_metric is None:
            use_07_metric = self.year == "2007"
        annots = {
            ix: self._load_annotation_with_difficult(ix)
            for ix in self.image_set_index
        }
        aps = {}
        for cls_idx, cls in enumerate(self.classes):
            if cls == "__background__":
                continue
            dets_by_img = {
                ix: detections[cls_idx][i]
                for i, ix in enumerate(self.image_set_index)
            }
            rec, prec, ap = voc_eval(
                dets_by_img, annots, cls_idx, ovthresh=0.5, use_07_metric=use_07_metric
            )
            aps[cls] = ap
        aps["mAP"] = float(np.mean([v for k, v in aps.items() if k != "mAP"]))
        return aps

    def _load_annotation_with_difficult(self, index: str) -> Dict:
        """Gt + difficult flags for eval (difficult boxes don't count
        against precision — ``pascal_voc_eval.py`` semantics)."""
        tree = ET.parse(self.annotation_path(index))
        boxes, classes, difficult = [], [], []
        for obj in tree.findall("object"):
            cls_name = obj.find("name").text.lower().strip()
            if cls_name not in self.classes:
                continue
            diff = obj.find("difficult")
            bb = obj.find("bndbox")
            boxes.append(
                [
                    float(bb.find("xmin").text) - 1,
                    float(bb.find("ymin").text) - 1,
                    float(bb.find("xmax").text) - 1,
                    float(bb.find("ymax").text) - 1,
                ]
            )
            classes.append(self.classes.index(cls_name))
            difficult.append(int(diff.text) if diff is not None else 0)
        return {
            "boxes": np.asarray(boxes, np.float32).reshape(-1, 4),
            "gt_classes": np.asarray(classes, np.int32),
            "difficult": np.asarray(difficult, bool),
        }
