"""RCNN classification/regression head (and mask head).

Reference: the ``cls_score``/``bbox_pred`` fully-connected pair appended
after the fc6-fc7 (VGG) or conv5-pool (ResNet) trunk in
``rcnn/symbol/symbol_{vgg,resnet}.py``; initialized Normal(0.01)/
Normal(0.001) respectively (``train_end2end.py :: train_net``).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from mx_rcnn_tpu.models.layers import conv


class RCNNHead(nn.Module):
    num_classes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(R, D) trunk features → cls logits (R, K), box deltas (R, 4K)."""
        cls_score = nn.Dense(
            self.num_classes,
            kernel_init=nn.initializers.normal(0.01),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="cls_score",
        )(x)
        bbox_pred = nn.Dense(
            4 * self.num_classes,
            kernel_init=nn.initializers.normal(0.001),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="bbox_pred",
        )(x)
        return cls_score.astype(jnp.float32), bbox_pred.astype(jnp.float32)


class MaskHead(nn.Module):
    """Mask R-CNN head: 4×conv + deconv ×2 + 1×1 per-class mask logits.

    Extension target (BASELINE config 5); no reference twin — follows the
    original Mask R-CNN paper head on (R, 14, 14, C) pooled features.
    """

    num_classes: int
    channels: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        for i in range(4):
            x = conv(self.channels, 3, 1, self.dtype, name=f"mask_conv{i + 1}",
                     use_bias=True)(x)
            x = nn.relu(x)
        x = nn.ConvTranspose(
            self.channels,
            (2, 2),
            strides=(2, 2),
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="mask_deconv",
        )(x)
        x = nn.relu(x)
        logits = conv(self.num_classes, 1, 1, self.dtype, name="mask_logits",
                      use_bias=True)(x)
        return logits.astype(jnp.float32)
