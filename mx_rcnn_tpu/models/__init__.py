from mx_rcnn_tpu.models.resnet import ResNetBackbone, ResNetTopHead
from mx_rcnn_tpu.models.vgg import VGGBackbone, VGGTopHead
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.models.heads import RCNNHead
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN
