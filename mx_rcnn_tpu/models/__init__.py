from mx_rcnn_tpu.models.resnet import ResNetBackbone, ResNetTopHead
from mx_rcnn_tpu.models.vgg import VGGBackbone, VGGTopHead
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.models.heads import RCNNHead
from mx_rcnn_tpu.models.faster_rcnn import FasterRCNN


def build_model(cfg):
    """Model factory: the single-level C4 graph or the FPN graph,
    selected by the config (USE_FPN) — the registry dispatch that replaces
    the reference's ``eval('get_' + network + '_train')`` symbol lookup."""
    if cfg.network.USE_FPN:
        from mx_rcnn_tpu.models.fpn import FPNFasterRCNN

        return FPNFasterRCNN(cfg)
    return FasterRCNN(cfg)
