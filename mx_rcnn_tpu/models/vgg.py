"""VGG-16 backbone + fc6/fc7 top head.

Reference: ``rcnn/symbol/symbol_vgg.py :: get_vgg_conv`` (13 convs, 4
pools → stride 16; conv1/conv2 frozen via FIXED_PARAMS) and the
fc6/fc7(4096) head applied to 7×7 pooled rois in ``get_vgg_train``.
NHWC, biases on (VGG has no BN).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.models.layers import conv

# (number of convs, channels) per block; pool after each of the first 4
_VGG16 = ((2, 64), (2, 128), (3, 256), (3, 512), (3, 512))

# leading-block order for the frozen-prefix stop_gradient boundary; block
# b's convs are named conv{b}_{i} in VGGBackbone.__call__
VGG_BLOCK_ORDER = ("conv1", "conv2", "conv3", "conv4", "conv5")


class VGGBackbone(nn.Module):
    """(B, H, W, 3) → (B, H/16, W/16, 512).

    Block 5 convs run at stride 16 with no trailing pool, matching the
    reference (pool5 is replaced by ROI pooling).
    """

    dtype: Any = jnp.float32
    # number of leading conv blocks whose output gradient is stopped (the
    # FIXED_PARAMS optimizer mask freezes their params; the stop lets XLA
    # skip their backward pass — see resnet.frozen_prefix_len)
    frozen_prefix: int = 0

    @nn.compact
    def __call__(self, x: jnp.ndarray, pad_mask=None) -> jnp.ndarray:
        # pad_mask: re-zero bucket padding before every spatial op so the
        # valid region is canvas-independent (the conv biases repaint the
        # padding nonzero after each layer) — see layers.make_pad_mask
        pm = pad_mask if pad_mask is not None else (lambda v: v)
        x = x.astype(self.dtype)
        for b, (n_convs, ch) in enumerate(_VGG16, start=1):
            for i in range(n_convs):
                x = conv(
                    ch, 3, 1, self.dtype, name=f"conv{b}_{i + 1}", use_bias=True
                )(pm(x))
                x = nn.relu(x)
            if b < 5:
                x = nn.max_pool(pm(x), (2, 2), strides=(2, 2))
            if b == self.frozen_prefix:
                x = jax.lax.stop_gradient(x)
        return x


class VGGTopHead(nn.Module):
    """fc6/fc7 on pooled rois: (R, 7, 7, 512) → (R, 4096)."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, rois_feat: jnp.ndarray) -> jnp.ndarray:
        x = rois_feat.reshape(rois_feat.shape[0], -1)
        x = nn.Dense(4096, dtype=self.dtype, param_dtype=jnp.float32, name="fc6")(x)
        x = nn.relu(x)
        x = nn.Dense(4096, dtype=self.dtype, param_dtype=jnp.float32, name="fc7")(x)
        return nn.relu(x)
