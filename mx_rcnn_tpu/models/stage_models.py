"""Stage graphs for alternate training: RPN-only and Fast-RCNN-on-proposals.

Reference: the per-stage Symbol builders — ``get_*_rpn``/``get_*_rpn_test``
(RPN-only graphs used by ``rcnn/tools/train_rpn.py``/``test_rpn.py``) and
``get_*_rcnn``/``get_*_rcnn_test`` (Fast R-CNN graphs on precomputed
proposals used by ``rcnn/tools/train_rcnn.py``, fed by
``rcnn/core/loader.py :: ROIIter``).  Same TPU-native stance as
:class:`FasterRCNN`: everything in one jitted graph, fixed shapes,
validity masks.

Both models expose the standard ``(… , train)`` __call__ so the generic
``make_train_step``/``Predictor`` machinery works unchanged; batch dicts
carry exactly the keyword names each signature needs.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.heads import RCNNHead
from mx_rcnn_tpu.models.resnet import (
    RESNET_BLOCK_ORDER,
    ResNetBackbone,
    ResNetTopHead,
    frozen_prefix_len,
)
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.models.vgg import VGG_BLOCK_ORDER, VGGBackbone, VGGTopHead
from mx_rcnn_tpu.ops.anchors import shifted_anchors
from mx_rcnn_tpu.ops.losses import accuracy, softmax_cross_entropy, weighted_smooth_l1
from mx_rcnn_tpu.ops.proposal import propose
from mx_rcnn_tpu.ops.roi_align import extract_roi_features_batched
from mx_rcnn_tpu.ops.targets import assign_anchor, bbox_denorm_vectors, sample_rois


def _dtype_of(cfg: Config):
    return jnp.bfloat16 if cfg.network.COMPUTE_DTYPE == "bfloat16" else jnp.float32


def build_backbone(
    cfg: Config, dtype, fixed_params: Optional[Tuple[str, ...]] = None
) -> Tuple[nn.Module, nn.Module]:
    """(backbone, top_head) for the configured network — shared across
    FasterRCNN / RPNOnly / FastRCNN so param trees align for
    ``combine_model``.

    The backbone stops gradients at the contiguous-prefix boundary of
    the freeze set: those params get zero updates from the optimizer
    mask either way, so XLA skipping their backward pass is free speed.
    ``fixed_params`` must name the set the optimizer actually freezes
    (stage-2 alternate training passes FIXED_PARAMS_SHARED); defaults to
    ``cfg.network.FIXED_PARAMS``."""
    fixed = cfg.network.FIXED_PARAMS if fixed_params is None else fixed_params
    if cfg.network.name == "vgg":
        n = frozen_prefix_len(fixed, VGG_BLOCK_ORDER)
        return VGGBackbone(dtype=dtype, frozen_prefix=n), VGGTopHead(dtype=dtype)
    n = frozen_prefix_len(fixed, RESNET_BLOCK_ORDER, requires=("bn",))
    fold = cfg.network.FOLD_BN
    return (
        ResNetBackbone(depth=cfg.network.depth, dtype=dtype, frozen_prefix=n,
                       fold_bn=fold),
        ResNetTopHead(depth=cfg.network.depth, dtype=dtype, fold_bn=fold),
    )


class RPNOnly(nn.Module):
    """RPN training/inference graph (get_*_rpn / get_*_rpn_test twin).

    Param tree: {backbone, rpn} — name-compatible with FasterRCNN so
    stage checkpoints transfer by subtree copy.

    ``fixed_params``: the freeze set the optimizer will use, when it
    differs from cfg.network.FIXED_PARAMS (stage-4 alternate training
    freezes FIXED_PARAMS_SHARED) — keeps the backbone's backward-skip
    boundary aligned with the actual freeze.
    """

    cfg: Config
    fixed_params: Optional[Tuple[str, ...]] = None

    def setup(self):
        cfg = self.cfg
        dtype = _dtype_of(cfg)
        self.backbone, _ = build_backbone(cfg, dtype, self.fixed_params)
        self.rpn = RPNHead(
            num_anchors=cfg.network.NUM_ANCHORS, channels=512, dtype=dtype
        )

    def _anchors(self, feat_h: int, feat_w: int) -> jnp.ndarray:
        net = self.cfg.network
        return jnp.asarray(
            shifted_anchors(
                feat_h, feat_w, net.RPN_FEAT_STRIDE,
                ratios=net.ANCHOR_RATIOS, scales=net.ANCHOR_SCALES,
            )
        )

    def __call__(
        self,
        images: jnp.ndarray,
        im_info: jnp.ndarray,
        gt_boxes: Optional[jnp.ndarray] = None,
        gt_valid: Optional[jnp.ndarray] = None,
        train: bool = False,
        sample_seeds: Optional[jnp.ndarray] = None,
    ):
        from mx_rcnn_tpu.models.layers import normalize_images

        cfg = self.cfg
        t = cfg.TRAIN
        b = images.shape[0]
        feat = self.backbone(normalize_images(images, im_info, cfg))
        rpn_logits, rpn_deltas = self.rpn(feat)
        anchors = self._anchors(feat.shape[1], feat.shape[2])

        if not train:
            te = cfg.TEST
            fg_scores = jax.nn.softmax(rpn_logits, axis=-1)[..., 1]
            props = jax.vmap(
                lambda s, d, info: propose(
                    s, d, anchors, info, te.RPN_PRE_NMS_TOP_N,
                    te.RPN_POST_NMS_TOP_N, te.RPN_NMS_THRESH, te.RPN_MIN_SIZE,
                )
            )(fg_scores, rpn_deltas, im_info)
            return {
                "rois": props.rois,
                "roi_scores": props.scores,
                "roi_valid": props.valid,
            }

        key = self.make_rng("sampling")
        if sample_seeds is not None:
            keys = jax.vmap(lambda s: jax.random.fold_in(key, s))(sample_seeds)
        else:
            keys = jax.random.split(key, b)
        atgt = jax.vmap(
            lambda gtb, gtv, info, k: assign_anchor(
                anchors, gtb[:, :4], gtv, info, k, cfg
            )
        )(gt_boxes, gt_valid, im_info, keys)

        rpn_norm = float(t.RPN_BATCH_SIZE * b)
        rpn_cls_loss = softmax_cross_entropy(
            rpn_logits.reshape(-1, 2), atgt.labels.reshape(-1), -1, rpn_norm
        )
        rpn_bbox_loss = weighted_smooth_l1(
            rpn_deltas.reshape(-1, 4),
            atgt.bbox_targets.reshape(-1, 4),
            atgt.bbox_weights.reshape(-1, 4),
            sigma=3.0,
            norm=rpn_norm,
        )
        total = rpn_cls_loss + rpn_bbox_loss
        aux = {
            "RPNAcc": accuracy(rpn_logits.reshape(-1, 2), atgt.labels.reshape(-1)),
            "RPNLogLoss": rpn_cls_loss,
            "RPNL1Loss": rpn_bbox_loss,
            # diagnostic: zero here means no anchor fits the image border
            # (image smaller than the smallest anchor) — loss silently 0
            "num_fg_anchors": (atgt.labels == 1).sum(),
        }
        return total, aux


class FastRCNN(nn.Module):
    """Fast-R-CNN-on-proposals graph (get_*_rcnn / get_*_rcnn_test twin;
    TRAIN.HAS_RPN=False mode).  Proposals arrive from the batch (dumped by
    an RPN via ``generate_proposals``) instead of an in-graph RPN.

    Param tree: {backbone, top_head, rcnn} — name-compatible with
    FasterRCNN.  ``fixed_params`` as on :class:`RPNOnly`.
    """

    cfg: Config
    fixed_params: Optional[Tuple[str, ...]] = None

    def setup(self):
        cfg = self.cfg
        dtype = _dtype_of(cfg)
        self.backbone, self.top_head = build_backbone(cfg, dtype, self.fixed_params)
        self.rcnn = RCNNHead(num_classes=cfg.dataset.NUM_CLASSES, dtype=dtype)

    def _roi_features(
        self, feat: jnp.ndarray, rois: jnp.ndarray, fwd_only: bool = False
    ) -> jnp.ndarray:
        net = self.cfg.network
        pooled = extract_roi_features_batched(
            feat, rois, net.ROI_MODE, net.POOLED_SIZE,
            1.0 / net.RCNN_FEAT_STRIDE, net.ROI_SAMPLE_RATIO,
            fwd_only=fwd_only,
        )
        b, r = pooled.shape[0], pooled.shape[1]
        return self.top_head(pooled.reshape((b * r,) + pooled.shape[2:]))

    def __call__(
        self,
        images: jnp.ndarray,
        im_info: jnp.ndarray,
        proposals: jnp.ndarray = None,
        prop_valid: jnp.ndarray = None,
        gt_boxes: Optional[jnp.ndarray] = None,
        gt_valid: Optional[jnp.ndarray] = None,
        train: bool = False,
        sample_seeds: Optional[jnp.ndarray] = None,
    ):
        cfg = self.cfg
        from mx_rcnn_tpu.models.layers import normalize_images

        t = cfg.TRAIN
        b = images.shape[0]
        k = cfg.dataset.NUM_CLASSES
        feat = self.backbone(normalize_images(images, im_info, cfg))

        if not train:
            trunk = self._roi_features(feat, proposals, fwd_only=True)
            cls_logits, bbox_deltas = self.rcnn(trunk)
            r = proposals.shape[1]
            means, stds = bbox_denorm_vectors(cfg, k)
            bbox_deltas = bbox_deltas * stds[None, :] + means[None, :]
            return {
                "rois": proposals,
                "roi_scores": jnp.zeros(proposals.shape[:2], jnp.float32),
                "roi_valid": prop_valid,
                "cls_prob": jax.nn.softmax(cls_logits).reshape(b, r, k),
                "bbox_deltas": bbox_deltas.reshape(b, r, 4 * k),
            }

        key = self.make_rng("sampling")
        if sample_seeds is not None:
            keys = jax.vmap(lambda s: jax.random.fold_in(key, s))(sample_seeds)
        else:
            keys = jax.random.split(key, b)
        samples = jax.vmap(
            lambda r, rv, gtb, gtv, kk: sample_rois(r, rv, gtb, gtv, kk, cfg)
        )(proposals, prop_valid, gt_boxes, gt_valid, keys)

        trunk = self._roi_features(feat, samples.rois)
        cls_logits, bbox_pred_out = self.rcnn(trunk)
        labels = samples.labels.reshape(-1)
        bbox_targets = samples.bbox_targets.reshape(bbox_pred_out.shape)
        bbox_weights = samples.bbox_weights.reshape(bbox_pred_out.shape)

        rcnn_norm = float(t.BATCH_ROIS * b)
        rcnn_cls_loss = softmax_cross_entropy(cls_logits, labels, -1, rcnn_norm)
        rcnn_bbox_loss = weighted_smooth_l1(
            bbox_pred_out, bbox_targets, bbox_weights, sigma=1.0, norm=rcnn_norm
        )
        total = rcnn_cls_loss + rcnn_bbox_loss
        aux = {
            "RCNNAcc": accuracy(cls_logits, labels),
            "RCNNLogLoss": rcnn_cls_loss,
            "RCNNL1Loss": rcnn_bbox_loss,
            "num_fg_rois": (labels > 0).sum(),
        }
        return total, aux
