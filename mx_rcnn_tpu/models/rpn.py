"""Region Proposal Network head.

Reference: the ``rpn_conv_3x3`` → ``rpn_cls_score``/``rpn_bbox_pred`` limb
of ``rcnn/symbol/symbol_vgg.py :: get_vgg_train`` (and the resnet twin).
Emits per-anchor objectness logits and box deltas in the per-pixel
(y, x, anchor) layout that :func:`mx_rcnn_tpu.ops.anchors.shifted_anchors`
uses, so flattening the head output aligns 1:1 with the anchor table —
no reshuffling op needed (the reference needed explicit Reshape/transpose
gymnastics to match its NCHW layout; NHWC makes the layouts agree for
free).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

from mx_rcnn_tpu.models.layers import conv


class RPNHead(nn.Module):
    num_anchors: int = 9
    channels: int = 512
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, feat: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(B, H, W, C) → logits (B, H*W*A, 2), deltas (B, H*W*A, 4)."""
        b, h, w, _ = feat.shape
        x = conv(self.channels, 3, 1, self.dtype, name="rpn_conv", use_bias=True)(feat)
        x = nn.relu(x)
        logits = conv(
            2 * self.num_anchors, 1, 1, self.dtype, name="rpn_cls_score", use_bias=True
        )(x)
        deltas = conv(
            4 * self.num_anchors, 1, 1, self.dtype, name="rpn_bbox_pred", use_bias=True
        )(x)
        return (
            logits.reshape(b, h * w * self.num_anchors, 2).astype(jnp.float32),
            deltas.reshape(b, h * w * self.num_anchors, 4).astype(jnp.float32),
        )
