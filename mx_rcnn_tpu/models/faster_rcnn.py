"""Faster R-CNN: one Flax module, one jitted graph, zero host round-trips.

Reference: the train/test Symbol builders ``rcnn/symbol/symbol_vgg.py ::
get_vgg_train/test`` and ``symbol_resnet.py :: get_resnet_train/test``
(SURVEY §4.5) — but where the reference graph hops to Python twice per
step (proposal + proposal_target CustomOps), here the proposal layer,
anchor-target assignment, and roi sampling are all jnp inside the same
XLA program.  Anchors are a trace-time constant derived from the (static,
bucketed) feature shape — the reference needed ``feat_sym.infer_shape``
machinery for the same purpose (``rcnn/core/loader.py :: AnchorLoader``).

Train call returns (losses, aux-for-metrics); test call returns padded
detections inputs (rois, class probs, de-normalized deltas).  Bbox-target
normalization stays in the loss/test-path (never folded into weights —
SURVEY §5.5 explains the reference's checkpoint quirk we deliberately
avoid).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.heads import RCNNHead
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.ops.anchors import shifted_anchors
from mx_rcnn_tpu.ops.losses import (
    accuracy,
    smooth_l1,
    softmax_cross_entropy,
    weighted_smooth_l1,
)
from mx_rcnn_tpu.ops.proposal import _NEG_INF, anchor_grid_mask, propose
from mx_rcnn_tpu.ops.roi_align import extract_roi_features_batched
from mx_rcnn_tpu.ops.targets import assign_anchor, bbox_denorm_vectors, sample_rois


def _dtype_of(cfg: Config):
    return jnp.bfloat16 if cfg.network.COMPUTE_DTYPE == "bfloat16" else jnp.float32


class FasterRCNN(nn.Module):
    """Two-stage detector over a single-level feature map (VGG / ResNet-C4)."""

    cfg: Config

    def setup(self):
        cfg = self.cfg
        if cfg.network.USE_FPN:
            # loud failure until the FPN graph exists — silently training
            # a C4 model with FPN anchor settings was ADVICE r1's top bug
            raise NotImplementedError(
                "USE_FPN: FasterRCNN builds a single-level C4 graph; use the "
                "FPN model once implemented"
            )
        dtype = _dtype_of(cfg)
        from mx_rcnn_tpu.models.stage_models import build_backbone

        self.backbone, self.top_head = build_backbone(cfg, dtype)
        self.rpn = RPNHead(
            num_anchors=cfg.network.NUM_ANCHORS, channels=512, dtype=dtype
        )
        self.rcnn = RCNNHead(num_classes=cfg.dataset.NUM_CLASSES, dtype=dtype)
        if cfg.network.USE_MASK:
            raise NotImplementedError(
                "USE_MASK: mask targets/loss are not wired into the C4 "
                "graph; the mask path lands with the FPN model"
            )

    def _anchors(self, feat_h: int, feat_w: int) -> jnp.ndarray:
        net = self.cfg.network
        return jnp.asarray(
            shifted_anchors(
                feat_h,
                feat_w,
                net.RPN_FEAT_STRIDE,
                ratios=net.ANCHOR_RATIOS,
                scales=net.ANCHOR_SCALES,
            )
        )

    def _roi_features(
        self, feat: jnp.ndarray, rois: jnp.ndarray, fwd_only: bool = False,
        valid_hw=None,
    ) -> jnp.ndarray:
        """(B, Hf, Wf, C) × (B, R, 4) → (B*R, D) head trunk features."""
        net = self.cfg.network
        pooled = extract_roi_features_batched(
            feat,
            rois,
            net.ROI_MODE,
            net.POOLED_SIZE,
            1.0 / net.RCNN_FEAT_STRIDE,
            net.ROI_SAMPLE_RATIO,
            fwd_only=fwd_only,
            valid_hw=valid_hw,
        )
        b, r = pooled.shape[0], pooled.shape[1]
        return self.top_head(pooled.reshape((b * r,) + pooled.shape[2:]))

    def __call__(
        self,
        images: jnp.ndarray,
        im_info: jnp.ndarray,
        gt_boxes: Optional[jnp.ndarray] = None,
        gt_valid: Optional[jnp.ndarray] = None,
        train: bool = False,
        sample_seeds: Optional[jnp.ndarray] = None,
    ):
        from mx_rcnn_tpu.models.layers import normalize_images

        images = normalize_images(images, im_info, self.cfg)
        if train:
            return self.train_forward(
                images, im_info, gt_boxes, gt_valid, sample_seeds
            )
        return self.test_forward(images, im_info)

    # ------------------------------------------------------------------ train
    def train_forward(
        self,
        images: jnp.ndarray,
        im_info: jnp.ndarray,
        gt_boxes: jnp.ndarray,
        gt_valid: jnp.ndarray,
        sample_seeds: Optional[jnp.ndarray] = None,
    ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
        cfg = self.cfg
        t = cfg.TRAIN
        b = images.shape[0]

        feat = self.backbone(images)
        rpn_logits, rpn_deltas = self.rpn(feat)           # (B, N, 2/4)
        anchors = self._anchors(feat.shape[1], feat.shape[2])

        key = self.make_rng("sampling")
        # per-image keys from batch-supplied seeds when available: sampling
        # then depends only on (step rng, image id), so any device topology
        # (1 chip × batch B or B chips × batch 1) draws identical samples —
        # the property the DP-equivalence test asserts exactly
        if sample_seeds is not None:
            keys = jax.vmap(
                lambda s: jax.random.split(jax.random.fold_in(key, s), 2)
            )(sample_seeds)
        else:
            keys = jax.random.split(key, (b, 2))

        # --- RPN anchor targets (reference: rcnn/io/rpn.py :: assign_anchor)
        atgt = jax.vmap(
            lambda gtb, gtv, info, k: assign_anchor(anchors, gtb[:, :4], gtv, info, k, cfg)
        )(gt_boxes, gt_valid, im_info, keys[:, 0])

        # --- proposals (stop-gradient: reference proposal op has no backward)
        fg_scores = jax.nn.softmax(rpn_logits, axis=-1)[..., 1]
        props = jax.vmap(
            lambda s, d, info: propose(
                s,
                d,
                anchors,
                info,
                t.RPN_PRE_NMS_TOP_N,
                t.RPN_POST_NMS_TOP_N,
                t.RPN_NMS_THRESH,
                t.RPN_MIN_SIZE,
            )
        )(jax.lax.stop_gradient(fg_scores), jax.lax.stop_gradient(rpn_deltas), im_info)

        # --- sample rois + RCNN targets (reference: proposal_target CustomOp)
        samples = jax.vmap(
            lambda r, rv, gtb, gtv, k: sample_rois(r, rv, gtb, gtv, k, cfg)
        )(props.rois, props.valid, gt_boxes, gt_valid, keys[:, 1])

        # --- second stage
        trunk = self._roi_features(feat, samples.rois)     # (B*R, D)
        cls_logits, bbox_pred_out = self.rcnn(trunk)       # (B*R, K), (B*R, 4K)

        labels = samples.labels.reshape(-1)
        bbox_targets = samples.bbox_targets.reshape(bbox_pred_out.shape)
        bbox_weights = samples.bbox_weights.reshape(bbox_pred_out.shape)

        # --- losses, reference normalization semantics (SURVEY §4.5)
        rpn_norm = float(t.RPN_BATCH_SIZE * b)
        rcnn_norm = float(t.BATCH_ROIS * b)
        rpn_cls_loss = softmax_cross_entropy(
            rpn_logits.reshape(-1, 2), atgt.labels.reshape(-1), -1, rpn_norm
        )
        rpn_bbox_loss = weighted_smooth_l1(
            rpn_deltas.reshape(-1, 4),
            atgt.bbox_targets.reshape(-1, 4),
            atgt.bbox_weights.reshape(-1, 4),
            sigma=3.0,
            norm=rpn_norm,
        )
        rcnn_cls_loss = softmax_cross_entropy(cls_logits, labels, -1, rcnn_norm)
        rcnn_bbox_loss = weighted_smooth_l1(
            bbox_pred_out, bbox_targets, bbox_weights, sigma=1.0, norm=rcnn_norm
        )
        total = rpn_cls_loss + rpn_bbox_loss + rcnn_cls_loss + rcnn_bbox_loss

        aux = {
            # the reference's six metrics (rcnn/core/metric.py), same names
            "RPNAcc": accuracy(rpn_logits.reshape(-1, 2), atgt.labels.reshape(-1)),
            "RPNLogLoss": rpn_cls_loss,
            "RPNL1Loss": rpn_bbox_loss,
            "RCNNAcc": accuracy(cls_logits, labels),
            "RCNNLogLoss": rcnn_cls_loss,
            "RCNNL1Loss": rcnn_bbox_loss,
            "num_fg_rois": (labels > 0).sum(),
            "num_valid_props": props.valid.sum(),
            # zero when the image is smaller than every anchor (RPN loss
            # silently contributes nothing) — watch this on tiny inputs
            "num_fg_anchors": (atgt.labels == 1).sum(),
        }
        return total, aux

    # ------------------------------------------------------------------- test
    def test_forward(self, images: jnp.ndarray, im_info: jnp.ndarray):
        """→ dict with padded per-image rois, class probs, decoded deltas.

        Mirrors ``get_*_test`` + the head of ``rcnn/core/tester.py ::
        im_detect``: proposals from the RPN, class posteriors, and
        *de-normalized* class-specific deltas (the reference baked the
        de-normalization into saved weights; we keep it explicit here).
        """
        cfg = self.cfg
        te = cfg.TEST
        from mx_rcnn_tpu.models.layers import make_pad_mask, pad_feat_to_ladder

        # serving invariance: re-zero bucket padding before every spatial
        # op (frozen BN repaints zeros with its bias, so without this the
        # edge convs read different neighbours on different canvases and
        # detections depend on the bucket).  Inference-only — the train
        # graph keeps its original arithmetic.
        pad_mask = make_pad_mask(im_info, (images.shape[1], images.shape[2]))
        feat = pad_mask(self.backbone(images, pad_mask=pad_mask))
        rpn_logits, rpn_deltas = self.rpn(feat)
        anchors = self._anchors(feat.shape[1], feat.shape[2])

        fg_scores = jax.nn.softmax(rpn_logits, axis=-1)[..., 1]
        # kill anchors sitting on bucket padding: their scores come from
        # zero-padded features, so keeping them would make the pre-NMS
        # top-k set (and thus detections) depend on which bucket the
        # image padded into.  Inference-only — train keeps the full pool
        # (its tuned gate trajectories assume it).
        grid_ok = jax.vmap(
            lambda info: anchor_grid_mask(
                ((feat.shape[1], feat.shape[2]),),
                (cfg.network.RPN_FEAT_STRIDE,),
                cfg.network.NUM_ANCHORS,
                info,
            )
        )(im_info)
        fg_scores = jnp.where(grid_ok, fg_scores, _NEG_INF)
        props = jax.vmap(
            lambda s, d, info: propose(
                s,
                d,
                anchors,
                info,
                te.RPN_PRE_NMS_TOP_N,
                te.RPN_POST_NMS_TOP_N,
                te.RPN_NMS_THRESH,
                te.RPN_MIN_SIZE,
            )
        )(fg_scores, rpn_deltas, im_info)

        # one ladder-wide shape into roi_align so the second stage is the
        # SAME program for every bucket (see layers.pad_feat_to_ladder)
        feat = pad_feat_to_ladder(
            feat, cfg.network.RCNN_FEAT_STRIDE, cfg.SHAPE_BUCKETS
        )
        trunk = self._roi_features(
            feat, props.rois, fwd_only=True, valid_hw=im_info[:, :2]
        )
        cls_logits, bbox_deltas = self.rcnn(trunk)
        b, r = images.shape[0], te.RPN_POST_NMS_TOP_N
        k = cfg.dataset.NUM_CLASSES

        means, stds = bbox_denorm_vectors(cfg, k)
        bbox_deltas = bbox_deltas * stds[None, :] + means[None, :]

        return {
            "rois": props.rois,                                  # (B, R, 4)
            "roi_scores": props.scores,                          # (B, R)
            "roi_valid": props.valid,                            # (B, R)
            "cls_prob": jax.nn.softmax(cls_logits).reshape(b, r, k),
            "bbox_deltas": bbox_deltas.reshape(b, r, 4 * k),
        }
