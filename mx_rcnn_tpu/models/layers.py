"""Shared NN building blocks.

TPU-first conventions: NHWC layout (XLA's native conv layout on TPU),
optional bfloat16 compute with float32 parameters (MXU-friendly), and
*frozen* batch-norm as an affine transform using stored moments —
the reference runs every BN with ``use_global_stats=True`` during detection
training (``rcnn/symbol/symbol_resnet.py :: residual_unit``, eps 2e-5), so
BN never updates and is exactly a per-channel scale/shift.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class FrozenBatchNorm(nn.Module):
    """BatchNorm with frozen moments: y = (x - mean) / sqrt(var + eps) * γ + β.

    All four tensors live in ``params`` so checkpoints carry them, but
    ``mean``/``var`` get zero gradient by construction (they only appear
    inside ``lax.stop_gradient``) and γ/β are excluded from the optimizer
    via the FIXED_PARAMS mask (reference: ``FIXED_PARAMS`` incl. BN
    gammas/betas).
    """

    eps: float = 2e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        mean = self.param("mean", nn.initializers.zeros, (c,), jnp.float32)
        var = self.param("var", nn.initializers.ones, (c,), jnp.float32)
        mean = jax.lax.stop_gradient(mean)
        var = jax.lax.stop_gradient(var)
        # fold into a single multiply-add; XLA fuses it into the conv
        mul = scale * jax.lax.rsqrt(var + self.eps)
        add = bias - mean * mul
        return (x * mul.astype(self.dtype) + add.astype(self.dtype)).astype(self.dtype)


def normalize_images(images: jnp.ndarray, im_info, cfg) -> jnp.ndarray:
    """On-device image normalization for uint8-transferred batches
    (TEST.UINT8_TRANSFER: raw RGB crosses host→device at 1/4 the bytes).
    float batches arrive already normalized by the loader and pass
    through untouched, so every model entry point can call this
    unconditionally.

    The bucket padding is re-zeroed from ``im_info`` (true pre-padding
    h/w): the host path pads AFTER normalization, so padding must be 0
    in normalized space — normalizing raw zero pixels would instead
    paint the padding "blacker than black" ((0−mean)/std) and shift
    boundary conv features vs the float path."""
    if images.dtype != jnp.uint8:
        return images
    means = jnp.asarray(cfg.network.PIXEL_MEANS, jnp.float32)
    inv_stds = 1.0 / jnp.asarray(cfg.network.PIXEL_STDS, jnp.float32)
    out = (images.astype(jnp.float32) - means) * inv_stds
    bh, bw = images.shape[1], images.shape[2]
    rows = jnp.arange(bh, dtype=jnp.float32)[None, :, None, None]
    cols = jnp.arange(bw, dtype=jnp.float32)[None, None, :, None]
    mask = (rows < im_info[:, 0, None, None, None]) & (
        cols < im_info[:, 1, None, None, None]
    )
    return out * mask


def conv(
    features: int,
    kernel: int,
    stride: int = 1,
    dtype: Any = jnp.float32,
    name: str | None = None,
    use_bias: bool = False,
    dilation: int = 1,
) -> nn.Conv:
    """3x3/1x1/7x7 conv helper, NHWC, f32 params.

    Padding is explicit symmetric ``(k-1)//2`` — identical to SAME at
    stride 1, but at stride 2 SAME pads (0, 1) while every public
    ResNet/VGG checkpoint family (caffe/torch) pads symmetrically; the
    explicit form keeps imported pretrained weights spatially aligned.
    """
    pad = dilation * (kernel - 1) // 2
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        use_bias=use_bias,
        kernel_dilation=(dilation, dilation),
        dtype=dtype,
        param_dtype=jnp.float32,
        name=name,
    )
