"""Shared NN building blocks.

TPU-first conventions: NHWC layout (XLA's native conv layout on TPU),
optional bfloat16 compute with float32 parameters (MXU-friendly), and
*frozen* batch-norm as an affine transform using stored moments —
the reference runs every BN with ``use_global_stats=True`` during detection
training (``rcnn/symbol/symbol_resnet.py :: residual_unit``, eps 2e-5), so
BN never updates and is exactly a per-channel scale/shift.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

# the reference's BN epsilon (use_global_stats=True, eps 2e-5); shared by
# the unfused FrozenBatchNorm and the folded fused_conv_bn so the two
# graphs can never silently diverge
BN_EPS = 2e-5


class FrozenBatchNorm(nn.Module):
    """BatchNorm with frozen moments: y = (x - mean) / sqrt(var + eps) * γ + β.

    All four tensors live in ``params`` so checkpoints carry them, but
    ``mean``/``var`` get zero gradient by construction (they only appear
    inside ``lax.stop_gradient``) and γ/β are excluded from the optimizer
    via the FIXED_PARAMS mask (reference: ``FIXED_PARAMS`` incl. BN
    gammas/betas).
    """

    eps: float = BN_EPS
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        c = x.shape[-1]
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        mean = self.param("mean", nn.initializers.zeros, (c,), jnp.float32)
        var = self.param("var", nn.initializers.ones, (c,), jnp.float32)
        mean = jax.lax.stop_gradient(mean)
        var = jax.lax.stop_gradient(var)
        # fold into a single multiply-add; XLA fuses it into the conv
        mul = scale * jax.lax.rsqrt(var + self.eps)
        add = bias - mean * mul
        return (x * mul.astype(self.dtype) + add.astype(self.dtype)).astype(self.dtype)


def normalize_images(images: jnp.ndarray, im_info, cfg) -> jnp.ndarray:
    """On-device image normalization for uint8-transferred batches
    (TEST.UINT8_TRANSFER: raw RGB crosses host→device at 1/4 the bytes).
    float batches arrive already normalized by the loader and pass
    through untouched, so every model entry point can call this
    unconditionally.

    The bucket padding is re-zeroed from ``im_info`` (true pre-padding
    h/w): the host path pads AFTER normalization, so padding must be 0
    in normalized space — normalizing raw zero pixels would instead
    paint the padding "blacker than black" ((0−mean)/std) and shift
    boundary conv features vs the float path."""
    if images.dtype != jnp.uint8:
        return images
    means = jnp.asarray(cfg.network.PIXEL_MEANS, jnp.float32)
    inv_stds = 1.0 / jnp.asarray(cfg.network.PIXEL_STDS, jnp.float32)
    out = (images.astype(jnp.float32) - means) * inv_stds
    bh, bw = images.shape[1], images.shape[2]
    rows = jnp.arange(bh, dtype=jnp.float32)[None, :, None, None]
    cols = jnp.arange(bw, dtype=jnp.float32)[None, None, :, None]
    mask = (rows < im_info[:, 0, None, None, None]) & (
        cols < im_info[:, 1, None, None, None]
    )
    return out * mask


def make_pad_mask(im_info, canvas_hw):
    """→ ``fn(x)`` that zeroes feature cells sitting on bucket padding.

    The serving/inference invariance tool: ``normalize_images`` zeroes
    the padding at the input, but the first frozen BN maps those zeros to
    its bias, so every subsequent k>1 conv at the valid-region edge would
    read different neighbours on an exact-fit canvas (explicit zero
    padding) than on a larger bucket (BN-propagated values) — detections
    would depend on which bucket the image landed in.  Re-zeroing the pad
    region *before each spatial op* restores the induction: edge convs
    read zeros on every canvas, so the valid region is bitwise canvas-
    independent (at fixed batch size; XLA's conv algorithm choice varies
    with batch).

    A cell (y, x) at feature stride s is valid iff ``s·y < h`` — the same
    criterion as ``ops.proposal.anchor_grid_mask``.  The stride is
    recovered from the canvas/feature ratio snapped to a power of two
    (feature extents are ceil-of-halving chains, so the ratio is exact
    for bucket-divisible levels and within [s/2, s] otherwise)."""
    ch, cw = float(canvas_hw[0]), float(canvas_hw[1])

    def snap(ratio: float) -> float:
        import math

        return float(2 ** round(math.log2(ratio))) if ratio > 1.0 else 1.0

    def apply(x: jnp.ndarray) -> jnp.ndarray:
        fh, fw = x.shape[1], x.shape[2]
        sy, sx = snap(ch / fh), snap(cw / fw)
        rows = jnp.arange(fh, dtype=jnp.float32) * sy
        cols = jnp.arange(fw, dtype=jnp.float32) * sx
        ok = (rows[None, :] < im_info[:, 0, None])[:, :, None] & (
            cols[None, :] < im_info[:, 1, None]
        )[:, None, :]
        return x * ok[..., None].astype(x.dtype)

    return apply


def pad_feat_to_ladder(feat: jnp.ndarray, stride: int, shape_buckets):
    """Zero-pad a (B, H, W, C) feature map to the bucket ladder's max
    extent at this stride.

    Companion to :func:`make_pad_mask` for EXACT cross-bucket serving
    invariance: the masked feature values are canvas-independent, but the
    roi-align → heads subgraph still compiles per canvas shape, and XLA's
    shape-dependent scheduling can reassociate its reductions differently
    (observed at ~1e-6 on box deltas under multi-device CPU).  Padding
    the (masked) map to one ladder-wide shape gives that subgraph a
    single HLO signature — identical inputs, identical program, identical
    bits.  No-op when the canvas already reaches the ladder max (callers
    outside the ladder keep their shapes)."""
    if not shape_buckets:
        return feat
    th = max(feat.shape[1], max(-(-bh // stride) for bh, _ in shape_buckets))
    tw = max(feat.shape[2], max(-(-bw // stride) for _, bw in shape_buckets))
    if (th, tw) == (feat.shape[1], feat.shape[2]):
        return feat
    return jnp.pad(
        feat,
        ((0, 0), (0, th - feat.shape[1]), (0, tw - feat.shape[2]), (0, 0)),
    )


class _ConvKernel(nn.Module):
    """Parameter bank declaring an nn.Conv-compatible HWIO kernel.

    Same param name ("kernel"), shape, dtype, and initializer as the
    nn.Conv the unfused path builds, so a module that swaps between
    fused and unfused conv+BN keeps a byte-identical param tree."""

    features: int
    kernel: int

    @nn.compact
    def __call__(self, cin: int) -> jnp.ndarray:
        return self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (self.kernel, self.kernel, cin, self.features),
            jnp.float32,
        )


class _BNParams(nn.Module):
    """Parameter bank declaring FrozenBatchNorm's four tensors."""

    @nn.compact
    def __call__(self, c: int):
        scale = self.param("scale", nn.initializers.ones, (c,), jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (c,), jnp.float32)
        mean = self.param("mean", nn.initializers.zeros, (c,), jnp.float32)
        var = self.param("var", nn.initializers.ones, (c,), jnp.float32)
        return scale, bias, mean, var


def fused_conv_bn(
    x: jnp.ndarray,
    features: int,
    kernel: int,
    stride: int,
    dtype: Any,
    conv_name: str,
    bn_name: str,
    eps: float = BN_EPS,
) -> jnp.ndarray:
    """conv → FrozenBatchNorm with the BN affine folded into the kernel.

    Algebraically identical to the unfused pair — y = conv(x, W)·mul + add
    = conv(x, W·mul) + add since mul is per-output-channel — but the
    fold happens on the (tiny) weight tensor in f32 instead of the (huge)
    activation tensor, removing the activation-side multiply and its
    backward twin entirely.  Gradients flow to W and the BN affine
    through the fold arithmetic unchanged; mean/var stay stop_gradient'd
    exactly as in FrozenBatchNorm.  Param paths ({conv_name}/kernel,
    {bn_name}/{scale,bias,mean,var}) match the unfused modules, so
    checkpoints and the pretrained importer work with either path.

    Call only inside an @nn.compact parent (instantiates param banks)."""
    w = _ConvKernel(features, kernel, name=conv_name)(x.shape[-1])
    scale, bias, mean, var = _BNParams(name=bn_name)(features)
    mean = jax.lax.stop_gradient(mean)
    var = jax.lax.stop_gradient(var)
    mul = scale * jax.lax.rsqrt(var + eps)            # (cout,) f32
    w = (w * mul[None, None, None, :]).astype(dtype)
    add = (bias - mean * mul).astype(dtype)
    pad = (kernel - 1) // 2
    y = jax.lax.conv_general_dilated(
        x.astype(dtype),
        w,
        (stride, stride),
        [(pad, pad), (pad, pad)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    return y + add


def make_conv_bn(fold: bool, dtype: Any):
    """→ ``cbn(x, features, kernel, stride, conv_name, bn_name)`` — ONE
    conv→frozen-BN wiring shared by the folded and unfused graphs, so a
    structural edit (stride placement, shortcut condition) can never be
    made on one side only.  Param paths are identical either way."""
    if fold:
        def cbn(x, features, kernel, stride, conv_name, bn_name):
            return fused_conv_bn(
                x, features, kernel, stride, dtype, conv_name, bn_name
            )
    else:
        def cbn(x, features, kernel, stride, conv_name, bn_name):
            y = conv(features, kernel, stride, dtype, name=conv_name)(x)
            return FrozenBatchNorm(dtype=dtype, name=bn_name)(y)
    return cbn


def conv(
    features: int,
    kernel: int,
    stride: int = 1,
    dtype: Any = jnp.float32,
    name: str | None = None,
    use_bias: bool = False,
    dilation: int = 1,
) -> nn.Conv:
    """3x3/1x1/7x7 conv helper, NHWC, f32 params.

    Padding is explicit symmetric ``(k-1)//2`` — identical to SAME at
    stride 1, but at stride 2 SAME pads (0, 1) while every public
    ResNet/VGG checkpoint family (caffe/torch) pads symmetrically; the
    explicit form keeps imported pretrained weights spatially aligned.
    """
    pad = dilation * (kernel - 1) // 2
    return nn.Conv(
        features,
        (kernel, kernel),
        strides=(stride, stride),
        padding=((pad, pad), (pad, pad)),
        use_bias=use_bias,
        kernel_dilation=(dilation, dilation),
        dtype=dtype,
        param_dtype=jnp.float32,
        name=name,
    )
