"""Feature Pyramid Network Faster R-CNN (BASELINE config 4).

No reference twin — the MXNet reference has no FPN (SURVEY §7.2 step 7
calls this new design work).  Design follows Lin et al. CVPR'17 with
TPU-native shape discipline throughout:

- **Neck**: lateral 1×1 convs on C2..C5 + nearest top-down upsample-add +
  3×3 smoothing → P2..P5; P6 = stride-2 maxpool of P5 (RPN only).
- **Anchors**: one scale per level (FPN_ANCHOR_SCALES) × 3 ratios on
  strides FPN_FEAT_STRIDES; all levels concatenated into ONE static
  anchor table, so RPN target assignment (``assign_anchor``) is the
  unmodified single-level code on a bigger N.
- **Proposals**: per-level top-k (bounds work per level), then one NMS
  over the union — fixed shapes, Pallas NMS on TPU.
- **ROI level assignment**: k = ⌊k0 + log2(√(wh)/224)⌋ clamped to
  [2, 5].  Rather than gathering rois per level (dynamic shapes), ROI
  features are extracted from ALL four levels with the batched Pallas
  ROIAlign and blended with a one-hot level mask — 4× flops on a cheap
  op in exchange for a single fused static-shape graph.
- **Head**: 2-fc (1024) box head (the standard FPN-RCNN head; conv5 has
  no place once the pyramid exists).

Param tree: {backbone, neck, rpn, top_head, rcnn} — backbone includes
stage4 (C5 is part of the pyramid), so the torchvision importer maps
layer4 into the backbone here (``import_resnet(..., fpn=True)``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.models.heads import MaskHead, RCNNHead
from mx_rcnn_tpu.models.layers import conv
from mx_rcnn_tpu.models.resnet import (
    RESNET_BLOCK_ORDER,
    ResNetBackbone,
    frozen_prefix_len,
)
from mx_rcnn_tpu.models.rpn import RPNHead
from mx_rcnn_tpu.ops.anchors import shifted_anchors
from mx_rcnn_tpu.ops.losses import (
    accuracy,
    one_hot_select,
    softmax_cross_entropy,
    weighted_smooth_l1,
)
from mx_rcnn_tpu.ops.nms import nms
from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.proposal import anchor_grid_mask
from mx_rcnn_tpu.ops.roi_align import extract_roi_features_batched
from mx_rcnn_tpu.ops.targets import assign_anchor, bbox_denorm_vectors, sample_rois

_NEG_INF = -1e10


def _dtype_of(cfg: Config):
    return jnp.bfloat16 if cfg.network.COMPUTE_DTYPE == "bfloat16" else jnp.float32


class FPNNeck(nn.Module):
    """C2..C5 → P2..P5 (+P6 via maxpool, appended by the caller)."""

    channels: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, feats: Tuple[jnp.ndarray, ...],
                 pad_mask=None) -> List[jnp.ndarray]:
        # pad_mask (layers.make_pad_mask): re-zero bucket padding before
        # the 3×3 smoothing convs — the laterals' biases repaint it
        # nonzero, and the 1×1s / nearest upsample are spatially safe
        # (valid fine cell i reads coarse cell ⌊i/2⌋, itself valid)
        pm = pad_mask if pad_mask is not None else (lambda v: v)
        c2, c3, c4, c5 = feats
        laterals = [
            conv(self.channels, 1, 1, self.dtype, name=f"lateral{i + 2}",
                 use_bias=True)(c)
            for i, c in enumerate((c2, c3, c4, c5))
        ]
        # top-down: nearest-neighbour upsample + add
        outs = [laterals[3]]
        for i in (2, 1, 0):
            up = outs[0]
            target = laterals[i]
            up = jax.image.resize(
                up, target.shape[:1] + target.shape[1:3] + up.shape[3:],
                method="nearest",
            )
            outs.insert(0, target + up)
        return [
            conv(self.channels, 3, 1, self.dtype, name=f"post{i + 2}",
                 use_bias=True)(pm(p))
            for i, p in enumerate(outs)
        ]


class FPNTopHead(nn.Module):
    """2-fc box head on pooled rois: (R, 7, 7, C) → (R, 1024)."""

    width: int = 1024
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, rois_feat: jnp.ndarray) -> jnp.ndarray:
        x = rois_feat.reshape(rois_feat.shape[0], -1)
        x = nn.Dense(self.width, dtype=self.dtype, param_dtype=jnp.float32,
                     name="fc1")(x)
        x = nn.relu(x)
        x = nn.Dense(self.width, dtype=self.dtype, param_dtype=jnp.float32,
                     name="fc2")(x)
        return nn.relu(x)


def roi_levels(rois: jnp.ndarray, k0: int = 4, canonical: float = 224.0,
               lo: int = 2, hi: int = 5) -> jnp.ndarray:
    """(…, 4) boxes → FPN level index in [lo, hi] (Lin et al. eq. 1)."""
    w = jnp.maximum(rois[..., 2] - rois[..., 0] + 1.0, 1.0)
    h = jnp.maximum(rois[..., 3] - rois[..., 1] + 1.0, 1.0)
    k = jnp.floor(k0 + jnp.log2(jnp.sqrt(w * h) / canonical))
    return jnp.clip(k, lo, hi).astype(jnp.int32)


class FPNFasterRCNN(nn.Module):
    """Multi-level two-stage detector; same external contract as
    :class:`FasterRCNN` (train → (loss, aux); test → padded detections),
    so the trainer/Predictor/eval stack is reused unchanged."""

    cfg: Config

    def setup(self):
        cfg = self.cfg
        dtype = _dtype_of(cfg)
        self.backbone = ResNetBackbone(
            depth=cfg.network.depth,
            dtype=dtype,
            return_pyramid=True,
            frozen_prefix=frozen_prefix_len(
                cfg.network.FIXED_PARAMS, RESNET_BLOCK_ORDER, requires=("bn",)
            ),
            fold_bn=cfg.network.FOLD_BN,
        )
        self.neck = FPNNeck(channels=cfg.network.FPN_CHANNELS, dtype=dtype)
        # one RPN head shared across levels (FPN paper); 3 anchors/cell
        self.rpn = RPNHead(
            num_anchors=len(cfg.network.ANCHOR_RATIOS)
            * len(cfg.network.FPN_ANCHOR_SCALES),
            channels=cfg.network.FPN_CHANNELS,
            dtype=dtype,
        )
        self.top_head = FPNTopHead(dtype=dtype)
        self.rcnn = RCNNHead(num_classes=cfg.dataset.NUM_CLASSES, dtype=dtype)
        if cfg.network.USE_MASK:
            self.mask_head = MaskHead(
                num_classes=cfg.dataset.NUM_CLASSES, dtype=dtype
            )

    # ----------------------------------------------------------- helpers
    def _pyramid(self, images: jnp.ndarray, pad_mask=None) -> List[jnp.ndarray]:
        """→ [P2, P3, P4, P5, P6].  P6's 1×1-window pool mixes nothing
        spatially, so it needs no mask of its own."""
        c_feats = self.backbone(images, pad_mask=pad_mask)
        ps = self.neck(c_feats, pad_mask=pad_mask)
        p6 = nn.max_pool(ps[-1], (1, 1), strides=(2, 2))
        return ps + [p6]

    def _level_anchors(self, shapes) -> List[np.ndarray]:
        net = self.cfg.network
        return [
            shifted_anchors(
                h, w, stride,
                ratios=net.ANCHOR_RATIOS, scales=net.FPN_ANCHOR_SCALES,
            )
            for (h, w), stride in zip(shapes, net.FPN_FEAT_STRIDES)
        ]

    def _rpn_over_levels(self, pyramid):
        """Shared RPN on each level → concat logits/deltas + anchor table."""
        logits, deltas = [], []
        for p in pyramid:
            lg, dl = self.rpn(p)               # (B, Hl*Wl*A, 2/4)
            logits.append(lg)
            deltas.append(dl)
        shapes = [(p.shape[1], p.shape[2]) for p in pyramid]
        anchors = jnp.asarray(
            np.concatenate(self._level_anchors(shapes), axis=0)
        )
        bounds = np.cumsum(
            [0] + [lg.shape[1] for lg in logits]
        )  # python ints, static
        return (
            jnp.concatenate(logits, axis=1),
            jnp.concatenate(deltas, axis=1),
            anchors,
            bounds,
        )

    def _propose_multilevel(
        self, fg_scores, deltas, anchors, bounds, im_info,
        pre_per_level, post_nms, nms_thresh, min_size,
    ):
        """One image: per-level top-k → union NMS → fixed post_nms set."""
        h, w, scale = im_info[0], im_info[1], im_info[2]
        boxes = bbox_pred(anchors, deltas)
        boxes = clip_boxes(boxes, (h, w))
        ms = min_size * scale
        ws = boxes[:, 2] - boxes[:, 0] + 1.0
        hs = boxes[:, 3] - boxes[:, 1] + 1.0
        keep = (ws >= ms) & (hs >= ms)
        scores = jnp.where(keep, fg_scores, _NEG_INF)

        top_boxes, top_scores = [], []
        for li in range(len(bounds) - 1):
            s_l = scores[bounds[li]:bounds[li + 1]]
            b_l = boxes[bounds[li]:bounds[li + 1]]
            k = min(pre_per_level, s_l.shape[0])
            ts, idx = jax.lax.top_k(s_l, k)
            top_scores.append(ts)
            top_boxes.append(b_l[idx])
        cat_scores = jnp.concatenate(top_scores)
        cat_boxes = jnp.concatenate(top_boxes, axis=0)
        valid = cat_scores > _NEG_INF / 2
        out_boxes, out_scores, out_valid = nms(
            cat_boxes, cat_scores, nms_thresh, post_nms, valid
        )
        return out_boxes, out_scores, out_valid

    def _roi_features(
        self, pyramid, rois: jnp.ndarray, fwd_only: bool = False,
        valid_hw=None,
    ) -> jnp.ndarray:
        """Masked multi-level ROIAlign: (B, R, 4) → (B*R, D)."""
        net = self.cfg.network
        levels = roi_levels(rois)                        # (B, R) in [2, 5]
        pooled = None
        for li, stride in enumerate(net.FPN_FEAT_STRIDES[:4]):  # P2..P5
            feats = extract_roi_features_batched(
                pyramid[li], rois, "roi_align", net.POOLED_SIZE,
                1.0 / stride, net.ROI_SAMPLE_RATIO, fwd_only=fwd_only,
                valid_hw=valid_hw,
            )                                            # (B, R, ph, pw, C)
            mask = (levels == li + 2)[..., None, None, None]
            contrib = jnp.where(mask, feats, 0.0)
            pooled = contrib if pooled is None else pooled + contrib
        b, r = pooled.shape[0], pooled.shape[1]
        return self.top_head(pooled.reshape((b * r,) + pooled.shape[2:]))

    # ------------------------------------------------------------------ api
    def __call__(
        self,
        images: jnp.ndarray,
        im_info: jnp.ndarray,
        gt_boxes: Optional[jnp.ndarray] = None,
        gt_valid: Optional[jnp.ndarray] = None,
        train: bool = False,
        sample_seeds: Optional[jnp.ndarray] = None,
        gt_masks: Optional[jnp.ndarray] = None,
        proposals: Optional[jnp.ndarray] = None,
        prop_valid: Optional[jnp.ndarray] = None,
    ):
        from mx_rcnn_tpu.models.layers import normalize_images

        images = normalize_images(images, im_info, self.cfg)
        if train:
            return self.train_forward(
                images, im_info, gt_boxes, gt_valid, sample_seeds, gt_masks,
                proposals, prop_valid,
            )
        return self.test_forward(images, im_info)

    def train_forward(self, images, im_info, gt_boxes, gt_valid,
                      sample_seeds=None, gt_masks=None,
                      proposals=None, prop_valid=None):
        cfg = self.cfg
        t = cfg.TRAIN
        b = images.shape[0]
        pyramid = self._pyramid(images)
        rpn_logits, rpn_deltas, anchors, bounds = self._rpn_over_levels(pyramid)

        key = self.make_rng("sampling")
        if sample_seeds is not None:
            keys = jax.vmap(
                lambda s: jax.random.split(jax.random.fold_in(key, s), 2)
            )(sample_seeds)
        else:
            keys = jax.random.split(key, (b, 2))

        atgt = jax.vmap(
            lambda gtb, gtv, info, k: assign_anchor(
                anchors, gtb[:, :4], gtv, info, k, cfg
            )
        )(gt_boxes, gt_valid, im_info, keys[:, 0])

        fg_scores = jax.nn.softmax(rpn_logits, axis=-1)[..., 1]
        if proposals is not None:
            # frozen-proposal mode (ROIIter role / churn ablation): the
            # RCNN+mask branches train on an EXTERNAL fixed proposal set
            # instead of the live RPN's — RPN losses still train the RPN,
            # but its drift no longer reshuffles roi labels step to step
            if prop_valid is None:
                raise ValueError(
                    "frozen-proposal mode needs prop_valid alongside "
                    "proposals (a padded-count validity mask)"
                )
            prop_boxes = proposals
        else:
            n_levels = len(bounds) - 1
            pre_per_level = max(t.RPN_PRE_NMS_TOP_N // n_levels, 256)
            prop_boxes, prop_scores, prop_valid = jax.vmap(
                lambda s, d, info: self._propose_multilevel(
                    s, d, anchors, bounds, info, pre_per_level,
                    t.RPN_POST_NMS_TOP_N, t.RPN_NMS_THRESH, t.RPN_MIN_SIZE,
                )
            )(
                jax.lax.stop_gradient(fg_scores),
                jax.lax.stop_gradient(rpn_deltas),
                im_info,
            )

        samples = jax.vmap(
            lambda r, rv, gtb, gtv, k: sample_rois(r, rv, gtb, gtv, k, cfg)
        )(prop_boxes, prop_valid, gt_boxes, gt_valid, keys[:, 1])

        trunk = self._roi_features(pyramid, samples.rois)
        cls_logits, bbox_pred_out = self.rcnn(trunk)
        labels = samples.labels.reshape(-1)
        bbox_targets = samples.bbox_targets.reshape(bbox_pred_out.shape)
        bbox_weights = samples.bbox_weights.reshape(bbox_pred_out.shape)

        rpn_norm = float(t.RPN_BATCH_SIZE * b)
        rcnn_norm = float(t.BATCH_ROIS * b)
        rpn_cls_loss = softmax_cross_entropy(
            rpn_logits.reshape(-1, 2), atgt.labels.reshape(-1), -1, rpn_norm
        )
        rpn_bbox_loss = weighted_smooth_l1(
            rpn_deltas.reshape(-1, 4),
            atgt.bbox_targets.reshape(-1, 4),
            atgt.bbox_weights.reshape(-1, 4),
            sigma=3.0,
            norm=rpn_norm,
        )
        rcnn_cls_loss = softmax_cross_entropy(cls_logits, labels, -1, rcnn_norm)
        rcnn_bbox_loss = weighted_smooth_l1(
            bbox_pred_out, bbox_targets, bbox_weights, sigma=1.0, norm=rcnn_norm
        )
        total = rpn_cls_loss + rpn_bbox_loss + rcnn_cls_loss + rcnn_bbox_loss

        aux = {
            "RPNAcc": accuracy(rpn_logits.reshape(-1, 2), atgt.labels.reshape(-1)),
            "RPNLogLoss": rpn_cls_loss,
            "RPNL1Loss": rpn_bbox_loss,
            "RCNNAcc": accuracy(cls_logits, labels),
            "RCNNLogLoss": rcnn_cls_loss,
            "RCNNL1Loss": rcnn_bbox_loss,
            "num_fg_rois": (labels > 0).sum(),
            "num_valid_props": prop_valid.sum(),
            "num_fg_anchors": (atgt.labels == 1).sum(),
        }

        if cfg.network.USE_MASK:
            mask_loss, mask_aux = self._mask_loss(
                pyramid, samples, gt_boxes, gt_valid, gt_masks
            )
            total = total + mask_loss
            aux.update(mask_aux)
        return total, aux

    def test_forward(self, images, im_info):
        cfg = self.cfg
        te = cfg.TEST
        b = images.shape[0]
        k = cfg.dataset.NUM_CLASSES
        from mx_rcnn_tpu.models.layers import make_pad_mask

        # serving invariance (see FasterRCNN.test_forward): mask bucket
        # padding through the backbone/neck and on every pyramid level
        # before the shared RPN's 3×3 conv.  Exactness additionally needs
        # bucket dims divisible by the max feature stride (SHAPE_BUCKETS
        # are), else the nearest-upsample index map varies per canvas.
        pad_mask = make_pad_mask(im_info, (images.shape[1], images.shape[2]))
        pyramid = [pad_mask(p) for p in self._pyramid(images, pad_mask)]
        rpn_logits, rpn_deltas, anchors, bounds = self._rpn_over_levels(pyramid)
        fg_scores = jax.nn.softmax(rpn_logits, axis=-1)[..., 1]
        # padding-invariance (see FasterRCNN.test_forward): drop anchors
        # whose grid cell lies in the bucket padding, per level
        shapes = tuple((p.shape[1], p.shape[2]) for p in pyramid)
        a_per_cell = len(cfg.network.ANCHOR_RATIOS) * len(
            cfg.network.FPN_ANCHOR_SCALES
        )
        grid_ok = jax.vmap(
            lambda info: anchor_grid_mask(
                shapes, cfg.network.FPN_FEAT_STRIDES, a_per_cell, info
            )
        )(im_info)
        fg_scores = jnp.where(grid_ok, fg_scores, _NEG_INF)
        n_levels = len(bounds) - 1
        pre_per_level = max(te.RPN_PRE_NMS_TOP_N // n_levels, 256)
        rois, roi_scores, roi_valid = jax.vmap(
            lambda s, d, info: self._propose_multilevel(
                s, d, anchors, bounds, info, pre_per_level,
                te.RPN_POST_NMS_TOP_N, te.RPN_NMS_THRESH, te.RPN_MIN_SIZE,
            )
        )(fg_scores, rpn_deltas, im_info)

        # one ladder-wide shape per level into roi_align so the second
        # stage is the SAME program for every bucket (see
        # layers.pad_feat_to_ladder); P6 is RPN-only and stays unpadded
        from mx_rcnn_tpu.models.layers import pad_feat_to_ladder

        pyramid = [
            pad_feat_to_ladder(p, s, cfg.SHAPE_BUCKETS)
            for p, s in zip(pyramid[:4], cfg.network.FPN_FEAT_STRIDES[:4])
        ] + pyramid[4:]
        trunk = self._roi_features(
            pyramid, rois, fwd_only=True, valid_hw=im_info[:, :2]
        )
        cls_logits, bbox_deltas = self.rcnn(trunk)
        r = te.RPN_POST_NMS_TOP_N
        means, stds = bbox_denorm_vectors(cfg, k)
        bbox_deltas = bbox_deltas * stds[None, :] + means[None, :]
        out = {
            "rois": rois,
            "roi_scores": roi_scores,
            "roi_valid": roi_valid,
            "cls_prob": jax.nn.softmax(cls_logits).reshape(b, r, k),
            "bbox_deltas": bbox_deltas.reshape(b, r, 4 * k),
        }
        if cfg.network.USE_MASK:
            out["mask_logits"] = self._mask_forward(
                pyramid, rois, valid_hw=im_info[:, :2]
            )
        return out

    # ------------------------------------------------------------- mask head
    def _mask_pooled(self, pyramid, rois, fwd_only: bool = False,
                     valid_hw=None):
        """(B, R, 4) → (B*R, 14, 14, C) mask-branch roi features."""
        net = self.cfg.network
        levels = roi_levels(rois)
        pooled = None
        for li, stride in enumerate(net.FPN_FEAT_STRIDES[:4]):
            feats = extract_roi_features_batched(
                pyramid[li], rois, "roi_align", (14, 14),
                1.0 / stride, net.ROI_SAMPLE_RATIO, fwd_only=fwd_only,
                valid_hw=valid_hw,
            )
            mask = (levels == li + 2)[..., None, None, None]
            contrib = jnp.where(mask, feats, 0.0)
            pooled = contrib if pooled is None else pooled + contrib
        b, r = pooled.shape[0], pooled.shape[1]
        return pooled.reshape((b * r,) + pooled.shape[2:])

    def _mask_forward(self, pyramid, rois, valid_hw=None):
        """→ (B, R, 28, 28, K) per-class mask logits (test path)."""
        b, r = rois.shape[0], rois.shape[1]
        logits = self.mask_head(
            self._mask_pooled(pyramid, rois, fwd_only=True, valid_hw=valid_hw)
        )
        return logits.reshape((b, r) + logits.shape[1:])

    def _mask_loss(self, pyramid, samples, gt_boxes, gt_valid, gt_masks=None):
        """Per-fg-roi BCE against gt masks cropped to the roi (28×28).

        The matched gt is ``samples.gt_index`` — the SAME assignment
        ``sample_rois`` derived the roi's label and bbox target from.
        Re-deriving a fresh best-IoU argmax here could pair a roi
        labeled class A with a mask cropped from a different
        (higher-IoU) gt.

        Targets: with ``gt_masks`` (B, G, M, M) box-frame bitmaps (real
        polygon/RLE gts via ``data/masks.py``), each fg roi's target is
        its matched bitmap bilinearly resampled under the roi grid and
        binarized at 0.5.  Without (box-only datasets), the gt "mask"
        is its full rectangle — ``rasterize_box_masks``.
        """
        from mx_rcnn_tpu.ops.mask_targets import (
            crop_resize_masks,
            rasterize_box_masks,
        )

        cfg = self.cfg
        size = cfg.TRAIN.MASK_SIZE
        # The mask branch only ever contributes loss on FG rois, and
        # sample_rois packs fg first (ops/targets.py: fg priority wins
        # the top_k, quota FG_FRACTION·BATCH_ROIS) — so the branch runs
        # on just the first nfg roi slots.  EXACT: every fg roi lives in
        # that prefix; bg rows that pad it get zero loss weight either
        # way.  At the bench config this is 4× less mask-branch work
        # (second ROIAlign, 4conv+deconv head, target resampling: 128 →
        # 32 rois).
        nfg = min(
            int(round(cfg.TRAIN.FG_FRACTION * cfg.TRAIN.BATCH_ROIS)),
            samples.rois.shape[1],
        )
        m_rois = samples.rois[:, :nfg]
        m_labels = samples.labels[:, :nfg]
        m_gt_index = samples.gt_index[:, :nfg]
        b, r = m_rois.shape[0], m_rois.shape[1]
        logits = self.mask_head(self._mask_pooled(pyramid, m_rois))
        logits = logits.reshape(b, r, size, size, -1)

        fg = m_labels > 0                                         # (B, R)
        if gt_masks is None:
            targets = jax.vmap(
                lambda rois_i, gi, gtb: rasterize_box_masks(
                    rois_i, gtb[gi, :4], size
                )
            )(m_rois, m_gt_index, gt_boxes)                       # (B, R, S, S)
        else:
            soft = jax.vmap(
                lambda rois_i, gi, gtb, gtm: crop_resize_masks(
                    rois_i, gtb[gi, :4], gtm[gi], size
                )
            )(m_rois, m_gt_index, gt_boxes, gt_masks)
            targets = (soft >= 0.5).astype(jnp.float32)

        cls = jnp.clip(m_labels, 0)                               # (B, R)
        sel = one_hot_select(
            logits, cls[..., None, None]
        )                                                         # (B, R, S, S)
        bce = optax_sigmoid_bce(sel, targets)
        per_roi = bce.mean(axis=(-1, -2))                         # (B, R)
        loss = (per_roi * fg).sum() / jnp.maximum(fg.sum(), 1.0)
        return loss, {"MaskBCELoss": loss}

    def mask_iou_probe(self, images, im_info, gt_boxes, gt_valid, gt_masks):
        """Decoupled mask-quality metric (VERDICT r4 #2): predict masks
        AT the gt boxes with the gt classes — no RPN, no detection
        scoring, no NMS confound — and return per-instance IoU of the
        thresholded 28×28 prediction against the gt polygon bitmap
        resampled onto the same grid.

        → (iou (B, G) f32, gt_valid (B, G) bool).  A rectangle-biased
        head scores ≈ box-occupancy here (ellipse ≈ 0.785, triangle
        ≈ 0.5), so mean IoU ≥ 0.8 on the synthetic ellipse/triangle set
        is evidence of actual shape learning.
        """
        from mx_rcnn_tpu.models.layers import normalize_images
        from mx_rcnn_tpu.ops.mask_targets import crop_resize_masks

        cfg = self.cfg
        size = cfg.TRAIN.MASK_SIZE
        images = normalize_images(images, im_info, cfg)
        pyramid = self._pyramid(images)
        boxes = gt_boxes[..., :4]                                 # (B, G, 4)
        logits = self._mask_forward(pyramid, boxes)               # (B, G, S, S, K)
        cls = jnp.clip(gt_boxes[..., 4].astype(jnp.int32), 0)
        pred = one_hot_select(logits, cls[..., None, None]) > 0.0  # (B, G, S, S)

        # gt bitmap in the same box frame: roi == gt box, so this is a
        # pure M→S bilinear resize of the box-frame bitmap
        target = jax.vmap(
            lambda rois_i, gtb, gtm: crop_resize_masks(
                rois_i, gtb, gtm, size
            )
        )(boxes, boxes, gt_masks) >= 0.5                          # (B, G, S, S)

        inter = (pred & target).sum(axis=(-1, -2)).astype(jnp.float32)
        union = (pred | target).sum(axis=(-1, -2)).astype(jnp.float32)
        iou = inter / jnp.maximum(union, 1.0)
        return iou, gt_valid


def optax_sigmoid_bce(logits, labels):
    return jnp.maximum(logits, 0) - logits * labels + jnp.log1p(
        jnp.exp(-jnp.abs(logits))
    )
