"""ResNet-50/101 backbone + conv5 top head, detection-style.

Reference: ``rcnn/symbol/symbol_resnet.py`` — conv1..conv4 (stride 16) as
the shared feature extractor, conv5 applied *after* ROI pooling as the RCNN
head, every BN frozen (``use_global_stats=True``, eps 2e-5), conv1+stage1
parameters frozen during training (``FIXED_PARAMS``).

Architectural stance: post-activation bottleneck (conv-BN-relu) in NHWC.
The reference uses MXNet's pre-activation variant; we keep the classic
post-act form because it is the layout every public ImageNet ResNet
checkpoint family uses, which keeps a future weight importer trivial, and
is numerically equivalent in capacity.  Stage/unit naming (``stage1`` ..
``stage4``) mirrors the reference so FIXED_PARAMS path-prefix freezing
matches both codebases.
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from mx_rcnn_tpu.models.layers import conv, make_conv_bn

_BLOCKS = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3), 152: (3, 8, 36, 3)}

# leading-block order used for the frozen-prefix stop_gradient boundary;
# must match the module names in ResNetBackbone.__call__
RESNET_BLOCK_ORDER = ("conv0", "stage1", "stage2", "stage3")


def frozen_prefix_len(
    fixed_params: Sequence[str],
    order: Sequence[str],
    requires: Sequence[str] = (),
) -> int:
    """Length of the contiguous leading run of ``order`` whose names are
    frozen under FIXED_PARAMS prefix semantics (core.train.is_frozen_path).
    The backbone stops gradients at that boundary: parameters below it
    get zero updates from the optimizer mask anyway, so skipping their
    backward pass is an exact-semantics compute saving (~25% of the
    ResNet-101 backbone step at the default conv0+stage1 freeze).

    ``requires``: patterns that must also be present in ``fixed_params``
    for any stop to engage.  ResNet callers pass ("bn",): the stop lands
    after each block's FrozenBatchNorm, so the BN affines must be frozen
    too or the stop would silently zero their (trainable) grads.

    Matching delegates to ``core.train.is_frozen_path`` — the optimizer
    mask's own rule — so the stop boundary can never drift from what the
    optimizer actually freezes."""
    from mx_rcnn_tpu.core.train import is_frozen_path

    if any(req not in fixed_params for req in requires):
        return 0
    n = 0
    for name in order:
        if is_frozen_path((name,), fixed_params):
            n += 1
        else:
            break
    return n


class Bottleneck(nn.Module):
    """1x1 → 3x3 → 1x1(×4) bottleneck with projection shortcut."""

    filters: int
    stride: int = 1
    dtype: Any = jnp.float32
    fold_bn: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, pad_mask=None) -> jnp.ndarray:
        pm = pad_mask if pad_mask is not None else (lambda v: v)
        cbn = make_conv_bn(self.fold_bn, self.dtype)
        y = cbn(x, self.filters, 1, self.stride, "conv1", "bn1")
        y = nn.relu(y)
        # the only spatial (3×3) op in the unit: re-zero bucket padding
        # first so edge cells read zeros on every canvas (layers.make_pad_mask)
        y = cbn(pm(y), self.filters, 3, 1, "conv2", "bn2")
        y = nn.relu(y)
        y = cbn(y, self.filters * 4, 1, 1, "conv3", "bn3")
        residual = x
        if residual.shape != y.shape:
            residual = cbn(x, self.filters * 4, 1, self.stride, "sc", "sc_bn")
        return nn.relu(y + residual)


class ResNetStage(nn.Module):
    filters: int
    num_units: int
    stride: int
    dtype: Any = jnp.float32
    fold_bn: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, pad_mask=None) -> jnp.ndarray:
        for i in range(self.num_units):
            x = Bottleneck(
                self.filters,
                stride=self.stride if i == 0 else 1,
                dtype=self.dtype,
                fold_bn=self.fold_bn,
                name=f"unit{i + 1}",
            )(x, pad_mask=pad_mask)
        return x


class ResNetBackbone(nn.Module):
    """conv1..conv4: (B, H, W, 3) → C4 feature (B, H/16, W/16, 1024).

    When ``return_pyramid`` is set, also returns (C2, C3, C4, C5) for FPN —
    C5 computed convolutionally (the FPN layout; the plain Faster R-CNN
    path instead applies stage4 per-roi via :class:`ResNetTopHead`).
    """

    depth: int = 101
    dtype: Any = jnp.float32
    return_pyramid: bool = False
    # number of leading blocks [conv0, stage1, stage2, stage3] whose output
    # gradient is stopped (their params are frozen via the FIXED_PARAMS
    # optimizer mask; the stop makes XLA skip their backward entirely)
    frozen_prefix: int = 0
    # fold the frozen-BN affines into the conv kernels (exact rewrite;
    # same param tree — see layers.fused_conv_bn)
    fold_bn: bool = False

    @nn.compact
    def __call__(self, x: jnp.ndarray, pad_mask=None):
        blocks = _BLOCKS[self.depth]
        pm = pad_mask if pad_mask is not None else (lambda v: v)

        def boundary(x, idx):
            return jax.lax.stop_gradient(x) if self.frozen_prefix == idx else x

        x = x.astype(self.dtype)
        x = make_conv_bn(self.fold_bn, self.dtype)(x, 64, 7, 2, "conv0", "bn0")
        x = nn.relu(x)
        # re-zero bucket padding before the 3×3 pool: relu output is ≥ 0,
        # and every valid pool window holds ≥ 1 valid cell, so masked
        # zeros can never win a max that real values would have won
        x = nn.max_pool(pm(x), (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        x = boundary(x, 1)

        def stage(filters, n_units, stride, name):
            return ResNetStage(
                filters, n_units, stride, self.dtype,
                fold_bn=self.fold_bn, name=name,
            )

        c2 = boundary(stage(64, blocks[0], 1, "stage1")(x, pad_mask), 2)
        c3 = boundary(stage(128, blocks[1], 2, "stage2")(c2, pad_mask), 3)
        c4 = boundary(stage(256, blocks[2], 2, "stage3")(c3, pad_mask), 4)
        if not self.return_pyramid:
            return c4
        c5 = stage(512, blocks[3], 2, "stage4")(c4, pad_mask)
        return c2, c3, c4, c5


class ResNetTopHead(nn.Module):
    """conv5 stage on pooled rois: (R, 14, 14, 1024) → (R, 2048) vector.

    Reference: the post-ROIPooling conv5 + global-average-pool tail of
    ``rcnn/symbol/symbol_resnet.py :: get_resnet_train``.
    """

    depth: int = 101
    dtype: Any = jnp.float32
    fold_bn: bool = False

    @nn.compact
    def __call__(self, rois_feat: jnp.ndarray) -> jnp.ndarray:
        blocks = _BLOCKS[self.depth]
        x = ResNetStage(512, blocks[3], 2, self.dtype,
                        fold_bn=self.fold_bn, name="stage4")(rois_feat)
        return jnp.mean(x, axis=(1, 2))
