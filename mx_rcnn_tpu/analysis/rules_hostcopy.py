"""R1 host-copy escape and R2 use-after-donate.

Both rules encode the PR 4 donation incident: on CPU,
``jax.device_get`` returns ZERO-COPY numpy views of device buffers, so
any view that outlives the device value it aliases (returned, yielded,
stored on an object, or captured by a closure) reads garbage the moment
a donating step reuses that buffer.  ``resilience.host_copy`` (=
``tree_map(np.array, device_get(tree))``) is the owning-copy idiom.

R1 flags device_get results that ESCAPE the expression that produced
them.  Immediate consumption (passed straight into another call,
reduced to a python scalar, ``.tobytes()``-style copying methods) is
not an escape.  The walk deliberately errs silent on constructs it
cannot classify — the analyzer must be zero-noise on a clean tree.

R2 tracks callables built with live donation in the SAME scope —
``jax.jit(f, donate_argnums=...)``, ``jax.pmap(...)``, and the project
factories ``make_train_step(..., donate=True)`` /
``make_parallel_train_step(..., donate=True)`` (donated position 0) —
and flags any read of a bare-name argument passed in a donated position
after the donating call, unless the name was rebound first
(``state = step(state, ...)`` is the safe pattern).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from mx_rcnn_tpu.analysis.engine import Finding, Module, Rule, dotted

DEVICE_GET = {"jax.device_get", "device_get"}
# calls that take ownership / produce a fresh host object
SAFE_CALLS = {
    "host_copy",
    "resilience.host_copy",
    "np.array",
    "numpy.array",
    "onp.array",
    "float",
    "int",
    "bool",
    "str",
    "len",
}
# view-preserving wrappers the walk sees through
PASSTHROUGH_CALLS = {
    "dict",
    "list",
    "tuple",
    "sorted",
    "np.asarray",
    "numpy.asarray",
    "jax.tree_util.tree_leaves",
    "tree_leaves",
}
TREE_MAP = {"jax.tree_util.tree_map", "tree_map", "jax.tree.map"}
COPYING_FNS = {"np.array", "numpy.array", "onp.array"}
# methods on an array that return a fresh host object
COPY_METHODS = {"tobytes", "copy", "astype", "item", "tolist", "sum", "mean"}


def _is_device_get(node: ast.AST) -> bool:
    return (
        isinstance(node, ast.Call) and (dotted(node.func) or "") in DEVICE_GET
    )


class HostCopyEscape(Rule):
    id = "R1"
    name = "host-copy escape"

    def check_module(self, module: Module) -> List[Finding]:
        out: List[Finding] = []
        for node in ast.walk(module.tree):
            if _is_device_get(node):
                f = self._classify(module, node)
                if f is not None:
                    out.append(f)
        return out

    # ---- escape classification -------------------------------------

    def _walk_up(
        self, module: Module, node: ast.AST
    ) -> Optional[Tuple[str, ast.AST]]:
        """Follow the value of ``node`` upward through view-preserving
        constructs.  Returns (escape-kind, carrier-node) or None when the
        value is consumed/copied before it can escape."""
        while True:
            par = module.parent(node)
            if par is None:
                return None
            if isinstance(par, ast.Call):
                d = dotted(par.func) or ""
                if node is par.func:
                    return None
                if d in SAFE_CALLS:
                    return None
                if d in TREE_MAP and par.args and (
                    dotted(par.args[0]) in COPYING_FNS
                ):
                    return None  # the host_copy idiom itself
                if d in PASSTHROUGH_CALLS:
                    node = par
                    continue
                return None  # consumed by a call we can't see through
            if isinstance(par, ast.Attribute) and par.value is node:
                gp = module.parent(par)
                if isinstance(gp, ast.Call) and gp.func is par:
                    if par.attr in COPY_METHODS:
                        return None
                    node = gp  # assume view-preserving method (.reshape)
                    continue
                return None
            if isinstance(par, ast.Subscript) and par.value is node:
                gp = module.parent(par)
                if isinstance(gp, ast.Assign) and par in gp.targets:
                    return None  # store INTO the container, not an escape
                node = par  # indexing a view yields a view
                continue
            if isinstance(par, ast.Starred):
                node = par
                continue
            if isinstance(par, (ast.Tuple, ast.List, ast.Dict, ast.Set)):
                gp = module.parent(par)
                if isinstance(gp, ast.Assign) and par in gp.targets:
                    return None  # unpacking target, handled by caller
                node = par
                continue
            if isinstance(par, ast.Return):
                return ("returned", par)
            if isinstance(par, (ast.Yield, ast.YieldFrom)):
                return ("yielded", par)
            if isinstance(par, ast.Assign):
                return ("assigned", par)
            if isinstance(par, ast.AnnAssign) and par.value is node:
                return ("assigned", par)
            return None  # comprehension / boolop / anything else: silent

    def _classify(self, module: Module, call: ast.Call) -> Optional[Finding]:
        esc = self._walk_up(module, call)
        if esc is None:
            return None
        kind, carrier = esc
        scope = module.scope_of(call)
        if kind in ("returned", "yielded"):
            return Finding(
                self.id,
                module.path,
                call.lineno,
                scope,
                f"device_get result {kind} without host_copy — on CPU this "
                f"is a zero-copy view that donation can corrupt",
            )
        # assigned: attribute target escapes immediately; name targets
        # escape if the name is later returned/yielded/stored/closed over
        assert isinstance(carrier, (ast.Assign, ast.AnnAssign))
        targets = (
            carrier.targets
            if isinstance(carrier, ast.Assign)
            else [carrier.target]
        )
        names: List[str] = []
        for t in targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)):
                return Finding(
                    self.id,
                    module.path,
                    call.lineno,
                    scope,
                    "device_get view stored on an object/container "
                    "without host_copy",
                )
            for leaf in ast.walk(t):
                if isinstance(leaf, ast.Name) and isinstance(
                    leaf.ctx, ast.Store
                ):
                    names.append(leaf.id)
        owner = module.enclosing_def(call)
        if owner is None:
            return None  # module-level assignment: import-time, no steps yet
        for name in names:
            hit = self._name_escapes(module, owner, name, carrier.lineno)
            if hit is not None:
                how, line = hit
                return Finding(
                    self.id,
                    module.path,
                    call.lineno,
                    scope,
                    f"device_get view bound to `{name}` is {how} "
                    f"(line {line}) without host_copy",
                )
        return None

    def _name_escapes(
        self, module: Module, owner: ast.AST, name: str, after: int
    ) -> Optional[Tuple[str, int]]:
        for n in ast.walk(owner):
            if not (
                isinstance(n, ast.Name)
                and n.id == name
                and isinstance(n.ctx, ast.Load)
                and n.lineno >= after
            ):
                continue
            if module.enclosing_def(n) is not owner:
                return ("captured by a nested function", n.lineno)
            esc = self._walk_up(module, n)
            if esc is None:
                continue
            kind, carrier = esc
            if kind in ("returned", "yielded"):
                return (kind, n.lineno)
            if kind == "assigned":
                targets = (
                    carrier.targets
                    if isinstance(carrier, ast.Assign)
                    else [carrier.target]
                )
                if any(
                    isinstance(t, (ast.Attribute, ast.Subscript))
                    for t in targets
                ):
                    return ("stored on an object", n.lineno)
        return None


class UseAfterDonate(Rule):
    id = "R2"
    name = "use-after-donate"

    JIT_WRAPPERS = {"jax.jit", "jax.pmap", "jit", "pmap"}
    DONATING_FACTORIES = {"make_train_step", "make_parallel_train_step"}

    def check_module(self, module: Module) -> List[Finding]:
        out: List[Finding] = []
        scopes = [module.tree] + [
            n
            for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            out.extend(self._check_scope(module, scope))
        return out

    def _donated_positions(self, call: ast.Call) -> Optional[Set[int]]:
        d = dotted(call.func) or ""
        if d in self.JIT_WRAPPERS:
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    val = kw.value
                    if isinstance(val, ast.IfExp):
                        val = val.body  # model the donating branch
                    if isinstance(val, ast.Constant) and isinstance(
                        val.value, int
                    ):
                        return {val.value}
                    if isinstance(val, ast.Tuple) and all(
                        isinstance(e, ast.Constant) for e in val.elts
                    ):
                        return {e.value for e in val.elts}
                    return None
            return None
        if d.split(".")[-1] in self.DONATING_FACTORIES:
            for kw in call.keywords:
                if kw.arg == "donate" and isinstance(kw.value, ast.Constant):
                    return {0} if kw.value.value is True else None
            # make_train_step donates by default
            return {0}
        return None

    def _check_scope(self, module: Module, scope: ast.AST) -> List[Finding]:
        body_nodes = [
            n
            for n in ast.walk(scope)
            if module.enclosing_def(n)
            is (scope if isinstance(scope, (ast.FunctionDef, ast.AsyncFunctionDef)) else None)
        ]
        donating: Dict[str, str] = {}  # callable name -> positions repr
        positions: Dict[str, Set[int]] = {}
        for n in body_nodes:
            if isinstance(n, ast.Assign) and isinstance(n.value, ast.Call):
                pos = self._donated_positions(n.value)
                if pos:
                    for t in n.targets:
                        name = dotted(t)
                        if name:
                            donating[name] = dotted(n.value.func) or "?"
                            positions[name] = pos

        if not donating:
            return []

        # events: (line, priority, kind, payload)
        events: List[Tuple[int, int, str, Tuple]] = []
        for n in body_nodes:
            if isinstance(n, ast.Call):
                callee = dotted(n.func)
                if callee in donating:
                    for i in sorted(positions[callee]):
                        if i < len(n.args) and isinstance(
                            n.args[i], ast.Name
                        ):
                            events.append(
                                (
                                    n.end_lineno or n.lineno,
                                    1,
                                    "donate",
                                    (n.args[i].id, callee, n.lineno),
                                )
                            )
            if isinstance(n, ast.Name):
                if isinstance(n.ctx, ast.Load):
                    events.append((n.lineno, 0, "load", (n.id, n.lineno)))
                elif isinstance(n.ctx, ast.Store):
                    stmt = n
                    while module.parent(stmt) is not None and not isinstance(
                        stmt, ast.stmt
                    ):
                        stmt = module.parent(stmt)
                    events.append(
                        (stmt.end_lineno or n.lineno, 2, "store", (n.id,))
                    )

        events.sort(key=lambda e: (e[0], e[1]))
        out: List[Finding] = []
        live: Dict[str, Tuple[str, int]] = {}
        flagged: Set[str] = set()
        for _, _, kind, payload in events:
            if kind == "donate":
                name, callee, line = payload
                live[name] = (callee, line)
            elif kind == "store":
                live.pop(payload[0], None)
            elif kind == "load":
                name, line = payload
                if name in live and name not in flagged:
                    callee, dline = live[name]
                    flagged.add(name)
                    out.append(
                        Finding(
                            self.id,
                            module.path,
                            line,
                            module.scope_of(scope)
                            if not isinstance(scope, ast.Module)
                            else "<module>",
                            f"`{name}` read after being donated to "
                            f"`{callee}` (donating call at line {dline}) — "
                            f"its device buffer may already be reused",
                        )
                    )
        return out
