"""graftlint rule engine: module model, suppressions, baseline, report.

Stdlib-``ast`` only — the analyzer must import (and run) without jax so
it can gate CI on boxes where the accelerator stack is absent.

Suppression layers, innermost wins:

1. inline pragma on the finding line (or the line directly above)::

       x = jax.device_get(t)  # graftlint: disable=R1(outputs never donated)

   A reason inside the parentheses is REQUIRED — a bare ``disable=R1``
   is ignored and the finding stands.

2. the checked-in baseline file (``tools/lint_baseline.json``): entries
   match on (rule, path, scope [, contains]).  An entry that matches no
   current finding is STALE and fails the lint run — the baseline can
   only shrink or track real code.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Z][A-Z0-9]*(?:\s*,\s*[A-Z][A-Z0-9]*)*)"
    r"\(([^()]+)\)"
)

#: scan roots, relative to the repo root
DEFAULT_TARGETS: Tuple[str, ...] = ("mx_rcnn_tpu", "bench.py")
EXCLUDE_PARTS = {"__pycache__"}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # repo-relative posix path
    line: int
    scope: str  # dotted qualname of the enclosing def/class, or <module>
    message: str

    def format(self) -> str:
        return (
            f"{self.rule} {self.path}:{self.line} [{self.scope}] "
            f"{self.message}"
        )


class Module:
    """Parsed source file plus the lookup tables every rule needs:
    parent links and def/class qualnames."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self.qualnames: Dict[ast.AST, str] = {}
        self._index(self.tree, [])

    def _index(self, node: ast.AST, stack: List[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                qual = stack + [child.name]
                self.qualnames[child] = ".".join(qual)
                self._index(child, qual)
            else:
                self._index(child, stack)

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def scope_of(self, node: ast.AST) -> str:
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in self.qualnames:
                return self.qualnames[cur]
            cur = self.parents.get(cur)
        return "<module>"

    def enclosing_def(self, node: ast.AST):
        """Nearest enclosing FunctionDef/AsyncFunctionDef (not Lambda)."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return cur
            cur = self.parents.get(cur)
        return None

    def pragma_rules(self, line: int) -> Dict[str, str]:
        """rule -> reason for valid pragmas on ``line`` or the line above."""
        out: Dict[str, str] = {}
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = PRAGMA_RE.search(self.lines[ln - 1])
                if m:
                    reason = m.group(2).strip()
                    for rule in re.split(r"\s*,\s*", m.group(1)):
                        out.setdefault(rule, reason)
        return out


def dotted(node: Optional[ast.AST]) -> Optional[str]:
    """'jax.device_get' for a Name/Attribute chain, else None."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else None
    return None


class Rule:
    id = "R0"
    name = "base"

    def check_module(self, module: Module) -> List[Finding]:
        return []

    def finalize(self, modules: Sequence[Module]) -> List[Finding]:
        """Cross-module pass, runs once after every check_module."""
        return []


@dataclass
class BaselineEntry:
    rule: str
    path: str
    scope: str
    reason: str
    contains: Optional[str] = None
    hits: int = 0

    def matches(self, f: Finding) -> bool:
        return (
            f.rule == self.rule
            and f.path == self.path
            and fnmatch.fnmatchcase(f.scope, self.scope)
            and (self.contains is None or self.contains in f.message)
        )


def load_baseline(path: Path) -> List[BaselineEntry]:
    raw = json.loads(path.read_text())
    out = []
    for e in raw.get("suppressions", []):
        out.append(
            BaselineEntry(
                rule=e["rule"],
                path=e["path"],
                scope=e["scope"],
                reason=e["reason"],
                contains=e.get("contains"),
            )
        )
    return out


@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    inline_suppressed: List[Tuple[Finding, str]] = field(default_factory=list)
    baseline_suppressed: List[Tuple[Finding, BaselineEntry]] = field(
        default_factory=list
    )
    stale_baseline: List[BaselineEntry] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.findings and not self.stale_baseline and not self.errors

    def summary(self) -> str:
        return (
            f"{len(self.findings)} finding(s), "
            f"{len(self.inline_suppressed)} inline-suppressed, "
            f"{len(self.baseline_suppressed)} baseline-suppressed, "
            f"{len(self.stale_baseline)} stale baseline entr(y/ies), "
            f"{len(self.errors)} error(s)"
        )


def discover(root: Path, targets: Sequence[str] = DEFAULT_TARGETS) -> List[Path]:
    files: List[Path] = []
    for t in targets:
        p = root / t
        if p.is_file():
            files.append(p)
        elif p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if not EXCLUDE_PARTS.intersection(f.parts):
                    files.append(f)
    return files


def load_modules(
    root: Path, targets: Sequence[str] = DEFAULT_TARGETS
) -> Tuple[List[Module], List[str]]:
    modules, errors = [], []
    for f in discover(root, targets):
        rel = f.relative_to(root).as_posix()
        try:
            modules.append(Module(rel, f.read_text()))
        except SyntaxError as e:  # unparseable source is itself a failure
            errors.append(f"parse error in {rel}: {e}")
    return modules, errors


def analyze(
    modules: Sequence[Module],
    rules: Sequence[Rule],
    baseline: Sequence[BaselineEntry] = (),
    errors: Sequence[str] = (),
) -> Report:
    by_path = {m.path: m for m in modules}
    raw: List[Finding] = []
    for rule in rules:
        for m in modules:
            raw.extend(rule.check_module(m))
    for rule in rules:
        raw.extend(rule.finalize(modules))
    raw = sorted(set(raw), key=lambda f: (f.path, f.line, f.rule, f.message))

    report = Report(errors=list(errors))
    entries = list(baseline)
    for f in raw:
        mod = by_path.get(f.path)
        pragmas = mod.pragma_rules(f.line) if mod else {}
        if f.rule in pragmas:
            report.inline_suppressed.append((f, pragmas[f.rule]))
            continue
        hit = next((e for e in entries if e.matches(f)), None)
        if hit is not None:
            hit.hits += 1
            report.baseline_suppressed.append((f, hit))
            continue
        report.findings.append(f)
    report.stale_baseline = [e for e in entries if e.hits == 0]
    return report


def default_rules() -> List[Rule]:
    # imported lazily so engine.py stays importable standalone in tests
    from mx_rcnn_tpu.analysis.rules_hostcopy import HostCopyEscape, UseAfterDonate
    from mx_rcnn_tpu.analysis.rules_jit import JitPurity
    from mx_rcnn_tpu.analysis.rules_locks import LockOrder
    from mx_rcnn_tpu.analysis.rules_futures import ExactlyOnce
    from mx_rcnn_tpu.analysis.rules_faults import FaultCoverage
    from mx_rcnn_tpu.analysis.rules_signals import SignalSafety
    from mx_rcnn_tpu.analysis.rules_requeue import BoundedRequeue

    return [
        HostCopyEscape(),
        UseAfterDonate(),
        JitPurity(),
        LockOrder(),
        ExactlyOnce(),
        FaultCoverage(),
        SignalSafety(),
        BoundedRequeue(),
    ]


def analyze_snippets(
    sources: Dict[str, str],
    rules: Optional[Sequence[Rule]] = None,
    baseline: Sequence[BaselineEntry] = (),
) -> Report:
    """Analyze in-memory {relpath: source} modules — the fixture-matrix
    entry point used by tests/test_analysis.py."""
    modules = [Module(p, s) for p, s in sources.items()]
    return analyze(modules, rules or default_rules(), baseline)
