"""R8 bounded requeue — the ISSUE 12 containment-loop rule.

The replica pool's failure handling re-dispatches work: a drain
requeues, a slow primary hedges, the engine splits an implicated batch
and resubmits members solo.  Every one of those re-dispatch edges must
be charged against a per-request :class:`RetryBudget` — an uncharged
requeue loop is exactly how a query of death circulates forever,
serially tripping replicas (the incident class PR 12 contains).

Detection: a *requeue site* is a ``<recv>.submit(...)`` call whose
receiver's last segment names a dispatch target
(``replica``/``primary``/``backup``/``sibling``/``batcher`` — NOT
``engine`` or the completion ``pool``, whose submits are intake, not
re-dispatch).  The site is *triggered* when it can run more than once
for the same work item:

* lexically inside a ``for``/``while`` loop, or
* inside an ``except`` handler (failure-path re-dispatch), or
* in a function whose name says retry
  (``hedge``/``requeue``/``resubmit``/``failover``/``retry``).

A triggered site is clean only if its enclosing function reaches a
``<...budget>.spend(...)`` call — directly, or through calls resolved
to a fixed point across the serve modules (the R4 idiom: receivers by
unique method name).  Anything else is an unbounded requeue.

Like R5, this is an under-approximation by design: spending under a
condition still counts (the runtime raises ``RetriesExhausted`` at
zero), and the fault matrix owns the stronger guarantee.  It is
zero-noise on code that charges its re-dispatches.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.analysis.engine import Finding, Module, Rule, dotted

# receivers whose .submit() is a re-dispatch of existing work
REDISPATCH_RECV = re.compile(
    r"(replica|primary|backup|sibling|batcher)$", re.IGNORECASE
)
# function names that declare a retry path
RETRYISH_NAME = re.compile(
    r"(hedge|requeue|resubmit|failover|retry)", re.IGNORECASE
)
BUDGETISH = re.compile(r"budget", re.IGNORECASE)

_FuncKey = Tuple[str, str]  # (module path, qualname)


def _last_segment(recv: Optional[str]) -> str:
    return (recv or "").rsplit(".", 1)[-1]


def _spends_budget(call: ast.Call) -> bool:
    return (
        isinstance(call.func, ast.Attribute)
        and call.func.attr == "spend"
        and bool(BUDGETISH.search(_last_segment(dotted(call.func.value))))
    )


class BoundedRequeue(Rule):
    id = "R8"
    name = "bounded requeue"

    def _in_scope(self, module: Module) -> bool:
        return "/serve/" in f"/{module.path}"

    def finalize(self, modules: Sequence[Module]) -> List[Finding]:
        scoped = [m for m in modules if self._in_scope(m)]
        if not scoped:
            return []

        # ---- pass 1: per-function spend seeds and call edges ---------
        funcs: Dict[_FuncKey, ast.FunctionDef] = {}
        by_name: Dict[str, List[_FuncKey]] = {}
        spends: Set[_FuncKey] = set()
        calls: Dict[_FuncKey, Set[str]] = {}
        for m in scoped:
            for node in ast.walk(m.tree):
                if not isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    continue
                key = (m.path, m.qualnames.get(node, node.name))
                funcs[key] = node
                by_name.setdefault(node.name, []).append(key)
                callees: Set[str] = set()
                for n in self._own_nodes(m, node):
                    if not isinstance(n, ast.Call):
                        continue
                    if _spends_budget(n):
                        spends.add(key)
                    elif isinstance(n.func, ast.Attribute):
                        callees.add(n.func.attr)
                    elif isinstance(n.func, ast.Name):
                        callees.add(n.func.id)
                calls[key] = callees

        # ---- pass 2: propagate spend-reachability to a fixed point ---
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for key, callees in calls.items():
                if key in spends:
                    continue
                for name in callees:
                    owners = by_name.get(name, ())
                    # unique-name resolution, the R4 fallback: an
                    # ambiguous callee never transfers coverage
                    if len(owners) == 1 and owners[0] in spends:
                        spends.add(key)
                        changed = True
                        break

        # ---- pass 3: triggered requeue sites must reach a spend ------
        out: List[Finding] = []
        for m in scoped:
            for n in ast.walk(m.tree):
                if not (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "submit"
                ):
                    continue
                recv = dotted(n.func.value)
                if not REDISPATCH_RECV.search(_last_segment(recv)):
                    continue
                fn = m.enclosing_def(n)
                if fn is None:
                    continue
                trigger = self._trigger(m, n, fn)
                if trigger is None:
                    continue
                key = (m.path, m.qualnames.get(fn, fn.name))
                if key in spends:
                    continue
                out.append(
                    Finding(
                        self.id,
                        m.path,
                        n.lineno,
                        m.scope_of(n),
                        f"`{recv}.submit` re-dispatches on a retry path "
                        f"({trigger}) with no reachable "
                        f"`RetryBudget.spend` — an unbounded requeue "
                        f"loops a query of death forever",
                    )
                )
        return out

    # ---- helpers ----------------------------------------------------

    def _own_nodes(self, m: Module, fn: ast.AST):
        """Walk ``fn`` excluding nested def bodies (their spends don't
        execute on this function's path), but including lambdas."""
        stack = list(ast.iter_child_nodes(fn))
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            yield n
            stack.extend(ast.iter_child_nodes(n))

    def _trigger(
        self, m: Module, call: ast.Call, fn: ast.AST
    ) -> Optional[str]:
        cur = m.parent(call)
        while cur is not None and cur is not fn:
            if isinstance(cur, (ast.For, ast.While)):
                return "inside a loop"
            if isinstance(cur, ast.ExceptHandler):
                return "inside an except handler"
            cur = m.parent(cur)
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            RETRYISH_NAME.search(fn.name)
        ):
            return f"function `{fn.name}` is a retry path"
        return None
