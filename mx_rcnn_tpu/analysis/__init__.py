"""graftlint — project-native static analysis for the mx_rcnn_tpu stack.

Rules distilled from real incidents (see ANALYSIS.md):

* R1 host-copy escape      (rules_hostcopy)  — PR 4 zero-copy device_get views
* R2 use-after-donate      (rules_hostcopy)  — PR 4 donation discipline
* R3 jit purity            (rules_jit)       — recompile / trace hazards
* R4 lock order + device-under-lock (rules_locks) — serve-stack deadlocks
* R5 exactly-once resolution (rules_futures) — PR 6 requeue-never-drop
* R6 fault-hook coverage   (rules_faults)    — MX_RCNN_FAULTS drift

``lockcheck`` is the runtime counterpart of R4 (MX_RCNN_LOCK_CHECK=1) and
is imported by the serve stack at construction time, so this package
must stay stdlib-only and cheap to import.
"""
