"""R3 jit purity — traced bodies must not touch host state.

A function that ends up inside ``jax.jit``/``jax.pmap`` runs ONCE per
compile, not once per step.  Three hazard classes this rule catches:

* mutating nonlocal/closure state (``global``/``nonlocal`` declarations,
  attribute stores on closed-over objects) — silently freezes at trace
  time, or worse, fires once per recompile;
* calling the ``utils.faults`` injection hooks — their env-driven
  side effects are host code and would be baked into (or elided from)
  the compiled program depending on compile-time state;
* branching on ``.item()``/``float()``/``int()``/``bool()`` of a traced
  value in an ``if``/``while`` test — either a trace error or a
  data-dependent recompile per distinct value (the ROOFLINE recompile
  hazard).

Jitted bodies are found two ways: decorator form (``@jax.jit``,
``@partial(jax.jit, ...)``) and wrapper form — a ``def f`` whose NAME is
later passed as the first argument to ``jax.jit``/``jax.pmap`` anywhere
in the module (the project's dominant idiom: ``jax.jit(fwd, **kw)``,
``jax.jit(sharded_step, donate_argnums=...)``).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from mx_rcnn_tpu.analysis.engine import Finding, Module, Rule, dotted

JIT_NAMES = {"jax.jit", "jax.pmap", "jit", "pmap"}
PARTIAL_NAMES = {"partial", "functools.partial"}
SCALARIZERS = {"float", "int", "bool"}


def _decorator_is_jit(dec: ast.AST) -> bool:
    d = dotted(dec)
    if d in JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        dc = dotted(dec.func)
        if dc in JIT_NAMES:
            return True
        if dc in PARTIAL_NAMES and dec.args and dotted(dec.args[0]) in JIT_NAMES:
            return True
    return False


class JitPurity(Rule):
    id = "R3"
    name = "jit purity"

    def check_module(self, module: Module) -> List[Finding]:
        jitted_names: Set[str] = set()
        for n in ast.walk(module.tree):
            if (
                isinstance(n, ast.Call)
                and dotted(n.func) in JIT_NAMES
                and n.args
                and isinstance(n.args[0], ast.Name)
            ):
                jitted_names.add(n.args[0].id)

        out: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if any(_decorator_is_jit(d) for d in fn.decorator_list) or (
                fn.name in jitted_names
            ):
                out.extend(self._check_body(module, fn))
        return out

    def _check_body(self, module: Module, fn: ast.AST) -> List[Finding]:
        out: List[Finding] = []
        scope = module.scope_of(fn)
        local: Set[str] = {a.arg for a in fn.args.args}
        local.update(a.arg for a in fn.args.posonlyargs)
        local.update(a.arg for a in fn.args.kwonlyargs)
        if fn.args.vararg:
            local.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local.add(fn.args.kwarg.arg)
        own = [n for n in ast.walk(fn) if module.enclosing_def(n) is fn]
        for n in own:
            if isinstance(n, ast.Name) and isinstance(
                n.ctx, (ast.Store, ast.Del)
            ):
                local.add(n.id)

        for n in own:
            if isinstance(n, (ast.Global, ast.Nonlocal)):
                out.append(
                    Finding(
                        self.id,
                        module.path,
                        n.lineno,
                        scope,
                        f"jitted body declares "
                        f"{'global' if isinstance(n, ast.Global) else 'nonlocal'} "
                        f"{', '.join(n.names)} — traced once, not per step",
                    )
                )
            if isinstance(n, (ast.Assign, ast.AugAssign)):
                targets = (
                    n.targets if isinstance(n, ast.Assign) else [n.target]
                )
                for t in targets:
                    root = t
                    while isinstance(root, (ast.Attribute, ast.Subscript)):
                        root = root.value
                    if (
                        isinstance(t, (ast.Attribute, ast.Subscript))
                        and isinstance(root, ast.Name)
                        and root.id not in local
                    ):
                        out.append(
                            Finding(
                                self.id,
                                module.path,
                                n.lineno,
                                scope,
                                f"jitted body mutates closed-over object "
                                f"`{root.id}` — side effect happens at "
                                f"trace time only",
                            )
                        )
            if isinstance(n, ast.Call):
                d = dotted(n.func) or ""
                if d.startswith("faults."):
                    out.append(
                        Finding(
                            self.id,
                            module.path,
                            n.lineno,
                            scope,
                            f"faults hook `{d}` called inside a jitted body "
                            f"— injection state is compile-time, not "
                            f"per-step",
                        )
                    )
            if isinstance(n, (ast.If, ast.While)):
                hazard = self._host_branch(n.test)
                if hazard:
                    out.append(
                        Finding(
                            self.id,
                            module.path,
                            n.lineno,
                            scope,
                            f"jitted body branches on `{hazard}` of a traced "
                            f"value — trace error or per-value recompile",
                        )
                    )
        return out

    def _host_branch(self, test: ast.AST) -> Optional[str]:
        for n in ast.walk(test):
            if isinstance(n, ast.Call):
                d = dotted(n.func)
                if d in SCALARIZERS:
                    return f"{d}()"
                if (
                    isinstance(n.func, ast.Attribute)
                    and n.func.attr == "item"
                ):
                    return ".item()"
        return None
