"""Runtime lock-order assertion — the dynamic counterpart of rule R4.

The serve stack constructs every lock through :func:`make_lock` /
:func:`make_condition`.  By default these return the plain ``threading``
primitive (zero overhead, zero behavior change).  With
``MX_RCNN_LOCK_CHECK=1`` in the environment they return an
:class:`OrderedLock` proxy that maintains a process-wide
lock-*name* acquisition graph (edge ``A -> B`` = "B acquired while A
held") and raises :class:`LockOrderViolation` the moment any thread
tries to acquire in an order that closes a cycle — i.e. it turns a
maybe-someday deadlock into a deterministic test failure at the exact
acquire site.  The fault-matrix suites (test_replica.py,
test_registry.py) run with the check on.

Semantics:

* edges are keyed by lock NAME ("Replica._lock"), not instance, so an
  inversion between any two Replica objects and a ModelRegistry is
  caught even if the specific instances differ across tests;
* nesting two locks of the SAME name (e.g. merging two
  LatencyHistograms) records no edge — cross-instance order within one
  name class is not tracked;
* re-entering an rlock-mode OrderedLock is allowed and records nothing;
  re-acquiring a non-reentrant one in the same thread raises instead of
  deadlocking.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple


class LockOrderViolation(RuntimeError):
    """A lock acquisition closed a cycle in the observed order graph."""


_graph_mu = threading.Lock()
_edges: Dict[str, Set[str]] = {}
_edge_sites: Dict[Tuple[str, str], str] = {}
_tls = threading.local()


def enabled() -> bool:
    return os.environ.get("MX_RCNN_LOCK_CHECK", "0") == "1"


def reset() -> None:
    """Clear the process-wide order graph (test isolation)."""
    with _graph_mu:
        _edges.clear()
        _edge_sites.clear()


def _held() -> List["OrderedLock"]:
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


def _reaches(src: str, dst: str) -> bool:
    # DFS over the recorded name graph; caller holds _graph_mu
    stack, seen = [src], set()
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(_edges.get(n, ()))
    return False


class OrderedLock:
    def __init__(self, name: str, rlock: bool = False):
        self.name = name
        self.rlock = rlock
        self._lock = threading.RLock() if rlock else threading.Lock()

    def _check_before_acquire(self) -> bool:
        """Returns True when this is an rlock re-entry (no edge)."""
        held = _held()
        if any(h is self for h in held):
            if self.rlock:
                return True
            raise LockOrderViolation(
                f"re-acquisition of non-reentrant lock {self.name} "
                f"in the same thread (guaranteed deadlock)"
            )
        with _graph_mu:
            for h in held:
                if h.name == self.name:
                    continue
                if _reaches(self.name, h.name):
                    first = _edge_sites.get((self.name, h.name), "")
                    raise LockOrderViolation(
                        f"lock order inversion: acquiring {self.name} while "
                        f"holding {h.name}, but order {self.name} -> "
                        f"{h.name} was established earlier"
                        + (f" ({first})" if first else "")
                    )
        return False

    def _record(self) -> None:
        held = _held()
        with _graph_mu:
            for h in held:
                if h.name == self.name:
                    continue
                if self.name not in _edges.setdefault(h.name, set()):
                    _edges[h.name].add(self.name)
                    _edge_sites[(h.name, self.name)] = (
                        f"first observed in thread "
                        f"{threading.current_thread().name}"
                    )

    def acquire(self, blocking: bool = True, timeout: float = -1):
        reentry = self._check_before_acquire()
        if timeout == -1:
            ok = self._lock.acquire(blocking)
        else:
            ok = self._lock.acquire(blocking, timeout)
        if ok and not reentry:
            self._record()
            _held().append(self)
        return ok

    def release(self) -> None:
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                break
        self._lock.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        inner = self._lock
        return inner.locked() if hasattr(inner, "locked") else False

    def _is_owned(self) -> bool:
        # Condition-protocol hook: without it, Condition falls back to a
        # probing acquire(False), which the proxy would report as a
        # same-thread re-acquisition
        return any(h is self for h in _held())


def make_lock(name: str, rlock: bool = False):
    """A threading.Lock/RLock, or an order-asserting proxy under
    MX_RCNN_LOCK_CHECK=1."""
    if enabled():
        return OrderedLock(name, rlock=rlock)
    return threading.RLock() if rlock else threading.Lock()


def make_condition(name: str):
    """A threading.Condition whose underlying lock participates in the
    order graph when the check is on."""
    return threading.Condition(make_lock(name))
