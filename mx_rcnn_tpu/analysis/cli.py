"""graftlint CLI — ``python tools/lint.py`` / ``make lint``.

Exit 0 only when the tree is clean: zero unsuppressed findings, zero
stale baseline entries, and every committed ``BENCH_*.json`` artifact
still parses (the artifact-schema piggyback guard).
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional, Sequence

from mx_rcnn_tpu.analysis import engine as eng


# per-artifact required report shape: {filename: (report_keys, scenario
# names that must each carry the per-scenario keys)}.  Catches a bench
# refactor silently committing an artifact that no longer proves what
# the Makefile target's comment says it proves.
_ELASTIC_SCENARIOS = (
    "lose_1_of_8", "wedge", "lose_then_regrow", "preempt_during_shrink",
)
_ELASTIC_SCENARIO_KEYS = ("recovery_s", "zero_lost_steps", "bit_identical")


def _check_elastic_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    scenarios = report.get("scenarios")
    if not isinstance(scenarios, dict):
        return [f"bench artifact {name}: report.scenarios missing"]
    for s in _ELASTIC_SCENARIOS:
        if s not in scenarios:
            errors.append(f"bench artifact {name}: scenario '{s}' missing")
            continue
        for k in _ELASTIC_SCENARIO_KEYS:
            if k not in scenarios[s]:
                errors.append(
                    f"bench artifact {name}: scenario '{s}' missing '{k}'"
                )
    return errors


# the SLO artifact must keep proving the two-lane claims: per-lane
# latency phases, bulk-throughput retention, compile stability, and the
# response-cache + bf16-parity evidence (ISSUE 11 acceptance shape)
_SLO_REPORT_KEYS = ("baseline", "two_lane", "compile", "response_cache",
                    "bf16")
_SLO_PHASE_KEYS = ("interactive_ms", "bulk_imgs_per_sec", "lost_requests",
                   "scheduler")
_SLO_METRIC_PREFIXES = (
    "serve_slo_interactive_p99_ms_baseline",
    "serve_slo_interactive_p99_ms_two_lane",
    "serve_slo_interactive_p99_speedup",
    "serve_slo_bulk_retention",
    "serve_slo_cache_hit_rate",
    "serve_slo_steady_state_compile_misses",
    "serve_slo_lost_requests",
)


def _check_slo_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    for k in _SLO_REPORT_KEYS:
        if k not in report:
            errors.append(f"bench artifact {name}: report.{k} missing")
    for phase in ("baseline", "two_lane"):
        p = report.get(phase)
        if not isinstance(p, dict):
            continue
        for k in _SLO_PHASE_KEYS:
            if k not in p:
                errors.append(
                    f"bench artifact {name}: report.{phase}.{k} missing"
                )
    cache = report.get("response_cache")
    if isinstance(cache, dict) and "byte_identical" not in cache:
        errors.append(
            f"bench artifact {name}: response_cache.byte_identical missing"
        )
    bf16 = report.get("bf16")
    if isinstance(bf16, dict) and "parity" not in bf16:
        errors.append(f"bench artifact {name}: bf16.parity missing")
    metrics = {
        r.get("metric", "")
        for r in doc.get("records", [])
        if isinstance(r, dict)
    }
    for prefix in _SLO_METRIC_PREFIXES:
        if not any(m.startswith(prefix) for m in metrics):
            errors.append(
                f"bench artifact {name}: no record metric '{prefix}*'"
            )
    return errors


# the poison artifact must keep proving the four ISSUE 12 containment
# claims — a bench refactor that drops one (or lets it go false) is a
# lint failure, not a quietly weaker artifact
_POISON_CLAIMS = (
    "zero_healthy_lost", "healthy_byte_identical",
    "poison_quarantined_within_k", "all_replicas_healthy",
)
_POISON_METRIC_PREFIXES = (
    "serve_poison_healthy_lost",
    "serve_poison_healthy_byte_identical",
    "serve_poison_quarantined_within_k",
    "serve_poison_replicas_healthy",
)


def _check_poison_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    claims = report.get("claims")
    if not isinstance(claims, dict):
        return [f"bench artifact {name}: report.claims missing"]
    for c in _POISON_CLAIMS:
        if c not in claims:
            errors.append(f"bench artifact {name}: claim '{c}' missing")
        elif claims[c] is not True:
            errors.append(f"bench artifact {name}: claim '{c}' not true")
    if not report.get("digests"):
        errors.append(f"bench artifact {name}: report.digests empty — the "
                      f"run drew no poison, so the claims are vacuous")
    metrics = {
        r.get("metric", "")
        for r in doc.get("records", [])
        if isinstance(r, dict)
    }
    for prefix in _POISON_METRIC_PREFIXES:
        if not any(m.startswith(prefix) for m in metrics):
            errors.append(
                f"bench artifact {name}: no record metric '{prefix}*'"
            )
    return errors


# the overlap artifact must keep proving the four ISSUE 13 acceptance
# claims: the depth-2 speedup against the calibrated stub stall, the
# byte-identity of detections across depths, and the fault-matrix
# invariants (no request lost, no steady-state recompile) at depth=2
_OVERLAP_CLAIMS = (
    "speedup_ge_1_3", "byte_identical",
    "zero_lost_under_faults", "zero_steady_state_recompiles",
)
_OVERLAP_METRIC_PREFIXES = (
    "serve_overlap_speedup",
    "serve_overlap_byte_identical",
    "serve_overlap_fault_lost",
    "serve_overlap_steady_state_compile_misses",
)


def _check_overlap_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    claims = report.get("claims")
    if not isinstance(claims, dict):
        return [f"bench artifact {name}: report.claims missing"]
    for c in _OVERLAP_CLAIMS:
        if c not in claims:
            errors.append(f"bench artifact {name}: claim '{c}' missing")
        elif claims[c] is not True:
            errors.append(f"bench artifact {name}: claim '{c}' not true")
    for leg in ("depth1", "depth2"):
        leg_doc = report.get(leg)
        if not isinstance(leg_doc, dict) \
                or "device_busy_fraction" not in leg_doc:
            errors.append(
                f"bench artifact {name}: report.{leg}.device_busy_fraction "
                f"missing — the overlap claim has no utilization evidence"
            )
    metrics = {
        r.get("metric", "")
        for r in doc.get("records", [])
        if isinstance(r, dict)
    }
    for prefix in _OVERLAP_METRIC_PREFIXES:
        if not any(m.startswith(prefix) for m in metrics):
            errors.append(
                f"bench artifact {name}: no record metric '{prefix}*'"
            )
    return errors


# mask-family serving bench (ISSUE 14): the device-side mask selection
# artifact must carry the three closure claims — the >=5x fetch-byte
# reduction, per-detection RLE byte-identity vs the host path, and zero
# steady-state recompiles — plus the measured fetch-byte evidence the
# reduction claim rests on.
_MASK_CLAIMS = (
    "fetch_reduction_ge_5x",
    "rle_byte_identical",
    "zero_steady_state_recompiles",
)

_MASK_METRIC_PREFIXES = (
    "serve_mask_p50_ms",
    "serve_mask_p99_ms",
    "serve_mask_fetch_bytes_per_batch_raw",
    "serve_mask_fetch_bytes_per_batch_device",
    "serve_mask_fetch_reduction",
    "serve_mask_rle_byte_identical",
    "serve_mask_steady_state_compile_misses",
)


def _check_mask_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    claims = report.get("claims")
    if not isinstance(claims, dict):
        return [f"bench artifact {name}: report.claims missing"]
    for c in _MASK_CLAIMS:
        if c not in claims:
            errors.append(f"bench artifact {name}: claim '{c}' missing")
        elif claims[c] is not True:
            errors.append(f"bench artifact {name}: claim '{c}' not true")
    fb = report.get("fetch_bytes")
    if not isinstance(fb, dict) or not {
        "raw_per_batch", "device_per_batch", "reduction"
    } <= set(fb):
        errors.append(
            f"bench artifact {name}: report.fetch_bytes incomplete — the "
            f"fetch-reduction claim has no measured byte evidence"
        )
    metrics = {
        r.get("metric", "")
        for r in doc.get("records", [])
        if isinstance(r, dict)
    }
    for prefix in _MASK_METRIC_PREFIXES:
        if not any(m.startswith(prefix) for m in metrics):
            errors.append(
                f"bench artifact {name}: no record metric '{prefix}*'"
            )
    return errors


# tenant-fair front door bench (ISSUE 16): the scale artifact must
# carry the four closure claims — victim p99 isolation under an
# aggressor blast, the autoscaler-initiated zero-loss byte-identical
# scale-down, bounded no-flap trace convergence with the breaker
# engaging on the oscillating trace, and zero steady-state recompiles
# at every pool size — plus the victim latency evidence the isolation
# claim rests on.
_SCALE_CLAIMS = (
    "tenant_isolation",
    "zero_loss_shrink",
    "no_flap",
    "zero_steady_state_recompiles",
)

_SCALE_METRIC_PREFIXES = (
    "serve_scale_victim_solo_p99_ms",
    "serve_scale_victim_contended_p99_ms",
    "serve_scale_aggressor_shed",
    "serve_scale_shrink_lost_requests",
    "serve_scale_detections_match",
    "serve_scale_shrink_recompiles",
    "serve_scale_diurnal_events",
    "serve_scale_oscillating_events",
)


def _check_scale_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    claims = report.get("claims")
    if not isinstance(claims, dict):
        return [f"bench artifact {name}: report.claims missing"]
    for c in _SCALE_CLAIMS:
        if c not in claims:
            errors.append(f"bench artifact {name}: claim '{c}' missing")
        elif claims[c] is not True:
            errors.append(f"bench artifact {name}: claim '{c}' not true")
    victim = report.get("victim")
    if not isinstance(victim, dict) or not {
        "solo_p99_ms", "contended_p99_ms"
    } <= set(victim):
        errors.append(
            f"bench artifact {name}: report.victim incomplete — the "
            f"isolation claim has no latency evidence"
        )
    metrics = {
        r.get("metric", "")
        for r in doc.get("records", [])
        if isinstance(r, dict)
    }
    for prefix in _SCALE_METRIC_PREFIXES:
        if not any(m.startswith(prefix) for m in metrics):
            errors.append(
                f"bench artifact {name}: no record metric '{prefix}*'"
            )
    return errors


# progressive rollout bench (ISSUE 17): the artifact must prove the
# full closed loop — zero requests lost through split + promote, the
# control arm byte-identical to a no-rollout run, the divergence-
# injected candidate auto-rolled-back while the incumbent kept serving,
# zero steady-state recompiles end to end, and the distilled candidate
# promoted through the serve→train→serve loop — plus the shadow
# divergence evidence the rollback claim rests on.
_ROLLOUT_CLAIMS = (
    "zero_lost_requests",
    "control_arm_byte_identical",
    "divergence_auto_rollback",
    "zero_steady_state_recompiles",
    "closed_loop_promoted",
)

_ROLLOUT_METRIC_PREFIXES = (
    "rollout_split_served",
    "rollout_shadow_compared",
    "rollout_promote_lost_requests",
    "rollout_rollback_incumbent_identical",
    "rollout_steady_state_recompiles",
    "rollout_distill_records",
    "rollout_loop_promoted_version",
)


def _check_rollout_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    claims = report.get("claims")
    if not isinstance(claims, dict):
        return [f"bench artifact {name}: report.claims missing"]
    for c in _ROLLOUT_CLAIMS:
        if c not in claims:
            errors.append(f"bench artifact {name}: claim '{c}' missing")
        elif claims[c] is not True:
            errors.append(f"bench artifact {name}: claim '{c}' not true")
    div = report.get("divergence")
    if not isinstance(div, dict) or not {
        "compared", "max_box_delta_px"
    } <= set(div):
        errors.append(
            f"bench artifact {name}: report.divergence incomplete — the "
            f"rollback claim has no shadow-comparison evidence"
        )
    metrics = {
        r.get("metric", "")
        for r in doc.get("records", [])
        if isinstance(r, dict)
    }
    for prefix in _ROLLOUT_METRIC_PREFIXES:
        if not any(m.startswith(prefix) for m in metrics):
            errors.append(
                f"bench artifact {name}: no record metric '{prefix}*'"
            )
    return errors


_CASCADE_CLAIMS = (
    "cost_reduction_ge_1p3x_at_matched_accuracy",
    "full_escalation_byte_identical",
    "zero_steady_state_recompiles",
    "int8_parity_ok_box_and_mask",
    "bf16_parity_ok_box_and_mask",
)

_CASCADE_METRIC_PREFIXES = (
    "serve_cascade_cost_ms_per_image",
    "serve_cascade_cost_reduction",
    "serve_cascade_accuracy",
    "serve_cascade_escalation_rate",
    "serve_cascade_parity_rungs_ok",
    "serve_cascade_int8_compression",
    "serve_cascade_steady_state_compile_misses",
)


def _check_cascade_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    claims = report.get("claims")
    if not isinstance(claims, dict):
        return [f"bench artifact {name}: report.claims missing"]
    for c in _CASCADE_CLAIMS:
        if c not in claims:
            errors.append(f"bench artifact {name}: claim '{c}' missing")
        elif claims[c] is not True:
            errors.append(f"bench artifact {name}: claim '{c}' not true")
    sweep = report.get("sweep")
    if not isinstance(sweep, list) or len(sweep) < 2:
        errors.append(
            f"bench artifact {name}: report.sweep missing — the cost "
            f"claim has no threshold-curve evidence"
        )
    matrix = report.get("parity_matrix")
    if not isinstance(matrix, list) or {
        (r.get("family"), r.get("precision"))
        for r in matrix
        if isinstance(r, dict)
    } != {
        (f, p)
        for f in ("box", "mask")
        for p in ("f32", "bf16", "int8")
    }:
        errors.append(
            f"bench artifact {name}: report.parity_matrix must cover "
            f"{{box,mask}} x {{f32,bf16,int8}}"
        )
    metrics = {
        r.get("metric", "")
        for r in doc.get("records", [])
        if isinstance(r, dict)
    }
    for prefix in _CASCADE_METRIC_PREFIXES:
        if not any(m.startswith(prefix) for m in metrics):
            errors.append(
                f"bench artifact {name}: no record metric '{prefix}*'"
            )
    return errors


# multi-host fleet bench (ISSUE 19): the artifact must prove the
# scale-out story end to end — N=1 gateway responses byte-identical to
# the direct engine (the wire adds routing, never bytes), >=1.7x/>=3x
# aggregate imgs/s at 2/4 backend processes, and the SIGKILL chaos
# phase losing zero requests with surviving responses byte-identical
# to an unfaulted run — plus the per-size scaling evidence and the
# chaos accounting (lost/requeued) the claims rest on.
_FLEET_CLAIMS = (
    "n1_byte_identical",
    "scaling_2x",
    "scaling_4x",
    "chaos_zero_lost",
    "chaos_byte_identical",
)

_FLEET_METRIC_PREFIXES = (
    "serve_fleet_imgs_per_sec",
    "serve_fleet_speedup_2x",
    "serve_fleet_speedup_4x",
    "serve_fleet_n1_byte_identical",
    "serve_fleet_chaos_lost",
    "serve_fleet_chaos_requeued",
    "serve_fleet_chaos_byte_identical",
)


def _check_fleet_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    claims = report.get("claims")
    if not isinstance(claims, dict):
        return [f"bench artifact {name}: report.claims missing"]
    for c in _FLEET_CLAIMS:
        if c not in claims:
            errors.append(f"bench artifact {name}: claim '{c}' missing")
        elif claims[c] is not True:
            errors.append(f"bench artifact {name}: claim '{c}' not true")
    scaling = report.get("scaling")
    if not isinstance(scaling, list) or not {
        r.get("backends") for r in scaling if isinstance(r, dict)
    } >= {1, 2, 4}:
        errors.append(
            f"bench artifact {name}: report.scaling must cover 1/2/4 "
            f"backends — the speedup claims have no sweep evidence"
        )
    chaos = report.get("chaos")
    if not isinstance(chaos, dict) or not {
        "lost", "requeued", "byte_identical"
    } <= set(chaos):
        errors.append(
            f"bench artifact {name}: report.chaos incomplete — the "
            f"zero-loss claim has no kill-phase accounting"
        )
    metrics = {
        r.get("metric", "")
        for r in doc.get("records", [])
        if isinstance(r, dict)
    }
    for prefix in _FLEET_METRIC_PREFIXES:
        if not any(m.startswith(prefix) for m in metrics):
            errors.append(
                f"bench artifact {name}: no record metric '{prefix}*'"
            )
    return errors


# streaming bench (ISSUE 20): the artifact must prove the streaming
# closure — device-paste RLEs byte-identical to the host-paste path,
# the >=5x host paste-ms/frame reduction at flagship geometry, zero
# steady-state recompiles through warmup + hot-swap, per-stream
# in-order completion with zero lost frames under the chaos matrix
# with surviving bytes identical to the unfaulted run, and a monotone
# priming recall/latency table — plus the paste-ms and ordering
# evidence the claims rest on.
_STREAMING_CLAIMS = (
    "paste_rle_byte_identical",
    "paste_reduction_ge_5x",
    "zero_steady_state_recompiles",
    "stream_in_order_under_chaos",
    "chaos_bytes_identical",
    "priming_monotone_tradeoff",
)

_STREAMING_METRIC_PREFIXES = (
    "streaming_paste_host_ms_per_frame",
    "streaming_paste_device_ms_per_frame",
    "streaming_paste_reduction_x",
    "streaming_paste_rle_byte_identical",
    "streaming_steady_state_compile_misses",
    "streaming_chaos_lost_frames",
    "streaming_chaos_in_order",
    "streaming_priming_recall_gain",
)


def _check_streaming_schema(name: str, doc: dict) -> List[str]:
    errors = []
    report = doc.get("report") if isinstance(doc, dict) else None
    if not isinstance(report, dict):
        return [f"bench artifact {name}: missing report object"]
    claims = report.get("claims")
    if not isinstance(claims, dict):
        return [f"bench artifact {name}: report.claims missing"]
    for c in _STREAMING_CLAIMS:
        if c not in claims:
            errors.append(f"bench artifact {name}: claim '{c}' missing")
        elif claims[c] is not True:
            errors.append(f"bench artifact {name}: claim '{c}' not true")
    paste = report.get("paste")
    if not isinstance(paste, dict) or not isinstance(
        paste.get("stub"), dict
    ) or not {
        "host_paste_ms_per_frame", "device_paste_ms_per_frame",
        "reduction_x",
    } <= set(paste["stub"]):
        errors.append(
            f"bench artifact {name}: report.paste.stub incomplete — the "
            f"paste-reduction claim has no measured ms evidence"
        )
    chaos = report.get("chaos")
    if not isinstance(chaos, dict) or not all(
        isinstance(s, dict) and {"in_order", "lost_frames"} <= set(s)
        for s in chaos.values()
    ) or len(chaos) < 2:
        errors.append(
            f"bench artifact {name}: report.chaos incomplete — the "
            f"in-order claim has no per-scenario ordering evidence"
        )
    priming = report.get("priming")
    if not isinstance(priming, dict) or not isinstance(
        priming.get("table"), list
    ) or len(priming["table"]) < 3:
        errors.append(
            f"bench artifact {name}: report.priming.table missing — the "
            f"tradeoff claim has no sweep rows"
        )
    metrics = {
        r.get("metric", "")
        for r in doc.get("records", [])
        if isinstance(r, dict)
    }
    for prefix in _STREAMING_METRIC_PREFIXES:
        if not any(m.startswith(prefix) for m in metrics):
            errors.append(
                f"bench artifact {name}: no record metric '{prefix}*'"
            )
    return errors


def check_bench_artifacts(root: Path) -> List[str]:
    errors = []
    for f in sorted(root.glob("BENCH_*.json")):
        try:
            doc = json.loads(f.read_text())
        except (json.JSONDecodeError, OSError) as e:
            errors.append(f"bench artifact {f.name}: unparseable ({e})")
            continue
        if not isinstance(doc, (dict, list)) or not doc:
            errors.append(f"bench artifact {f.name}: empty or non-object")
            continue
        if f.name == "BENCH_elastic_cpu.json":
            errors += _check_elastic_schema(f.name, doc)
        if f.name == "BENCH_serve_slo_cpu.json":
            errors += _check_slo_schema(f.name, doc)
        if f.name == "BENCH_poison_cpu.json":
            errors += _check_poison_schema(f.name, doc)
        if f.name == "BENCH_serve_overlap_cpu.json":
            errors += _check_overlap_schema(f.name, doc)
        if f.name == "BENCH_serve_mask_cpu.json":
            errors += _check_mask_schema(f.name, doc)
        if f.name == "BENCH_serve_scale_cpu.json":
            errors += _check_scale_schema(f.name, doc)
        if f.name == "BENCH_rollout_cpu.json":
            errors += _check_rollout_schema(f.name, doc)
        if f.name == "BENCH_cascade_cpu.json":
            errors += _check_cascade_schema(f.name, doc)
        if f.name == "BENCH_serve_fleet_cpu.json":
            errors += _check_fleet_schema(f.name, doc)
        if f.name == "BENCH_streaming_cpu.json":
            errors += _check_streaming_schema(f.name, doc)
    return errors


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="graftlint")
    ap.add_argument(
        "--root", type=Path, default=None,
        help="repo root (default: two levels above this file)",
    )
    ap.add_argument(
        "--baseline", type=Path, default=None,
        help="baseline suppressions (default: <root>/tools/lint_baseline.json)",
    )
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--no-bench-schema", action="store_true",
        help="skip the BENCH_*.json parse guard",
    )
    args = ap.parse_args(argv)

    root = args.root or Path(__file__).resolve().parents[2]
    baseline_path = args.baseline or root / "tools" / "lint_baseline.json"
    baseline = (
        eng.load_baseline(baseline_path) if baseline_path.exists() else []
    )

    modules, errors = eng.load_modules(root)
    if not args.no_bench_schema:
        errors = list(errors) + check_bench_artifacts(root)
    report = eng.analyze(modules, eng.default_rules(), baseline, errors)

    if args.format == "json":
        print(
            json.dumps(
                {
                    "ok": report.ok,
                    "findings": [f.__dict__ for f in report.findings],
                    "baseline_suppressed": len(report.baseline_suppressed),
                    "inline_suppressed": len(report.inline_suppressed),
                    "stale_baseline": [
                        e.__dict__ for e in report.stale_baseline
                    ],
                    "errors": report.errors,
                },
                indent=1,
            )
        )
        return 0 if report.ok else 1

    for f in report.findings:
        print(f.format())
    for e in report.stale_baseline:
        print(
            f"STALE baseline entry {e.rule} {e.path} [{e.scope}] — matches "
            f"no current finding; remove it"
        )
    for msg in report.errors:
        print(f"ERROR {msg}")
    print(f"graftlint: {report.summary()}")
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
