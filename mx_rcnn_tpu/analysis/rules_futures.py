"""R5 exactly-once resolution — the PR 6 requeue-never-drop rule.

Anything popped from a dispatch queue carries a caller-visible future;
the holder must resolve it, requeue it, or hand it to someone who will,
on EVERY exit path.  The incident class this catches is the early
``return``/``continue`` that silently drops a dispatch, wedging the
caller until its deadline.

Detection: a *take* is a name bound from ``<recv>.get(...)``,
``<recv>.get_nowait()``, ``<recv>.popleft()`` or
``<recv>.next_batch(...)`` where the receiver name looks like a
dispatch queue (``inbox``/``queue``/``batcher``/``pending``).  From the
take, every control-flow path to a scope exit (or to falling off the
end of the enclosing loop body, which re-takes) must REFERENCE the
bound name at least once — resolving, requeuing, forwarding, and the
``if d is None: break`` sentinel check all count.  A path that exits
without ever looking at the value cannot possibly have resolved it.

This is deliberately an under-approximation (a path could look at the
value and still drop it); the fault-matrix tests own the stronger
guarantee.  It is also zero-noise by construction on code that checks
its takes.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from mx_rcnn_tpu.analysis.engine import Finding, Module, Rule, dotted

TAKE_METHODS = {"get", "get_nowait", "popleft", "next_batch"}
QUEUEISH = re.compile(r"(inbox|queue|batcher|pending)", re.IGNORECASE)


def _uses_name(node: ast.AST, name: str) -> bool:
    return any(
        isinstance(n, ast.Name) and n.id == name for n in ast.walk(node)
    )


class ExactlyOnce(Rule):
    id = "R5"
    name = "exactly-once resolution"

    def _in_scope(self, module: Module) -> bool:
        return "/serve/" in f"/{module.path}"

    def check_module(self, module: Module) -> List[Finding]:
        if not self._in_scope(module):
            return []
        out: List[Finding] = []
        for n in ast.walk(module.tree):
            if not (
                isinstance(n, ast.Assign)
                and isinstance(n.value, ast.Call)
                and isinstance(n.value.func, ast.Attribute)
                and n.value.func.attr in TAKE_METHODS
            ):
                continue
            recv = dotted(n.value.func.value) or ""
            if not QUEUEISH.search(recv):
                continue
            if len(n.targets) != 1 or not isinstance(n.targets[0], ast.Name):
                continue
            name = n.targets[0].id
            if not self._covered(module, n, name):
                out.append(
                    Finding(
                        self.id,
                        module.path,
                        n.lineno,
                        module.scope_of(n),
                        f"`{name}` taken from `{recv}.{n.value.func.attr}` "
                        f"can reach a scope exit without being resolved, "
                        f"requeued, or forwarded",
                    )
                )
        return out

    # ---- path coverage ----------------------------------------------

    def _covered(self, module: Module, take: ast.stmt, name: str) -> bool:
        cont = self._continuation(module, take)
        return self._paths_touch(cont, name)

    def _continuation(self, module: Module, stmt: ast.stmt) -> List[ast.stmt]:
        """Statements that execute after ``stmt``: following siblings at
        each enclosing block level, up to the enclosing function.  The
        loop back-edge (falling off a loop body re-takes) is treated as
        a safe exit by truncating at the loop."""
        out: List[ast.stmt] = []
        node: ast.AST = stmt
        while True:
            parent = module.parent(node)
            if parent is None or isinstance(
                parent, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)
            ):
                out.extend(self._siblings_after(parent, node))
                return out
            out.extend(self._siblings_after(parent, node))
            if isinstance(parent, (ast.For, ast.While)):
                return out  # back-edge: next iteration re-takes
            node = parent

    def _siblings_after(
        self, parent: Optional[ast.AST], node: ast.AST
    ) -> List[ast.stmt]:
        if parent is None:
            return []
        out: List[ast.stmt] = []
        for field in ("body", "orelse", "finalbody"):
            blk = getattr(parent, field, None)
            if isinstance(blk, list) and node in blk:
                out.extend(blk[blk.index(node) + 1:])
        if isinstance(parent, ast.Try):
            for h in parent.handlers:
                if node in h.body:
                    out.extend(h.body[h.body.index(node) + 1:])
                    out.extend(parent.finalbody)
        if isinstance(parent, ast.ExceptHandler):
            if node in parent.body:
                out.extend(parent.body[parent.body.index(node) + 1:])
        return out

    def _paths_touch(self, stmts: List[ast.stmt], name: str) -> bool:
        """True when every path through ``stmts`` references ``name``
        before exiting the scope."""
        for i, s in enumerate(stmts):
            rest = stmts[i + 1:]
            if _uses_name(s, name):
                return True  # this path has looked at the take
            if isinstance(s, (ast.Return, ast.Raise, ast.Break, ast.Continue)):
                return False  # exit without ever touching it
            if isinstance(s, ast.If):
                return self._paths_touch(s.body + rest, name) and (
                    self._paths_touch(s.orelse + rest, name)
                )
            if isinstance(s, ast.Try):
                ok = self._paths_touch(s.body + s.orelse + s.finalbody + rest, name)
                for h in s.handlers:
                    ok = ok and self._paths_touch(
                        h.body + s.finalbody + rest, name
                    )
                return ok
            if isinstance(s, ast.With):
                return self._paths_touch(s.body + rest, name)
            if isinstance(s, (ast.For, ast.While)):
                # zero-iteration possibility: coverage must come later
                continue
        return False  # fell off the end of the scope without touching
