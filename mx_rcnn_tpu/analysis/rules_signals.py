"""R7 signal-safety: what a registered signal handler may touch.

A Python signal handler runs on the main thread at an arbitrary bytecode
boundary — possibly in the middle of a jit dispatch, while a serve lock
is held, or inside the fault registry's parse.  The project's contract
(``PreemptionGuard``) is that handlers only flip flags and re-raise:
anything heavier belongs after the step loop polls the flag.

This rule finds every handler registered via ``signal.signal(SIG, h)``
and walks its body — plus same-class ``self.*`` methods and same-module
functions it calls, to a fixed point — flagging:

* **device work**: ``jax.device_put/device_get/jit/pmap`` or the
  project placement helpers (``host_copy``, ``replicate``,
  ``shard_batch``, ``make_place_fn``) — a handler interrupting the very
  dispatch it re-enters can deadlock the runtime;
* **lock acquisition**: ``with <lock-ish attribute>:`` or an explicit
  ``.acquire()`` — the interrupted frame may already hold that lock
  (classic async-signal deadlock);
* **fault-injection hooks**: any ``faults.*`` call — the registry
  re-parses on env change and mutates shared trigger counters, neither
  of which is reentrant.

``PreemptionGuard._handle`` (flag flip, handler restore, ``os.kill``
re-raise) is the canonical clean fixture and must produce no findings.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.analysis.engine import Finding, Module, Rule, dotted

# dotted names (exact) that are device/compile work
DEVICE_EXACT = {"jax.device_put", "jax.device_get", "jax.jit", "jax.pmap"}
# last-component names that are device/placement work wherever they live
DEVICE_TAILS = {
    "device_put", "device_get", "host_copy", "replicate", "shard_batch",
    "make_place_fn",
}
# attribute names that look like locks when used as ``with self.<attr>:``
_LOCKISH = ("lock", "mutex", "cond", "cv")


def _lockish_attr(name: str) -> bool:
    low = name.lower()
    return any(t in low for t in _LOCKISH)


class _Handler:
    """One registered handler: the function node plus where it was
    registered (for the finding's anchor when the body lives elsewhere)."""

    def __init__(
        self,
        module: Module,
        fn: ast.FunctionDef,
        cls: Optional[ast.ClassDef],
        reg_line: int,
    ):
        self.module = module
        self.fn = fn
        self.cls = cls
        self.reg_line = reg_line


class SignalSafety(Rule):
    id = "R7"
    name = "signal safety"

    def finalize(self, modules: Sequence[Module]) -> List[Finding]:
        out: List[Finding] = []
        for m in modules:
            for h in self._handlers(m):
                out.extend(self._check_handler(h))
        return out

    # ---- registration discovery ------------------------------------

    def _handlers(self, m: Module) -> List[_Handler]:
        found: List[_Handler] = []
        for node in ast.walk(m.tree):
            if not (isinstance(node, ast.Call) and len(node.args) >= 2):
                continue
            if dotted(node.func) != "signal.signal":
                continue
            target = node.args[1]
            fn, cls = self._resolve_handler(m, node, target)
            if fn is not None:
                found.append(_Handler(m, fn, cls, node.lineno))
        return found

    def _resolve_handler(
        self, m: Module, site: ast.Call, target: ast.AST
    ) -> Tuple[Optional[ast.FunctionDef], Optional[ast.ClassDef]]:
        # self._handle → method of the class enclosing the registration
        if (
            isinstance(target, ast.Attribute)
            and isinstance(target.value, ast.Name)
            and target.value.id == "self"
        ):
            cls = self._enclosing_class(m, site)
            if cls is not None:
                fn = self._class_method(cls, target.attr)
                if fn is not None:
                    return fn, cls
            return None, None
        # bare name → module-level function (or a local def in scope)
        if isinstance(target, ast.Name):
            fn = self._module_function(m, target.id)
            if fn is not None:
                return fn, None
        return None, None

    @staticmethod
    def _enclosing_class(m: Module, node: ast.AST) -> Optional[ast.ClassDef]:
        cur = m.parent(node)
        while cur is not None:
            if isinstance(cur, ast.ClassDef):
                return cur
            cur = m.parent(cur)
        return None

    @staticmethod
    def _class_method(cls: ast.ClassDef, name: str) -> Optional[ast.FunctionDef]:
        for child in cls.body:
            if isinstance(child, ast.FunctionDef) and child.name == name:
                return child
        return None

    @staticmethod
    def _module_function(m: Module, name: str) -> Optional[ast.FunctionDef]:
        for child in m.tree.body:
            if isinstance(child, ast.FunctionDef) and child.name == name:
                return child
        return None

    # ---- reachability + checks -------------------------------------

    def _check_handler(self, h: _Handler) -> List[Finding]:
        out: List[Finding] = []
        visited: Set[int] = set()
        queue: List[Tuple[ast.FunctionDef, Optional[ast.ClassDef]]] = [
            (h.fn, h.cls)
        ]
        while queue:
            fn, cls = queue.pop()
            if id(fn) in visited:
                continue
            visited.add(id(fn))
            scope = h.module.scope_of(fn)
            for n in ast.walk(fn):
                if isinstance(n, ast.With):
                    for item in n.items:
                        ctx = item.context_expr
                        attr = (
                            ctx.attr if isinstance(ctx, ast.Attribute)
                            else ctx.id if isinstance(ctx, ast.Name)
                            else None
                        )
                        if attr is not None and _lockish_attr(attr):
                            out.append(self._finding(
                                h, n.lineno, scope,
                                f"acquires lock `{attr}` — the interrupted "
                                f"frame may already hold it",
                            ))
                if not isinstance(n, ast.Call):
                    continue
                d = dotted(n.func) or ""
                tail = d.rsplit(".", 1)[-1]
                if d in DEVICE_EXACT or tail in DEVICE_TAILS:
                    out.append(self._finding(
                        h, n.lineno, scope,
                        f"device/placement work `{d}` — a handler can "
                        f"interrupt the dispatch it re-enters",
                    ))
                elif tail == "acquire" and isinstance(n.func, ast.Attribute):
                    out.append(self._finding(
                        h, n.lineno, scope,
                        "explicit `.acquire()` — the interrupted frame may "
                        "already hold the lock",
                    ))
                elif d.startswith("faults.") or d.startswith(
                    "mx_rcnn_tpu.utils.faults."
                ):
                    out.append(self._finding(
                        h, n.lineno, scope,
                        f"fault-injection hook `{d}` — the registry's "
                        f"parse/trigger state is not reentrant",
                    ))
                else:
                    # follow same-class and same-module callees
                    nxt = self._callee(h, cls, n)
                    if nxt is not None:
                        queue.append(nxt)
        return out

    def _callee(
        self, h: _Handler, cls: Optional[ast.ClassDef], call: ast.Call
    ) -> Optional[Tuple[ast.FunctionDef, Optional[ast.ClassDef]]]:
        f = call.func
        if (
            cls is not None
            and isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
        ):
            fn = self._class_method(cls, f.attr)
            if fn is not None:
                return fn, cls
        if isinstance(f, ast.Name):
            fn = self._module_function(h.module, f.id)
            if fn is not None:
                return fn, None
        return None

    def _finding(self, h: _Handler, line: int, scope: str, msg: str) -> Finding:
        return Finding(
            self.id, h.module.path, line, scope,
            f"reachable from signal handler `{h.module.scope_of(h.fn)}` "
            f"(registered at line {h.reg_line}): {msg}",
        )
