"""R4 lock order + device-work-under-lock across the serve stack.

Builds a static lock-acquisition graph over every class in
``mx_rcnn_tpu/serve/``:

* lock attributes are assignments of ``threading.Lock/RLock/Condition``
  or the project's ``make_lock("Name")`` / ``make_condition("Name")``
  (lockcheck.py) to ``self.<attr>``;
* a ``with self._lock:`` (or ``with other._lock:`` where ``other``'s
  class is resolvable) acquires that lock for the lexical extent of the
  block;
* method calls inside a held block propagate the callee's own (direct +
  transitive) acquisitions, computed to a fixed point.  Receivers are
  resolved by constructor typing (``self.batcher = DynamicBatcher(...)``),
  a small table of project attribute/parameter naming conventions, or —
  failing both — by method-name uniqueness across the analyzed classes.

Findings:

* a CYCLE in the resulting lock-name graph (potential deadlock);
* re-acquisition of a non-reentrant lock on some call path (guaranteed
  deadlock; RLocks are exempt);
* ``jax.device_put`` / ``jax.jit`` / ``jax.device_get`` (directly or
  transitively) executed while holding a serve lock — device placement
  and compiles take arbitrarily long and must not serialize the
  serving control plane (the PR 7 post-stop-placement rule).

The runtime counterpart is ``analysis/lockcheck.py``
(MX_RCNN_LOCK_CHECK=1), which catches inversions this lexical analysis
cannot see.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from mx_rcnn_tpu.analysis.engine import Finding, Module, Rule, dotted

LOCK_CTORS = {"threading.Lock", "threading.RLock", "make_lock",
              "lockcheck.make_lock"}
COND_CTORS = {"threading.Condition", "make_condition",
              "lockcheck.make_condition"}
RLOCK_CTORS = {"threading.RLock"}
DEVICE_CALLS = {"jax.device_put", "jax.device_get", "jax.jit", "jax.pmap"}

# project attribute/parameter naming conventions (documented fallback
# when constructor typing can't resolve a receiver)
NAME_HINTS = {
    "registry": "ModelRegistry",
    "reg": "ModelRegistry",
    "batcher": "DynamicBatcher",
    "pool": "ReplicaPool",
    "slot": "_ModelSlot",
    "runner": "ServeRunner",
    "replica": "Replica",
    "primary": "Replica",
    "backup": "Replica",
    "compile_cache": "CompileCache",
}


class _ClassInfo:
    def __init__(self, name: str, module: Module, node: ast.ClassDef):
        self.name = name
        self.module = module
        self.node = node
        self.locks: Dict[str, bool] = {}  # attr -> is_reentrant
        self.attr_types: Dict[str, str] = {}
        self.methods: Dict[str, ast.FunctionDef] = {}


class _MethodInfo:
    def __init__(self, cls: _ClassInfo, node: ast.FunctionDef):
        self.cls = cls
        self.node = node
        # direct acquisitions: (lock qualname "Class.attr", reentrant)
        self.direct: Set[Tuple[str, bool]] = set()
        self.direct_device: List[ast.Call] = []
        # (held locks at site, callee class or None, callee name, node)
        self.calls: List[
            Tuple[Tuple[Tuple[str, bool], ...], Optional[str], str, ast.AST]
        ] = []
        # fixed-point results
        self.all_locks: Set[Tuple[str, bool]] = set()
        self.uses_device = False


def _lock_ctor_kind(call: ast.Call) -> Optional[bool]:
    """None if not a lock ctor, else is_reentrant."""
    d = dotted(call.func) or ""
    if d in RLOCK_CTORS:
        return True
    if d in LOCK_CTORS:
        for kw in call.keywords:
            if kw.arg == "rlock" and isinstance(kw.value, ast.Constant):
                return bool(kw.value.value)
        return False
    if d in COND_CTORS:
        # Condition() defaults to RLock underneath; make_condition uses a
        # plain named lock but is never re-entered by the stack
        return d == "threading.Condition" and not call.args
    return None


class LockOrder(Rule):
    id = "R4"
    name = "lock order"

    def _in_scope(self, module: Module) -> bool:
        return "/serve/" in f"/{module.path}"

    def finalize(self, modules: Sequence[Module]) -> List[Finding]:
        classes: Dict[str, _ClassInfo] = {}
        for m in modules:
            if not self._in_scope(m):
                continue
            for node in ast.walk(m.tree):
                if isinstance(node, ast.ClassDef):
                    classes[node.name] = self._scan_class(m, node)
        if not classes:
            return []

        methods: Dict[Tuple[str, str], _MethodInfo] = {}
        for ci in classes.values():
            for mname, fn in ci.methods.items():
                methods[(ci.name, mname)] = self._scan_method(
                    ci, fn, classes
                )

        self._fixed_point(methods, classes)
        out: List[Finding] = []
        edges: Dict[str, Set[str]] = {}
        edge_site: Dict[Tuple[str, str], Tuple[Module, int, str]] = {}

        for (cname, mname), mi in methods.items():
            scope = f"{cname}.{mname}"
            for held, callee_cls, callee_name, node in mi.calls:
                if not held:
                    continue
                target = self._resolve(callee_cls, callee_name, methods)
                if target is None:
                    continue
                tinfo = methods[target]
                for lock, reentrant in tinfo.all_locks:
                    for hname, hre in held:
                        if hname == lock:
                            if not (reentrant and hre):
                                out.append(
                                    Finding(
                                        self.id,
                                        mi.cls.module.path,
                                        node.lineno,
                                        scope,
                                        f"call path re-acquires non-"
                                        f"reentrant lock {lock} while "
                                        f"already held",
                                    )
                                )
                            continue
                        if lock not in edges.setdefault(hname, set()):
                            edges[hname].add(lock)
                            edge_site[(hname, lock)] = (
                                mi.cls.module,
                                node.lineno,
                                scope,
                            )
                if tinfo.uses_device:
                    out.append(
                        Finding(
                            self.id,
                            mi.cls.module.path,
                            node.lineno,
                            scope,
                            f"device/compile work reached while holding "
                            f"{', '.join(h for h, _ in held)} — placement "
                            f"and compiles must not run under serve locks",
                        )
                    )
            for call in mi.direct_device:
                held = self._held_at(mi, call)
                if held:
                    out.append(
                        Finding(
                            self.id,
                            mi.cls.module.path,
                            call.lineno,
                            scope,
                            f"`{dotted(call.func)}` called while holding "
                            f"{', '.join(h for h, _ in held)} — placement "
                            f"and compiles must not run under serve locks",
                        )
                    )

        out.extend(self._find_cycles(edges, edge_site))
        return out

    # ---- class/method scanning -------------------------------------

    def _scan_class(self, m: Module, node: ast.ClassDef) -> _ClassInfo:
        ci = _ClassInfo(node.name, m, node)
        for child in node.body:
            if isinstance(child, ast.FunctionDef):
                ci.methods[child.name] = child
        for n in ast.walk(node):
            if not (isinstance(n, ast.Assign) and isinstance(n.value, ast.Call)):
                continue
            for t in n.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    kind = _lock_ctor_kind(n.value)
                    if kind is not None:
                        ci.locks[t.attr] = kind
                    else:
                        ctor = dotted(n.value.func)
                        if ctor:
                            ci.attr_types[t.attr] = ctor.split(".")[-1]
            # element typing for replica lists: self.xs = [Cls(...) ...]
            if isinstance(n.value, ast.ListComp) and isinstance(
                n.value.elt, ast.Call
            ):
                ctor = dotted(n.value.elt.func)
                for t in n.targets:
                    if (
                        ctor
                        and isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        ci.attr_types[t.attr] = ctor.split(".")[-1]
        return ci

    def _resolve_receiver_type(
        self,
        expr: ast.AST,
        ci: _ClassInfo,
        classes: Dict[str, _ClassInfo],
        aliases: Dict[str, str],
    ) -> Optional[str]:
        d = dotted(expr)
        if d is None:
            return None
        d = aliases.get(d, d)
        if d == "self":
            return ci.name
        parts = d.split(".")
        if parts[0] == "self" and len(parts) == 2:
            attr = parts[1]
            if attr in ci.attr_types and ci.attr_types[attr] in classes:
                return ci.attr_types[attr]
            if attr in NAME_HINTS and NAME_HINTS[attr] in classes:
                return NAME_HINTS[attr]
            return None
        if len(parts) == 1:
            hint = NAME_HINTS.get(parts[0])
            if hint in classes:
                return hint
        return None

    def _local_aliases(self, fn: ast.FunctionDef) -> Dict[str, str]:
        """name -> dotted origin for trivial assigns incl. tuple unpack
        (``reg, e = self.registry, self.entry``)."""
        out: Dict[str, str] = {}
        for n in ast.walk(fn):
            if not isinstance(n, ast.Assign) or len(n.targets) != 1:
                continue
            t, v = n.targets[0], n.value
            if isinstance(t, ast.Name):
                src = dotted(v)
                if src:
                    out[t.id] = src
            elif (
                isinstance(t, ast.Tuple)
                and isinstance(v, ast.Tuple)
                and len(t.elts) == len(v.elts)
            ):
                for te, ve in zip(t.elts, v.elts):
                    if isinstance(te, ast.Name):
                        src = dotted(ve)
                        if src:
                            out[te.id] = src
        return out

    def _lock_of_with_item(
        self,
        expr: ast.AST,
        ci: _ClassInfo,
        classes: Dict[str, _ClassInfo],
        aliases: Dict[str, str],
    ) -> Optional[Tuple[str, bool]]:
        if not isinstance(expr, ast.Attribute):
            return None
        owner_type = self._resolve_receiver_type(
            expr.value, ci, classes, aliases
        )
        if owner_type is None and isinstance(expr.value, ast.Name):
            hint = NAME_HINTS.get(aliases.get(expr.value.id, expr.value.id))
            if hint in classes:
                owner_type = hint
        if owner_type is None:
            return None
        oc = classes.get(owner_type)
        if oc and expr.attr in oc.locks:
            return (f"{owner_type}.{expr.attr}", oc.locks[expr.attr])
        return None

    def _scan_method(
        self,
        ci: _ClassInfo,
        fn: ast.FunctionDef,
        classes: Dict[str, _ClassInfo],
    ) -> _MethodInfo:
        mi = _MethodInfo(ci, fn)
        aliases = self._local_aliases(fn)
        mi._aliases = aliases
        mi._classes = classes

        def walk(stmts, held: Tuple[Tuple[str, bool], ...]):
            for s in stmts:
                if isinstance(s, ast.With):
                    locks = []
                    for item in s.items:
                        lk = self._lock_of_with_item(
                            item.context_expr, ci, classes, aliases
                        )
                        if lk:
                            locks.append(lk)
                            mi.direct.add(lk)
                    inner = held + tuple(locks)
                    self._scan_exprs(s.items, mi, ci, classes, aliases, held)
                    walk(s.body, inner)
                    continue
                self._scan_stmt(s, mi, ci, classes, aliases, held, walk)

        walk(fn.body, ())
        return mi

    def _scan_stmt(self, s, mi, ci, classes, aliases, held, walk):
        # recurse into compound statements, keeping held set
        if isinstance(s, (ast.If,)):
            self._scan_exprs([s.test], mi, ci, classes, aliases, held)
            walk(s.body, held)
            walk(s.orelse, held)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            self._scan_exprs([s.iter], mi, ci, classes, aliases, held)
            walk(s.body, held)
            walk(s.orelse, held)
        elif isinstance(s, ast.While):
            self._scan_exprs([s.test], mi, ci, classes, aliases, held)
            walk(s.body, held)
            walk(s.orelse, held)
        elif isinstance(s, ast.Try):
            walk(s.body, held)
            for h in s.handlers:
                walk(h.body, held)
            walk(s.orelse, held)
            walk(s.finalbody, held)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested defs execute later; analyze with empty held set
            walk(s.body, ())
        else:
            self._scan_exprs([s], mi, ci, classes, aliases, held)

    def _scan_exprs(self, nodes, mi, ci, classes, aliases, held):
        for root in nodes:
            for n in ast.walk(root):
                if not isinstance(n, ast.Call):
                    continue
                d = dotted(n.func) or ""
                if d in DEVICE_CALLS:
                    mi.direct_device.append(n)
                    mi._device_held = getattr(mi, "_device_held", {})
                    mi._device_held[id(n)] = held
                    continue
                if isinstance(n.func, ast.Attribute):
                    recv_type = self._resolve_receiver_type(
                        n.func.value, ci, classes, aliases
                    )
                    mi.calls.append((held, recv_type, n.func.attr, n))
                elif isinstance(n.func, ast.Name):
                    # bare call: constructor of an analyzed class?
                    if n.func.id in classes:
                        mi.calls.append((held, n.func.id, "__init__", n))

    def _held_at(self, mi: _MethodInfo, call: ast.Call):
        return getattr(mi, "_device_held", {}).get(id(call), ())

    # ---- propagation + cycles --------------------------------------

    def _resolve(
        self,
        cls: Optional[str],
        name: str,
        methods: Dict[Tuple[str, str], _MethodInfo],
    ) -> Optional[Tuple[str, str]]:
        if cls is not None:
            return (cls, name) if (cls, name) in methods else None
        owners = [k for k in methods if k[1] == name]
        return owners[0] if len(owners) == 1 else None

    def _fixed_point(self, methods, classes) -> None:
        for mi in methods.values():
            mi.all_locks = set(mi.direct)
            mi.uses_device = bool(mi.direct_device)
        changed = True
        iters = 0
        while changed and iters < 50:
            changed = False
            iters += 1
            for mi in methods.values():
                for held, cls, name, _ in mi.calls:
                    target = self._resolve(cls, name, methods)
                    if target is None:
                        continue
                    ti = methods[target]
                    if not ti.all_locks.issubset(mi.all_locks):
                        mi.all_locks |= ti.all_locks
                        changed = True
                    if ti.uses_device and not mi.uses_device:
                        mi.uses_device = True
                        changed = True

    def _find_cycles(self, edges, edge_site) -> List[Finding]:
        out: List[Finding] = []
        seen_cycles: Set[Tuple[str, ...]] = set()
        for start in sorted(edges):
            path: List[str] = []
            on_path: Set[str] = set()

            def dfs(n: str) -> None:
                if n in on_path:
                    cyc = path[path.index(n):] + [n]
                    key = tuple(sorted(set(cyc)))
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        mod, line, scope = edge_site.get(
                            (cyc[0], cyc[1]), (None, 0, "<graph>")
                        )
                        out.append(
                            Finding(
                                self.id,
                                mod.path if mod else "<serve>",
                                line,
                                scope,
                                "lock-order cycle: " + " -> ".join(cyc),
                            )
                        )
                    return
                if n in path:
                    return
                path.append(n)
                on_path.add(n)
                for nxt in sorted(edges.get(n, ())):
                    dfs(nxt)
                path.pop()
                on_path.discard(n)

            dfs(start)
        return out
