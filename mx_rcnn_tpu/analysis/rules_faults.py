"""R6 fault-hook coverage — MX_RCNN_FAULTS must not drift.

The fault-injection surface (``utils/faults.py``) is only as good as
its wiring: a hook nobody calls is dead coverage (the fault matrix
believes a path is exercised when it is not), and a call to a
misspelled hook raises AttributeError only when that injector fires.
This rule cross-references, at lint time:

* every public hook in faults.py (a module-level function that consults
  ``_active()``) is called from at least one non-test module;
* every ``faults.<name>(...)`` call in the tree resolves to a real
  module-level function in faults.py;
* the ``_KNOWN_KINDS`` whitelist (which makes spec typos a hard parse
  error) exactly matches the set of kind strings the hooks actually
  consult — adding a kind to a hook without whitelisting it (or vice
  versa) fails the lint run.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from mx_rcnn_tpu.analysis.engine import Finding, Module, Rule, dotted


class FaultCoverage(Rule):
    id = "R6"
    name = "fault-hook coverage"

    FAULTS_SUFFIX = "utils/faults.py"

    def finalize(self, modules: Sequence[Module]) -> List[Finding]:
        faults_mod = next(
            (m for m in modules if m.path.endswith(self.FAULTS_SUFFIX)), None
        )
        if faults_mod is None:
            return []
        out: List[Finding] = []

        hooks: Dict[str, int] = {}
        funcs: Set[str] = set()
        collections: Dict[str, Set[str]] = {}
        known_kinds: Optional[Set[str]] = None
        known_kinds_line = 0

        for node in faults_mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                t = node.targets[0]
                if isinstance(t, ast.Name):
                    strings = {
                        n.value
                        for n in ast.walk(node.value)
                        if isinstance(n, ast.Constant)
                        and isinstance(n.value, str)
                    }
                    if t.id == "_KNOWN_KINDS":
                        # literal strings plus any referenced collection
                        # (e.g. ``| set(_SERVE_KINDS)``) gathered above
                        for n in ast.walk(node.value):
                            if (
                                isinstance(n, ast.Name)
                                and n.id in collections
                            ):
                                strings |= collections[n.id]
                        known_kinds = strings
                        known_kinds_line = node.lineno
                    elif strings:
                        collections[t.id] = strings
            if isinstance(node, ast.FunctionDef):
                funcs.add(node.name)
                if any(
                    isinstance(n, ast.Call) and dotted(n.func) == "_active"
                    for n in ast.walk(node)
                ):
                    hooks[node.name] = node.lineno

        # kinds each hook consults: literal comparisons + collections used
        consulted: Set[str] = set()
        for name in hooks:
            fn = next(
                n
                for n in faults_mod.tree.body
                if isinstance(n, ast.FunctionDef) and n.name == name
            )
            for n in ast.walk(fn):
                if isinstance(n, ast.Compare):
                    for side in [n.left] + list(n.comparators):
                        if isinstance(side, ast.Constant) and isinstance(
                            side.value, str
                        ):
                            d = dotted(n.left)
                            if (d or "").endswith("kind") or any(
                                (dotted(c) or "").endswith("kind")
                                for c in n.comparators
                            ):
                                consulted.add(side.value)
                if isinstance(n, ast.Name) and n.id in collections:
                    consulted.update(collections[n.id])

        if known_kinds is not None and consulted and known_kinds != consulted:
            missing = sorted(consulted - known_kinds)
            extra = sorted(known_kinds - consulted)
            parts = []
            if missing:
                parts.append(f"hooks consult unlisted kind(s) {missing}")
            if extra:
                parts.append(f"whitelisted kind(s) {extra} never consulted")
            out.append(
                Finding(
                    self.id,
                    faults_mod.path,
                    known_kinds_line,
                    "<module>",
                    "_KNOWN_KINDS drift: " + "; ".join(parts),
                )
            )

        # cross-module call census
        called: Set[str] = set()
        for m in modules:
            if m is faults_mod:
                continue
            for n in ast.walk(m.tree):
                if isinstance(n, ast.Call):
                    d = dotted(n.func) or ""
                    if d.startswith("faults."):
                        name = d.split(".", 1)[1]
                        called.add(name)
                        if name not in funcs:
                            out.append(
                                Finding(
                                    self.id,
                                    m.path,
                                    n.lineno,
                                    m.scope_of(n),
                                    f"call to nonexistent fault hook "
                                    f"`faults.{name}` — would raise "
                                    f"AttributeError when reached",
                                )
                            )

        for name, line in sorted(hooks.items()):
            if name not in called:
                out.append(
                    Finding(
                        self.id,
                        faults_mod.path,
                        line,
                        name,
                        f"fault hook `{name}` is never called from any "
                        f"non-test module — its injectors can never fire",
                    )
                )
        return out
