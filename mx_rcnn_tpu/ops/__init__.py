from mx_rcnn_tpu.ops.anchors import generate_anchors, shifted_anchors
from mx_rcnn_tpu.ops.boxes import (
    bbox_overlaps,
    bbox_transform,
    bbox_pred,
    clip_boxes,
)
from mx_rcnn_tpu.ops.nms import nms, batched_class_nms
