"""ROI feature extraction: ROIAlign (bilinear) and exact ROIPool compat.

Reference: MXNet's C++/CUDA ``ROIPooling`` op (SURVEY N6) — max-pool each
roi into a fixed grid with quantized bin edges; the single external custom
kernel the reference graph depends on.  Two TPU-native implementations
behind one signature:

- :func:`roi_align` — bilinear sampling on continuous coordinates
  (align_corners=False convention, `sample_ratio`² points per bin,
  averaged).  Differentiable by construction (pure gather + arithmetic;
  XLA derives the scatter-add backward automatically — no hand-written
  ``custom_vjp`` needed for correctness; the Pallas kernel in
  ``ops/pallas/`` is the perf path).
- :func:`roi_pool` — exact MXNet ROIPooling semantics: rois quantized by
  ``round(x * scale)``, bin edges floor/ceil, max over each bin, computed
  as two masked-max contractions (no data-dependent shapes).

Both are chunked with ``lax.map`` over rois to bound the gather
intermediates in HBM (R×grid×W×C blow-up otherwise).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _feat_limits(feat_hw, valid_hw, spatial_scale):
    """Per-axis sample-clamp limits: the canvas extent, or — when the true
    pre-padding image size ``valid_hw`` is given — the number of feature
    rows/cols that carry image content, ``ceil(h·scale)``.  Rows past that
    are functions of the zero padding only, and (crucially) the clamp at
    ``size − 1`` then lands at the same coordinate for every canvas the
    image fits in, so the gather is bit-identical across shape buckets
    (the serving padding-invariance guarantee; see SERVING.md)."""
    if valid_hw is None:
        return [(float(s), s) for s in feat_hw]
    lims = []
    for s, v in zip(feat_hw, (valid_hw[0], valid_hw[1])):
        lim = jnp.minimum(jnp.ceil(v * spatial_scale), float(s))
        lims.append((lim, lim.astype(jnp.int32)))
    return lims


def _bilinear_one_roi(feat, roi, pooled, sample_ratio, spatial_scale,
                      valid_hw=None):
    """(H, W, C) × (4,) roi → (ph, pw, C) via average of bilinear samples."""
    hf, wf = feat.shape[0], feat.shape[1]
    ph, pw = pooled
    x1, y1, x2, y2 = roi[0], roi[1], roi[2], roi[3]
    x1, y1, x2, y2 = (v * spatial_scale for v in (x1, y1, x2, y2))
    roi_w = jnp.maximum(x2 - x1, 1.0)
    roi_h = jnp.maximum(y2 - y1, 1.0)
    bin_w = roi_w / pw
    bin_h = roi_h / ph
    s = sample_ratio

    # sample grid: for bin p, samples at y1 + (p + (j+0.5)/s) * bin_h
    gy = y1 + (jnp.arange(ph * s) + 0.5) / s * bin_h      # (ph*s,)
    gx = x1 + (jnp.arange(pw * s) + 0.5) / s * bin_w      # (pw*s,)

    def axis_weights(g, lim_f, lim_i):
        g = jnp.clip(g, 0.0, lim_f - 1.0)
        lo = jnp.floor(g).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, lim_i - 1)
        whi = g - lo
        return lo, hi, 1.0 - whi, whi

    (lh_f, lh_i), (lw_f, lw_i) = _feat_limits((hf, wf), valid_hw, spatial_scale)
    ylo, yhi, wy0, wy1 = axis_weights(gy, lh_f, lh_i)
    xlo, xhi, wx0, wx1 = axis_weights(gx, lw_f, lw_i)

    # two-stage separable gather: rows then columns
    rows0 = jnp.take(feat, ylo, axis=0)       # (ph*s, W, C)
    rows1 = jnp.take(feat, yhi, axis=0)
    rows = rows0 * wy0[:, None, None] + rows1 * wy1[:, None, None]
    cols0 = jnp.take(rows, xlo, axis=1)       # (ph*s, pw*s, C)
    cols1 = jnp.take(rows, xhi, axis=1)
    samples = cols0 * wx0[None, :, None] + cols1 * wx1[None, :, None]

    # average the s×s samples per bin
    c = feat.shape[2]
    samples = samples.reshape(ph, s, pw, s, c)
    return samples.mean(axis=(1, 3))


def roi_align(
    feat: jnp.ndarray,
    rois: jnp.ndarray,
    pooled: tuple = (14, 14),
    spatial_scale: float = 1.0 / 16.0,
    sample_ratio: int = 2,
    chunk: int = 32,
    valid_hw=None,
) -> jnp.ndarray:
    """(H, W, C) feature + (R, 4) image-coord rois → (R, ph, pw, C).

    ``valid_hw`` (2,) = the true pre-padding image (h, w): samples are
    clamped to the valid feature extent instead of the canvas extent, so
    the output is independent of which shape bucket padded the image."""
    r = rois.shape[0]
    pad = (-r) % chunk
    rois_p = jnp.concatenate([rois, jnp.zeros((pad, 4), rois.dtype)], axis=0)
    chunks = rois_p.reshape(-1, chunk, 4)

    def run_chunk(rs):
        return jax.vmap(
            lambda roi: _bilinear_one_roi(
                feat, roi, pooled, sample_ratio, spatial_scale, valid_hw
            )
        )(rs)

    out = jax.lax.map(run_chunk, chunks)
    return out.reshape(-1, pooled[0], pooled[1], feat.shape[2])[:r]


def _maxpool_one_roi(feat, roi, pooled, spatial_scale, valid_hw=None):
    """Exact MXNet ROIPooling for one roi via masked-max contractions."""
    hf, wf = feat.shape[0], feat.shape[1]
    ph, pw = pooled
    # quantized roi in feature cells (+1 width convention)
    x1 = jnp.round(roi[0] * spatial_scale)
    y1 = jnp.round(roi[1] * spatial_scale)
    x2 = jnp.round(roi[2] * spatial_scale)
    y2 = jnp.round(roi[3] * spatial_scale)
    roi_w = jnp.maximum(x2 - x1 + 1.0, 1.0)
    roi_h = jnp.maximum(y2 - y1 + 1.0, 1.0)
    bin_w = roi_w / pw
    bin_h = roi_h / ph

    def bin_mask(start, bin_sz, nbins, size, lim):
        # mask[b, i]: cell i belongs to bin b (floor/ceil edges, clipped
        # to the valid feature extent so padded cells never win the max)
        b = jnp.arange(nbins, dtype=jnp.float32)
        lo = jnp.clip(jnp.floor(start + b * bin_sz), 0, lim)           # (nb,)
        hi = jnp.clip(jnp.ceil(start + (b + 1.0) * bin_sz), 0, lim)
        i = jnp.arange(size, dtype=jnp.float32)
        return (i[None, :] >= lo[:, None]) & (i[None, :] < hi[:, None])

    (lh, _), (lw, _) = _feat_limits((hf, wf), valid_hw, spatial_scale)
    mh = bin_mask(y1, bin_h, ph, hf, lh)   # (ph, H)
    mw = bin_mask(x1, bin_w, pw, wf, lw)   # (pw, W)

    neg = jnp.finfo(feat.dtype).min
    # max over h per bin row, then over w per bin col
    tmp = jnp.where(mh[:, :, None, None], feat[None, :, :, :], neg).max(axis=1)  # (ph, W, C)
    out = jnp.where(mw[None, :, :, None], tmp[:, None, :, :], neg).max(axis=2)   # (ph, pw, C)
    # empty bins (hi<=lo) produce neg; MXNet emits 0 there
    empty = (~mh.any(axis=1))[:, None] | (~mw.any(axis=1))[None, :]
    return jnp.where(empty[:, :, None], 0.0, out)


def roi_pool(
    feat: jnp.ndarray,
    rois: jnp.ndarray,
    pooled: tuple = (7, 7),
    spatial_scale: float = 1.0 / 16.0,
    chunk: int = 4,
    valid_hw=None,
) -> jnp.ndarray:
    """(H, W, C) feature + (R, 4) rois → (R, ph, pw, C), max-pooled.

    ``chunk`` bounds the live (chunk, ph, H, W, C) masked-max
    intermediate; at the flagship VGG shape (38×64×512 bf16, ph=7) each
    chunked roi holds ~17 MB, so chunk=4 keeps the scan body ~70 MB.
    The body is rematerialized (jax.checkpoint): reverse-mode through
    lax.map otherwise SAVES each iteration's masked intermediate as a
    scan residual — the full (chunks, chunk, ph, H, W, C) tensor,
    16.6 GB at flagship across a batch of 8 (observed HBM OOM).
    Callers must also not vmap over the batch dim (vmap batches the
    scan body the same way); extract_roi_features_batched runs a
    sequential batch loop for roi_pool."""
    r = rois.shape[0]
    pad = (-r) % chunk
    rois_p = jnp.concatenate([rois, jnp.zeros((pad, 4), rois.dtype)], axis=0)
    chunks = rois_p.reshape(-1, chunk, 4)

    @jax.checkpoint
    def run_chunk(rs):
        return jax.vmap(
            lambda roi: _maxpool_one_roi(feat, roi, pooled, spatial_scale,
                                         valid_hw)
        )(rs)

    out = jax.lax.map(run_chunk, chunks)
    return out.reshape(-1, pooled[0], pooled[1], feat.shape[2])[:r]


def extract_roi_features(
    feat: jnp.ndarray,
    rois: jnp.ndarray,
    mode: str,
    pooled: tuple,
    spatial_scale: float,
    sample_ratio: int = 2,
    valid_hw=None,
) -> jnp.ndarray:
    """Dispatch on config ROI_MODE ('roi_align' | 'roi_pool')."""
    if mode == "roi_align":
        return roi_align(feat, rois, pooled, spatial_scale, sample_ratio,
                         valid_hw=valid_hw)
    if mode == "roi_pool":
        return roi_pool(feat, rois, pooled, spatial_scale, valid_hw=valid_hw)
    raise ValueError(f"unknown ROI_MODE {mode!r}")


def extract_roi_features_batched(
    feat: jnp.ndarray,
    rois: jnp.ndarray,
    mode: str,
    pooled: tuple,
    spatial_scale: float,
    sample_ratio: int = 2,
    fwd_only: bool = False,
    valid_hw=None,
) -> jnp.ndarray:
    """(B, H, W, C) × (B, R, 4) → (B, R, ph, pw, C).

    On TPU backends the roi_align path uses the Pallas MXU kernel
    (``ops/pallas/roi_align.py``); elsewhere (and for roi_pool) the
    chunked-gather jnp implementations under vmap.

    ``fwd_only``: callers that never differentiate this op (eval /
    test_forward) should set it.  For over-VMEM maps the streaming
    kernel only beats the chunked gather when the backward pass is in
    play (real-TPU P2-shape timings, scripts/probe_stream_kernel.py:
    fwd 160 vs 121 ms, fwd+bwd 108 vs 326 ms), so forward-only graphs
    take the gather path there.

    ``valid_hw`` (B, 2) = true pre-padding image sizes (``im_info[:, :2]``):
    sample coordinates clamp to the valid feature extent instead of the
    canvas, making the pooled features independent of the shape bucket
    (the serving padding-invariance contract).  The Pallas kernels clamp
    to the canvas, so a non-None ``valid_hw`` takes the jnp gather path
    on every backend — inference-only callers pay a modest TPU perf cost
    for exactness under bucketing.
    """
    from mx_rcnn_tpu.utils.platform import use_pallas

    # Two Pallas kernels: the resident one keeps an (H, W, cblk) feature
    # block in VMEM across the roi sweep; maps over the budget (FPN P2 at
    # flagship resolution is 152×256) take the STREAMING kernel, which
    # row-blocks the feature through VMEM and accumulates the roi-block
    # outputs in scratch (ops/pallas/roi_align_stream.py)
    from mx_rcnn_tpu.ops.pallas.roi_align import fits_vmem

    if mode == "roi_align" and valid_hw is None and use_pallas():
        if fits_vmem(
            feat.shape[1], feat.shape[2], feat.shape[3],
            pooled_max=max(pooled),
        ):
            from mx_rcnn_tpu.ops.pallas.roi_align import roi_align_pallas

            return roi_align_pallas(
                feat, rois, pooled, spatial_scale, sample_ratio
            )
        if not fwd_only:
            from mx_rcnn_tpu.ops.pallas.roi_align_stream import (
                roi_align_stream,
            )

            return roi_align_stream(
                feat, rois, pooled, spatial_scale, sample_ratio
            )
    if mode == "roi_pool" and not fwd_only:
        # SEQUENTIAL over the batch: differentiating roi_pool's chunked
        # masked-max under vmap saves every chunk's intermediate as a
        # batched scan residual — one (chunks, B, chunk, ph, H, W, C)
        # allocation, 16.6 GB at the flagship VGG shape (observed HBM
        # OOM).  lax.map keeps one image's chunk live at a time.
        # Forward-only graphs (eval) have no residuals, so they fall
        # through to the batch-parallel vmap below: only one chunk's
        # live body exists at a time (~0.5 GB at flagship).
        if valid_hw is None:
            return jax.lax.map(
                lambda fr: extract_roi_features(
                    fr[0], fr[1], mode, pooled, spatial_scale, sample_ratio
                ),
                (feat, rois),
            )
        return jax.lax.map(
            lambda fr: extract_roi_features(
                fr[0], fr[1], mode, pooled, spatial_scale, sample_ratio,
                valid_hw=fr[2],
            ),
            (feat, rois, valid_hw),
        )
    if valid_hw is None:
        return jax.vmap(
            lambda f, r: extract_roi_features(
                f, r, mode, pooled, spatial_scale, sample_ratio
            )
        )(feat, rois)
    return jax.vmap(
        lambda f, r, v: extract_roi_features(
            f, r, mode, pooled, spatial_scale, sample_ratio, valid_hw=v
        )
    )(feat, rois, valid_hw)
