"""Non-maximum suppression, TPU-style: fixed shapes, validity masks.

Reference: ``rcnn/cython/nms_kernel.cu`` (bitmask GPU NMS),
``rcnn/cython/cpu_nms.pyx`` and ``rcnn/processing/nms.py`` (dispatch +
pure-python fallback).  TPU/XLA has no dynamic output shapes, so instead of
a variable-length keep list every routine here returns values padded to a
static size with an explicit validity mask — callers thread the mask, never
the length.

Three implementations, one contract:

- :func:`nms_mask` — in-graph greedy NMS via ``lax.fori_loop`` over
  score-sorted boxes.  O(N) memory (IoU rows computed on the fly), exact
  greedy semantics.  This is the interim/debug path; the Pallas blocked
  kernel (``mx_rcnn_tpu.ops.pallas.nms``) is the fast path behind the same
  contract.
- :func:`nms` — mask + top-k selection → fixed ``max_out`` boxes.
- :func:`nms_numpy` — host-side greedy NMS for the per-class filtering in
  ``pred_eval`` (reference: ``rcnn/processing/nms.py :: nms``).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from mx_rcnn_tpu.ops.boxes import bbox_overlaps
from mx_rcnn_tpu.utils.platform import use_pallas as _use_pallas

_NEG_INF = -1e10


def _iou_row(box: jnp.ndarray, boxes: jnp.ndarray) -> jnp.ndarray:
    """IoU of one (4,) box against (N, 4) boxes → (N,)."""
    return bbox_overlaps(box[None, :], boxes)[0]


def nms_mask(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    thresh: float,
    valid: jnp.ndarray | None = None,
    sorted_input: bool = False,
    max_keep: int = 0,
) -> jnp.ndarray:
    """Greedy NMS → bool keep mask aligned with the *input* order.

    Exactly the sequential greedy algorithm of the reference CPU/GPU
    kernels: walk boxes in descending score; a box survives iff no
    higher-scoring *surviving* box overlaps it above ``thresh``.
    Invalid (padding) entries never survive and never suppress.

    ``sorted_input``: promise that ``boxes``/``valid`` are already in
    descending-score order (e.g. straight out of ``lax.top_k``) — skips
    an argsort + scatter round-trip.

    ``max_keep``: with ``sorted_input``, stop the sweep once that many
    survivors exist — exact iff the caller keeps only the top
    ``max_keep`` survivors by score (``nms`` does).
    """
    n = boxes.shape[0]
    if valid is None:
        valid = jnp.ones((n,), dtype=bool)
    if _use_pallas():
        from mx_rcnn_tpu.ops.pallas.nms import (
            nms_mask_pallas,
            nms_mask_sorted_pallas,
        )

        if sorted_input:
            return nms_mask_sorted_pallas(
                boxes, valid, thresh, max_keep=max_keep
            )
        return nms_mask_pallas(boxes, scores, thresh, valid)
    if sorted_input:
        b, v, order = boxes.astype(jnp.float32), valid, None
    else:
        scores = jnp.where(valid, scores, _NEG_INF)
        order = jnp.argsort(-scores)
        b = boxes[order].astype(jnp.float32)
        v = valid[order]

    def body(i, alive):
        row = _iou_row(b[i], b)
        suppress = (row > thresh) & (jnp.arange(n) > i) & alive[i]
        return alive & ~suppress

    alive = jax.lax.fori_loop(0, n, body, v)
    if order is None:
        return alive
    # scatter back to input order
    keep = jnp.zeros((n,), dtype=bool).at[order].set(alive)
    return keep


def nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    thresh: float,
    max_out: int,
    valid: jnp.ndarray | None = None,
    sorted_input: bool = False,
    with_idx: bool = False,
):
    """NMS + select top ``max_out`` survivors by score (fixed shape).

    Returns ``(boxes (max_out, 4), scores (max_out,), valid (max_out,))``;
    padding rows are zero boxes with score ``-1e10`` and ``valid=False``.
    This is the in-graph replacement for the keep-list interface of
    ``gpu_nms`` — the pad-to-``post_nms_top_n`` discipline the reference
    already applied in ``rcnn/symbol/proposal.py`` generalized.

    ``with_idx`` appends the top-k source indices ``idx (max_out,)`` —
    each survivor's position in the INPUT order, which downstream gathers
    (device mask selection) use to index back into per-roi head outputs.
    ``idx`` is only meaningful where ``valid``; when ``N < max_out`` the
    scores are padded before ``top_k``, so invalid slots may carry
    indices ≥ N — callers must clamp or mask before gathering.
    """
    # with a sorted input the kernel may stop once max_out survivors
    # exist — the top_k below only ever reads that prefix
    keep = nms_mask(
        boxes, scores, thresh, valid, sorted_input=sorted_input,
        max_keep=max_out if sorted_input else 0,
    )
    masked = jnp.where(keep, scores, _NEG_INF)
    if masked.shape[0] < max_out:  # static: pad so top_k(k) is well-formed
        pad = max_out - masked.shape[0]
        masked = jnp.concatenate([masked, jnp.full((pad,), _NEG_INF)])
        boxes = jnp.concatenate([boxes, jnp.zeros((pad, 4), boxes.dtype)])
    top_scores, idx = jax.lax.top_k(masked, max_out)
    out_valid = top_scores > _NEG_INF / 2
    out_boxes = jnp.where(out_valid[:, None], boxes[idx], 0.0)
    if with_idx:
        return out_boxes, top_scores, out_valid, idx
    return out_boxes, top_scores, out_valid


def batched_class_nms(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    thresh: float,
    max_out: int,
    valid: jnp.ndarray | None = None,
    with_idx: bool = False,
):
    """Per-class NMS, vmapped over a leading class axis.

    ``boxes`` (C, N, 4), ``scores`` (C, N) → (C, max_out, ·) padded.
    Replaces the per-class python loop in
    ``rcnn/core/tester.py :: pred_eval`` with one in-graph batched op.
    ``with_idx`` threads the per-class survivor source indices through
    (see :func:`nms`) for device-side mask gathering.
    """
    if valid is None:
        valid = jnp.ones(scores.shape, dtype=bool)
    return jax.vmap(
        lambda b, s, v: nms(b, s, thresh, max_out, v, with_idx=with_idx)
    )(boxes, scores, valid)


def nms_numpy(dets: np.ndarray, thresh: float) -> list:
    """Host greedy NMS on (N, 5) [x1, y1, x2, y2, score] → kept indices.

    Reference: ``rcnn/processing/nms.py :: nms`` (the pure-python
    fallback); used by host-side eval tooling and as the golden oracle in
    kernel tests.
    """
    if dets.size == 0:
        return []
    x1, y1, x2, y2, scores = dets[:, 0], dets[:, 1], dets[:, 2], dets[:, 3], dets[:, 4]
    areas = (x2 - x1 + 1) * (y2 - y1 + 1)
    # stable sort pins the equal-score visit order (descending index
    # after the reversal) so the native C path (hostops.c) can match it
    # exactly; numpy's default introsort leaves tie order unspecified
    order = scores.argsort(kind="stable")[::-1]
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(int(i))
        xx1 = np.maximum(x1[i], x1[order[1:]])
        yy1 = np.maximum(y1[i], y1[order[1:]])
        xx2 = np.minimum(x2[i], x2[order[1:]])
        yy2 = np.minimum(y2[i], y2[order[1:]])
        w = np.maximum(0.0, xx2 - xx1 + 1)
        h = np.maximum(0.0, yy2 - yy1 + 1)
        inter = w * h
        ovr = inter / (areas[i] + areas[order[1:]] - inter)
        inds = np.where(ovr <= thresh)[0]
        order = order[inds + 1]
    return keep
