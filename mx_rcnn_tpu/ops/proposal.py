"""Proposal generation: RPN outputs → fixed-size roi set, fully in-graph.

Reference: ``rcnn/symbol/proposal.py :: ProposalOperator.forward`` — a
host-side CustomOp that copies RPN outputs to CPU every step, decodes with
numpy, calls the CUDA NMS, and copies rois back (boundary B1 in SURVEY
§4.1).  Here the whole thing is jnp inside the train/test jit: decode →
clip → min-size mask → top-k → masked NMS → pad to POST_NMS_TOP_N.  The
reference already padded its output to a fixed size; we extend that
discipline with an explicit validity mask instead of its zero-row hack.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import nms

_NEG_INF = -1e10


class Proposals(NamedTuple):
    rois: jnp.ndarray    # (POST_NMS, 4) image-coordinate boxes, padded
    scores: jnp.ndarray  # (POST_NMS,)
    valid: jnp.ndarray   # (POST_NMS,) bool


def anchor_grid_mask(feat_shapes, strides, num_anchors, im_info) -> jnp.ndarray:
    """One image: which anchor slots sit on image content — (N,) bool over
    the concatenated per-level anchor table, row-major (y, x, anchor) per
    level, matching ``shifted_anchors`` + the RPN head emission order.

    An anchor whose grid cell lies in the bucket padding scores zero-image
    features, so its fg score depends on the CANVAS rather than the image:
    two buckets padding the same image would rank different pre-NMS top-k
    sets and detections would drift with the bucket (the serving
    padding-invariance bug).  Cell (y, x) is kept iff its top-left corner
    ``(stride·y, stride·x)`` is inside the unpadded image — a canvas-
    independent criterion, and every kept cell exists (with bit-identical
    features) in every bucket the image fits.
    """
    h, w = im_info[0], im_info[1]
    parts = []
    for (fh, fw), stride in zip(feat_shapes, strides):
        ys = jnp.arange(fh, dtype=jnp.float32) * stride < h
        xs = jnp.arange(fw, dtype=jnp.float32) * stride < w
        m = (ys[:, None] & xs[None, :]).reshape(-1)
        parts.append(jnp.repeat(m, num_anchors))
    return parts[0] if len(parts) == 1 else jnp.concatenate(parts)


def propose(
    fg_scores: jnp.ndarray,
    deltas: jnp.ndarray,
    anchors: jnp.ndarray,
    im_info: jnp.ndarray,
    pre_nms_top_n: int,
    post_nms_top_n: int,
    nms_thresh: float,
    min_size: float,
) -> Proposals:
    """One image: (N,) anchor fg scores + (N, 4) deltas → proposals.

    ``im_info`` = (h, w, scale) of the unpadded image; ``min_size`` is
    scaled by ``im_info[2]`` exactly as the reference does.
    """
    h, w, scale = im_info[0], im_info[1], im_info[2]
    boxes = bbox_pred(anchors, deltas)
    boxes = clip_boxes(boxes, (h, w))

    ms = min_size * scale
    ws = boxes[:, 2] - boxes[:, 0] + 1.0
    hs = boxes[:, 3] - boxes[:, 1] + 1.0
    keep = (ws >= ms) & (hs >= ms)

    scores = jnp.where(keep, fg_scores, _NEG_INF)
    k = min(pre_nms_top_n, scores.shape[0])
    top_scores, idx = jax.lax.top_k(scores, k)
    top_boxes = boxes[idx]
    top_valid = top_scores > _NEG_INF / 2

    # top_k output is descending-score: the NMS can skip its own sort
    out_boxes, out_scores, out_valid = nms(
        top_boxes, top_scores, nms_thresh, post_nms_top_n, top_valid,
        sorted_input=True,
    )
    return Proposals(out_boxes, out_scores, out_valid)
