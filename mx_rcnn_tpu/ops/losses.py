"""Loss primitives.

Reference: MXNet C++ ops ``smooth_l1`` (with ``scalar`` = sigma) and
``SoftmaxOutput`` (with ``ignore_label=-1``, ``use_ignore``,
``normalization='valid'``) used by ``rcnn/symbol/symbol_vgg.py`` /
``symbol_resnet.py`` (SURVEY N7).  Rewritten as plain jnp — XLA fuses these
into the surrounding graph, so there is nothing to hand-optimize.

Normalization semantics preserved exactly:
- RPN cls/bbox losses divide by ``RPN_BATCH_SIZE`` (256),
- RCNN cls loss divides by valid rois, bbox loss by ``BATCH_ROIS`` (128),
carried by the caller via the ``norm`` argument so padded/ignored entries
keep the reference's effective learning-rate semantics.
"""

from __future__ import annotations

import jax.numpy as jnp


def smooth_l1(pred: jnp.ndarray, target: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Elementwise smooth-L1 (Huber) with transition at 1/sigma².

    Matches ``mx.symbol.smooth_l1(scalar=sigma)``:
    ``0.5*(sigma*x)^2`` if ``|x| < 1/sigma²`` else ``|x| - 0.5/sigma²``.
    """
    sigma2 = sigma * sigma
    diff = pred - target
    adiff = jnp.abs(diff)
    return jnp.where(
        adiff < 1.0 / sigma2,
        0.5 * sigma2 * diff * diff,
        adiff - 0.5 / sigma2,
    )


def weighted_smooth_l1(
    pred: jnp.ndarray,
    target: jnp.ndarray,
    weight: jnp.ndarray,
    sigma: float,
    norm: jnp.ndarray | float,
) -> jnp.ndarray:
    """sum(weight * smooth_l1) / norm — the ``smooth_l1 × bbox_weight``
    with ``grad_scale 1/N`` pattern of the reference train graphs."""
    return jnp.sum(weight * smooth_l1(pred, target, sigma)) / norm


def softmax_cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    ignore_label: int = -1,
    norm: jnp.ndarray | float | None = None,
) -> jnp.ndarray:
    """Mean softmax CE over entries whose label != ignore_label.

    Matches ``SoftmaxOutput(use_ignore=True, ignore_label=-1,
    normalization='valid')``: ignored entries contribute zero loss and zero
    gradient.  ``norm`` overrides the divisor (e.g. a fixed 256 for RPN).
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_label
    safe_labels = jnp.where(valid, labels, 0).astype(jnp.int32)
    logz = jnp.log(jnp.sum(jnp.exp(logits - logits.max(-1, keepdims=True)), -1))
    ll = jnp.take_along_axis(
        logits - logits.max(-1, keepdims=True), safe_labels[..., None], axis=-1
    )[..., 0]
    nll = (logz - ll) * valid
    if norm is None:
        norm = jnp.maximum(valid.sum(), 1)
    return jnp.sum(nll) / norm


def accuracy(
    logits: jnp.ndarray, labels: jnp.ndarray, ignore_label: int = -1
) -> jnp.ndarray:
    """Classification accuracy over non-ignored entries (metric, not loss).

    Reference: ``rcnn/core/metric.py :: RPNAccMetric / RCNNAccMetric``.
    """
    valid = labels != ignore_label
    pred = jnp.argmax(logits, axis=-1)
    correct = (pred == labels) & valid
    return correct.sum() / jnp.maximum(valid.sum(), 1)
