"""Loss primitives.

Reference: MXNet C++ ops ``smooth_l1`` (with ``scalar`` = sigma) and
``SoftmaxOutput`` (with ``ignore_label=-1``, ``use_ignore``,
``normalization='valid'``) used by ``rcnn/symbol/symbol_vgg.py`` /
``symbol_resnet.py`` (SURVEY N7).  Rewritten as plain jnp — XLA fuses these
into the surrounding graph, so there is nothing to hand-optimize.

Normalization semantics preserved exactly:
- RPN cls/bbox losses divide by ``RPN_BATCH_SIZE`` (256),
- RCNN cls loss divides by valid rois, bbox loss by ``BATCH_ROIS`` (128),
carried by the caller via the ``norm`` argument so padded/ignored entries
keep the reference's effective learning-rate semantics.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def smooth_l1(pred: jnp.ndarray, target: jnp.ndarray, sigma: float) -> jnp.ndarray:
    """Elementwise smooth-L1 (Huber) with transition at 1/sigma².

    Matches ``mx.symbol.smooth_l1(scalar=sigma)``:
    ``0.5*(sigma*x)^2`` if ``|x| < 1/sigma²`` else ``|x| - 0.5/sigma²``.
    """
    sigma2 = sigma * sigma
    diff = pred - target
    adiff = jnp.abs(diff)
    return jnp.where(
        adiff < 1.0 / sigma2,
        0.5 * sigma2 * diff * diff,
        adiff - 0.5 / sigma2,
    )


def weighted_smooth_l1(
    pred: jnp.ndarray,
    target: jnp.ndarray,
    weight: jnp.ndarray,
    sigma: float,
    norm: jnp.ndarray | float,
) -> jnp.ndarray:
    """sum(weight * smooth_l1) / norm — the ``smooth_l1 × bbox_weight``
    with ``grad_scale 1/N`` pattern of the reference train graphs."""
    return jnp.sum(weight * smooth_l1(pred, target, sigma)) / norm


def one_hot_select(x: jnp.ndarray, idx: jnp.ndarray) -> jnp.ndarray:
    """``x[..., idx]`` over the minor axis WITHOUT a gather.

    take_along_axis lowers to a serialized TPU gather (1.45 ms/step on
    the flagship trace for the RPN CE's 175k rows, plus a scatter in its
    backward); the broadcast-compare multiply-sum stays a fused VPU
    pass.  Exact: one match per row, the rest contribute zero.  ``idx``
    broadcasts against ``x``'s leading dims."""
    classes = jax.lax.broadcasted_iota(jnp.int32, x.shape, x.ndim - 1)
    return jnp.sum(jnp.where(classes == idx[..., None], x, 0.0), -1)


def softmax_cross_entropy(
    logits: jnp.ndarray,
    labels: jnp.ndarray,
    ignore_label: int = -1,
    norm: jnp.ndarray | float | None = None,
) -> jnp.ndarray:
    """Mean softmax CE over entries whose label != ignore_label.

    Matches ``SoftmaxOutput(use_ignore=True, ignore_label=-1,
    normalization='valid')``: ignored entries contribute zero loss and zero
    gradient.  ``norm`` overrides the divisor (e.g. a fixed 256 for RPN).
    """
    logits = logits.astype(jnp.float32)
    valid = labels != ignore_label
    safe_labels = jnp.where(valid, labels, 0).astype(jnp.int32)
    shifted = logits - logits.max(-1, keepdims=True)
    logz = jnp.log(jnp.sum(jnp.exp(shifted), -1))
    ll = one_hot_select(shifted, safe_labels)
    nll = (logz - ll) * valid
    if norm is None:
        norm = jnp.maximum(valid.sum(), 1)
    return jnp.sum(nll) / norm


def accuracy(
    logits: jnp.ndarray, labels: jnp.ndarray, ignore_label: int = -1
) -> jnp.ndarray:
    """Classification accuracy over non-ignored entries (metric, not loss).

    Reference: ``rcnn/core/metric.py :: RPNAccMetric / RCNNAccMetric``.
    """
    valid = labels != ignore_label
    pred = jnp.argmax(logits, axis=-1)
    correct = (pred == labels) & valid
    return correct.sum() / jnp.maximum(valid.sum(), 1)
