"""Anchor generation.

Reference: ``rcnn/processing/generate_anchor.py :: generate_anchors`` (the
classic py-faster-rcnn enumeration via ``_whctrs/_mkanchors/_ratio_enum/
_scale_enum``).  Behaviorally identical output; implemented as one
vectorized numpy routine because anchors are a compile-time constant on
TPU — they're baked into the jitted graph, never computed on device.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np


def generate_anchors(
    base_size: int = 16,
    ratios: Sequence[float] = (0.5, 1.0, 2.0),
    scales: Sequence[int] = (8, 16, 32),
) -> np.ndarray:
    """Return (A, 4) anchor windows [x1, y1, x2, y2] around (0, 0).

    Matches the classic algorithm: start from the [0, 0, 15, 15] base box,
    enumerate aspect ratios preserving (rounded) area, then scale each.
    Uses the legacy +1 width/height convention throughout.
    """
    ratios = np.asarray(ratios, dtype=np.float64)
    scales = np.asarray(scales, dtype=np.float64)

    w = h = float(base_size)
    x_ctr = 0.5 * (w - 1.0)
    y_ctr = 0.5 * (h - 1.0)

    # ratio enumeration: round(sqrt(area / ratio)) widths
    size = w * h
    size_ratios = size / ratios
    ws = np.round(np.sqrt(size_ratios))            # (R,)
    hs = np.round(ws * ratios)                     # (R,)

    # scale enumeration applied to every ratio anchor
    ws = (ws[:, None] * scales[None, :]).reshape(-1)   # (R*S,)
    hs = (hs[:, None] * scales[None, :]).reshape(-1)

    anchors = np.stack(
        [
            x_ctr - 0.5 * (ws - 1.0),
            y_ctr - 0.5 * (hs - 1.0),
            x_ctr + 0.5 * (ws - 1.0),
            y_ctr + 0.5 * (hs - 1.0),
        ],
        axis=1,
    )
    return anchors.astype(np.float32)


def shifted_anchors(
    feat_height: int,
    feat_width: int,
    feat_stride: int = 16,
    base_anchors: np.ndarray | None = None,
    ratios: Sequence[float] = (0.5, 1.0, 2.0),
    scales: Sequence[int] = (8, 16, 32),
) -> np.ndarray:
    """All anchors on an H×W feature grid: (H*W*A, 4), row-major over
    (y, x, anchor) — the per-pixel layout the RPN head emits.

    Reference: the shift-enumeration prologue of
    ``rcnn/symbol/proposal.py :: ProposalOperator.forward`` and
    ``rcnn/io/rpn.py :: assign_anchor``.
    """
    if base_anchors is None:
        base_anchors = generate_anchors(feat_stride, ratios, scales)
    shift_x = np.arange(feat_width, dtype=np.float32) * feat_stride
    shift_y = np.arange(feat_height, dtype=np.float32) * feat_stride
    sx, sy = np.meshgrid(shift_x, shift_y)  # (H, W)
    shifts = np.stack([sx, sy, sx, sy], axis=-1).reshape(-1, 1, 4)  # (H*W,1,4)
    all_anchors = shifts + base_anchors[None, :, :]                 # (H*W,A,4)
    return all_anchors.reshape(-1, 4).astype(np.float32)
