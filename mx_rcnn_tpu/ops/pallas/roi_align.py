"""Pallas TPU ROIAlign — bilinear pooling as one-hot interpolation matmuls.

Reference: MXNet's ``roi_pooling.cu`` / torchvision ``roi_align.cu``
(SURVEY N6) — CUDA kernels that gather 4 neighbours per sample point and
scatter-add bilinear weights in the backward pass.  Gather/scatter is the
wrong shape for a TPU; this kernel reformulates ROIAlign as dense matrix
algebra that rides the MXU:

- Bilinear sampling is **separable**: the weight of cell (h, w) for sample
  point (gy, gx) factors into wy(h)·wx(w), and the s×s-sample average per
  output bin factors into (mean of row weights)·(mean of col weights).
- So per roi, pooling is exactly ``out = My @ feat @ Mxᵀ`` with
  My (PH, H) and Mx (PW, W) tiny interpolation matrices built on-chip
  from iota comparisons — two MXU contractions, zero gathers.
- Backward is the transpose pair ``dfeat += Myᵀ @ g @ Mx`` — again
  matmuls, accumulated across rois in a VMEM-resident block; no
  scatter-add (the CUDA kernel's atomics have no TPU analog).

Grid: (B, C-blocks, R) with roi boxes scalar-prefetched to SMEM; the
feature block stays resident in VMEM across the entire roi sweep, so HBM
traffic is feat×(C/CBLK reads) + out, independent of R.

Exactness: same edge semantics as ``ops.roi_align.roi_align`` (clip to
[0, size-1], hi=lo+1 capped, roi w/h floored at 1) — validated against it
in interpret mode by ``tests/test_pallas_roi_align.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interp_matrix(lo_f, whi, size: int, nbins: int, s: int):
    """Mean-of-samples one-hot interpolation matrix (nbins, size).

    ``lo_f``/``whi`` are (nbins*s,) f32 vectors of floor indices and
    hi-weights for each sample point; folds the 1/s sample average in.
    """
    n = nbins * s
    # int iota cast to f32: Mosaic's tpu.iota only emits integer vectors
    cell = jax.lax.broadcasted_iota(jnp.int32, (n, size), 1).astype(jnp.float32)
    lo = lo_f.reshape(n, 1)
    hi = jnp.minimum(lo + 1.0, float(size - 1))
    w1 = whi.reshape(n, 1)
    m = jnp.where(cell == lo, 1.0 - w1, 0.0) + jnp.where(cell == hi, w1, 0.0)
    # average the s sample rows of each bin
    return m.reshape(nbins, s, size).sum(axis=1) * (1.0 / s)


def _sample_coords(c1, c2, size: int, nbins: int, s: int):
    """Sample-point floors/weights along one axis for one roi.

    c1/c2: scaled roi edges (scalars).  Returns (lo_f (nbins*s,), whi)."""
    length = jnp.maximum(c2 - c1, 1.0)
    bin_sz = length / nbins
    i = jax.lax.broadcasted_iota(jnp.int32, (nbins * s, 1), 0).astype(jnp.float32)
    g = c1 + (i + 0.5) / s * bin_sz                                  # (n, 1)
    g = jnp.clip(g, 0.0, float(size - 1))
    lo_f = jnp.floor(g)
    return lo_f, g - lo_f


def _matrices_for_roi(rois_ref, b, r, hf: int, wf: int, pooled, s: int, scale: float):
    """``rois_ref`` is scalar-prefetched SMEM in (B, 4, R) layout — the
    coordinate dim must NOT be minor: SMEM pads the minor dim to 128
    lanes, so (B, R, 4) would blow up 32× and overflow the 1 MB SMEM at
    eval roi counts (B=8, R=300 → 1.2 MB)."""
    ph, pw = pooled
    x1 = rois_ref[b, 0, r] * scale
    y1 = rois_ref[b, 1, r] * scale
    x2 = rois_ref[b, 2, r] * scale
    y2 = rois_ref[b, 3, r] * scale
    ylo, ywhi = _sample_coords(y1, y2, hf, ph, s)
    xlo, xwhi = _sample_coords(x1, x2, wf, pw, s)
    my = _interp_matrix(ylo, ywhi, hf, ph, s)                        # (PH, H)
    mx = _interp_matrix(xlo, xwhi, wf, pw, s)                        # (PW, W)
    return my, mx


def _fwd_kernel(rois_ref, feat_ref, out_ref, *, pooled, s, scale):
    b, r = pl.program_id(0), pl.program_id(2)
    hf, wf = feat_ref.shape[1], feat_ref.shape[2]
    my, mx = _matrices_for_roi(rois_ref, b, r, hf, wf, pooled, s, scale)
    feat = feat_ref[0]                                               # (H, W, CB)
    # rows: (PH, W, CB) = contract H;   out: (PH, PW, CB) = contract W
    # Precision follows the graph's dtype: a bf16 training graph gets
    # single-pass bf16 dots with f32 accumulation (the same contract as
    # every conv around it); an f32 graph (eval parity) keeps 6-pass
    # HIGHEST — there the kernel must match the gather reference to
    # ~1e-5, not ~1e-3.
    if feat.dtype == jnp.bfloat16:
        prec = jax.lax.Precision.DEFAULT
        my, mx = my.astype(jnp.bfloat16), mx.astype(jnp.bfloat16)

        def dot1(a, bmat, dims):
            return jax.lax.dot_general(
                a, bmat, dims, preferred_element_type=jnp.float32,
                precision=prec,
            )

        rows = dot1(my, feat, (((1,), (0,)), ((), ()))).astype(jnp.bfloat16)
        out = dot1(mx, rows, (((1,), (1,)), ((), ())))
    else:
        rows = jax.lax.dot_general(
            my, feat.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        out = jax.lax.dot_general(
            mx, rows, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )                                                            # (PW, PH, CB)
    out_ref[0, 0] = out.transpose(1, 0, 2).astype(out_ref.dtype)


def _bwd_kernel(rois_ref, g_ref, dfeat_ref, *, pooled, s, scale):
    """dfeat is accumulated across the roi sweep in f32 (the out_shape is
    forced f32 regardless of feat dtype — 128 sequential bf16 adds would
    swallow small per-roi contributions); cast back outside the kernel.

    Two deliberate asymmetries vs the forward kernel: the accumulator is
    laid out TRANSPOSED, (W, H, CB) — the second dot emits that order,
    and one XLA transpose of the final (B, W, H, C) outside the kernel
    replaces B·R·(C/CB) in-kernel transposes (measured 35 ms → a few ms
    on the flagship step).  Precision mirrors the forward's dtype
    branch: bf16 cotangents (the bf16 training graph) take default MXU
    passes — 6-pass HIGHEST buys nothing the rest of that backward
    has — while f32 cotangents (COMPUTE_DTYPE=float32 runs) keep
    HIGHEST so gradients round at ~1e-5, not bf16-mantissa ~1e-3."""
    b, r = pl.program_id(0), pl.program_id(2)
    wf, hf = dfeat_ref.shape[1], dfeat_ref.shape[2]
    my, mx = _matrices_for_roi(rois_ref, b, r, hf, wf, pooled, s, scale)
    prec = (
        jax.lax.Precision.HIGHEST
        if g_ref.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )
    g = g_ref[0, 0].astype(jnp.float32)                              # (PH, PW, CB)
    # t: (H, PW, CB) = Myᵀ contract PH;  d: (W, H, CB) = Mxᵀ contract PW
    t = jax.lax.dot_general(
        my, g, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        precision=prec,
    )                                                                # (H, PW, CB)
    d = jax.lax.dot_general(
        mx, t, (((0,), (1,)), ((), ())), preferred_element_type=jnp.float32,
        precision=prec,
    )                                                                # (W, H, CB)

    @pl.when(r == 0)
    def _():
        dfeat_ref[0] = d

    @pl.when(r > 0)
    def _():
        dfeat_ref[0] = dfeat_ref[0] + d


def _cblk(c: int, largest: int = 512) -> int:
    for blk in (512, 256, 128):
        if blk <= largest and c % blk == 0:
            return blk
    return c


_VMEM_BUDGET = 5 * 2**20  # per resident feature block (of ~16MB total)


def fits_vmem(h: int, w: int, c: int) -> bool:
    """True iff some channel block keeps the resident (H, W, cblk) f32
    feature slab within the VMEM budget."""
    return h * w * _cblk(c, largest=128) * 4 <= _VMEM_BUDGET


def _cblk_fit(h: int, w: int, c: int, largest: int) -> int:
    """Largest channel block whose (H, W, cblk) f32 slab fits the budget."""
    blk = _cblk(c, largest)
    while blk > 128 and h * w * blk * 4 > _VMEM_BUDGET:
        blk //= 2
    return blk


def _roi_align_fwd_impl(feat, rois, pooled, scale, s, interpret):
    b, hf, wf, c = feat.shape
    r = rois.shape[1]
    cblk = _cblk_fit(hf, wf, c, largest=512)
    grid = (b, c // cblk, r)
    kernel = partial(_fwd_kernel, pooled=pooled, s=s, scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, hf, wf, cblk),
                    lambda bb, cb, rr, rois_ref: (bb, 0, 0, cb),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, pooled[0], pooled[1], cblk),
                lambda bb, cb, rr, rois_ref: (bb, rr, 0, 0, cb),
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((b, r, pooled[0], pooled[1], c), feat.dtype),
        interpret=interpret,
    )(rois.astype(jnp.float32).transpose(0, 2, 1), feat)


def _roi_align_bwd_impl(feat_shape, feat_dtype, rois, g, pooled, scale, s, interpret):
    b, hf, wf, c = feat_shape
    r = rois.shape[1]
    # 256 cap: the f32 accumulator block + its transpose scratch must fit
    # the scoped-VMEM budget (512 OOMs at 600x1000/stride-16 shapes)
    cblk = _cblk_fit(hf, wf, c, largest=256)
    grid = (b, c // cblk, r)
    kernel = partial(_bwd_kernel, pooled=pooled, s=s, scale=scale)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, 1, pooled[0], pooled[1], cblk),
                    lambda bb, cb, rr, rois_ref: (bb, rr, 0, 0, cb),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, wf, hf, cblk),
                lambda bb, cb, rr, rois_ref: (bb, 0, 0, cb),
            ),
        ),
        # (B, W, H, C): the kernel accumulates transposed (see docstring)
        out_shape=jax.ShapeDtypeStruct((b, wf, hf, c), jnp.float32),
        interpret=interpret,
    )(rois.astype(jnp.float32).transpose(0, 2, 1), g)
    return out.swapaxes(1, 2).astype(feat_dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def roi_align_pallas(
    feat: jnp.ndarray,
    rois: jnp.ndarray,
    pooled: tuple = (14, 14),
    spatial_scale: float = 1.0 / 16.0,
    sample_ratio: int = 2,
    interpret: bool = False,
) -> jnp.ndarray:
    """(B, H, W, C) feature + (B, R, 4) image-coord rois → (B, R, ph, pw, C).

    Batched twin of ``ops.roi_align.roi_align`` backed by the Pallas MXU
    kernel; differentiable in ``feat`` (rois get zero cotangent, matching
    the stop-gradient proposal semantics of the reference's Proposal op).
    """
    return _roi_align_fwd_impl(
        feat, rois, pooled, spatial_scale, sample_ratio, interpret
    )


def _vjp_fwd(feat, rois, pooled, spatial_scale, sample_ratio, interpret):
    out = _roi_align_fwd_impl(feat, rois, pooled, spatial_scale, sample_ratio, interpret)
    # feat rides along only for its shape/dtype; it is already live as a
    # backbone activation so this costs nothing extra
    return out, (feat, rois)


def _vjp_bwd(pooled, spatial_scale, sample_ratio, interpret, res, g):
    feat, rois = res
    dfeat = _roi_align_bwd_impl(
        feat.shape, feat.dtype, rois, g, pooled, spatial_scale, sample_ratio, interpret
    )
    return dfeat, jnp.zeros_like(rois)


roi_align_pallas.defvjp(_vjp_fwd, _vjp_bwd)
