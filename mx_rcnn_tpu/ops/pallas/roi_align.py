"""Pallas TPU ROIAlign — bilinear pooling as one-hot interpolation matmuls.

Reference: MXNet's ``roi_pooling.cu`` / torchvision ``roi_align.cu``
(SURVEY N6) — CUDA kernels that gather 4 neighbours per sample point and
scatter-add bilinear weights in the backward pass.  Gather/scatter is the
wrong shape for a TPU; this kernel reformulates ROIAlign as dense matrix
algebra that rides the MXU:

- Bilinear sampling is **separable**: the weight of cell (h, w) for sample
  point (gy, gx) factors into wy(h)·wx(w), and the s×s-sample average per
  output bin factors into (mean of row weights)·(mean of col weights).
- So per roi, pooling is exactly ``out = My @ feat @ Mxᵀ`` with
  My (PH, H) and Mx (PW, W) tiny interpolation matrices built on-chip
  from iota comparisons — two MXU contractions, zero gathers.
- Backward is the transpose pair ``dfeat += Myᵀ @ g @ Mx`` — again
  matmuls, accumulated across rois in a VMEM-resident block; no
  scatter-add (the CUDA kernel's atomics have no TPU analog).

Grid: (B, C-blocks, R) with roi boxes scalar-prefetched to SMEM; the
feature block stays resident in VMEM across the entire roi sweep, so HBM
traffic is feat×(C/CBLK reads) + out, independent of R.

Exactness: same edge semantics as ``ops.roi_align.roi_align`` (clip to
[0, size-1], hi=lo+1 capped, roi w/h floored at 1) — validated against it
in interpret mode by ``tests/test_pallas_roi_align.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax renamed TPUCompilerParams -> CompilerParams; accept either
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


def _interp_matrix(lo_f, whi, size: int, nbins: int, s: int):
    """Mean-of-samples one-hot interpolation matrix (nbins, size).

    ``lo_f``/``whi`` are (nbins*s,) f32 vectors of floor indices and
    hi-weights for each sample point; folds the 1/s sample average in.
    """
    n = nbins * s
    # int iota cast to f32: Mosaic's tpu.iota only emits integer vectors
    cell = jax.lax.broadcasted_iota(jnp.int32, (n, size), 1).astype(jnp.float32)
    lo = lo_f.reshape(n, 1)
    hi = jnp.minimum(lo + 1.0, float(size - 1))
    w1 = whi.reshape(n, 1)
    m = jnp.where(cell == lo, 1.0 - w1, 0.0) + jnp.where(cell == hi, w1, 0.0)
    # average the s sample rows of each bin
    return m.reshape(nbins, s, size).sum(axis=1) * (1.0 / s)


def _sample_coords(c1, c2, size: int, nbins: int, s: int):
    """Sample-point floors/weights along one axis for one roi.

    c1/c2: scaled roi edges (scalars).  Returns (lo_f (nbins*s,), whi)."""
    length = jnp.maximum(c2 - c1, 1.0)
    bin_sz = length / nbins
    i = jax.lax.broadcasted_iota(jnp.int32, (nbins * s, 1), 0).astype(jnp.float32)
    g = c1 + (i + 0.5) / s * bin_sz                                  # (n, 1)
    g = jnp.clip(g, 0.0, float(size - 1))
    lo_f = jnp.floor(g)
    return lo_f, g - lo_f


def _matrices_for_roi(rois_ref, b, r, hf: int, wf: int, pooled, s: int, scale: float):
    """``rois_ref`` is scalar-prefetched SMEM in (B, 4, R) layout — the
    coordinate dim must NOT be minor: SMEM pads the minor dim to 128
    lanes, so (B, R, 4) would blow up 32× and overflow the 1 MB SMEM at
    eval roi counts (B=8, R=300 → 1.2 MB)."""
    ph, pw = pooled
    x1 = rois_ref[b, 0, r] * scale
    y1 = rois_ref[b, 1, r] * scale
    x2 = rois_ref[b, 2, r] * scale
    y2 = rois_ref[b, 3, r] * scale
    ylo, ywhi = _sample_coords(y1, y2, hf, ph, s)
    xlo, xwhi = _sample_coords(x1, x2, wf, pw, s)
    my = _interp_matrix(ylo, ywhi, hf, ph, s)                        # (PH, H)
    mx = _interp_matrix(xlo, xwhi, wf, pw, s)                        # (PW, W)
    return my, mx


def _fwd_kernel(rois_ref, feat_ref, out_ref, *, pooled, s, scale, rblk):
    """Blocked forward: RBLK rois per grid step.

    The W-contraction (the majority of the flops — W ≥ H in every
    landscape bucket) runs once on a STACKED (RBLK·PW, W) interpolation
    matrix: M=112 rows at the default rblk=8/pw=14 instead of 14, so the
    MXU's 128-row tiles are ~90% occupied instead of ~11%.  The
    H-contraction needs a different My per roi on the non-contracted
    side, so it stays per-roi; putting the SHORTER spatial axis (H) on
    the per-roi side minimizes that tail, and its (PH, H)@(H, PW, CB)
    form emits (PH, PW, CB) directly — no in-kernel transpose.  Blocking
    the per-roi side would need a block-diagonal My whose 7/8 zero flops
    exactly cancel the utilization win."""
    b, rb = pl.program_id(0), pl.program_id(2)
    hf, wf = feat_ref.shape[1], feat_ref.shape[2]
    _, pw = pooled  # only PW shapes the stacked contraction below
    mys, mxs = [], []
    for k in range(rblk):
        my, mx = _matrices_for_roi(
            rois_ref, b, rb * rblk + k, hf, wf, pooled, s, scale
        )
        mys.append(my)
        mxs.append(mx)
    mx_blk = jnp.concatenate(mxs, axis=0)                            # (RB*PW, W)
    feat = feat_ref[0]                                               # (H, W, CB)
    # Precision follows the graph's dtype: a bf16 training graph gets
    # single-pass bf16 dots with f32 accumulation (the same contract as
    # every conv around it); an f32 graph (eval parity) keeps 6-pass
    # HIGHEST — there the kernel must match the gather reference to
    # ~1e-5, not ~1e-3.
    if feat.dtype == jnp.bfloat16:
        prec = jax.lax.Precision.DEFAULT
        mx_blk = mx_blk.astype(jnp.bfloat16)
        mys = [m.astype(jnp.bfloat16) for m in mys]
    else:
        prec = jax.lax.Precision.HIGHEST
        feat = feat.astype(jnp.float32)

    # W first on the stacked matrix, H per-roi: the per-roi tail then
    # contracts the SHORTER axis (H) and emits (PH, PW, CB) directly —
    # no in-kernel transpose.  (A bf16 preferred_element_type would drop
    # the f32 cols buffer and fit cblk=512, but tpu.matmul requires a
    # 32-bit accumulator — Mosaic rejects it at lowering.)
    cols = jax.lax.dot_general(
        mx_blk, feat, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    )                                                                # (RB*PW, H, CB)
    if feat.dtype == jnp.bfloat16:
        cols = cols.astype(jnp.bfloat16)
    for k in range(rblk):
        out_k = jax.lax.dot_general(
            mys[k], cols[k * pw:(k + 1) * pw],
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        )                                                            # (PH, PW, CB)
        # per-roi sub-block stores: a single jnp.stack write measured
        # 3% SLOWER end-to-end (the stack materializes a VMEM concat)
        out_ref[0, k] = out_k.astype(out_ref.dtype)


def _bwd_kernel(rois_ref, g_ref, dfeat_ref, *, pooled, s, scale, rblk):
    """Blocked backward: RBLK rois per grid step.

    dfeat is accumulated across the roi-block sweep in f32 (the
    out_shape is forced f32 regardless of feat dtype — sequential bf16
    adds would swallow small per-roi contributions); cast back outside
    the kernel.

    d = Σ_k Mxᵀ_k @ (Myᵀ_k @ g_k) restructured so the roi sum rides the
    contraction: the per-roi half (t_k = Myᵀ_k @ g_k, K=PH=14) stays
    small, but the second half stacks t_k into (W, RB·PW, CB)-shaped U
    and contracts K=RB·PW=112 against the stacked Mx — one matmul sums
    all RBLK rois, with ~90% K-tile occupancy instead of ~11% and 8×
    fewer accumulator read-modify-writes.

    Two deliberate asymmetries vs the forward kernel: the accumulator is
    laid out TRANSPOSED, (W, H, CB) — the stacked dot emits that order,
    and one XLA transpose of the final (B, W, H, C) outside the kernel
    replaces per-step in-kernel transposes (measured 35 ms → a few ms on
    the flagship step).  Precision mirrors the forward's dtype branch:
    bf16 cotangents (the bf16 training graph) take default MXU passes —
    6-pass HIGHEST buys nothing the rest of that backward has — while
    f32 cotangents (COMPUTE_DTYPE=float32 runs) keep HIGHEST so
    gradients round at ~1e-5, not bf16-mantissa ~1e-3."""
    b, rb = pl.program_id(0), pl.program_id(2)
    wf, hf = dfeat_ref.shape[1], dfeat_ref.shape[2]
    ph, pw = pooled
    prec = (
        jax.lax.Precision.HIGHEST
        if g_ref.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )
    ts, mxs = [], []
    for k in range(rblk):
        my, mx = _matrices_for_roi(
            rois_ref, b, rb * rblk + k, hf, wf, pooled, s, scale
        )
        g = g_ref[0, k].astype(jnp.float32)                          # (PH, PW, CB)
        # t_k: (H, PW, CB) = Myᵀ_k contract PH
        ts.append(jax.lax.dot_general(
            my, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32, precision=prec,
        ))
        mxs.append(mx)
    mx_blk = jnp.concatenate(mxs, axis=0)                            # (RB*PW, W)
    t_blk = jnp.concatenate(ts, axis=1)                              # (H, RB*PW, CB)
    # d: (W, H, CB) = stacked Mxᵀ contract RB·PW — sums the roi block
    d = jax.lax.dot_general(
        mx_blk, t_blk, (((0,), (1,)), ((), ())),
        preferred_element_type=jnp.float32, precision=prec,
    )

    @pl.when(rb == 0)
    def _():
        dfeat_ref[0] = d

    @pl.when(rb > 0)
    def _():
        dfeat_ref[0] = dfeat_ref[0] + d


def _cblk(c: int, largest: int = 512) -> int:
    for blk in (512, 256, 128):
        if blk <= largest and c % blk == 0:
            return blk
    return c


# Per-step working-set budget.  The flagship bf16 C4 configs validated
# on a real v5e hold 5.6 MB (fwd) / 6.9 MB (bwd) under this accounting
# and compile+run; the historical over-commit (old 512-cap bwd,
# ~13.7 MB accounted) failed scoped-VMEM allocation.  8 MB keeps every
# hardware-validated config resident with margin for Mosaic's
# double-buffering of the streamed g/out blocks (~1 MB) inside the
# chip's ~16 MB.
_VMEM_BUDGET = 8 * 2**20

_RBLK = 8  # rois per grid step; M/K tiles go 14 → 112 of the MXU's 128


def _resident_bytes(
    h: int, w: int, blk: int, esize: int, pooled_max: int = 14
) -> int:
    """Worst-case VMEM bytes the blocked kernels hold per step: the
    resident (H, W, blk) slab (feat dtype) or f32 accumulator PLUS the
    f32 stacked roi-block intermediate — fwd's cols (RB·PW, H, blk) or
    bwd's t_blk (H, RB·PW, blk), bounded by max(h, w) on the spatial
    axis.  The pre-blocking heuristic counted only the slab; the
    stacked intermediate is the same order of magnitude, so omitting it
    would re-create exactly the silent over-commit the historical
    512-cap comment records (fit check passes, Mosaic scoped-VMEM
    allocation fails).  ``esize``: feat dtype bytes for the fwd slab; the
    bwd accumulator is always f32, so bwd callers pass 4.

    The stacked intermediate's spatial axis is H in both passes (the
    kernels contract W on the stacked side), so portrait buckets
    (H > W) genuinely hold the larger intermediate and size down to a
    smaller cblk — that is the honest cost of the fixed W-stacked axis
    order, not over-counting.

    The stacked intermediate is ALWAYS f32: tpu.matmul requires a
    32-bit accumulator, so even bf16 graphs materialize fwd cols /
    bwd t_blk in f32 before any cast."""
    pooled_stack = _RBLK * pooled_max
    return (h * w * esize + pooled_stack * h * 4) * blk


def fits_vmem(h: int, w: int, c: int, pooled_max: int = 14) -> bool:
    """True iff some channel block keeps the blocked kernels' per-step
    working set (slab + stacked roi-block intermediate) in budget —
    checked for the BACKWARD's f32 accumulator (the larger of the two
    passes), so a map dispatched resident never OOMs in its grad.
    ``pooled_max``: max(PH, PW) of the pooled output — sizes the stacked
    roi-block intermediate (ADVICE r4: was hardcoded 14)."""
    return (
        _resident_bytes(h, w, _cblk(c, largest=128), 4, pooled_max)
        <= _VMEM_BUDGET
    )


def _cblk_fit(
    h: int, w: int, c: int, largest: int, esize: int = 4, pooled_max: int = 14
) -> int:
    """Largest channel block whose per-step working set fits the budget."""
    blk = _cblk(c, largest)
    while blk > 128 and _resident_bytes(h, w, blk, esize, pooled_max) > _VMEM_BUDGET:
        blk //= 2
    return blk


def _pad_rois(rois, rblk):
    """(B, R, 4) → ((B, 4, Rp) SMEM layout, Rp) with R padded to rblk.

    Pad rois are all-zero boxes — degenerate but numerically safe
    (length floors at 1 in _sample_coords), and their outputs are
    sliced away / their cotangents are structurally zero."""
    r = rois.shape[1]
    rp = -(-r // rblk) * rblk
    rois_t = rois.astype(jnp.float32).transpose(0, 2, 1)
    if rp != r:
        rois_t = jnp.pad(rois_t, ((0, 0), (0, 0), (0, rp - r)))
    return rois_t, rp


def _roi_align_fwd_impl(feat, rois, pooled, scale, s, interpret):
    b, hf, wf, c = feat.shape
    r = rois.shape[1]
    # 256 cap: the blocked (RB·PW, H, CB) f32 cols intermediate shares
    # VMEM with the resident feature slab
    cblk = _cblk_fit(
        hf, wf, c, largest=256, esize=feat.dtype.itemsize,
        pooled_max=max(pooled),
    )
    rois_t, rp = _pad_rois(rois, _RBLK)
    grid = (b, c // cblk, rp // _RBLK)
    kernel = partial(_fwd_kernel, pooled=pooled, s=s, scale=scale, rblk=_RBLK)
    out = pl.pallas_call(
        kernel,
        # every fwd grid step writes a disjoint out block — declaring all
        # three axes parallel lets Mosaic pipeline/overlap grid steps
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel"),
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, hf, wf, cblk),
                    lambda bb, cb, rr, rois_ref: (bb, 0, 0, cb),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, _RBLK, pooled[0], pooled[1], cblk),
                lambda bb, cb, rr, rois_ref: (bb, rr, 0, 0, cb),
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((b, rp, pooled[0], pooled[1], c), feat.dtype),
        interpret=interpret,
    )(rois_t, feat)
    return out[:, :r] if rp != r else out


def _roi_align_bwd_impl(feat_shape, feat_dtype, rois, g, pooled, scale, s, interpret):
    b, hf, wf, c = feat_shape
    r = rois.shape[1]
    # 256 cap: the f32 accumulator block + the stacked t intermediate
    # must fit the scoped-VMEM budget (512 OOMs at 600x1000/stride-16)
    cblk = _cblk_fit(hf, wf, c, largest=256, esize=4, pooled_max=max(pooled))
    rois_t, rp = _pad_rois(rois, _RBLK)
    if rp != r:
        g = jnp.pad(g, ((0, 0), (0, rp - r)) + ((0, 0),) * (g.ndim - 2))
    grid = (b, c // cblk, rp // _RBLK)
    kernel = partial(_bwd_kernel, pooled=pooled, s=s, scale=scale, rblk=_RBLK)
    out = pl.pallas_call(
        kernel,
        # batch/channel blocks are independent; the roi axis carries the
        # accumulator read-modify-write and must stay sequential
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, _RBLK, pooled[0], pooled[1], cblk),
                    lambda bb, cb, rr, rois_ref: (bb, rr, 0, 0, cb),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, wf, hf, cblk),
                lambda bb, cb, rr, rois_ref: (bb, 0, 0, cb),
            ),
        ),
        # (B, W, H, C): the kernel accumulates transposed (see docstring)
        out_shape=jax.ShapeDtypeStruct((b, wf, hf, c), jnp.float32),
        interpret=interpret,
    )(rois_t, g)
    return out.swapaxes(1, 2).astype(feat_dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def roi_align_pallas(
    feat: jnp.ndarray,
    rois: jnp.ndarray,
    pooled: tuple = (14, 14),
    spatial_scale: float = 1.0 / 16.0,
    sample_ratio: int = 2,
    interpret: bool = False,
) -> jnp.ndarray:
    """(B, H, W, C) feature + (B, R, 4) image-coord rois → (B, R, ph, pw, C).

    Batched twin of ``ops.roi_align.roi_align`` backed by the Pallas MXU
    kernel; differentiable in ``feat`` (rois get zero cotangent, matching
    the stop-gradient proposal semantics of the reference's Proposal op).
    """
    return _roi_align_fwd_impl(
        feat, rois, pooled, spatial_scale, sample_ratio, interpret
    )


def _vjp_fwd(feat, rois, pooled, spatial_scale, sample_ratio, interpret):
    out = _roi_align_fwd_impl(feat, rois, pooled, spatial_scale, sample_ratio, interpret)
    # feat rides along only for its shape/dtype; it is already live as a
    # backbone activation so this costs nothing extra
    return out, (feat, rois)


def _vjp_bwd(pooled, spatial_scale, sample_ratio, interpret, res, g):
    feat, rois = res
    dfeat = _roi_align_bwd_impl(
        feat.shape, feat.dtype, rois, g, pooled, spatial_scale, sample_ratio, interpret
    )
    return dfeat, jnp.zeros_like(rois)


roi_align_pallas.defvjp(_vjp_fwd, _vjp_bwd)
