"""Streaming Pallas ROIAlign for feature maps too large for VMEM (FPN P2).

The resident kernel (``ops/pallas/roi_align.py``) keeps one (H, W, cblk)
feature slab in VMEM across the roi sweep — impossible for FPN's P2 at
flagship resolution (152×256×128 f32 ≈ 20 MB).  Until round 3 those
shapes silently fell back to the chunked-gather path (VERDICT r3 #3).

This kernel STREAMS the feature map through VMEM in row blocks instead:

- forward: grid (B, C-blocks, roi-blocks, H-blocks); a VMEM scratch
  accumulator holds the roi-block's (rblk, PH, PW, cblk) outputs while
  row blocks stream past; each roi adds ``My[:, rows] @ F @ Mxᵀ`` for
  the rows it intersects (``pl.when`` skips non-intersecting blocks, so
  compute scales with roi extent, not map height).  HBM feature traffic
  is (R/rblk)× the map per channel block — independent of R's 512.
- backward: grid (B, C-blocks, H-blocks, roi-blocks); the (hblk, W,
  cblk) dfeat block stays resident while roi-blocks of cotangents
  stream past, accumulating ``My[:, rows]ᵀ @ g @ Mx``.

Same bilinear semantics as the resident kernel (shared interpolation
helpers; the row-restricted matrices are the same one-hot construction
with a global row offset, so rows outside the block simply get zero
weight).  Validated against the gather reference in interpret mode by
``tests/test_pallas_roi_align.py``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from mx_rcnn_tpu.ops.pallas.roi_align import _sample_coords


def _interp_matrix_rows(lo_f, whi, offset, hblk: int, nbins: int, s: int):
    """Row-restricted one-hot interpolation matrix (nbins, hblk): global
    row index = offset + local iota; sample points outside the block get
    zero weight automatically (their lo/hi never match)."""
    n = nbins * s
    cell = jax.lax.broadcasted_iota(jnp.int32, (n, hblk), 1).astype(
        jnp.float32
    ) + offset
    lo = lo_f.reshape(n, 1)
    w1 = whi.reshape(n, 1)
    # hi = lo + 1 capped at the LAST GLOBAL row (size-1), matching
    # _interp_matrix; the cap index is threaded via the caller's clip
    m = jnp.where(cell == lo, 1.0 - w1, 0.0) + jnp.where(
        cell == lo + 1.0, w1, 0.0
    )
    return m.reshape(nbins, s, hblk).sum(axis=1) * (1.0 / s)


def _row_matrices(rois_ref, b, r, hf: int, wf: int, offset, hblk: int,
                  pooled, s: int, scale: float):
    """(My_sub (PH, hblk), Mx (PW, W), y-extent scalars) for one roi.

    The hi=lo+1 cap at size-1 is folded into the coords: a sample with
    lo == size-1 gets whi forced to 0 so all its weight lands on lo —
    identical to the resident kernel's ``min(lo+1, size-1)`` + both
    one-hot terms colliding on the same cell.
    """
    ph, pw = pooled
    x1 = rois_ref[b, 0, r] * scale
    y1 = rois_ref[b, 1, r] * scale
    x2 = rois_ref[b, 2, r] * scale
    y2 = rois_ref[b, 3, r] * scale
    valid = x2 >= x1  # inverted boxes are _pad_rois fillers
    ylo, ywhi = _sample_coords(y1, y2, hf, ph, s)
    xlo, xwhi = _sample_coords(x1, x2, wf, pw, s)
    # cap: when lo is the last row/col, send the hi-weight to lo as well
    # (resident kernel achieves this because lo==hi makes both one-hot
    # terms hit the same cell; here lo+1 would fall outside)
    ylo_last = ylo == float(hf - 1)
    ywhi = jnp.where(ylo_last, 0.0, ywhi)
    xlo_last = xlo == float(wf - 1)
    xwhi = jnp.where(xlo_last, 0.0, xwhi)

    my = _interp_matrix_rows(ylo, ywhi, offset, hblk, ph, s)     # (PH, hblk)
    from mx_rcnn_tpu.ops.pallas.roi_align import _interp_matrix

    mx = _interp_matrix(xlo, xwhi, wf, pw, s)                    # (PW, W)
    # conservative GLOBAL row extent of the roi's sample support, for
    # the caller's block-skip predicate.  Sample points live in
    # [clip(y1), clip(y1 + max(y2-y1, 1))] (the min-length clamp in
    # _sample_coords means a degenerate roi still reaches ~y1+1, NOT
    # y2!), and each contributes to rows floor(g) and floor(g)+1;
    # clamping into [0, hf-1] keeps fully-offscreen rois pointing at
    # the edge rows their clipped samples actually hit.
    lo_cell = jnp.clip(jnp.floor(y1), 0.0, float(hf - 1))
    hi_cell = jnp.clip(
        jnp.floor(y1 + jnp.maximum(y2 - y1, 1.0)) + 1.0, 0.0, float(hf - 1)
    )
    return my, mx, valid, lo_cell, hi_cell


def _fwd_kernel(rois_ref, feat_ref, out_ref, acc_ref, *, pooled, s, scale,
                hblk, n_hblk, rblk, hf):
    b = pl.program_id(0)
    rb = pl.program_id(2)
    hb = pl.program_id(3)
    wf = feat_ref.shape[2]
    offset = hb * hblk  # int; promotes against the f32 iota/extents

    @pl.when(hb == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    feat = feat_ref[0]                                           # (hblk, W, CB)
    # rows past H in the (padded) last block hold uninitialized memory;
    # their interpolation weight is zero, but 0·NaN/Inf would still
    # poison the matmul accumulator — mask them to real zeros
    row_ids = jax.lax.broadcasted_iota(jnp.int32, (hblk, 1, 1), 0) + offset
    feat = jnp.where(row_ids < hf, feat, jnp.zeros_like(feat))
    f32 = feat.dtype != jnp.bfloat16

    def body(i, _):
        r = rb * rblk + i
        my, mx, valid, lo_cell, hi_cell = _row_matrices(
            rois_ref, b, r, hf, wf, offset, hblk, pooled, s, scale
        )

        # skip fillers and row blocks outside the sample-support extent
        @pl.when(valid & (hi_cell >= offset) & (lo_cell <= offset + (hblk - 1)))
        def _():
            if f32:
                rows = jax.lax.dot_general(
                    my, feat.astype(jnp.float32), (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                )
                out = jax.lax.dot_general(
                    mx, rows, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                )                                                # (PW, PH, CB)
            else:
                rows = jax.lax.dot_general(
                    my.astype(jnp.bfloat16), feat, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                ).astype(jnp.bfloat16)
                out = jax.lax.dot_general(
                    mx.astype(jnp.bfloat16), rows, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            # TRANSPOSED accumulator (PW, PH, CB) — the second dot's
            # natural order; one transpose at the flush replaces
            # R×n_hblk in-kernel transposes (the resident backward
            # measured that pattern at 35 ms)
            acc_ref[i] = acc_ref[i] + out

        return 0

    jax.lax.fori_loop(0, rblk, body, 0)

    @pl.when(hb == n_hblk - 1)
    def _():
        out_ref[0] = acc_ref[...].transpose(0, 2, 1, 3).astype(out_ref.dtype)


def _bwd_kernel(rois_ref, g_ref, dfeat_ref, *, pooled, s, scale, hblk,
                rblk, hf):
    b = pl.program_id(0)
    hb = pl.program_id(2)
    rb = pl.program_id(3)
    wf = dfeat_ref.shape[2]
    offset = hb * hblk

    @pl.when(rb == 0)
    def _():
        dfeat_ref[...] = jnp.zeros_like(dfeat_ref)

    # mirror the resident backward's precision contract: f32 cotangents
    # (COMPUTE_DTYPE=float32 runs) keep HIGHEST (~1e-5 gradients), bf16
    # training graphs take default MXU passes
    prec = (
        jax.lax.Precision.HIGHEST
        if g_ref.dtype == jnp.float32
        else jax.lax.Precision.DEFAULT
    )

    def body(i, _):
        r = rb * rblk + i
        my, mx, valid, lo_cell, hi_cell = _row_matrices(
            rois_ref, b, r, hf, wf, offset, hblk, pooled, s, scale
        )

        @pl.when(valid & (hi_cell >= offset) & (lo_cell <= offset + (hblk - 1)))
        def _():
            g = g_ref[0, i].astype(jnp.float32)                  # (PH, PW, CB)
            # t: (W, PH, CB) = Mxᵀ contract PW;  d: (hblk, W, CB)
            t = jax.lax.dot_general(
                mx, g, (((0,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec,
            )
            d = jax.lax.dot_general(
                my, t, (((0,), (1,)), ((), ())),
                preferred_element_type=jnp.float32, precision=prec,
            )                                                    # (hblk, W, CB)
            dfeat_ref[0] = dfeat_ref[0] + d

        return 0

    jax.lax.fori_loop(0, rblk, body, 0)


def _pick_hblk(w: int, cblk: int, budget: int = 2 * 2**20) -> int:
    h = budget // (w * cblk * 4)
    return max(8, (h // 8) * 8)


def _pick_rblk(pooled, cblk: int, budget: int = 4 * 2**20) -> int:
    """roi-block size bounded by the f32 scratch accumulator's VMEM
    footprint — (rblk, ph, pw, cblk) must fit ``budget`` at any pooled
    size (the 14×14 mask head quadruples the 7×7 box head's area)."""
    r = budget // (pooled[0] * pooled[1] * cblk * 4)
    return max(8, min(128, (r // 8) * 8))


def _pad_rois(rois, rblk):
    b, r, _ = rois.shape
    pad = (-r) % rblk
    if pad:
        # inverted (x2 < x1) filler rois: the kernels' validity term in
        # the block-skip predicate drops them entirely, so padding costs
        # no MXU work (their rows would otherwise clip into block 0)
        filler = jnp.tile(
            jnp.asarray([0.0, 0.0, -1.0, -1.0], rois.dtype), (b, pad, 1)
        )
        rois = jnp.concatenate([rois, filler], axis=1)
    return rois, r


def _fwd_impl(feat, rois, pooled, scale, s, interpret, rblk=None):
    b, hf, wf, c = feat.shape
    cblk = 128 if c % 128 == 0 else c
    rblk = rblk or _pick_rblk(pooled, cblk)
    rois_p, r_true = _pad_rois(rois, rblk)
    r = rois_p.shape[1]
    hblk = _pick_hblk(wf, cblk)
    n_hblk = -(-hf // hblk)
    grid = (b, c // cblk, r // rblk, n_hblk)
    kernel = partial(
        _fwd_kernel, pooled=pooled, s=s, scale=scale, hblk=hblk,
        n_hblk=n_hblk, rblk=rblk, hf=hf,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, hblk, wf, cblk),
                    lambda bb, cb, rb, hb, rois_ref: (bb, hb, 0, cb),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, rblk, pooled[0], pooled[1], cblk),
                lambda bb, cb, rb, hb, rois_ref: (bb, rb, 0, 0, cb),
            ),
            scratch_shapes=[
                # transposed (PW, PH) layout — see the kernel's flush
                pltpu.VMEM((rblk, pooled[1], pooled[0], cblk), jnp.float32)
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(
            (b, r, pooled[0], pooled[1], c), feat.dtype
        ),
        interpret=interpret,
    )(rois_p.astype(jnp.float32).transpose(0, 2, 1), feat)
    return out[:, :r_true]


def _bwd_impl(feat_shape, feat_dtype, rois, g, pooled, scale, s, interpret,
              rblk=None):
    b, hf, wf, c = feat_shape
    cblk = 128 if c % 128 == 0 else c
    rblk = rblk or _pick_rblk(pooled, cblk)
    rois_p, r_true = _pad_rois(rois, rblk)
    r = rois_p.shape[1]
    if r != g.shape[1]:
        g = jnp.concatenate(
            [g, jnp.zeros((b, r - g.shape[1]) + g.shape[2:], g.dtype)], axis=1
        )
    hblk = _pick_hblk(wf, cblk)
    n_hblk = -(-hf // hblk)
    n_rblk = r // rblk
    grid = (b, c // cblk, n_hblk, n_rblk)
    kernel = partial(
        _bwd_kernel, pooled=pooled, s=s, scale=scale, hblk=hblk,
        rblk=rblk, hf=hf,
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec(
                    (1, rblk, pooled[0], pooled[1], cblk),
                    lambda bb, cb, hb, rb, rois_ref: (bb, rb, 0, 0, cb),
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, hblk, wf, cblk),
                lambda bb, cb, hb, rb, rois_ref: (bb, hb, 0, cb),
            ),
        ),
        out_shape=jax.ShapeDtypeStruct((b, hf, wf, c), jnp.float32),
        interpret=interpret,
    )(rois_p.astype(jnp.float32).transpose(0, 2, 1), g)
    return out.astype(feat_dtype)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def roi_align_stream(
    feat: jnp.ndarray,
    rois: jnp.ndarray,
    pooled: tuple = (7, 7),
    spatial_scale: float = 0.25,
    sample_ratio: int = 2,
    interpret: bool = False,
) -> jnp.ndarray:
    """(B, H, W, C) × (B, R, 4) → (B, R, ph, pw, C); the streaming twin
    of ``roi_align_pallas`` for maps over the VMEM budget."""
    return _fwd_impl(feat, rois, pooled, spatial_scale, sample_ratio, interpret)


def _vjp_fwd(feat, rois, pooled, spatial_scale, sample_ratio, interpret):
    out = _fwd_impl(feat, rois, pooled, spatial_scale, sample_ratio, interpret)
    return out, (feat, rois)


def _vjp_bwd(pooled, spatial_scale, sample_ratio, interpret, res, g):
    feat, rois = res
    dfeat = _bwd_impl(
        feat.shape, feat.dtype, rois, g, pooled, spatial_scale,
        sample_ratio, interpret,
    )
    return dfeat, jnp.zeros_like(rois)


roi_align_stream.defvjp(_vjp_fwd, _vjp_bwd)
