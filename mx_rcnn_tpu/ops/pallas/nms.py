"""Pallas TPU NMS kernel — blocked greedy suppression.

Reference: ``rcnn/cython/nms_kernel.cu`` (SURVEY N1) — the classic
py-faster-rcnn bitmask GPU kernel: 64×64 IoU tiles, per-(box, block)
suppression bitmasks, host-side sequential reduce.  The TPU formulation
keeps the same blocked structure but runs *entirely* on-chip with no host
reduce and no bitmask materialization:

- boxes arrive score-sorted (the proposal path already top-k sorts);
- process lane-width (128) blocks of boxes in order;
- per block: an exact sequential greedy scan *within* the block (128
  tiny VPU steps on (1, 128) vectors), then one vectorized (128, N) IoU
  slab that kills every later box overlapping a surviving block member —
  the O(N²) work rides the VPU in 8×128 tiles, and the unavoidable
  greedy serialization is only O(N) scalar steps instead of O(N²).

Layout notes (TPU tiling): boxes are carried as (8, N) — four coordinate
sublanes + area + three padding sublanes — so the lane dimension is the
box index and every slab op is natively tiled; a (N, 4) layout would
waste 32× VMEM in lane padding.

Semantics identical to ``ops.nms.nms_mask`` (validated against it and the
numpy oracle in tests/test_pallas_nms.py): invalid boxes neither survive
nor suppress; returns a keep mask over the *sorted* input.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 128


def _nms_kernel(boxes_ref, keep_in_ref, keep_ref, *, thresh: float, n: int):
    """boxes_ref: (8, N) [x1, y1, x2, y2, area, pad...]; keep_ref: (1, N)
    f32 output aliased onto ``keep_in_ref`` (the validity mask) — arrives
    as validity, leaves as the keep mask."""
    keep_ref[:, :] = keep_in_ref[:, :]
    n_blocks = n // BLOCK
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, BLOCK), 1)      # (1,128)
    lane_n = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)        # (1,N)

    def iou_slab(blk, blk_area, allx, all_area):
        """IoU of a (8, BLOCK) block vs (8, M) boxes → (BLOCK, M)."""
        # transpose block coords into the sublane dim: (BLOCK, 1) each
        bx1 = blk[0:1, :].reshape(BLOCK, 1)
        by1 = blk[1:2, :].reshape(BLOCK, 1)
        bx2 = blk[2:3, :].reshape(BLOCK, 1)
        by2 = blk[3:4, :].reshape(BLOCK, 1)
        ba = blk_area.reshape(BLOCK, 1)
        iw = jnp.minimum(bx2, allx[2:3, :]) - jnp.maximum(bx1, allx[0:1, :]) + 1.0
        ih = jnp.minimum(by2, allx[3:4, :]) - jnp.maximum(by1, allx[1:2, :]) + 1.0
        inter = jnp.maximum(iw, 0.0) * jnp.maximum(ih, 0.0)        # (BLOCK, M)
        union = ba + all_area - inter
        return inter / jnp.maximum(union, 1e-12)

    def outer(j, _):
        start = pl.multiple_of(j * BLOCK, BLOCK)
        blk = boxes_ref[:, pl.ds(start, BLOCK)]                    # (8,128)
        blk_area = blk[4:5, :]                                     # (1,128)
        valid_row = keep_ref[:, pl.ds(start, BLOCK)]               # (1,128) f32

        # Intra-block greedy via synchronous fixpoint iteration instead of
        # a 128-step scalar scan (TPU scalar-loop overhead is ~µs/step —
        # the scan was the whole kernel's cost).  Iterating
        #   alive_i ← valid_i ∧ ¬∃j<i (alive_j ∧ iou_ji > t)
        # is exact once iteration count ≥ the longest suppression-
        # dependency chain (each pass finalizes one more DAG level), and
        # the while_loop stops at the first unchanged pass — typically
        # 3-6 vectorized (128×128) VPU steps.
        sub = jax.lax.broadcasted_iota(jnp.int32, (BLOCK, BLOCK), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (BLOCK, BLOCK), 1)
        iou_b = iou_slab(blk, blk_area, blk, blk_area)
        kill_edge = jnp.where((iou_b > thresh) & (sub < col), 1.0, 0.0)

        def fix_cond(carry):
            return carry[1]

        def fix_body(carry):
            alive_col, _ = carry
            killed = jnp.max(kill_edge * alive_col, axis=0, keepdims=True)
            new_row = jnp.where(killed > 0.5, 0.0, valid_row)      # (1,128)
            new_col = new_row.reshape(BLOCK, 1)
            return new_col, jnp.any(new_col != alive_col)

        alive_col, _ = jax.lax.while_loop(
            fix_cond, fix_body, (valid_row.reshape(BLOCK, 1), True)
        )
        alive = alive_col.reshape(1, BLOCK)
        keep_ref[:, pl.ds(start, BLOCK)] = alive

        # cross-block: surviving block members kill all later overlaps
        all_boxes = boxes_ref[:, :]                                # (8,N)
        iou_all = iou_slab(blk, blk_area, all_boxes, all_boxes[4:5, :])
        killed = jnp.max(
            jnp.where((iou_all > thresh) & (alive.reshape(BLOCK, 1) > 0.5), 1.0, 0.0),
            axis=0,
            keepdims=True,
        )                                                          # (1,N)
        later = lane_n >= (start + BLOCK)
        keep_ref[:, :] = jnp.where(later & (killed > 0.5), 0.0, keep_ref[:, :])
        return 0

    jax.lax.fori_loop(0, n_blocks, outer, 0)


@partial(jax.jit, static_argnames=("thresh", "interpret"))
def nms_mask_sorted_pallas(
    boxes: jnp.ndarray, valid: jnp.ndarray, thresh: float, interpret: bool = False
) -> jnp.ndarray:
    """Keep mask for (N, 4) boxes ALREADY sorted by descending score.

    ``valid`` (N,) bool marks real rows.  N is padded to a lane multiple
    internally; returns (N,) bool.  ``interpret=True`` runs the kernel in
    the Pallas interpreter (CPU tests).
    """
    n = boxes.shape[0]
    n_pad = ((n + BLOCK - 1) // BLOCK) * BLOCK
    coords = jnp.zeros((8, n_pad), jnp.float32)
    bt = boxes.astype(jnp.float32).T                               # (4, N)
    coords = coords.at[0:4, :n].set(bt)
    area = (bt[2] - bt[0] + 1.0) * (bt[3] - bt[1] + 1.0)
    coords = coords.at[4, :n].set(area)
    keep0 = jnp.zeros((1, n_pad), jnp.float32).at[0, :n].set(
        valid.astype(jnp.float32)
    )

    keep = pl.pallas_call(
        partial(_nms_kernel, thresh=float(thresh), n=n_pad),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        input_output_aliases={1: 0},
        interpret=interpret,
    )(coords, keep0)
    return keep[0, :n] > 0.5


def nms_mask_pallas(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    thresh: float,
    valid: jnp.ndarray | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in twin of ``ops.nms.nms_mask`` backed by the Pallas kernel:
    sorts by score, runs the kernel, scatters back to input order."""
    n = boxes.shape[0]
    if valid is None:
        valid = jnp.ones((n,), dtype=bool)
    scores = jnp.where(valid, scores, -jnp.inf)
    order = jnp.argsort(-scores)
    keep_sorted = nms_mask_sorted_pallas(
        boxes[order], valid[order], thresh, interpret
    )
    return jnp.zeros((n,), bool).at[order].set(keep_sorted)
