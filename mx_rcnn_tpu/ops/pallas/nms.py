"""Pallas TPU NMS kernel — blocked greedy suppression.

Reference: ``rcnn/cython/nms_kernel.cu`` (SURVEY N1) — the classic
py-faster-rcnn bitmask GPU kernel: 64×64 IoU tiles, per-(box, block)
suppression bitmasks, host-side sequential reduce.  The TPU formulation
keeps the same blocked structure but runs *entirely* on-chip with no host
reduce and no bitmask materialization:

- boxes arrive score-sorted (the proposal path already top-k sorts);
- process lane-width (128) blocks of boxes in order;
- per block: an exact sequential greedy scan *within* the block (128
  tiny VPU steps on (1, 128) vectors), then one vectorized (128, N) IoU
  slab that kills every later box overlapping a surviving block member —
  the O(N²) work rides the VPU in 8×128 tiles, and the unavoidable
  greedy serialization is only O(N) scalar steps instead of O(N²).

Layout notes (TPU tiling): boxes are carried as (8, N) — four coordinate
sublanes + area + three padding sublanes — so the lane dimension is the
box index and every slab op is natively tiled; a (N, 4) layout would
waste 32× VMEM in lane padding.

Semantics identical to ``ops.nms.nms_mask`` (validated against it and the
numpy oracle in tests/test_pallas_nms.py): invalid boxes neither survive
nor suppress; returns a keep mask over the *sorted* input.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

BLOCK = 128


def _nms_kernel(
    boxes_ref,
    keep_in_ref,
    keep_ref,
    kept_ref,
    *,
    thresh: float,
    n: int,
    chunk: int,
    max_keep: int,
):
    """boxes_ref: (8, N) [x1, y1, x2, y2, area, pad...]; keep_ref: (1, N)
    f32 output aliased onto ``keep_in_ref`` (the validity mask) — arrives
    as validity, leaves as the keep mask.  ``chunk`` (divides N) is the
    lane width of the cross-block suppression slabs: only chunks at or
    after the current block are visited, so the O(N²) IoU work drops to
    the ~N²/2 upper triangle that can actually suppress.

    ``max_keep`` ≤ 0 runs the full greedy scan.  When > 0, the heavy
    cross-block chunk sweep collapses to an empty loop (its upper bound
    drops to ``first_chunk`` via the SMEM survivor counter ``kept_ref``)
    once ≥ ``max_keep`` boxes have survived: in descending-score order
    every survivor past that point ranks below the first ``max_keep``
    survivors, so a caller that keeps only the top ``max_keep``
    survivors (ops.nms.nms) sees identical results.  The mask beyond the
    stopping point is NOT a valid full NMS mask — truncated-exactness
    only.  (Mosaic cannot nest the vector-carry fixpoint inside a
    while/cond region, so the sweep itself stays an unconditional fori
    and only the chunk loop's dynamic bound is gated — the per-block
    128×128 fixpoint that still runs is ~2% of the skipped slab work.)"""
    keep_ref[:, :] = keep_in_ref[:, :]
    kept_ref[0] = 0.0
    n_blocks = n // BLOCK
    lane_c = jax.lax.broadcasted_iota(jnp.int32, (1, chunk), 1)    # (1,C)

    def iou_slab(blk, blk_area, allx, all_area):
        """IoU of a (8, BLOCK) block vs (8, M) boxes → (BLOCK, M)."""
        # transpose block coords into the sublane dim: (BLOCK, 1) each
        bx1 = blk[0:1, :].reshape(BLOCK, 1)
        by1 = blk[1:2, :].reshape(BLOCK, 1)
        bx2 = blk[2:3, :].reshape(BLOCK, 1)
        by2 = blk[3:4, :].reshape(BLOCK, 1)
        ba = blk_area.reshape(BLOCK, 1)
        iw = jnp.minimum(bx2, allx[2:3, :]) - jnp.maximum(bx1, allx[0:1, :]) + 1.0
        ih = jnp.minimum(by2, allx[3:4, :]) - jnp.maximum(by1, allx[1:2, :]) + 1.0
        inter = jnp.maximum(iw, 0.0) * jnp.maximum(ih, 0.0)        # (BLOCK, M)
        union = ba + all_area - inter
        return inter / jnp.maximum(union, 1e-12)

    def outer(j, _):
        start = pl.multiple_of(j * BLOCK, BLOCK)
        blk = boxes_ref[:, pl.ds(start, BLOCK)]                    # (8,128)
        blk_area = blk[4:5, :]                                     # (1,128)
        valid_row = keep_ref[:, pl.ds(start, BLOCK)]               # (1,128) f32

        # Intra-block greedy via synchronous fixpoint iteration instead of
        # a 128-step scalar scan (TPU scalar-loop overhead is ~µs/step —
        # the scan was the whole kernel's cost).  Iterating
        #   alive_i ← valid_i ∧ ¬∃j<i (alive_j ∧ iou_ji > t)
        # is exact once iteration count ≥ the longest suppression-
        # dependency chain (each pass finalizes one more DAG level), and
        # the while_loop stops at the first unchanged pass — typically
        # 3-6 vectorized (128×128) VPU steps.
        sub = jax.lax.broadcasted_iota(jnp.int32, (BLOCK, BLOCK), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (BLOCK, BLOCK), 1)
        iou_b = iou_slab(blk, blk_area, blk, blk_area)
        kill_edge = jnp.where((iou_b > thresh) & (sub < col), 1.0, 0.0)

        def fix_cond(carry):
            return carry[1]

        def fix_body(carry):
            alive_col, _ = carry
            killed = jnp.max(kill_edge * alive_col, axis=0, keepdims=True)
            new_row = jnp.where(killed > 0.5, 0.0, valid_row)      # (1,128)
            new_col = new_row.reshape(BLOCK, 1)
            return new_col, jnp.any(new_col != alive_col)

        alive_col, _ = jax.lax.while_loop(
            fix_cond, fix_body, (valid_row.reshape(BLOCK, 1), True)
        )
        alive = alive_col.reshape(1, BLOCK)
        keep_ref[:, pl.ds(start, BLOCK)] = alive

        # cross-block: surviving block members kill all later overlaps.
        # Visit only chunks containing boxes after this block — the
        # first such chunk may straddle the block, so the in-chunk
        # ``later`` lane mask protects its leading boxes.
        alive_col2 = alive.reshape(BLOCK, 1) > 0.5

        def chunk_body(kc, _):
            cstart = pl.multiple_of(kc * chunk, chunk)
            cbox = boxes_ref[:, pl.ds(cstart, chunk)]              # (8,C)
            iou_c = iou_slab(blk, blk_area, cbox, cbox[4:5, :])
            killed = jnp.max(
                jnp.where((iou_c > thresh) & alive_col2, 1.0, 0.0),
                axis=0,
                keepdims=True,
            )                                                      # (1,C)
            later = (cstart + lane_c) >= (start + BLOCK)
            cur = keep_ref[:, pl.ds(cstart, chunk)]
            keep_ref[:, pl.ds(cstart, chunk)] = jnp.where(
                later & (killed > 0.5), 0.0, cur
            )
            return 0

        first_chunk = (start + BLOCK) // chunk
        hi = n // chunk
        if max_keep > 0:
            # enough survivors → empty chunk loop from here on; the
            # counter only grows, so once collapsed it stays collapsed
            hi = jnp.where(kept_ref[0] < float(max_keep), hi, first_chunk)
        jax.lax.fori_loop(first_chunk, hi, chunk_body, 0)
        # re-read the block's final mask from VMEM for the survivor
        # count: summing the while-carry vector directly trips a Mosaic
        # relayout bug (replicated-offset carry → scalar reduce)
        alive_mem = keep_ref[:, pl.ds(start, BLOCK)]
        kept_ref[0] = kept_ref[0] + jnp.sum(alive_mem)
        return 0

    jax.lax.fori_loop(0, n_blocks, outer, 0)


@partial(jax.jit, static_argnames=("thresh", "interpret", "max_keep"))
def nms_mask_sorted_pallas(
    boxes: jnp.ndarray,
    valid: jnp.ndarray,
    thresh: float,
    interpret: bool = False,
    max_keep: int = 0,
) -> jnp.ndarray:
    """Keep mask for (N, 4) boxes ALREADY sorted by descending score.

    ``valid`` (N,) bool marks real rows.  N is padded to a lane multiple
    internally; returns (N,) bool.  ``interpret=True`` runs the kernel in
    the Pallas interpreter (CPU tests).  ``max_keep`` > 0 enables the
    early-exit sweep: the mask is only exact for selecting the top
    ``max_keep`` survivors by score (see the kernel docstring).
    """
    n = boxes.shape[0]
    n_pad = ((n + BLOCK - 1) // BLOCK) * BLOCK
    # cross-block slab lane width: the largest candidate whose padding
    # waste stays ≤ 12.5% of the block-padded N (a fixed 2048 would pad
    # the default test shape 6016 → 8192, +36% slab area; 1536 pads it
    # to 6144, +2%).  BLOCK always divides n_pad, so the loop terminates.
    for chunk in (2048, 1536, 1024, 512, 256, BLOCK):
        padded = ((n_pad + chunk - 1) // chunk) * chunk
        if chunk <= n_pad and padded - n_pad <= n_pad // 8:
            break
    n_pad = ((n_pad + chunk - 1) // chunk) * chunk
    coords = jnp.zeros((8, n_pad), jnp.float32)
    bt = boxes.astype(jnp.float32).T                               # (4, N)
    coords = coords.at[0:4, :n].set(bt)
    area = (bt[2] - bt[0] + 1.0) * (bt[3] - bt[1] + 1.0)
    coords = coords.at[4, :n].set(area)
    keep0 = jnp.zeros((1, n_pad), jnp.float32).at[0, :n].set(
        valid.astype(jnp.float32)
    )

    keep = pl.pallas_call(
        partial(
            _nms_kernel,
            thresh=float(thresh),
            n=n_pad,
            chunk=chunk,
            max_keep=int(max_keep),
        ),
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        input_output_aliases={1: 0},
        scratch_shapes=[pltpu.SMEM((1,), jnp.float32)],
        interpret=interpret,
    )(coords, keep0)
    return keep[0, :n] > 0.5


def nms_mask_pallas(
    boxes: jnp.ndarray,
    scores: jnp.ndarray,
    thresh: float,
    valid: jnp.ndarray | None = None,
    interpret: bool = False,
) -> jnp.ndarray:
    """Drop-in twin of ``ops.nms.nms_mask`` backed by the Pallas kernel:
    sorts by score, runs the kernel, scatters back to input order."""
    n = boxes.shape[0]
    if valid is None:
        valid = jnp.ones((n,), dtype=bool)
    scores = jnp.where(valid, scores, -jnp.inf)
    order = jnp.argsort(-scores)
    keep_sorted = nms_mask_sorted_pallas(
        boxes[order], valid[order], thresh, interpret
    )
    return jnp.zeros((n,), bool).at[order].set(keep_sorted)
