"""Box geometry: IoU, encode/decode, clipping.

Reference: ``rcnn/processing/bbox_transform.py`` (``nonlinear_transform``,
``nonlinear_pred``, ``clip_boxes``) and the Cython hot loop
``rcnn/cython/bbox.pyx :: bbox_overlaps_cython``.  The Cython O(N*K) loop
becomes a single broadcast expression — XLA vectorizes it onto the VPU/MXU
with no native code needed.  All functions are jittable, shape-polymorphic
at trace time, and keep the legacy +1 width convention of the reference so
goldens match.
"""

from __future__ import annotations

import jax.numpy as jnp

# guard against exp() overflow on garbage deltas of padded boxes
_BBOX_XFORM_CLIP = 4.135166556742356  # log(1000 / 16)


def bbox_overlaps(boxes: jnp.ndarray, query_boxes: jnp.ndarray) -> jnp.ndarray:
    """IoU matrix between (N, 4) and (K, 4) boxes → (N, K) float32.

    Reference: ``rcnn/cython/bbox.pyx :: bbox_overlaps_cython``.
    """
    boxes = boxes.astype(jnp.float32)
    query_boxes = query_boxes.astype(jnp.float32)
    bx1, by1, bx2, by2 = jnp.split(boxes[:, :4], 4, axis=1)        # (N,1)
    qx1, qy1, qx2, qy2 = (query_boxes[:, i] for i in range(4))     # (K,)

    iw = jnp.minimum(bx2, qx2[None, :]) - jnp.maximum(bx1, qx1[None, :]) + 1.0
    ih = jnp.minimum(by2, qy2[None, :]) - jnp.maximum(by1, qy1[None, :]) + 1.0
    inter = jnp.maximum(iw, 0.0) * jnp.maximum(ih, 0.0)            # (N,K)

    area_b = (bx2 - bx1 + 1.0) * (by2 - by1 + 1.0)                 # (N,1)
    area_q = (qx2 - qx1 + 1.0) * (qy2 - qy1 + 1.0)                 # (K,)
    union = area_b + area_q[None, :] - inter
    return inter / jnp.maximum(union, 1e-12)


def bbox_transform(ex_rois: jnp.ndarray, gt_rois: jnp.ndarray) -> jnp.ndarray:
    """Encode gt boxes w.r.t. example rois → (N, 4) [dx, dy, dw, dh].

    Reference: ``rcnn/processing/bbox_transform.py :: nonlinear_transform``.
    """
    ex_w = ex_rois[:, 2] - ex_rois[:, 0] + 1.0
    ex_h = ex_rois[:, 3] - ex_rois[:, 1] + 1.0
    ex_cx = ex_rois[:, 0] + 0.5 * (ex_w - 1.0)
    ex_cy = ex_rois[:, 1] + 0.5 * (ex_h - 1.0)

    gt_w = gt_rois[:, 2] - gt_rois[:, 0] + 1.0
    gt_h = gt_rois[:, 3] - gt_rois[:, 1] + 1.0
    gt_cx = gt_rois[:, 0] + 0.5 * (gt_w - 1.0)
    gt_cy = gt_rois[:, 1] + 0.5 * (gt_h - 1.0)

    dx = (gt_cx - ex_cx) / (ex_w + 1e-14)
    dy = (gt_cy - ex_cy) / (ex_h + 1e-14)
    dw = jnp.log(jnp.maximum(gt_w, 1.0) / jnp.maximum(ex_w, 1e-14))
    dh = jnp.log(jnp.maximum(gt_h, 1.0) / jnp.maximum(ex_h, 1e-14))
    return jnp.stack([dx, dy, dw, dh], axis=1)


def bbox_pred(boxes: jnp.ndarray, box_deltas: jnp.ndarray) -> jnp.ndarray:
    """Decode (N, 4K) deltas against (N, 4) boxes → (N, 4K) predicted boxes.

    Reference: ``rcnn/processing/bbox_transform.py :: nonlinear_pred``.
    Class-agnostic (K=1) and class-specific (K=num_classes) layouts both
    flow through the same reshape.
    """
    n = boxes.shape[0]
    k4 = box_deltas.shape[1]
    widths = boxes[:, 2] - boxes[:, 0] + 1.0
    heights = boxes[:, 3] - boxes[:, 1] + 1.0
    ctr_x = boxes[:, 0] + 0.5 * (widths - 1.0)
    ctr_y = boxes[:, 1] + 0.5 * (heights - 1.0)

    deltas = box_deltas.reshape(n, -1, 4)
    dx, dy = deltas[..., 0], deltas[..., 1]
    dw = jnp.minimum(deltas[..., 2], _BBOX_XFORM_CLIP)
    dh = jnp.minimum(deltas[..., 3], _BBOX_XFORM_CLIP)

    pred_cx = dx * widths[:, None] + ctr_x[:, None]
    pred_cy = dy * heights[:, None] + ctr_y[:, None]
    pred_w = jnp.exp(dw) * widths[:, None]
    pred_h = jnp.exp(dh) * heights[:, None]

    out = jnp.stack(
        [
            pred_cx - 0.5 * (pred_w - 1.0),
            pred_cy - 0.5 * (pred_h - 1.0),
            pred_cx + 0.5 * (pred_w - 1.0),
            pred_cy + 0.5 * (pred_h - 1.0),
        ],
        axis=-1,
    )
    return out.reshape(n, k4)


def clip_boxes(boxes: jnp.ndarray, im_shape) -> jnp.ndarray:
    """Clip (N, 4K) boxes into the image: x∈[0, W-1], y∈[0, H-1].

    Reference: ``rcnn/processing/bbox_transform.py :: clip_boxes``.
    ``im_shape`` is (height, width) — scalars or traced values.
    """
    h, w = im_shape[0], im_shape[1]
    n = boxes.shape[0]
    b = boxes.reshape(n, -1, 4)
    x1 = jnp.clip(b[..., 0], 0.0, w - 1.0)
    y1 = jnp.clip(b[..., 1], 0.0, h - 1.0)
    x2 = jnp.clip(b[..., 2], 0.0, w - 1.0)
    y2 = jnp.clip(b[..., 3], 0.0, h - 1.0)
    return jnp.stack([x1, y1, x2, y2], axis=-1).reshape(boxes.shape)
