"""Mask target rasterization (Mask R-CNN extension).

No reference twin (the MXNet reference has no mask path; SURVEY N5 covers
only the eval-side RLE API).  Targets are produced fully in-graph on
fixed shapes: for each roi, the matched gt region is rasterized onto the
roi's S×S grid by cell-center inclusion testing — the box-mask special
case of the general "crop gt mask to roi and resize" op.  Polygon/RLE gt
masks plug in upstream by rasterizing to boxes' bitmaps on host and
passing them through the same crop-resize (future work, gated on real
COCO masks being on disk).
"""

from __future__ import annotations

import jax.numpy as jnp


def rasterize_box_masks(
    rois: jnp.ndarray, gt_boxes: jnp.ndarray, size: int
) -> jnp.ndarray:
    """(R, 4) rois × (R, 4) matched gt boxes → (R, S, S) {0,1} targets.

    Cell (i, j) of a roi's S×S grid is foreground iff its center lies
    inside the matched gt box (the intersection rasterized in roi
    coordinates).
    """
    x1, y1, x2, y2 = rois[:, 0], rois[:, 1], rois[:, 2], rois[:, 3]
    # +1 pixel convention ([0, 13] covers 14 pixels), cell centers offset
    # -0.5 so integer coordinates are pixel centers
    w = jnp.maximum(x2 - x1 + 1.0, 1.0)
    h = jnp.maximum(y2 - y1 + 1.0, 1.0)
    fr = (jnp.arange(size, dtype=jnp.float32) + 0.5) / size
    cx = x1[:, None] + fr[None, :] * w[:, None] - 0.5
    cy = y1[:, None] + fr[None, :] * h[:, None] - 0.5
    inside_x = (cx >= gt_boxes[:, None, 0]) & (cx <= gt_boxes[:, None, 2])
    inside_y = (cy >= gt_boxes[:, None, 1]) & (cy <= gt_boxes[:, None, 3])
    return (inside_y[:, :, None] & inside_x[:, None, :]).astype(jnp.float32)
