"""Mask target rasterization (Mask R-CNN extension).

No reference twin (the MXNet reference has no mask path; SURVEY N5 covers
only the eval-side RLE API — ``rcnn/pycocotools/maskApi.c`` lineage).
Targets are produced fully in-graph on fixed shapes, from two sources:

- ``rasterize_box_masks``: the box-mask special case (gt mask == gt
  rectangle) used by box-only datasets — cell-center inclusion testing.
- ``crop_resize_masks``: the general polygon/RLE path.  Host code
  rasterizes each gt's polygons ONCE into a small gt-box-frame bitmap
  (``data/masks.py``, M×M, default 64); in-graph, each roi's S×S target
  is a bilinear resample of its matched gt bitmap under the roi grid.
  The bilinear sample separates per axis, so the whole op is two small
  matmuls per roi — (S, M) @ (M, M) @ (M, S) — batched over rois, which
  XLA tiles straight onto the MXU instead of 2·S·S gathers.
"""

from __future__ import annotations

import jax.numpy as jnp


def rasterize_box_masks(
    rois: jnp.ndarray, gt_boxes: jnp.ndarray, size: int
) -> jnp.ndarray:
    """(R, 4) rois × (R, 4) matched gt boxes → (R, S, S) {0,1} targets.

    Cell (i, j) of a roi's S×S grid is foreground iff its center lies
    inside the matched gt box (the intersection rasterized in roi
    coordinates).
    """
    x1, y1, x2, y2 = rois[:, 0], rois[:, 1], rois[:, 2], rois[:, 3]
    # +1 pixel convention ([0, 13] covers 14 pixels), cell centers offset
    # -0.5 so integer coordinates are pixel centers
    w = jnp.maximum(x2 - x1 + 1.0, 1.0)
    h = jnp.maximum(y2 - y1 + 1.0, 1.0)
    fr = (jnp.arange(size, dtype=jnp.float32) + 0.5) / size
    cx = x1[:, None] + fr[None, :] * w[:, None] - 0.5
    cy = y1[:, None] + fr[None, :] * h[:, None] - 0.5
    inside_x = (cx >= gt_boxes[:, None, 0]) & (cx <= gt_boxes[:, None, 2])
    inside_y = (cy >= gt_boxes[:, None, 1]) & (cy <= gt_boxes[:, None, 3])
    return (inside_y[:, :, None] & inside_x[:, None, :]).astype(jnp.float32)


def _axis_weights(centers: jnp.ndarray, box_lo, box_span, m: int) -> jnp.ndarray:
    """Bilinear weight matrix for one axis: (R, S) image-space cell
    centers → (R, S, M) weights over the matched gt bitmap's M cells.

    The gt bitmap covers the gt box ([lo, lo+span-1] in image pixels,
    +1 convention) with M cells; a center maps to continuous bitmap
    coordinate u ∈ [-0.5, M-0.5] and takes hat-function weights
    relu(1 - |u - m|).  Centers outside the box fade to zero weight —
    the zero-padding convention (nothing of the gt exists there).
    """
    u = (centers - box_lo[:, None]) / box_span[:, None] * m - 0.5   # (R, S)
    idx = jnp.arange(m, dtype=jnp.float32)                          # (M,)
    return jnp.maximum(0.0, 1.0 - jnp.abs(u[:, :, None] - idx))     # (R, S, M)


def crop_resize_masks(
    rois: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_masks: jnp.ndarray,
    size: int,
) -> jnp.ndarray:
    """(R, 4) rois × (R, 4) matched gt boxes × (R, M, M) matched gt-frame
    bitmaps → (R, S, S) soft targets in [0, 1].

    ``gt_masks[r]`` is the r-th roi's matched gt rasterized over its OWN
    box (row m covers the gt's y-extent, col n its x-extent — the
    ``data/masks.py`` layout).  Each roi cell center is mapped into that
    frame and bilinearly sampled; callers binarize at 0.5 (the standard
    Mask R-CNN target convention).  All shapes static; everything is
    batched matmuls.

    Coordinates: boxes carry inclusive pixel indices (x2 = last pixel,
    +1 width convention); the bitmap lives in CONTINUOUS space where
    pixel p covers [p, p+1) — poly_fill's convention — so a roi cell
    center's continuous coordinate is ``x1 + fr·w`` (its pixel-center
    form ``x1 + fr·w − 0.5`` shifted by the half-pixel).
    """
    x1, y1, x2, y2 = rois[:, 0], rois[:, 1], rois[:, 2], rois[:, 3]
    w = jnp.maximum(x2 - x1 + 1.0, 1.0)
    h = jnp.maximum(y2 - y1 + 1.0, 1.0)
    fr = (jnp.arange(size, dtype=jnp.float32) + 0.5) / size         # (S,)
    cx = x1[:, None] + fr[None, :] * w[:, None]                     # (R, S)
    cy = y1[:, None] + fr[None, :] * h[:, None]

    gx1, gy1, gx2, gy2 = (gt_boxes[:, i] for i in range(4))
    gw = jnp.maximum(gx2 - gx1 + 1.0, 1.0)
    gh = jnp.maximum(gy2 - gy1 + 1.0, 1.0)
    m = gt_masks.shape[-1]
    wy = _axis_weights(cy, gy1, gh, m)                              # (R, S, M)
    wx = _axis_weights(cx, gx1, gw, m)                              # (R, S, M)
    masks = gt_masks.astype(jnp.float32)                            # (R, M, M)
    rows = jnp.einsum("rym,rmn->ryn", wy, masks)                    # (R, S, M)
    return jnp.einsum("ryn,rxn->ryx", rows, wx)                     # (R, S, S)
