"""In-graph target assignment: RPN anchor targets and RCNN roi sampling.

Reference: ``rcnn/io/rpn.py :: assign_anchor`` (host numpy, per image, in
the data loader) and ``rcnn/symbol/proposal_target.py`` +
``rcnn/io/rcnn.py :: sample_rois`` (host numpy via a CustomOp callback
*inside* the GPU graph — the reference's biggest perf wart, SURVEY §4.5).

Here both run inside jit on fixed shapes: gt boxes arrive padded to
``MAX_GT_BOXES`` with a validity mask, subsampling uses ``jax.random``
(reproducible, device-side), and "choose K of M at random" becomes
"rank random priorities, keep the top K" — identical distribution, static
shapes.  Known, documented deviations from the reference:

- the per-gt-argmax fg rule only fires for gts with positive best overlap
  (the reference's ``overlaps == gt_max`` quirk marks *every* anchor fg
  for a gt with zero overlap everywhere);
- when fewer than ``BATCH_ROIS`` fg+bg candidates exist (pathological,
  e.g. tiny unit tests), remaining slots are filled with zero-weight
  ignore rois instead of the reference's sample-with-replacement padding.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.ops.boxes import bbox_overlaps, bbox_transform

_BIG = 1e9


def _random_keep_k(key, candidate_mask: jnp.ndarray, k) -> jnp.ndarray:
    """Keep a uniformly-random size-``min(k, n_candidates)`` subset.

    Returns a bool mask.  ``k`` may be a traced scalar.
    Ranks candidates by iid uniforms; non-candidates rank last.
    """
    n = candidate_mask.shape[0]
    priority = jax.random.uniform(key, (n,)) - (~candidate_mask) * 2.0
    # rank[i] = position of i in descending priority order
    order = jnp.argsort(-priority)
    rank = jnp.zeros((n,), jnp.int32).at[order].set(jnp.arange(n, dtype=jnp.int32))
    return candidate_mask & (rank < k)


def bbox_denorm_vectors(cfg: Config, num_classes: int):
    """(4K,) de-normalization (means, stds) for test-time delta decode.

    The per-class tables flatten class-major — exactly the 4K
    class-specific layout ``sample_rois`` emits — so test forwards can
    keep their single elementwise multiply-add regardless of whether
    normalization was class-agnostic (end2end convention) or per-class
    (``add_bbox_regression_targets`` precomputed-stats parity).
    """
    t = cfg.TRAIN
    if t.BBOX_STDS_PER_CLASS is not None:
        means = jnp.asarray(t.BBOX_MEANS_PER_CLASS, jnp.float32).reshape(-1)
        stds = jnp.asarray(t.BBOX_STDS_PER_CLASS, jnp.float32).reshape(-1)
        assert means.shape == (4 * num_classes,), (
            f"per-class bbox stats shape {means.shape} != K={num_classes}"
        )
        return means, stds
    return (
        jnp.tile(jnp.asarray(t.BBOX_MEANS, jnp.float32), num_classes),
        jnp.tile(jnp.asarray(t.BBOX_STDS, jnp.float32), num_classes),
    )


class AnchorTargets(NamedTuple):
    labels: jnp.ndarray        # (N,) int32: 1 fg / 0 bg / -1 ignore
    bbox_targets: jnp.ndarray  # (N, 4) float32
    bbox_weights: jnp.ndarray  # (N, 4) float32


def assign_anchor(
    anchors: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    im_info: jnp.ndarray,
    key: jax.Array,
    cfg: Config,
    allowed_border: float = 0.0,
) -> AnchorTargets:
    """RPN anchor target assignment for one image, fully in-graph.

    ``anchors`` (N, 4) static table; ``gt_boxes`` (G, 4) padded;
    ``gt_valid`` (G,) mask; ``im_info`` = (h, w, scale) of the *unpadded*
    image.  Semantics follow ``rcnn/io/rpn.py :: assign_anchor``: only
    anchors inside the image participate; fg = per-gt best anchors plus
    IoU ≥ RPN_POSITIVE_OVERLAP; bg = IoU < RPN_NEGATIVE_OVERLAP; subsample
    to RPN_FG_FRACTION·RPN_BATCH_SIZE fg and the remainder bg.
    """
    t = cfg.TRAIN
    n = anchors.shape[0]
    h, w = im_info[0], im_info[1]

    inside = (
        (anchors[:, 0] >= -allowed_border)
        & (anchors[:, 1] >= -allowed_border)
        & (anchors[:, 2] < w + allowed_border)
        & (anchors[:, 3] < h + allowed_border)
    )

    overlaps = bbox_overlaps(anchors, gt_boxes[:, :4])          # (N, G)
    overlaps = jnp.where(gt_valid[None, :], overlaps, -1.0)
    overlaps = jnp.where(inside[:, None], overlaps, -1.0)
    max_ov = overlaps.max(axis=1)                               # (N,)
    argmax_gt = overlaps.argmax(axis=1)                         # (N,)
    gt_max_ov = overlaps.max(axis=0)                            # (G,)

    # per-gt best anchors (ties included), only for gts that touch anything
    is_gt_best = (
        (overlaps == gt_max_ov[None, :]) & (gt_max_ov[None, :] > 0) & gt_valid[None, :]
    ).any(axis=1)

    fg = inside & (is_gt_best | (max_ov >= t.RPN_POSITIVE_OVERLAP))
    bg = inside & (max_ov < t.RPN_NEGATIVE_OVERLAP) & ~fg
    if t.RPN_CLOBBER_POSITIVES:
        bg = inside & (max_ov < t.RPN_NEGATIVE_OVERLAP)
        fg = fg & ~bg

    k_fg, k_bg = jax.random.split(key)
    num_fg = int(t.RPN_FG_FRACTION * t.RPN_BATCH_SIZE)
    fg = _random_keep_k(k_fg, fg, num_fg)
    bg = _random_keep_k(k_bg, bg, t.RPN_BATCH_SIZE - fg.sum())

    labels = jnp.where(fg, 1, jnp.where(bg, 0, -1)).astype(jnp.int32)

    targets = bbox_transform(anchors, gt_boxes[argmax_gt, :4])
    targets = jnp.where(fg[:, None], targets, 0.0)
    weights = jnp.where(
        fg[:, None], jnp.asarray(t.RPN_BBOX_WEIGHTS, jnp.float32)[None, :], 0.0
    )
    return AnchorTargets(labels, targets.astype(jnp.float32), weights)


class RoiSamples(NamedTuple):
    rois: jnp.ndarray          # (R, 4) float32, image coords
    labels: jnp.ndarray        # (R,) int32: class id, 0 = bg, -1 = ignore
    bbox_targets: jnp.ndarray  # (R, 4K) class-specific layout
    bbox_weights: jnp.ndarray  # (R, 4K)
    gt_index: jnp.ndarray      # (R,) int32: matched gt slot (the SAME
    #   assignment the label/bbox targets came from — mask targets must
    #   reuse it, not re-derive a fresh best-IoU argmax, or a roi labeled
    #   class A can be trained on a mask cropped from a different gt)


def sample_rois(
    rois: jnp.ndarray,
    rois_valid: jnp.ndarray,
    gt_boxes: jnp.ndarray,
    gt_valid: jnp.ndarray,
    key: jax.Array,
    cfg: Config,
) -> RoiSamples:
    """Sample BATCH_ROIS proposals for the RCNN head, fully in-graph.

    ``rois`` (P, 4) padded proposals; ``gt_boxes`` (G, 5) padded
    [x1, y1, x2, y2, cls].  Follows
    ``rcnn/io/rcnn.py :: sample_rois``: gt boxes are appended to the
    proposal set (so every gt is a candidate roi), fg = IoU ≥ FG_THRESH
    sampled to FG_FRACTION·BATCH_ROIS, bg = IoU ∈ [BG_THRESH_LO,
    BG_THRESH_HI) fills the rest; bbox targets are class-specific 4K
    layout normalized by BBOX_MEANS/STDS
    (``rcnn/processing/bbox_regression.py :: expand_bbox_regression_targets``).
    """
    t = cfg.TRAIN
    num_classes = cfg.dataset.NUM_CLASSES
    r_out = t.BATCH_ROIS

    # append gt boxes to the candidate pool (reference does exactly this)
    cand = jnp.concatenate([rois[:, :4], gt_boxes[:, :4]], axis=0)       # (P+G, 4)
    cand_valid = jnp.concatenate([rois_valid, gt_valid], axis=0)
    p = cand.shape[0]

    overlaps = bbox_overlaps(cand, gt_boxes[:, :4])                       # (P+G, G)
    overlaps = jnp.where(gt_valid[None, :], overlaps, -1.0)
    max_ov = overlaps.max(axis=1)
    argmax_gt = overlaps.argmax(axis=1)
    cls_of = gt_boxes[argmax_gt, 4].astype(jnp.int32)

    fg_cand = cand_valid & (max_ov >= t.FG_THRESH)
    bg_cand = (
        cand_valid & (max_ov < t.BG_THRESH_HI) & (max_ov >= t.BG_THRESH_LO) & ~fg_cand
    )

    k_fg, k_bg, k_tie = jax.random.split(key, 3)
    num_fg = int(round(t.FG_FRACTION * r_out))
    fg_sel = _random_keep_k(k_fg, fg_cand, num_fg)
    bg_sel = _random_keep_k(k_bg, bg_cand, r_out - fg_sel.sum())

    # pack: fg first, then bg, then ignore padding — fixed R_out rows.
    # LOAD-BEARING ordering: the Mask R-CNN branch (models/fpn.py::
    # _mask_loss) runs on only the first FG_FRACTION·BATCH_ROIS slots,
    # relying on every fg roi landing in that prefix
    sel_priority = jnp.where(fg_sel, 2.0 * _BIG, 0.0) + jnp.where(bg_sel, _BIG, 0.0)
    sel_priority = sel_priority + jax.random.uniform(k_tie, (p,))
    if p < r_out:  # static: fewer candidates than the roi budget (tiny tests)
        pad = r_out - p
        sel_priority = jnp.concatenate([sel_priority, jnp.full((pad,), -_BIG)])
        cand = jnp.concatenate([cand, jnp.zeros((pad, 4), cand.dtype)])
        fg_sel = jnp.concatenate([fg_sel, jnp.zeros((pad,), bool)])
        bg_sel = jnp.concatenate([bg_sel, jnp.zeros((pad,), bool)])
        cls_of = jnp.concatenate([cls_of, jnp.zeros((pad,), jnp.int32)])
        argmax_gt = jnp.concatenate([argmax_gt, jnp.zeros((pad,), argmax_gt.dtype)])
    _, idx = jax.lax.top_k(sel_priority, r_out)
    picked_fg = fg_sel[idx]
    picked_bg = bg_sel[idx]

    out_rois = cand[idx]
    labels = jnp.where(
        picked_fg, cls_of[idx], jnp.where(picked_bg, 0, -1)
    ).astype(jnp.int32)

    # bbox regression targets, normalized then expanded to 4K layout;
    # per-class tables (the reference's precomputed-normalization path)
    # override the class-agnostic vectors when present
    raw = bbox_transform(out_rois, gt_boxes[argmax_gt[idx], :4])
    if t.BBOX_STDS_PER_CLASS is not None:
        means_t = jnp.asarray(t.BBOX_MEANS_PER_CLASS, jnp.float32)   # (K, 4)
        stds_t = jnp.asarray(t.BBOX_STDS_PER_CLASS, jnp.float32)
        means = means_t[jnp.clip(labels, 0)]                         # (R, 4)
        stds = stds_t[jnp.clip(labels, 0)]
        raw = (raw - means) / stds
    else:
        means = jnp.asarray(t.BBOX_MEANS, jnp.float32)
        stds = jnp.asarray(t.BBOX_STDS, jnp.float32)
        raw = (raw - means[None, :]) / stds[None, :]
    raw = jnp.where(picked_fg[:, None], raw, 0.0)

    cls_onehot = jax.nn.one_hot(
        jnp.clip(labels, 0), num_classes, dtype=jnp.float32
    ) * picked_fg[:, None]                                                # (R, K)
    bbox_targets = (cls_onehot[:, :, None] * raw[:, None, :]).reshape(r_out, -1)
    bbox_weights = jnp.repeat(cls_onehot, 4, axis=1)
    return RoiSamples(
        out_rois, labels, bbox_targets, bbox_weights,
        argmax_gt[idx].astype(jnp.int32),
    )
