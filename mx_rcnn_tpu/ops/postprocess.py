"""Device-side eval post-processing: per-class decode + NMS in one jit.

Reference: the HOST loop in ``rcnn/core/tester.py :: pred_eval`` — per
class: threshold, stack [boxes|score], ``cpu_nms``.  On a weak-host TPU
deployment that loop is the eval bottleneck twice over: the full
``(B, R, K)`` + ``(B, R, 4K)`` head outputs cross the relay (76 MB/batch
at flagship shapes), and the per-class C NMS runs K−1 times per image on
one core.  Here the whole thing is a batched device program — decode →
clip → per-class NMS (vmap over classes × images, the Pallas kernel on
TPU) — and only the per-class keep lists (≈0.5 MB/batch) come back.

Equivalence with the host path (asserted in
``tests/test_postprocess.py``): below-threshold and padding rows are
excluded BEFORE suppression (they neither survive nor suppress — same
as the host's pre-filter), and the decode → resized-clip → /scale →
original-extent-clip chain runs ON DEVICE before NMS.  The last step
matters: under the +1 pixel convention IoU is NOT scale-invariant
(areas pick up +1 at whichever scale they're measured), so suppressing
in resized coordinates would flip borderline keep decisions vs the
reference host loop — NMS must see original-space boxes, which is why
eval batches carry ``orig_hw``.
"""

from __future__ import annotations

from typing import Dict

import jax

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import batched_class_nms


def make_test_postprocess(
    cfg: Config, num_classes: int, thresh: float, max_out: int = 100
):
    """→ jittable ``fn(out, im_info, orig_hw) -> {det_boxes, det_scores,
    det_valid}`` with shapes (B, K−1, max_out, ·); class j's detections
    live at row j−1 (background has none).  Boxes are in ORIGINAL image
    coordinates (``orig_hw`` (B, 2) = pre-resize heights/widths, shipped
    by TestLoader)."""
    te = cfg.TEST

    def one_image(rois, valid, scores, deltas, info, ohw):
        r, k = scores.shape
        boxes = bbox_pred(rois, deltas)                      # (R, 4K)
        boxes = clip_boxes(boxes, (info[0], info[1]))
        boxes = clip_boxes(boxes / info[2], (ohw[0], ohw[1]))
        # foreground classes on the leading axis for the shared
        # batched per-class NMS helper
        boxes_k = boxes.reshape(r, k, 4).transpose(1, 0, 2)[1:]   # (K-1, R, 4)
        scores_k = scores.T[1:]                                   # (K-1, R)
        valid_k = valid[None, :] & (scores_k > thresh)
        return batched_class_nms(boxes_k, scores_k, te.NMS, max_out, valid_k)

    def batched(out: Dict, im_info, orig_hw):
        ob, os_, ov = jax.vmap(one_image)(
            out["rois"],
            out["roi_valid"].astype(bool),
            out["cls_prob"],
            out["bbox_deltas"],
            im_info,
            orig_hw,
        )
        return {"det_boxes": ob, "det_scores": os_, "det_valid": ov}

    return batched
