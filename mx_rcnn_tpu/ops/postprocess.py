"""Device-side eval post-processing: per-class decode + NMS in one jit.

Reference: the HOST loop in ``rcnn/core/tester.py :: pred_eval`` — per
class: threshold, stack [boxes|score], ``cpu_nms``.  On a weak-host TPU
deployment that loop is the eval bottleneck twice over: the full
``(B, R, K)`` + ``(B, R, 4K)`` head outputs cross the relay (76 MB/batch
at flagship shapes), and the per-class C NMS runs K−1 times per image on
one core.  Here the whole thing is a batched device program — decode →
clip → per-class NMS (vmap over classes × images, the Pallas kernel on
TPU) — and only the per-class keep lists (≈0.5 MB/batch) come back.

Equivalence with the host path (asserted in
``tests/test_postprocess.py``): below-threshold and padding rows are
excluded BEFORE suppression (they neither survive nor suppress — same
as the host's pre-filter), and the decode → resized-clip → /scale →
original-extent-clip chain runs ON DEVICE before NMS.  The last step
matters: under the +1 pixel convention IoU is NOT scale-invariant
(areas pick up +1 at whichever scale they're measured), so suppressing
in resized coordinates would flip borderline keep decisions vs the
reference host loop — NMS must see original-space boxes, which is why
eval batches carry ``orig_hw``.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp

from mx_rcnn_tpu.config import Config
from mx_rcnn_tpu.ops.boxes import bbox_pred, clip_boxes
from mx_rcnn_tpu.ops.nms import batched_class_nms

_NEG_INF = -1e10


def make_test_postprocess(
    cfg: Config, num_classes: int, thresh: float, max_out: int = 100,
    paste: bool = False,
):
    """→ jittable ``fn(out, im_info, orig_hw) -> {det_boxes, det_scores,
    det_valid}`` with shapes (B, K−1, max_out, ·); class j's detections
    live at row j−1 (background has none).  Boxes are in ORIGINAL image
    coordinates (``orig_hw`` (B, 2) = pre-resize heights/widths, shipped
    by TestLoader).

    Mask models: when ``out`` carries ``mask_logits`` (B, R, S, S, K),
    the same program additionally gathers — still on device — each
    surviving detection's S×S grid for its predicted class, for the
    cross-class top ``max_det = TEST.MAX_PER_IMAGE`` survivors by score
    (the per-image cap the host applies anyway in ``cap_detections``).
    Three fixed-shape outputs ride along: ``det_masks`` (B, max_det,
    S, S) float32 LOGITS (sigmoid stays host so the bits match the
    reference ``im_detect`` numpy expression exactly), ``det_mask_idx``
    (B, max_det) int32 flat index ``(class_row)*max_out + slot`` into
    the det grid (−1 on padding), and ``det_mask_valid`` (B, max_det).
    Only these come over the wire — the raw ``(R, S, S, K)`` stack never
    leaves the device.  ``max_det`` is static, so the CompileCache
    bucket ladder stays zero-recompile.

    ``paste=True`` (streaming mask serving): the program ADDITIONALLY
    pastes each survivor's grid into its box footprint on a fixed
    ``det_canvas`` (B, max_det, Hc, Wc) uint8 binary canvas, where
    (Hc, Wc) is the padded bucket extent (``batched`` gains a trailing
    ``canvas_hw`` argument, supplied by the Predictor from the traced
    image shape — one canvas shape per `(model, bucket)` rung, so the
    zero-recompile ladder is untouched).  Boxes are mapped to CANVAS
    (= resized-image) coordinates by ``im_info[2]`` and the grid is
    bilinearly resized to the box's pixel extent (floor/ceil +1
    convention of ``eval/segm.py::paste_mask``) then thresholded at
    probability 0.5 — i.e. logit 0: interpolation runs in logit space,
    where prob 0.5 is exactly the zero crossing.  All paste arithmetic
    is INTEGER fixed point (8 fractional bits on the quantized logits,
    7 on the interpolation weights — int32 throughout, no overflow by
    construction), so the device canvas is bitwise identical to the
    numpy mirror ``eval/segm.py::paste_mask_canvas`` on every backend:
    the streaming bench's RLE byte-identity bar is structural, not
    float luck."""
    te = cfg.TEST
    max_det = te.MAX_PER_IMAGE if te.MAX_PER_IMAGE > 0 \
        else (num_classes - 1) * max_out
    # the det grid only holds (K-1)*max_out candidates — a larger cap
    # would make top_k's k exceed its operand
    max_det = min(max_det, (num_classes - 1) * max_out)

    def one_image(rois, valid, scores, deltas, info, ohw):
        r, k = scores.shape
        boxes = bbox_pred(rois, deltas)                      # (R, 4K)
        boxes = clip_boxes(boxes, (info[0], info[1]))
        boxes = clip_boxes(boxes / info[2], (ohw[0], ohw[1]))
        # foreground classes on the leading axis for the shared
        # batched per-class NMS helper
        boxes_k = boxes.reshape(r, k, 4).transpose(1, 0, 2)[1:]   # (K-1, R, 4)
        scores_k = scores.T[1:]                                   # (K-1, R)
        valid_k = valid[None, :] & (scores_k > thresh)
        return batched_class_nms(
            boxes_k, scores_k, te.NMS, max_out, valid_k, with_idx=True
        )

    def one_image_masks(ob, os_, ov, oi, mask_logits):
        # (K-1, max_out) det grid → flat cross-class top-max_det by
        # score; ties break toward the lower flat index (top_k), which
        # only diverges from the host cap on exact float score ties.
        r = mask_logits.shape[0]
        flat_scores = jnp.where(ov, os_, _NEG_INF).reshape(-1)
        top_s, top_flat = jax.lax.top_k(flat_scores, max_det)
        mvalid = top_s > _NEG_INF / 2
        # survivor's source roi (per-class nms idx may exceed R on
        # padding slots — clamp before the gather) and class channel
        roi_idx = jnp.clip(oi.reshape(-1)[top_flat], 0, r - 1)
        roi_idx = jnp.where(mvalid, roi_idx, 0)
        cls = jnp.where(mvalid, top_flat // ov.shape[1] + 1, 1)
        grids = jax.vmap(lambda ri, c: mask_logits[ri, :, :, c])(
            roi_idx, cls
        )
        # large-negative logits on padding rows: padding-count invariant
        # AND safe if one ever leaks to paste (sigmoid ≈ 0, empty mask,
        # no exp overflow on host)
        grids = jnp.where(
            mvalid[:, None, None], grids, jnp.float32(-80.0)
        ).astype(jnp.float32)
        midx = jnp.where(mvalid, top_flat, -1).astype(jnp.int32)
        return grids, midx, mvalid

    def one_image_paste(ob, oi_flat, grids, mvalid, info, canvas_hw):
        # fixed-size-canvas device paste: each survivor's S×S logit
        # grid → binary mask in its box footprint on the (Hc, Wc)
        # bucket canvas.  Every arithmetic step below is mirrored
        # op-for-op by eval/segm.py::paste_mask_canvas; the bilinear
        # blend itself is int32 fixed point, so the two are bitwise
        # equal by construction (see make_test_postprocess docstring).
        hc, wc = canvas_hw
        s = grids.shape[1]
        # survivor boxes in canvas (= resized-image) coordinates:
        # original coords × im_info scale, clipped to the canvas — the
        # clip guarantees the floor/ceil footprint stays inside it
        bf = ob.reshape(-1, 4)
        box = bf[jnp.clip(oi_flat, 0, bf.shape[0] - 1)] * info[2]
        x1 = jnp.clip(box[:, 0], 0.0, wc - 1.0)
        y1 = jnp.clip(box[:, 1], 0.0, hc - 1.0)
        x2 = jnp.clip(box[:, 2], 0.0, wc - 1.0)
        y2 = jnp.clip(box[:, 3], 0.0, hc - 1.0)
        x1i = jnp.floor(x1).astype(jnp.int32)
        y1i = jnp.floor(y1).astype(jnp.int32)
        x2i = jnp.ceil(x2).astype(jnp.int32)
        y2i = jnp.ceil(y2).astype(jnp.int32)
        bw = jnp.maximum(x2i - x1i + 1, 1)
        bh = jnp.maximum(y2i - y1i + 1, 1)
        # quantize logits once: 8 fractional bits, |logit| capped at 60
        # (sigmoid there is 1 to float precision anyway) → |q| ≤ 2^14
        q = jnp.round(
            jnp.clip(grids, -60.0, 60.0) * jnp.float32(256.0)
        ).astype(jnp.int32)

        def paste_one(qd, bx1, by1, bx2, by2, bwd, bhd, ok):
            xs = jnp.arange(wc, dtype=jnp.int32)
            ys = jnp.arange(hc, dtype=jnp.int32)

            def axis(coords, lo, extent):
                # cv2-convention half-pixel source mapping dst → src,
                # border-replicate clamped; weights quantized to 7 bits
                d = (coords - lo).astype(jnp.float32)
                t = (d + jnp.float32(0.5)) * jnp.float32(s) \
                    / extent.astype(jnp.float32) - jnp.float32(0.5)
                sc = jnp.clip(t, 0.0, s - 1.0)
                i0 = jnp.floor(sc).astype(jnp.int32)
                i1 = jnp.minimum(i0 + 1, s - 1)
                w = jnp.round(
                    (sc - i0.astype(jnp.float32)) * jnp.float32(128.0)
                ).astype(jnp.int32)
                return i0, i1, w

            x0, x1b, wx = axis(xs, bx1, bwd)
            y0, y1b, wy = axis(ys, by1, bhd)
            q00 = qd[y0][:, x0]
            q01 = qd[y0][:, x1b]
            q10 = qd[y1b][:, x0]
            q11 = qd[y1b][:, x1b]
            val = (128 - wy)[:, None] * (
                (128 - wx)[None, :] * q00 + wx[None, :] * q01
            ) + wy[:, None] * (
                (128 - wx)[None, :] * q10 + wx[None, :] * q11
            )
            inside = (
                (xs >= bx1) & (xs <= bx2)
            )[None, :] & ((ys >= by1) & (ys <= by2))[:, None]
            return ((val >= 0) & inside & ok).astype(jnp.uint8)

        return jax.vmap(paste_one)(q, x1i, y1i, x2i, y2i, bw, bh, mvalid)

    def batched(out: Dict, im_info, orig_hw, canvas_hw=None):
        ob, os_, ov, oi = jax.vmap(one_image)(
            out["rois"],
            out["roi_valid"].astype(bool),
            out["cls_prob"],
            out["bbox_deltas"],
            im_info,
            orig_hw,
        )
        res = {"det_boxes": ob, "det_scores": os_, "det_valid": ov}
        if "mask_logits" in out:
            grids, midx, mvalid = jax.vmap(one_image_masks)(
                ob, os_, ov, oi, out["mask_logits"]
            )
            res["det_masks"] = grids
            res["det_mask_idx"] = midx
            res["det_mask_valid"] = mvalid
            if paste and canvas_hw is not None:
                res["det_canvas"] = jax.vmap(
                    lambda b, i, g, m, info: one_image_paste(
                        b, i, g, m, info, tuple(canvas_hw)
                    )
                )(ob, midx, grids, mvalid, im_info)
        return res

    # the Predictor passes the traced image extent as canvas_hw only to
    # postprocess closures that declare they want it
    batched.wants_canvas = bool(paste)
    return batched
