"""Deterministic synthetic load generator for the serving engine.

Closed-loop: ``concurrency`` client threads each submit a request and
block on its future before submitting the next — the standard way to
saturate a serving stack without modeling an arrival process.  All
randomness (per-request image size from a mixed-aspect menu, pixel
content) is derived from ``seed`` + request index BEFORE any thread
races, so two runs offer byte-identical traffic regardless of thread
scheduling; only timings differ.

Mixed sizes are the point: they exercise every ladder bucket and prove
(via the runner's CompileCache) that traffic never triggers a compile
after warmup.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from mx_rcnn_tpu.serve.batcher import QueueFull

# landscape / portrait / small — covers both default bucket orientations
DEFAULT_SIZES: Tuple[Tuple[int, int], ...] = (
    (480, 640),
    (640, 480),
    (300, 500),
)


def synthetic_image(index: int, h: int, w: int, seed: int = 0) -> np.ndarray:
    """Deterministic RGB noise image for request ``index``."""
    rng = np.random.RandomState((seed * 1_000_003 + index) % (2**31 - 1))
    return rng.randint(0, 256, (h, w, 3)).astype(np.float32)


#: poison_mix flavors (ISSUE 12).  The malformed three must be rejected
#: at the admission gate; "qod" is a WELL-FORMED query of death — valid
#: pixels whose digest the bench wires to a ``poison_*`` fault injector.
POISON_FLAVORS = ("qod", "nan", "empty", "objdtype")


def qod_image(h: int, w: int, seed: int = 0) -> np.ndarray:
    """The deterministic query-of-death image for size ``(h, w)``.
    Depends on (h, w, seed) only — NOT the request index — so every qod
    request of one size shares a single digest, which is what lets the
    bench compute ``request_digest(qod_image(...))`` up front and key
    its fault spec on it."""
    rng = np.random.RandomState((seed * 7_777_777 + h * 10_007 + w)
                                % (2**31 - 1))
    return rng.randint(0, 256, (h, w, 3)).astype(np.float32)


def poison_image(flavor: str, index: int, h: int, w: int,
                 seed: int = 0) -> np.ndarray:
    """Materialize one poison_mix flavor for request ``index``."""
    if flavor == "qod":
        return qod_image(h, w, seed)
    if flavor == "nan":
        im = synthetic_image(index, h, w, seed)
        im[0, 0, 0] = np.nan
        return im
    if flavor == "empty":
        return np.zeros((0, 0, 3), np.float32)
    if flavor == "objdtype":
        return np.empty((2, 2, 3), dtype=object)
    raise ValueError(f"unknown poison flavor {flavor!r}")


def run_load(
    engine,
    num_requests: int = 64,
    concurrency: int = 8,
    sizes: Sequence[Tuple[int, int]] = DEFAULT_SIZES,
    seed: int = 0,
    deadline_s: Optional[float] = None,
    queue_full_backoff: float = 0.002,
    collect: bool = False,
    models: Optional[Sequence[str]] = None,
    lanes: Optional[Sequence[Optional[str]]] = None,
    poison_mix: Optional[Sequence[Optional[str]]] = None,
) -> Dict:
    """Drive ``engine`` with ``num_requests`` synthetic images; returns a
    report dict (wall/throughput/outcome counts + the engine's metrics
    snapshot).  ``QueueFull`` is the backpressure signal — the client
    backs off and resubmits, counting the rejection.

    ``models`` (optional) assigns each request a model id drawn
    deterministically from the sequence — the multi-tenancy traffic mix.
    The draw happens from ``seed`` before any thread starts (same rng
    stream discipline as sizes), so the (index → model) mapping is
    identical across runs.

    ``lanes`` (optional) does the same for SLO classes — each request's
    lane is drawn from the sequence (``None`` entries mean "let the
    engine default", i.e. the model's registry SLO class), producing a
    deterministic mixed-lane stream.  Drawn AFTER sizes and models, so
    adding lanes to an existing scenario leaves its size/model streams
    unchanged.  Per-lane outcome counts land under
    ``report["lane_outcomes"]``.

    ``poison_mix`` (optional) draws each request's poison flavor from
    the sequence the same way (``None`` entries mean healthy traffic —
    e.g. ``[None]*19 + ["qod"]`` is a ~5% poison mix).  Flavors are the
    :data:`POISON_FLAVORS`: the malformed three must be rejected at the
    engine's admission gate, while ``"qod"`` submits the deterministic
    :func:`qod_image` whose digest a fault spec can target.  Drawn AFTER
    lanes so existing scenarios keep their streams.  Per-flavor outcome
    counts land under ``report["poison_outcomes"]``.

    ``collect=True`` additionally stores each request's resolution under
    ``report["_results"]`` — ``{index: ("ok", detections) | (kind, repr)}``
    — which is what lets a faulted run be compared byte-for-byte against
    an unfaulted one (pop the key before JSON-dumping the report), plus
    per-request submit/done monotonic timestamps under
    ``report["_times"]`` — ``{index: (t_submit, t_done)}`` — which is how
    the swap bench classifies requests as entirely-before / entirely-
    after / straddling a live swap window.  Because traffic is derived
    from ``seed + index`` alone, equal indices mean equal input images
    across runs."""
    size_rng = np.random.RandomState(seed)
    req_sizes = [
        sizes[size_rng.randint(len(sizes))] for i in range(num_requests)
    ]
    req_models = (
        [models[size_rng.randint(len(models))] for _ in range(num_requests)]
        if models else None
    )
    req_lanes = (
        [lanes[size_rng.randint(len(lanes))] for _ in range(num_requests)]
        if lanes else None
    )
    req_poison = (
        [poison_mix[size_rng.randint(len(poison_mix))]
         for _ in range(num_requests)]
        if poison_mix else None
    )
    counter = iter(range(num_requests))
    lock = threading.Lock()
    outcomes = {"ok": 0, "deadline": 0, "error": 0, "queue_full_retries": 0,
                "invalid": 0, "poison": 0, "exhausted": 0}
    lane_outcomes: Dict[str, Dict[str, int]] = {}
    poison_outcomes: Dict[str, Dict[str, int]] = {}
    results: Dict[int, Tuple[str, object]] = {}
    times: Dict[int, Tuple[float, float]] = {}

    def classify(e: BaseException) -> str:
        name = type(e).__name__
        if "InvalidRequest" in name:
            return "invalid"
        if "Poison" in name:
            return "poison"
        if "Exhausted" in name:
            return "exhausted"
        return "deadline" if "Deadline" in name else "error"

    def note(key: str, lane: Optional[str] = None,
             flavor: Optional[str] = None) -> None:
        with lock:
            outcomes[key] += 1
            if lane is not None:
                per = lane_outcomes.setdefault(
                    lane, {"ok": 0, "deadline": 0, "error": 0}
                )
                if key in per:
                    per[key] += 1
            if flavor is not None:
                pf = poison_outcomes.setdefault(flavor, {})
                pf[key] = pf.get(key, 0) + 1

    def client() -> None:
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            h, w = req_sizes[i]
            flavor = req_poison[i] if req_poison is not None else None
            if flavor is None:
                im = synthetic_image(i, h, w, seed)
            else:
                im = poison_image(flavor, i, h, w, seed)
            mkw = (
                {} if req_models is None or req_models[i] is None
                else {"model": req_models[i]}
            )
            lane = req_lanes[i] if req_lanes is not None else None
            if lane is not None:
                mkw["lane"] = lane
            t_submit = time.monotonic()
            fut = None
            while True:
                try:
                    fut = engine.submit(im, deadline_s=deadline_s, **mkw)
                    break
                except QueueFull:
                    note("queue_full_retries")
                    time.sleep(queue_full_backoff)
                except Exception as e:
                    # synchronous reject: admission gate (InvalidRequest)
                    # or quarantine fast-fail (PoisonRequest)
                    kind = classify(e)
                    note(kind, lane, flavor)
                    if collect:
                        with lock:
                            results[i] = (kind, repr(e))
                    break
            if fut is not None:
                try:
                    dets = fut.result()
                    note("ok", lane, flavor)
                    if collect:
                        with lock:
                            results[i] = ("ok", dets)
                except Exception as e:
                    kind = classify(e)
                    note(kind, lane, flavor)
                    if collect:
                        with lock:
                            results[i] = (kind, repr(e))
            if collect:
                with lock:
                    times[i] = (t_submit, time.monotonic())

    threads = [
        threading.Thread(target=client, name=f"loadgen-{t}", daemon=True)
        for t in range(max(1, concurrency))
    ]
    t0 = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0

    snap = engine.snapshot()
    report = {
        "requests": num_requests,
        "concurrency": concurrency,
        "sizes": [list(s) for s in sizes],
        "seed": seed,
        "wall_s": round(wall, 4),
        "imgs_per_sec": round(outcomes["ok"] / wall, 3) if wall else None,
        "outcomes": outcomes,
        "engine": snap,
    }
    if models:
        report["models"] = list(models)
    if lanes:
        report["lanes"] = list(lanes)
        report["lane_outcomes"] = lane_outcomes
    if poison_mix:
        report["poison_mix"] = list(poison_mix)
        report["poison_flavors"] = (
            [req_poison[i] for i in range(num_requests)]
        )
        report["poison_outcomes"] = poison_outcomes
    if collect:
        report["_results"] = results
        report["_times"] = times
    return report
