"""Deterministic synthetic load generator for the serving engine.

Closed-loop: ``concurrency`` client threads each submit a request and
block on its future before submitting the next — the standard way to
saturate a serving stack without modeling an arrival process.  All
randomness (per-request image size from a mixed-aspect menu, pixel
content) is derived from ``seed`` + request index BEFORE any thread
races, so two runs offer byte-identical traffic regardless of thread
scheduling; only timings differ.

Mixed sizes are the point: they exercise every ladder bucket and prove
(via the runner's CompileCache) that traffic never triggers a compile
after warmup.

:func:`run_stream_load` is the open-loop streaming counterpart (ISSUE
20): one client per stream submits frames in order at frame cadence
without blocking on results, so consecutive frames of one stream are in
flight together and the engine's per-stream ordering gate — not client
pacing — is what keeps delivery in order.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from mx_rcnn_tpu.serve.batcher import QueueFull

# landscape / portrait / small — covers both default bucket orientations
DEFAULT_SIZES: Tuple[Tuple[int, int], ...] = (
    (480, 640),
    (640, 480),
    (300, 500),
)


def synthetic_image(index: int, h: int, w: int, seed: int = 0) -> np.ndarray:
    """Deterministic RGB noise image for request ``index``."""
    rng = np.random.RandomState((seed * 1_000_003 + index) % (2**31 - 1))
    return rng.randint(0, 256, (h, w, 3)).astype(np.float32)


#: poison_mix flavors (ISSUE 12).  The malformed three must be rejected
#: at the admission gate; "qod" is a WELL-FORMED query of death — valid
#: pixels whose digest the bench wires to a ``poison_*`` fault injector.
POISON_FLAVORS = ("qod", "nan", "empty", "objdtype")


def qod_image(h: int, w: int, seed: int = 0) -> np.ndarray:
    """The deterministic query-of-death image for size ``(h, w)``.
    Depends on (h, w, seed) only — NOT the request index — so every qod
    request of one size shares a single digest, which is what lets the
    bench compute ``request_digest(qod_image(...))`` up front and key
    its fault spec on it."""
    rng = np.random.RandomState((seed * 7_777_777 + h * 10_007 + w)
                                % (2**31 - 1))
    return rng.randint(0, 256, (h, w, 3)).astype(np.float32)


def poison_image(flavor: str, index: int, h: int, w: int,
                 seed: int = 0) -> np.ndarray:
    """Materialize one poison_mix flavor for request ``index``."""
    if flavor == "qod":
        return qod_image(h, w, seed)
    if flavor == "nan":
        im = synthetic_image(index, h, w, seed)
        im[0, 0, 0] = np.nan
        return im
    if flavor == "empty":
        return np.zeros((0, 0, 3), np.float32)
    if flavor == "objdtype":
        return np.empty((2, 2, 3), dtype=object)
    raise ValueError(f"unknown poison flavor {flavor!r}")


def diurnal_arrivals(
    num_requests: int,
    lo_rps: float,
    hi_rps: float,
    cycles: float = 1.0,
    seed: int = 0,
) -> Tuple[float, ...]:
    """Trace-driven arrival offsets (seconds from start) following a
    diurnal ramp: the instantaneous rate sweeps sinusoidally between
    ``lo_rps`` and ``hi_rps`` over ``cycles`` full periods.  Built by
    integrating the rate curve and inverse-sampling uniform quantiles —
    fully deterministic for a given argument tuple (``seed`` only
    perturbs sub-slot jitter), so two runs replay the identical trace."""
    if num_requests < 1:
        return ()
    rng = np.random.RandomState(seed)
    # cumulative arrivals at fine time resolution, then invert
    steps = max(1024, num_requests * 8)
    # total duration such that the mean rate delivers num_requests
    mean_rps = (lo_rps + hi_rps) / 2.0
    duration = num_requests / mean_rps
    t = np.linspace(0.0, duration, steps)
    phase = 2.0 * np.pi * cycles * t / duration
    rate = lo_rps + (hi_rps - lo_rps) * 0.5 * (1.0 - np.cos(phase))
    cum = np.concatenate([[0.0], np.cumsum(rate[:-1] * np.diff(t))])
    targets = (np.arange(num_requests) + rng.uniform(0, 1, num_requests)) \
        * cum[-1] / num_requests
    offsets = np.interp(targets, cum, t)
    return tuple(float(x) for x in np.sort(offsets))


def flash_arrivals(
    num_requests: int,
    base_rps: float,
    flash_frac: float = 0.5,
    flash_at: float = 0.5,
    flash_rps: Optional[float] = None,
    seed: int = 0,
) -> Tuple[float, ...]:
    """Flash-crowd arrival offsets: steady ``base_rps`` background with
    ``flash_frac`` of all requests compressed into a spike at
    ``flash_at`` (fraction of the run) arriving at ``flash_rps``
    (default 10× base).  Deterministic like :func:`diurnal_arrivals`."""
    if num_requests < 1:
        return ()
    rng = np.random.RandomState(seed)
    n_flash = int(num_requests * flash_frac)
    n_base = num_requests - n_flash
    duration = max(n_base, 1) / base_rps
    base = np.sort(rng.uniform(0.0, duration, n_base))
    spike_rate = flash_rps if flash_rps is not None else base_rps * 10.0
    spike_t0 = duration * flash_at
    spike = spike_t0 + np.sort(rng.uniform(0, 1, n_flash)) \
        * (n_flash / spike_rate)
    return tuple(float(x) for x in np.sort(np.concatenate([base, spike])))


def run_load(
    engine,
    num_requests: int = 64,
    concurrency: int = 8,
    sizes: Sequence[Tuple[int, int]] = DEFAULT_SIZES,
    seed: int = 0,
    deadline_s: Optional[float] = None,
    queue_full_backoff: float = 0.002,
    collect: bool = False,
    models: Optional[Sequence[str]] = None,
    lanes: Optional[Sequence[Optional[str]]] = None,
    poison_mix: Optional[Sequence[Optional[str]]] = None,
    tenants: Optional[Sequence[Optional[str]]] = None,
    arrivals: Optional[Sequence[float]] = None,
    backoff_give_up: Optional[int] = None,
) -> Dict:
    """Drive ``engine`` with ``num_requests`` synthetic images; returns a
    report dict (wall/throughput/outcome counts + the engine's metrics
    snapshot).  ``QueueFull`` is the backpressure signal — the client
    backs off and resubmits, counting the rejection.

    ``models`` (optional) assigns each request a model id drawn
    deterministically from the sequence — the multi-tenancy traffic mix.
    The draw happens from ``seed`` before any thread starts (same rng
    stream discipline as sizes), so the (index → model) mapping is
    identical across runs.

    ``lanes`` (optional) does the same for SLO classes — each request's
    lane is drawn from the sequence (``None`` entries mean "let the
    engine default", i.e. the model's registry SLO class), producing a
    deterministic mixed-lane stream.  Drawn AFTER sizes and models, so
    adding lanes to an existing scenario leaves its size/model streams
    unchanged.  Per-lane outcome counts land under
    ``report["lane_outcomes"]``.

    ``poison_mix`` (optional) draws each request's poison flavor from
    the sequence the same way (``None`` entries mean healthy traffic —
    e.g. ``[None]*19 + ["qod"]`` is a ~5% poison mix).  Flavors are the
    :data:`POISON_FLAVORS`: the malformed three must be rejected at the
    engine's admission gate, while ``"qod"`` submits the deterministic
    :func:`qod_image` whose digest a fault spec can target.  Drawn AFTER
    lanes so existing scenarios keep their streams.  Per-flavor outcome
    counts land under ``report["poison_outcomes"]``.

    ``tenants`` (optional) draws each request's tenant tag from the
    sequence (``None`` entries = untagged) — the deterministic
    multi-tenant client mix (ISSUE 16).  Drawn AFTER poison so existing
    scenarios keep their streams.  Per-tenant outcome counts land under
    ``report["tenant_outcomes"]`` mirroring ``lane_outcomes``, with the
    ``over_budget``/``shed`` rejections attributable per tenant.

    ``arrivals`` (optional) switches the driver from closed-loop to
    trace-driven: entry ``i`` is request ``i``'s offset in seconds from
    load start (see :func:`diurnal_arrivals` / :func:`flash_arrivals`),
    and a client thread holding request ``i`` sleeps until that offset
    before submitting.  A client behind schedule submits immediately, so
    the trace is an arrival-time floor — exactly the open-loop shape an
    autoscaler must chase.

    ``backoff_give_up`` (optional) bounds QueueFull/over-budget retries
    per request: after that many rejections the request resolves as its
    last rejection kind instead of retrying forever — shed traffic must
    be COUNTABLE for the fairness bench, not retried into admission.

    ``collect=True`` additionally stores each request's resolution under
    ``report["_results"]`` — ``{index: ("ok", detections) | (kind, repr)}``
    — which is what lets a faulted run be compared byte-for-byte against
    an unfaulted one (pop the key before JSON-dumping the report), plus
    per-request submit/done monotonic timestamps under
    ``report["_times"]`` — ``{index: (t_submit, t_done)}`` — which is how
    the swap bench classifies requests as entirely-before / entirely-
    after / straddling a live swap window.  Because traffic is derived
    from ``seed + index`` alone, equal indices mean equal input images
    across runs."""
    size_rng = np.random.RandomState(seed)
    req_sizes = [
        sizes[size_rng.randint(len(sizes))] for i in range(num_requests)
    ]
    req_models = (
        [models[size_rng.randint(len(models))] for _ in range(num_requests)]
        if models else None
    )
    req_lanes = (
        [lanes[size_rng.randint(len(lanes))] for _ in range(num_requests)]
        if lanes else None
    )
    req_poison = (
        [poison_mix[size_rng.randint(len(poison_mix))]
         for _ in range(num_requests)]
        if poison_mix else None
    )
    req_tenants = (
        [tenants[size_rng.randint(len(tenants))]
         for _ in range(num_requests)]
        if tenants else None
    )
    counter = iter(range(num_requests))
    lock = threading.Lock()
    outcomes = {"ok": 0, "deadline": 0, "error": 0, "queue_full_retries": 0,
                "invalid": 0, "poison": 0, "exhausted": 0,
                "over_budget": 0, "queue_full": 0}
    lane_outcomes: Dict[str, Dict[str, int]] = {}
    poison_outcomes: Dict[str, Dict[str, int]] = {}
    tenant_outcomes: Dict[str, Dict[str, int]] = {}
    results: Dict[int, Tuple[str, object]] = {}
    times: Dict[int, Tuple[float, float]] = {}

    def classify(e: BaseException) -> str:
        name = type(e).__name__
        if "InvalidRequest" in name:
            return "invalid"
        if "OverBudget" in name:
            return "over_budget"
        if "QueueFull" in name:
            return "queue_full"
        if "Poison" in name:
            return "poison"
        if "Exhausted" in name:
            return "exhausted"
        return "deadline" if "Deadline" in name else "error"

    def note(key: str, lane: Optional[str] = None,
             flavor: Optional[str] = None,
             tenant: Optional[str] = None) -> None:
        with lock:
            outcomes[key] += 1
            if lane is not None:
                per = lane_outcomes.setdefault(
                    lane, {"ok": 0, "deadline": 0, "error": 0}
                )
                if key in per:
                    per[key] += 1
            if flavor is not None:
                pf = poison_outcomes.setdefault(flavor, {})
                pf[key] = pf.get(key, 0) + 1
            if tenant is not None:
                pt = tenant_outcomes.setdefault(tenant, {})
                pt[key] = pt.get(key, 0) + 1

    def client(t_start: float) -> None:
        while True:
            with lock:
                i = next(counter, None)
            if i is None:
                return
            h, w = req_sizes[i]
            flavor = req_poison[i] if req_poison is not None else None
            if flavor is None:
                im = synthetic_image(i, h, w, seed)
            else:
                im = poison_image(flavor, i, h, w, seed)
            mkw = (
                {} if req_models is None or req_models[i] is None
                else {"model": req_models[i]}
            )
            lane = req_lanes[i] if req_lanes is not None else None
            if lane is not None:
                mkw["lane"] = lane
            tenant = req_tenants[i] if req_tenants is not None else None
            if tenant is not None:
                mkw["tenant"] = tenant
            if arrivals is not None:
                # trace-driven: hold request i until its scheduled
                # arrival offset (behind schedule = submit immediately)
                wait = t_start + arrivals[i] - time.monotonic()
                if wait > 0:
                    time.sleep(wait)
            t_submit = time.monotonic()
            fut = None
            retries = 0
            while True:
                try:
                    fut = engine.submit(im, deadline_s=deadline_s, **mkw)
                    break
                except QueueFull as e:
                    retries += 1
                    if backoff_give_up is not None \
                            and retries >= backoff_give_up:
                        note("queue_full", lane, flavor, tenant)
                        if collect:
                            with lock:
                                results[i] = ("queue_full", repr(e))
                        break
                    note("queue_full_retries")
                    time.sleep(queue_full_backoff)
                except Exception as e:
                    # synchronous reject: admission gate (InvalidRequest),
                    # quarantine fast-fail (PoisonRequest), or tenant
                    # admission (UnknownTenant / TenantOverBudget)
                    kind = classify(e)
                    note(kind, lane, flavor, tenant)
                    if collect:
                        with lock:
                            results[i] = (kind, repr(e))
                    break
            if fut is not None:
                try:
                    dets = fut.result()
                    note("ok", lane, flavor, tenant)
                    if collect:
                        with lock:
                            results[i] = ("ok", dets)
                except Exception as e:
                    kind = classify(e)
                    note(kind, lane, flavor, tenant)
                    if collect:
                        with lock:
                            results[i] = (kind, repr(e))
            if collect:
                with lock:
                    times[i] = (t_submit, time.monotonic())

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=client, args=(t0,), name=f"loadgen-{t}",
                         daemon=True)
        for t in range(max(1, concurrency))
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t0

    snap = engine.snapshot()
    report = {
        "requests": num_requests,
        "concurrency": concurrency,
        "sizes": [list(s) for s in sizes],
        "seed": seed,
        "wall_s": round(wall, 4),
        "imgs_per_sec": round(outcomes["ok"] / wall, 3) if wall else None,
        "outcomes": outcomes,
        "engine": snap,
    }
    if models:
        report["models"] = list(models)
    if lanes:
        report["lanes"] = list(lanes)
        report["lane_outcomes"] = lane_outcomes
    if poison_mix:
        report["poison_mix"] = list(poison_mix)
        report["poison_flavors"] = (
            [req_poison[i] for i in range(num_requests)]
        )
        report["poison_outcomes"] = poison_outcomes
    if tenants:
        report["tenants"] = list(tenants)
        report["tenant_outcomes"] = tenant_outcomes
    if arrivals is not None:
        report["trace"] = {
            "arrivals": len(arrivals),
            "span_s": round(float(arrivals[-1]), 4) if len(arrivals) else 0.0,
        }
    if collect:
        report["_results"] = results
        report["_times"] = times
    return report


def stream_arrivals(
    num_streams: int,
    frames_per_stream: int,
    fps: float,
    stagger_s: float = 0.0,
    seed: int = 0,
) -> Dict[Tuple[int, int], float]:
    """Per-stream frame-cadence arrival offsets (ISSUE 20): frame ``f``
    of stream ``s`` arrives at ``s*stagger_s + f/fps`` plus a small
    deterministic jitter (< 20% of the frame period, so cadence order
    within a stream is never perturbed).  Returns ``{(s, f): offset}``
    — the open-loop shape of N cameras delivering frames on a clock,
    which is what makes several frames of one stream be in flight
    together (the precondition for the ordering guarantee to matter)."""
    rng = np.random.RandomState(seed)
    jit = rng.uniform(0.0, 0.2 / fps, (num_streams, frames_per_stream))
    return {
        (s, f): s * stagger_s + f / fps + float(jit[s, f])
        for s in range(num_streams)
        for f in range(frames_per_stream)
    }


def run_stream_load(
    engine,
    num_streams: int = 4,
    frames_per_stream: int = 16,
    fps: float = 30.0,
    sizes: Sequence[Tuple[int, int]] = DEFAULT_SIZES,
    seed: int = 0,
    deadline_s: Optional[float] = None,
    model: Optional[str] = None,
    masks: bool = False,
    stagger_s: float = 0.0,
    collect: bool = False,
    stream_prefix: str = "cam",
) -> Dict:
    """Streaming counterpart of :func:`run_load`: one client thread per
    stream submits its frames IN ORDER at frame cadence (``fps``),
    pipelined — it does not block on results, so consecutive frames of
    one stream are genuinely in flight together and only the engine's
    per-stream gate (not client pacing) enforces delivery order.

    Traffic is deterministic from ``seed`` alone: stream ``s`` keeps one
    image size for all its frames (a camera doesn't change resolution
    mid-stream — frames of a stream share a ladder bucket), and frame
    pixels derive from ``seed + s*frames + f``, so a faulted run's
    result bytes are comparable entry-for-entry against an unfaulted
    one.

    The report carries the ordering evidence: ``completion_order[s]`` =
    frame indices of stream ``s`` in the order their futures RESOLVED
    (recorded by done-callbacks against a global sequence counter),
    ``in_order`` = whether every stream's list is sorted, and
    ``lost_frames`` = submitted-but-never-resolved count (must be 0).
    ``collect=True`` stores each frame's resolution under
    ``report["_results"][(s, f)]`` for byte comparison."""
    size_rng = np.random.RandomState(seed)
    stream_sizes = [
        sizes[size_rng.randint(len(sizes))] for _ in range(num_streams)
    ]
    arr = stream_arrivals(num_streams, frames_per_stream, fps,
                          stagger_s=stagger_s, seed=seed)
    lock = threading.Lock()
    seq = [0]
    completion: Dict[int, list] = {s: [] for s in range(num_streams)}
    completion_seq: Dict[Tuple[int, int], int] = {}
    outcomes = {"ok": 0, "deadline": 0, "error": 0, "queue_full": 0,
                "invalid": 0, "poison": 0, "exhausted": 0, "rejected": 0}
    results: Dict[Tuple[int, int], Tuple[str, object]] = {}
    resolved = [0]

    def classify(e: BaseException) -> str:
        name = type(e).__name__
        if "InvalidRequest" in name:
            return "invalid"
        if "QueueFull" in name:
            return "queue_full"
        if "Poison" in name:
            return "poison"
        if "Exhausted" in name:
            return "exhausted"
        return "deadline" if "Deadline" in name else "error"

    def on_done(s: int, f: int):
        def cb(fut) -> None:
            with lock:
                completion[s].append(f)
                completion_seq[(s, f)] = seq[0]
                seq[0] += 1
                resolved[0] += 1
                try:
                    r = fut.result()
                    outcomes["ok"] += 1
                    if collect:
                        results[(s, f)] = ("ok", r)
                except Exception as e:  # noqa: BLE001 — typed taxonomy
                    kind = classify(e)
                    outcomes[kind] += 1
                    if collect:
                        results[(s, f)] = (kind, repr(e))
        return cb

    submitted = [0]

    def stream_client(s: int, t_start: float) -> None:
        h, w = stream_sizes[s]
        sid = f"{stream_prefix}{s}"
        for f in range(frames_per_stream):
            wait = t_start + arr[(s, f)] - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            im = synthetic_image(s * frames_per_stream + f, h, w, seed)
            try:
                fut = engine.submit(
                    im, deadline_s=deadline_s, model=model,
                    stream=sid, frame=f, masks=masks,
                )
            except Exception as e:  # noqa: BLE001 — synchronous reject
                # a rejected frame is NOT registered (no gap): later
                # frames still deliver; count it, keep streaming
                with lock:
                    outcomes["rejected"] += 1
                    if collect:
                        results[(s, f)] = (classify(e), repr(e))
                continue
            with lock:
                submitted[0] += 1
            fut.add_done_callback(on_done(s, f))

    t0 = time.monotonic()
    threads = [
        threading.Thread(target=stream_client, args=(s, t0),
                         name=f"stream-{s}", daemon=True)
        for s in range(num_streams)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # drain: every submitted frame must resolve (zero lost frames)
    deadline = time.monotonic() + 300.0
    while time.monotonic() < deadline:
        with lock:
            if resolved[0] >= submitted[0]:
                break
        time.sleep(0.005)
    wall = time.monotonic() - t0

    in_order = all(
        completion[s] == sorted(completion[s]) for s in range(num_streams)
    )
    report = {
        "streams": num_streams,
        "frames_per_stream": frames_per_stream,
        "fps": fps,
        "seed": seed,
        "wall_s": round(wall, 4),
        "frames_per_sec": (
            round(outcomes["ok"] / wall, 3) if wall else None
        ),
        "submitted": submitted[0],
        "resolved": resolved[0],
        "lost_frames": submitted[0] - resolved[0],
        "outcomes": outcomes,
        "in_order": in_order,
        "completion_order": {
            str(s): list(completion[s]) for s in range(num_streams)
        },
        "engine": engine.snapshot(),
    }
    if collect:
        report["_results"] = results
        report["_completion_seq"] = completion_seq
    return report
