"""One serving replica: a :class:`ServeRunner` wrapped in a health-gated
state machine.

The train side has been guarded since PR 1 (``GuardedLoop`` retries a
diverged step, ``StepWatchdog`` aborts a wedged one), but a serving
fleet cannot abort the process — a wedged or persistently-failing
predict path on ONE device must cost that device's capacity, not the
endpoint.  A :class:`Replica` therefore owns one runner, one dispatch
queue, and one worker thread, and moves through:

::

    WARMING ──warmup ok──▶ HEALTHY ◀──probe ok / good dispatch──┐
                              │                                 │
                 failure / slow EWMA                        DEGRADED
                              ▼                                 │
                          DEGRADED ──failure budget / stall──▶ DRAINING
                                                                │
              (queued + in-flight dispatches fail over          │
               with ReplicaDrained — the router requeues        ▼
               them on a sibling; nothing is dropped)      RECOVERING
                                                                │
                      breaker backoff → fresh runner (factory → │
                      recompile) → warmup → probe batch ────────┘
                                 ok → HEALTHY (rejoin)
                                 fail → breaker reopens, backoff ×2

Health signals, all O(1) per dispatch:

* **stall watchdog** — a wall-clock timer armed around every predict
  (the :class:`~mx_rcnn_tpu.core.resilience.StepWatchdog` idiom: a
  thread timer, because neither SIGALRM nor cooperative checks fire
  while the worker is wedged inside native XLA code).  On expiry the
  replica trips straight to DRAINING and its in-flight dispatch is
  failed over immediately — the caller never waits out the wedge.
* **consecutive-failure count** — a dispatch that fails all in-place
  retries (``make_retry_policy("replica")``) marks DEGRADED; reaching
  ``fail_threshold`` trips DRAINING.
* **predict-latency EWMA** — a successful dispatch slower than
  ``latency_factor ×`` the warmed EWMA marks DEGRADED (the router stops
  routing to it; an idle DEGRADED replica self-probes its way back).

Recovery runs on the replica's own worker thread: circuit-breaker
backoff (exponential in the number of recent trips — a flapping replica
waits longer each time), then a FRESH runner from the factory (a real
recompile, not a state reset), ``warmup()`` over the ladder, and a probe
batch through the same fault-injectable predict path; only a probe
success rejoins the pool.  Every transition is appended to
``transitions`` with a monotonic timestamp, reason, and batch ordinal —
the log ``tests/test_replica.py`` asserts against the injected schedule.

Fault injection: ``utils/faults.py :: predict_fault(replica, ordinal)``
is called once per predict attempt, so every path above is
deterministically reproducible on CPU (``predict_fail`` / ``predict_stall``
/ ``replica_wedge`` keyed by replica index and batch ordinal).

Overlapped execution (ISSUE 13): with a split-capable runner
(``dispatch``/``complete`` halves, the real :class:`ServeRunner`) the
worker keeps up to ``inflight_depth`` dispatches outstanding — batch
N+1's H2D staging and device compute overlap batch N's fetch and host
postprocess.  Every dispatch still carries its own stall watchdog and
resolves exactly once; a trip fails the WHOLE in-flight window over
(requeue, never drop) and records every windowed digest as one combined
quarantine suspect list.  Depth adds no jit signatures (same bucket,
same ``max_batch`` pad) and depth=1 is byte-identical to the serial
path (``run == complete ∘ dispatch``).  Split-less runners (legacy
fakes) always serve serially.

A note on hard wedges: the watchdog fails the *dispatch* over instantly,
but the worker thread itself stays parked inside the native call until
the runtime returns — recovery (and rejoin) begins at that point.  A
truly permanent wedge keeps the replica in DRAINING forever, which is
exactly the fleet-level behavior wanted: the pool routes around it and
its capacity is simply absent until an operator restarts the process.
"""

from __future__ import annotations

import enum
import logging
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from mx_rcnn_tpu.analysis.lockcheck import make_lock
from mx_rcnn_tpu.core.resilience import RetryPolicy, make_retry_policy
from mx_rcnn_tpu.serve.metrics import LatencyHistogram, OverlapStats
from mx_rcnn_tpu.utils import faults

logger = logging.getLogger(__name__)


class ReplicaState(enum.Enum):
    WARMING = "warming"
    HEALTHY = "healthy"
    DEGRADED = "degraded"
    DRAINING = "draining"
    RECOVERING = "recovering"


class ReplicaDrained(RuntimeError):
    """The dispatch's replica tripped into DRAINING before producing a
    result — the router must requeue the batch on a sibling (this is a
    routing signal, never a client-visible error)."""


@dataclass(frozen=True)
class HealthPolicy:
    """Knobs for the per-replica health monitor and circuit breaker.

    Defaults suit a real device; tests shrink every time constant.
    ``retry`` is the in-place retry for one dispatch (transient device
    hiccups) — deliberately tighter than the single-runner engine's
    policy, because a pooled dispatch should fail over instead of
    burning its latency budget in place.
    """

    stall_timeout: float = 30.0       # wall-clock watchdog per predict
    fail_threshold: int = 2           # consecutive failed dispatches → DRAINING
    latency_factor: float = 8.0       # dispatch slower than f×EWMA → DEGRADED
    ewma_decay: float = 0.8           # EWMA update weight on the old value
    ewma_warmup: int = 3              # dispatches before the EWMA gate arms
    breaker_backoff: float = 0.05     # RECOVERING wait, doubled per recent trip
    breaker_max_backoff: float = 2.0
    flap_window: float = 30.0         # trips within this window count as flapping
    retry: RetryPolicy = field(
        default_factory=lambda: make_retry_policy("replica")
    )


@dataclass
class _Dispatch:
    """One batch handed to one replica; ``future`` resolves exactly once
    with the predict outputs, a predict error, or :class:`ReplicaDrained`."""

    batch: Dict[str, np.ndarray]
    deadline: Optional[float] = None
    kind: str = "serve"  # "serve" | "probe"
    future: Future = field(default_factory=Future)
    ordinal: int = -1    # per-replica batch ordinal, set at predict time
    model: Optional[str] = None  # registry model id (None = default)
    lane: Optional[str] = None   # SLO class tag (observability only)
    digests: Tuple[str, ...] = ()  # member request digests (containment)
    implicated: bool = False     # this dispatch's digests were trip suspects
    # overlapped-path state: the device handle from the dispatch half, or
    # the exception it raised (settled at _finish time, in window order)
    handle: Any = None
    error: Optional[BaseException] = None
    t0: float = 0.0              # dispatch-half start (latency accounting)

    def resolve(self, result=None, exc: Optional[BaseException] = None) -> bool:
        """Set the future if still unset; False when it already resolved
        (the watchdog failed this dispatch over while we computed)."""
        try:
            if exc is not None:
                self.future.set_exception(exc)
            else:
                self.future.set_result(result)
            return True
        except InvalidStateError:
            return False


class Replica:
    """One pool member: runner + worker thread + health state machine."""

    def __init__(
        self,
        index: int,
        runner_factory: Callable[[int], Any],
        policy: Optional[HealthPolicy] = None,
        name: str = "replica",
        quarantine: Optional[Any] = None,
        inflight_depth: int = 2,
    ):
        self.index = int(index)
        self.policy = policy or HealthPolicy()
        # bounded in-flight window for split-capable runners (ISSUE 13):
        # up to this many dispatches outstanding, so batch N+1's staging
        # and device compute overlap batch N's fetch.  Runners without
        # dispatch/complete halves always serve serially (depth 1).
        self.inflight_depth = max(1, int(inflight_depth))
        self.quarantine = quarantine  # pool-shared QuarantineTable (or None)
        self._factory = runner_factory
        self.runner = runner_factory(self.index)
        self._lock = make_lock("Replica._lock")
        self._inbox: "queue.Queue[Optional[_Dispatch]]" = queue.Queue()
        # in-flight dispatches keyed by ordinal, each with its own stall
        # watchdog (armed per dispatch, cancelled individually) — the
        # serial loop holds at most one entry, but trip/attribution code
        # treats the whole window uniformly
        self._inflight: Dict[int, _Dispatch] = {}
        self._watchdogs: Dict[int, threading.Timer] = {}
        self._stop = False
        self.state = ReplicaState.WARMING
        # health-monitor state
        self._ordinal = 0
        self._consecutive_failures = 0
        self._ewma_s: Optional[float] = None
        self._ewma_n = 0
        self._trip_times: List[float] = []
        # observability (read under no lock by snapshots: int/float writes
        # are atomic enough for counters; the transition log is locked)
        self.latency = LatencyHistogram()
        self.overlap = OverlapStats()
        # runner-side paste accounting (mask_rles_for) lands in the same
        # pool-merged OverlapStats as fetch_bytes
        if hasattr(self.runner, "overlap"):
            self.runner.overlap = self.overlap
        self.transitions: List[Dict[str, Any]] = []
        self.dispatches = 0
        self.failures = 0
        self.retried = 0
        self.requeued_out = 0   # dispatches failed over with ReplicaDrained
        self.abandoned = 0      # results that arrived after the failover
        self.probes = 0
        self.rewarms = 0
        self.partial_rewarms = 0     # recoveries warmed from traffic history
        self.last_rewarm_rungs = 0   # rungs the last partial rewarm compiled
        self.breaker_opens = 0
        self.last_backoff = 0.0
        self.isolation_probes = 0     # suspect replays run before rejoin
        self.isolation_confirmed = 0  # replays that confirmed poison
        self.isolation_cleared = 0    # replays that cleared the suspect
        self._t0 = time.monotonic()
        self._worker = threading.Thread(
            target=self._loop, name=f"{name}-{index}", daemon=True
        )
        self._worker.start()

    # ------------------------------------------------------------- state
    def _log_transition(self, new: ReplicaState, reason: str) -> None:
        # caller holds self._lock
        old = self.state
        self.state = new
        self.transitions.append(
            {
                "t": round(time.monotonic() - self._t0, 4),
                "from": old.value,
                "to": new.value,
                "reason": reason,
                "ordinal": self._ordinal,
            }
        )
        logger.info(
            "replica %d: %s -> %s (%s)", self.index, old.value, new.value,
            reason,
        )

    def _set_state(self, new: ReplicaState, reason: str) -> None:
        with self._lock:
            if self.state is not new:
                self._log_transition(new, reason)

    @property
    def routable(self) -> bool:
        """The router dispatches ONLY to HEALTHY replicas."""
        return self.state is ReplicaState.HEALTHY

    def load(self) -> int:
        """Queued + in-flight dispatches (the least-loaded routing key)."""
        with self._lock:
            return self._inbox.qsize() + len(self._inflight)

    @property
    def _split(self) -> bool:
        """The current runner exposes the dispatch/complete halves."""
        r = self.runner
        return hasattr(r, "dispatch") and hasattr(r, "complete")

    def depth(self) -> int:
        """Effective in-flight window: ``inflight_depth`` with a
        split-capable runner, else 1 (the serial path).  The router's
        hedging reads this — a dispatch waiting behind pipelined work on
        a depth-k replica is not replica silence."""
        return self.inflight_depth if self._split else 1

    # ---------------------------------------------------------- dispatch
    def submit(
        self,
        batch: Dict[str, np.ndarray],
        deadline: Optional[float] = None,
        model: Optional[str] = None,
        lane: Optional[str] = None,
        digests: Optional[Tuple[str, ...]] = None,
    ) -> _Dispatch:
        """Enqueue one batch; returns the dispatch whose future resolves
        exactly once.  A non-routable replica fails it immediately with
        :class:`ReplicaDrained` instead of accepting work it would only
        drain later."""
        d = _Dispatch(batch=batch, deadline=deadline, model=model, lane=lane,
                      digests=tuple(digests or ()))
        with self._lock:
            if self._stop or self.state not in (
                ReplicaState.HEALTHY, ReplicaState.DEGRADED
            ):
                d.resolve(exc=ReplicaDrained(
                    f"replica {self.index} is {self.state.value}"
                ))
                return d
            self._inbox.put(d)
        return d

    def trip(self, reason: str,
             suspect: Optional[_Dispatch] = None) -> None:
        """Force DRAINING now (watchdog expiry, failure budget, or an
        operator drain): fail every in-flight dispatch over, requeue-fail
        everything queued, and let the worker run recovery.  Idempotent;
        callable from any thread.  ``suspect`` names the dispatch that
        caused a failure-budget trip (already out of the window — its
        future resolved with the predict error); together with the whole
        in-flight window it forms the trip's attribution suspects, and
        every member digest lands in the pool's quarantine table in ONE
        ``note_trip`` call (one trip event, however deep the window).
        Queued dispatches were never running, so they drain *without*
        implication."""
        with self._lock:
            if self.state in (ReplicaState.DRAINING, ReplicaState.RECOVERING):
                return
            self._log_transition(ReplicaState.DRAINING, reason)
            self._trip_times.append(time.monotonic())
            victims = list(self._inflight.values())
            self._inflight.clear()
            dogs = list(self._watchdogs.values())
            self._watchdogs.clear()
        for t in dogs:
            t.cancel()
        if suspect is not None and suspect not in victims:
            victims.insert(0, suspect)
        drained = ReplicaDrained(f"replica {self.index} draining ({reason})")
        suspects: List[Any] = []
        for cur in victims:
            # mark before resolving so the router's waiter can observe it
            cur.implicated = True
            if cur.resolve(exc=drained):
                self.requeued_out += 1
            if cur.digests:
                suspects.extend(self._suspect_list(cur))
        while True:
            try:
                d = self._inbox.get_nowait()
            except queue.Empty:
                break
            if d is not None and d.resolve(exc=drained):
                self.requeued_out += 1
        if suspects and self.quarantine is not None:
            self.quarantine.note_trip(
                suspects, replica=self.index, reason=reason
            )

    def drain(self) -> None:
        """Operator-initiated drain (same path as a health trip)."""
        self.trip("drain")

    def _suspect_list(self, d: _Dispatch) -> List[Any]:
        """(digest, payload) per batch member for quarantine attribution.
        Slot k of every batch array IS request k's prepared data
        (``assemble`` keeps submit order and pads the tail), so the
        payload captured here is enough to rebuild a sacrificial
        batch-of-1 for the isolation probe."""
        arrays = {
            k: v for k, v in d.batch.items()
            if isinstance(v, np.ndarray) and v.ndim >= 1
        }
        slots = next(iter(arrays.values())).shape[0] if arrays else 0
        out = []
        for i, dg in enumerate(d.digests):
            payload = None
            if arrays and i < slots:
                payload = {
                    "arrays": {
                        k: np.array(v[i]) for k, v in arrays.items()
                        if v.shape[0] == slots
                    },
                    "slots": slots,
                    "model": d.model,
                }
            out.append((dg, payload))
        return out

    # ------------------------------------------------------------ worker
    def _loop(self) -> None:
        self._recover(initial=True)
        # local in-flight window, dispatch order; entries mirror
        # self._inflight (the dict is the trip/attribution view, the
        # deque is the completion order)
        pending: "deque[_Dispatch]" = deque()
        while not self._stop:
            if self.state is ReplicaState.DRAINING:
                # trip() already failed every windowed dispatch over
                pending.clear()
                self._recover()
                continue
            if (
                self.state is ReplicaState.DEGRADED
                and not pending
                and self._inbox.empty()
            ):
                self._probe()
                continue
            if not self._split:
                # split-less runner (legacy fakes): the serial path
                try:
                    d = self._inbox.get(timeout=0.02)
                except queue.Empty:
                    continue
                if d is None:
                    break
                self._serve(d)
                continue
            # overlapped path: top the window up to depth, then finish
            # the oldest entry — batch N+1's dispatch half (staging +
            # async forward) runs before batch N's fetch blocks the host
            sentinel = False
            while len(pending) < self.inflight_depth:
                try:
                    d = (
                        self._inbox.get(timeout=0.02)
                        if not pending
                        else self._inbox.get_nowait()
                    )
                except queue.Empty:
                    break
                if d is None:
                    sentinel = True
                    break
                entry = self._begin(d)
                if entry is not None:
                    pending.append(entry)
            if sentinel:
                # stop() trips before posting the sentinel, so windowed
                # entries were already failed over
                break
            if pending:
                entry = pending.popleft()
                self._finish(entry)

    def _begin(self, d: _Dispatch) -> Optional[_Dispatch]:
        """Dispatch half of one windowed entry: admission + ordinal under
        the lock, watchdog armed, then the async device dispatch through
        the fault-injectable path.  A dispatch-half failure is recorded
        on the entry and settled at :meth:`_finish` time, in window
        order, so retries and failure attribution stay ordered.  Returns
        None when the replica is no longer servable (the dispatch was
        failed over)."""
        with self._lock:
            if self._stop or self.state not in (
                ReplicaState.HEALTHY, ReplicaState.DEGRADED
            ):
                d.resolve(exc=ReplicaDrained(
                    f"replica {self.index} is {self.state.value}"
                ))
                self.requeued_out += 1
                return None
            d.ordinal = self._ordinal
            self._ordinal += 1
            self._inflight[d.ordinal] = d
            depth_now = len(self._inflight)
        self.dispatches += 1
        self.overlap.note_depth(depth_now)
        self._arm_watchdog(d.ordinal)
        d.t0 = time.monotonic()
        try:
            faults.predict_fault(self.index, d.ordinal)
            faults.poison_input(d.digests)
            if d.model is None:
                d.handle = self.runner.dispatch(d.batch)
            else:
                d.handle = self.runner.dispatch(d.batch, model=d.model)
            if depth_now > 1:
                # this staging/dispatch host work ran while another
                # dispatch was in flight: the window hid it
                self.overlap.note_hidden(time.monotonic() - d.t0)
        except Exception as e:  # noqa: BLE001 — settled at _finish
            d.error = e
        return d

    def _retry_tail(self, d: _Dispatch, first_exc: BaseException):
        """In-place retries for a windowed dispatch whose first attempt
        (either half) failed: the remaining ``policy.retry`` attempts run
        as BLOCKING full predicts, exactly the serial path's tail — the
        window is not refilled around a failing batch."""
        p = self.policy.retry
        tries = max(1, p.tries)
        last = first_exc
        for attempt in range(1, tries):
            if p.delay:
                time.sleep(p.delay * p.backoff ** (attempt - 1))
            try:
                return self._predict(d.batch, d.ordinal, attempt,
                                     model=d.model, digests=d.digests)
            except Exception as e:  # noqa: BLE001 — re-raised below
                last = e
        raise last

    def _finish(self, d: _Dispatch) -> None:
        """Completion half: force the oldest windowed dispatch's outputs
        (``runner.complete`` under the ``host_copy`` discipline), resolve
        its future exactly once, and feed the health monitor — the same
        success/failure bookkeeping as the serial path."""
        try:
            if d.error is not None:
                raise d.error
            hidden = len(self._inflight) > 1  # a sibling covers this fetch
            t_f = time.monotonic()
            out = self.runner.complete(d.handle)
            self.overlap.note_fetch(
                time.monotonic() - t_f, hidden=hidden,
                # complete() just ran on THIS thread, so the runner's
                # last-fetch size/cost are this dispatch's host copy
                nbytes=getattr(self.runner, "last_fetch_bytes", 0),
                model=getattr(d.handle, "model", None),
                device_ms=getattr(self.runner, "last_device_ms", 0.0),
            )
        except Exception as first:  # noqa: BLE001 — in-place retry tail
            try:
                out = self._retry_tail(d, first)
            except Exception as e:  # noqa: BLE001 — typed failover
                self._disarm_watchdog(d.ordinal)
                with self._lock:
                    self._inflight.pop(d.ordinal, None)
                    depth_now = len(self._inflight)
                self.overlap.note_depth(depth_now)
                self.failures += 1
                if not d.resolve(exc=e):
                    self.abandoned += 1
                self._note_failure(d.ordinal, dispatch=d)
                return
        self._disarm_watchdog(d.ordinal)
        dt = time.monotonic() - d.t0
        with self._lock:
            self._inflight.pop(d.ordinal, None)
            depth_now = len(self._inflight)
        self.overlap.note_depth(depth_now)
        if not d.resolve(out):
            # the watchdog already failed this dispatch over (the batch
            # reran elsewhere); the late result is discarded, not served
            self.abandoned += 1
            return
        self.latency.record(dt)
        self._note_success(dt, d.ordinal)

    def _arm_watchdog(self, ordinal: int) -> None:
        t = threading.Timer(self.policy.stall_timeout, self._watchdog_fire,
                            args=(ordinal,))
        t.daemon = True
        with self._lock:
            if ordinal not in self._inflight:
                return  # tripped between admission and arming
            self._watchdogs[ordinal] = t
        t.start()

    def _watchdog_fire(self, ordinal: int) -> None:
        with self._lock:
            self._watchdogs.pop(ordinal, None)
        self.trip(f"stall>{self.policy.stall_timeout:g}s")

    def _disarm_watchdog(self, ordinal: int) -> None:
        with self._lock:
            t = self._watchdogs.pop(ordinal, None)
        if t is not None:
            t.cancel()

    def _predict(self, batch, ordinal: int, attempt: int,
                 model: Optional[str] = None,
                 digests: Tuple[str, ...] = ()):
        if attempt:
            self.retried += 1
        faults.predict_fault(self.index, ordinal)
        faults.poison_input(digests)
        # model kwarg only when the dispatch carries one, so runner
        # fakes with the legacy run(batch) signature keep working
        if model is None:
            return self.runner.run(batch)
        return self.runner.run(batch, model=model)

    def _serve(self, d: _Dispatch) -> None:
        with self._lock:
            if self._stop or self.state not in (
                ReplicaState.HEALTHY, ReplicaState.DEGRADED
            ):
                d.resolve(exc=ReplicaDrained(
                    f"replica {self.index} is {self.state.value}"
                ))
                self.requeued_out += 1
                return
            d.ordinal = self._ordinal
            self._ordinal += 1
            self._inflight[d.ordinal] = d
        self.dispatches += 1
        self._arm_watchdog(d.ordinal)
        t0 = time.monotonic()
        try:
            out = self.policy.retry.run(
                lambda attempt: self._predict(
                    d.batch, d.ordinal, attempt, model=d.model,
                    digests=d.digests,
                )
            )
        except Exception as e:  # noqa: BLE001 — typed failover, never a drop
            self._disarm_watchdog(d.ordinal)
            with self._lock:
                self._inflight.pop(d.ordinal, None)
            self.failures += 1
            if not d.resolve(exc=e):
                self.abandoned += 1
            self._note_failure(d.ordinal, dispatch=d)
            return
        self._disarm_watchdog(d.ordinal)
        dt = time.monotonic() - t0
        with self._lock:
            self._inflight.pop(d.ordinal, None)
        if not d.resolve(out):
            # the watchdog already failed this dispatch over (the batch
            # reran elsewhere); the late result is discarded, not served
            self.abandoned += 1
            return
        self.latency.record(dt)
        self._note_success(dt, d.ordinal)

    # ----------------------------------------------------- health monitor
    def _note_success(self, dt: float, ordinal: int) -> None:
        self._consecutive_failures = 0
        slow = False
        if self._ewma_s is None:
            self._ewma_s = dt
        else:
            if (
                self._ewma_n >= self.policy.ewma_warmup
                and dt > self.policy.latency_factor * self._ewma_s
            ):
                slow = True
            self._ewma_s = (
                self.policy.ewma_decay * self._ewma_s
                + (1.0 - self.policy.ewma_decay) * dt
            )
        self._ewma_n += 1
        if slow and self.state is ReplicaState.HEALTHY:
            self._set_state(
                ReplicaState.DEGRADED,
                f"latency {dt * 1e3:.0f}ms > {self.policy.latency_factor:g}x ewma",
            )
        elif self.state is ReplicaState.DEGRADED and not slow:
            self._set_state(ReplicaState.HEALTHY, "good dispatch")

    def _note_failure(self, ordinal: int,
                      dispatch: Optional[_Dispatch] = None) -> None:
        self._consecutive_failures += 1
        if self._consecutive_failures >= self.policy.fail_threshold:
            # the dispatch whose failure crossed the budget is the trip's
            # attribution suspect even though its future already resolved
            self.trip(f"{self._consecutive_failures} consecutive failures",
                      suspect=dispatch)
        else:
            self._set_state(ReplicaState.DEGRADED, "dispatch failed")

    def _probe_batch(self) -> Dict[str, np.ndarray]:
        """Smallest-rung all-zeros batch through the real prepare path —
        the breaker's half-open probe and the DEGRADED self-check."""
        bh, bw = next(iter(self.runner.ladder))
        req = self.runner.make_request(np.zeros((bh, bw, 3), np.float32))
        return self.runner.assemble([req])

    def _probe(self) -> bool:
        """One probe batch through the fault-injectable predict path;
        success promotes DEGRADED→HEALTHY, failure counts toward the
        drain budget exactly like a served dispatch."""
        self.probes += 1
        with self._lock:
            ordinal = self._ordinal
            self._ordinal += 1
        t0 = time.monotonic()
        try:
            self.policy.retry.run(
                lambda attempt: self._predict(self._probe_batch(), ordinal,
                                              attempt)
            )
        except Exception:  # noqa: BLE001 — probes exist to absorb faults
            self.failures += 1
            self._note_failure(ordinal)
            return False
        self._note_success(time.monotonic() - t0, ordinal)
        if self.state is ReplicaState.DEGRADED:
            self._set_state(ReplicaState.HEALTHY, "probe ok")
        return True

    # ----------------------------------------------------------- recovery
    def _backoff_s(self) -> float:
        now = time.monotonic()
        recent = [
            t for t in self._trip_times if now - t <= self.policy.flap_window
        ]
        self._trip_times = recent
        if len(recent) <= 1:
            return 0.0  # first trip in the window: rejoin eagerly
        return min(
            self.policy.breaker_backoff * 2.0 ** (len(recent) - 2),
            self.policy.breaker_max_backoff,
        )

    def _recover(self, initial: bool = False) -> None:
        """WARMING/DRAINING → (breaker wait →) recompile → warmup →
        probe → HEALTHY.  Runs on the worker thread; loops (with a
        growing breaker backoff) until a probe passes or stop()."""
        if not initial:
            self._set_state(ReplicaState.RECOVERING, "begin recovery")
        while not self._stop:
            backoff = 0.0 if initial else self._backoff_s()
            if backoff > 0.0:
                self.breaker_opens += 1
                self.last_backoff = backoff
                logger.info(
                    "replica %d: breaker open, backoff %.3fs "
                    "(%d recent trips)", self.index, backoff,
                    len(self._trip_times),
                )
                time.sleep(backoff)
            try:
                if not initial:
                    # a REAL recompile: fresh runner (new jit callables,
                    # new compile cache) — but rewarm only the (model,
                    # bucket) signatures this replica ACTUALLY served
                    # (ISSUE 7 per-bucket warm partitioning); anything it
                    # never saw warms lazily on first dispatch.  Falls
                    # back to the full ladder when there is no traffic
                    # history or the runner predates the buckets= kwarg.
                    served = {
                        m: set(bs)
                        for m, bs in getattr(
                            self.runner, "served_buckets", {}
                        ).items()
                        if bs
                    }
                    self.runner = self._factory(self.index)
                    if hasattr(self.runner, "overlap"):
                        self.runner.overlap = self.overlap
                    self.rewarms += 1
                    if served:
                        try:
                            self.runner.warmup(buckets=served)
                            self.partial_rewarms += 1
                            self.last_rewarm_rungs = sum(
                                len(b) for b in served.values()
                            )
                        except TypeError:
                            self.runner.warmup()
                    else:
                        self.runner.warmup()
                else:
                    self.runner.warmup()
            except Exception as e:  # noqa: BLE001 — keep the replica parked
                self.failures += 1
                logger.error("replica %d: rewarm failed: %r", self.index, e)
                with self._lock:
                    self._trip_times.append(time.monotonic())
                initial = False
                continue
            # half-open: one probe batch must pass before taking traffic
            self.probes += 1
            with self._lock:
                ordinal = self._ordinal
                self._ordinal += 1
            try:
                self.policy.retry.run(
                    lambda attempt: self._predict(
                        self._probe_batch(), ordinal, attempt
                    )
                )
            except Exception as e:  # noqa: BLE001 — breaker reopens
                self.failures += 1
                logger.warning(
                    "replica %d: recovery probe failed: %r", self.index, e
                )
                with self._lock:
                    self._trip_times.append(time.monotonic())
                initial = False
                continue
            if not initial:
                # sacrificial suspect replay: confirm or clear the pool's
                # top attribution suspect before taking real traffic, so
                # K is reached in O(1) extra trips instead of K downed
                # replicas.  Its verdict never blocks the rejoin.
                self._isolation_probe()
            self._consecutive_failures = 0
            self._set_state(
                ReplicaState.HEALTHY, "warmup ok" if initial else "rejoin"
            )
            return

    @staticmethod
    def _replay_batch(payload: Dict[str, Any]) -> Dict[str, np.ndarray]:
        """Rebuild a batch-of-1 from a captured suspect payload, padded
        by slot-0 replication to the original slot count so the replay
        hits an already-warmed compile signature."""
        slots = max(1, int(payload.get("slots", 1)))
        return {k: np.stack([v] * slots)
                for k, v in payload["arrays"].items()}

    def _isolation_probe(self) -> None:
        """Replay the quarantine table's top suspect alone through the
        fault-instrumented predict path.  A clean, fast replay clears
        the suspect; a raise or a wedge (wall time past the stall
        watchdog) confirms poison and quarantines the digest
        immediately.  The probe is sacrificial: any outcome, the
        recovery proceeds."""
        qt = self.quarantine
        if qt is None:
            return
        top = qt.top_suspect()
        if top is None:
            return
        digest, payload = top
        if payload is None:
            qt.probe_result(digest, ok=None)  # nothing to replay: abstain
            return
        self.isolation_probes += 1
        with self._lock:
            ordinal = self._ordinal
            self._ordinal += 1
        t0 = time.monotonic()
        ok = True
        try:
            batch = self._replay_batch(payload)
            self._predict(batch, ordinal, 0, model=payload.get("model"),
                          digests=(digest,))
        except Exception as e:  # noqa: BLE001 — probe verdict, not a fault
            logger.info(
                "replica %d: isolation probe of %s raised: %r",
                self.index, digest[:12], e,
            )
            ok = False
        if ok and time.monotonic() - t0 > self.policy.stall_timeout:
            ok = False  # the suspect wedges predict: poison confirmed
        if ok:
            self.isolation_cleared += 1
        else:
            self.isolation_confirmed += 1
        qt.probe_result(digest, ok)

    # ---------------------------------------------------------- lifecycle
    def stop(self, timeout: float = 5.0) -> None:
        """Stop the worker; queued dispatches fail over (never hang).  A
        worker parked inside a wedged native call is abandoned as a
        daemon thread — joining it would inherit the wedge."""
        with self._lock:
            self._stop = True
        self.trip("stop")
        self._inbox.put(None)
        self._worker.join(timeout=timeout)

    # -------------------------------------------------------- observability
    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            transitions = list(self.transitions)
            state = self.state.value
        return {
            "index": self.index,
            "state": state,
            "inflight_depth": self.depth(),
            "dispatches": self.dispatches,
            "failures": self.failures,
            "retried": self.retried,
            "requeued_out": self.requeued_out,
            "abandoned": self.abandoned,
            "probes": self.probes,
            "rewarms": self.rewarms,
            "partial_rewarms": self.partial_rewarms,
            "last_rewarm_rungs": self.last_rewarm_rungs,
            "breaker_opens": self.breaker_opens,
            "last_backoff_s": round(self.last_backoff, 4),
            "isolation_probes": self.isolation_probes,
            "isolation_confirmed": self.isolation_confirmed,
            "isolation_cleared": self.isolation_cleared,
            "ewma_ms": (
                round(self._ewma_s * 1e3, 3) if self._ewma_s is not None
                else None
            ),
            "latency": self.latency.snapshot(),
            "overlap": self.overlap.snapshot(),
            "transitions": transitions,
        }
