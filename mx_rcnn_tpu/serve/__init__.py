"""Online inference serving for the TPU Faster R-CNN.

A request-level layer over the jitted test forward (ISSUE 2): images go
through a fixed (H, W) bucket ladder (``buckets``), a deadline-aware
dynamic micro-batcher (``batcher``), and one canonical predict path
(``runner``) shared with ``core/tester.py`` and ``tools/demo.py``;
``engine`` wires them into a threaded serving loop with per-request
retry, and ``metrics``/``loadgen`` provide latency observability and a
deterministic synthetic driver.  ISSUE 6 adds fault tolerance at fleet
scale: ``replica`` wraps one runner in a health-gated state machine
(WARMING → HEALTHY → DEGRADED → DRAINING → RECOVERING) and ``router``
pools N of them behind the same engine intake with least-loaded
bucket-affine dispatch, hedging, requeue-never-drop, and load shedding.
ISSUE 7 adds the model lifecycle: ``registry`` owns versioned model
state (LOADING → VERIFYING → WARMING → LIVE → RETIRED) with background
hot-swap, automatic rollback, and multi-tenant ``(model, version)``
resolution through the same batcher and pool.
ISSUE 16 adds the front door: ``tenancy`` (token-bucket admission +
weighted-fair release + shed-over-budget-first), ``frontend`` (the
length-prefixed wire protocol with a typed error taxonomy), and
``autoscaler`` (elastic replica count with a flap breaker and zero-loss
scale-down).
ISSUE 19 scales above the host: ``fleet`` runs a wire-protocol
``FleetGateway`` over N backend engine *processes* — pipelined
connection pools, host-level health/hedging/requeue-never-drop, and
fleet-merged snapshots.
See SERVING.md for the architecture and failure semantics.
"""

from mx_rcnn_tpu.serve.autoscaler import AutoScaler, ScaleBreaker, ScalePolicy
from mx_rcnn_tpu.serve.batcher import DynamicBatcher, QueueFull, Request
from mx_rcnn_tpu.serve.frontend import Frontend, FrontendClient
from mx_rcnn_tpu.serve.buckets import (
    BucketLadder,
    BucketOverflow,
    CompileCache,
)
from mx_rcnn_tpu.serve.engine import (
    DeadlineExceeded,
    EngineStopped,
    ServingEngine,
)
from mx_rcnn_tpu.serve.fleet import (
    BackendProc,
    BadWireVersion,
    FleetGateway,
    InvalidWireFrame,
    NoHealthyBackend,
    launch_backends,
    spawn_stub_backends,
)
from mx_rcnn_tpu.serve.metrics import (
    LatencyHistogram,
    ServeMetrics,
    merge_snapshots,
)
from mx_rcnn_tpu.serve.registry import (
    DEFAULT_MODEL,
    ModelRegistry,
    ModelVersion,
    RegistryError,
    SwapCancelled,
    SwapController,
    SwapError,
    SwapInProgress,
    SwapRolledBack,
    UnknownModel,
    VersionState,
)
from mx_rcnn_tpu.serve.replica import (
    HealthPolicy,
    Replica,
    ReplicaDrained,
    ReplicaState,
)
from mx_rcnn_tpu.serve.router import NoHealthyReplica, ReplicaPool
from mx_rcnn_tpu.serve.runner import ServeRunner
from mx_rcnn_tpu.serve.tenancy import (
    TenantOverBudget,
    TenantPolicy,
    TenantTable,
    UnknownTenant,
    WeightedFairScheduler,
)

__all__ = [
    "AutoScaler",
    "BackendProc",
    "BadWireVersion",
    "BucketLadder",
    "BucketOverflow",
    "CompileCache",
    "DEFAULT_MODEL",
    "DeadlineExceeded",
    "DynamicBatcher",
    "EngineStopped",
    "FleetGateway",
    "Frontend",
    "FrontendClient",
    "HealthPolicy",
    "InvalidWireFrame",
    "LatencyHistogram",
    "ModelRegistry",
    "ModelVersion",
    "NoHealthyBackend",
    "NoHealthyReplica",
    "QueueFull",
    "RegistryError",
    "Replica",
    "ReplicaDrained",
    "ReplicaPool",
    "ReplicaState",
    "Request",
    "ScaleBreaker",
    "ScalePolicy",
    "ServeMetrics",
    "ServeRunner",
    "ServingEngine",
    "SwapCancelled",
    "SwapController",
    "SwapError",
    "SwapInProgress",
    "SwapRolledBack",
    "TenantOverBudget",
    "TenantPolicy",
    "TenantTable",
    "UnknownModel",
    "UnknownTenant",
    "VersionState",
    "WeightedFairScheduler",
    "launch_backends",
    "merge_snapshots",
    "spawn_stub_backends",
]
