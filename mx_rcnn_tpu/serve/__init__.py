"""Online inference serving for the TPU Faster R-CNN.

A request-level layer over the jitted test forward (ISSUE 2): images go
through a fixed (H, W) bucket ladder (``buckets``), a deadline-aware
dynamic micro-batcher (``batcher``), and one canonical predict path
(``runner``) shared with ``core/tester.py`` and ``tools/demo.py``;
``engine`` wires them into a threaded serving loop with per-request
retry, and ``metrics``/``loadgen`` provide latency observability and a
deterministic synthetic driver.  See SERVING.md for the architecture.
"""

from mx_rcnn_tpu.serve.batcher import DynamicBatcher, QueueFull, Request
from mx_rcnn_tpu.serve.buckets import (
    BucketLadder,
    BucketOverflow,
    CompileCache,
)
from mx_rcnn_tpu.serve.engine import DeadlineExceeded, ServingEngine
from mx_rcnn_tpu.serve.metrics import LatencyHistogram, ServeMetrics
from mx_rcnn_tpu.serve.runner import ServeRunner

__all__ = [
    "BucketLadder",
    "BucketOverflow",
    "CompileCache",
    "DeadlineExceeded",
    "DynamicBatcher",
    "LatencyHistogram",
    "QueueFull",
    "Request",
    "ServeMetrics",
    "ServeRunner",
    "ServingEngine",
]
