"""Threaded serving loop: submit → batcher → device → per-request futures.

Thread layout (why threads, not an async dispatch chain: the relay-
attached TPU does not overlap stages of successive one-thread dispatches
— measured in ``core/tester.py :: pipelined`` — but blocking predicts
from separate threads DO overlap, the GIL dropping during relay I/O):

  * N client threads: ``submit`` prepares the image (resize/quantize/
    pad) in the CALLER's thread, so host preprocessing of the next
    requests overlaps device execution of earlier batches, then enqueues
    into the bounded batcher (``QueueFull`` → backpressure).
  * 1 assembler thread: pulls bucket-homogeneous batches from the
    batcher, fails requests whose deadline already passed (cheaper than
    running them), pads to ``max_batch``, and hands the batch to…
  * ``in_flight`` completion threads: blocking ``runner.run`` (wrapped
    in PR 1's :class:`~mx_rcnn_tpu.core.resilience.RetryPolicy` — a
    transient device/relay fault retries the whole batch
    deterministically), then per-request detections + future resolution.
    The workers live in a bounded
    :class:`~mx_rcnn_tpu.data.assembler.CompletionPool` whose blocking
    submit keeps the assembler at most ``in_flight`` batches ahead, so
    device-side queueing stays bounded too — and whose counters land in
    :meth:`ServingEngine.snapshot`.

Every request resolves exactly once: detections list, or
:class:`DeadlineExceeded` / :class:`QueueFull` /
:class:`~mx_rcnn_tpu.serve.buckets.BucketOverflow` / the predict error
after retries are exhausted / :class:`EngineStopped` when the engine is
torn down first (``stop`` sweeps the live-request registry, so a
submitter can never block forever on a dead engine).

The runner may also be a :class:`~mx_rcnn_tpu.serve.router.ReplicaPool`
(detected by its ``replicas`` attribute): the engine then passes each
batch's tightest deadline to ``run`` and disables its own RetryPolicy —
retry, hedging, and failover belong to the pool — and ``submit`` sheds
load early (``QueueFull`` + ``shed`` counter) when the pool's healthy
fraction scales the effective queue capacity below the current backlog.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Dict, List, Optional

import numpy as np

from mx_rcnn_tpu.core.resilience import RetryPolicy, make_retry_policy
from mx_rcnn_tpu.analysis.lockcheck import make_lock
from mx_rcnn_tpu.data.assembler import CompletionPool
from mx_rcnn_tpu.serve.batcher import (
    DEFAULT_LANE,
    DeadlineExceeded,
    DynamicBatcher,
    LANES,
    QueueFull,
    Request,
)
from mx_rcnn_tpu.serve.metrics import ServeMetrics
from mx_rcnn_tpu.serve.quarantine import (
    BatchBudget,
    InvalidRequest,
    PoisonRequest,
    RetriesExhausted,
    RetryBudget,
    request_digest,
    validate_image,
)
from mx_rcnn_tpu.serve.runner import ServeRunner
from mx_rcnn_tpu.serve.streams import StreamTable

# DeadlineExceeded historically lived here; it moved to serve.batcher so
# the expired-request sweep can raise it without a circular import, and
# stays re-exported for every existing `from serve.engine import` site.
# The containment taxonomy (ISSUE 12) lives in serve.quarantine and is
# re-exported here for the same reason: clients catch engine errors.
__all__ = [
    "DeadlineExceeded", "EngineStopped", "ServingEngine",
    "InvalidRequest", "PoisonRequest", "RetriesExhausted",
]


class EngineStopped(RuntimeError):
    """The engine was torn down before this request completed — a
    terminal resolution, so no submitter is ever left blocked on a
    future the engine will never touch again."""


class ServingEngine:
    """Online inference front-end over a :class:`ServeRunner`."""

    def __init__(
        self,
        runner: ServeRunner,
        max_linger: float = 0.005,
        max_queue: int = 64,
        in_flight: int = 2,
        retry: Optional[RetryPolicy] = None,
        interactive_linger: float = 0.0,
        bulk_age_limit: float = 2.0,
        response_cache=None,
        retry_budget: int = 8,
        tenants=None,
        shed_fraction: float = 0.75,
    ):
        self.runner = runner
        # multi-tenant front door (ISSUE 16): a TenantTable turns on
        # token-bucket admission at submit, weighted-fair release in the
        # batcher, shed-over-budget-tenant-first under pressure, and the
        # per-tenant metrics partition
        self.tenants = tenants
        self.shed_fraction = float(shed_fraction)
        fair = None
        if tenants is not None:
            from mx_rcnn_tpu.serve.tenancy import WeightedFairScheduler

            fair = WeightedFairScheduler(weight_fn=tenants.weight)
        self.batcher = DynamicBatcher(
            runner.max_batch, max_linger=max_linger, max_queue=max_queue,
            interactive_linger=interactive_linger,
            bulk_age_limit=bulk_age_limit,
            on_expired=self._expire_swept,
            fair=fair,
        )
        # idempotent response cache (serve/respcache.py), keyed by image
        # digest per (model, live version); the registry's live-pointer
        # hook invalidates on hot-swap so hits can never be stale
        self.response_cache = response_cache
        if response_cache is not None:
            reg = getattr(runner, "registry", None)
            if reg is not None and hasattr(reg, "subscribe_live"):
                reg.subscribe_live(response_cache.invalidate_model)
        self.metrics = ServeMetrics()
        self.retry = retry if retry is not None else make_retry_policy("serve")
        self._in_flight = max(1, int(in_flight))
        self._pool: Optional[CompletionPool] = None
        self._assembler: Optional[threading.Thread] = None
        self._started = False
        # a ReplicaPool routes/retries/hedges internally; the engine then
        # skips its own RetryPolicy and sheds early on pool health
        self._routed = hasattr(runner, "replicas")
        # query-of-death containment (ISSUE 12): active when the pool
        # carries a QuarantineTable — the engine then digests every
        # request at admission, attaches retry budgets, and splits
        # implicated batches instead of failing them wholesale
        self._quarantine = getattr(runner, "quarantine", None)
        self._retry_budget = max(1, int(retry_budget))
        self._aborting = False
        # elastic capacity (ISSUE 16): a background AutoScaler attached
        # via attach_autoscaler; stop() joins it BEFORE pool teardown
        self.autoscaler = None
        # progressive rollout (ISSUE 17): a RolloutController attached
        # via attach_rollout — submit consults it for arm assignment,
        # _complete feeds it evidence; stop() joins it with the swaps
        self.rollout = None
        # confidence-gated cascade (ISSUE 18): a CascadeRouter attached
        # via attach_cascade — submit reroutes flagship requests to the
        # cheap family, _complete runs the gate and escalates uncertain
        # first passes back through the batcher as flagship requests
        self.cascade = None
        # streaming mode (ISSUE 20): per-stream in-order delivery gate
        # at _resolve — the exactly-once choke point every redispatch
        # path (trip/requeue/hedge/resubmit/escalation) funnels through,
        # so frames of one stream complete in order no matter how they
        # executed.  Untagged requests bypass it entirely.
        self.streams = StreamTable()
        # every not-yet-resolved request, so stop() can sweep leftovers
        # with a terminal EngineStopped instead of stranding submitters
        self._live: Dict[int, Request] = {}
        self._live_lock = make_lock("ServingEngine._live_lock")

    # ---------------------------------------------------------- lifecycle
    def start(self, warmup: bool = True) -> "ServingEngine":
        if self._started:
            return self
        if warmup:
            self.runner.warmup()
        # same thread layout as before (in_flight workers, submit blocks
        # at depth=in_flight — the old semaphore), but the pool exports
        # the shared data-plane counters into snapshot()
        self._pool = CompletionPool(
            self._in_flight, depth=self._in_flight, name="serve-complete"
        )
        self._assembler = threading.Thread(
            target=self._assemble_loop, name="serve-assemble", daemon=True
        )
        self._started = True
        self._assembler.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop accepting and join threads.  ``drain=True`` finishes
        queued work first; ``drain=False`` aborts — queued batches are
        failed instead of dispatched.  Either way every still-pending
        future is resolved (terminal :class:`EngineStopped`) before this
        returns: no submitter is left blocked on a dead engine.

        Swap interlock (ISSUE 7): any in-flight background model swap is
        cancelled FIRST, waiting for its controller thread to exit — so
        no orphaned warmup thread survives the engine and no swap-side
        ``device_put`` runs after stop returns.

        Autoscaler interlock (ISSUE 16, same pattern): the controller
        thread is stopped and JOINED before pool teardown — a stop
        racing a scale-up must not leave an orphaned controller minting
        replicas (and device placements) into a pool being closed."""
        if not self._started:
            return
        reg = getattr(self.runner, "registry", None)
        if reg is not None:
            reg.cancel_swaps(wait=True)
        if self.rollout is not None:
            # same interlock as swaps: cancel in-flight rollouts and
            # join the shadow worker before any pool/batcher teardown,
            # so no rollout-side device work runs after stop returns
            self.rollout.stop()
        if self.autoscaler is not None:
            self.autoscaler.stop()
        if not drain:
            self._aborting = True
        self.batcher.close()
        if self._assembler is not None:
            self._assembler.join()
        # raise_errors=False: request futures already carry per-request
        # failures; an engine drain must not re-raise them at shutdown
        if self._pool is not None:
            self._pool.close(raise_errors=False)
        self._started = False
        # results already settled but parked behind a stream gap must
        # ship before the leftover sweep fails their successors — no
        # settled result is ever lost to a stop (ordering is best-effort
        # at teardown: the gap frames resolve EngineStopped below)
        self.streams.flush()
        with self._live_lock:
            leftovers = list(self._live.values())
            self._live.clear()
        stopped = EngineStopped("engine stopped before request completed")
        for r in leftovers:
            try:
                r.future.set_exception(stopped)
            except InvalidStateError:
                continue
            self.metrics.inc("stopped")

    def attach_autoscaler(self, policy=None, signal_fn=None, start=True):
        """Create (and by default start) an
        :class:`~mx_rcnn_tpu.serve.autoscaler.AutoScaler` bound to this
        engine's replica pool.  Requires a routed runner.  The engine
        owns its lifecycle from here: ``stop()`` joins the controller
        before tearing the pool down."""
        if not self._routed:
            raise RuntimeError(
                "autoscaling needs a ReplicaPool runner — single-runner "
                "engines have nothing to scale"
            )
        from mx_rcnn_tpu.serve.autoscaler import AutoScaler

        self.autoscaler = AutoScaler(
            self.runner, policy=policy, engine=self, signal_fn=signal_fn
        )
        if start:
            self.autoscaler.start()
        return self.autoscaler

    def attach_rollout(self, policy=None):
        """Create a
        :class:`~mx_rcnn_tpu.serve.rollout.RolloutController` bound to
        this engine's registry and runner/pool.  From here ``submit``
        consults it for deterministic arm assignment, ``_complete``
        feeds it per-arm evidence and mirrors incumbent completions
        into the shadow lane, and ``stop()`` joins it alongside the
        swap interlock."""
        reg = getattr(self.runner, "registry", None)
        if reg is None:
            raise RuntimeError(
                "progressive rollout needs a registry-backed "
                "ServeRunner/ReplicaPool"
            )
        from mx_rcnn_tpu.serve.rollout import RolloutController

        self.rollout = RolloutController(
            reg, self.runner, engine=self, policy=policy
        )
        return self.rollout

    def attach_cascade(self, policy) -> "CascadeRouter":
        """Bind a :class:`~mx_rcnn_tpu.serve.cascade.CascadePolicy` to
        this engine.  From here every request resolving to the policy's
        flagship family first serves on the cheap family; ``_complete``
        runs the pure-host confidence gate on the first pass's
        detections and either resolves (sufficient) or re-enters the
        batcher as a flagship request with the original lane, tenant,
        deadline, digest, and retry budget intact.  Requests addressed
        to any other family — including direct cheap-family traffic —
        are untouched."""
        from mx_rcnn_tpu.serve.cascade import CascadePolicy, CascadeRouter

        if not isinstance(policy, CascadePolicy):
            policy = CascadePolicy(**dict(policy))
        reg = getattr(self.runner, "registry", None)
        if reg is not None:
            for mid in (policy.cheap, policy.flagship):
                if not reg.has(mid):
                    from mx_rcnn_tpu.serve.registry import UnknownModel

                    raise UnknownModel(
                        f"cascade family {mid!r} is not registered"
                    )
        self.cascade = CascadeRouter(policy)
        return self.cascade

    def _precision_tag(self, model: Optional[str]) -> str:
        """Serve-graph precision of ``model`` on this engine's runner
        ("f32" for stub runners without precision plumbing) — joins the
        response-cache key so rungs never share bytes."""
        pf = getattr(self.runner, "_precision_for", None)
        if pf is None:
            return "f32"
        try:
            return pf(self._resolved_mid(model))
        except Exception:  # noqa: BLE001 — unknown model: default tag
            return "f32"

    def _resolved_mid(self, model: Optional[str]) -> Optional[str]:
        """Registry model id a request resolves to (the rollout tables
        are keyed by it, never by None)."""
        if model is not None:
            return model
        mid = getattr(self.runner, "default_model", None)
        if mid is not None:
            return mid
        reg = getattr(self.runner, "registry", None)
        if reg is not None:
            try:
                return reg.default_model
            except Exception:  # noqa: BLE001 — empty registry
                return None
        return None

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- client
    def _lane_for(self, model: Optional[str], lane: Optional[str]) -> str:
        """Resolve a request's SLO lane: explicit tag wins, else the
        model's registry-declared SLO class (an interactive-tier model
        taints its requests' lane), else bulk."""
        if lane is not None:
            if lane not in LANES:
                raise ValueError(f"unknown SLO lane {lane!r}")
            return lane
        reg = getattr(self.runner, "registry", None)
        if reg is not None and hasattr(reg, "slo_class"):
            return reg.slo_class(model)
        return DEFAULT_LANE

    def _live_version(self, model: Optional[str]) -> Optional[int]:
        """Current live version of ``model`` (None when the runner has no
        registry — stub runners — or no live version yet)."""
        reg = getattr(self.runner, "registry", None)
        if reg is None or not hasattr(reg, "live"):
            return None
        try:
            return int(reg.live(model).version)
        except Exception:  # noqa: BLE001 — no live version = no caching
            return None

    def _stream_admit(self, stream, frame) -> bool:
        """Validate + register a streaming submit's ``(stream, frame)``
        identity; True when registered (the caller must cancel on any
        later synchronous rejection, or the permanent gap would buffer
        the stream's later frames forever)."""
        if stream is None and frame is None:
            return False
        if stream is None or frame is None:
            self.metrics.inc("invalid")
            self.metrics.inc("rejected")
            raise InvalidRequest(
                "stream and frame must be provided together"
            )
        try:
            self.streams.register(stream, frame)
        except (TypeError, ValueError) as e:
            self.metrics.inc("invalid")
            self.metrics.inc("rejected")
            raise InvalidRequest(f"bad stream/frame: {e}")
        return True

    def _stream_cancel(self, stream, frame) -> None:
        if stream is not None and frame is not None:
            self.streams.cancel(stream, int(frame))

    def submit(
        self,
        im: np.ndarray,
        deadline_s: Optional[float] = None,
        model: Optional[str] = None,
        lane: Optional[str] = None,
        tenant: Optional[str] = None,
        stream: Optional[str] = None,
        frame: Optional[int] = None,
        masks: bool = False,
    ) -> Future:
        """Enqueue one image; returns a Future resolving to the
        per-class detections list.  ``model`` selects a registry family
        (None = the default model — the tenancy request schema);
        ``lane`` tags the SLO class (``"interactive"`` | ``"bulk"``,
        None = the model's registry default); ``tenant`` is the fair-
        share identity (None = untagged in-process caller).

        Streaming mode (ISSUE 20): ``stream``/``frame`` (always
        together; ``frame`` strictly increasing per stream) put the
        request under the per-stream in-order delivery guarantee —
        frames of one stream resolve in frame order no matter how
        trips, requeues, hedges, or escalations reorder execution;
        cross-stream and untagged traffic is unordered and unaffected.
        ``masks=True`` resolves to ``(cls_dets, rles)`` — canvas-space
        mask RLEs from the runner's device-paste path (requires a mask
        model family).  Raises
        :class:`~mx_rcnn_tpu.serve.quarantine.InvalidRequest` (failed
        the admission gate),
        :class:`~mx_rcnn_tpu.serve.quarantine.PoisonRequest` (digest is
        quarantined),
        :class:`~mx_rcnn_tpu.serve.tenancy.UnknownTenant` /
        :class:`~mx_rcnn_tpu.serve.tenancy.TenantOverBudget` (tenant
        admission, with a TenantTable configured),
        :class:`~mx_rcnn_tpu.serve.buckets.BucketOverflow` (oversize),
        :class:`~mx_rcnn_tpu.serve.batcher.QueueFull` (backpressure), or
        :class:`~mx_rcnn_tpu.serve.registry.UnknownModel` synchronously
        — all count as ``rejected``."""
        if not self._started:
            raise RuntimeError("engine not started")
        if self.tenants is not None:
            # tenant admission BEFORE any image work: an unknown tenant
            # or an empty token bucket must cost nothing but this check
            # (the quarantine fast-fail discipline, applied per tenant)
            from mx_rcnn_tpu.serve.tenancy import TenantOverBudget

            try:
                self.tenants.admit(tenant)
            except TenantOverBudget:
                self.metrics.inc("over_budget")
                self.metrics.inc("rejected")
                self.metrics.record_tenant(tenant, rejected=True)
                raise
            except Exception:
                self.metrics.inc("rejected")
                raise
        reg = getattr(self.runner, "registry", None)
        if model is not None:
            if reg is not None and not reg.has(model):
                self.metrics.inc("rejected")
                from mx_rcnn_tpu.serve.registry import UnknownModel

                raise UnknownModel(model)
        # admission gate (ISSUE 12): malformed work fails the CALLER
        # with a typed error before it can reach the batcher or crash
        # the shared assembler thread; registry-declared per-model
        # bounds tighten the default shape/size limits
        limits = None
        if reg is not None and hasattr(reg, "limits"):
            try:
                limits = reg.limits(model)
            except Exception:  # noqa: BLE001 — no entry yet: defaults
                limits = None
        try:
            im = validate_image(im, limits)
        except InvalidRequest:
            self.metrics.inc("invalid")
            self.metrics.inc("rejected")
            raise
        digest = None
        if self._quarantine is not None:
            digest = request_digest(im)
            if self._quarantine.quarantined(digest):
                # fail fast: a quarantined query of death must not cost
                # another replica trip, or even a queue slot
                self.metrics.inc("poisoned")
                self.metrics.inc("rejected")
                raise PoisonRequest(
                    f"digest {digest[:12]} is quarantined (query of death)"
                )
        lane = self._lane_for(model, lane)
        # streaming admission: validate + register the (stream, frame)
        # identity BEFORE any path that can resolve the future (cache
        # hits included), so every resolution goes through the gate in
        # registration order
        streamed = self._stream_admit(stream, frame)
        # cascade reroute (ISSUE 18): a request resolving to the
        # flagship family serves the cheap family first; the gate at
        # completion decides escalation.  The LANE above was resolved
        # from the original (flagship) target — the cheap pass and any
        # escalation both ride it, so cascading never demotes an SLO.
        serve_model = model
        cascade_first = False
        if self.cascade is not None \
                and self._resolved_mid(model) == self.cascade.policy.flagship:
            serve_model = self.cascade.policy.cheap
            cascade_first = True
        arm_version = None
        if self.rollout is not None:
            # deterministic arm assignment (ISSUE 17): the content
            # digest — not a coin flip — picks the arm, so a repeated
            # request always lands on the same version and the response
            # cache stays arm-coherent by construction.  Under a
            # cascade the first pass serves the CHEAP family, so its
            # rollouts are the ones consulted here; a flagship rollout
            # is consulted at escalation time instead.
            mid_r = self._resolved_mid(serve_model)
            if mid_r is not None and self.rollout.active(mid_r):
                if digest is None:
                    digest = request_digest(im)
                arm_version = self.rollout.arm_for(mid_r, digest)
        cache_key = None
        # masks requests bypass the response cache: keys are image-
        # content keyed and a (dets, rles) tuple must never collide
        # with a plain-detections entry for the same bytes
        if self.response_cache is not None and not masks:
            t0 = time.monotonic()
            if cascade_first:
                # the final serving of a cascaded digest may be the
                # flagship (escalated earlier) — probe that key first;
                # the gate is deterministic per (policy, cheap version,
                # image), so at most one of the two keys can exist
                fmid = self.cascade.policy.flagship
                fver = self._live_version(fmid)
                if fver is not None:
                    fhit = self.response_cache.get(
                        self.response_cache.key_for(
                            im, fmid, fver, self._precision_tag(fmid)
                        )
                    )
                    if fhit is not None:
                        return self._cached_future(
                            fhit, t0, lane, tenant, model, stream, frame
                        )
            # split serving: the key carries the SERVED arm's version,
            # not the live pointer — two versions serve concurrently
            # under a split and must never share cache entries
            version = (
                arm_version if arm_version is not None
                else self._live_version(serve_model)
            )
            if version is not None:
                reg = getattr(self.runner, "registry", None)
                mid = (
                    serve_model if serve_model is not None
                    else getattr(self.runner, "default_model", None)
                    or reg.default_model
                )
                cache_key = self.response_cache.key_for(
                    im, mid, version, self._precision_tag(mid)
                )
                hit = self.response_cache.get(cache_key)
                if hit is not None:
                    # byte-identical by construction: the stored arrays
                    # ARE what the miss returned (callers treat
                    # detections as immutable)
                    return self._cached_future(
                        hit, t0, lane, tenant, model, stream, frame
                    )
        cap = self.batcher.max_queue
        if self._routed:
            # load shedding: scale the effective intake capacity by the
            # pool's healthy fraction — when half the replicas are out,
            # rejecting at half queue depth beats queueing work the pool
            # cannot clear before its deadlines
            frac = self.runner.healthy_fraction()
            cap = max(1, int(self.batcher.max_queue * frac))
            if frac == 0.0 or self.batcher.pending() >= cap:
                self.metrics.inc("shed")
                self.metrics.inc("rejected")
                if tenant is not None:
                    self.metrics.record_tenant(tenant, shed=True)
                if streamed:
                    self._stream_cancel(stream, frame)
                raise QueueFull(
                    f"shedding load: healthy fraction {frac:.2f}, "
                    f"effective queue capacity {cap if frac else 0}"
                )
        if self.tenants is not None and tenant is not None:
            # shed the over-budget tenant FIRST: past the pressure
            # threshold, a tenant already holding more than its weight
            # share of the backlog is rejected while under-share tenants
            # keep landing until the hard cap — overload cost falls on
            # whoever caused it
            pending = self.batcher.pending()
            if pending >= self.shed_fraction * cap:
                by_t = self.batcher.queued_by_tenant()
                if self.tenants.over_share(tenant, by_t):
                    from mx_rcnn_tpu.serve.tenancy import TenantOverBudget

                    self.tenants.note_shed(tenant)
                    self.metrics.inc("tenant_shed")
                    self.metrics.inc("shed")
                    self.metrics.inc("rejected")
                    self.metrics.record_tenant(tenant, shed=True)
                    if streamed:
                        self._stream_cancel(stream, frame)
                    raise TenantOverBudget(
                        f"shedding tenant {tenant!r}: holds "
                        f"{by_t.get(tenant, 0)}/{pending} queued requests, "
                        f"over its fair share under pressure"
                    )
        deadline = (
            time.monotonic() + deadline_s if deadline_s is not None else None
        )
        try:
            # model passed only when explicit, so runner fakes/stubs with
            # the legacy two-arg make_request keep working unchanged
            if serve_model is None:
                req = self.runner.make_request(im, deadline=deadline)
            else:
                req = self.runner.make_request(
                    im, deadline=deadline, model=serve_model
                )
            req.lane = lane
            req.tenant = tenant
            req.cache_key = cache_key
            if streamed:
                req.stream = stream
                req.frame = int(frame)
            req.masks = bool(masks)
            if cascade_first:
                # keep the validated pixels so an escalation can
                # re-prepare them for the flagship family's config
                req.cascade = True
                req.raw_image = im
            if digest is not None:
                req.digest = digest
                if self._quarantine is not None:
                    req.budget = RetryBudget(self._retry_budget)
            if arm_version is not None:
                # candidate-arm requests release as a batch-of-1 (solo):
                # a device batch is never a mix of arms, so one predict
                # serves exactly one version
                req.arm_version = arm_version
                req.solo = True
            self.batcher.submit(req)
        except Exception:
            self.metrics.inc("rejected")
            if streamed:
                # withdraw the registration or the stream deadlocks on
                # the permanent gap
                self._stream_cancel(stream, frame)
            raise
        with self._live_lock:
            self._live[id(req)] = req
            if req.future.done():
                # a concurrent sweep resolved it between batcher.submit
                # and here — don't leave a dead entry in the live set
                self._live.pop(id(req), None)
        self.metrics.inc("submitted")
        self.metrics.record_queue_depth(self.batcher.pending())
        return req.future

    def _cached_future(
        self,
        hit,
        t0: float,
        lane: str,
        tenant: Optional[str],
        model: Optional[str],
        stream: Optional[str] = None,
        frame: Optional[int] = None,
    ) -> Future:
        """Resolve a response-cache hit: a pre-completed Future plus the
        same request accounting a recompute would have produced.  A
        stream-tagged hit still goes through the delivery gate — a
        cached frame N+1 must not resolve before in-flight frame N."""
        f: Future = Future()

        def fire() -> bool:
            try:
                f.set_result(hit)
                return True
            except InvalidStateError:
                return False

        if stream is None:
            fire()
        else:
            self.streams.settle(stream, int(frame), fire)
        self.metrics.inc("submitted")
        self.metrics.inc("completed")
        e2e = time.monotonic() - t0
        self.metrics.e2e.record(e2e)
        self.metrics.record_lane(lane, e2e_s=e2e)
        self.metrics.record_tenant(tenant, e2e_s=e2e)
        if model is not None:
            self.metrics.record_model(model, e2e)
        return f

    # ------------------------------------------------------------- device
    def _expire_swept(self, req: Request, now: float) -> None:
        """Batcher sweep hook: a queued request's deadline passed before
        any batch could include it — fail it NOW (the client has already
        moved on) instead of letting it occupy queue and batch slots
        until pickup.  Runs under the batcher's condition lock; both
        callees only take leaf locks."""
        self.metrics.inc("expired")
        self.metrics.record_lane(req.lane, expired=True)
        self.metrics.record_tenant(req.tenant, expired=True)
        self._resolve(
            req,
            exc=DeadlineExceeded(
                f"deadline passed {now - req.deadline:.3f}s before "
                f"device pickup (swept from queue)"
            ),
        )

    def _resolve(self, req: Request, result=None,
                 exc: Optional[BaseException] = None) -> bool:
        """Resolve one request exactly once and retire it from the live
        registry; False when it already resolved elsewhere (e.g. swept
        by a concurrent ``stop``).

        Stream-tagged requests route through the StreamTable gate:
        delivery (success AND failure — a client never sees frame N+1
        before learning frame N's fate) waits for every earlier frame
        of the stream, while cross-stream and untagged resolutions are
        untouched.  True here means the settlement was ACCEPTED — it
        fires now or when the stream gap closes, exactly once."""
        with self._live_lock:
            self._live.pop(id(req), None)

        def fire() -> bool:
            try:
                if exc is not None:
                    req.future.set_exception(exc)
                else:
                    req.future.set_result(result)
                return True
            except InvalidStateError:
                return False

        if req.stream is None:
            return fire()
        return self.streams.settle(req.stream, req.frame, fire)

    def _assemble_loop(self) -> None:
        while True:
            batch_reqs = self.batcher.next_batch()
            if batch_reqs is None:
                return
            if self._aborting:
                stopped = EngineStopped("engine aborted before dispatch")
                for r in batch_reqs:
                    if self._resolve(r, exc=stopped):
                        self.metrics.inc("stopped")
                continue
            now = time.monotonic()
            live: List[Request] = []
            for r in batch_reqs:
                if r.expired(now):
                    self.metrics.inc("expired")
                    self.metrics.record_lane(r.lane, expired=True)
                    self.metrics.record_tenant(r.tenant, expired=True)
                    self._resolve(
                        r,
                        exc=DeadlineExceeded(
                            f"deadline passed {now - r.deadline:.3f}s before "
                            f"device pickup"
                        ),
                    )
                else:
                    self.metrics.queue_wait.record(r.picked_t - r.enqueue_t)
                    live.append(r)
            self.metrics.record_queue_depth(self.batcher.pending())
            if not live:
                continue
            batch = self.runner.assemble(live)
            # pool submit blocks at depth=in_flight: at most in_flight
            # batches on the device (the old explicit semaphore)
            self._pool.submit(self._complete, live, batch)

    def _complete(
        self, reqs: List[Request], batch: Dict[str, np.ndarray]
    ) -> None:
        # runs on a completion-pool worker; the pool's depth slot is
        # released when this returns, unblocking the assembler
        t0 = time.monotonic()
        model = reqs[0].model
        lane = reqs[0].lane
        # model kwarg only when the batch carries one (legacy runner
        # fakes keep their run(batch) signature)
        mkw = {} if model is None else {"model": model}
        # rollout split (ISSUE 17): a candidate-arm request is always
        # solo, so the whole batch shares one arm_version
        arm_ver = reqs[0].arm_version
        served_version: Optional[int] = None
        try:
            if arm_ver is not None and self.rollout is not None:
                try:
                    out = self.runner.run_version(
                        batch, version=arm_ver, **mkw
                    )
                    served_version = arm_ver
                except Exception as arm_e:  # noqa: BLE001 — any arm failure
                    # the candidate arm failed (rolled back mid-flight,
                    # or the candidate itself raised): count it as
                    # evidence, then serve the request on the incumbent
                    # — a rollout never loses a request
                    self.rollout.note_arm_error(
                        self._resolved_mid(model), arm_e
                    )
                    out = self._run_batch(batch, reqs, lane, mkw)
            else:
                out = self._run_batch(batch, reqs, lane, mkw)
        except Exception as e:
            self._settle_failed(reqs, e)
            return
        done = time.monotonic()
        self.metrics.service.record(done - t0)
        self.metrics.record_batch(len(reqs), self.runner.max_batch)
        self.metrics.record_lane_batch(lane, len(reqs), self.runner.max_batch)
        for k, r in enumerate(reqs):
            # deadline re-check at completion: a request that expired
            # while its batch waited behind a slow/hedged predict must
            # report DeadlineExceeded, not a stale success
            if r.expired():
                self.metrics.inc("expired")
                self.metrics.record_lane(r.lane, expired=True)
                self.metrics.record_tenant(r.tenant, expired=True)
                self._resolve(
                    r,
                    exc=DeadlineExceeded(
                        "deadline passed while the batch was in flight"
                    ),
                )
                continue
            try:
                if r.masks:
                    # streaming mask serve: canvas-space RLEs from the
                    # device-paste path (host keeps only RLE encoding);
                    # result = (cls_dets, rles), paste cost counted
                    cls_dets, rles = self.runner.mask_rles_for(
                        out, batch, k, orig_hw=r.orig_hw, **mkw
                    )
                    dets = (cls_dets, rles)
                    lp = getattr(self.runner, "last_paste_ms", None)
                    if lp is None:
                        ref = getattr(self.runner, "_ref", None)
                        lp = getattr(ref, "last_paste_ms", 0.0)
                        lb = getattr(ref, "last_paste_bytes", 0)
                    else:
                        lb = getattr(self.runner, "last_paste_bytes", 0)
                    self.metrics.record_paste(lp or 0.0, lb or 0)
                else:
                    dets = self.runner.detections_for(
                        out, batch, k, orig_hw=r.orig_hw, **mkw
                    )
            except Exception as e:  # postprocess bug: fail this request
                self.metrics.inc("failed")
                if model is not None:
                    self.metrics.record_model(model, ok=False)
                self.metrics.record_lane(r.lane, ok=False)
                self.metrics.record_tenant(r.tenant, ok=False)
                self._resolve(r, exc=e)
                continue
            if r.cascade and not r.escalated and self.cascade is not None:
                # confidence gate (ISSUE 18): pure host numpy over the
                # decoded cheap-pass detections — no lock held, nothing
                # on device.  Sufficient → the cheap answer ships below
                # under the CHEAP family's cache key; uncertain → the
                # request re-enters the batcher as a flagship request
                # and nothing about this pass is cached or resolved.
                if self.cascade.sufficient(dets[0] if r.masks else dets):
                    self.metrics.inc("first_pass_sufficient")
                else:
                    self.metrics.inc("escalations")
                    self._escalate(r)
                    continue
            if r.cache_key is not None and self.response_cache is not None:
                # store only if the version that SERVED is still the one
                # the key was minted against — a swap that landed
                # mid-flight, or a candidate arm that fell back to the
                # incumbent, must not seed the cache under a version
                # that did not produce these bytes
                if arm_ver is not None:
                    ok_put = (
                        served_version is not None
                        and served_version == r.cache_key[1]
                    )
                else:
                    ok_put = self._live_version(model) == r.cache_key[1]
                if ok_put:
                    self.response_cache.put(r.cache_key, dets)
            if self._quarantine is not None and r.digest is not None:
                # a suspect that completes cleanly was an innocent
                # co-batched bystander: drop the suspicion
                if self._quarantine.exonerate(r.digest):
                    self.metrics.inc("exonerated")
            self.metrics.inc("completed")
            e2e_s = time.monotonic() - r.enqueue_t
            self.metrics.e2e.record(e2e_s)
            if model is not None:
                self.metrics.record_model(model, e2e_s)
            self.metrics.record_lane(
                r.lane, e2e_s, queue_wait_s=r.picked_t - r.enqueue_t
            )
            self.metrics.record_tenant(
                r.tenant, e2e_s, queue_wait_s=r.picked_t - r.enqueue_t
            )
            if self.rollout is not None:
                mid_r = self._resolved_mid(model)
                sv = (
                    served_version if served_version is not None
                    else self._live_version(model)
                )
                if mid_r is not None and sv is not None:
                    self.metrics.record_version(mid_r, sv, e2e_s)
                    self.rollout.note_serve(mid_r, sv, True, e2e_s)
                if arm_ver is None and mid_r is not None:
                    # shadow lane: mirror the incumbent's resolved
                    # response for off-SLO candidate re-scoring (a full
                    # queue drops, never blocks this thread)
                    self.rollout.mirror(mid_r, r, dets)
            self._resolve(r, dets)

    def _run_batch(
        self, batch: Dict[str, np.ndarray], reqs: List[Request],
        lane: str, mkw: Dict,
    ):
        """The incumbent (live-version) predict path: pool routing with
        containment plumbing when routed, engine-side RetryPolicy when
        not — factored out of :meth:`_complete` so the rollout's
        candidate-arm fallback reuses it verbatim."""

        def attempt_run(attempt: int):
            if attempt:
                self.metrics.inc("retried")
            return self.runner.run(batch, **mkw)

        if self._routed:
            # the pool retries/hedges/fails-over internally — the
            # engine's own RetryPolicy would rerun an already-hedged
            # batch; the tightest live deadline drives the hedge,
            # and the lane tag tightens it further for interactive
            deadlines = [r.deadline for r in reqs if r.deadline is not None]
            rkw = dict(mkw)
            if self._quarantine is not None:
                # containment: the pool sees member identities and a
                # shared budget view (one re-dispatch re-runs every
                # member, so one spend decrements each)
                rkw["digests"] = tuple(r.digest for r in reqs)
                rkw["budget"] = BatchBudget([r.budget for r in reqs])
            return self.runner.run(
                batch, deadline=min(deadlines) if deadlines else None,
                lane=lane, **rkw,
            )
        return self.retry.run(attempt_run)

    # -------------------------------------------------- containment triage
    def _fail_one(self, req: Request,
                  exc: BaseException) -> None:
        self.metrics.inc("failed")
        if req.model is not None:
            self.metrics.record_model(req.model, ok=False)
        self.metrics.record_lane(req.lane, ok=False)
        self.metrics.record_tenant(req.tenant, ok=False)
        self._resolve(req, exc=exc)

    def _settle_failed(self, reqs: List[Request],
                       exc: BaseException) -> None:
        """Batch-level failure triage.  Without containment this is the
        legacy wholesale fail.  With it, each member settles on its own:
        a quarantined digest fails fast as :class:`PoisonRequest`, a
        member with budget left is split out and resubmitted solo (so
        the next trip attributes unambiguously and innocents stop
        co-tripping with the poison), and a spent budget resolves
        :class:`RetriesExhausted`."""
        qt = self._quarantine
        for r in reqs:
            if qt is not None and r.digest is not None \
                    and qt.quarantined(r.digest):
                self.metrics.inc("poisoned")
                self._fail_one(r, PoisonRequest(
                    f"digest {r.digest[:12]} quarantined after replica "
                    f"trips"
                ))
                continue
            budget = r.budget
            if qt is not None and budget is not None \
                    and budget.remaining > 0 and self._started \
                    and not self._aborting:
                self._resubmit(r)
                continue
            if budget is not None and budget.remaining <= 0:
                e: BaseException = RetriesExhausted(
                    f"retry budget {budget.total} spent; last error: "
                    f"{exc!r}"
                )
                e.__cause__ = exc
                self.metrics.inc("exhausted")
                self._fail_one(r, e)
                continue
            self._fail_one(r, exc)

    def _resubmit(self, req: Request) -> None:
        """Solo retry of one member of a failed or implicated batch.
        The spend here is what bounds the containment loop (graftlint
        R8); ``solo`` makes the batcher release it as a batch-of-1."""
        try:
            req.budget.spend("resubmit")
        except RetriesExhausted as e:
            self.metrics.inc("exhausted")
            self._fail_one(req, e)
            return
        req.solo = True
        self.metrics.inc("resubmitted")
        try:
            self.batcher.submit(req)
        except Exception as e:  # noqa: BLE001 — closed batcher at stop
            self._fail_one(req, e)

    def _escalate(self, req: Request) -> None:
        """Re-enter an uncertain cascade first pass as a flagship
        request.  The new request carries the ORIGINAL future, lane,
        tenant, absolute deadline, enqueue time, digest, and retry
        budget — escalation changes which model serves, never the
        request's identity — and is marked ``escalated`` so it re-enters
        above the queue cap (it was admitted once, at submit) and the
        gate never runs twice.  Exactly-once: the original request's
        live-set entry is REPLACED by the escalated one in the same
        locked section, so a concurrent ``stop`` sweep resolves the
        shared future exactly once, from whichever entry it finds."""
        pol = self.cascade.policy
        if req.expired():
            self.metrics.inc("expired")
            self.metrics.record_lane(req.lane, expired=True)
            self.metrics.record_tenant(req.tenant, expired=True)
            self._resolve(req, exc=DeadlineExceeded(
                "deadline passed before escalation could re-enter"
            ))
            return
        try:
            req2 = self.runner.make_request(
                req.raw_image, deadline=req.deadline, model=pol.flagship
            )
        except Exception as e:  # noqa: BLE001 — flagship prep failed
            self._fail_one(req, e)
            return
        req2.future = req.future
        req2.lane = req.lane
        req2.tenant = req.tenant
        req2.enqueue_t = req.enqueue_t  # e2e spans both passes
        req2.digest = req.digest
        req2.budget = req.budget
        # stream identity rides the escalation: the flagship pass
        # settles the SAME (stream, frame) registration, so in-order
        # delivery survives the cascade re-entry
        req2.stream = req.stream
        req2.frame = req.frame
        req2.masks = req.masks
        req2.escalated = True
        if self.rollout is not None and self.rollout.active(pol.flagship):
            # a flagship rollout splits escalated traffic too — same
            # digest-deterministic assignment as submit, so a repeated
            # escalation lands on the same arm.  Submit only digests
            # when quarantine or a CHEAP-family rollout is on, so the
            # digest may still be missing here
            if req2.digest is None:
                req2.digest = request_digest(req.raw_image)
            arm_version = self.rollout.arm_for(pol.flagship, req2.digest)
            if arm_version is not None:
                req2.arm_version = arm_version
                req2.solo = True
        if self.response_cache is not None:
            version = (
                req2.arm_version if req2.arm_version is not None
                else self._live_version(pol.flagship)
            )
            if version is not None:
                req2.cache_key = self.response_cache.key_for(
                    req.raw_image, pol.flagship, version,
                    self._precision_tag(pol.flagship),
                )
        with self._live_lock:
            self._live.pop(id(req), None)
            self._live[id(req2)] = req2
        try:
            self.batcher.submit(req2)
        except Exception as e:  # noqa: BLE001 — closed batcher at stop
            self._fail_one(req2, e)

    # ----------------------------------------------------------- lifecycle
    def swap(
        self,
        model: str,
        checkpoint: str,
        block: bool = False,
        timeout: Optional[float] = None,
    ):
        """Hot-swap ``model`` to ``checkpoint`` while serving: launches a
        background :class:`~mx_rcnn_tpu.serve.registry.SwapController`
        (load → verify → warm → commit-between-batches → canary, with
        automatic rollback) targeting this engine's runner/pool.
        Returns the controller, or its result dict with ``block=True``
        (which raises ``SwapRolledBack``/``SwapCancelled`` inline)."""
        reg = getattr(self.runner, "registry", None)
        if reg is None:
            raise RuntimeError(
                "runner has no model registry — hot-swap needs a "
                "registry-backed ServeRunner/ReplicaPool"
            )
        return reg.swap(
            model, checkpoint, target=self.runner, block=block,
            timeout=timeout,
        )

    def admin(self, line: str):
        """Operator command surface (``tools/serve.py`` wires it):

        * ``swap <model> <checkpoint_dir>`` — blocking hot-swap
        * ``rollout <model> <checkpoint_dir>`` — blocking progressive
          rollout (attaches a default-policy controller on first use)
        * ``rollout status`` — rollout controller snapshot
        * ``models`` — registry snapshot
        """
        parts = line.split()
        if len(parts) == 3 and parts[0] == "swap":
            return self.swap(parts[1], parts[2], block=True)
        if parts == ["rollout", "status"]:
            return self.rollout.snapshot() if self.rollout else {}
        if len(parts) == 3 and parts[0] == "rollout":
            if self.rollout is None:
                self.attach_rollout()
            return self.rollout.start(parts[1], parts[2], block=True)
        if parts == ["models"]:
            reg = getattr(self.runner, "registry", None)
            return reg.snapshot() if reg is not None else {}
        raise ValueError(f"unknown admin command: {line!r}")

    # ---------------------------------------------------------- reporting
    def snapshot(self) -> Dict:
        out = self.metrics.snapshot(self.runner.compile_cache)
        out["scheduler"] = self.batcher.stats()
        streams = self.streams.snapshot()
        if streams["registered"]:
            out["streams"] = streams
        if self.response_cache is not None:
            out["response_cache"] = self.response_cache.snapshot()
        parity = getattr(self.runner, "parity", None)
        if parity:
            out["parity"] = dict(parity)
        if self._pool is not None:
            out["completion"] = self._pool.stats()
        if self._routed:
            out["pool"] = self.runner.snapshot()
        if self._quarantine is not None:
            out["quarantine"] = self._quarantine.snapshot()
        if self.tenants is not None:
            out["tenancy"] = self.tenants.snapshot()
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.snapshot()
        if self.rollout is not None:
            out["rollout"] = self.rollout.snapshot()
        if self.cascade is not None:
            out["cascade"] = self.cascade.snapshot()
        dmm = getattr(self.runner, "device_ms_by_model", None)
        if dmm:
            # single-runner engines surface the cost counter directly;
            # routed pools already merge it into out["pool"]["overlap"]
            out["device_ms_by_model"] = {
                k: round(v, 3) for k, v in dmm.items()
            }
        reg = getattr(self.runner, "registry", None)
        if reg is not None:
            out["registry"] = reg.snapshot()
        return out
