"""Threaded serving loop: submit → batcher → device → per-request futures.

Thread layout (why threads, not an async dispatch chain: the relay-
attached TPU does not overlap stages of successive one-thread dispatches
— measured in ``core/tester.py :: pipelined`` — but blocking predicts
from separate threads DO overlap, the GIL dropping during relay I/O):

  * N client threads: ``submit`` prepares the image (resize/quantize/
    pad) in the CALLER's thread, so host preprocessing of the next
    requests overlaps device execution of earlier batches, then enqueues
    into the bounded batcher (``QueueFull`` → backpressure).
  * 1 assembler thread: pulls bucket-homogeneous batches from the
    batcher, fails requests whose deadline already passed (cheaper than
    running them), pads to ``max_batch``, and hands the batch to…
  * ``in_flight`` completion threads: blocking ``runner.run`` (wrapped
    in PR 1's :class:`~mx_rcnn_tpu.core.resilience.RetryPolicy` — a
    transient device/relay fault retries the whole batch
    deterministically), then per-request detections + future resolution.
    The workers live in a bounded
    :class:`~mx_rcnn_tpu.data.assembler.CompletionPool` whose blocking
    submit keeps the assembler at most ``in_flight`` batches ahead, so
    device-side queueing stays bounded too — and whose counters land in
    :meth:`ServingEngine.snapshot`.

Every request resolves exactly once: detections list, or
:class:`DeadlineExceeded` / :class:`QueueFull` /
:class:`~mx_rcnn_tpu.serve.buckets.BucketOverflow` / the predict error
after retries are exhausted.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as np

from mx_rcnn_tpu.core.resilience import RetryPolicy
from mx_rcnn_tpu.data.assembler import CompletionPool
from mx_rcnn_tpu.serve.batcher import DynamicBatcher, QueueFull, Request
from mx_rcnn_tpu.serve.metrics import ServeMetrics
from mx_rcnn_tpu.serve.runner import ServeRunner


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before the device could run it."""


class ServingEngine:
    """Online inference front-end over a :class:`ServeRunner`."""

    def __init__(
        self,
        runner: ServeRunner,
        max_linger: float = 0.005,
        max_queue: int = 64,
        in_flight: int = 2,
        retry: Optional[RetryPolicy] = None,
    ):
        self.runner = runner
        self.batcher = DynamicBatcher(
            runner.max_batch, max_linger=max_linger, max_queue=max_queue
        )
        self.metrics = ServeMetrics()
        self.retry = retry if retry is not None else RetryPolicy(tries=3)
        self._in_flight = max(1, int(in_flight))
        self._pool: Optional[CompletionPool] = None
        self._assembler: Optional[threading.Thread] = None
        self._started = False

    # ---------------------------------------------------------- lifecycle
    def start(self, warmup: bool = True) -> "ServingEngine":
        if self._started:
            return self
        if warmup:
            self.runner.warmup()
        # same thread layout as before (in_flight workers, submit blocks
        # at depth=in_flight — the old semaphore), but the pool exports
        # the shared data-plane counters into snapshot()
        self._pool = CompletionPool(
            self._in_flight, depth=self._in_flight, name="serve-complete"
        )
        self._assembler = threading.Thread(
            target=self._assemble_loop, name="serve-assemble", daemon=True
        )
        self._started = True
        self._assembler.start()
        return self

    def stop(self) -> None:
        """Drain: stop accepting, finish queued work, join threads."""
        if not self._started:
            return
        self.batcher.close()
        self._assembler.join()
        # raise_errors=False: request futures already carry per-request
        # failures; an engine drain must not re-raise them at shutdown
        self._pool.close(raise_errors=False)
        self._started = False

    def __enter__(self) -> "ServingEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ------------------------------------------------------------- client
    def submit(
        self, im: np.ndarray, deadline_s: Optional[float] = None
    ) -> Future:
        """Enqueue one image; returns a Future resolving to the
        per-class detections list.  Raises
        :class:`~mx_rcnn_tpu.serve.buckets.BucketOverflow` (oversize) or
        :class:`~mx_rcnn_tpu.serve.batcher.QueueFull` (backpressure)
        synchronously — both count as ``rejected``."""
        if not self._started:
            raise RuntimeError("engine not started")
        deadline = (
            time.monotonic() + deadline_s if deadline_s is not None else None
        )
        try:
            req = self.runner.make_request(im, deadline=deadline)
            self.batcher.submit(req)
        except Exception:
            self.metrics.inc("rejected")
            raise
        self.metrics.inc("submitted")
        self.metrics.record_queue_depth(self.batcher.pending())
        return req.future

    # ------------------------------------------------------------- device
    def _assemble_loop(self) -> None:
        while True:
            batch_reqs = self.batcher.next_batch()
            if batch_reqs is None:
                return
            now = time.monotonic()
            live: List[Request] = []
            for r in batch_reqs:
                if r.expired(now):
                    self.metrics.inc("expired")
                    r.future.set_exception(
                        DeadlineExceeded(
                            f"deadline passed {now - r.deadline:.3f}s before "
                            f"device pickup"
                        )
                    )
                else:
                    self.metrics.queue_wait.record(r.picked_t - r.enqueue_t)
                    live.append(r)
            self.metrics.record_queue_depth(self.batcher.pending())
            if not live:
                continue
            batch = self.runner.assemble(live)
            # pool submit blocks at depth=in_flight: at most in_flight
            # batches on the device (the old explicit semaphore)
            self._pool.submit(self._complete, live, batch)

    def _complete(
        self, reqs: List[Request], batch: Dict[str, np.ndarray]
    ) -> None:
        # runs on a completion-pool worker; the pool's depth slot is
        # released when this returns, unblocking the assembler
        t0 = time.monotonic()

        def attempt_run(attempt: int):
            if attempt:
                self.metrics.inc("retried")
            return self.runner.run(batch)

        try:
            out = self.retry.run(attempt_run)
        except Exception as e:
            self.metrics.inc("failed", len(reqs))
            for r in reqs:
                r.future.set_exception(e)
            return
        done = time.monotonic()
        self.metrics.service.record(done - t0)
        self.metrics.record_batch(len(reqs), self.runner.max_batch)
        for k, r in enumerate(reqs):
            try:
                dets = self.runner.detections_for(
                    out, batch, k, orig_hw=r.orig_hw
                )
            except Exception as e:  # postprocess bug: fail this request
                self.metrics.inc("failed")
                r.future.set_exception(e)
                continue
            self.metrics.inc("completed")
            self.metrics.e2e.record(time.monotonic() - r.enqueue_t)
            r.future.set_result(dets)

    # ---------------------------------------------------------- reporting
    def snapshot(self) -> Dict:
        out = self.metrics.snapshot(self.runner.compile_cache)
        if self._pool is not None:
            out["completion"] = self._pool.stats()
        return out
